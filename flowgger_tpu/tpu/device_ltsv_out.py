"""Device-side rfc5424→LTSV encode (ltsv_encoder.rs:18-74 semantics,
mirroring encode_ltsv_block.py's ``_ltsv_core`` segment plan
byte-for-byte).

Same no-escape-stage shape as the →RFC5424 kernels: the tier demands
rows whose emitted spans need no LTSV value escaping (no tab/newline
anywhere in the row, no ':' inside SD names, no JSON-escaped SD
values), so every segment re-emits verbatim from the raw batch and the
static table is pairs-first (name ':' value '\\t' per slot) followed by
the fixed label columns, exactly like the host tier.

Elision drops three row-positioned constants from the device body —
``\\ttime:<stamp>`` (the stamp is rendered host-side anyway),
``\\tfull_message:``, and the framing suffix — and exports two 2-byte
``gap0``/``gap1`` probe channels so the host splice knows where the
variable-width pair stream ends.  ~33 elided bytes/row against ~4
fetched probe bytes.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.ltsv:LTSVEncoder"
DIFF_TEST = (
    "tests/test_device_encode_out.py::test_device_ltsv_out_matches_scalar",
)

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .device_common import (
    TS_W,
    _out_width,
    assemble_rows,
    build_bank,
    encode_route_ok,
    fetch_encode_driver,
)

_I32 = jnp.int32
_U8 = jnp.uint8

_PARTS = {
    "col": b":",
    "tab": b"\t",
    "host": b"host:",
    "time": b"\ttime:",
    "msgl": b"\tmessage:",
    "full": b"\tfull_message:",
    "lvl": b"\tlevel:",
    "fac": b"\tfacility:",
    "app": b"\tappname:",
    "proc": b"\tprocid:",
    "msgid": b"\tmsgid:",
    "dec": b"0123456789 ",
    "extra": b"",  # replaced per-config by _bank
    "tail": b"",
}


def _bank(suffix: bytes, extras: Tuple[Tuple[str, str], ...] = ()
          ) -> Tuple[bytes, Dict[str, int], Dict[str, bytes]]:
    """Constant bank; ``ltsv_extra`` pairs render to the same single
    pre-escaped blob the host tier emits (ltsv_extra_blob), so the two
    tiers can never disagree on extras bytes."""
    from .block_common import ltsv_extra_blob

    parts = dict(_PARTS)
    parts["extra"] = ltsv_extra_blob(list(extras))
    bank, offs = build_bank(parts, suffix)
    return bank, offs, parts


def _render_display(val: float) -> bytes:
    """Stamp text: Rust ``Display``-compatible shortest float form —
    the same display_f64 the host tier's ts_scratch uses."""
    from ..utils.rustfmt import display_f64

    return display_f64(val).encode("ascii")


def elide_spec(suffix: bytes, extras=()):
    return make_elide(suffix)


def make_elide(suffix: bytes):
    """Callable elide: restore ``\\ttime:<stamp>`` at gap0,
    ``\\tfull_message:`` at gap1, and the framing suffix at the row
    end, from the kernel's 2-byte gap probe channels."""
    TIME = b"\ttime:"
    FULL = b"\tfull_message:"

    def splice(body, row_off, small, ts_text, ts_len, ridx):
        from .device_common import splice_rows

        R = ridx.size
        W = ts_text.shape[1] if ts_text.ndim == 2 else 0
        stride = len(TIME) + W
        buf = np.zeros((R, stride), dtype=np.uint8)
        buf[:, :len(TIME)] = np.frombuffer(TIME, dtype=np.uint8)
        if W:
            buf[:, len(TIME):] = np.asarray(ts_text, np.uint8)[ridx]
        ins_src = np.concatenate(
            [buf.ravel(), np.frombuffer(FULL + suffix, dtype=np.uint8)])
        gap0 = small["gap0"][ridx].astype(np.int64)
        gap1 = small["gap1"][ridx].astype(np.int64)
        lens = np.diff(row_off).astype(np.int64)
        ins_at = np.stack([gap0, gap1, lens], axis=1)
        ins_a = np.stack([
            np.arange(R, dtype=np.int64) * stride,
            np.full(R, R * stride, dtype=np.int64),
            np.full(R, R * stride + len(FULL), dtype=np.int64),
        ], axis=1)
        ins_l = np.stack([
            len(TIME) + np.asarray(ts_len, dtype=np.int64)[ridx],
            np.full(R, len(FULL), dtype=np.int64),
            np.full(R, len(suffix), dtype=np.int64),
        ], axis=1)
        return splice_rows(body, row_off, ins_src, ins_at, ins_a, ins_l)

    return splice


@partial(jax.jit, static_argnames=("suffix", "extras", "assemble",
                                   "elide"))
def _encode_kernel(batch, lens, dec, ts_text, ts_len, *, suffix: bytes,
                   extras: Tuple[Tuple[str, str], ...] = (),
                   assemble: bool = True, elide: bool = False):
    """rfc5424→LTSV: _ltsv_core's plan (pairs first, then the fixed
    label columns) as a static device segment table."""
    N, L = batch.shape
    bank, off, parts = _bank(suffix, extras)
    OW = _out_width(L, L + len(bank) + TS_W)
    zero = jnp.zeros((N,), dtype=_I32)
    cbase = L
    tbase = L + len(bank)
    segs = []

    def add_const(name, gate=None):
        ln = zero + len(parts[name]) + (len(suffix) if name == "tail"
                                        else 0)
        if gate is not None:
            ln = jnp.where(gate, ln, 0)
        segs.append((zero + (cbase + off[name]), ln))

    def add_span(s, e, gate=None):
        ln = jnp.maximum(e - s, 0)
        if gate is not None:
            ln = jnp.where(gate, ln, 0)
        segs.append((s, ln))

    fac = dec["facility"].astype(_I32)
    sev = dec["severity"].astype(_I32)
    host_s, host_e = dec["host_start"].astype(_I32), dec["host_end"].astype(_I32)
    app_s, app_e = dec["app_start"].astype(_I32), dec["app_end"].astype(_I32)
    proc_s, proc_e = dec["proc_start"].astype(_I32), dec["proc_end"].astype(_I32)
    msgid_s, msgid_e = (dec["msgid_start"].astype(_I32),
                        dec["msgid_end"].astype(_I32))
    full_s = dec["full_start"].astype(_I32)
    msg_s = dec["msg_trim_start"].astype(_I32)
    trim_e = dec["trim_end"].astype(_I32)
    msg_l = jnp.maximum(trim_e - msg_s, 0)
    has_msg = msg_l > 0
    pc = dec["pair_count"].astype(_I32)
    P = dec["name_start"].shape[1]

    # pairs first: name ':' value '\t' per occupied slot
    pairs_total = zero
    for j in range(P):
        pv = j < pc
        ns = dec["name_start"][:, j].astype(_I32)
        ne = dec["name_end"][:, j].astype(_I32)
        vs = dec["val_start"][:, j].astype(_I32)
        ve = dec["val_end"][:, j].astype(_I32)
        add_span(ns, ne, pv)
        add_const("col", pv)
        add_span(vs, ve, pv)
        add_const("tab", pv)
        pairs_total = pairs_total + jnp.where(
            pv, jnp.maximum(ne - ns, 0) + jnp.maximum(ve - vs, 0) + 2, 0)

    add_const("extra")
    add_const("host")
    add_span(host_s, host_e)
    if not elide:
        # constant-elision skips "\ttime:<stamp>" here (spliced back
        # host-side at gap0), "\tfull_message:" at gap1, and the tail
        add_const("time")
        segs.append((zero + tbase, ts_len.astype(_I32)))
    add_const("msgl", has_msg)
    add_span(msg_s, trim_e)
    if not elide:
        add_const("full")
    add_span(full_s, trim_e)
    add_const("lvl")
    segs.append((cbase + off["dec"] + sev, zero + 1))
    add_const("fac")
    segs.append((cbase + off["dec"] + (fac // 10) % 10,
                 jnp.where(fac >= 10, 1, 0)))
    segs.append((cbase + off["dec"] + fac % 10, zero + 1))
    add_const("app")
    add_span(app_s, app_e)
    add_const("proc")
    add_span(proc_s, proc_e)
    add_const("msgid")
    add_span(msgid_s, msgid_e)
    if not elide:
        add_const("tail")

    out_len = segs[0][1]
    for _, ln in segs[1:]:
        out_len = out_len + ln

    # tier screens mirror the host cand: no tab/newline anywhere in the
    # row (LTSV value escape), no ':' inside SD names (key escape), no
    # JSON-escaped SD values
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)
    valid = iota < lens.astype(_I32)[:, None]
    row_esc = (((batch == 9) | (batch == 10)) & valid).any(axis=1)
    name_mask = jnp.zeros((N, L), dtype=bool)
    val_esc_any = jnp.zeros((N,), dtype=bool)
    for j in range(P):
        pv = j < pc
        ns = dec["name_start"][:, j].astype(_I32)
        ne = dec["name_end"][:, j].astype(_I32)
        name_mask |= ((iota >= ns[:, None]) & (iota < ne[:, None])
                      & pv[:, None])
        val_esc_any |= dec["val_has_esc"][:, j].astype(bool) & pv
    colon_in_names = ((batch == ord(":")) & name_mask).any(axis=1)

    tier = (dec["ok"].astype(bool)
            & ~dec["has_high"].astype(bool)
            & ~row_esc
            & ~colon_in_names
            & ~val_esc_any
            & (out_len <= OW))
    if not assemble:
        gap0 = (pairs_total + len(parts["extra"]) + len(b"host:")
                + jnp.maximum(host_e - host_s, 0))
        gap1 = gap0 + jnp.where(has_msg, len(b"\tmessage:"), 0) + msg_l
        gdt = jnp.uint16 if OW <= 0xFFFF else _I32
        return {"tier": tier,
                "gap0": gap0.astype(gdt), "gap1": gap1.astype(gdt)}
    acc, out_len2 = assemble_rows(segs, batch.astype(_U8), bank, ts_text,
                                  N, OW)
    return acc, out_len2, tier


def _small_fetch(out, fetch):
    small = {k: fetch(out[k])
             for k in ("ok", "days", "sod", "off", "nanos")}
    small["gap0"] = fetch(out["gap0"])
    small["gap1"] = fetch(out["gap1"])
    return small


def route_ok(encoder, merger) -> bool:
    """Device encode applies to LTSV output over line/nul/syslen
    framing (ltsv_extra always renders to one static blob)."""
    from ..encoders.ltsv import LTSVEncoder

    return encode_route_ok(encoder, merger, LTSVEncoder)


# same ladder constants as the →GELF split tier
FALLBACK_FRAC = 0.05
DECLINE_LIMIT = 3
COOLDOWN = 16


def fetch_encode(handle, packed, encoder, merger, route_state=None):
    """rfc5424→LTSV split-tier entry; returns
    (BlockResult | None, fetch_seconds)."""
    from .block_common import merger_suffix
    from .materialize import _scalar_line

    out, _, _, _max_sd, _impl_unused, batch_dev, lens_dev = handle
    suffix, syslen = merger_suffix(merger)
    extras = tuple((str(k), str(v)) for k, v in
                   getattr(encoder, "extra", []))

    def kernel(ts_text, ts_len, assemble):
        return _encode_kernel(batch_dev, lens_dev, dict(out), ts_text,
                              ts_len, suffix=suffix, extras=extras,
                              assemble=assemble, elide=True)

    from .aot import encode_wrap
    from .rfc5424 import best_scan_impl

    kernel = encode_wrap("device_ltsv_out", kernel, batch_dev, lens_dev,
                         dict(out), suffix, best_scan_impl(), extras)

    return fetch_encode_driver(
        kernel, out, batch_dev, lens_dev, packed, encoder, merger,
        route_state, suffix, syslen, scalar_fn=_scalar_line,
        fallback_frac=FALLBACK_FRAC, decline_limit=DECLINE_LIMIT,
        cooldown=COOLDOWN, ts_render=_render_display,
        small_fetch_fn=_small_fetch, elide=make_elide(suffix),
        route_label="rfc5424_ltsv", fused_counters=False)
