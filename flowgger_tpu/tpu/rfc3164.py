r"""Columnar RFC3164 fast path.

Scalar spec: flowgger_tpu/decoders/rfc3164.py (reference
rfc3164_decoder.rs:31-213).  RFC3164 is deliberately lenient — the
scalar decoder tries two layouts, optional years, an IANA timezone
token, and whitespace-run tokenization.  The kernel fast-paths only the
overwhelmingly common shape:

    [<pri>]Mon d hh:mm:ss host msg...

with single spaces between tokens and no year/timezone token, because
those are the cases whose decode is position-determined:

- the month is matched with twelve shifted-byte-plane patterns at the
  post-PRI offset (the technique from the LTSV special keys);
- day (1-2 digits, no padding) picks between two fixed layouts for the
  hh:mm:ss / host offsets;
- any whitespace *run* (double space), trailing space, tab, or leading
  space would change the reference's rebuilt-with-single-spaces message
  — rows containing one in the message region fall back;
- a fourth token that could plausibly be an IANA timezone name (all of
  ``[A-Za-z0-9/_+-]`` — note digit-bearing zones like ``EST5EDT`` and
  ``Etc/GMT+1`` exist) falls back, since the scalar path would consult
  the tz database; a token with a byte outside that set (the ``.`` of an
  FQDN or IP) can never be a tz name and stays on the fast path;
- the current UTC year is a runtime argument (not baked into the jit
  cache) — the reference assumes it at decode time
  (rfc3164_decoder.rs:179-184).

Everything flagged decodes via the scalar oracle, so output stays
byte-identical (tests/test_tpu_rfc3164.py).
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp

from .rfc5424 import (
    _at,
    _days_from_civil,
    _days_in_month,
    _min_where,
    _shift_left,
)

_I32 = jnp.int32
_MONTHS = (b"Jan", b"Feb", b"Mar", b"Apr", b"May", b"Jun",
           b"Jul", b"Aug", b"Sep", b"Oct", b"Nov", b"Dec")


def decode_rfc3164(batch: jnp.ndarray, lens: jnp.ndarray, year,
                   scan_impl: str = "lax") -> Dict[str, jnp.ndarray]:
    N, L = batch.shape
    lens = lens.astype(_I32)
    year = jnp.asarray(year, _I32)
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)
    valid = iota < lens[:, None]
    # uint8 byte plane (see rfc5424.py): widen inside consumer fusions
    bb = jnp.where(valid, batch, jnp.uint8(0))
    is_digit = (bb >= 48) & (bb <= 57)
    dig = bb.astype(_I32) - 48

    # ---- optional <pri> --------------------------------------------------
    has_pri = bb[:, 0] == ord("<")
    gt = _min_where((bb == ord(">")) & valid, iota, L)
    ndig = gt - 1
    pri_zone = (iota >= 1) & (iota < gt[:, None]) & has_pri[:, None]
    e = gt[:, None] - 1 - iota
    w = jnp.where(e == 0, 1, jnp.where(e == 1, 10, jnp.where(e == 2, 100, 0)))
    pri = jnp.sum(jnp.where(pri_zone, dig * w, 0), axis=1)
    pri_ok = jnp.where(
        has_pri,
        (gt < L) & (ndig >= 1) & (ndig <= 3) & (pri <= 255)
        & ~jnp.any(pri_zone & ~is_digit, axis=1),
        True)
    m0 = jnp.where(has_pri, gt + 1, 0)
    ok = pri_ok

    # ---- month via shifted-plane patterns at m0 --------------------------
    month = jnp.zeros_like(lens)
    for i, mon in enumerate(_MONTHS):
        pat = (bb == mon[0])
        pat &= _shift_left(bb, 1, 0) == mon[1]
        pat &= _shift_left(bb, 2, 0) == mon[2]
        hit = jnp.any(pat & (iota == m0[:, None]), axis=1)
        month = jnp.where(hit, i + 1, month)
    ok &= month > 0

    # ---- day layouts after "Mon " -----------------------------------------
    #   A: "Mon dd "  (two digits)           time at m0+7
    #   B: "Mon d "   (single digit)         time at m0+6
    #   C: "Mon  d "  (classic double-space single digit) time at m0+7
    r = iota - m0[:, None]
    c4 = _at(iota, m0 + 3, bb)
    ok &= c4 == 32  # space after month
    d0 = _at(iota, m0 + 4, bb).astype(_I32)
    d1 = _at(iota, m0 + 5, bb).astype(_I32)
    d2 = _at(iota, m0 + 6, bb).astype(_I32)
    d0_dig = (d0 >= 48) & (d0 <= 57)
    d1_dig = (d1 >= 48) & (d1 <= 57)
    case_a = d0_dig & d1_dig
    case_b = d0_dig & (d1 == 32)
    case_c = (d0 == 32) & d1_dig & (d2 == 32)
    ok &= case_a | case_b | case_c
    day = jnp.where(case_a, (d0 - 48) * 10 + (d1 - 48),
                    jnp.where(case_b, d0 - 48, d1 - 48))
    t0 = m0 + jnp.where(case_b, 6, 7)  # time start
    ok &= _at(iota, t0 - 1, bb) == 32
    rt = r - (t0 - m0)[:, None]
    in_time = (rt >= 0) & (rt < 8)
    dzt = jnp.where(in_time, dig, 0)
    hour = jnp.sum(dzt * ((rt == 0) * 10 + (rt == 1)), axis=1)
    minute = jnp.sum(dzt * ((rt == 3) * 10 + (rt == 4)), axis=1)
    sec = jnp.sum(dzt * ((rt == 6) * 10 + (rt == 7)), axis=1)
    tviol = jnp.any(in_time & ((rt == 2) | (rt == 5)) & (bb != ord(":")), axis=1)
    tviol |= jnp.any(
        in_time & (rt != 2) & (rt != 5) & ~is_digit, axis=1)
    ok &= ~tviol & (hour <= 23) & (minute <= 59) & (sec <= 59)
    ok &= (day >= 1) & (day <= _days_in_month(year, month))

    # ---- host token -------------------------------------------------------
    host_s = t0 + 9
    ok &= _at(iota, t0 + 8, bb) == 32
    is_sp = (bb == 32) & valid
    host_e = _min_where(is_sp & (iota >= host_s[:, None]), iota, L)
    host_e = jnp.minimum(host_e, lens)
    ok &= host_e > host_s  # nonempty hostname token
    # need >3 whitespace tokens overall: host + at least one msg token
    # (reference standard layout requires tokens_vec.len() > 3 —
    # month/day/time are 3, host is the 4th; message may then be empty)
    msg_start = jnp.minimum(host_e + 1, lens)

    # ---- strictness ------------------------------------------------------
    # whitespace-run tokenization means any non-space whitespace, or a
    # double space from the time token onward (the rebuilt-message
    # region), or leading/trailing spaces would change the scalar output
    # single-byte whitespace per str.split(): tab, LF, VT, FF, CR, and
    # the 0x1C-0x1F separator control bytes (LF is reachable inside a
    # message via nul framing and UDP datagrams; multi-byte unicode
    # whitespace is caught by the materializer's byte-length-vs-
    # char-length check)
    ws_other = ((bb >= 9) & (bb <= 13)
                | ((bb >= 28) & (bb <= 31))) & valid
    dbl = is_sp & _shift_left(is_sp, 1, False) & (iota >= t0[:, None])
    last_ch_sp = _at(iota, lens - 1, bb) == 32
    first_ch_sp = bb[:, 0] == 32
    ok &= ~jnp.any(ws_other | dbl, axis=1) & ~last_ch_sp & ~first_ch_sp
    ok &= lens >= 1

    # ---- timezone-lookalike guard for the token after the time ----------
    # (that token is the hostname on the fast path; if every byte could
    # appear in an IANA tz name, the scalar path might consult the tz db
    # and consume it — fall back)
    in_host = (iota >= host_s[:, None]) & (iota < host_e[:, None])
    # IANA names use letters, digits (EST5EDT, Etc/GMT+1, GMT0), '/',
    # '_', '+', '-'; every name starts with an uppercase letter except
    # the system-zoneinfo oddities "localtime"/"posixrules" (verified
    # against this system's tz database).  A token is provably NOT a
    # timezone — and thus safely the hostname — when it contains a byte
    # outside the tz set (the '.' of an FQDN/IP) or starts lowercase/
    # digit and is not one of those two literals.
    tz_char = (
        ((bb >= ord("A")) & (bb <= ord("Z")))
        | ((bb >= ord("a")) & (bb <= ord("z")))
        | ((bb >= ord("0")) & (bb <= ord("9")))
        | (bb == ord("/")) | (bb == ord("_"))
        | (bb == ord("+")) | (bb == ord("-"))
    )
    has_non_tz_byte = jnp.any(in_host & ~tz_char, axis=1)
    first_host = _at(iota, host_s, bb)
    humble_first = ((first_host >= ord("a")) & (first_host <= ord("z"))) | (
        (first_host >= ord("0")) & (first_host <= ord("9")))

    def _literal_at(text: bytes, pos, tok_len):
        pat = bb == text[0]
        for i, ch in enumerate(text[1:], start=1):
            pat &= _shift_left(bb, i, 0) == ch
        return jnp.any(pat & (iota == pos[:, None]), axis=1) & (
            tok_len == len(text))

    host_len = host_e - host_s
    is_tz_alias = (_literal_at(b"localtime", host_s, host_len)
                   | _literal_at(b"posixrules", host_s, host_len))
    ok &= has_non_tz_byte | (humble_first & ~is_tz_alias)

    days = _days_from_civil(year, month, day)
    sod = hour * 3600 + minute * 60 + sec

    return {
        "ok": ok,
        "has_pri": has_pri,
        "has_high": jnp.any((bb >= 128) & valid, axis=1),
        "facility": pri >> 3,
        "severity": pri & 7,
        "days": days,
        "sod": sod,
        "off": jnp.zeros_like(sod),
        "nanos": jnp.zeros_like(sod),
        "host_start": host_s, "host_end": host_e,
        "msg_start": msg_start,
    }


@functools.partial(jax.jit, static_argnames=("demand",))
def decode_rfc3164_jit(batch, lens, year, demand=None):
    """``demand`` (static frozenset): keep only the channels the
    consumer reads so XLA dead-code-eliminates the rest (the fused
    rfc3164→GELF route drops e.g. the facility channel)."""
    out = decode_rfc3164(batch, lens, year)
    if demand is not None:
        out = {k: v for k, v in out.items() if k in demand}
    return out


def decode_rfc3164_submit(batch, lens, sharded=None):
    """Asynchronous dispatch (pair with decode_rfc3164_fetch) — the
    rfc3164 leg of the block pipeline's double buffering.  ``sharded``
    swaps in the multi-chip mesh kernel (parallel.mesh.ShardedDecode);
    the year scalar rides replicated.  The handle carries the uploaded
    device arrays so the device-side encode (tpu/device_rfc3164.py)
    reuses them without a re-upload."""
    import jax.numpy as jnp

    from ..utils.timeparse import current_year_utc

    if sharded is not None:
        b, ln = sharded.put(batch, lens)
        return sharded.fn(b, ln, jnp.int32(current_year_utc())), b, ln
    from .aot import decode_call

    b, ln = jnp.asarray(batch), jnp.asarray(lens)
    year = jnp.int32(current_year_utc())
    # zero-JIT boot: a loaded AOT artifact replaces the trace+compile
    out = decode_call("rfc3164", (b, ln, year))
    if out is None:
        out = decode_rfc3164_jit(b, ln, year)
    return out, b, ln


def decode_rfc3164_fetch(handle):
    import numpy as np

    return {k: np.asarray(v) for k, v in handle[0].items()}
