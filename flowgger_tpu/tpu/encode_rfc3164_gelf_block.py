"""Columnar RFC3164→GELF encoding: the legacy-syslog fast path's span
tables become framed GELF bytes with eleven fixed segments per row.

An rfc3164 fast-path record (materialize_rfc3164.py) carries no SD, no
appname/procid/msgid, an unstripped message, and the whole line as
full_message, so its sorted-key GELF object is exactly::

    {"full_message":F,"host":H,["level":N,]"short_message":M,
     "timestamp":T,"version":"1.1"}

with JSON escaping on the three spans (the shared sparse EscapeMap) and
the level segments zero-length for no-PRI rows.  Rows outside the tier
(kernel-flagged, oversized, non-ASCII via the kernel's has_high
channel) re-run the scalar rfc3164 oracle, keeping bytes identical to
decoder→GelfEncoder in every case.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.gelf:GelfEncoder"
DIFF_TEST = "tests/test_encode_gelf_block.py::test_rfc3164_gelf_block_route_matches_scalar"

from typing import Dict, Optional

import numpy as np

from ..mergers import Merger
from ..utils.rustfmt import json_f64
from .assemble import (
    build_source,
    concat_segments,
    escape_json,
    exclusive_cumsum,
)
from .block_common import (
    BlockResult,
    apply_syslen_prefix,
    finish_block,
    merger_suffix,
    ts_scratch,
)
from .materialize_rfc3164 import _scalar_3164

_C_OPEN = b'{"full_message":"'
_C_HOST = b'","host":"'
_C_LEVEL = b'","level":'
_C_SHORT_PRI = b',"short_message":"'     # after the bare level number
_C_SHORT_NOPRI = b'","short_message":"'  # closing the host string
_C_TS = b'","timestamp":'
_C_TAIL = b',"version":"1.1"}'
_C_SEVD = b"01234567"

_SEGS = 13  # incl. the two extras slot columns (empty without extras)

_FIXED_3164 = ("full_message", "host", "level", "short_message",
               "timestamp", "version")


def gelf_extra_consts_3164(extra):
    """Fold ``[output.gelf_extra]`` pairs into this layout's constants
    (same static-placement idea as encode_gelf_block.gelf_extra_slots,
    adapted to the gated ``level`` key): returns
    (open, host_const, hl_slot, l2_pri, l2_nopri, short_pri,
    short_nopri, ts_const, tail_const) or None when a key needs dynamic
    placement.  The level→short slot is per-row dual-form — after the
    bare level digit (number form) when PRI is present, after a string
    value otherwise — mirroring the existing short-const selection."""
    from .block_common import extra_forms, extra_tail

    pre = hl = b""
    l2a = l2b = b""          # level<k<short: (pri, no-pri) variants
    fh = b""                 # full<k<host
    st = b""                 # short<k<timestamp
    tv = b""                 # timestamp<k<version (number form)
    vz = b""                 # > version (inside tail)
    for k, v in sorted(extra or ()):
        if k in _FIXED_3164:
            return None
        sf, sc, nm = extra_forms(k, v)
        if k < "full_message":
            pre += sf
        elif k < "host":
            fh += sc
        elif k < "level":
            hl += sc
        elif k < "short_message":
            l2a += nm
            l2b += sc
        elif k < "timestamp":
            st += sc
        elif k < "version":
            tv += nm
        else:
            vz += sc
    tail = extra_tail(_C_TAIL, tv, vz)
    # an l2a chain ends quoted -> short needs the after-number variant;
    # an l2b chain ends unquoted -> the string-close variant: exactly
    # the existing has_pri pairing, so no new selection logic is needed
    return (b"{" + pre + _C_OPEN[1:], fh + _C_HOST, hl, l2a, l2b,
            _C_SHORT_PRI, _C_SHORT_NOPRI, st + _C_TS, tail)


def encode_rfc3164_gelf_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    spec = merger_suffix(merger)
    if spec is None:
        return None
    econsts = gelf_extra_consts_3164(encoder.extra)
    if econsts is None:
        return None
    (c_open, c_host, c_hl, c_l2a, c_l2b, c_short_p, c_short_n, c_ts,
     c_tail) = econsts
    suffix, syslen = spec

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    cand = ok & (lens64 <= max_len) & ~has_high

    ridx = np.flatnonzero(cand)
    R = ridx.size
    final_buf = b""
    row_off = np.zeros(1, dtype=np.int64)
    prefix_lens_tier: Optional[np.ndarray] = None

    if R:
        emap = escape_json(chunk_arr)
        st = starts64[ridx]

        def espan(a_abs, b_abs):
            ea = emap.map(a_abs)
            return ea, emap.map(b_abs) - ea

        row_end = st + lens64[ridx]
        full_src, full_len = espan(st, row_end)
        host_a = st + np.asarray(out["host_start"])[:n][ridx]
        host_b = st + np.asarray(out["host_end"])[:n][ridx]
        host_src, host_len = espan(host_a, host_b)
        msg_a = st + np.asarray(out["msg_start"])[:n][ridx]
        msg_src, msg_len = espan(msg_a, row_end)
        has_pri = np.asarray(out["has_pri"][:n], dtype=bool)[ridx]
        sev = np.asarray(out["severity"])[:n][ridx].astype(np.int64)

        scratch, ts_off, ts_len = ts_scratch(out, n, ridx, json_f64)
        consts, offs = build_source(
            c_open, c_host, _C_LEVEL, c_short_p, c_short_n,
            c_ts, c_tail + suffix, _C_SEVD, c_hl, c_l2a, c_l2b, scratch)
        (o_open, o_host, o_level, o_short_p, o_short_n, o_ts, o_tail,
         o_sevd, o_hl, o_l2a, o_l2b, o_scratch) = offs
        cbase = int(emap.esc.size)
        src = np.concatenate([emap.esc, consts])

        # (no empty-host substitution: the kernel only marks rows ok
        # when the host span is non-empty, rfc3164.py host_e > host_s)
        seg_src = np.empty((R, _SEGS), dtype=np.int64)
        seg_len = np.empty((R, _SEGS), dtype=np.int64)
        cols = (
            (cbase + o_open, len(c_open)),
            (full_src, full_len),
            (cbase + o_host, len(c_host)),
            (host_src, host_len),
            (cbase + o_hl, len(c_hl)),
            (cbase + o_level, np.where(has_pri, len(_C_LEVEL), 0)),
            (cbase + o_sevd + sev, np.where(has_pri, 1, 0)),
            (np.where(has_pri, cbase + o_l2a, cbase + o_l2b),
             np.where(has_pri, len(c_l2a), len(c_l2b))),
            (np.where(has_pri, cbase + o_short_p, cbase + o_short_n),
             np.where(has_pri, len(c_short_p), len(c_short_n))),
            (msg_src, msg_len),
            (cbase + o_ts, len(c_ts)),
            (cbase + o_scratch + ts_off, ts_len),
            (cbase + o_tail, len(c_tail) + len(suffix)),
        )
        for k, (s, ln) in enumerate(cols):
            seg_src[:, k] = s
            seg_len[:, k] = ln

        flat_src = seg_src.ravel()
        flat_len = seg_len.ravel()
        dst0 = exclusive_cumsum(flat_len)
        body = concat_segments(src, flat_src, flat_len, dst0)
        row_off = dst0[::_SEGS]
        tier_lens = np.diff(row_off)
        if syslen:
            final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
                body, row_off, tier_lens)
        else:
            final_buf = body.tobytes()

    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder, scalar_fn=_scalar_3164)
