"""Columnar RFC3164→GELF encoding: the legacy-syslog fast path's span
tables become framed GELF bytes with eleven fixed segments per row.

An rfc3164 fast-path record (materialize_rfc3164.py) carries no SD, no
appname/procid/msgid, an unstripped message, and the whole line as
full_message, so its sorted-key GELF object is exactly::

    {"full_message":F,"host":H,["level":N,]"short_message":M,
     "timestamp":T,"version":"1.1"}

with JSON escaping on the three spans (the shared sparse EscapeMap) and
the level segments zero-length for no-PRI rows.  Rows outside the tier
(kernel-flagged, oversized, non-ASCII via the kernel's has_high
channel) re-run the scalar rfc3164 oracle, keeping bytes identical to
decoder→GelfEncoder in every case.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..mergers import Merger
from ..utils.rustfmt import json_f64
from .assemble import (
    build_source,
    concat_segments,
    escape_json,
    exclusive_cumsum,
)
from .block_common import (
    BlockResult,
    apply_syslen_prefix,
    finish_block,
    merger_suffix,
    ts_scratch,
)
from .materialize_rfc3164 import _scalar_3164

_C_OPEN = b'{"full_message":"'
_C_HOST = b'","host":"'
_C_LEVEL = b'","level":'
_C_SHORT_PRI = b',"short_message":"'     # after the bare level number
_C_SHORT_NOPRI = b'","short_message":"'  # closing the host string
_C_TS = b'","timestamp":'
_C_TAIL = b',"version":"1.1"}'
_C_SEVD = b"01234567"

_SEGS = 11


def encode_rfc3164_gelf_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    spec = merger_suffix(merger)
    if spec is None or encoder.extra:
        return None
    suffix, syslen = spec

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    cand = ok & (lens64 <= max_len) & ~has_high

    ridx = np.flatnonzero(cand)
    R = ridx.size
    final_buf = b""
    row_off = np.zeros(1, dtype=np.int64)
    prefix_lens_tier: Optional[np.ndarray] = None

    if R:
        emap = escape_json(chunk_arr)
        st = starts64[ridx]

        def espan(a_abs, b_abs):
            ea = emap.map(a_abs)
            return ea, emap.map(b_abs) - ea

        row_end = st + lens64[ridx]
        full_src, full_len = espan(st, row_end)
        host_a = st + np.asarray(out["host_start"])[:n][ridx]
        host_b = st + np.asarray(out["host_end"])[:n][ridx]
        host_src, host_len = espan(host_a, host_b)
        msg_a = st + np.asarray(out["msg_start"])[:n][ridx]
        msg_src, msg_len = espan(msg_a, row_end)
        has_pri = np.asarray(out["has_pri"][:n], dtype=bool)[ridx]
        sev = np.asarray(out["severity"])[:n][ridx].astype(np.int64)

        scratch, ts_off, ts_len = ts_scratch(out, n, ridx, json_f64)
        consts, offs = build_source(
            _C_OPEN, _C_HOST, _C_LEVEL, _C_SHORT_PRI, _C_SHORT_NOPRI,
            _C_TS, _C_TAIL + suffix, _C_SEVD, scratch)
        (o_open, o_host, o_level, o_short_p, o_short_n, o_ts, o_tail,
         o_sevd, o_scratch) = offs
        cbase = int(emap.esc.size)
        src = np.concatenate([emap.esc, consts])

        # (no empty-host substitution: the kernel only marks rows ok
        # when the host span is non-empty, rfc3164.py host_e > host_s)
        seg_src = np.empty((R, _SEGS), dtype=np.int64)
        seg_len = np.empty((R, _SEGS), dtype=np.int64)
        cols = (
            (cbase + o_open, len(_C_OPEN)),
            (full_src, full_len),
            (cbase + o_host, len(_C_HOST)),
            (host_src, host_len),
            (cbase + o_level, np.where(has_pri, len(_C_LEVEL), 0)),
            (cbase + o_sevd + sev, np.where(has_pri, 1, 0)),
            (np.where(has_pri, cbase + o_short_p, cbase + o_short_n),
             np.where(has_pri, len(_C_SHORT_PRI), len(_C_SHORT_NOPRI))),
            (msg_src, msg_len),
            (cbase + o_ts, len(_C_TS)),
            (cbase + o_scratch + ts_off, ts_len),
            (cbase + o_tail, len(_C_TAIL) + len(suffix)),
        )
        for k, (s, ln) in enumerate(cols):
            seg_src[:, k] = s
            seg_len[:, k] = ln

        flat_src = seg_src.ravel()
        flat_len = seg_len.ravel()
        dst0 = exclusive_cumsum(flat_len)
        body = concat_segments(src, flat_src, flat_len, dst0)
        row_off = dst0[::_SEGS]
        tier_lens = np.diff(row_off)
        if syslen:
            final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
                body, row_off, tier_lens)
        else:
            final_buf = body.tobytes()

    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder, scalar_fn=_scalar_3164)
