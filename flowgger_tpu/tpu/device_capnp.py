"""Device-side rfc5424→Cap'n Proto encode (capnp_encoder.rs:36-109
semantics, mirroring encode_capnp_block.py / capnp_wire.py
byte-for-byte).

The wire image is the same fixed-skeleton shape as the DNS block
encoder's 13-segment assembly: framing | root ptr | root struct |
NUL-padded texts | pairs tag+elements | per-pair texts | constant
extra blob.  Every pointer is a self-relative word, so the whole
layout reduces to integer word arithmetic over span lengths — all
computed on device as int32 lanes and emitted as little-endian byte
planes that ride the assembly gather's scratch argument (the
computed analogue of the timestamp text plane).

No escape stage: the tier excludes rows whose emitted SD values carry
JSON escapes (host work), so text segments re-emit verbatim from the
raw batch.  Elision drops the 32-byte framing+data-words head (its
``nwords`` is recomputed host-side from the body length, the stamp is
rendered host-side anyway, facility/severity ride one-byte probe
channels) and the framing suffix.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.capnp:CapnpEncoder"
DIFF_TEST = (
    "tests/test_device_encode_out.py::test_device_capnp_matches_scalar",
)

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..capnp_wire import (
    PAIR_DATA_WORDS,
    PAIR_PTR_WORDS,
    RECORD_DATA_WORDS,
    RECORD_PTR_WORDS,
    WORD,
)
from .device_common import (
    TS_W,
    _out_width,
    assemble_rows,
    build_bank,
    encode_route_ok,
    fetch_encode_driver,
)

_I32 = jnp.int32
_U8 = jnp.uint8

_PAIR_WORDS = PAIR_DATA_WORDS + PAIR_PTR_WORDS   # 4
_ROOT_WORDS = RECORD_DATA_WORDS + RECORD_PTR_WORDS  # 11
_HDR_BYTES = 8 + 8 + _ROOT_WORDS * WORD  # 104
_PW0 = 1 + RECORD_DATA_WORDS  # word index of root pointer slot 0
_ROOT_PTR = (RECORD_DATA_WORDS | (RECORD_PTR_WORDS << 16)) << 32

_PARTS = {
    "z16": b"\x00" * 16,
    "us": b"_",
    "blob": b"",  # replaced per-config by _bank
    "tail": b"",
}


def _bank(suffix: bytes, extras: Tuple[Tuple[str, str], ...] = ()
          ) -> Tuple[bytes, Dict[str, int], Dict[str, bytes]]:
    """Constant bank; ``capnp_extra`` renders to the host tier's exact
    row-invariant blob (_extra_blob), so the two tiers can never
    disagree on extras bytes."""
    from .encode_capnp_block import _extra_blob

    parts = dict(_PARTS)
    parts["blob"] = _extra_blob(list(extras))
    bank, offs = build_bank(parts, suffix)
    return bank, offs, parts


def _render_le_f64(val: float) -> bytes:
    """Stamp bytes: the raw little-endian f64 pattern the root struct's
    second data word carries."""
    import struct

    return struct.pack("<d", float(val))


def elide_spec(suffix: bytes, extras=()):
    return make_elide(suffix)


def make_elide(suffix: bytes):
    """Callable elide: rebuild the 32-byte framing+data-words head
    (segment count, ``nwords`` from the body length, root pointer,
    stamp, facility/severity) and append the framing suffix."""

    def splice(body, row_off, small, ts_text, ts_len, ridx):
        from .device_common import splice_rows

        R = ridx.size
        lens = np.diff(row_off).astype(np.int64)
        nwords = lens // WORD + (32 - 8) // WORD
        head = np.zeros((R, 32), dtype=np.uint8)
        head[:, 4:8] = nwords.astype("<u4").view(np.uint8).reshape(R, 4)
        head[:, 8:16] = np.frombuffer(
            int(_ROOT_PTR).to_bytes(8, "little"), dtype=np.uint8)
        W = ts_text.shape[1] if ts_text.ndim == 2 else 0
        head[:, 16:16 + min(8, W)] = np.asarray(
            ts_text, np.uint8)[ridx][:, :8]
        head[:, 24] = small["fac8"][ridx]
        head[:, 25] = small["sev8"][ridx]
        ins_src = np.concatenate(
            [head.ravel(), np.frombuffer(suffix, dtype=np.uint8)])
        ins_at = np.stack([np.zeros(R, dtype=np.int64), lens], axis=1)
        ins_a = np.stack([
            np.arange(R, dtype=np.int64) * 32,
            np.full(R, R * 32, dtype=np.int64),
        ], axis=1)
        ins_l = np.stack([
            np.full(R, 32, dtype=np.int64),
            np.full(R, len(suffix), dtype=np.int64),
        ], axis=1)
        return splice_rows(body, row_off, ins_src, ins_at, ins_a, ins_l)

    return splice


def _le8(lo, hi):
    """[N] i32 lo/hi word halves → [N, 8] little-endian bytes."""
    cols = [((lo >> (8 * i)) & 0xFF).astype(_U8) for i in range(4)]
    cols += [((hi >> (8 * i)) & 0xFF).astype(_U8) for i in range(4)]
    return jnp.stack(cols, axis=1)


def _tw(blen):
    """Words a NUL-terminated text of blen bytes occupies."""
    return (blen + 1 + WORD - 1) // WORD


@partial(jax.jit, static_argnames=("suffix", "extras", "assemble",
                                   "elide"))
def _encode_kernel(batch, lens, dec, ts_text, ts_len, *, suffix: bytes,
                   extras: Tuple[Tuple[str, str], ...] = (),
                   assemble: bool = True, elide: bool = False):
    """rfc5424→capnp: _capnp_assemble's word layout + segment plan as
    int32 device arithmetic; pointer/tag/element words become
    little-endian byte planes appended to the assembly scratch."""
    N, L = batch.shape
    bank, off, parts = _bank(suffix, extras)
    blob = parts["blob"]
    blob_w = len(blob) // WORD
    P = dec["name_start"].shape[1]
    PLANE_W = _HDR_BYTES + WORD + P * _PAIR_WORDS * WORD
    OW = _out_width(L, L + len(bank) + PLANE_W)
    zero = jnp.zeros((N,), dtype=_I32)
    cbase = L
    tbase = L + len(bank)

    def span(sk, ek):
        s = dec[sk].astype(_I32)
        return s, jnp.maximum(dec[ek].astype(_I32) - s, 0)

    host_s, host_l = span("host_start", "host_end")
    app_s, app_l = span("app_start", "app_end")
    proc_s, proc_l = span("proc_start", "proc_end")
    msgid_s, msgid_l = span("msgid_start", "msgid_end")
    msg_s = dec["msg_trim_start"].astype(_I32)
    trim_e = dec["trim_end"].astype(_I32)
    msg_l = jnp.maximum(trim_e - msg_s, 0)
    has_msg = msg_l > 0
    full_s = dec["full_start"].astype(_I32)
    full_l = jnp.maximum(trim_e - full_s, 0)
    sdc = dec["sd_count"].astype(_I32)
    has_sd = sdc > 0
    sid_s = dec["sid_start"][:, 0].astype(_I32)
    sid_l = jnp.maximum(dec["sid_end"][:, 0].astype(_I32) - sid_s, 0)
    pc = dec["pair_count"].astype(_I32)

    # capnp carries only sd[0] (capnp_encoder.rs:78-80) — pair_sd is
    # nondecreasing, so block-0 membership is a prefix mask and the
    # first k0 element slots are exactly the emitted ones
    pvalid, name_s, name_l, val_s, val_l = [], [], [], [], []
    esc_any = jnp.zeros((N,), dtype=bool)
    for j in range(P):
        pv = (j < pc) & (dec["pair_sd"][:, j].astype(_I32) == 0)
        pvalid.append(pv)
        ns = dec["name_start"][:, j].astype(_I32)
        vs = dec["val_start"][:, j].astype(_I32)
        name_s.append(ns)
        name_l.append(jnp.where(
            pv, jnp.maximum(dec["name_end"][:, j].astype(_I32) - ns, 0), 0))
        val_s.append(vs)
        val_l.append(jnp.where(
            pv, jnp.maximum(dec["val_end"][:, j].astype(_I32) - vs, 0), 0))
        esc_any |= dec["val_has_esc"][:, j].astype(bool) & (j < pc)

    # ---- word layout (encode_capnp_block.py:149-195) ----
    texts = [
        (host_s, host_l, None),
        (app_s, app_l, None),
        (proc_s, proc_l, None),
        (msgid_s, msgid_l, None),
        (msg_s, msg_l, has_msg),
        (full_s, full_l, None),
    ]
    tw = [_tw(l) if g is None else jnp.where(g, _tw(l), 0)
          for _, l, g in texts]
    si_w = jnp.where(has_sd, _tw(sid_l), 0)
    key_w = [jnp.where(pvalid[j], _tw(name_l[j] + 1), 0)
             for j in range(P)]
    valw = [jnp.where(pvalid[j], _tw(val_l[j]), 0) for j in range(P)]
    k0 = zero
    for j in range(P):
        k0 = k0 + jnp.where(pvalid[j], 1, 0)
    kw_sum = zero
    for j in range(P):
        kw_sum = kw_sum + key_w[j] + valw[j]
    pairs_w = jnp.where(has_sd, 1 + k0 * _PAIR_WORDS + kw_sum, 0)

    w_at = [zero + (1 + _ROOT_WORDS)]
    for w in tw:
        w_at.append(w_at[-1] + w)
    w_sid = w_at[-1]
    w_pairs = w_sid + si_w
    w_extra = w_pairs + pairs_w
    nwords = w_extra + blob_w

    tier = (dec["ok"].astype(bool)
            & ~dec["has_high"].astype(bool)
            & ~esc_any)

    def _lptr(ptr_word, target, count, elem, gate):
        off_w = target - ptr_word - 1
        lo = (off_w << 2) | 1
        hi = elem | (count << 3)
        if gate is not None:
            lo = jnp.where(gate, lo, 0)
            hi = jnp.where(gate, hi, 0)
        return _le8(lo, hi)

    segs = [None]  # slot 0: hdr plane segment, filled below
    out_parts = []

    def add_const(name, gate=None, ln=None):
        l0 = len(parts[name]) if ln is None else ln
        lv = zero + l0
        if gate is not None:
            lv = jnp.where(gate, lv, 0)
        segs.append((zero + (cbase + off[name]), lv))
        out_parts.append(lv)

    def add_seg(s, lv):
        segs.append((s, lv))
        out_parts.append(lv)

    # ---- segment plan (encode_capnp_block.py:279-307) ----
    for (s, l, g), w in zip(texts, tw):
        gl = l if g is None else jnp.where(g, l, 0)
        add_seg(s, gl)
        pad = w * WORD - gl
        if g is not None:
            pad = jnp.where(g, pad, 0)
        add_seg(zero + (cbase + off["z16"]), pad)
    add_seg(sid_s, jnp.where(has_sd, sid_l, 0))
    add_seg(zero + (cbase + off["z16"]),
            jnp.where(has_sd, si_w * WORD - sid_l, 0))
    add_seg(zero + (tbase + _HDR_BYTES),
            jnp.where(has_sd, WORD + k0 * _PAIR_WORDS * WORD, 0))
    for j in range(P):
        pv = pvalid[j]
        add_const("us", pv, 1)
        add_seg(name_s[j], name_l[j])
        add_seg(zero + (cbase + off["z16"]),
                jnp.where(pv, key_w[j] * WORD - (name_l[j] + 1), 0))
        add_seg(val_s[j], val_l[j])
        add_seg(zero + (cbase + off["z16"]),
                jnp.where(pv, valw[j] * WORD - val_l[j], 0))
    add_const("blob")
    if not elide:
        add_const("tail", ln=len(suffix))

    hdr_seg_len = 72 if elide else _HDR_BYTES
    out_len = zero + hdr_seg_len
    for lv in out_parts:
        out_len = out_len + lv
    tier = tier & (out_len <= OW)
    if not assemble:
        return {"tier": tier,
                "fac8": dec["facility"].astype(_U8),
                "sev8": dec["severity"].astype(_U8)}

    # ---- byte planes: root pointers, header, pairs scratch ----
    ptr_planes = []
    for slot, ((_, l, g), w0) in enumerate(zip(texts, w_at)):
        ptr_planes.append(_lptr(zero + (_PW0 + slot), w0, l + 1, 2, g))
    ptr_planes.append(_lptr(zero + (_PW0 + 6), w_sid, sid_l + 1, 2,
                            has_sd))
    ptr_planes.append(_lptr(zero + (_PW0 + 7), w_pairs,
                            k0 * _PAIR_WORDS, 7, has_sd))
    if blob_w:
        ptr_planes.append(_lptr(zero + (_PW0 + 8), w_extra,
                                jnp.full((N,), len(extras) * _PAIR_WORDS,
                                         dtype=_I32), 7, None))
    else:
        ptr_planes.append(jnp.zeros((N, 8), dtype=_U8))

    tsb = ts_text.astype(_U8)
    if tsb.shape[1] < 8:
        tsb = jnp.pad(tsb, ((0, 0), (0, 8 - tsb.shape[1])))
    root8 = jnp.broadcast_to(
        jnp.asarray(np.frombuffer(int(_ROOT_PTR).to_bytes(8, "little"),
                                  dtype=np.uint8)), (N, 8))
    hdr = jnp.concatenate(
        [jnp.zeros((N, 4), dtype=_U8),
         _le8(nwords, zero)[:, :4],
         root8,
         tsb[:, :8],
         dec["facility"].astype(_U8)[:, None],
         dec["severity"].astype(_U8)[:, None],
         jnp.zeros((N, 6), dtype=_U8)] + ptr_planes, axis=1)

    tag = _le8(jnp.where(has_sd, k0 << 2, 0),
               jnp.where(has_sd,
                         zero + (PAIR_DATA_WORDS | (PAIR_PTR_WORDS << 16)),
                         0))
    pblocks = [tag]
    cursor = w_pairs + 1 + k0 * _PAIR_WORDS
    for j in range(P):
        kw0 = cursor
        cursor = cursor + key_w[j]
        kw1 = cursor
        cursor = cursor + valw[j]
        base = w_pairs + 1 + j * _PAIR_WORDS
        pblocks.append(jnp.zeros((N, PAIR_DATA_WORDS * WORD), dtype=_U8))
        pblocks.append(_lptr(base + PAIR_DATA_WORDS, kw0,
                             name_l[j] + 2, 2, pvalid[j]))
        pblocks.append(_lptr(base + PAIR_DATA_WORDS + 1, kw1,
                             val_l[j] + 1, 2, pvalid[j]))
    plane = jnp.concatenate([hdr] + pblocks, axis=1)

    segs[0] = ((zero + (tbase + 32), zero + 72) if elide
               else (zero + tbase, zero + _HDR_BYTES))
    acc, out_len2 = assemble_rows(segs, batch.astype(_U8), bank, plane,
                                  N, OW)
    return acc, out_len2, tier


def _small_fetch(out, fetch):
    small = {k: fetch(out[k])
             for k in ("ok", "days", "sod", "off", "nanos")}
    small["fac8"] = fetch(out["fac8"])
    small["sev8"] = fetch(out["sev8"])
    return small


def route_ok(encoder, merger) -> bool:
    """Device encode applies to capnp output over line/nul/syslen
    framing (capnp_extra always renders to one static blob)."""
    from ..encoders.capnp import CapnpEncoder

    return encode_route_ok(encoder, merger, CapnpEncoder)


# same ladder constants as the →GELF split tier
FALLBACK_FRAC = 0.05
DECLINE_LIMIT = 3
COOLDOWN = 16


def fetch_encode(handle, packed, encoder, merger, route_state=None):
    """rfc5424→capnp split-tier entry; returns
    (BlockResult | None, fetch_seconds)."""
    from .block_common import merger_suffix
    from .materialize import _scalar_line

    out, _, _, _max_sd, _impl_unused, batch_dev, lens_dev = handle
    suffix, syslen = merger_suffix(merger)
    extras = tuple((str(k), str(v)) for k, v in
                   getattr(encoder, "extra", []))

    def kernel(ts_text, ts_len, assemble):
        return _encode_kernel(batch_dev, lens_dev, dict(out), ts_text,
                              ts_len, suffix=suffix, extras=extras,
                              assemble=assemble, elide=True)

    from .aot import encode_wrap
    from .rfc5424 import best_scan_impl

    kernel = encode_wrap("device_capnp", kernel, batch_dev, lens_dev,
                         dict(out), suffix, best_scan_impl(), extras)

    return fetch_encode_driver(
        kernel, out, batch_dev, lens_dev, packed, encoder, merger,
        route_state, suffix, syslen, scalar_fn=_scalar_line,
        fallback_frac=FALLBACK_FRAC, decline_limit=DECLINE_LIMIT,
        cooldown=COOLDOWN, ts_render=_render_le_f64,
        small_fetch_fn=_small_fetch, elide=make_elide(suffix),
        route_label="rfc5424_capnp", fused_counters=False)
