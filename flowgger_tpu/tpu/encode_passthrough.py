"""Columnar-to-passthrough encode: kernel-ok RFC5424 rows emit their raw
line (BOM-stripped, whitespace-rtrimmed) without materializing Records —
the passthrough encoder's output *is* ``full_msg``
(passthrough_encoder.rs:22-46), which for the fast path is a byte slice.
Rows flagged by the kernel, oversized, or non-ASCII take the Record path.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..encoders import EncodeError
from .encode_gelf import EncodedResult
from .materialize import _scalar_line


def encode_rfc5424_passthrough(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
) -> List[EncodedResult]:
    ok = np.asarray(out["ok"]).tolist()
    full_start = np.asarray(out["full_start"]).tolist()
    starts_l = starts.tolist() if hasattr(starts, "tolist") else starts
    lens_l = orig_lens.tolist() if hasattr(orig_lens, "tolist") else orig_lens
    results: List[EncodedResult] = []
    for n in range(n_real):
        s = starts_l[n]
        ln = lens_l[n]
        raw = chunk_bytes[s:s + ln]
        if ok[n] and ln <= max_len and raw.isascii():
            results.append(EncodedResult(raw[full_start[n]:].rstrip(), None, ""))
            continue
        try:
            line = raw.decode("utf-8")
        except UnicodeDecodeError:
            results.append(EncodedResult(None, "__utf8__", ""))
            continue
        res = _scalar_line(line)
        if res.record is None:
            results.append(EncodedResult(None, res.error, line))
            continue
        try:
            results.append(EncodedResult(encoder.encode(res.record), None, line))
        except EncodeError as e:
            results.append(EncodedResult(None, str(e), line))
    return results
