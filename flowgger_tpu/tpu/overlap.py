"""Overlapped batch execution: the in-flight submit/fetch window and
the device-vs-host route economics.

The serial BatchHandler shape — pack, dispatch, fetch, encode, sink,
one batch at a time — sums every stage's latency, so the e2e rate is
bounded by the *slowest sequential path* instead of the slowest *stage*
(BENCH r5: device_fetch alone was 7.94s of an 8.08s batch wall).
ParPaRaw (arxiv 1905.13415) and simdjson (1902.08318) both get their
throughput from stage pipelining; this module is the flowgger-tpu shape
of that idea:

``InflightWindow``
    A bounded window of submitted device batches (``input.tpu_inflight``,
    default 2).  The ingest thread packs and *submits* batch N+1 while a
    dedicated fetcher thread *fetches/encodes/enqueues* batch N — device
    compute, D2H transfer, and host encode overlap instead of summing.
    Strict batch ordering is structural: one fetcher thread pops a FIFO,
    so blocks reach the merger in submit order no matter how long any
    fetch takes.  A full window blocks ``submit`` (``overlap_stall_
    seconds``) — backpressure flows to the splitter and from there to
    the socket, exactly like the bounded queue it feeds.

    Failure semantics: the pop function owns degradation (the device-
    decode circuit breaker re-decodes a failed batch through the scalar
    oracle *at its position in the window*, so byte-identity and
    ordering survive mid-window device failures).  An exception the pop
    function chooses to propagate (breaker disabled = legacy fail-fast)
    is stashed and re-raised on the ingest thread at the next
    ``fence()``/``submit()`` — batches behind the failed one still drain
    in order first.

``RouteEconomics``
    The device-encode tier is gated by *applicability* (route_ok) and
    *health* (decline hysteresis), but never by *profitability*: on a
    backend where the kernels execute slowly (CPU fallback, a wedged
    relay), the device tier can cost more wall time than the host block
    encode it replaces while every probe still "succeeds".  This tracker
    keeps an EWMA of measured seconds/row for both paths and routes
    batches to the cheaper one, re-probing the loser periodically
    (``input.tpu_encode_probe_every``) so a recovered device wins back
    the traffic.  On a real TPU the device tier wins the comparison and
    nothing changes; on this container's CPU backend the host path wins
    ~8x and the executor becomes host-stage-bound, which is the point.

``LaneSet``
    N per-device lanes, each an ``InflightWindow`` with its own fetcher
    thread and submit-ahead depth, fed round-robin by the ingest thread
    (ParPaRaw's parallel-lane shape: log decode has no cross-record
    state, so lanes never need to talk).  The pop function runs
    concurrently across lanes but returns an *emit closure* instead of
    enqueueing directly; a single FIFO sequencer (a ticket turnstile)
    runs those closures in global submit order, so blocks reach the
    merger in exactly the order batches were ingested no matter which
    lane finished first.  ``fence()`` fences **all** lanes — every
    synchronous-emit path (breaker degradation, Record path, shutdown
    drain) keeps its ordering barrier across the whole lane set.

Metrics: ``inflight_depth`` gauge (total in-flight across lanes),
``lane_depth`` (deepest lane) and per-lane ``lane{i}_depth`` gauges,
``overlap_stall_seconds``, ``dispatch_seconds`` (submit-side
pack+dispatch, recorded by the handler), ``fetch_seconds``
(fetch-behind stage wall), and ``encode_route_device`` /
``encode_route_host`` batch counters (per-lane seconds/row ride as
``lane{i}_route_*_spr`` gauges).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from ..utils.metrics import registry as _metrics

DEFAULT_INFLIGHT = 2
DEFAULT_PROBE_EVERY = 256
# the loser path must be this much slower (seconds/row) before traffic
# moves; hysteresis against flapping on noisy single-batch samples
ECON_MARGIN = 1.5
# EWMA weight of the newest sample (small history, fast adaptation)
ECON_ALPHA = 0.4
# a device tier at or under this measured seconds/row is performing at
# accelerator levels — no host path can beat it, so the comparison
# sample (one host-routed batch) is never paid.  Only a device tier
# slower than ~100K rows/s (CPU fallback, wedged relay) triggers the
# host probe at all.
DEVICE_OK_SPR = 1e-5


class InflightWindow:
    """Bounded FIFO of submitted batches with a fetch-behind worker.

    ``pop_fn(entry)`` runs on the fetcher thread and must do the fetch +
    encode + enqueue for one entry; entries complete in submit order.
    ``depth=0`` disables the worker: ``submit`` pops inline (strictly
    serial, the pre-overlap behavior) — the degenerate window tests and
    single-threaded debugging use this.
    """

    def __init__(self, depth: int, pop_fn: Callable, name: str = "tpu",
                 supervisor=None, gauge: str = "inflight_depth"):
        self.depth = max(0, int(depth))
        self._pop_fn = pop_fn
        self._name = name
        self._supervisor = supervisor
        self._gauge = gauge
        self._lock = threading.Lock()
        self._nonfull = threading.Condition(self._lock)
        self._nonempty = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._popping = False      # fetcher is inside pop_fn
        self._pending_exc: Optional[BaseException] = None
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        _metrics.init_gauge(gauge, 0)

    # -- ingest side -------------------------------------------------------
    def submit(self, entry) -> None:
        """Queue one submitted batch; blocks while the window is full
        (backpressure), re-raising any stashed fetcher exception."""
        if self.depth == 0:
            self._pop_fn(entry)
            return
        self._ensure_thread()
        t0 = time.perf_counter()
        with self._lock:
            self._raise_pending_locked()
            while len(self._queue) + (1 if self._popping else 0) >= self.depth:
                self._nonfull.wait(timeout=0.5)
                self._raise_pending_locked()
            self._queue.append(entry)
            _metrics.set_gauge(self._gauge,
                               len(self._queue) + (1 if self._popping else 0))
            self._nonempty.notify()
        stalled = time.perf_counter() - t0
        if stalled > 1e-4:
            _metrics.add_seconds("overlap_stall_seconds", stalled)

    def fence(self) -> None:
        """Block until every submitted batch has been fetched and
        emitted (the in-flight window is empty and the fetcher idle),
        then re-raise any exception the fetcher stashed.  This is the
        ordering barrier every synchronous-emit path takes before
        bypassing the window (breaker-open scalar batches, Record-path
        encodes, shutdown drain)."""
        if self.depth == 0:
            return
        with self._lock:
            while self._queue or self._popping:
                self._idle.wait(timeout=0.5)
            self._raise_pending_locked()

    def pending(self) -> int:
        with self._lock:
            return len(self._queue) + (1 if self._popping else 0)

    def close(self) -> None:
        """Stop the fetcher after the queue drains (tests/shutdown)."""
        if self.depth == 0 or self._thread is None:
            return
        self.fence()
        with self._lock:
            self._closed = True
            self._nonempty.notify_all()
        self._thread.join(timeout=5)

    # -- fetcher side ------------------------------------------------------
    def _raise_pending_locked(self) -> None:
        if self._pending_exc is not None:
            exc, self._pending_exc = self._pending_exc, None
            raise exc

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._closed = False
            name = f"{self._name}-fetch"
            if self._supervisor is not None:
                self._thread = self._supervisor.spawn(
                    self._run, name, exhausted="exit")
            else:
                self._thread = threading.Thread(
                    target=self._run, name=name, daemon=True)
                self._thread.start()

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._closed:
                    self._nonempty.wait(timeout=0.5)
                if self._closed and not self._queue:
                    self._idle.notify_all()
                    return
                entry = self._queue.popleft()
                self._popping = True
                _metrics.set_gauge(self._gauge, len(self._queue) + 1)
                self._nonfull.notify()
            t0 = time.perf_counter()
            try:
                self._pop_fn(entry)
            except BaseException as e:  # noqa: BLE001 - ferried to ingest
                # the pop fn already owns degradation (breaker + scalar
                # fallback); anything it lets out is the legacy fail-
                # fast contract and belongs on the ingest thread
                exc = e
            else:
                exc = None
            _metrics.add_seconds("fetch_seconds", time.perf_counter() - t0)
            with self._lock:
                if exc is not None and self._pending_exc is None:
                    self._pending_exc = exc
                self._popping = False
                _metrics.set_gauge(self._gauge, len(self._queue))
                self._nonfull.notify()
                if not self._queue:
                    self._idle.notify_all()


class _Sequencer:
    """FIFO ticket turnstile: emits happen in ticket order.

    ``ticket()`` hands out monotonically increasing tickets at submit
    time; a lane that finished its fetch+encode calls ``wait_turn(t)``
    before emitting and ``done(t)`` after (or instead, when it failed
    and has nothing to emit — ``done`` alone releases the turnstile so
    one failed batch can never wedge the lanes behind it).  ``done`` is
    idempotent and order-independent: completed tickets park in a set
    and the cursor advances over every contiguous finished ticket."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._issued = 0
        self._next = 0
        self._finished = set()

    def ticket(self) -> int:
        with self._lock:
            t = self._issued
            self._issued += 1
            return t

    def wait_turn(self, ticket: int) -> None:
        with self._lock:
            while self._next != ticket:
                self._cond.wait(timeout=0.5)

    def done(self, ticket: int) -> None:
        with self._lock:
            if ticket < self._next:
                return  # already advanced past (idempotent)
            self._finished.add(ticket)
            while self._next in self._finished:
                self._finished.discard(self._next)
                self._next += 1
            self._cond.notify_all()


class LaneSet:
    """N per-device dispatch lanes behind one FIFO sequencer.

    Each lane is an ``InflightWindow`` (own fetcher thread, own
    submit-ahead ``depth``); ``submit`` assigns a global ticket and
    round-robins entries across lanes, so device decode / D2H / host
    encode for several batches run concurrently on several devices while
    the sequencer still emits blocks in strict submit order.

    Pop contract (different from ``InflightWindow``'s!): ``pop_fn(
    payload, lane)`` runs concurrently on the lane fetcher threads and
    must return either ``None`` or a zero-argument *emit closure*; the
    LaneSet runs that closure under the sequencer turnstile.  An
    exception out of ``pop_fn`` keeps the InflightWindow ferry contract
    (stashed, re-raised on the ingest thread at the lane's next
    ``submit``/``fence``) and releases the failed ticket so later
    batches still drain in order.

    ``lanes=1`` is byte-for-byte the PR 4 single-window executor (the
    turnstile is always open for the only in-order lane)."""

    def __init__(self, depth: int, pop_fn: Callable, lanes: int = 1,
                 name: str = "tpu", supervisor=None):
        self.lanes = max(1, int(lanes))
        self.depth = max(0, int(depth))
        self._pop_fn = pop_fn
        self._seq = _Sequencer()
        self._rr = 0
        self._submit_lock = threading.Lock()
        multi = self.lanes > 1
        self._windows = [
            InflightWindow(
                depth, self._lane_pop, name=f"{name}-lane{i}" if multi
                else name, supervisor=supervisor,
                gauge=f"lane{i}_depth" if multi else "inflight_depth")
            for i in range(self.lanes)
        ]
        if multi:
            _metrics.init_gauge("inflight_depth", 0)
            _metrics.init_gauge("lane_depth", 0)

    # -- ingest side -------------------------------------------------------
    def next_lane(self) -> int:
        """Reserve the next round-robin lane index (callers that need
        the lane's device *before* building the submit payload)."""
        with self._submit_lock:
            lane = self._rr
            self._rr = (self._rr + 1) % self.lanes
            return lane

    def submit(self, lane: int, payload) -> None:
        """Ticket + enqueue one batch on ``lane``; blocks while that
        lane's window is full (backpressure), re-raising any ferried
        fetcher exception.  Tickets are issued in call order under one
        lock, so emission order is exactly submission order."""
        with self._submit_lock:
            ticket = self._seq.ticket()
            try:
                self._windows[lane % self.lanes].submit(
                    (ticket, lane, payload))
            except BaseException:
                # the window refused the entry (ferried fetcher
                # exception re-raised, depth-0 inline pop failed):
                # release the ticket or the sequencer wedges every
                # later batch behind a turn that can never come
                self._seq.done(ticket)
                raise
        self._update_depth_gauges()

    def fence(self) -> None:
        """Fence every lane (and therefore the sequencer: an empty lane
        set has run every emit closure).  All lanes are fenced even when
        one re-raises a ferried exception — the first exception
        propagates after the others have drained, so a synchronous emit
        after a throwing fence still cannot overtake in-flight work."""
        pending_exc = None
        for w in self._windows:
            try:
                w.fence()
            except BaseException as e:  # noqa: BLE001 - ferried, re-raised below
                if pending_exc is None:
                    pending_exc = e
        self._update_depth_gauges()
        if pending_exc is not None:
            raise pending_exc

    def pending(self) -> int:
        return sum(w.pending() for w in self._windows)

    def close(self) -> None:
        for w in self._windows:
            w.close()

    def _update_depth_gauges(self) -> None:
        if self.lanes <= 1:
            return  # the single window owns inflight_depth itself
        depths = [w.pending() for w in self._windows]
        _metrics.set_gauge("inflight_depth", sum(depths))
        _metrics.set_gauge("lane_depth", max(depths))

    # -- lane fetcher side -------------------------------------------------
    def _lane_pop(self, entry) -> None:
        """Runs on a lane's fetcher thread: compute (concurrent), then
        emit under the sequencer turnstile (strict submit order)."""
        ticket, lane, payload = entry
        try:
            emit = self._pop_fn(payload, lane)
            self._seq.wait_turn(ticket)
            if emit is not None:
                emit()
        finally:
            # always release the turnstile — a failed batch (ferried
            # fail-fast exception) must not wedge the lanes behind it
            self._seq.done(ticket)


def resolve_lanes(config, mesh_mode: str = "auto"):
    """Resolve ``input.tpu_lanes`` to (lane_count, per-lane devices).

    Default ("auto", same precedent as ``input.tpu_mesh``): one lane per
    local device when more than one *real* accelerator is visible, else
    1 — so CPU test meshes and single-chip hosts keep the PR 4
    single-window executor.  An explicit integer engages anywhere
    (tests/benches set ``tpu_lanes = 2`` on the forced-host CPU mesh);
    more lanes than devices cycle over them (extra lanes still overlap
    host encode).  Lane dispatch and the sharded decode mesh are
    mutually exclusive — lanes give each chip its *own* batches (no
    cross-chip sync on the hot path), the mesh shards one batch across
    chips — so ``tpu_lanes > 1`` with ``tpu_mesh = "on"`` is a config
    error, and auto-resolved lanes > 1 disable the mesh.  Multi-host:
    lanes span only this host's chips (``jax.local_devices()``), like
    the mesh's dp axis — each host lane-dispatches its own stream.

    Lane 0 of a single-lane set stays on the default device (``None``)
    so the resolved setup is identical to the pre-lane executor."""
    from ..config import ConfigError

    req = config.lookup_int(
        "input.tpu_lanes",
        "input.tpu_lanes must be an integer (device lanes)", None)
    if req is not None and req < 1:
        raise ConfigError("input.tpu_lanes must be >= 1")
    if req is not None and req > 1 and mesh_mode == "on":
        raise ConfigError(
            'input.tpu_lanes > 1 and input.tpu_mesh = "on" are mutually '
            "exclusive (lanes give each chip its own batches; the mesh "
            "shards one batch across chips)")
    if req == 1:
        return 1, [None]
    import jax

    if req is None:
        if mesh_mode == "on" or jax.default_backend() == "cpu":
            return 1, [None]
        devs = list(jax.local_devices())
        if len(devs) <= 1:
            return 1, [None]
        return len(devs), devs
    devs = list(jax.local_devices())
    return req, [devs[i % len(devs)] for i in range(req)]


class RouteEconomics:
    """Measured seconds/row for the device-encode tier vs the host
    block-encode path; ``allow_device()`` routes each batch to the
    cheaper one with periodic re-probes of the loser.

    Probing order: the device tier goes first; while its measured
    seconds/row stays at accelerator levels (``DEVICE_OK_SPR``) the host
    path is never paid at all.  Only a device tier measuring slow buys
    one host batch for the comparison, after which the loser re-probes
    every ``probe_every`` batches.  ``enabled=False`` pins the legacy
    always-device behavior."""

    def __init__(self, enabled: bool = True,
                 probe_every: int = DEFAULT_PROBE_EVERY,
                 margin: float = ECON_MARGIN,
                 ok_spr: float = DEVICE_OK_SPR,
                 label: Optional[str] = None):
        self.enabled = enabled
        self.probe_every = max(2, int(probe_every))
        self.margin = margin
        self.ok_spr = ok_spr
        # label ("lane0", ...) exports this tracker's EWMAs as gauges —
        # per-lane economics so one sick chip degrades alone, visibly
        self.label = label
        self._lock = threading.Lock()
        # EWMA seconds/row per path: "fused" (single-program
        # decode→encode, tpu/fused_routes.py), "device" (split decode +
        # device encode), "host" (split decode + host block encode)
        self._spr = {"fused": None, "device": None, "host": None}
        self._batches = 0
        self._fused_batches = 0
        # steady-state winner per comparison arm, for the degradation
        # journal: the device/fused tiers are the probe-first defaults,
        # so the first measured re-route away from them (and every flip
        # back) is one economics_switch event
        self._winner = {"split": "device", "fused": "fused"}

    def allow_fused(self) -> bool:
        """Fused-vs-split arm of the economics, decided at submit time
        (the fused/split choice changes what gets dispatched).  Probing
        order mirrors allow_device: the fused tier goes first; while it
        measures at accelerator speed the split path is never paid.  A
        slow fused tier buys split batches for the comparison, after
        which the loser re-probes every ``probe_every`` batches.  The
        split path's own device-vs-host economics stay in
        ``allow_device`` — this arm only picks which pipeline runs."""
        if not self.enabled:
            return True
        with self._lock:
            self._fused_batches += 1
            fused = self._spr["fused"]
            split = [v for v in (self._spr["device"], self._spr["host"])
                     if v is not None]
            best_split = min(split) if split else None
            if fused is None:
                return True          # no fused sample yet: probe it
            if best_split is None:
                # healthy fused tier: never pay the split comparison; a
                # slow-measuring one buys split batches to compare
                return fused <= self.ok_spr
            probe = self._fused_batches % self.probe_every == 0
            if fused > best_split * self.margin:
                return probe         # fused losing: re-probe on schedule
            if best_split > fused * self.margin:
                return not probe     # split losing: re-sample on schedule
            return True              # within noise: prefer fused

    def allow_device(self) -> bool:
        if not self.enabled:
            return True
        with self._lock:
            self._batches += 1
            dev, host = self._spr["device"], self._spr["host"]
            if dev is None:
                return True          # no device sample yet: probe it
            if host is None:
                # healthy accelerator: never pay the host comparison;
                # a slow-measuring device buys one host batch to compare
                return dev <= self.ok_spr
            probe = self._batches % self.probe_every == 0
            if dev > host * self.margin:
                return probe         # device losing: re-probe on schedule
            if host > dev * self.margin:
                return not probe     # host losing: re-sample it on schedule
            return True              # within noise: prefer the device tier

    def observe(self, path: str, rows: int, seconds: float) -> None:
        if not self.enabled or rows <= 0 or path not in self._spr:
            return
        spr = seconds / rows
        switches = []
        with self._lock:
            prev = self._spr[path]
            ewma = spr if prev is None else prev + ECON_ALPHA * (spr - prev)
            self._spr[path] = ewma
            switches = self._winner_flips_locked()
        _metrics.inc(f"encode_route_{path}")
        if self.label is not None:
            _metrics.set_gauge(f"{self.label}_route_{path}_spr", ewma)
        for arm, old, new, new_spr, old_spr in switches:
            from ..obs import events as _events

            _events.emit(
                "economics", "economics_switch", route=arm,
                detail=f"{old} -> {new} "
                       f"({old}={old_spr:.3g} s/row, {new}={new_spr:.3g})",
                lane=(int(self.label[4:]) if self.label
                      and self.label.startswith("lane") else None),
                cost=new_spr, cost_unit="s_per_row",
                msg=f"route economics [{self.label or 'lane0'}/{arm}]: "
                    f"{old} -> {new} (measured {new_spr:.3g} s/row vs "
                    f"{old_spr:.3g})")

    def _winner_flips_locked(self):
        """Steady-state winner changes (margin-hysteretic, mirroring
        allow_device/allow_fused routing) for the journal; returns
        [(arm, old, new, new_spr, old_spr), ...]."""
        flips = []
        dev, host = self._spr["device"], self._spr["host"]
        if dev is not None and host is not None:
            old = self._winner["split"]
            new = old
            if dev > host * self.margin:
                new = "host"
            elif host > dev * self.margin:
                new = "device"
            if new != old:
                self._winner["split"] = new
                flips.append(("split", old, new,
                              dev if new == "device" else host,
                              host if new == "device" else dev))
        fused = self._spr["fused"]
        split = [v for v in (dev, host) if v is not None]
        best_split = min(split) if split else None
        if fused is not None and best_split is not None:
            old = self._winner["fused"]
            new = old
            if fused > best_split * self.margin:
                new = "split"
            elif best_split > fused * self.margin:
                new = "fused"
            if new != old:
                self._winner["fused"] = new
                flips.append(("fused", old, new,
                              fused if new == "fused" else best_split,
                              best_split if new == "fused" else fused))
        return flips

    def snapshot(self) -> dict:
        with self._lock:
            return {"fused_s_per_row": self._spr["fused"],
                    "device_s_per_row": self._spr["device"],
                    "host_s_per_row": self._spr["host"],
                    "batches": self._batches}

    @classmethod
    def from_config(cls, config, label: Optional[str] = None
                    ) -> "RouteEconomics":
        enabled = config.lookup_bool(
            "input.tpu_encode_economics",
            "input.tpu_encode_economics must be a boolean", True)
        probe_every = config.lookup_int(
            "input.tpu_encode_probe_every",
            "input.tpu_encode_probe_every must be an integer (batches)",
            DEFAULT_PROBE_EVERY)
        return cls(enabled=enabled, probe_every=probe_every, label=label)


def inflight_depth_from_config(config) -> int:
    from ..config import ConfigError

    depth = config.lookup_int(
        "input.tpu_inflight",
        "input.tpu_inflight must be an integer (batches)", DEFAULT_INFLIGHT)
    if depth < 0:
        raise ConfigError("input.tpu_inflight must be >= 0")
    return depth
