r"""Columnar LTSV decoder (BASELINE.json config #2).

Scalar spec: flowgger_tpu/decoders/ltsv.py (reference
ltsv_decoder.rs:23-267).  Line shape: tab-separated ``key:value`` parts;
special keys time/host/message/level; everything else becomes an SD pair
(typed by the host-side schema).

Columnar plan (same no-gather discipline as tpu/rfc5424.py):

- tab cumsum segments the line into parts; the k-th part's span and its
  first ``:`` come from payload-packed masked min-reductions;
- the special keys are found *elementwise*: position p starts ``time:``
  iff the five shifted byte-planes match ``t i m e :`` and p is a part
  start (line start or preceded by a tab) — one vectorized pattern per
  special key, last occurrence wins via a max-reduction (the scalar
  decoder's assignments also overwrite);
- ``time`` values parse on-device for the two fast-path forms: plain
  unix float (optional sign/fraction) and (optionally ``[...]``-wrapped)
  RFC3339; apache-english timestamps and other oddities flag the row to
  the scalar oracle;
- ``level`` parses as an int; out-of-range falls back (exact error text
  comes from the oracle);
- remaining parts are emitted as (key, value) span pairs; schema typing
  (u64/i64/f64/bool + suffixes) happens at host materialization where
  Python values are being built anyway.

ts result is returned as integer pieces: unix float values as
(mantissa, scale) can't cover the f64 domain, so the kernel only
fast-paths RFC3339 (days/sod/off/nanos like rfc5424) and flags plain
floats for a *vectorized host* parse (numpy float64 on the value spans
is exact and cheap) — ``ts_kind`` 0=rfc3339, 1=float-span, else fallback.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp

from .rfc5424 import (
    _cummax,
    _days_from_civil,
    _days_in_month,
    _min_where,
    _scan_ordinals,
    _shift_left,
    _shift_right,
    best_extract_impl,
    best_scan_impl,
    extract_by_ord,
)

DEFAULT_MAX_PARTS = 24
_I32 = jnp.int32


def _match_at(bb, text: bytes, valid):
    """Elementwise: does ``text`` start at each position?  Uses shifted
    byte planes only (no gathers)."""
    m = (bb == text[0]) & valid
    for i, ch in enumerate(text[1:], start=1):
        m &= _shift_left(bb, i, 0) == ch
    return m


def decode_ltsv(batch: jnp.ndarray, lens: jnp.ndarray,
                max_parts: int = DEFAULT_MAX_PARTS,
                scan_impl: str = None,
                extract_impl: str = None) -> Dict[str, jnp.ndarray]:
    if scan_impl is None:
        scan_impl = best_scan_impl()
    if extract_impl is None:
        extract_impl = best_extract_impl()
    N, L = batch.shape
    lens = lens.astype(_I32)
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)
    valid = iota < lens[:, None]
    # uint8 byte plane (see rfc5424.py): widen inside consumer fusions
    bb = jnp.where(valid, batch, jnp.uint8(0))
    is_digit = (bb >= 48) & (bb <= 57)
    dig = bb.astype(_I32) - 48

    is_tab = (bb == 9) & valid
    (tab_ord,) = _scan_ordinals([is_tab], scan_impl)
    n_tabs = jnp.max(jnp.where(is_tab, tab_ord, 0), axis=1).astype(_I32)
    n_parts = n_tabs + 1
    ok = n_parts <= max_parts

    # part starts: 0 and tab+1; part ends: tab positions and len —
    # tab positions via packed-sum extraction words (one word per 3
    # ordinals) instead of one masked min-reduction per ordinal
    tab_pos = extract_by_ord(is_tab, tab_ord, iota, max_parts - 1, L,
                             extract_impl)
    part_end = jnp.concatenate(
        [jnp.minimum(tab_pos, lens[:, None]), lens[:, None]], axis=1)
    part_start = jnp.concatenate(
        [jnp.zeros_like(lens)[:, None],
         jnp.minimum(tab_pos + 1, lens[:, None])], axis=1)

    # first ':' in each part (or L): a colon is first-in-its-part iff the
    # last tab-or-colon strictly before it is a tab (or line start).  One
    # cummax of a tagged channel (2*iota+1 at tabs, 2*iota at colons) plus
    # ONE packed-sum extraction keyed on part ordinals replaces the old
    # P=24 per-part _min_where stack (round-5 fusion fold; the same shape
    # that took rfc5424's sid_end stack out).
    is_colon = (bb == ord(":")) & valid
    tag = jnp.where(is_tab, 2 * iota + 1,
                    jnp.where(is_colon, 2 * iota, -1))
    last_tc = _shift_right(_cummax(tag, scan_impl), 1, -1)
    # -1 & 1 == 1, so line start (no prior tab/colon) also counts as tab
    first_colon = is_colon & ((last_tc & 1) == 1)
    # part ordinal of a (non-tab) position = tabs at or before it
    part_of = tab_ord.astype(_I32)
    colon_pos = extract_by_ord(first_colon, part_of + 1, iota, max_parts, L,
                               extract_impl)
    has_colon = colon_pos < part_end

    # ---- special keys, elementwise pattern matches ----------------------
    at_part_start = (iota == 0) | (_shift_right(is_tab, 1, False))
    # pack (position, part ordinal) in one word so the max-reduction that
    # finds the key also yields which part holds it (fold: the 4 per-key
    # value_span min-reductions become [N, P]-sized part_end selects)
    tbits = int(L + 1).bit_length()
    pos_part = (iota << tbits) | part_of

    def special(key: bytes):
        pat = _match_at(bb, key + b":", valid) & at_part_start
        # last occurrence wins (scalar decoder overwrites); max over the
        # packed word orders by position (the high field)
        w = jnp.max(jnp.where(pat, pos_part, -1), axis=1)
        pos = jnp.where(w >= 0, w >> tbits, -1)
        pidx = jnp.where(w >= 0, w & ((1 << tbits) - 1), 0)
        return pos, pidx

    time_pos, time_pi = special(b"time")
    host_pos, host_pi = special(b"host")
    msg_pos, msg_pi = special(b"message")
    level_pos, level_pi = special(b"level")

    krange = jnp.arange(max_parts, dtype=_I32)

    def value_span(pos, pidx, key_len):
        """[value_start, part end) for a special key at pos — tabs are
        separators, so the value always runs to its part's end; select
        part_end[n, pidx] with a tiny [N, P] masked sum (no gather)."""
        vstart = pos + key_len + 1
        vend = jnp.sum(
            jnp.where(krange[None, :] == pidx[:, None], part_end, 0), axis=1)
        return vstart, jnp.where(pos >= 0, vend, -1)

    host_start, host_end = value_span(host_pos, host_pi, 4)
    msg_start, msg_end = value_span(msg_pos, msg_pi, 7)
    level_start, level_end = value_span(level_pos, level_pi, 5)
    time_start, time_end = value_span(time_pos, time_pi, 4)

    has_time = time_pos >= 0
    has_host = host_pos >= 0
    ok &= has_time & has_host  # missing -> oracle for exact error text
    tv_len = time_end - time_start

    # ---- level parse ----------------------------------------------------
    has_level = level_pos >= 0
    lv_r = iota - level_start[:, None]
    lv_len = level_end - level_start
    in_lv = (lv_r >= 0) & (lv_r < lv_len[:, None]) & has_level[:, None]
    lv_digits_ok = ~jnp.any(in_lv & ~is_digit, axis=1)
    lv_w = jnp.where(lv_r >= 0, 10 ** jnp.clip(lv_len[:, None] - 1 - lv_r, 0, 8), 0)
    level_val = jnp.sum(jnp.where(in_lv, dig * lv_w, 0), axis=1)
    lv_ok = (~has_level) | (lv_digits_ok & (lv_len >= 1) & (lv_len <= 3)
                            & (level_val <= 7))
    ok &= lv_ok  # >7 or junk -> oracle reproduces the exact error

    # ---- time parse -----------------------------------------------------
    # optional [ ... ] wrapper.  The bytes at time_start, time_start+1 and
    # time_end-1 ride ONE packed 8-bit-field sum (fold: was 3 reductions —
    # t_first, t_last, and the post-bracket c0); coinciding positions for
    # 1/2-char values land in separate fields, so no carries.
    bi = bb.astype(_I32)
    w3 = jnp.sum(
        jnp.where(iota == time_start[:, None], bi, 0)
        + (jnp.where(iota == (time_start + 1)[:, None], bi, 0) << 8)
        + (jnp.where(iota == (time_end - 1)[:, None], bi, 0) << 16), axis=1)
    w3 = jnp.where(has_time, w3, 0)
    t_first = w3 & 255
    t_second = (w3 >> 8) & 255
    t_last = (w3 >> 16) & 255
    bracketed = (t_first == ord("[")) & (t_last == ord("]")) & (tv_len >= 2)
    ts_s = jnp.where(bracketed, time_start + 1, time_start)
    ts_e = jnp.where(bracketed, time_end - 1, time_end)
    tlen = ts_e - ts_s

    r = iota - ts_s[:, None]
    in_t = (r >= 0) & (r < tlen[:, None])

    # float form: [+-]? digits [. digits]  (exponents/inf/nan -> fallback)
    c0 = jnp.where(bracketed, t_second, t_first)
    has_sign = (c0 == ord("+")) | (c0 == ord("-"))
    body_from = jnp.where(has_sign, 1, 0)
    dot_pos = _min_where(in_t & (bb == ord(".")), r, 1 << 20)
    n_dots = jnp.sum((in_t & (bb == ord("."))).astype(_I32), axis=1)
    # both disqualifiers share ONE any-reduction (fold: was 2)
    float_viol = (
        (in_t & (r >= body_from[:, None]) & (r != dot_pos[:, None]) & ~is_digit)
        | (in_t & (r == body_from[:, None]) & (bb == ord(".")))
    )
    float_ok = (
        ~jnp.any(float_viol, axis=1) & (n_dots <= 1) & (tlen >= 1)
        & (tlen - body_from >= 1)
    )

    # exact split-integer parse of the float span for the device-encode
    # tier: value == (ts_hi * 1e9 + ts_lo) / 10**frac.  The tier bounds
    # total digits (<= 16 within 2**53) so the f64 combine on the host
    # is the correctly rounded strtod value — byte-identical to the
    # scalar path's float(span) + json_f64.  ts_meta packs
    # frac_digits | n_digits<<8 | has_sign<<16, all elementwise.
    has_dot = n_dots == 1
    nd_digits = tlen - body_from - has_dot.astype(_I32)
    frac_digits = jnp.where(has_dot, tlen - 1 - dot_pos, 0)
    di = r - body_from[:, None] - (r > dot_pos[:, None]).astype(_I32)
    place = nd_digits[:, None] - 1 - di
    dig_m = (in_t & is_digit & (r >= body_from[:, None])
             & (r != dot_pos[:, None]))
    lo_w = jnp.where(dig_m & (place >= 0) & (place <= 8),
                     10 ** jnp.clip(place, 0, 8), 0)
    hi_w = jnp.where(dig_m & (place >= 9) & (place <= 17),
                     10 ** jnp.clip(place - 9, 0, 8), 0)
    ts_lo = jnp.sum(dig * lo_w, axis=1)
    ts_hi = jnp.sum(dig * hi_w, axis=1)
    ts_meta = (jnp.clip(frac_digits, 0, 255)
               | (jnp.clip(nd_digits, 0, 255) << 8)
               | (has_sign.astype(_I32) << 16))

    # rfc3339 form: reuse the rfc5424 timestamp machinery inline.
    # Digit sums ride packed 8/14-bit fields: month|day|hour|minute in one
    # word, year|sec in a second (fold: was 6 reductions); per-field sums
    # are <= 99/9999, so fields never carry.
    dz = jnp.where(in_t, dig, 0)
    w_mdhm = ((r == 5) * 10 + (r == 6)
              + (((r == 8) * 10 + (r == 9)) << 8)
              + (((r == 11) * 10 + (r == 12)) << 16)
              + (((r == 14) * 10 + (r == 15)) << 24))
    wm = jnp.sum(dz * w_mdhm, axis=1)
    month = wm & 255
    day = (wm >> 8) & 255
    hour = (wm >> 16) & 255
    minute = (wm >> 24) & 255
    w_ys = ((r == 0) * 1000 + (r == 1) * 100 + (r == 2) * 10 + (r == 3)
            + (((r == 17) * 10 + (r == 18)) << 14))
    wy = jnp.sum(dz * w_ys, axis=1)
    year = wy & 16383
    sec = (wy >> 14) & 255
    digit_off = ((r >= 0) & (r <= 18) &
                 (r != 4) & (r != 7) & (r != 10) & (r != 13) & (r != 16))
    # every structural disqualifier (digit slots, separators, and — below —
    # the numeric-offset shape) ORs into one mask for a single any (fold:
    # was 6 reductions across rviol/oviol)
    viol_mask = in_t & digit_off & ~is_digit
    viol_mask |= in_t & ((r == 4) | (r == 7)) & (bb != ord("-"))
    viol_mask |= in_t & (r == 10) & (bb != ord("T")) & (bb != ord("t"))
    viol_mask |= in_t & ((r == 13) | (r == 16)) & (bb != ord(":"))
    has_frac = jnp.sum(jnp.where(in_t & (r == 19), bb.astype(_I32), 0),
                       axis=1) == ord(".")
    rd = r - 20
    frac_run = _min_where(in_t & (rd >= 0) & (rd < 10) & ~is_digit, rd, 10)
    frac_run = jnp.minimum(frac_run, jnp.maximum(tlen - 20, 0))
    frac_len = jnp.where(has_frac, frac_run, 0)
    w_frac = ((rd == 0) * 100000000 + (rd == 1) * 10000000 + (rd == 2) * 1000000
              + (rd == 3) * 100000 + (rd == 4) * 10000 + (rd == 5) * 1000
              + (rd == 6) * 100 + (rd == 7) * 10 + (rd == 8))
    nanos = jnp.sum(jnp.where(in_t & (rd >= 0) & (rd < frac_len[:, None]),
                              dig * w_frac, 0), axis=1)
    opos = jnp.where(has_frac, 20 + frac_len, 19)
    r2 = r - opos[:, None]
    oc = jnp.sum(jnp.where(in_t & (r2 == 0), bb.astype(_I32), 0), axis=1)
    is_zulu = (oc == ord("Z")) | (oc == ord("z"))
    is_num_off = (oc == ord("+")) | (oc == ord("-"))
    off_ok = jnp.where(is_zulu, tlen == opos + 1, True)
    viol_mask |= (in_t & ((r2 == 1) | (r2 == 2) | (r2 == 4) | (r2 == 5))
                  & ~is_digit & is_num_off[:, None])
    viol_mask |= (in_t & (r2 == 3) & (bb != ord(":")) & is_num_off[:, None])
    struct_viol = jnp.any(viol_mask, axis=1)
    # oh|om packed in one 8-bit-field sum (fold: was 2 reductions)
    w_ohm = jnp.sum(dz * ((r2 == 1) * 10 + (r2 == 2)
                          + (((r2 == 4) * 10 + (r2 == 5)) << 8)), axis=1)
    oh = w_ohm & 255
    om = (w_ohm >> 8) & 255
    off_ok &= jnp.where(is_num_off,
                        (tlen == opos + 6) & (oh <= 23) & (om <= 59),
                        True)
    rfc_ok = (
        (tlen >= 20) & ~struct_viol & (is_zulu | is_num_off) & off_ok
        & (month >= 1) & (month <= 12) & (day >= 1)
        & (day <= _days_in_month(year, month))
        & (hour <= 23) & (minute <= 59) & (sec <= 59)
        & jnp.where(has_frac, (frac_len >= 1) & (frac_len <= 9), True)
    )
    off_secs = jnp.where(is_num_off,
                         jnp.where(oc == ord("-"), -1, 1) * (oh * 3600 + om * 60),
                         0)
    days = _days_from_civil(year, month, day)
    sod = hour * 3600 + minute * 60 + sec

    # ts_kind: 0 = rfc3339 (days/sod/off/nanos valid), 1 = float span
    # (host parses the span), 2 = neither -> row fallback
    ts_kind = jnp.where(rfc_ok, 0, jnp.where(float_ok, 1, 2))
    ok &= ts_kind < 2

    return {
        "ok": ok,
        "has_high": jnp.any((bb >= 128) & valid, axis=1),
        "n_parts": n_parts,
        "part_start": part_start,
        "part_end": part_end,
        "colon_pos": jnp.where(has_colon, colon_pos, -1),
        "time_pos": time_pos, "host_pos": host_pos,
        "msg_pos": msg_pos, "level_pos": level_pos,
        "host_start": host_start, "host_end": host_end,
        "msg_start": msg_start, "msg_end": msg_end,
        "level_val": jnp.where(has_level, level_val, -1),
        "ts_kind": ts_kind,
        "ts_start": ts_s, "ts_end": ts_e,
        "days": days, "sod": sod, "off": off_secs, "nanos": nanos,
        "ts_hi": ts_hi, "ts_lo": ts_lo, "ts_meta": ts_meta,
    }


@functools.partial(jax.jit, static_argnames=("max_parts", "demand"))
def decode_ltsv_jit(batch, lens, max_parts=DEFAULT_MAX_PARTS, demand=None):
    """``demand`` (static frozenset): keep only the channels the
    consumer reads so XLA dead-code-eliminates the rest — the fused
    ltsv→GELF route drops e.g. the raw ts span channels."""
    out = decode_ltsv(batch, lens, max_parts=max_parts)
    if demand is not None:
        out = {k: v for k, v in out.items() if k in demand}
    return out


def decode_ltsv_submit(batch, lens, sharded=None):
    """Asynchronous dispatch (pair with decode_ltsv_fetch) — the ltsv
    leg of the block pipeline's double buffering.  ``sharded`` swaps in
    the multi-chip mesh kernel (parallel.mesh.ShardedDecode).  The
    handle carries the uploaded device arrays so the device-side encode
    (tpu/device_ltsv.py) reuses them without a re-upload."""
    import jax.numpy as jnp

    if sharded is not None:
        b, ln = sharded.put(batch, lens)
        return sharded.fn(b, ln), b, ln
    from .aot import decode_call

    b, ln = jnp.asarray(batch), jnp.asarray(lens)
    # zero-JIT boot: a loaded AOT artifact replaces the trace+compile
    out = decode_call("ltsv", (b, ln))
    if out is None:
        out = decode_ltsv_jit(b, ln)
    return out, b, ln


def decode_ltsv_fetch(handle):
    import numpy as np

    return {k: np.asarray(v) for k, v in handle[0].items()}
