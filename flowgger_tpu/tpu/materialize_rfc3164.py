"""Materialize columnar RFC3164 fast-path output into Records.

Fast-path rows are the standard single-spaced ``[<pri>]Mon d hh:mm:ss
host msg`` layout (tpu/rfc3164.py); everything else re-runs the scalar
decoder (flowgger_tpu/decoders/rfc3164.py) for byte-identical leniency.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..decoders import DecodeError
from ..decoders.rfc3164 import RFC3164Decoder
from ..record import Record
from .materialize import LineResult, compute_ts

_SCALAR = RFC3164Decoder()


def materialize_rfc3164(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
) -> List[LineResult]:
    ts = compute_ts(out).tolist()
    o = {k: np.asarray(v).tolist() for k, v in out.items()}
    ok = o["ok"]
    results: List[LineResult] = []
    for n in range(n_real):
        s = int(starts[n])
        ln = int(orig_lens[n])
        raw = chunk_bytes[s:s + ln]
        try:
            line = raw.decode("utf-8")
        except UnicodeDecodeError:
            results.append(LineResult(None, "__utf8__", ""))
            continue
        if not ok[n] or ln > max_len or len(line) != ln:
            results.append(_scalar_3164(line))
            continue
        has_pri = o["has_pri"][n]
        record = Record(
            ts=float(ts[n]),
            hostname=line[o["host_start"][n]:o["host_end"][n]],
            facility=o["facility"][n] if has_pri else None,
            severity=o["severity"][n] if has_pri else None,
            msg=line[o["msg_start"][n]:],
            full_msg=line,
            sd=None,
        )
        results.append(LineResult(record, None, line))
    return results


def _scalar_3164(line: str) -> LineResult:
    try:
        return LineResult(_SCALAR.decode(line), None, line)
    except DecodeError as e:
        return LineResult(None, str(e), line)
