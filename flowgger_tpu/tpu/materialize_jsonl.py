"""Materialize columnar JSON-lines tokenizer output into Records.

Stage 2 of the simdjson-style split: token spans → Python values.
Key routing follows the scalar oracle (flowgger_tpu/decoders/jsonl.py):
duplicate keys keep the last value, processing iterates keys in
*sorted* order, specials timestamp/host/message/level validate with
the same messages.  Escaped strings, numbers, and nested-container
spans parse with ``json.loads`` on the token span, so edge cases
(\\u escapes, leading zeros, huge exponents, malformed nested JSON)
behave exactly like the oracle's whole-line parse.
"""

from __future__ import annotations

import json
from typing import Dict, List

import numpy as np

from ..decoders import DecodeError
from ..decoders.jsonl import JSONLDecoder, PARSE_ERR, route_obj
from .jsonidx import (
    VT_ARRAY,
    VT_FALSE,
    VT_NULL,
    VT_NUMBER,
    VT_OBJECT,
    VT_STRING,
    VT_TRUE,
)
from .materialize import LineResult

_SCALAR = JSONLDecoder()


def materialize_jsonl(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
) -> List[LineResult]:
    out = {k: np.asarray(v).tolist() for k, v in out.items()}
    ok = out["ok"]
    results: List[LineResult] = []
    for n in range(n_real):
        s = int(starts[n])
        ln = int(orig_lens[n])
        raw = chunk_bytes[s:s + ln]
        try:
            line = raw.decode("utf-8")
        except UnicodeDecodeError:
            results.append(LineResult(None, "__utf8__", ""))
            continue
        if not ok[n] or ln > max_len:
            from ..utils.metrics import registry as _m
            _m.inc("fallback_rows")
            results.append(_scalar_jsonl(line))
            continue
        results.append(_from_spans(line, raw, len(line) == ln, n, out))
    return results


def _scalar_jsonl(line: str) -> LineResult:
    try:
        return LineResult(_SCALAR.decode(line), None, line)
    except DecodeError as e:
        return LineResult(None, str(e), line)


def _from_spans(line: str, raw: bytes, byte_ok: bool, n: int,
                o: Dict[str, np.ndarray]) -> LineResult:
    def take(a: int, b: int) -> str:
        if byte_ok:
            return line[a:b]
        return raw[a:b].decode("utf-8")

    obj = {}
    try:
        for k in range(int(o["n_fields"][n])):
            ks, ke = int(o["key_start"][n][k]), int(o["key_end"][n][k])
            key = take(ks, ke)
            if o["key_esc"][n][k]:
                key = json.loads(f'"{key}"')
            elif any(ord(c) < 0x20 for c in key):
                raise ValueError("control char")
            vt = int(o["val_type"][n][k])
            vs, ve = int(o["val_start"][n][k]), int(o["val_end"][n][k])
            if vt == VT_STRING:
                value = take(vs, ve)
                if o["val_esc"][n][k]:
                    value = json.loads(f'"{value}"')
                elif any(ord(c) < 0x20 for c in value):
                    raise ValueError("control char")  # oracle rejects too
            elif vt == VT_NUMBER:
                value = json.loads(take(vs, ve))
            elif vt == VT_TRUE:
                value = True
            elif vt == VT_FALSE:
                value = False
            elif vt == VT_NULL:
                value = None
            elif vt in (VT_OBJECT, VT_ARRAY):
                # the container's exact span; json.loads applies the
                # whole-line parse's own rules (dup keys last-win,
                # control chars reject) to just these bytes
                value = json.loads(take(vs, ve))
            else:
                raise ValueError("bad token")
            obj[key] = value  # duplicates: last wins, like json.loads
    except (ValueError, json.JSONDecodeError):
        return LineResult(None, PARSE_ERR, line)

    # sorted-key routing: THE oracle's own helper (decoders/jsonl.py),
    # so a rule change there can never drift this path
    try:
        record = route_obj(obj)
    except DecodeError as e:
        return LineResult(None, str(e), line)
    return LineResult(record, None, line)
