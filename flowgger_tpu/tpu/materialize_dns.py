"""Materialize columnar DNS-decode output into Records.

Fixed grammar means fixed routing: ok rows build their Record straight
from the six field spans (the kernel already validated the ts/latency
grammars, so no per-row error path exists on the tier); everything
else re-runs the scalar oracle for the exact error text.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..decoders import DecodeError
from ..decoders.dns import DNSDecoder
from ..record import Record, SDValue, StructuredData
from .materialize import LineResult

_SCALAR = DNSDecoder()


def materialize_dns(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
) -> List[LineResult]:
    out = {k: np.asarray(v) for k, v in out.items()}
    ok = out["ok"]
    results: List[LineResult] = []
    # dedup caches: repetitive streams share few distinct stamps and
    # latencies, so the float/int parse is per-unique, not per-row
    ts_cache: dict = {}
    lat_cache: dict = {}
    for n in range(n_real):
        s = int(starts[n])
        ln = int(orig_lens[n])
        raw = chunk_bytes[s:s + ln]
        try:
            line = raw.decode("utf-8")
        except UnicodeDecodeError:
            results.append(LineResult(None, "__utf8__", ""))
            continue
        if not ok[n] or ln > max_len:
            from ..utils.metrics import registry as _m
            _m.inc("fallback_rows")
            results.append(_scalar_dns(line))
            continue

        def span(key):
            a = int(out[key + "_start"][n])
            b = int(out[key + "_end"][n])
            return raw[a:b]

        ts_b = span("ts")
        ts = ts_cache.get(ts_b)
        if ts is None:
            ts = ts_cache[ts_b] = float(ts_b)
        lat_b = span("lat")
        lat = lat_cache.get(lat_b)
        if lat is None:
            lat = lat_cache[lat_b] = int(lat_b)
        sd = StructuredData(None)
        sd.pairs.append(("_latency_us", SDValue.u64(lat)))
        sd.pairs.append(("_qtype",
                         SDValue.string(span("qtype").decode("utf-8"))))
        sd.pairs.append(("_rcode",
                         SDValue.string(span("rcode").decode("utf-8"))))
        record = Record(
            ts=ts,
            hostname=span("client").decode("utf-8"),
            msg=span("qname").decode("utf-8"),
            sd=[sd],
        )
        results.append(LineResult(record, None, line))
    return results


def _scalar_dns(line: str) -> LineResult:
    try:
        return LineResult(_SCALAR.decode(line), None, line)
    except DecodeError as e:
        return LineResult(None, str(e), line)
