"""Single-VMEM structural-pass Pallas kernels: framing spans, the
compiled-NFA stage-1 classifier, and the fused framing→decode entry.

Every device kernel before this PR was composed jnp ops, which XLA
materializes between fusions: the syslen framing chain resolves its
pointer-doubling hops as HBM scatter/gather passes (measured 0.13x
host memcpy on CPU), and the ``jsonidx`` structural screen makes ~60
HBM round-trips over the [N, L] plane.  This module rewrites those
inner loops as true Pallas kernels — the bytes are read into VMEM
once, every intermediate plane lives on-chip, and only the compact
span/index outputs are written back:

- **framing spans** (``frame_sep_spans_pallas`` /
  ``frame_syslen_spans_pallas``): the delimiter/prefix lookahead
  planes build with Mosaic-lowerable log-shift ladders, then the
  data-dependent frame chain resolves as a *sequential scalar walk*
  over VMEM-resident planes (``ref[0, pl.ds(pos, 1)]`` hops) — O(ncap)
  one-element VMEM reads replace the jnp tier's log2(B) full-plane
  scatter/gather passes, because chasing a chain is exactly what a
  scalar loop over on-chip memory is good at;
- **stage-1 classifier** (``structural_index_pallas``): the jsonidx
  structural index as a [block_rows, L] tile kernel whose string
  machine is the compiled-NFA transition-table scan
  (``jsonidx.NFA_TABLE``) and whose scans/lookarounds/extractions all
  use the ``manual``/``sum`` Mosaic-safe forms — one read of the byte
  plane, one write of the packed index;
- **fused framing→decode** (``fused_frame_decode_rfc5424`` /
  ``_jsonl``): spans → gather → decode composed under one jit so the
  dense [rows, max_len] batch is an internal value that never
  materializes as a program output.

``interpret=True`` runs every kernel in the Pallas interpreter so this
CPU container differential-tests them byte-for-byte against the scalar
oracles; on a real TPU the same bodies lower through Mosaic (inputs
are widened u8→i32 *outside* the kernels — this jax's Mosaic can't
load u8 refs; the widen is one elementwise pass, still collapsing the
jnp tier's dozens).  Region-sized kernels run as one VMEM block, so
the tier self-gates at ``PALLAS_MAX_REGION`` bytes and larger regions
stay on the jnp tier.

Decline ladder: the tier rides the existing machinery — framing-side
probes run under the compile watchdog (slot ``pallas/<kind>``) inside
``framing.device_frame_region`` and fall back to the *jnp* span
kernels (then host) on any decline; the decode tier
(``decode_tier``) declines to the format's ``decode_*_jit`` after
``DECLINE_LIMIT`` failures and cools down like the framing tier.
Engagement is the ``input.tpu_pallas = auto|on|off`` key resolved by
the batch handler into :func:`set_mode` ("compiled" on accelerator
backends, "interpret" for ``on`` on the CPU backend, "off" otherwise).
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .jsonidx import structural_index
from .rfc5424 import _shift_left

SCALAR_ORACLE = (
    "flowgger_tpu.tpu.pack:split_chunk",
    "flowgger_tpu.splitters:_scan_syslen_region",
    "flowgger_tpu.decoders.rfc5424:parse_line",
    "flowgger_tpu.decoders.jsonl:parse_line",
)
DIFF_TEST = (
    "tests/test_pallas_kernels.py::test_sep_spans_match_jnp_and_host",
    "tests/test_pallas_kernels.py::test_syslen_spans_match_jnp_and_host",
    "tests/test_pallas_kernels.py::test_structural_index_pallas_matches_jnp",
    "tests/test_pallas_kernels.py::test_raw_ingest_byte_identity_pallas",
)

_I32 = jnp.int32
# numpy scalar (framing._BIG precedent): folds into traced code without
# costing a fresh-process jit compile at import time
_BIG = np.int32(1 << 30)

# single-block VMEM ceiling for the region kernels: beyond this the
# lookahead planes (~5 x i32 x B) stop fitting comfortably in VMEM and
# the region stays on the jnp tier (which tiles through XLA)
PALLAS_MAX_REGION = 1 << 20

# decode-tier decline hysteresis (framing's DECLINE_LIMIT/COOLDOWN
# pattern, scoped per decode format)
DECLINE_LIMIT = 3
COOLDOWN = 32

from .framing import MAX_PREFIX_DIGITS, _POW10  # noqa: E402 - shared prefix-parse contract


# ---------------------------------------------------------------------------
# engagement mode (set by the batch handler from input.tpu_pallas; the
# pack._SHAPE_BUCKETS module-state precedent — only an explicit config
# resolution touches it)

_mode_lock = threading.Lock()
_MODE = {"mode": "off"}
_DECODE_STATE: Dict[str, Dict] = {}


def set_mode(mode: str) -> None:
    """``off`` | ``compiled`` | ``interpret`` — resolved by the batch
    handler from ``input.tpu_pallas`` and the backend."""
    if mode not in ("off", "compiled", "interpret"):
        raise ValueError(f"unknown pallas mode {mode!r}")
    with _mode_lock:
        _MODE["mode"] = mode
        _DECODE_STATE.clear()


def mode() -> str:
    return _MODE["mode"]


def engaged() -> bool:
    return _MODE["mode"] != "off"


def interpret_mode() -> bool:
    return _MODE["mode"] == "interpret"


def framing_engaged(region_bytes: int) -> bool:
    """The framing tier probes pallas first for regions that fit the
    single-VMEM-block kernels."""
    return engaged() and region_bytes <= PALLAS_MAX_REGION


def fused_leg_mode() -> str:
    """The pallas mode a fused decode→encode program's rfc5424 leg
    traces with: ``compiled`` on accelerators, else ``off`` — interpret
    mode inlined into a fused program explodes XLA CPU compile time
    (the interpreter unrolls the kernel body into the already-large
    encode graph), so CPU tests exercise the standalone fused entries
    (``fused_frame_decode_*``) instead."""
    return "compiled" if _MODE["mode"] == "compiled" else "off"


# ---------------------------------------------------------------------------
# in-kernel ladder helpers (axis-1, fill-aware; Mosaic-safe pad/slice)

def _rev_cummin(x, fill):
    L = x.shape[1]
    k = 1
    while k < L:
        x = jnp.minimum(x, _shift_left(x, k, fill))
        k <<= 1
    return x


def _rev_cumsum(x):
    L = x.shape[1]
    k = 1
    while k < L:
        x = x + _shift_left(x, k, 0)
        k <<= 1
    return x


def _pow10_select(exp):
    """10**exp for exp in [0, MAX_PREFIX_DIGITS) as a branchless select
    chain (the jnp tier's ``pow10[exp]`` gather is not Mosaic-lowerable;
    nine immediates are)."""
    out = jnp.full_like(exp, _POW10[0])
    for e in range(1, MAX_PREFIX_DIGITS):
        out = jnp.where(exp == e, np.int32(_POW10[e]), out)
    return out


def _read1(ref, pos):
    """One scalar from an (1, B) VMEM ref at a traced position."""
    from jax.experimental import pallas as pl

    return ref[0, pl.ds(pos, 1)][0]


def _store_meta(meta_ref, scalars):
    """Per-slot (1,)-stores of traced scalars (jnp.stack of scalars
    does not lower through Mosaic)."""
    from jax.experimental import pallas as pl

    for i, v in enumerate(scalars):
        meta_ref[0, pl.ds(i, 1)] = v.reshape(1)


# ---------------------------------------------------------------------------
# stage A: framing span kernels (single VMEM block + scalar chain walk)

def _sep_kernel(r_ref, l_ref, starts_ref, lens_ref, meta_ref, nxt_ref,
                *, sep: int, strip_cr: bool, ncap: int):
    from jax.experimental import pallas as pl

    B = r_ref.shape[1]
    bb = r_ref[...]
    # (1, 1) view for vector ops (Mosaic rejects traced-scalar vs
    # vector compares), scalar view for the walk's scalar arithmetic
    rlv = l_ref[...]
    idx = jax.lax.broadcasted_iota(_I32, (1, B), 1)
    valid = idx < rlv
    is_sep = (bb == sep) & valid
    # integer reductions don't lower on this Mosaic; f32 is exact to
    # 2^24 and B is capped at PALLAS_MAX_REGION = 2^20
    n = jnp.sum(is_sep.astype(jnp.float32)).astype(_I32)
    # next separator at-or-after each position (reverse-cummin ladder),
    # staged into VMEM scratch for the chain walk's scalar hops
    nxt_ref[...] = _rev_cummin(jnp.where(is_sep, idx, _BIG), _BIG)
    starts_ref[...] = jnp.zeros((1, ncap), _I32)
    lens_ref[...] = jnp.zeros((1, ncap), _I32)

    def body(k, carry):
        pos, consumed = carry
        e = _read1(nxt_ref, jnp.minimum(pos, B - 1))
        live = (k < n) & (e < _BIG)
        ec = jnp.minimum(e, B - 1)
        ln = e - pos
        if strip_cr:
            before = _read1(r_ref, jnp.maximum(ec - 1, 0))
            ln = ln - (live & (ln > 0) & (before == 13)).astype(_I32)
        starts_ref[0, pl.ds(k, 1)] = jnp.where(live, pos, 0).reshape(1)
        lens_ref[0, pl.ds(k, 1)] = jnp.where(live, ln, 0).reshape(1)
        nxt_pos = jnp.where(live, e + 1, pos)
        return nxt_pos, jnp.where(live, e + 1, consumed)

    _, consumed = jax.lax.fori_loop(
        0, ncap, body, (jnp.int32(0), jnp.int32(0)))
    _store_meta(meta_ref, (n, consumed, (n > ncap).astype(_I32),
                           jnp.int32(0)))


@functools.partial(jax.jit,
                   static_argnames=("sep", "strip_cr", "ncap", "interpret"))
def frame_sep_spans_pallas(region, rlen, sep: int = 10,
                           strip_cr: bool = True, ncap: int = 256,
                           interpret: bool = False):
    """Pallas tier of ``framing.frame_sep_spans_jit`` — same output
    dict, one VMEM pass (bytes in, span metadata out)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = region.shape[0]
    x = region.astype(_I32).reshape(1, B)
    rl = jnp.asarray(rlen, _I32).reshape(1, 1)
    starts, lens, meta = pl.pallas_call(
        functools.partial(_sep_kernel, sep=sep, strip_cr=strip_cr,
                          ncap=ncap),
        grid=(1,),
        in_specs=[pl.BlockSpec((1, B), lambda i: (0, 0)),
                  pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((1, ncap), lambda i: (0, 0)),
                   pl.BlockSpec((1, ncap), lambda i: (0, 0)),
                   pl.BlockSpec((1, 4), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((1, ncap), _I32),
                   jax.ShapeDtypeStruct((1, ncap), _I32),
                   jax.ShapeDtypeStruct((1, 4), _I32)],
        scratch_shapes=[pltpu.VMEM((1, B), _I32)],
        interpret=interpret,
    )(x, rl)
    return {"starts": starts[0], "lens": lens[0], "n": meta[0, 0],
            "consumed": meta[0, 1], "overflow": meta[0, 2] != 0}


def _syslen_kernel(r_ref, l_ref, starts_ref, lens_ref, meta_ref,
                   sp_ref, nd_ref, suf_ref, *, ncap: int):
    from jax.experimental import pallas as pl

    B = r_ref.shape[1]
    bb = r_ref[...]
    rlv = l_ref[...]
    # the walk's rlen must be the same value *species* as the dynamic
    # plane reads (a ds-load extract): Mosaic refuses cmpi between a
    # statically-loaded scalar and a dynamically-extracted one
    rlen = l_ref[0, pl.ds(0, 1)][0]
    zero = rlen * 0
    idx = jax.lax.broadcasted_iota(_I32, (1, B), 1)
    valid = idx < rlv
    is_digit = (bb >= 48) & (bb <= 57) & valid
    is_space = (bb == 32) & valid
    # lookahead planes (framing.frame_syslen_spans_jit's sp/nd/suf,
    # with manual ladders), staged into VMEM scratch — the chain walk
    # below replaces the jnp tier's pointer-doubling scatter/gather
    sp = _rev_cummin(jnp.where(is_space, idx, _BIG), _BIG)
    # clamp via where (minimum against the (1,1) view trips a Mosaic
    # scalar/vector cmpi type check)
    idx_c = jnp.where(valid, idx, jnp.broadcast_to(rlv, idx.shape))
    nd = _rev_cummin(jnp.where(is_digit, _BIG, idx_c), _BIG)
    has_space = sp < rlv
    exp = jnp.clip(sp - 1 - idx, 0, MAX_PREFIX_DIGITS - 1)
    w = jnp.where(is_digit & has_space,
                  (bb - 48) * _pow10_select(exp), 0)
    sp_ref[...] = sp
    nd_ref[...] = nd
    suf_ref[...] = _rev_cumsum(w)
    starts_ref[...] = jnp.zeros((1, ncap), _I32)
    lens_ref[...] = jnp.zeros((1, ncap), _I32)

    def body(k, carry):
        pos, count, consumed, done, decline = carry
        posc = jnp.minimum(pos, B - 1)
        sp_p = _read1(sp_ref, posc)
        nd_p = _read1(nd_ref, posc)
        prefix_ok = (sp_p < rlen) & (nd_p == sp_p) & (sp_p > pos)
        too_long = prefix_ok & (sp_p - pos > MAX_PREFIX_DIGITS)
        # each frame's digit window sums < 1e9: the wrapped difference
        # of two suffix-cumsum samples is exact (jnp-tier argument)
        val = _read1(suf_ref, posc) - _read1(
            suf_ref, jnp.minimum(sp_p, B - 1))
        body_start = sp_p + 1
        nxt = body_start + val
        frame_ok = prefix_ok & (~too_long) & (nxt <= rlen)
        live = frame_ok & (done == 0)
        rec = live & (k < ncap)
        si = jnp.minimum(k, ncap - 1)
        cur_s = _read1(starts_ref, si)
        cur_l = _read1(lens_ref, si)
        starts_ref[0, pl.ds(si, 1)] = jnp.where(
            rec, body_start, cur_s).reshape(1)
        lens_ref[0, pl.ds(si, 1)] = jnp.where(rec, val, cur_l).reshape(1)
        decline = decline | (live & (k >= ncap)).astype(_I32) \
            | (too_long & (done == 0)).astype(_I32)
        return (jnp.where(live, nxt, pos), count + live.astype(_I32),
                jnp.where(live, nxt, consumed),
                done | (~live).astype(_I32), decline)

    _, n, consumed, _, decline = jax.lax.fori_loop(
        0, ncap + 1, body, (zero, zero, zero, zero, zero))
    # stop analysis, mirroring the host scan (framing jnp tier): a
    # reachable space with a non-digit (or empty) prefix before it
    stop = jnp.clip(consumed, 0, B - 1)
    sp_stop = _read1(sp_ref, stop)
    nd_stop = _read1(nd_ref, stop)
    bad_prefix = (sp_stop < rlen) & ((nd_stop != sp_stop)
                                     | (sp_stop == consumed))
    err = ((consumed < rlen) & bad_prefix).astype(_I32)
    _store_meta(meta_ref, (n, consumed, err, decline))


@functools.partial(jax.jit, static_argnames=("ncap", "interpret"))
def frame_syslen_spans_pallas(region, rlen, ncap: int = 256,
                              interpret: bool = False):
    """Pallas tier of ``framing.frame_syslen_spans_jit``: identical
    output dict whenever ``decline`` is False (a declining region's
    exact ``n`` is unknowable to the bounded walk — both tiers raise
    FramingDeclined before anyone reads it)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = region.shape[0]
    x = region.astype(_I32).reshape(1, B)
    rl = jnp.asarray(rlen, _I32).reshape(1, 1)
    starts, lens, meta = pl.pallas_call(
        functools.partial(_syslen_kernel, ncap=ncap),
        grid=(1,),
        in_specs=[pl.BlockSpec((1, B), lambda i: (0, 0)),
                  pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((1, ncap), lambda i: (0, 0)),
                   pl.BlockSpec((1, ncap), lambda i: (0, 0)),
                   pl.BlockSpec((1, 4), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((1, ncap), _I32),
                   jax.ShapeDtypeStruct((1, ncap), _I32),
                   jax.ShapeDtypeStruct((1, 4), _I32)],
        scratch_shapes=[pltpu.VMEM((1, B), _I32),
                        pltpu.VMEM((1, B), _I32),
                        pltpu.VMEM((1, B), _I32)],
        interpret=interpret,
    )(x, rl)
    return {"starts": starts[0], "lens": lens[0], "n": meta[0, 0],
            "consumed": meta[0, 1], "err": meta[0, 2] != 0,
            "decline": meta[0, 3] != 0}


# ---------------------------------------------------------------------------
# stage B: per-row gather (dynamic-slice copy from the VMEM region)

# rows per grid step: Mosaic wants the output block's second-minor dim
# divisible by 8 (or equal to the array's)
_GATHER_ROWG = 8


def _gather_kernel(r_ref, s_ref, l_ref, out_ref, *, max_len: int):
    from jax.experimental import pallas as pl

    pid = pl.program_id(0)
    col = jax.lax.broadcasted_iota(_I32, (1, max_len), 1)
    for j in range(_GATHER_ROWG):
        r = pid * _GATHER_ROWG + j
        s = _read1(s_ref, r)
        lv = l_ref[0, pl.ds(r, 1)].reshape(1, 1)
        seg = r_ref[0, pl.ds(s, max_len)].reshape(1, max_len)
        out_ref[pl.ds(j, 1), :] = jnp.where(
            col < jnp.minimum(lv, max_len), seg, 0)


@functools.partial(jax.jit, static_argnames=("max_len", "interpret"))
def frame_gather_pallas(region, starts, lens, max_len: int = 512,
                        interpret: bool = False):
    """Pallas tier of ``framing.frame_gather_jit``: dynamic-slice row
    copies from the VMEM-resident region, ``_GATHER_ROWG`` rows per
    grid step (the region is padded by ``max_len`` so a tail slice
    never clamps; rows are padded to the row-group)."""
    from jax.experimental import pallas as pl

    B = region.shape[0]
    rows = starts.shape[0]
    rows_p = -(-rows // _GATHER_ROWG) * _GATHER_ROWG
    x = jnp.pad(region.astype(_I32), (0, max_len)).reshape(1, B + max_len)
    s2 = jnp.pad(starts.astype(_I32), (0, rows_p - rows)).reshape(1, rows_p)
    l2 = jnp.pad(lens.astype(_I32), (0, rows_p - rows)).reshape(1, rows_p)
    out = pl.pallas_call(
        functools.partial(_gather_kernel, max_len=max_len),
        grid=(rows_p // _GATHER_ROWG,),
        in_specs=[pl.BlockSpec((1, B + max_len), lambda i: (0, 0)),
                  pl.BlockSpec((1, rows_p), lambda i: (0, 0)),
                  pl.BlockSpec((1, rows_p), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((_GATHER_ROWG, max_len), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows_p, max_len), _I32),
        interpret=interpret,
    )(x, s2, l2)
    return (out[:rows].astype(jnp.uint8),
            jnp.minimum(lens.astype(_I32), max_len))


# ---------------------------------------------------------------------------
# stage-1 structural classifier (jsonidx as a block kernel; the string
# machine is the compiled-NFA scan — jsonidx.NFA_TABLE)

_SI_KEYS_1D = ("ok", "n_fields")
_SI_KEYS_F = ("key_start", "key_end", "val_start", "val_end", "val_type",
              "key_esc", "val_esc")
_SI_BOOL = ("ok", "key_esc", "val_esc")

DEFAULT_BLOCK_ROWS = 256


def structural_index_pallas(batch, lens, max_fields: int,
                            nested: int = 0,
                            block_rows: int = DEFAULT_BLOCK_ROWS,
                            interpret: bool = False
                            ) -> Dict[str, jnp.ndarray]:
    """``jsonidx.structural_index`` as a Pallas block kernel: [br, L]
    byte tiles resident in VMEM, the compiled-NFA string machine, and
    manual/sum scan+extract forms — one HBM read of the bytes, one
    write of the packed index.  Channel-identical to the jnp screen
    (``scan_impl`` of either flavor) at ``extract_impl="sum"``."""
    from jax.experimental import pallas as pl

    N_orig, L = batch.shape
    N = N_orig
    br = min(block_rows, N)
    if N % br:
        pad = br - N % br
        batch = jnp.pad(batch, ((0, pad), (0, 0)))
        lens = jnp.pad(lens, (0, pad))
        N += pad
    x = batch.astype(_I32)
    lens2 = lens.astype(_I32).reshape(N, 1)
    F = max_fields

    def kernel(b_ref, l_ref, *outs):
        res = structural_index(b_ref[...], l_ref[...][:, 0], max_fields,
                               scan_impl="manual", extract_impl="sum",
                               nested=nested, string_impl="nfa")
        i = 0
        for k in _SI_KEYS_1D:
            outs[i][...] = res[k].astype(_I32).reshape(br, 1)
            i += 1
        for k in _SI_KEYS_F:
            outs[i][...] = res[k].astype(_I32)
            i += 1

    out_shape = (
        [jax.ShapeDtypeStruct((N, 1), _I32) for _ in _SI_KEYS_1D]
        + [jax.ShapeDtypeStruct((N, F), _I32) for _ in _SI_KEYS_F])
    out_specs = (
        [pl.BlockSpec((br, 1), lambda i: (i, 0)) for _ in _SI_KEYS_1D]
        + [pl.BlockSpec((br, F), lambda i: (i, 0)) for _ in _SI_KEYS_F])
    outs = pl.pallas_call(
        kernel,
        grid=(N // br,),
        in_specs=[pl.BlockSpec((br, L), lambda i: (i, 0)),
                  pl.BlockSpec((br, 1), lambda i: (i, 0))],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(x, lens2)
    res = {}
    i = 0
    for k in _SI_KEYS_1D:
        v = outs[i][:N_orig, 0]
        res[k] = (v != 0) if k in _SI_BOOL else v
        i += 1
    for k in _SI_KEYS_F:
        v = outs[i][:N_orig]
        res[k] = (v != 0) if k in _SI_BOOL else v
        i += 1
    return res


@functools.partial(jax.jit,
                   static_argnames=("max_fields", "nested", "interpret"))
def decode_jsonl_pallas(batch, lens, max_fields: int = None,
                        nested: int = None, interpret: bool = False):
    """The jsonl decode contract (``decode_jsonl_jit``) on the Pallas
    classifier."""
    from .jsonl import DEFAULT_MAX_FIELDS, NESTED_DEPTH

    if max_fields is None:
        max_fields = DEFAULT_MAX_FIELDS
    if nested is None:
        nested = NESTED_DEPTH
    return structural_index_pallas(batch, lens, max_fields,
                                   nested=nested, interpret=interpret)


# ---------------------------------------------------------------------------
# fused framing→decode entries: raw region bytes -> decode channels
# with the dense batch as an internal value (never a program output)

@functools.partial(jax.jit, static_argnames=(
    "sep", "strip_cr", "ncap", "max_len", "max_sd", "interpret"))
def fused_frame_decode_rfc5424(region, rlen, sep: int = 10,
                               strip_cr: bool = False, ncap: int = 256,
                               max_len: int = 512, max_sd: int = None,
                               interpret: bool = False):
    """line/nul-framed raw region -> rfc5424 decode channels in one
    program: spans walk, row gather, and the rfc5424 block kernel
    compose under one jit, so the [ncap, max_len] batch lives only
    between kernels.  Returns ``(spans, channels)``; rows past
    ``spans['n']`` decode padding and must be masked by the caller."""
    from .rfc5424 import DEFAULT_MAX_SD, decode_rfc5424_pallas

    if max_sd is None:
        max_sd = DEFAULT_MAX_SD
    spans = frame_sep_spans_pallas(region, rlen, sep=sep,
                                   strip_cr=strip_cr, ncap=ncap,
                                   interpret=interpret)
    batch, lens_c = frame_gather_pallas(region, spans["starts"],
                                        spans["lens"], max_len=max_len,
                                        interpret=interpret)
    dec = decode_rfc5424_pallas(batch, lens_c, max_sd=max_sd,
                                block_rows=min(DEFAULT_BLOCK_ROWS, ncap),
                                interpret=interpret)
    return spans, dec


@functools.partial(jax.jit, static_argnames=(
    "sep", "strip_cr", "ncap", "max_len", "max_fields", "nested",
    "interpret"))
def fused_frame_decode_jsonl(region, rlen, sep: int = 10,
                             strip_cr: bool = True, ncap: int = 256,
                             max_len: int = 512, max_fields: int = None,
                             nested: int = None,
                             interpret: bool = False):
    """line/nul-framed raw region -> jsonl structural index, dense
    batch internal (see ``fused_frame_decode_rfc5424``)."""
    spans = frame_sep_spans_pallas(region, rlen, sep=sep,
                                   strip_cr=strip_cr, ncap=ncap,
                                   interpret=interpret)
    batch, lens_c = frame_gather_pallas(region, spans["starts"],
                                        spans["lens"], max_len=max_len,
                                        interpret=interpret)
    dec = decode_jsonl_pallas(batch, lens_c, max_fields=max_fields,
                              nested=nested, interpret=interpret)
    return spans, dec


# ---------------------------------------------------------------------------
# decode-tier dispatch (probed by decode_*_submit between the AOT
# lookup and the jnp jit; never raises)

def _decode_state(fmt: str) -> Dict:
    return _DECODE_STATE.setdefault(fmt, {})


def decode_tier(fmt: str, batch_dev, lens_dev,
                max_sd: Optional[int] = None) -> Optional[Dict]:
    """Run one packed batch through the format's Pallas kernel, or
    return None (tier off, format unwired, cooldown, or a
    decline) — the caller falls to its ``decode_*_jit`` exactly like
    an AOT miss.  Failures ride the framing-style decline ladder:
    watchdogged first compile, DECLINE_LIMIT strikes then COOLDOWN
    batches of jnp decode before the next probe."""
    from ..obs import events as _events
    from ..utils.metrics import registry as _metrics
    from .device_common import guarded_compile_call
    from .framing import in_cooldown, note_decline, note_success

    if not engaged() or fmt not in ("rfc5424", "jsonl"):
        return None
    state = _decode_state(fmt)
    if in_cooldown(state):
        return None
    N, L = batch_dev.shape
    interp = interpret_mode()
    slot = f"pallas/decode_{fmt}:{N}x{L}"

    def run():
        # zero-JIT boot: a pallas-family AOT artifact replaces the
        # trace+compile (byte-identical by construction); None → live
        from . import aot as _aot

        out = _aot.pallas_call(f"decode_{fmt}",
                               (batch_dev, lens_dev),
                               _aot.pallas_statics(f"decode_{fmt}", N, 0))
        if out is not None:
            return out
        if fmt == "rfc5424":
            from .rfc5424 import DEFAULT_MAX_SD, decode_rfc5424_pallas

            return decode_rfc5424_pallas(
                batch_dev, lens_dev,
                max_sd=DEFAULT_MAX_SD if max_sd is None else max_sd,
                interpret=interp)
        return decode_jsonl_pallas(batch_dev, lens_dev, interpret=interp)

    try:
        out = guarded_compile_call(slot, run)
    except Exception as e:  # noqa: BLE001 - decline to the jnp tier, never lose the batch
        note_decline(state)
        _metrics.inc("pallas_declines")
        _events.emit("decode", "pallas_decline", route=fmt,
                     detail=f"{type(e).__name__}: {e}")
        return None
    note_success(state)
    _metrics.inc("pallas_rows", N)
    return out
