"""Device-side RFC5424→GELF encode: the kernel emits the *final framed
output bytes* as one dense ``[N, OW]`` byte matrix plus a length vector,
then compacts the tier rows on-device (device_common._compact_kernel)
so the host fetch is ~``sum(out_len)`` bytes — truly output-sized —
instead of ~24 span channels or the padded matrix (the reference fuses
decode→encode per line in its hot loop, line_splitter.rs:44-54 →
gelf_encoder.rs:59-115 — this is the batched-TPU shape of that fusion).
The row-constant head, timestamp-label, and tail segments never cross
PCIe at all: the kernel runs with ``elide=True`` and the driver splices
those exact host-tier bytes back after the fetch
(device_common.splice_elided_rows), which is what brings fetched
bytes/row *under* emitted bytes/row.

Everything is gather-free (the environment's recorded XLA-on-TPU fact:
dynamic gathers lower near-serially — never gather):

- **JSON escaping** is a monotone expansion: each byte's destination is
  ``j + #escapes-before(j) (+1 for the escaped byte itself)``, placed
  collision-free by the MSB-first barrel shifter
  (device_common._monotone_expand).
- **Segment assembly** is an OR-accumulation over a *static* list of
  ~48 segments (1 brace + 5 per SD pair + 17 tail parts, mirroring
  encode_gelf_block.py's layout byte-for-byte) via
  device_common.assemble_rows.
- **SD pair sorting** (serde_json's BTreeMap key order) extracts each
  name's first 8 bytes into two packed int32 words via masked one-hot
  sums, runs a 12-comparator sorting network over the ≤6-pair tier with
  the d-mapped spans riding as payload, and falls the row back to the
  host tiers when keys are ambiguous (equal 8-byte prefixes that zero-
  padding cannot order) or duplicate (dict last-wins semantics).

Rows outside the tier (kernel-flagged, non-ASCII, >6 pairs, RFC5424
value escapes, 6-byte ``\\u00XX`` control escapes, oversized output)
keep their existing host paths, so observable bytes stay identical to
the scalar route in every case.

The timestamp digits (shortest round-trip f64, serde_json/Ryu form) are
formatted host-side (native threaded formatter) and uploaded as a
``[N, TS_W]`` text block — the only host↔device round-trip; everything
else rides the decode call's device-resident channels.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.gelf:GelfEncoder"
DIFF_TEST = "tests/test_device_gelf.py::test_device_matches_scalar_and_engages"

import os
from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .device_common import (  # noqa: F401  (re-exported for tests/siblings)
    COMPACT_G,
    COMPACT_MIN_SAVING,
    E_CAP,
    TS_W,
    _AMBIG_LEN,
    _BIG,
    _NET6,
    _compact_kernel,
    _monotone_expand,
    _rot_rows,
    _out_width,
    assemble_rows,
    escape_stage,
    fetch_encode_driver,
    sort_pairs_by_key8,
    ts_text_block as _ts_text_block,
)
from .rfc5424 import _cumsum, best_scan_impl

_I32 = jnp.int32
_U8 = jnp.uint8

# constant bank: the same byte constants the host tier uses (single
# source of truth — the two tiers must never diverge, since fallback
# rows splice host-tier output into device-tier blocks)
from .encode_gelf_block import (  # noqa: E402
    _C_APP, _C_DASH, _C_FULL, _C_HOST, _C_LEVEL, _C_OPEN, _C_P0, _C_P1,
    _C_P2, _C_PROC, _C_SDID, _C_SEVD, _C_SHORT, _C_TAIL, _C_TS,
    _C_UNKNOWN,
)

_PARTS = {
    "open": _C_OPEN,
    "p0": _C_P0,
    "p1": _C_P1,
    "p2": _C_P2,
    "app": _C_APP,
    "full": _C_FULL,
    "host": _C_HOST,
    "level": _C_LEVEL,
    "proc": _C_PROC,
    "sdid": _C_SDID,
    "short": _C_SHORT,
    "ts": _C_TS,
    "tail": _C_TAIL,
    "unknown": _C_UNKNOWN,
    "dash": _C_DASH,
    "sevd": _C_SEVD,
}

def _bank(suffix: bytes, extras: Tuple[Tuple[str, str], ...] = ()
          ) -> Tuple[bytes, Dict[str, int], Dict[str, bytes]]:
    """Constant bank with any ``gelf_extra`` pairs folded into the
    neighbouring segment constants (static insertion slots — the same
    gelf_extra_consts the host tier uses, so the two tiers can never
    disagree on extras placement)."""
    from .encode_gelf_block import gelf_extra_consts

    parts = dict(_PARTS)
    if extras:
        econsts = gelf_extra_consts(list(extras))
        assert econsts is not None  # route_ok pre-checked
        (parts["open"], parts["app"], parts["full"], parts["host"],
         parts["level"], parts["proc"], parts["p6x"], parts["short"],
         parts["ts"], parts["tail"]) = econsts
    from .device_common import build_bank

    bank, offs = build_bank(parts, suffix)
    return bank, offs, parts


def elide_spec(suffix: bytes, extras=()):
    """(head, ts-label, tail) constants the elided kernel skips and the
    host splice restores — single source shared with the fused route."""
    _, _, parts = _bank(suffix, extras)
    return (parts["open"], parts["ts"], parts["tail"] + suffix)


@partial(jax.jit, static_argnames=("suffix", "max_sd", "impl",
                                   "assemble", "extras", "elide"))
def _encode_kernel(batch, lens, dec, ts_text, ts_len, *, suffix: bytes,
                   max_sd: int, impl: str, assemble: bool = True,
                   extras: Tuple[Tuple[str, str], ...] = (),
                   elide: bool = False):
    N, L = batch.shape
    bank, off, parts = _bank(suffix, extras)
    OW = _out_width(L, L + E_CAP + len(bank) + TS_W)
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)
    bb = batch.astype(_I32)

    es = escape_stage(batch, lens, iota,
                      lambda x: _cumsum(x, impl), assemble)
    dmap = es["dmap"]

    # ---- fixed-field spans in escaped coordinates ------------------------
    app_s, app_e = dmap(dec["app_start"]), dmap(dec["app_end"])
    proc_s, proc_e = dmap(dec["proc_start"]), dmap(dec["proc_end"])
    host_s, host_e = dmap(dec["host_start"]), dmap(dec["host_end"])
    full_s = dmap(dec["full_start"])
    trim_e = dmap(dec["trim_end"])
    msg_s = dmap(dec["msg_trim_start"])

    sd_count = dec["sd_count"].astype(_I32)
    nsd = sd_count > 0
    # last SD block id span (select over the small static block axis)
    sid_s_raw = jnp.zeros_like(sd_count)
    sid_e_raw = jnp.zeros_like(sd_count)
    for k in range(dec["sid_start"].shape[1]):
        pick = sd_count - 1 == k
        sid_s_raw = jnp.where(pick, dec["sid_start"][:, k].astype(_I32),
                              sid_s_raw)
        sid_e_raw = jnp.where(pick, dec["sid_end"][:, k].astype(_I32),
                              sid_e_raw)
    sid_s, sid_e = dmap(sid_s_raw), dmap(sid_e_raw)

    # ---- SD pairs: 8-byte name keys, d-mapped spans, shared sorter ------
    pair_count = dec["pair_count"].astype(_I32)
    P = dec["name_start"].shape[1]
    val_esc_any = jnp.zeros((N,), dtype=bool)
    cols = {"_pair_count": pair_count, "ns_raw": [], "ne_raw": [],
            "ns": [], "ne": [], "vs": [], "ve": []}
    for p in range(P):
        ns_r = dec["name_start"][:, p].astype(_I32)
        ne_r = dec["name_end"][:, p].astype(_I32)
        val_esc_any |= (dec["val_has_esc"][:, p].astype(bool)
                        & (p < pair_count))
        cols["ns_raw"].append(ns_r)
        cols["ne_raw"].append(ne_r)
        cols["ns"].append(dmap(ns_r))
        cols["ne"].append(dmap(ne_r))
        cols["vs"].append(dmap(dec["val_start"][:, p]))
        cols["ve"].append(dmap(dec["val_end"][:, p]))
    ambig = sort_pairs_by_key8(bb, iota, cols, P)

    # ---- segment table ---------------------------------------------------
    EW = L + E_CAP
    cbase = EW
    tbase = EW + len(bank)
    zero = jnp.zeros((N,), dtype=_I32)
    segs = []  # (src0 [N], seglen [N]) in destination order

    def add_const(name, gate=None):
        ln = zero + len(parts[name]) + (len(suffix) if name == "tail"
                                        else 0)
        if gate is not None:
            ln = jnp.where(gate, ln, 0)
        segs.append((zero + (cbase + off[name]), ln))

    def add_span(s, e, gate=None):
        ln = jnp.maximum(e - s, 0)
        if gate is not None:
            ln = jnp.where(gate, ln, 0)
        segs.append((s, ln))

    if not elide:
        # constant-elision mode skips the row-constant head, timestamp
        # label, and tail segments: the host splice restores them after
        # an output-sized (variable-bytes-only) D2H fetch
        # (device_common.splice_elided_rows)
        add_const("open")
    for p in range(P):
        pv = p < pair_count
        add_const("p0", pv)
        add_span(cols["ns"][p], cols["ne"][p], pv)
        add_const("p1", pv)
        add_span(cols["vs"][p], cols["ve"][p], pv)
        add_const("p2", pv)

    add_const("app")
    add_span(app_s, app_e)
    add_const("full")
    add_span(full_s, trim_e)
    add_const("host")
    host_empty = host_e <= host_s
    segs.append((jnp.where(host_empty, cbase + off["unknown"], host_s),
                 jnp.where(host_empty, len(parts["unknown"]),
                           host_e - host_s)))
    add_const("level")
    segs.append((cbase + off["sevd"] + dec["severity"].astype(_I32),
                 zero + 1))
    add_const("proc")
    add_span(proc_s, proc_e)
    if parts.get("p6x"):
        # extras sorting between "process_id" and "sd_id": always-on
        # constant ahead of the (gated) sd_id segment
        add_const("p6x")
    add_const("sdid", nsd)
    add_span(sid_s, sid_e, nsd)
    add_const("short")
    msg_empty = trim_e <= msg_s
    segs.append((jnp.where(msg_empty, cbase + off["dash"], msg_s),
                 jnp.where(msg_empty, 1, trim_e - msg_s)))
    if not elide:
        add_const("ts")
    segs.append((zero + tbase, ts_len.astype(_I32)))
    if not elide:
        add_const("tail")

    out_len = segs[0][1]
    for _, ln in segs[1:]:
        out_len = out_len + ln

    # ---- tier ------------------------------------------------------------
    tier = (dec["ok"].astype(bool)
            & ~dec["has_high"].astype(bool)
            & ~jnp.any(es["bad_ctl"], axis=1)
            & (es["ne_total"] <= E_CAP)
            & (pair_count <= P)
            & (sd_count <= max_sd)
            & ~val_esc_any
            & ~ambig
            & (out_len <= OW))
    if not assemble:
        return tier
    acc, out_len2 = assemble_rows(segs, es["esc_row"], bank, ts_text,
                                  N, OW)
    return acc, out_len2, tier


def route_ok(encoder, merger) -> bool:
    """Device encode applies to GELF output over line/nul/syslen framing
    (the syslen prefix is spliced host-side over the output-sized device
    body); gelf_extra rides as constant segments when its keys have
    static placement (encode_gelf_block.gelf_extra_slots)."""
    from .device_common import gelf_route_ok
    from .encode_gelf_block import gelf_extra_slots

    return gelf_route_ok(
        encoder, merger, lambda e: gelf_extra_slots(e) is not None)


# fraction of non-tier rows above which the span-fetch host path wins
# (scalar oracle ≈70K rows/s vs native assembler ≈1.16M rows/s per core).
# Rows the decode kernel itself flagged — including 7-16-pair rows the
# span path would rescue through the wider tier-2 kernel — count against
# this budget, so a stream that is persistently rescue-heavy declines to
# the span path rather than scalar-oracling those rows forever.
FALLBACK_FRAC = 0.05

# hysteresis: after this many consecutive declined batches, skip the
# device attempt entirely for COOLDOWN batches before probing again
DECLINE_LIMIT = 3
COOLDOWN = 16


def fetch_encode(handle, packed, encoder, merger, route_state=None):
    """Run the device encode for a submitted rfc5424 decode; returns
    (BlockResult | None, fetch_seconds). None = caller should use the
    span-fetch host path (high fallback fraction).  See
    device_common.fetch_encode_driver for the shared flow."""
    from .block_common import merger_suffix

    out, _, _, max_sd, impl_unused, batch_dev, lens_dev = handle
    suffix, syslen = merger_suffix(merger)
    impl = best_scan_impl()
    extras = tuple((k, v) for k, v in getattr(encoder, "extra", ()))
    # constant elision: the head, timestamp-label, and tail constants
    # never cross PCIe — the kernel skips them and the driver splices
    # these exact host-tier bytes back (same _bank the kernel uses, so
    # the two sides cannot disagree)
    espec = elide_spec(suffix, extras)

    def kernel(ts_text, ts_len, assemble):
        return _encode_kernel(batch_dev, lens_dev, dict(out), ts_text,
                              ts_len, suffix=suffix, max_sd=max_sd,
                              impl=impl, assemble=assemble,
                              extras=extras, elide=True)

    # zero-JIT boot: a loaded AOT artifact replaces the trace+compile
    # (same program, byte-identical); misses/rejects fall through to
    # the jit closure under the same watchdog
    from .aot import encode_wrap

    kernel = encode_wrap("device_gelf", kernel, batch_dev, lens_dev,
                         dict(out), suffix, impl, extras, max_sd=max_sd)

    def wide():
        """Pair-budget escalation: re-decode the batch on-device at the
        decode rescue width (16 SD pairs) and encode from those
        channels — the [N, 16] pair axis sizes the sorter and segment
        table automatically.  Lazy: a 7+-pair stream pays the second
        decode + wide compile only when the base width declines."""
        from .rfc5424 import RESCUE_MAX_PAIRS, decode_rfc5424_jit

        out_w = decode_rfc5424_jit(batch_dev, lens_dev, max_sd=max_sd,
                                   max_pairs=RESCUE_MAX_PAIRS)

        def kernel_w(ts_text, ts_len, assemble):
            return _encode_kernel(batch_dev, lens_dev, dict(out_w),
                                  ts_text, ts_len, suffix=suffix,
                                  max_sd=max_sd, impl=impl,
                                  assemble=assemble, extras=extras,
                                  elide=True)
        return out_w, kernel_w

    from .materialize import _scalar_line

    return fetch_encode_driver(
        kernel, out, batch_dev, lens_dev, packed, encoder, merger,
        route_state, suffix, syslen, scalar_fn=_scalar_line,
        fallback_frac=FALLBACK_FRAC, decline_limit=DECLINE_LIMIT,
        cooldown=COOLDOWN, wide=wide, elide=espec)
