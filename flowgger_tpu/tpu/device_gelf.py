"""Device-side RFC5424→GELF encode: the kernel emits the *final framed
output bytes* as one dense ``[N, OW]`` byte matrix plus a length vector,
then compacts the tier rows on-device (``_compact_kernel``) so the host
fetch is ~``sum(out_len)`` bytes — truly output-sized — instead of
~24 span channels or the padded matrix (the reference fuses
decode→encode per line in its hot loop, line_splitter.rs:44-54 →
gelf_encoder.rs:59-115 — this is the batched-TPU shape of that fusion).

Everything is gather-free (the environment's recorded XLA-on-TPU fact:
dynamic gathers lower near-serially — never gather):

- **JSON escaping** is a monotone expansion: each byte's destination is
  ``j + #escapes-before(j) (+1 for the escaped byte itself)``, shifts are
  nondecreasing along the row, and an MSB-first barrel shifter places
  bytes collision-free in ``log2(E_CAP)`` masked-select passes (proof:
  after processing bit k, positions ``j + (s>>k<<k)`` stay strictly
  increasing whenever ``s`` is nondecreasing — right-shifts only).
- **Segment assembly** is an OR-accumulation over a *static* list of
  ~48 segments (1 brace + 5 per SD pair + 17 tail parts, mirroring
  encode_gelf_block.py's layout byte-for-byte): each segment masks its
  source span out of a concatenated source row (escaped line ∥ constant
  bank ∥ timestamp text) and cyclically rotates it to its destination
  with a per-row power-of-2 barrel (``log2(OW)`` selects), where the
  destination offsets are an exclusive running sum of segment lengths.
- **SD pair sorting** (serde_json's BTreeMap key order) extracts each
  name's first 8 bytes into two packed int32 words via masked one-hot
  sums, runs a 12-comparator sorting network over the ≤6-pair tier with
  the d-mapped spans riding as payload, and falls the row back to the
  host tiers when keys are ambiguous (equal 8-byte prefixes that zero-
  padding cannot order) or duplicate (dict last-wins semantics).

Rows outside the tier (kernel-flagged, non-ASCII, >6 pairs, RFC5424
value escapes, 6-byte ``\\u00XX`` control escapes, oversized output)
keep their existing host paths, so observable bytes stay identical to
the scalar route in every case.

The timestamp digits (shortest round-trip f64, serde_json/Ryu form) are
formatted host-side from a small scalar fetch and uploaded as a
``[N, TS_W]`` text block — the only host↔device round-trip; everything
else rides the decode call's device-resident channels.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.rustfmt import json_f64
from .assemble import exclusive_cumsum
from .block_common import finish_block, merger_suffix
from .materialize import compute_ts
from .rfc5424 import _cumsum, best_scan_impl

_I32 = jnp.int32
_U8 = jnp.uint8

TS_W = 32          # timestamp text slot width (longest json_f64 ≈ 25)
E_CAP = 56         # max JSON escapes per row on the device tier
_AMBIG_LEN = 8     # name-key bytes captured for sorting
_BIG = 0x7FFFFFFF  # sort key for absent pairs (names are ASCII < 0x7f)

# constant bank: the same byte constants the host tier uses (single
# source of truth — the two tiers must never diverge, since fallback
# rows splice host-tier output into device-tier blocks)
from .encode_gelf_block import (  # noqa: E402
    _C_APP, _C_DASH, _C_FULL, _C_HOST, _C_LEVEL, _C_OPEN, _C_P0, _C_P1,
    _C_P2, _C_PROC, _C_SDID, _C_SEVD, _C_SHORT, _C_TAIL, _C_TS,
    _C_UNKNOWN,
)

_PARTS = {
    "open": _C_OPEN,
    "p0": _C_P0,
    "p1": _C_P1,
    "p2": _C_P2,
    "app": _C_APP,
    "full": _C_FULL,
    "host": _C_HOST,
    "level": _C_LEVEL,
    "proc": _C_PROC,
    "sdid": _C_SDID,
    "short": _C_SHORT,
    "ts": _C_TS,
    "tail": _C_TAIL,
    "unknown": _C_UNKNOWN,
    "dash": _C_DASH,
    "sevd": _C_SEVD,
}

# optimal 12-comparator sorting network for 6 elements
_NET6 = ((0, 5), (1, 3), (2, 4), (1, 2), (3, 4), (0, 3), (2, 5),
         (0, 1), (2, 3), (4, 5), (1, 2), (3, 4))


def _bank(suffix: bytes) -> Tuple[bytes, Dict[str, int]]:
    offs, bank = {}, b""
    for k, v in _PARTS.items():
        if k == "tail":
            v = v + suffix
        offs[k] = len(bank)
        bank += v
    return bank, offs


def _shr2d(arr, k):
    """Shift rows right by static k (drop tail, zero-fill head)."""
    if k == 0:
        return arr
    return jnp.pad(arr[:, :-k], ((0, 0), (k, 0)))


def _monotone_expand(vals, shifts, w_out, nbits):
    """Place vals[i,j] at column j + shifts[i,j]; shifts nondecreasing
    along each row, < 2**nbits. Vacated slots become 0 (vals must be 0
    where nothing is emitted). MSB-first barrel: collision-free because
    intermediate positions j + (s>>k<<k) stay strictly increasing."""
    x = jnp.pad(vals, ((0, 0), (0, w_out - vals.shape[1])))
    s = jnp.pad(shifts, ((0, 0), (0, w_out - shifts.shape[1])))
    for k in range(nbits - 1, -1, -1):
        d = 1 << k
        mv = s >= d
        xm = jnp.where(mv, x, 0)
        sm = jnp.where(mv, s - d, 0)
        x = jnp.where(mv, 0, x) | _shr2d(xm, d)
        s = jnp.where(mv, 0, s) + _shr2d(sm, d)
    return x


def _rot_rows(x, r, w: int):
    """Cyclic right-rotate each row of [N, w] by per-row r (w pow2)."""
    for k in range(w.bit_length() - 1):
        d = 1 << k
        bit = ((r >> k) & 1) == 1
        rolled = jnp.concatenate([x[:, -d:], x[:, :-d]], axis=1)
        x = jnp.where(bit[:, None], rolled, x)
    return x


def _out_width(L: int) -> int:
    """Static output width: a power of two covering the concatenated
    source row and typical GELF output for lines of width L."""
    w = 512
    while w < 2 * L:
        w *= 2
    return w


@partial(jax.jit, static_argnames=("suffix", "max_sd", "impl",
                                   "assemble"))
def _encode_kernel(batch, lens, dec, ts_text, ts_len, *, suffix: bytes,
                   max_sd: int, impl: str, assemble: bool = True):
    N, L = batch.shape
    OW = _out_width(L)
    bank, off = _bank(suffix)
    CB = len(bank)
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)
    bb = batch.astype(_I32)
    valid = iota < lens.astype(_I32)[:, None]

    # ---- escape classes --------------------------------------------------
    two_ctl = ((bb == 8) | (bb == 9) | (bb == 10) | (bb == 12) | (bb == 13))
    esc = ((bb == 34) | (bb == 92) | two_ctl) & valid
    bad_ctl = (bb < 32) & ~two_ctl & valid
    mapped = jnp.where(bb == 8, ord("b"),
             jnp.where(bb == 9, ord("t"),
             jnp.where(bb == 10, ord("n"),
             jnp.where(bb == 12, ord("f"),
             jnp.where(bb == 13, ord("r"), bb)))))
    mapped = jnp.where(valid, mapped, 0).astype(_I32)

    esc_i = esc.astype(_I32)
    ne_incl = _cumsum(esc_i, impl)
    ne_excl = ne_incl - esc_i
    ne_total = ne_incl[:, -1]

    nbits = E_CAP.bit_length()
    EW = L + E_CAP
    esc_row = None
    if assemble:
        s_main = jnp.minimum(ne_excl + esc_i, E_CAP)
        s_pref = jnp.minimum(ne_excl, E_CAP)
        main = _monotone_expand(mapped, s_main, EW, nbits)
        pref = _monotone_expand(jnp.where(esc, ord("\\"), 0).astype(_I32),
                                s_pref, EW, nbits)
        esc_row = (main | pref).astype(_U8)

    # d-map: raw index a -> escaped index a + #escapes-before(a)
    def dmap(a):
        a = a.astype(_I32)
        ne_at = jnp.sum(esc_i * (iota < a[:, None]), axis=1)
        return a + ne_at

    # ---- fixed-field spans in escaped coordinates ------------------------
    app_s, app_e = dmap(dec["app_start"]), dmap(dec["app_end"])
    proc_s, proc_e = dmap(dec["proc_start"]), dmap(dec["proc_end"])
    host_s, host_e = dmap(dec["host_start"]), dmap(dec["host_end"])
    full_s = dmap(dec["full_start"])
    trim_e = dmap(dec["trim_end"])
    msg_s = dmap(dec["msg_trim_start"])

    sd_count = dec["sd_count"].astype(_I32)
    nsd = sd_count > 0
    # last SD block id span (select over the small static block axis)
    sid_s_raw = jnp.zeros_like(sd_count)
    sid_e_raw = jnp.zeros_like(sd_count)
    for k in range(dec["sid_start"].shape[1]):
        pick = sd_count - 1 == k
        sid_s_raw = jnp.where(pick, dec["sid_start"][:, k].astype(_I32),
                              sid_s_raw)
        sid_e_raw = jnp.where(pick, dec["sid_end"][:, k].astype(_I32),
                              sid_e_raw)
    sid_s, sid_e = dmap(sid_s_raw), dmap(sid_e_raw)

    # ---- SD pairs: 8-byte name keys, d-mapped spans, sorting network -----
    pair_count = dec["pair_count"].astype(_I32)
    P = dec["name_start"].shape[1]
    val_esc_any = jnp.zeros((N,), dtype=bool)
    cols = {k: [] for k in ("hi", "lo", "nlen", "ns", "ne", "vs", "ve")}
    for p in range(P):
        ns_r = dec["name_start"][:, p].astype(_I32)
        ne_r = dec["name_end"][:, p].astype(_I32)
        pv = p < pair_count
        val_esc_any |= dec["val_has_esc"][:, p].astype(bool) & pv
        r = iota - ns_r[:, None]
        in_name = (r >= 0) & (iota < ne_r[:, None])
        z = jnp.where(in_name, bb, 0)
        hi = jnp.sum(z * ((r == 0) * (1 << 24) + (r == 1) * (1 << 16)
                          + (r == 2) * (1 << 8) + (r == 3)), axis=1)
        lo = jnp.sum(z * ((r == 4) * (1 << 24) + (r == 5) * (1 << 16)
                          + (r == 6) * (1 << 8) + (r == 7)), axis=1)
        cols["hi"].append(jnp.where(pv, hi, _BIG))
        cols["lo"].append(jnp.where(pv, lo, _BIG))
        cols["nlen"].append(jnp.where(pv, ne_r - ns_r, _BIG))
        cols["ns"].append(dmap(ns_r))
        cols["ne"].append(dmap(ne_r))
        cols["vs"].append(dmap(dec["val_start"][:, p]))
        cols["ve"].append(dmap(dec["val_end"][:, p]))

    for i, j in _NET6:
        if i >= P or j >= P:
            continue
        ah, bh = cols["hi"][i], cols["hi"][j]
        al, bl = cols["lo"][i], cols["lo"][j]
        an, bn = cols["nlen"][i], cols["nlen"][j]
        swap = (bh < ah) | ((bh == ah) & ((bl < al)
                            | ((bl == al) & (bn < an))))
        for key in cols:
            a, b = cols[key][i], cols[key][j]
            cols[key][i] = jnp.where(swap, b, a)
            cols[key][j] = jnp.where(swap, a, b)

    # ambiguity / duplicate detection on sorted neighbours: equal 8-byte
    # keys are adjacent after sorting; zero-padding orders them only when
    # exactly one name is ≤8 bytes (a strict prefix of the other)
    ambig = jnp.zeros((N,), dtype=bool)
    for p in range(P - 1):
        keq = ((cols["hi"][p] == cols["hi"][p + 1])
               & (cols["lo"][p] == cols["lo"][p + 1])
               & (cols["hi"][p] != _BIG))
        la, lb = cols["nlen"][p], cols["nlen"][p + 1]
        ambig |= keq & ((la == lb) | ((la > _AMBIG_LEN)
                                      & (lb > _AMBIG_LEN)))

    # ---- segment table ---------------------------------------------------
    cbase = EW
    tbase = EW + CB
    zero = jnp.zeros((N,), dtype=_I32)
    segs = []  # (src0 [N], seglen [N]) in destination order

    def add_const(name, gate=None):
        ln = zero + len(_PARTS[name]) + (len(suffix) if name == "tail"
                                         else 0)
        if gate is not None:
            ln = jnp.where(gate, ln, 0)
        segs.append((zero + (cbase + off[name]), ln))

    def add_span(s, e, gate=None):
        ln = jnp.maximum(e - s, 0)
        if gate is not None:
            ln = jnp.where(gate, ln, 0)
        segs.append((s, ln))

    add_const("open")
    for p in range(P):
        pv = p < pair_count
        add_const("p0", pv)
        add_span(cols["ns"][p], cols["ne"][p], pv)
        add_const("p1", pv)
        add_span(cols["vs"][p], cols["ve"][p], pv)
        add_const("p2", pv)

    add_const("app")
    add_span(app_s, app_e)
    add_const("full")
    add_span(full_s, trim_e)
    add_const("host")
    host_empty = host_e <= host_s
    segs.append((jnp.where(host_empty, cbase + off["unknown"], host_s),
                 jnp.where(host_empty, len(_PARTS["unknown"]),
                           host_e - host_s)))
    add_const("level")
    segs.append((cbase + off["sevd"] + dec["severity"].astype(_I32),
                 zero + 1))
    add_const("proc")
    add_span(proc_s, proc_e)
    add_const("sdid", nsd)
    add_span(sid_s, sid_e, nsd)
    add_const("short")
    msg_empty = trim_e <= msg_s
    segs.append((jnp.where(msg_empty, cbase + off["dash"], msg_s),
                 jnp.where(msg_empty, 1, trim_e - msg_s)))
    add_const("ts")
    segs.append((zero + tbase, ts_len.astype(_I32)))
    add_const("tail")

    # ---- assemble --------------------------------------------------------
    # stack the segment table [S, N] and scan: the roll body compiles
    # once instead of once per segment (48x smaller HLO graph), while
    # each step remains a handful of fused [N, OW] elementwise passes
    seg_src = jnp.stack([s for s, _ in segs])
    seg_len = jnp.stack([ln for _, ln in segs])
    seg_dst = jnp.cumsum(seg_len, axis=0) - seg_len
    out_len = seg_dst[-1] + seg_len[-1]

    acc = None
    if assemble:
        const_row = jnp.asarray(np.frombuffer(bank, dtype=np.uint8))
        src2 = jnp.concatenate([
            esc_row,
            jnp.broadcast_to(const_row[None, :], (N, CB)),
            ts_text.astype(_U8),
        ], axis=1)
        if src2.shape[1] > OW:
            raise ValueError(f"source row {src2.shape[1]} exceeds OW {OW}")
        src2 = jnp.pad(src2, ((0, 0), (0, OW - src2.shape[1])))
        iow = jax.lax.broadcasted_iota(_I32, (N, OW), 1)

        def step(a, xs):
            src0, seglen, dst0 = xs
            m = (iow >= src0[:, None]) & (iow < (src0 + seglen)[:, None])
            contrib = jnp.where(m, src2, jnp.uint8(0))
            return a | _rot_rows(contrib, (dst0 - src0) % OW, OW), None

        acc, _ = jax.lax.scan(step, jnp.zeros((N, OW), dtype=_U8),
                              (seg_src, seg_len, seg_dst))

    # ---- tier ------------------------------------------------------------
    tier = (dec["ok"].astype(bool)
            & ~dec["has_high"].astype(bool)
            & ~jnp.any(bad_ctl, axis=1)
            & (ne_total <= E_CAP)
            & (pair_count <= P)
            & (sd_count <= max_sd)
            & ~val_esc_any
            & ~ambig
            & (out_len <= OW))
    if not assemble:
        return tier
    return acc, out_len, tier


COMPACT_G = 32   # group granularity (bytes) of on-device row compaction
# skip compaction when padded size is within this factor of the real
# output (the extra device passes would not pay for the smaller fetch)
COMPACT_MIN_SAVING = 1.15


@partial(jax.jit, static_argnames=("G",))
def _compact_kernel(acc, out_len, tier, *, G: int = COMPACT_G):
    """Row compaction on device: pack the tier rows' output bytes into a
    contiguous group-aligned buffer so the host fetches ~sum(out_len)
    bytes instead of the padded ``[N, OW]`` matrix.

    Rows are already left-aligned, so compaction is a pure left-shift of
    whole G-byte groups: row i's ``ceil(len/G)`` leading groups move to
    group offset ``base[i] = sum_j<i ceil(len_j/G)``.  The per-group
    shift ``i*(OW/G) - base[i]`` is row-constant and nondecreasing, and
    destinations are strictly increasing, so an LSB-first barrel shifter
    is collision-free: after applying bits 0..k, two valid groups a < b
    satisfy ``p_b - p_a = (b-a) - ((s_b&m)-(s_a&m)) >= (b-a)-(s_b-s_a)
    >= 1`` (low-bit differences never exceed the full difference when
    the high bits are monotone).  Non-tier and padding groups are zeroed
    and stay put (shift 0); moving groups OR over them harmlessly.

    Returns the flat byte buffer; the host slices the first
    ``sum(ceil(gated_len/G))*G`` bytes (it recomputes base from the
    fetched lengths with the same integer math)."""
    N, OW = acc.shape
    assert OW % G == 0
    ngr = OW // G
    gated = jnp.where(tier, out_len, 0)
    used = (gated + (G - 1)) // G                          # [N]
    base = jnp.cumsum(used) - used                         # exclusive
    gi = jax.lax.broadcasted_iota(_I32, (N, ngr), 1)
    row = jax.lax.broadcasted_iota(_I32, (N, ngr), 0)
    valid = gi < used[:, None]
    shift = jnp.where(valid, row * ngr - base[:, None], 0).reshape(-1)
    x = jnp.where(valid.reshape(-1)[:, None], acc.reshape(N * ngr, G),
                  jnp.uint8(0))
    s = shift
    T = N * ngr
    for k in range(max(T - 1, 1).bit_length()):
        d = 1 << k
        if d >= T:
            break
        mv = ((s >> k) & 1) == 1
        xm = jnp.where(mv[:, None], x, jnp.uint8(0))
        sm = jnp.where(mv, s - d, 0)
        x = jnp.where(mv[:, None], jnp.uint8(0), x)
        s = jnp.where(mv, 0, s)
        x = x | jnp.concatenate(
            [xm[d:], jnp.zeros((d, G), jnp.uint8)], axis=0)
        s = s + jnp.concatenate(
            [sm[d:], jnp.zeros((d,), s.dtype)], axis=0)
    return x.reshape(-1)


def route_ok(encoder, merger) -> bool:
    """Device encode applies to GELF output without extras over line/nul
    framing (syslen's variable-width prefix stays on the host tiers)."""
    from ..encoders.gelf import GelfEncoder
    from ..mergers import LineMerger, NulMerger

    if os.environ.get("FLOWGGER_DEVICE_ENCODE", "1") == "0":
        return False
    if type(encoder) is not GelfEncoder or encoder.extra:
        return False
    return merger is None or type(merger) in (LineMerger, NulMerger)


# fraction of non-tier rows above which the span-fetch host path wins
# (scalar oracle ≈70K rows/s vs native assembler ≈1.16M rows/s per core).
# Rows the decode kernel itself flagged — including 7-16-pair rows the
# span path would rescue through the wider tier-2 kernel — count against
# this budget, so a stream that is persistently rescue-heavy declines to
# the span path rather than scalar-oracling those rows forever.
FALLBACK_FRAC = 0.05

# hysteresis: after this many consecutive declined batches, skip the
# device attempt entirely for COOLDOWN batches before probing again
DECLINE_LIMIT = 3
COOLDOWN = 16


def _ts_text_block(small: Dict[str, np.ndarray]):
    """Format per-row timestamp digits host-side.  The native threaded
    formatter (fg_format_f64_json: to_chars shortest round-trip,
    json_f64 notation — differentially fuzzed in
    tests/test_native_and_chunks.py) handles near-unique real-stream
    stamps at full rate; without the library, fall back to dedup +
    per-unique json_f64 (only fast for repetitive streams)."""
    from .. import native

    okh = small["ok"].astype(bool)
    masked = {k: np.where(okh, small[k], 0)
              for k in ("days", "sod", "off", "nanos")}
    ts_vals = compute_ts(masked)
    res = native.format_f64_json_native(ts_vals, TS_W)
    if res is not None:
        return res
    uniq, inv = np.unique(ts_vals, return_inverse=True)
    txt = np.zeros((uniq.size, TS_W), dtype=np.uint8)
    ulen = np.zeros(uniq.size, dtype=np.int32)
    for u, val in enumerate(uniq):
        s = json_f64(float(val)).encode("ascii")[:TS_W]
        txt[u, :len(s)] = np.frombuffer(s, dtype=np.uint8)
        ulen[u] = len(s)
    return txt[inv], ulen[inv]


def fetch_encode(handle, packed, encoder, merger, route_state=None):
    """Run the device encode for a submitted rfc5424 decode; returns
    (BlockResult | None, fetch_seconds). None = caller should use the
    span-fetch host path (high fallback fraction).

    Phase 1 runs a tier-only variant of the kernel (XLA dead-code-
    eliminates the whole assembly) with a pessimistic TS_W timestamp
    width, so persistently declining streams never pay the assembly or
    the host timestamp formatting; ``route_state`` (a caller-owned dict)
    adds cross-batch hysteresis on top."""
    import time as _time

    from ..utils.metrics import registry as _metrics

    out, _, _, max_sd, _, batch_dev, lens_dev = handle
    batch, lens, chunk, starts, orig_lens, n_real = packed
    n = int(n_real)
    suffix, syslen = merger_suffix(merger)
    assert not syslen

    if route_state is not None and route_state.get("cooldown", 0) > 0:
        route_state["cooldown"] -= 1
        return None, 0.0

    # size the per-row inputs from the *device* batch: a sharded submit
    # may have row-padded it to a dp multiple beyond the host batch
    N = batch_dev.shape[0]
    impl = best_scan_impl()
    empty_ts = jnp.zeros((N, 0), dtype=jnp.uint8)
    full_ts_len = jnp.full((N,), TS_W, dtype=jnp.int32)
    tier1 = _encode_kernel(batch_dev, lens_dev, dict(out), empty_ts,
                           full_ts_len, suffix=suffix, max_sd=max_sd,
                           impl=impl, assemble=False)

    t_fetch = 0.0
    fetched = [0]

    def _fetch(arr):
        nonlocal t_fetch
        t0 = _time.perf_counter()
        h = np.asarray(arr)
        t_fetch += _time.perf_counter() - t0
        fetched[0] += h.nbytes
        return h

    tier1_np = _fetch(tier1)[:n]

    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    max_len = batch.shape[1]
    cand1 = tier1_np & (lens64 <= max_len)

    if n and (1.0 - cand1.mean()) > FALLBACK_FRAC:
        _metrics.inc("device_encode_declined")
        _metrics.inc("device_encode_fetch_bytes", fetched[0])
        if route_state is not None:
            route_state["declines"] = route_state.get("declines", 0) + 1
            if route_state["declines"] >= DECLINE_LIMIT:
                route_state["cooldown"] = COOLDOWN
                route_state["declines"] = 0
        return None, t_fetch
    if route_state is not None:
        route_state["declines"] = 0

    small = {k: _fetch(out[k]) for k in ("ok", "days", "sod", "off",
                                         "nanos")}

    ts_text, ts_len = _ts_text_block(small)
    acc, out_len, tier = _encode_kernel(
        batch_dev, lens_dev, dict(out), jnp.asarray(ts_text),
        jnp.asarray(ts_len), suffix=suffix, max_sd=max_sd,
        impl=impl)

    # full-N fetches (tiny): the host must recompute the compaction
    # layout with the exact integer math the device used, including any
    # dp-padding rows beyond n
    tier_full = _fetch(tier)
    len_full = _fetch(out_len).astype(np.int64)
    tier_np = tier_full[:n]
    len_np = len_full[:n]

    # the real (shorter) timestamp text can only widen the tier vs the
    # pessimistic phase-1 gate; cand stays the decision set either way
    cand = tier_np & (lens64 <= max_len)
    ridx = np.flatnonzero(cand)

    N, OW = acc.shape
    G = COMPACT_G
    gated = np.where(tier_full, len_full, 0)
    total_bytes = int(gated.sum())
    if (total_bytes and ridx.size
            and N * OW > total_bytes * COMPACT_MIN_SAVING):
        # device-side row compaction: D2H ≈ sum(out_len), G-aligned
        flat = _compact_kernel(acc, out_len, tier)
        used = (gated + (G - 1)) // G
        base = np.cumsum(used) - used
        total_groups = int(used.sum())
        comp = _fetch(flat[: total_groups * G]).reshape(-1, G)
        if ridx.size:
            u = used[ridx]
            ucum = np.cumsum(u) - u
            pos = np.arange(int(u.sum()), dtype=np.int64) \
                - np.repeat(ucum, u)
            gidx = np.repeat(base[ridx], u) + pos
            gv = np.minimum(G, np.repeat(len_np[ridx], u) - pos * G)
            grp = comp[gidx]
            final_buf = grp[np.arange(G)[None, :] < gv[:, None]].tobytes()
            row_off = exclusive_cumsum(len_np[ridx])
        else:
            final_buf = b""
            row_off = np.zeros(1, dtype=np.int64)
    elif ridx.size:
        out_np = _fetch(acc)[:n]
        rows = out_np[ridx]
        m = np.arange(rows.shape[1])[None, :] < len_np[ridx, None]
        final_buf = rows[m].tobytes()
        row_off = exclusive_cumsum(len_np[ridx])
    else:
        final_buf = b""
        row_off = np.zeros(1, dtype=np.int64)

    _metrics.inc("device_encode_rows", int(ridx.size))
    _metrics.inc("device_encode_scalar_rows", int(n - ridx.size))
    _metrics.inc("device_encode_fetch_bytes", fetched[0])
    _metrics.inc("device_encode_out_bytes", len(final_buf))
    res = finish_block(chunk, starts64, lens64, n, cand, ridx, final_buf,
                       row_off, None, suffix, False, merger, encoder)
    return res, t_fetch
