"""Columnar DNS block encoders: the fixed-grammar field spans
(tpu/dns.py) become framed GELF or LTSV bytes per batch.

The grammar is fixed, so both layouts are a constant segment skeleton
with six span/scratch holes — no per-row branching at all:

GELF (sorted keys — the three ``_``-pairs sort before every special)::

    {"_latency_us":L,"_qtype":"Q","_rcode":"R","host":"C",
     "short_message":"N","timestamp":T,"version":"1.1"}

LTSV (pair order = Record construction order, prefix stripped)::

    latency_us:L\tqtype:Q\trcode:R\t<extras>host:C\ttime:T\tmessage:N

The timestamp re-formats per row through the dedup scratch (json_f64 /
display_f64); the latency re-emits verbatim when canonical (no leading
zero).  Rows needing escaping — control bytes beyond the five tabs,
quotes/backslashes (GELF), non-ASCII — or a non-canonical latency take
the scalar oracle, keeping bytes identical to DNSDecoder→encoder in
every case.
"""

from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# these routes must stay byte-identical to, and the differential
# tests that enforce it
SCALAR_ORACLE = "flowgger_tpu.decoders.dns:DNSDecoder"
DIFF_TEST = (
    "tests/test_tpu_dns.py::test_dns_gelf_block_matches_scalar",
    "tests/test_tpu_dns.py::test_dns_ltsv_block_matches_scalar",
)

from typing import Dict, Optional

import numpy as np

from ..mergers import Merger
from ..utils.rustfmt import json_f64
from .assemble import (
    build_source,
    concat_segments,
    count_in_spans,
    exclusive_cumsum,
)
from .block_common import (
    BlockResult,
    apply_syslen_prefix,
    finish_block,
    merger_suffix,
    span_f64_scratch,
)
from .materialize_dns import _scalar_dns


def dns_screen(chunk_bytes, starts, orig_lens, out, n_real: int,
               max_len: int, gelf_strings: bool):
    """Shared route screen: kernel-ok rows whose bytes re-emit
    verbatim.  ``gelf_strings`` additionally bans quotes/backslashes
    (JSON string escaping); both routes ban non-ASCII and any control
    byte other than the five separator tabs."""
    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
    chunk_pad = np.concatenate(
        [chunk_arr, np.zeros(max_len + 2, dtype=np.uint8)])

    cand = ok & (lens64 <= max_len) & ~has_high
    # control bytes other than tab would need escaping in either output
    ctl_cum = np.cumsum((chunk_arr < 0x20) & (chunk_arr != 9))
    row_end = starts64 + lens64
    cand &= count_in_spans(ctl_cum, starts64, row_end) == 0
    if gelf_strings:
        esc_cum = np.cumsum((chunk_arr == ord('"'))
                            | (chunk_arr == ord("\\")))
        cand &= count_in_spans(esc_cum, starts64, row_end) == 0

    # latency must be canonical to re-emit verbatim ("007" parses to 7)
    lat_a = starts64 + np.asarray(out["lat_start"])[:n]
    lat_b = starts64 + np.asarray(out["lat_end"])[:n]
    cand &= (chunk_pad[lat_a] != ord("0")) | (lat_b - lat_a == 1)

    def span(key):
        a = starts64 + np.asarray(out[key + "_start"])[:n]
        b = starts64 + np.asarray(out[key + "_end"])[:n]
        return a, b

    return dict(n=n, starts64=starts64, lens64=lens64, cand=cand,
                chunk_arr=chunk_arr, span=span,
                lat_a=lat_a, lat_b=lat_b)


def _assemble_fixed(chunk_bytes, s, cols_fn, fmt_fn, suffix, syslen,
                    merger, encoder):
    """Shared fixed-skeleton assembly: ``cols_fn(ridx, consts_offsets,
    cbase, ts_off, ts_len)`` returns the per-row (src, len) column
    grid."""
    n, starts64, lens64, cand = (s["n"], s["starts64"], s["lens64"],
                                 s["cand"])
    chunk_arr = s["chunk_arr"]
    ridx = np.flatnonzero(cand)
    R = ridx.size
    final_buf = b""
    row_off = np.zeros(1, dtype=np.int64)
    prefix_lens_tier: Optional[np.ndarray] = None
    if R:
        tsa, tsb = s["span"]("ts")
        scratch, ts_off, ts_len = span_f64_scratch(
            chunk_bytes, tsa[ridx], tsb[ridx], fmt_fn)
        consts, offs, cbase, src = cols_fn.build(scratch, chunk_arr)
        cols = cols_fn(ridx, offs, cbase, ts_off, ts_len)
        FIXED = len(cols)
        fd = (np.arange(R, dtype=np.int64) * FIXED)[:, None] \
            + np.arange(FIXED, dtype=np.int64)[None, :]
        seg_src = np.empty(R * FIXED, dtype=np.int64)
        seg_len = np.empty(R * FIXED, dtype=np.int64)
        fsrc = np.empty((R, FIXED), dtype=np.int64)
        flen = np.empty((R, FIXED), dtype=np.int64)
        for k, (s_, ln) in enumerate(cols):
            fsrc[:, k] = s_
            flen[:, k] = ln
        seg_src[fd] = fsrc
        seg_len[fd] = flen
        dst0 = exclusive_cumsum(seg_len)
        body = concat_segments(src, seg_src, seg_len, dst0)
        rstart = np.arange(R, dtype=np.int64) * FIXED
        row_off = np.concatenate([dst0[rstart], dst0[-1:]])
        tier_lens = np.diff(row_off)
        if syslen:
            final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
                body, row_off, tier_lens)
        else:
            final_buf = body.tobytes()
    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder, scalar_fn=_scalar_dns)


def encode_dns_gelf_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    spec = merger_suffix(merger)
    if spec is None or encoder.extra:
        return None
    suffix, syslen = spec
    s = dns_screen(chunk_bytes, starts, orig_lens, out, n_real, max_len,
                   gelf_strings=True)

    class Cols:
        @staticmethod
        def build(scratch, chunk_arr):
            consts, offs = build_source(
                b'{"_latency_us":', b',"_qtype":"', b'","_rcode":"',
                b'","host":"', b'","short_message":"', b'","timestamp":',
                b',"version":"1.1"}' + suffix, scratch)
            cbase = int(chunk_arr.size)
            return consts, offs, cbase, np.concatenate(
                [chunk_arr, consts])

        def __call__(self, ridx, offs, cbase, ts_off, ts_len):
            (o_lat, o_qt, o_rc, o_host, o_short, o_ts, o_tail,
             o_scratch) = offs

            def sp(key):
                a, b = s["span"](key)
                return a[ridx], (b - a)[ridx]

            lat_a, lat_l = s["lat_a"][ridx], (s["lat_b"]
                                              - s["lat_a"])[ridx]
            qt_a, qt_l = sp("qtype")
            rc_a, rc_l = sp("rcode")
            cl_a, cl_l = sp("client")
            qn_a, qn_l = sp("qname")
            return (
                (cbase + o_lat, len(b'{"_latency_us":')),
                (lat_a, lat_l),
                (cbase + o_qt, len(b',"_qtype":"')),
                (qt_a, qt_l),
                (cbase + o_rc, len(b'","_rcode":"')),
                (rc_a, rc_l),
                (cbase + o_host, len(b'","host":"')),
                (cl_a, cl_l),
                (cbase + o_short, len(b'","short_message":"')),
                (qn_a, qn_l),
                (cbase + o_ts, len(b'","timestamp":')),
                (cbase + o_scratch + ts_off, ts_len),
                (cbase + o_tail, len(b',"version":"1.1"}')
                 + len(suffix)),
            )

    return _assemble_fixed(chunk_bytes, s, Cols(), json_f64, suffix,
                           syslen, merger, encoder)


def encode_dns_ltsv_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    from ..utils.rustfmt import display_f64
    from .block_common import ltsv_extra_blob

    spec = merger_suffix(merger)
    if spec is None:
        return None
    suffix, syslen = spec
    s = dns_screen(chunk_bytes, starts, orig_lens, out, n_real, max_len,
                   gelf_strings=False)
    extra_blob = ltsv_extra_blob(encoder.extra)

    class Cols:
        @staticmethod
        def build(scratch, chunk_arr):
            consts, offs = build_source(
                b"latency_us:", b"\tqtype:", b"\trcode:",
                b"\t" + extra_blob + b"host:", b"\ttime:",
                b"\tmessage:", suffix, scratch)
            cbase = int(chunk_arr.size)
            return consts, offs, cbase, np.concatenate(
                [chunk_arr, consts])

        def __call__(self, ridx, offs, cbase, ts_off, ts_len):
            (o_lat, o_qt, o_rc, o_host, o_time, o_msg, o_sfx,
             o_scratch) = offs

            def sp(key):
                a, b = s["span"](key)
                return a[ridx], (b - a)[ridx]

            lat_a, lat_l = s["lat_a"][ridx], (s["lat_b"]
                                              - s["lat_a"])[ridx]
            qt_a, qt_l = sp("qtype")
            rc_a, rc_l = sp("rcode")
            cl_a, cl_l = sp("client")
            qn_a, qn_l = sp("qname")
            return (
                (cbase + o_lat, len(b"latency_us:")),
                (lat_a, lat_l),
                (cbase + o_qt, len(b"\tqtype:")),
                (qt_a, qt_l),
                (cbase + o_rc, len(b"\trcode:")),
                (rc_a, rc_l),
                (cbase + o_host, len(b"\t" + extra_blob + b"host:")),
                (cl_a, cl_l),
                (cbase + o_time, len(b"\ttime:")),
                (cbase + o_scratch + ts_off, ts_len),
                (cbase + o_msg, len(b"\tmessage:")),
                (qn_a, qn_l),
                (cbase + o_sfx, len(suffix)),
            )

    return _assemble_fixed(chunk_bytes, s, Cols(), display_f64, suffix,
                           syslen, merger, encoder)
