"""Host-side batch packing: framed lines → dense [N, max_len] batches.

The arena replaces the reference's per-line ``Vec<u8>`` channel payloads
(mod.rs:461-468): lines live in one contiguous chunk described by
offset/length vectors; the dense pack is a native threaded memcpy
(flowgger_tpu/native.py) with a vectorized numpy fallback.  Shapes are
bucketed to powers of two to bound XLA recompilations.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

_MIN_ROWS = 256
_MIN_BYTES = 1 << 14

# thread-sliced pack (``input.pack_threads``): the dense pack is a pure
# bytes→ndarray scatter with no cross-row state, so rows slice evenly
# across threads.  1 = single Python-side slice (the native memcpy tier
# keeps its own internal default); >1 overrides the native thread count
# AND slices the numpy fallback, which otherwise runs single-threaded.
_PACK_THREADS = 1


def configure_pack_threads(n: int) -> None:
    global _PACK_THREADS
    _PACK_THREADS = max(1, int(n))


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _split_np(chunk: bytes, strip_cr: bool = True, sep: int = 10
              ) -> Tuple[np.ndarray, np.ndarray, int, bytes]:
    """Numpy separator scan: (starts, lens, n, carry) —
    BufRead::lines semantics for ``sep=\\n`` (one trailing CR stripped),
    BufRead::split semantics for other separators (nul framing)."""
    buf = np.frombuffer(chunk, dtype=np.uint8)
    nl = np.flatnonzero(buf == sep).astype(np.int32)
    n = int(nl.size)
    if n == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int32), 0, chunk
    starts = np.concatenate([np.zeros(1, np.int32), nl[:-1] + 1])
    ends = nl.copy()
    if strip_cr:
        has_cr = (ends > starts) & (buf[np.maximum(ends - 1, 0)] == 13)
        ends = ends - has_cr.astype(np.int32)
    return starts, ends - starts, n, chunk[int(nl[-1]) + 1:]


def _split(chunk: bytes, strip_cr: bool = True, sep: int = 10):
    from .. import native

    if sep == 10:
        res = native.split_chunk_native(chunk, strip_cr)
        if res is not None:
            return res
    return _split_np(chunk, strip_cr, sep)


def _pack_dense(chunk: bytes, starts: np.ndarray, lens: np.ndarray,
                max_len: int, np_rows: int) -> Tuple[np.ndarray, np.ndarray]:
    """(batch [np_rows, max_len] u8, clipped lens [np_rows]) — native
    threaded memcpy or the (optionally thread-sliced) numpy
    clip/mask/gather fallback."""
    from .. import native

    nt = _PACK_THREADS
    packed = native.pack_chunk_native(chunk, starts, lens, max_len, np_rows,
                                      n_threads=nt if nt > 1 else None)
    if packed is not None:
        return packed
    n = len(starts)
    buf = np.frombuffer(chunk, dtype=np.uint8)
    lens_c = np.minimum(lens, max_len)
    batch = np.zeros((np_rows, max_len), dtype=np.uint8)
    col = np.arange(max_len, dtype=np.int32)[None, :]

    def _fill(a: int, b: int) -> None:
        idx = starts[a:b, None] + col
        np.clip(idx, 0, max(buf.size - 1, 0), out=idx)
        mask = col < lens_c[a:b, None]
        np.multiply(buf[idx], mask, out=batch[a:b], casting="unsafe")

    if n:
        if nt > 1 and n >= 4 * nt:
            from concurrent.futures import ThreadPoolExecutor

            bounds = [(i * n // nt, (i + 1) * n // nt) for i in range(nt)]
            with ThreadPoolExecutor(max_workers=nt) as ex:
                list(ex.map(lambda ab: _fill(*ab), bounds))
        else:
            _fill(0, n)
    lens_p = np.zeros(np_rows, dtype=np.int32)
    lens_p[:n] = lens_c
    return batch, lens_p


def _finish(chunk: bytes, starts: np.ndarray, lens: np.ndarray, n: int,
            max_len: int):
    np_rows = max(_MIN_ROWS, _next_pow2(max(n, 1)))
    batch, lens_p = _pack_dense(chunk, starts, lens, max_len, np_rows)
    starts_p = np.zeros(np_rows, dtype=np.int32)
    starts_p[:n] = starts
    return batch, lens_p, chunk, starts_p, np.asarray(lens, dtype=np.int32), n


def pack_lines_2d(lines: List[bytes], max_len: int):
    """Pack a list of framed lines.  Returns
    (batch, clipped_lens, chunk, starts, orig_lens, n_real) with row
    count bucketed to a power of two."""
    n = len(lines)
    chunk = b"".join(lines)
    orig_lens = np.fromiter((len(ln) for ln in lines), dtype=np.int32, count=n)
    starts = np.zeros(n, dtype=np.int32)
    if n > 1:
        np.cumsum(orig_lens[:-1], out=starts[1:])
    return _finish(chunk, starts, orig_lens, n, max_len)


def pack_region_2d(region: bytes, max_len: int, sep: int = 10,
                   strip_cr: bool = True):
    """Pack a region of complete separator-terminated messages straight
    into a dense batch — the zero-per-line-Python fast path.  Same
    return contract as pack_lines_2d."""
    starts, lens, n, _carry = _split(region, strip_cr, sep)
    return _finish(region, starts, lens, n, max_len)


def pack_spans_2d(chunks: List[bytes], span_sets: List[Tuple[np.ndarray, np.ndarray]],
                  max_len: int):
    """Pack pre-framed spans (syslen framing: the scanner already knows
    every message's offset/length) from one or more chunk fragments.
    Same return contract as pack_lines_2d."""
    if len(chunks) == 1:
        chunk = chunks[0]
        starts, lens = span_sets[0]
    else:
        offs = np.cumsum([0] + [len(c) for c in chunks[:-1]])
        chunk = b"".join(chunks)
        starts = np.concatenate(
            [s + np.int32(o) for (s, _), o in zip(span_sets, offs)]) \
            if span_sets else np.zeros(0, np.int32)
        lens = np.concatenate([l for _, l in span_sets]) \
            if span_sets else np.zeros(0, np.int32)
    return _finish(chunk, np.asarray(starts, dtype=np.int32),
                   np.asarray(lens, dtype=np.int32), len(starts), max_len)


def subset_packed(packed, idx: np.ndarray):
    """Row-subset of a packed tuple (auto-detect partitioning): rows
    re-bucketed to a power of two so kernel shapes stay cached."""
    batch, lens, chunk, starts, orig_lens, _n = packed
    m = int(idx.size)
    rows = max(_MIN_ROWS, _next_pow2(max(m, 1)))
    b2 = np.zeros((rows, batch.shape[1]), dtype=np.uint8)
    l2 = np.zeros(rows, dtype=np.int32)
    s2 = np.zeros(rows, dtype=np.int32)
    if m:
        b2[:m] = batch[idx]
        l2[:m] = lens[idx]
        s2[:m] = starts[idx]
    return b2, l2, chunk, s2, np.asarray(orig_lens)[idx], m


# kept for callers that want raw framing metadata (tests, future C++ IO)
def split_chunk(chunk: bytes, strip_cr: bool = True):
    """(starts, lens, n, carry) over a raw chunk."""
    return _split(chunk, strip_cr)


def pack_lines(lines: List[bytes]) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Legacy 1-D layout: (padded chunk u8[B], starts, lens, n_real) for
    the on-device pack path (graft entry / CPU backend)."""
    n = len(lines)
    chunk = b"".join(lines)
    lens = np.fromiter((len(ln) for ln in lines), dtype=np.int32, count=n)
    starts = np.zeros(n, dtype=np.int32)
    if n > 1:
        np.cumsum(lens[:-1], out=starts[1:])
    np_rows = max(_MIN_ROWS, _next_pow2(n))
    nb = max(_MIN_BYTES, _next_pow2(len(chunk)))
    buf = np.zeros(nb, dtype=np.uint8)
    if chunk:
        buf[: len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
    starts_p = np.zeros(np_rows, dtype=np.int32)
    lens_p = np.zeros(np_rows, dtype=np.int32)
    starts_p[:n] = starts
    lens_p[:n] = lens
    return buf, starts_p, lens_p, n
