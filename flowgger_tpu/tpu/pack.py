"""Host-side batch packing: list-of-lines → (chunk, starts, lens) with
static padded shapes.

The arena replaces the reference's per-line ``Vec<u8>`` channel payloads
(mod.rs:461-468): lines are concatenated into one contiguous chunk and
described by offset/length vectors; the actual ``[N, L]`` gather happens
on device (tpu/rfc5424.py pack_on_device), so the host's per-line work is
one ``bytes.join``.  Shapes are bucketed to powers of two to bound XLA
recompilations.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

_MIN_ROWS = 256
_MIN_BYTES = 1 << 14


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def pack_lines(lines: List[bytes]) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Concatenate lines into a padded chunk.

    Returns (chunk uint8[B], starts int32[Np], lens int32[Np], n_real)
    where B and Np are bucketed; rows past n_real are zero-length padding.
    """
    n = len(lines)
    chunk = b"".join(lines)
    lens = np.fromiter((len(ln) for ln in lines), dtype=np.int32, count=n)
    starts = np.zeros(n, dtype=np.int32)
    if n > 1:
        np.cumsum(lens[:-1], out=starts[1:])
    np_rows = max(_MIN_ROWS, _next_pow2(n))
    nb = max(_MIN_BYTES, _next_pow2(len(chunk)))
    buf = np.zeros(nb, dtype=np.uint8)
    if chunk:
        buf[: len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
    starts_p = np.zeros(np_rows, dtype=np.int32)
    lens_p = np.zeros(np_rows, dtype=np.int32)
    starts_p[:n] = starts
    lens_p[:n] = lens
    return buf, starts_p, lens_p, n


def pack_lines_2d(lines: List[bytes], max_len: int
                  ) -> Tuple[np.ndarray, np.ndarray, bytes, np.ndarray, np.ndarray, int]:
    """Pack lines into a dense ``[N, max_len]`` uint8 batch on the host
    (vectorized numpy gather — XLA's device gather lowers near-serially
    on TPU, so the transpose-to-dense happens here).

    Returns (batch, clipped_lens, chunk, starts, orig_lens, n_real) with
    N bucketed to a power of two.
    """
    n = len(lines)
    chunk = b"".join(lines)
    orig_lens = np.fromiter((len(ln) for ln in lines), dtype=np.int32, count=n)
    starts = np.zeros(n, dtype=np.int32)
    if n > 1:
        np.cumsum(orig_lens[:-1], out=starts[1:])
    np_rows = max(_MIN_ROWS, _next_pow2(n))
    buf = np.frombuffer(chunk, dtype=np.uint8)
    lens_c = np.minimum(orig_lens, max_len)
    batch = np.zeros((np_rows, max_len), dtype=np.uint8)
    if n:
        idx = starts[:, None] + np.arange(max_len, dtype=np.int32)[None, :]
        np.clip(idx, 0, max(buf.size - 1, 0), out=idx)
        mask = np.arange(max_len, dtype=np.int32)[None, :] < lens_c[:, None]
        np.multiply(buf[idx], mask, out=batch[:n], casting="unsafe")
    starts_p = np.zeros(np_rows, dtype=np.int32)
    lens_p = np.zeros(np_rows, dtype=np.int32)
    starts_p[:n] = starts
    lens_p[:n] = lens_c
    return batch, lens_p, chunk, starts_p, orig_lens, n


def split_chunk(chunk: bytes, strip_cr: bool = True
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int, bytes]:
    """Newline-split a raw chunk columnar-ly (no per-line Python): returns
    (buf, starts, lens, n_real, carry) where carry is the trailing partial
    line to prepend to the next chunk — the batcher's version of the
    splitter's BufRead carry (SURVEY.md §5 long-context note)."""
    buf = np.frombuffer(chunk, dtype=np.uint8)
    nl = np.flatnonzero(buf == 10).astype(np.int32)
    if nl.size == 0:
        return buf, np.zeros(0, np.int32), np.zeros(0, np.int32), 0, chunk
    starts = np.concatenate([np.zeros(1, np.int32), nl[:-1] + 1])
    ends = nl.copy()
    if strip_cr:
        # drop one trailing \r per line (BufRead::lines semantics)
        has_cr = (ends > starts) & (buf[np.maximum(ends - 1, 0)] == 13)
        ends = ends - has_cr.astype(np.int32)
    lens = ends - starts
    carry = chunk[int(nl[-1]) + 1:]
    n = int(nl.size)
    np_rows = max(_MIN_ROWS, _next_pow2(n))
    nb = max(_MIN_BYTES, _next_pow2(buf.size))
    buf_p = np.zeros(nb, dtype=np.uint8)
    buf_p[: buf.size] = buf
    starts_p = np.zeros(np_rows, dtype=np.int32)
    lens_p = np.zeros(np_rows, dtype=np.int32)
    starts_p[:n] = starts
    lens_p[:n] = lens
    return buf_p, starts_p, lens_p, n, carry
