"""Host-side batch packing: framed lines → dense [N, max_len] batches.

The arena replaces the reference's per-line ``Vec<u8>`` channel payloads
(mod.rs:461-468): lines live in one contiguous chunk described by
offset/length vectors; the dense pack is a native threaded memcpy
(flowgger_tpu/native.py) with a vectorized numpy fallback.  Shapes are
bucketed to bound XLA recompilations: by default every power of two,
or — with ``input.tpu_shape_buckets`` configured — a small geometric
grid (``configure_shape_buckets``) so steady-state traffic hits a
handful of compiled shapes instead of one per pow2 (simdjson's lesson:
the parallel-decode win evaporates when per-input setup cost isn't
amortized; each fresh (rows, max_len) shape is a fresh XLA compile).
Padding rows have length 0 and fall outside ``n_real``, so bucket
choice never changes emitted bytes.  Every packed shape is recorded in
the ``distinct_compiled_shapes`` gauge — the number to watch when a
varied-length stream is compile-thrashing.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

import numpy as np

_MIN_ROWS = 256
_MIN_BYTES = 1 << 14

# row-bucket grid (sorted tuple) set by configure_shape_buckets; None =
# legacy every-power-of-two bucketing.  Module-wide like _PACK_THREADS:
# only an explicit config key touches it (BatchHandler guards), so a
# default-configured handler never resets another handler's grid.
_SHAPE_BUCKETS: Optional[Tuple[int, ...]] = None

# every (rows, max_len) shape this process has packed — the gauge that
# proves (or disproves) shape-bucket amortization
_shapes_seen: set = set()
_shapes_lock = threading.Lock()

# thread-sliced pack (``input.pack_threads``): the dense pack is a pure
# bytes→ndarray scatter with no cross-row state, so rows slice evenly
# across threads.  1 = single Python-side slice (the native memcpy tier
# keeps its own internal default); >1 overrides the native thread count
# AND slices the numpy fallback, which otherwise runs single-threaded.
_PACK_THREADS = 1


def configure_pack_threads(n: int) -> None:
    global _PACK_THREADS
    _PACK_THREADS = max(1, int(n))


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def shape_bucket_grid(n_buckets: int, cap_rows: int) -> Tuple[int, ...]:
    """A geometric grid of ``n_buckets`` row counts from ``_MIN_ROWS``
    up to (the next power of two covering) ``cap_rows``, each rounded up
    to a power of two and deduplicated — so a grid request can yield
    fewer, never more, distinct shapes."""
    top = _next_pow2(max(int(cap_rows), _MIN_ROWS))
    if n_buckets <= 1 or top <= _MIN_ROWS:
        return (top,)
    ratio = (top / _MIN_ROWS) ** (1.0 / (n_buckets - 1))
    vals = {top}
    for i in range(n_buckets):
        vals.add(min(top, _next_pow2(int(round(_MIN_ROWS * ratio ** i)))))
    return tuple(sorted(vals))


def configure_shape_buckets(grid) -> None:
    """Install the row-bucket grid (an iterable of row counts), or
    ``None`` to restore legacy every-power-of-two bucketing."""
    global _SHAPE_BUCKETS
    _SHAPE_BUCKETS = (tuple(sorted({int(g) for g in grid}))
                      if grid else None)


def active_bucket_grid() -> Optional[Tuple[int, ...]]:
    return _SHAPE_BUCKETS


def bucket_rows(n: int) -> int:
    """Padded row count for ``n`` real rows: the smallest grid bucket
    that fits, or (legacy / beyond the grid top) the next power of two.
    Rows above the top can happen — a flush dispatches *all* pending
    lines, which can exceed ``tpu_batch_size`` when a large region
    arrives at once — and must still pack rather than truncate."""
    n = max(int(n), 1)
    if _SHAPE_BUCKETS:
        for b in _SHAPE_BUCKETS:
            if b >= n:
                return b
    return max(_MIN_ROWS, _next_pow2(n))


def shapes_seen() -> set:
    """Copy of every (rows, max_len) shape packed so far (tests diff
    this around a stream to bound compile churn)."""
    with _shapes_lock:
        return set(_shapes_seen)


def _note_shape(rows: int, max_len: int) -> None:
    with _shapes_lock:
        _shapes_seen.add((rows, max_len))
        count = len(_shapes_seen)
    from ..utils.metrics import registry as _metrics

    _metrics.set_gauge("distinct_compiled_shapes", count)


def _split_np(chunk: bytes, strip_cr: bool = True, sep: int = 10
              ) -> Tuple[np.ndarray, np.ndarray, int, bytes]:
    """Numpy separator scan: (starts, lens, n, carry) —
    BufRead::lines semantics for ``sep=\\n`` (one trailing CR stripped),
    BufRead::split semantics for other separators (nul framing)."""
    buf = np.frombuffer(chunk, dtype=np.uint8)
    nl = np.flatnonzero(buf == sep).astype(np.int32)
    n = int(nl.size)
    if n == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int32), 0, chunk
    starts = np.concatenate([np.zeros(1, np.int32), nl[:-1] + 1])
    ends = nl.copy()
    if strip_cr:
        has_cr = (ends > starts) & (buf[np.maximum(ends - 1, 0)] == 13)
        ends = ends - has_cr.astype(np.int32)
    return starts, ends - starts, n, chunk[int(nl[-1]) + 1:]


def _split(chunk: bytes, strip_cr: bool = True, sep: int = 10):
    from .. import native

    if sep == 10:
        res = native.split_chunk_native(chunk, strip_cr)
        if res is not None:
            return res
    return _split_np(chunk, strip_cr, sep)


def _pack_dense(chunk: bytes, starts: np.ndarray, lens: np.ndarray,
                max_len: int, np_rows: int) -> Tuple[np.ndarray, np.ndarray]:
    """(batch [np_rows, max_len] u8, clipped lens [np_rows]) — native
    threaded memcpy or the (optionally thread-sliced) numpy
    clip/mask/gather fallback."""
    from .. import native

    nt = _PACK_THREADS
    packed = native.pack_chunk_native(chunk, starts, lens, max_len, np_rows,
                                      n_threads=nt if nt > 1 else None)
    if packed is not None:
        return packed
    n = len(starts)
    buf = np.frombuffer(chunk, dtype=np.uint8)
    lens_c = np.minimum(lens, max_len)
    batch = np.zeros((np_rows, max_len), dtype=np.uint8)
    col = np.arange(max_len, dtype=np.int32)[None, :]

    def _fill(a: int, b: int) -> None:
        idx = starts[a:b, None] + col
        np.clip(idx, 0, max(buf.size - 1, 0), out=idx)
        mask = col < lens_c[a:b, None]
        np.multiply(buf[idx], mask, out=batch[a:b], casting="unsafe")

    if n:
        if nt > 1 and n >= 4 * nt:
            from concurrent.futures import ThreadPoolExecutor

            bounds = [(i * n // nt, (i + 1) * n // nt) for i in range(nt)]
            with ThreadPoolExecutor(max_workers=nt) as ex:
                list(ex.map(lambda ab: _fill(*ab), bounds))
        else:
            _fill(0, n)
    lens_p = np.zeros(np_rows, dtype=np.int32)
    lens_p[:n] = lens_c
    return batch, lens_p


def _note_stage(name: str, seconds: float) -> None:
    """Host pack-stage walls, split so the device-framing tier's win
    is visible per component: ``pack_slice_seconds`` (separator scan /
    span assembly) + ``pack_copy_seconds`` (dense arena memcpy) sum to
    ``pack_stage_seconds`` — the host stage device framing deletes."""
    from ..utils.metrics import registry as _metrics

    _metrics.add_seconds(name, seconds)
    _metrics.add_seconds("pack_stage_seconds", seconds)


def _finish(chunk: bytes, starts: np.ndarray, lens: np.ndarray, n: int,
            max_len: int):
    import time as _time

    np_rows = bucket_rows(n)
    _note_shape(np_rows, max_len)
    t0 = _time.perf_counter()
    batch, lens_p = _pack_dense(chunk, starts, lens, max_len, np_rows)
    _note_stage("pack_copy_seconds", _time.perf_counter() - t0)
    starts_p = np.zeros(np_rows, dtype=np.int32)
    starts_p[:n] = starts
    return batch, lens_p, chunk, starts_p, np.asarray(lens, dtype=np.int32), n


def pack_lines_2d(lines: List[bytes], max_len: int):
    """Pack a list of framed lines.  Returns
    (batch, clipped_lens, chunk, starts, orig_lens, n_real) with row
    count bucketed to a power of two."""
    import time as _time

    t0 = _time.perf_counter()
    n = len(lines)
    chunk = b"".join(lines)
    orig_lens = np.fromiter((len(ln) for ln in lines), dtype=np.int32, count=n)
    starts = np.zeros(n, dtype=np.int32)
    if n > 1:
        np.cumsum(orig_lens[:-1], out=starts[1:])
    _note_stage("pack_slice_seconds", _time.perf_counter() - t0)
    return _finish(chunk, starts, orig_lens, n, max_len)


def pack_region_2d(region: bytes, max_len: int, sep: int = 10,
                   strip_cr: bool = True):
    """Pack a region of complete separator-terminated messages straight
    into a dense batch — the zero-per-line-Python fast path.  Same
    return contract as pack_lines_2d."""
    import time as _time

    t0 = _time.perf_counter()
    starts, lens, n, _carry = _split(region, strip_cr, sep)
    _note_stage("pack_slice_seconds", _time.perf_counter() - t0)
    return _finish(region, starts, lens, n, max_len)


def pack_spans_2d(chunks: List[bytes], span_sets: List[Tuple[np.ndarray, np.ndarray]],
                  max_len: int):
    """Pack pre-framed spans (syslen framing: the scanner already knows
    every message's offset/length) from one or more chunk fragments.
    Same return contract as pack_lines_2d."""
    import time as _time

    t0 = _time.perf_counter()
    if len(chunks) == 1:
        chunk = chunks[0]
        starts, lens = span_sets[0]
    else:
        offs = np.cumsum([0] + [len(c) for c in chunks[:-1]])
        chunk = b"".join(chunks)
        starts = np.concatenate(
            [s + np.int32(o) for (s, _), o in zip(span_sets, offs)]) \
            if span_sets else np.zeros(0, np.int32)
        lens = np.concatenate([l for _, l in span_sets]) \
            if span_sets else np.zeros(0, np.int32)
    _note_stage("pack_slice_seconds", _time.perf_counter() - t0)
    return _finish(chunk, np.asarray(starts, dtype=np.int32),
                   np.asarray(lens, dtype=np.int32), len(starts), max_len)


def subset_packed(packed, idx: np.ndarray):
    """Row-subset of a packed tuple (auto-detect partitioning): rows
    re-bucketed through the same grid so kernel shapes stay cached."""
    batch, lens, chunk, starts, orig_lens, _n = packed
    m = int(idx.size)
    rows = bucket_rows(m)
    _note_shape(rows, batch.shape[1])
    b2 = np.zeros((rows, batch.shape[1]), dtype=np.uint8)
    l2 = np.zeros(rows, dtype=np.int32)
    s2 = np.zeros(rows, dtype=np.int32)
    if m:
        b2[:m] = batch[idx]
        l2[:m] = lens[idx]
        s2[:m] = starts[idx]
    return b2, l2, chunk, s2, np.asarray(orig_lens)[idx], m


# kept for callers that want raw framing metadata (tests, future C++ IO)
def split_chunk(chunk: bytes, strip_cr: bool = True):
    """(starts, lens, n, carry) over a raw chunk."""
    return _split(chunk, strip_cr)


def pack_lines(lines: List[bytes]) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Legacy 1-D layout: (padded chunk u8[B], starts, lens, n_real) for
    the on-device pack path (graft entry / CPU backend)."""
    n = len(lines)
    chunk = b"".join(lines)
    lens = np.fromiter((len(ln) for ln in lines), dtype=np.int32, count=n)
    starts = np.zeros(n, dtype=np.int32)
    if n > 1:
        np.cumsum(lens[:-1], out=starts[1:])
    np_rows = max(_MIN_ROWS, _next_pow2(n))
    nb = max(_MIN_BYTES, _next_pow2(len(chunk)))
    buf = np.zeros(nb, dtype=np.uint8)
    if chunk:
        buf[: len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
    starts_p = np.zeros(np_rows, dtype=np.int32)
    lens_p = np.zeros(np_rows, dtype=np.int32)
    starts_p[:n] = starts
    lens_p[:n] = lens
    return buf, starts_p, lens_p, n
