r"""Columnar generic JSON-lines decoder.

Scalar spec: flowgger_tpu/decoders/jsonl.py.  Stage 1 is the shared
simdjson-style structural index (tpu/jsonidx.py — the same quote
parity / bit-packed backslash ladder / packed-ordinal extractors the
GELF screen rides), run in **nested** mode: a structural-character
depth channel turns top-level container values (``"k": {...}`` /
``"k": [...]``) into VT_OBJECT/VT_ARRAY spans whose contents may nest
up to ``NESTED_DEPTH`` further levels; deeper rows — and anything
structurally surprising — flag to the scalar oracle.

Stage 2 (host, materialize_jsonl.py) slices spans, json-parses only
the tokens that need it (escaped strings, numbers, nested containers),
and routes the timestamp/host/message/level specials.

Two-tier field budget like tpu/gelf.py: rows with more than
DEFAULT_MAX_FIELDS keys (up to RESCUE_MAX_FIELDS) re-dispatch through
a lazily-compiled wider kernel in ``decode_jsonl_fetch``.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp

from .jsonidx import structural_index
from .rfc5424 import (
    best_extract_impl,
    best_scan_impl,
    rescue_refetch,
)

DEFAULT_MAX_FIELDS = 8
RESCUE_MAX_FIELDS = 24
# containers below the top-level object may nest this many levels; the
# structural index bounds total bracket depth at 1 + NESTED_DEPTH
NESTED_DEPTH = 4


def decode_jsonl(batch: jnp.ndarray, lens: jnp.ndarray,
                 max_fields: int = DEFAULT_MAX_FIELDS,
                 scan_impl: str = None,
                 extract_impl: str = None) -> Dict[str, jnp.ndarray]:
    if scan_impl is None:
        scan_impl = best_scan_impl()
    if extract_impl is None:
        extract_impl = best_extract_impl()
    return structural_index(batch, lens, max_fields, scan_impl,
                            extract_impl, nested=NESTED_DEPTH)


def decode_jsonl_submit(batch, lens, sharded=None):
    """Asynchronous dispatch (pair with decode_jsonl_fetch) — the jsonl
    leg of the block pipeline's double buffering.  The handle carries
    the caller's host arrays so the tier-2 rescue never pays a
    full-batch D2H just to slice a few rescue rows."""
    import jax.numpy as jnp

    if sharded is not None:
        b, ln = sharded.put(batch, lens)
        return (sharded.fn(b, ln), b, ln, batch, lens)
    from .aot import decode_call

    b, ln = jnp.asarray(batch), jnp.asarray(lens)
    # zero-JIT boot: a loaded AOT artifact replaces the trace+compile
    out = decode_call("jsonl", (b, ln))
    if out is None:
        # Pallas tier: NFA string machine + structural index in one
        # VMEM pass; None (decline/cooldown/off) falls to the jnp jit
        from .pallas_kernels import decode_tier

        out = decode_tier("jsonl", b, ln)
    if out is None:
        out = decode_jsonl_jit(b, ln)
    return (out, b, ln, batch, lens)


_FIELD_KEYS = ("key_start", "key_end", "val_start", "val_end", "val_type",
               "key_esc", "val_esc")


def decode_jsonl_fetch(handle):
    """Block on a submitted decode; rows whose field count lies in
    (DEFAULT_MAX_FIELDS, RESCUE_MAX_FIELDS] re-dispatch through the
    wider tier-2 kernel so they stay on-device.  Field channels come
    back widened to RESCUE_MAX_FIELDS when tier 2 ran."""
    import numpy as np

    out, _b_dev, _ln_dev, batch, lens = handle
    host = {k: np.asarray(v) for k, v in out.items()}
    if host["key_start"].shape[1] >= RESCUE_MAX_FIELDS:
        return host
    nf = host["n_fields"]
    over = np.flatnonzero(~host["ok"] & (nf > DEFAULT_MAX_FIELDS)
                          & (nf <= RESCUE_MAX_FIELDS))

    def dispatch(sub_b, sub_l):
        out2 = decode_jsonl_jit(jnp.asarray(sub_b), jnp.asarray(sub_l),
                                max_fields=RESCUE_MAX_FIELDS)
        return {k: np.asarray(v) for k, v in out2.items()}

    return rescue_refetch(host, batch, lens, over, _FIELD_KEYS, dispatch,
                          RESCUE_MAX_FIELDS)


@functools.partial(jax.jit, static_argnames=("max_fields", "demand"))
def decode_jsonl_jit(batch, lens, max_fields=DEFAULT_MAX_FIELDS,
                     demand=None):
    """``demand`` (static frozenset): keep only the channels the
    consumer reads so XLA dead-code-eliminates the rest."""
    out = decode_jsonl(batch, lens, max_fields=max_fields)
    if demand is not None:
        out = {k: v for k, v in out.items() if k in demand}
    return out
