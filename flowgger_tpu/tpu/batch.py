"""BatchHandler: the TPU-path replacement for ScalarHandler.

Accumulates framed lines into a batch arena, ships the arena to the
device (pack + columnar decode in one jitted call), materializes Records,
encodes, and enqueues — preserving input order and the reference's
per-line error behavior (stderr + drop, line_splitter.rs:37-54).

Latency bound (SURVEY.md §7 hard-parts): the batch flushes when it
reaches ``input.tpu_batch_size`` lines (default 16384), when
``input.tpu_flush_ms`` (default 50) elapses with data pending, or at end
of stream — at most one batch-fill window of added latency vs the
scalar path.
"""

from __future__ import annotations

import sys
import threading
from typing import List, Optional

import numpy as np

from ..config import Config
from ..encoders import EncodeError
from ..splitters import Handler, ScalarHandler
from ..record import Record
from .. import tenancy as _tenancy
from ..obs import events as _events
from ..obs.trace import tracer as _tracer
from ..utils import faultinject as _faults
from ..utils.metrics import registry as _metrics

DEFAULT_BATCH_SIZE = 16384
DEFAULT_FLUSH_MS = 50
DEFAULT_MAX_LINE_LEN = 512


_NO_MERGER = object()  # sentinel: block mode only when the caller wires a merger


class BatchHandler(Handler):
    def __init__(self, tx, decoder, encoder, config: Optional[Config] = None,
                 fmt: str = "rfc5424", start_timer: bool = True,
                 merger=_NO_MERGER, supervisor=None):
        from . import apply_platform_env

        apply_platform_env()
        self.tx = tx
        self.encoder = encoder
        self.fmt = fmt
        # Block mode (one pre-framed EncodedBlock per batch) engages only
        # when the pipeline hands us its merger, so standalone handlers
        # keep the per-message queue contract.
        self._block_mode = merger is not _NO_MERGER
        self._merger = None if merger is _NO_MERGER else merger
        # scalar path for fallback rows and capnp handle_record
        self.scalar = ScalarHandler(tx, decoder, encoder)
        cfg = config or Config.from_string("")
        self._cfg = cfg
        # WAL spill tier (durability/manager.py): set by the pipeline
        # when [durability] is armed.  _guarded_dispatch diverts fresh
        # packed batches to disk instead of blocking on a full queue;
        # replay_spilled() re-enters them with sink-ack cursors.
        self.durability = None
        # device-decode circuit breaker: trips the whole handler onto the
        # scalar-oracle path on sustained device failure (None = disabled
        # via input.tpu_breaker = false, legacy fail-fast behavior)
        from .breaker import DecodeBreaker

        self._breaker = DecodeBreaker.from_config(cfg)
        self._auto_scalars: dict = {}  # per-class oracles for auto fallback
        self.batch_size = cfg.lookup_int(
            "input.tpu_batch_size", "input.tpu_batch_size must be an integer",
            DEFAULT_BATCH_SIZE)
        self.flush_ms = cfg.lookup_int(
            "input.tpu_flush_ms", "input.tpu_flush_ms must be an integer",
            DEFAULT_FLUSH_MS)
        self.max_len = cfg.lookup_int(
            "input.tpu_max_line_len", "input.tpu_max_line_len must be an integer",
            DEFAULT_MAX_LINE_LEN)
        pack_threads = cfg.lookup_int(
            "input.pack_threads",
            "input.pack_threads must be an integer (threads)", None)
        if pack_threads is not None:
            if pack_threads < 1:
                from ..config import ConfigError

                raise ConfigError("input.pack_threads must be >= 1")
            # only an explicit key touches the (module-wide) pack
            # setting, so a later default-configured handler can never
            # silently reset another handler's thread slicing
            from . import pack as _pack_mod

            _pack_mod.configure_pack_threads(pack_threads)
        self._lines: List[bytes] = []
        self._chunks: List[bytes] = []      # complete-line regions (fast path)
        self._chunk_lines = 0
        self._span_chunks: List[bytes] = []  # syslen regions + frame spans
        self._span_sets: List = []
        self._span_count = 0
        # online template mining (tenancy/templates.py): None unless
        # tenant.templates = "on" — the off path tracks nothing and the
        # only residue is `is None` checks
        from ..tenancy.templates import TemplateMinerSet

        self._miners = TemplateMinerSet.from_config(cfg)
        # per-ingest (tenant, line-count) runs, parallel to the pending
        # chunk/span/line arenas, so rows attribute to the tenant whose
        # connection delivered them (ingestion order is pack order) for
        # mining AND for the fair queue's lane choice on Record-route
        # emits; tracked while mining or while the ingest thread
        # carries a tenant tag (tenancy enabled)
        self._chunk_runs: List = []
        self._span_runs: List = []
        self._line_runs: List = []
        # template-ID enrichment rides the Record route (per-row JSON
        # fields don't fit the constant-segment block encoders), GELF
        # output only
        self._enrich_hook = None
        if self._miners is not None and self._miners.enrich:
            from ..encoders.gelf import GelfEncoder as _Gelf
            from ..tenancy.templates import make_gelf_enricher

            if type(encoder) is _Gelf:
                self._enrich_hook = make_gelf_enricher(self._miners)
                self.scalar.record_hook = self._enrich_hook
        # block routes with mined span channels pin the host encode path
        # (the miner consumes the fetched decode columns)
        self._mine_block = (self._miners is not None
                            and fmt in ("rfc5424", "rfc3164", "ltsv",
                                        "jsonl", "dns"))
        self._lock = threading.Lock()
        # serializes batch decodes so a timer flush racing a size flush
        # cannot reorder output
        self._decode_lock = threading.Lock()
        self._flush_t0 = 0.0
        self._timer: Optional[threading.Timer] = None
        self._start_timer = start_timer
        # per-handler hysteresis for the device-encode route (declines /
        # cooldown counters owned here, updated by device_gelf)
        self._device_route_state: dict = {}
        # multi-chip mesh: rows shard over dp, bytes over sp (SURVEY
        # §2.8 mapping).  "auto" engages whenever more than one real
        # device is visible; "on" also engages on the virtual CPU mesh
        # (tests); "off" disables.  Lane dispatch (below) supersedes the
        # mesh when it resolves to >1 lane — each chip then decodes its
        # own batches instead of a shard of every batch.
        self._mesh = None
        self._mesh_checked = False
        self._sharded: dict = {}
        self._mesh_mode = cfg.lookup_str(
            "input.tpu_mesh", "input.tpu_mesh must be a string", "auto")
        if self._mesh_mode not in ("auto", "on", "off"):
            from ..config import ConfigError

            raise ConfigError("input.tpu_mesh must be auto, on or off")
        self._mesh_sp = cfg.lookup_int(
            "input.tpu_sp", "input.tpu_sp must be an integer", 1)
        if self._mesh_sp < 1:
            from ..config import ConfigError

            raise ConfigError("input.tpu_sp must be >= 1")
        # fused decode→encode routes (tpu/fused_routes.py): "auto"
        # (default) runs the single-program fused tier whenever the
        # (in-format, out-format) route has a registered fused program,
        # declining to the split decode/encode path under the compile
        # watchdog; "off" pins the split path; "on" is "auto" plus a
        # startup notice when this config can never fuse
        self._fuse_mode = cfg.lookup_str(
            "input.tpu_fuse", "input.tpu_fuse must be a string", "auto")
        if self._fuse_mode not in ("auto", "on", "off"):
            from ..config import ConfigError

            raise ConfigError("input.tpu_fuse must be auto, on or off")
        # shape bucketing: pack row counts quantize to a small geometric
        # grid so steady-state traffic compiles a handful of shapes
        # (padding rows are masked — emitted bytes never change).  Like
        # pack_threads, only an explicit key touches the module-wide
        # grid so a default handler can't reset another's buckets.
        from . import pack as _pack_mod

        shape_buckets = cfg.lookup_int(
            "input.tpu_shape_buckets",
            "input.tpu_shape_buckets must be an integer (bucket count)",
            None)
        if shape_buckets is not None:
            if shape_buckets < 1:
                from ..config import ConfigError

                raise ConfigError("input.tpu_shape_buckets must be >= 1")
            _pack_mod.configure_shape_buckets(
                _pack_mod.shape_bucket_grid(shape_buckets, self.batch_size))
        # overlap executor: the block route submits batches into a set
        # of per-device lanes (tpu/overlap.py LaneSet) — default one
        # lane (the PR 4 in-flight window); with multiple real devices
        # (or an explicit input.tpu_lanes) batches round-robin across
        # lanes, each with its own fetcher thread, submit-ahead depth,
        # and route economics, while the LaneSet's FIFO sequencer keeps
        # blocks reaching the merger in strict batch order.  Every
        # synchronous-emit path fences ALL lanes first.
        from .overlap import (LaneSet, RouteEconomics,
                              inflight_depth_from_config, resolve_lanes)

        lanes, lane_devs = resolve_lanes(cfg, self._mesh_mode)
        if lanes > 1:
            # lanes own the devices; the sharded mesh would re-shard
            # each lane's batch across every chip and serialize them
            self._mesh_mode = "off"
        self._lane_devices = lane_devs
        self._econs = [
            RouteEconomics.from_config(
                cfg, label=f"lane{i}" if lanes > 1 else None)
            for i in range(lanes)
        ]
        self._window = LaneSet(
            inflight_depth_from_config(cfg), self._pop_emit, lanes=lanes,
            name=f"tpu-{fmt}", supervisor=supervisor)
        # zero-JIT boot (input.tpu_aot_dir): install — or, when the
        # pipeline already loaded it, revalidate against this handler's
        # max_len + bucket grid — the AOT artifact store before any
        # kernel dispatch.  Loaded programs replace trace+compile at
        # every call site below; the JIT + watchdog + persistent-cache
        # ladder stays the fallback for any miss/reject.
        from . import pack as _pack_aot
        from .aot import setup_aot

        setup_aot(cfg, max_len=self.max_len,
                  grid=_pack_aot.active_bucket_grid())
        # persistent compile cache (input.tpu_compile_cache_dir): wire
        # before any kernel dispatch so every compile below lands in it
        from .device_common import setup_compile_cache

        self._compile_cache_dir = setup_compile_cache(cfg)
        self._prewarm_cfg = cfg.lookup_bool(
            "input.tpu_prewarm", "input.tpu_prewarm must be a boolean",
            None)
        self._supervisor = supervisor
        # direct span->bytes encodes for rfc5424 routes
        from ..encoders.capnp import CapnpEncoder
        from ..encoders.gelf import GelfEncoder
        from ..encoders.ltsv import LTSVEncoder
        from ..encoders.passthrough import PassthroughEncoder
        from ..encoders.rfc3164 import RFC3164Encoder
        from ..encoders.rfc5424 import RFC5424Encoder

        passthrough_ok = (type(encoder) is PassthroughEncoder
                          and encoder.header_time_format is None)
        self._passthrough_ok = passthrough_ok
        self._fast_encode = (
            (fmt == "rfc5424"
             and (type(encoder) in (GelfEncoder, RFC5424Encoder,
                                    LTSVEncoder, CapnpEncoder)
                  or passthrough_ok))
            or (fmt in ("rfc3164", "ltsv", "gelf", "auto")
                and type(encoder) in (GelfEncoder, CapnpEncoder,
                                      LTSVEncoder, RFC5424Encoder))
            or (fmt in ("jsonl", "dns")
                and type(encoder) in (GelfEncoder, LTSVEncoder))
            or (fmt == "rfc3164"
                and (passthrough_ok
                     or type(encoder) is RFC3164Encoder)))
        # opt-in extra auto legs (input.auto_extra_formats): jsonl/dns
        # classes for the mixed-format dispatch; empty = classic table
        from .autodetect import auto_extra_formats

        self._auto_extras = (auto_extra_formats(cfg) if fmt == "auto"
                             else ())
        # single source of truth for kernel dispatch: fmt -> batch decoder
        auto_ltsv = self._auto_ltsv_decoder(cfg) if fmt == "auto" else None
        self._auto_ltsv = auto_ltsv
        self._kernel_fn = {
            "rfc5424": lambda lines: _decode_rfc5424_batch(lines, self.max_len),
            "ltsv": lambda lines: _decode_ltsv_batch(
                lines, self.max_len, self.scalar.decoder),
            "gelf": lambda lines: _decode_gelf_batch(lines, self.max_len),
            "rfc3164": lambda lines: _decode_rfc3164_batch(lines, self.max_len),
            "jsonl": lambda lines: _decode_jsonl_batch(lines, self.max_len),
            "dns": lambda lines: _decode_dns_batch(lines, self.max_len),
            "auto": lambda lines: _decode_auto_batch(
                lines, self.max_len, auto_ltsv, self._auto_extras),
        }.get(fmt)
        # the block route is config-static: if it can never engage, say
        # so once at startup — a *_tpu format that silently drops to the
        # per-record path is a ~30x throughput cliff the user should
        # see, not discover (VERDICT r3 weak #7)
        if self._block_mode:
            reason = self._route_cliff_reason()
            if reason:
                print(
                    f"flowgger-tpu: columnar block route disabled for "
                    f"format '{fmt}' ({reason}); throughput falls to the "
                    f"per-record path (~30x slower)", file=sys.stderr)
            elif self._fuse_mode == "on" and self._fused_route() is None:
                # the REAL runtime gate (_fused_route), not just
                # route_for: template mining and a mesh-owned format
                # also pin the split path, and "on" promises a notice
                # whenever this config can never fuse
                print(
                    'flowgger-tpu: input.tpu_fuse = "on" but this '
                    f"config cannot fuse format '{fmt}' (no registered "
                    "fused program for the route, template mining on, "
                    "or a sharded mesh owns the format); using the "
                    "split decode/encode path", file=sys.stderr)
        # device-resident framing (tpu/framing.py): "auto" lifts the
        # record-boundary scan + arena pack onto the accelerator
        # whenever the columnar block route is engaged on a non-CPU
        # backend (the mesh/lanes "auto" precedent; "on" also engages
        # on the CPU backend — tests/benches; "off" pins the host
        # splitters).  Raw transport chunks then reach this handler
        # through per-connection _RawSession objects instead of
        # pre-framed regions, and the splitter does zero scanning.
        from .framing import FramingEconomics

        self._framing_mode = cfg.lookup_str(
            "input.tpu_framing", "input.tpu_framing must be a string",
            "auto")
        if self._framing_mode not in ("auto", "on", "off"):
            from ..config import ConfigError

            raise ConfigError("input.tpu_framing must be auto, on or off")
        self._framing_econ = FramingEconomics.from_config(cfg)
        self._raw_sessions: List = []
        self._raw_est = 0
        framing_engaged = False
        if (self._framing_mode != "off" and self._block_mode
                and self.fmt != "auto" and self._kernel_fn is not None
                and self._block_route_ok()):
            if self._framing_mode == "on":
                framing_engaged = True
            else:
                import jax

                framing_engaged = jax.default_backend() != "cpu"
        if framing_engaged and self._sharded_for(self.fmt) is not None:
            # the sharded mesh owns this format's batches (it re-shards
            # host arrays across every chip); framing's lane-committed
            # device arrays would fight that placement
            framing_engaged = False
        self._framing_engaged = framing_engaged
        if (self._framing_mode == "on" and not framing_engaged
                and self._block_mode):
            print(
                'flowgger-tpu: input.tpu_framing = "on" but this config '
                f"cannot device-frame format '{fmt}' (the columnar "
                "block route is disabled, auto format, or a sharded "
                "mesh owns the format); using the host splitters",
                file=sys.stderr)
        # Pallas structural kernels (tpu/pallas_kernels.py): single-VMEM
        # framing→decode passes replacing the jnp scatter ladder and the
        # repeated [N,L] screen passes.  "auto" engages the compiled
        # kernels whenever the block route runs on a non-CPU backend;
        # "on" additionally engages interpret-mode kernels on the CPU
        # backend (tests/benches — interpret Pallas is *slower* than
        # jnp, so auto never picks it there); "off" pins the jnp tiers.
        # Declines ride the framing ladder shape (3 strikes → cooldown)
        # and fall back to the jnp tier — never dropping data.
        from . import pallas_kernels as _pallas_mod

        pallas_mode = cfg.lookup_str(
            "input.tpu_pallas", "input.tpu_pallas must be a string",
            "auto")
        if pallas_mode not in ("auto", "on", "off"):
            from ..config import ConfigError

            raise ConfigError("input.tpu_pallas must be auto, on or off")
        pallas_ok = (self._block_mode and self.fmt != "auto"
                     and self._kernel_fn is not None
                     and self._block_route_ok())
        if pallas_mode == "off" or not pallas_ok:
            _pallas_mod.set_mode("off")
            if pallas_mode == "on" and self._block_mode:
                print(
                    'flowgger-tpu: input.tpu_pallas = "on" but this '
                    f"config cannot run Pallas kernels for format "
                    f"'{fmt}' (the columnar block route is disabled or "
                    "auto format); using the jnp kernel tiers",
                    file=sys.stderr)
        else:
            import jax

            if jax.default_backend() == "cpu":
                _pallas_mod.set_mode(
                    "interpret" if pallas_mode == "on" else "off")
            else:
                _pallas_mod.set_mode("compiled")
        self._pallas_mode = pallas_mode
        # background kernel prewarm: compile the configured format's
        # decode (+ engaged device-encode) kernels for the shape-bucket
        # grid now, so the first real batch of each steady-state shape
        # never eats a cold compile or a watchdog decline.  Default: on
        # exactly when a persistent compile cache is configured (the
        # production signal); input.tpu_prewarm forces either way.
        # auto format skips (its per-class legs compile lazily per mix).
        prewarm = self._prewarm_cfg
        if prewarm is None:
            prewarm = self._compile_cache_dir is not None
        if (prewarm and self._block_mode and fmt != "auto"
                and self._kernel_fn is not None and self._block_route_ok()):
            from . import pack as _pack_mod
            from .device_common import prewarm_kernels

            grid = (_pack_mod.active_bucket_grid()
                    or (_pack_mod.bucket_rows(self.batch_size),))
            prewarm_kernels(
                fmt, self.max_len, grid, encoder=self.encoder,
                merger=self._merger,
                ltsv_decoder=(self.scalar.decoder if fmt == "ltsv"
                              else None),
                supervisor=supervisor,
                devices=[d for d in self._lane_devices if d is not None]
                or None,
                # warm the fused program only when dispatch can
                # actually use it — _fused_route() is the same gate
                # _emit_fast consults (fuse mode, template mining,
                # sharded mesh), so prewarm never background-compiles
                # a program that is never dispatched
                fused_route=self._fused_route())

    @property
    def _econ(self):
        """Lane-0 route economics (single-lane compatibility alias;
        multi-lane callers read ``_econs``)."""
        return self._econs[0]

    # -- Handler interface -------------------------------------------------
    def ingest_chunk(self, region: bytes) -> None:
        """Fast path fed by Line/NulSplitter: a region of *complete*
        separator-terminated messages straight off the wire — no
        per-message Python objects; native code does the framing at
        flush (the separator rides ``ingest_sep``, set by the splitter).
        """
        tag = _tenancy.current_name()
        with self._lock:
            self._chunks.append(region)
            n = region.count(self.ingest_sep)
            self._chunk_lines += n
            if self._miners is not None or tag is not None:
                self._chunk_runs.append(
                    (tag or _tenancy.DEFAULT_TENANT, n))
            full = self._pending_locked() >= self.batch_size
            if not full and self._timer is None and self._start_timer:
                self._timer = threading.Timer(self.flush_ms / 1000.0, self.flush)
                self._timer.daemon = True
                self._timer.start()
        if full:
            self.flush(drain=False)

    def ingest_spans(self, chunk: bytes, starts, lens) -> None:
        """Fast path fed by SyslenSplitter: a region plus pre-scanned
        frame offset/length arrays — zero per-message Python for the
        reference's ``framed=true`` mode."""
        tag = _tenancy.current_name()
        with self._lock:
            self._span_chunks.append(chunk)
            self._span_sets.append((starts, lens))
            self._span_count += len(starts)
            if self._miners is not None or tag is not None:
                self._span_runs.append(
                    (tag or _tenancy.DEFAULT_TENANT, len(starts)))
            full = self._pending_locked() >= self.batch_size
            if not full and self._timer is None and self._start_timer:
                self._timer = threading.Timer(self.flush_ms / 1000.0, self.flush)
                self._timer.daemon = True
                self._timer.start()
        if full:
            self.flush(drain=False)

    def wants_raw(self, framing: str) -> bool:
        """Device framing engaged for this framing: the splitter hands
        raw chunks via ``open_raw`` and does zero scanning."""
        return (self._framing_engaged
                and framing in ("line", "nul", "syslen"))

    def open_raw(self, framing: str):
        """One per-connection raw-framing session (the RegionBuffer):
        accumulates raw transport chunks and the carry-over tail for
        records split across chunk boundaries; framed at flush."""
        sess = _RawSession(self, framing)
        with self._lock:
            self._raw_sessions.append(sess)
        return sess

    def _pending_locked(self) -> int:
        return (self._chunk_lines + self._span_count + len(self._lines)
                + self._raw_est)

    def handle_bytes(self, raw: bytes) -> None:
        tag = _tenancy.current_name()
        with self._lock:
            self._lines.append(raw)
            if self._miners is not None or tag is not None:
                tenant = tag or _tenancy.DEFAULT_TENANT
                if self._line_runs and self._line_runs[-1][0] == tenant:
                    self._line_runs[-1][1] += 1
                else:
                    self._line_runs.append([tenant, 1])
            full = self._pending_locked() >= self.batch_size
            if not full and self._timer is None and self._start_timer:
                self._timer = threading.Timer(self.flush_ms / 1000.0, self.flush)
                self._timer.daemon = True
                self._timer.start()
        if full:
            self.flush(drain=False)

    def handle_record(self, record: Record) -> None:
        self._window.fence()  # keep queue order vs in-flight batches
        self.scalar.handle_record(record)

    def flush(self, drain: bool = True) -> None:
        """Decode pending input.  Block-route batches are *submitted*
        into the in-flight window (the fetcher thread fetches and emits
        them behind us, in order); ``drain=True`` (timer and
        end-of-stream flushes) additionally fences the window so every
        submitted batch has reached the queue before returning."""
        with self._lock:
            lines, self._lines = self._lines, []
            chunks, self._chunks = self._chunks, []
            self._chunk_lines = 0
            spans = (self._span_chunks, self._span_sets)
            self._span_chunks, self._span_sets = [], []
            self._span_count = 0
            chunk_runs, self._chunk_runs = self._chunk_runs, []
            span_runs, self._span_runs = self._span_runs, []
            line_runs, self._line_runs = self._line_runs, []
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        with self._decode_lock:
            import time as _time

            t0 = _time.perf_counter()
            # the e2e_batch_seconds anchor every batch dispatched from
            # this flush measures against (decode lock serializes
            # flushes, so an instance attribute is race-free)
            self._flush_t0 = t0
            n0 = _metrics.get("input_lines")
            if self._raw_sessions:
                # raw-framing sessions snapshot *inside* the decode
                # lock: region assembly chains each session's carry
                # across flushes, so snapshot order must equal
                # processing order no matter which thread flushes
                with self._lock:
                    raw = [(s, s.chunks) for s in self._raw_sessions
                           if s.chunks]
                    for s, _ch in raw:
                        s.chunks = []
                        s.nbytes = 0
                        self._raw_est -= s.est
                        s.est = 0
                for s, ch in raw:
                    self._decode_raw(s, ch)
                with self._lock:
                    carry_total = sum(len(s.carry)
                                      for s in self._raw_sessions)
                _metrics.set_gauge("framing_carry_bytes", carry_total)
            if chunks:
                self._decode_chunks(chunks, chunk_runs or None)
            if spans[0]:
                self._decode_spans(*spans, runs=span_runs or None)
            if lines:
                self._decode_batch(lines, line_runs or None)
            _metrics.add_seconds("dispatch_seconds",
                                 _time.perf_counter() - t0)
            if drain:
                self._window.fence()
            _metrics.inc("batches")
            _metrics.inc("batch_lines", _metrics.get("input_lines") - n0)
            _metrics.batch_seconds.observe(_time.perf_counter() - t0)

    def close(self) -> None:
        """Fence and stop the in-flight window's fetcher thread; the
        handler stays usable (a later submit respawns it).  Called at
        pipeline drain so long-lived processes don't accumulate idle
        fetcher threads across handler generations."""
        self._window.close()

    # -- WAL replay (durability/manager.py) --------------------------------
    def replay_spilled(self, limit: Optional[int] = None) -> int:
        """Re-enter spilled WAL records through the normal dispatch
        path: each record re-packs from its raw chunk + span vectors
        (byte-identical to the original pack) and rides an ack that
        advances the persisted replay cursor only once the sink flushed
        the bytes.  ``limit`` caps replayed records (None = drain the
        whole backlog).  Returns the number of lines replayed."""
        mgr = self.durability
        if mgr is None or not mgr.backlog():
            return 0
        from . import pack

        total_lines = 0
        replayed = 0
        while limit is None or replayed < limit:
            want = mgr.replay_batch if limit is None \
                else min(mgr.replay_batch, limit - replayed)
            recs = mgr.next_records(want)
            if not recs:
                break
            for rec in recs:
                if rec.fmt != self.fmt:
                    # config changed across the restart: the record
                    # still replays (bytes are bytes), but decode runs
                    # under this handler's format
                    print(f"durability: replaying a '{rec.fmt}' record "
                          f"through the '{self.fmt}' handler",
                          file=sys.stderr)
                with self._decode_lock:
                    packed = pack.pack_spans_2d(
                        [rec.body], [(rec.starts, rec.lens)],
                        self.max_len)
                    self._guarded_dispatch(
                        packed, runs=rec.runs,
                        ack=mgr.make_ack(rec.seq, rec.idx))
                _metrics.inc("replayed_lines", rec.n)
                total_lines += rec.n
                replayed += 1
            _events.emit(
                "durability", "spill_replay", route=self.fmt,
                cost=len(recs), cost_unit="records",
                msg=f"replayed {len(recs)} spilled record(s) "
                    f"({mgr.backlog()} pending)")
        # every replayed batch reaches the queue before we return, so
        # callers (boot replay, drain) can sequence against the sink
        self._window.fence()
        return total_lines

    # -- multi-chip mesh ---------------------------------------------------
    def _sharded_for(self, fmt: str):
        """Lazily build (and cache) the ShardedDecode for one format;
        None when the mesh doesn't engage (single device, cpu backend in
        "auto" mode, or tpu_mesh="off")."""
        if self._mesh_mode == "off":
            return None
        if fmt in ("jsonl", "dns"):
            # no mesh kernels for the new formats yet: lane dispatch is
            # their multi-chip story (each lane decodes its own batches)
            return None
        if fmt in self._sharded:
            return self._sharded[fmt]
        sharded = None
        try:
            import jax

            if not self._mesh_checked:
                self._mesh_checked = True
                if self.max_len % self._mesh_sp:
                    raise ValueError(
                        f"tpu_max_line_len {self.max_len} not divisible "
                        f"by tpu_sp={self._mesh_sp}")
                # Multi-host: decode is embarrassingly parallel over
                # records, so each host shards only its OWN ingest
                # stream across its OWN chips (dp within host).  A
                # global-device mesh would device_put host-local batches
                # with a global sharding — rows outside this host's
                # addressable shard would be dropped and fetches would
                # crash on non-addressable arrays.
                devs = (jax.local_devices() if jax.process_count() > 1
                        else jax.devices())
                engage = len(devs) > 1 and (
                    self._mesh_mode == "on"
                    or jax.default_backend() != "cpu")
                if engage:
                    from ..parallel.mesh import make_decode_mesh

                    self._mesh = make_decode_mesh(devs, sp=self._mesh_sp)
            if self._mesh is not None:
                from ..parallel.mesh import ShardedDecode
                from .rfc5424 import best_extract_impl

                kw = ({"extract_impl": best_extract_impl()}
                      if fmt == "rfc5424" else {})
                sharded = ShardedDecode(self._mesh, fmt, **kw)
                _metrics.inc("sharded_kernels")
        except ValueError as e:
            # e.g. device count not divisible by tpu_sp: surface once,
            # run single-device rather than dying mid-stream
            print(f"tpu_mesh disabled: {e}", file=sys.stderr)
            self._mesh_mode = "off"
            return None
        self._sharded[fmt] = sharded
        return sharded

    # -- batched decode ----------------------------------------------------
    @staticmethod
    def _auto_ltsv_decoder(config):
        from ..decoders.ltsv import LTSVDecoder

        return LTSVDecoder(config)

    def _decode_chunks(self, chunks: List[bytes], runs=None) -> None:
        from . import pack

        region = b"".join(chunks)
        sep = self.ingest_sep
        if self._kernel_fn is None or not self._device_allowed():
            # no columnar kernel, or the breaker is open: split once in
            # C speed and run the scalar oracle per line (after fencing
            # the window so older device batches keep their place)
            self._window.fence()
            self._scalar_region(region, sep)
            return
        import time as _time

        bid = _tracer.begin(self.fmt)
        tp0 = _time.perf_counter()
        packed = pack.pack_region_2d(
            region, self.max_len, sep=sep[0],
            strip_cr=self.ingest_strip_cr)
        if bid is not None:
            _tracer.span(bid, "pack", tp0, _time.perf_counter(),
                         rows=int(packed[5]), nbytes=len(region))
        self._guarded_dispatch(packed, runs, trace=bid)

    def _decode_spans(self, span_chunks, span_sets, runs=None) -> None:
        from . import pack

        if self._kernel_fn is None or not self._device_allowed():
            self._window.fence()
            for chunk, (starts, lens) in zip(span_chunks, span_sets):
                for s, ln in zip(starts.tolist(), lens.tolist()):
                    self._scalar_handle(chunk[s:s + ln])
            return
        import time as _time

        bid = _tracer.begin(self.fmt)
        tp0 = _time.perf_counter()
        packed = pack.pack_spans_2d(span_chunks, span_sets, self.max_len)
        if bid is not None:
            _tracer.span(bid, "pack", tp0, _time.perf_counter(),
                         rows=int(packed[5]))
        self._guarded_dispatch(packed, runs, trace=bid)

    # -- device-resident framing (raw sessions) ----------------------------
    def _decode_raw(self, sess, chunks) -> None:
        """Frame one session's pending raw bytes: device framing when
        the tier is engaged/healthy/economical, else the host splitter
        logic applied at flush — same records, same order, either way.
        The carry-over tail (a record split across chunk or flush
        boundaries) stays in the session."""
        region = sess.carry + b"".join(chunks) if sess.carry \
            else b"".join(chunks)
        sess.carry = b""
        if not region or sess.dead:
            return
        runs_tag = None
        if self._miners is not None or sess.tag is not None:
            runs_tag = sess.tag or _tenancy.DEFAULT_TENANT
        from . import framing as _framing

        state = _framing.cooldown_state(self._device_route_state,
                                        sess.framing)
        breaker_open = not self._device_allowed()
        use_device = (not breaker_open
                      and not _framing.in_cooldown(state)
                      and self._framing_econ.allow_framing())
        if sess.framing == "syslen":
            self._decode_raw_syslen(sess, region, state, use_device,
                                    breaker_open, runs_tag)
        else:
            self._decode_raw_sep(sess, region, state, use_device,
                                 breaker_open, runs_tag)

    def _decode_raw_sep(self, sess, region, state, use_device,
                        breaker_open, runs_tag) -> None:
        import time as _time

        from . import framing as _framing

        sep = sess.sep
        cut = region.rfind(sep)
        if cut < 0:
            sess.carry = region
            return
        framed, sess.carry = region[:cut + 1], region[cut + 1:]
        n = framed.count(sep)
        runs = [(runs_tag, n)] if runs_tag is not None else None
        charge = getattr(sess, "charge", None)
        if charge is not None and n:
            # record-aligned admission for raw (device-framed) sessions:
            # charge the tenant exactly what the host splitter would
            # have — one all-or-nothing admit per framed region, counted
            # in records and bytes.  A denial sheds the framed region
            # (the carry tail stays; its bytes are charged when framed)
            if not charge.admit_region(n, len(framed)):
                return
        if breaker_open:
            # breaker-open scalar oracle, same bytes (fence first so
            # older device batches keep their place)
            self._window.fence()
            self._scalar_raw_lines(framed, sep, sess.framing == "line")
            return
        if use_device:
            lane = self._window.next_lane()
            bid = _tracer.begin(self.fmt)
            t0 = _time.perf_counter()
            try:
                _faults.maybe_raise("device_decode")
                packed, _consumed, _err = _framing.device_frame_region(
                    framed, sess.framing, self.max_len, n_records=n,
                    device=self._lane_devices[lane])
            except _framing.FramingDeclined:
                _framing.note_decline(state)
                _tracer.end(bid)
            except Exception as e:  # noqa: BLE001 - device degradation boundary
                _tracer.end(bid)
                if self._breaker is None:
                    raise
                self._device_failed(e)
            else:
                _framing.note_success(state)
                t1 = _time.perf_counter()
                if bid is not None:
                    _tracer.span(bid, "frame", t0, t1, rows=n,
                                 nbytes=len(framed), note="device")
                self._framing_econ.observe("framing", n, t1 - t0)
                self._guarded_dispatch(packed, runs, lane=lane,
                                       trace=bid)
                return
        from . import pack

        bid = _tracer.begin(self.fmt)
        t0 = _time.perf_counter()
        packed = pack.pack_region_2d(framed, self.max_len, sep=sep[0],
                                     strip_cr=sess.framing == "line")
        t1 = _time.perf_counter()
        if bid is not None:
            _tracer.span(bid, "pack", t0, t1, rows=n,
                         nbytes=len(framed), note="host-frame")
        self._framing_econ.observe("hostpack", n, t1 - t0)
        self._guarded_dispatch(packed, runs, trace=bid)

    def _decode_raw_syslen(self, sess, region, state, use_device,
                           breaker_open, runs_tag) -> None:
        import time as _time

        from ..splitters import _scan_syslen_region
        from . import framing as _framing

        if use_device and not breaker_open:
            lane = self._window.next_lane()
            bid = _tracer.begin(self.fmt)
            t0 = _time.perf_counter()
            try:
                _faults.maybe_raise("device_decode")
                packed, consumed, err = _framing.device_frame_region(
                    region, "syslen", self.max_len,
                    n_records=max(region.count(b" "), 1),
                    device=self._lane_devices[lane])
            except _framing.FramingDeclined:
                _framing.note_decline(state)
                _tracer.end(bid)
            except Exception as e:  # noqa: BLE001 - device degradation boundary
                _tracer.end(bid)
                if self._breaker is None:
                    raise
                self._device_failed(e)
            else:
                _framing.note_success(state)
                n = packed[5]
                charge = getattr(sess, "charge", None)
                if (n and charge is not None
                        and not charge.admit_region(
                            int(n), int(packed[4][:n].sum()))):
                    # record-aligned shed: the framed records drop as a
                    # unit (host-splitter admission parity); the carry
                    # tail stays with the session
                    _tracer.end(bid)
                    self._finish_raw_syslen(sess, region, consumed, err)
                    return
                if n:
                    t1 = _time.perf_counter()
                    if bid is not None:
                        _tracer.span(bid, "frame", t0, t1, rows=int(n),
                                     nbytes=len(region), note="device")
                    self._framing_econ.observe("framing", n, t1 - t0)
                    runs = ([(runs_tag, n)] if runs_tag is not None
                            else None)
                    self._guarded_dispatch(packed, runs, lane=lane,
                                           trace=bid)
                else:
                    _tracer.end(bid)
                self._finish_raw_syslen(sess, region, consumed, err)
                return
        t0 = _time.perf_counter()
        starts, lens, n, consumed, err = _scan_syslen_region(region)
        charge = getattr(sess, "charge", None)
        if charge is not None and n and not charge.admit_region(
                int(n), int(lens.sum())):
            # same record-aligned shed on the host-framed tier
            self._finish_raw_syslen(sess, region, consumed, err)
            return
        if breaker_open:
            self._window.fence()
            for s, ln in zip(starts.tolist(), lens.tolist()):
                self._scalar_handle(region[s:s + ln])
            self._finish_raw_syslen(sess, region, consumed, err)
            return
        if n:
            from . import pack

            bid = _tracer.begin(self.fmt)
            packed = pack.pack_spans_2d([region[:consumed]],
                                        [(starts, lens)], self.max_len)
            t1 = _time.perf_counter()
            if bid is not None:
                _tracer.span(bid, "pack", t0, t1, rows=int(n),
                             nbytes=consumed, note="host-frame")
            self._framing_econ.observe("hostpack", n, t1 - t0)
            runs = [(runs_tag, n)] if runs_tag is not None else None
            self._guarded_dispatch(packed, runs, trace=bid)
        self._finish_raw_syslen(sess, region, consumed, err)

    def _finish_raw_syslen(self, sess, region, consumed, err) -> None:
        sess.carry = region[consumed:]
        if err:
            # host-scan parity: a malformed length prefix ends the
            # connection (the session goes dead; the splitter's next
            # push sees it and closes the stream like the host path)
            print("Can't read message's length", file=sys.stderr)
            sess.dead = True
            sess.carry = b""

    def _scalar_raw_lines(self, framed: bytes, sep: bytes,
                          strip_cr: bool) -> None:
        lines = framed.split(sep)
        lines.pop()  # framed regions end with the separator
        for raw in lines:
            if strip_cr and raw.endswith(b"\r"):
                raw = raw[:-1]
            self._scalar_handle(raw)

    def _dispatch_packed(self, packed, deferred=None, runs=None,
                         lane=None, trace=None, ack=None) -> None:
        """Route one packed tuple through the right decode/encode tier.
        ``deferred`` (single-element list) is set True when the batch
        was submitted to the in-flight window instead of emitted
        synchronously.  ``trace`` is the flight-recorder batch ID
        (None when tracing is off).  ``ack`` is a durability replay
        acknowledgment (see _guarded_dispatch)."""
        if self._fast_encode:
            self._emit_fast(packed, deferred, runs, lane, trace, ack)
            return
        if self.fmt == "auto":
            from .autodetect import decode_auto_packed

            self._window.fence()
            self._emit(decode_auto_packed(packed, self.max_len,
                                          self._auto_ltsv,
                                          self._auto_extras), runs)
            if ack is not None:
                ack()
            return
        self._window.fence()
        self._emit(_decode_packed(self.fmt, packed, self.scalar.decoder),
                   runs)
        if ack is not None:
            ack()

    def _decode_batch(self, lines: List[bytes], runs=None) -> None:
        if self._kernel_fn is None or not self._device_allowed():
            # no columnar kernel (or breaker open): scalar per line
            self._window.fence()
            for raw in lines:
                self._scalar_handle(raw)
            return
        bid = None
        try:
            _faults.maybe_raise("device_decode")
            if self._fast_encode:
                import time as _time

                from . import pack

                bid = _tracer.begin(self.fmt)
                tp0 = _time.perf_counter()
                packed = pack.pack_lines_2d(lines, self.max_len)
                if bid is not None:
                    _tracer.span(bid, "pack", tp0, _time.perf_counter(),
                                 rows=int(packed[5]))
                deferred = [False]
                self._emit_fast(packed, deferred, runs, trace=bid)
                if not deferred[0]:
                    # emitted synchronously: close the trace here (a
                    # deferred batch closes it at its sequenced emit)
                    self._finish_batch(bid, self._flush_t0,
                                       rows=int(packed[5]))
            else:
                results = self._kernel_fn(lines)
                self._window.fence()
                self._emit(results, runs)
        except Exception as e:  # noqa: BLE001 - device degradation boundary
            _tracer.end(bid)
            if self._breaker is None:
                raise
            self._device_failed(e)
            self._window.fence()
            for raw in lines:
                self._scalar_handle(raw)
            return
        self._record_sync_success()

    # -- degradation / circuit breaker -------------------------------------
    def _device_allowed(self) -> bool:
        return self._breaker is None or self._breaker.allow()

    def _device_failed(self, e: BaseException) -> None:
        # flowcheck: disable=FC07 -- called both under the flush decode lock AND off-lock from the lane fetcher/sequencer threads; staging would need a drain hook on every caller for one emit per failed device batch on an already-cold decline path
        _events.emit(
            "batch", "device_error", route=self.fmt,
            detail=f"{type(e).__name__}: {e}",
            msg=f"device decode failed ({type(e).__name__}: {e}); "
                f"re-decoding the batch through the scalar oracle")
        self._breaker.record_failure(e)

    def _record_sync_success(self) -> None:
        """A device batch completed synchronously (no deferred fetch)."""
        if self._breaker is not None and self._window.pending() == 0:
            self._breaker.record_success()

    def _guarded_dispatch(self, packed, runs=None, lane=None,
                          trace=None, ack=None) -> None:
        """Route one packed tuple to the device tier, degrading to the
        scalar oracle (same bytes, no lines lost) on any device/XLA
        error when the breaker is armed.  ``lane`` pins the dispatch
        lane (device framing already committed the batch there);
        ``trace`` is the flight-recorder batch ID.  ``ack`` is the
        durability replay acknowledgment riding a replayed batch (never
        set on fresh ingest); it travels with the batch to the sink and
        fires once the bytes are flushed downstream."""
        if (ack is None and self.durability is not None
                and self.durability.should_spill()):
            # queue past the watermark: divert this fresh batch to the
            # on-disk WAL instead of blocking ingest on a full queue.
            # The pack keeps the raw chunk plus per-row start/length
            # vectors, so the spilled record reconstructs byte-exactly
            # at replay.  mode=require raises (DurabilityError) when
            # the spill tier itself cannot take the batch.
            _batch, _lens, chunk, starts, orig_lens, n_real = packed
            if n_real and self.durability.spill(
                    self.fmt, chunk, starts, orig_lens, int(n_real),
                    runs=runs):
                _tracer.end(trace)
                return
        deferred = [False]
        try:
            _faults.maybe_raise("device_decode")
            self._dispatch_packed(packed, deferred, runs, lane, trace,
                                  ack)
        except Exception as e:  # noqa: BLE001 - device degradation boundary
            _tracer.end(trace)
            if self._breaker is None:
                raise
            self._device_failed(e)
            # drain every lane before emitting this batch's scalar
            # re-decode, so mid-window failures keep batch order.  A
            # second ferried failure surfacing from the fence must not
            # leak past this boundary and drop the current batch: the
            # fence has fully drained by the time it re-raises, so
            # record the failure and continue to the fallback
            try:
                self._window.fence()
            except Exception as fe:  # noqa: BLE001 - device degradation boundary
                self._device_failed(fe)
            self._scalar_fallback_packed(packed)
            return
        if not deferred[0]:
            # completed synchronously; deferred batches are judged at
            # fetch time in _pop_emit instead — and this batch's
            # flush→emit wall is complete right here
            self._record_sync_success()
            self._finish_batch(trace, self._flush_t0, rows=int(packed[5]))

    def _scalar_handle(self, raw: bytes) -> None:
        """One line through the right scalar oracle, honoring the
        splitter flags set on this handler."""
        if self.fmt == "auto":
            handler = self._auto_scalar_for(raw)
        else:
            handler = self.scalar
        handler.quiet_empty = self.quiet_empty
        handler.bare_errors = self.bare_errors
        handler.handle_bytes(raw)

    def _auto_scalar_for(self, raw: bytes) -> ScalarHandler:
        """auto format: classify the line host-side (same decision table
        as the device kernel) and use that class's scalar oracle, so the
        degraded path stays byte-identical to the columnar one."""
        from .autodetect import (F_DNS, F_GELF, F_JSONL, F_LTSV,
                                 F_RFC3164, F_RFC5424, classify)

        cls = classify(raw, self._auto_extras)
        handler = self._auto_scalars.get(cls)
        if handler is None:
            if cls == F_RFC5424:
                decoder = self.scalar.decoder
            elif cls == F_LTSV:
                decoder = self._auto_ltsv or self._auto_ltsv_decoder(self._cfg)
            elif cls == F_GELF:
                from ..decoders import GelfDecoder

                decoder = GelfDecoder(self._cfg)
            elif cls == F_JSONL:
                from ..decoders import JSONLDecoder

                decoder = JSONLDecoder(self._cfg)
            elif cls == F_DNS:
                from ..decoders import DNSDecoder

                decoder = DNSDecoder(self._cfg)
            else:
                from ..decoders import RFC3164Decoder

                decoder = RFC3164Decoder(self._cfg)
            handler = ScalarHandler(self.tx, decoder, self.encoder)
            handler.record_hook = self.scalar.record_hook
            self._auto_scalars[cls] = handler
        return handler

    def _scalar_region(self, region: bytes, sep: bytes) -> None:
        lines = region.split(sep)
        lines.pop()  # regions end with the separator
        if self.ingest_strip_cr:
            lines = [ln[:-1] if ln.endswith(b"\r") else ln
                     for ln in lines]
        for raw in lines:
            self._scalar_handle(raw)

    def _scalar_fallback_packed(self, packed) -> None:
        """Re-decode one packed tuple's rows through the scalar oracle:
        the pack keeps the raw chunk plus per-row start/length vectors,
        so the original line bytes reconstruct exactly."""
        _batch, _lens, chunk, starts, orig_lens, n_real = packed
        for i in range(n_real):
            s = int(starts[i])
            self._scalar_handle(bytes(chunk[s:s + int(orig_lens[i])]))

    def _block_route_ok(self) -> bool:
        """Cheap applicability check, evaluated before any kernel work so
        an inapplicable route never pays a wasted device decode."""
        if not self._block_mode or self.fmt not in ("rfc5424", "rfc3164",
                                                     "ltsv", "gelf",
                                                     "jsonl", "dns",
                                                     "auto"):
            return False
        if self._enrich_hook is not None:
            # per-row _template_id fields don't fit the constant-segment
            # block encoders: enrichment rides the Record path
            return False
        from ..encoders.gelf import GelfEncoder
        from ..encoders.ltsv import LTSVEncoder
        from ..encoders.passthrough import PassthroughEncoder
        from ..encoders.rfc5424 import RFC5424Encoder
        from .block_common import merger_suffix

        if merger_suffix(self._merger) is None:
            return False
        from ..encoders.capnp import CapnpEncoder

        if (type(self.encoder) is CapnpEncoder
                and self.fmt in ("rfc5424", "rfc3164", "ltsv", "gelf")):
            # columnar capnp (the reference's default kafka output wire
            # format, mod.rs:104) from every kernel decoder; capnp_extra
            # is a constant blob on this route, so extras stay on the
            # fast tier here.  A typed ltsv_schema keeps the Record
            # path (per-value typing is per-row host work).
            if self.fmt == "ltsv":
                return not getattr(self.scalar.decoder, "schema", None)
            return True
        if self.fmt == "rfc3164":
            from ..encoders.rfc3164 import RFC3164Encoder

            if type(self.encoder) is RFC3164Encoder:
                # syslog->syslog relay re-encode; the prepend-timestamp
                # option is wall-clock-at-encode-time (per-call)
                return self.encoder.header_time_format is None
            if type(self.encoder) in (LTSVEncoder, RFC5424Encoder):
                return True
            if type(self.encoder) is GelfEncoder:
                from .encode_rfc3164_gelf_block import (
                    gelf_extra_consts_3164,
                )

                return gelf_extra_consts_3164(
                    self.encoder.extra) is not None
            return self._passthrough_ok
        if self.fmt == "ltsv":
            # LTSV decode block-encodes GELF, LTSV (self re-encode),
            # RFC5424, and capnp; typed-schema support (and its
            # per-row fallbacks) lives in the encoders
            if type(self.encoder) in (LTSVEncoder, RFC5424Encoder):
                return not getattr(self.scalar.decoder, "schema", None)
            if type(self.encoder) is not GelfEncoder:
                return False
            from .encode_ltsv_gelf_block import gelf_extra_consts_ltsv

            return gelf_extra_consts_ltsv(self.encoder.extra) is not None
        if self.fmt == "gelf":
            if type(self.encoder) in (LTSVEncoder, RFC5424Encoder):
                return True
            return (type(self.encoder) is GelfEncoder
                    and not self.encoder.extra)
        if self.fmt in ("jsonl", "dns"):
            # the new formats block-encode GELF and LTSV (the
            # high-volume production outputs); everything else keeps
            # the Record path
            if type(self.encoder) is LTSVEncoder:
                return True
            return (type(self.encoder) is GelfEncoder
                    and not self.encoder.extra)
        if self.fmt == "auto":
            # every classic class leg supports all four columnar
            # encoders (round 5); the opt-in jsonl/dns legs support
            # GELF/LTSV only; gelf_extra still needs static placement
            if type(self.encoder) is GelfEncoder and self.encoder.extra:
                return False
            enc_ok = (GelfEncoder, LTSVEncoder) if self._auto_extras \
                else (GelfEncoder, CapnpEncoder, LTSVEncoder,
                      RFC5424Encoder)
            return (type(self.encoder) in enc_ok
                    and not (self._auto_ltsv and self._auto_ltsv.schema))
        if type(self.encoder) is GelfEncoder:
            # extras with static placement ride the columnar route as
            # constant segments (encode_gelf_block.gelf_extra_slots)
            from .encode_gelf_block import gelf_extra_slots

            return gelf_extra_slots(self.encoder.extra) is not None
        if type(self.encoder) is PassthroughEncoder:
            return self._passthrough_ok
        return type(self.encoder) in (RFC5424Encoder, LTSVEncoder)

    def _route_cliff_reason(self) -> Optional[str]:
        """Why ``_block_route_ok`` can never be true for this config
        (None when the block route engages).  Config-static, evaluated
        once at construction for the startup warning.  Each branch names
        the key that ACTUALLY blocks this (fmt, encoder) pair — never a
        key whose removal would still leave the route disabled."""
        if self._block_route_ok():
            return None
        if self._enrich_hook is not None:
            return ("tenant.template_enrich is set (per-record "
                    "_template_id rides the Record path)")
        from ..encoders.gelf import GelfEncoder
        from ..encoders.passthrough import PassthroughEncoder
        from .block_common import merger_suffix

        if merger_suffix(self._merger) is None:
            return (f"output.framing {type(self._merger).__name__} has "
                    "no block merger")
        enc = self.encoder
        t = type(enc)
        no_columnar = (f"output.format {t.__name__} has no columnar "
                       f"encoder for input format '{self.fmt}'")
        from ..encoders.capnp import CapnpEncoder

        from ..encoders.ltsv import LTSVEncoder
        from ..encoders.rfc5424 import RFC5424Encoder

        if t in (CapnpEncoder, LTSVEncoder, RFC5424Encoder):
            if (self.fmt == "auto" and self._auto_extras
                    and t in (CapnpEncoder, RFC5424Encoder)):
                return ("input.auto_extra_formats is set (the jsonl/dns "
                        "legs block-encode GELF/LTSV only)")
            if self.fmt in ("ltsv", "auto"):
                # every class leg supports these encoders; the only
                # blocker left is the typed schema on the ltsv leg
                return "input.ltsv_schema is set"
            return no_columnar
        if t is GelfEncoder:
            # GELF output is columnar for every kernel format, so the
            # only possible blockers are the extras / the auto schema
            if enc.extra:
                if self.fmt in ("rfc5424", "rfc3164", "ltsv"):
                    return ("output.gelf_extra keys need dynamic "
                            "placement (leading '_' or a fixed-key "
                            "overwrite)")
                return "output.gelf_extra is set"
            if (self.fmt == "auto" and self._auto_ltsv
                    and self._auto_ltsv.schema):
                return "input.ltsv_schema is set"
            return no_columnar
        if t is PassthroughEncoder and self.fmt in ("rfc5424", "rfc3164"):
            return "output.syslog_prepend_timestamp is set"
        from ..encoders.rfc3164 import RFC3164Encoder

        if t is RFC3164Encoder and self.fmt == "rfc3164":
            return "output.syslog_prepend_timestamp is set"
        return no_columnar

    def _fused_route(self):
        """The registered fused decode→encode route for this handler's
        config, or None: fuse mode off, auto format (its per-class legs
        submit at fetch time), template mining on (the miner consumes
        host-fetched decode columns the fused tier never materializes),
        the sharded mesh owning the batch, or simply no fused program
        for this (format, encoder, merger)."""
        if (self._fuse_mode == "off" or self.fmt == "auto"
                or self._mine_block):
            return None
        if self._sharded_for(self.fmt) is not None:
            return None
        from . import fused_routes

        return fused_routes.route_for(
            self.fmt, self.encoder, self._merger,
            self.scalar.decoder if self.fmt == "ltsv" else None)

    def _emit_fast(self, packed, deferred=None, runs=None,
                   lane=None, trace=None, ack=None) -> None:
        """Span→bytes encode for one packed tuple: the columnar block
        route when engaged (submitted onto the next dispatch lane; that
        lane's fetcher thread fetches and encodes behind us, and the
        LaneSet sequencer emits in strict batch order), else the per-row
        fast path (gelf/passthrough only), else the Record path.
        ``lane`` (device framing) reuses an already-reserved lane whose
        device holds the batch; ``trace`` rides the window payload so
        the lane fetcher / sequencer stages land on the same batch
        trace."""
        if self._block_route_ok():
            import time as _time

            if deferred is not None:
                deferred[0] = True
            if lane is None:
                lane = self._window.next_lane()
            if len(self._lane_devices) > 1:
                _metrics.inc(f"lane{lane}_rows", int(packed[5]))
            ctx = (trace, self._flush_t0, ack)
            if self.fmt == "auto":
                # the auto merger submits its per-class kernels at fetch
                # time, on the lane's fetcher thread (default device:
                # the per-class legs share one jit cache)
                ts0 = _time.perf_counter()
                self._window.submit(lane, (None, packed, runs, ctx))
                if trace is not None:
                    _tracer.span(trace, "submit", ts0,
                                 _time.perf_counter())
                return
            route = self._fused_route()
            if route is not None:
                from . import fused_routes

                state = fused_routes.cooldown_state(
                    self._device_route_state, route)
                if state.get("cooldown", 0) > 0:
                    # fused tier cooling down after declines: stay on
                    # the split submit below for this batch
                    state["cooldown"] -= 1
                elif self._econs[lane % len(self._econs)].allow_fused():
                    # commit inputs to the lane device now; the fused
                    # program itself dispatches on the lane fetcher
                    # thread, where a compile-watchdog wait can never
                    # stall ingest
                    td0 = _time.perf_counter()
                    handle = fused_routes.submit(
                        route, packed, self._lane_devices[lane])
                    ts0 = _time.perf_counter()
                    if trace is not None:
                        _tracer.span(trace, "decode", td0, ts0,
                                     rows=int(packed[5]),
                                     note=f"fused:{route.name} commit")
                    self._window.submit(lane, (handle, packed, runs,
                                               ctx))
                    if trace is not None:
                        _tracer.span(trace, "submit", ts0,
                                     _time.perf_counter())
                    return
            td0 = _time.perf_counter()
            handle = block_submit(
                self.fmt, packed, self._sharded_for(self.fmt),
                self._lane_devices[lane])
            ts0 = _time.perf_counter()
            if trace is not None:
                _tracer.span(trace, "decode", td0, ts0,
                             rows=int(packed[5]), note="split dispatch")
            self._window.submit(lane, (handle, packed, runs, ctx))
            if trace is not None:
                _tracer.span(trace, "submit", ts0, _time.perf_counter())
            return
        from ..encoders.gelf import GelfEncoder
        from ..encoders.passthrough import PassthroughEncoder

        self._window.fence()
        if (self.fmt == "rfc5424" and self._enrich_hook is None
                and type(self.encoder) in (GelfEncoder,
                                           PassthroughEncoder)):
            # per-row span->bytes encode; with template enrichment on,
            # fall through to the Record path below so every row gets
            # its _template_id stamped before encode
            self._emit_encoded(
                _encode_packed_rfc5424_gelf(packed, self.encoder), runs)
            if ack is not None:
                # per-message route: rows were enqueued individually,
                # so the replay ack fires on enqueue (weaker than the
                # block route's sink-flush ack, still at-least-once)
                ack()
            return
        if self.fmt == "auto":
            from .autodetect import decode_auto_packed

            self._emit(decode_auto_packed(packed, self.max_len,
                                          self._auto_ltsv,
                                          self._auto_extras), runs)
            if ack is not None:
                ack()
            return
        self._emit(_decode_packed(self.fmt, packed, self.scalar.decoder),
                   runs)
        if ack is not None:
            ack()

    def _pop_emit(self, payload, lane: int = 0):
        """Fetch + encode one in-flight entry on a lane fetcher thread
        (concurrent across lanes); returns the emit closure the LaneSet
        sequencer runs in global submit order."""
        handle, packed, runs, ctx = payload
        bid, t_flush, ack = ctx
        import time as _time

        t0 = _time.perf_counter()
        stats: dict = {}
        econ = self._econs[lane % len(self._econs)]
        try:
            _faults.maybe_raise("device_decode")
            emit = self._pop_emit_inner(handle, packed, stats, econ,
                                        runs, bid, ack)
        except Exception as e:  # noqa: BLE001 - device degradation boundary
            if self._breaker is None:
                _tracer.end(bid)
                raise
            self._device_failed(e)

            # emitted under the sequencer turnstile: the scalar re-
            # decode still lands at the batch's position in the stream
            def fallback():
                self._scalar_fallback_packed(packed)
                self._finish_batch(bid, t_flush, rows=int(packed[5]))

            return fallback
        # measure the route's compute wall now — the sequencer wait
        # ahead of emission is cross-lane scheduling, not route cost
        compute_s = _time.perf_counter() - t0 - stats.get("declined_s", 0.0)
        path = stats.get("path")
        t_done = _time.perf_counter()

        def finish():
            t_emit0 = _time.perf_counter()
            if bid is not None:
                # the gap between compute finishing and the turnstile
                # opening is cross-lane scheduling: its own span
                _tracer.span(bid, "sequence", t_done, t_emit0)
            try:
                emit()
            except Exception as e:  # noqa: BLE001 - device degradation boundary
                # the emit closure is still inside the degradation
                # boundary (it ran inside _pop_emit_inner pre-lanes): a
                # failure here re-decodes the batch through the scalar
                # oracle at its sequenced position instead of ferrying
                # and losing the lines
                if self._breaker is None:
                    _tracer.end(bid)
                    raise
                self._device_failed(e)
                self._scalar_fallback_packed(packed)
                self._finish_batch(bid, t_flush, rows=int(packed[5]))
                return
            if bid is not None:
                _tracer.span(bid, "emit", t_emit0, _time.perf_counter(),
                             rows=int(packed[5]))
            if self._breaker is not None:
                self._breaker.record_success()
            if path is not None:
                # feed this lane's device-vs-host encode-route economics
                # (tpu/overlap.py) with the measured wall share; wall
                # burned by a declined device attempt (compile-watchdog
                # waits) is the device tier's fault, not the host
                # path's — already subtracted
                econ.observe(path, int(packed[5]), compute_s)
            self._finish_batch(bid, t_flush, rows=int(packed[5]))

        return finish

    def _finish_batch(self, bid, t_flush: float, rows: int = 0) -> None:
        """One batch fully emitted: observe the flush→emit wall
        (e2e_batch_seconds, plus the per-route family the SLO engine
        and regression sentinel key on), count the route's rows, and
        close its flight-recorder trace."""
        import time as _time

        if _faults.enabled() and _faults.fire("route_throttle"):
            # the sentinel drill: an injected per-batch delay collapses
            # this route's lines/s with no byte-level change —
            # obs/sentinel.py must surface it as perf_regression
            _time.sleep(0.05)
        e2e = (_time.perf_counter() - t_flush) if t_flush else None
        if e2e is not None:
            _metrics.observe("e2e_batch_seconds", e2e)
            _metrics.observe(f"e2e_batch_seconds_{self.fmt}", e2e)
        if rows:
            _metrics.inc(f"route_rows_{self.fmt}", int(rows))
        _tracer.end(bid, e2e)

    def _pop_emit_inner(self, handle, packed, stats=None, econ=None,
                        runs=None, bid=None, ack=None):
        """Fetch + encode one entry; returns a zero-arg emit closure
        (runs later, under the sequencer) so lanes can compute
        concurrently without reordering the merger stream.  ``bid``
        is the flight-recorder batch ID the lane-side spans (fetch/
        encode) land on.  ``ack`` (durability replay) rides the emitted
        block to the sink, or fires on enqueue for per-record emits."""
        import time as _time

        if econ is None:
            econ = self._econs[0]
        t0 = _time.perf_counter()
        if self.fmt == "auto":
            from .autodetect import decode_auto_packed, encode_auto_gelf_blocks

            res = encode_auto_gelf_blocks(packed, self.encoder,
                                          self._merger, self._auto_ltsv,
                                          self._device_route_state,
                                          self._sharded_for,
                                          self._auto_extras)
            if res is None:
                results = decode_auto_packed(packed, self.max_len,
                                             self._auto_ltsv,
                                             self._auto_extras)
                if bid is not None:
                    _tracer.span(bid, "encode", t0,
                                 _time.perf_counter(), note="auto-record")
                return lambda: (self._emit(results, runs),
                                ack() if ack is not None else None)
            # per-leg fetch time is folded into encode_seconds here: the
            # merger interleaves four kernels' fetches with their encodes
            t1 = _time.perf_counter()
            _metrics.add_seconds("encode_seconds", t1 - t0)
            if bid is not None:
                _tracer.span(bid, "encode", t0, t1, rows=int(packed[5]),
                             note="auto merged fetch+encode")
            return lambda: self._emit_block(res, packed[5], ack)
        ltsv_dec = self.scalar.decoder if self.fmt == "ltsv" else None
        from . import fused_routes as _fr

        fused_declined_s = 0.0
        if isinstance(handle, _fr.FusedHandle):
            tf0 = _time.perf_counter()
            fres, ffetch_s = _fr.fetch_encode(
                handle, packed, self.encoder, self._merger, ltsv_dec,
                self._device_route_state)
            if fres is not None:
                tf1 = _time.perf_counter()
                if stats is not None:
                    stats["path"] = "fused"
                    stats["declined_s"] = 0.0
                _metrics.add_seconds("device_fetch_seconds", ffetch_s)
                _metrics.add_seconds("encode_seconds",
                                     tf1 - tf0 - ffetch_s)
                if bid is not None:
                    _tracer.span(bid, "fetch", tf0, tf0 + ffetch_s,
                                 note="fused")
                    _tracer.span(bid, "encode", tf0 + ffetch_s, tf1,
                                 rows=int(packed[5]), note="fused")
                return lambda: self._emit_block(fres, packed[5], ack)
            # fused tier declined (compile pending, cooldown, or tier
            # fraction): fall back to the split path right here on the
            # lane fetcher thread — re-dispatch the split decode on the
            # same lane device and continue down the existing ladder.
            # The wall burned by the declined fused attempt is charged
            # to the decline metric, not to the split path's economics
            # sample (subtracted via stats["declined_s"] below).
            fused_declined_s = _time.perf_counter() - tf0
            _metrics.add_seconds("device_encode_declined_seconds",
                                 fused_declined_s)
            _metrics.inc("fused_fallbacks")
            _metrics.inc(f"fused_fallbacks_{handle.route.name}")
            _events.emit("batch", "fused_fallback",
                         route=handle.route.name,
                         cost=fused_declined_s, cost_unit="declined_s")
            handle = block_submit(self.fmt, packed, None, handle.device)
        mined: list = []
        column_tap = None
        if self._mine_block:
            # pure span extraction on this (concurrent) fetcher thread;
            # the observe itself runs inside the sequenced emit closure
            # below, so template IDs assign in batch order and stay
            # stable across runs and lane counts
            column_tap = lambda host_out: mined.append(
                self._miners.extract_block(self.fmt, packed, host_out))
        res, fetch_s, declined_s = block_fetch_encode(
            self.fmt, handle, packed, self.encoder, self._merger,
            ltsv_dec, self._device_route_state,
            # mining consumes the fetched decode columns: pin the host
            # block path while it is on (the device-encode tier elides
            # exactly the channels the miner reads)
            allow_device=econ.allow_device() and not self._mine_block,
            stats=stats, column_tap=column_tap)
        if stats is not None:
            stats["declined_s"] = declined_s + fused_declined_s
        if res is None:
            # the route declined after the fact (e.g. an oversized
            # ltsv_schema or a configured suffix): Record path
            results = _decode_packed(self.fmt, packed, self.scalar.decoder)
            if bid is not None:
                _tracer.span(bid, "encode", t0, _time.perf_counter(),
                             note="record-path")
            return lambda: (self._emit(results, runs),
                            ack() if ack is not None else None)
        t2 = _time.perf_counter()
        _metrics.add_seconds("device_fetch_seconds", fetch_s)
        _metrics.add_seconds("encode_seconds",
                             t2 - t0 - fetch_s - declined_s)
        if bid is not None:
            # fetch interleaves with encode inside the driver, so the
            # two spans split the measured wall at the fetch share
            _tracer.span(bid, "fetch", t0, t0 + fetch_s,
                         note=stats.get("path") if stats else None)
            _tracer.span(bid, "encode", t0 + fetch_s, t2,
                         rows=int(packed[5]),
                         note=stats.get("path") if stats else None)
        if mined and mined[0] is not None:
            def emit_mined():
                self._miners.observe_rows(mined[0], runs)
                self._emit_block(res, packed[5], ack)

            return emit_mined
        return lambda: self._emit_block(res, packed[5], ack)

    def _emit_block(self, res, n_real: int, ack=None) -> None:
        _metrics.inc("input_lines", n_real)
        if self._breaker is not None:
            self._breaker.observe_batch(n_real, res.fallback_rows)
        if res.fallback_rows:
            _metrics.inc("fallback_rows", res.fallback_rows)
        for error, line in res.errors:
            if error == "__utf8__":
                _metrics.inc("invalid_utf8")
                print("Invalid UTF-8 input", file=sys.stderr)
                continue
            _metrics.inc("decode_errors")
            if self.bare_errors:
                print(error, file=sys.stderr)
            else:
                stripped = line.strip()
                if not (self.quiet_empty and not stripped):
                    print(f"{error}: [{stripped}]", file=sys.stderr)
        count = len(res.block)
        if count:
            _metrics.inc("decoded_records", count)
            _metrics.inc("enqueued", count)
            if ack is not None:
                # the replay ack rides the block to the sink: it fires
                # in outputs.ack_item once the bytes are flushed
                # downstream, and only then does the WAL cursor advance
                res.block.ack_cb = ack
            self.tx.put(res.block)
        elif ack is not None:
            # every row decoded to an error (nothing reaches the sink):
            # the record is fully consumed, so acknowledge it now
            ack()

    def _emit_encoded(self, results, runs=None) -> None:
        """Emit pre-encoded bytes from the span->bytes fast path."""
        _metrics.inc("input_lines", len(results))
        expanded = self._expand_runs(runs, len(results))
        prev_tag = _tenancy.current_name() if expanded is not None else None
        try:
            self._emit_encoded_rows(results, expanded)
        finally:
            if expanded is not None:
                _tenancy.set_current(prev_tag)

    def _emit_encoded_rows(self, results, expanded) -> None:
        for i, res in enumerate(results):
            if res.encoded is None:
                if res.error == "__utf8__":
                    _metrics.inc("invalid_utf8")
                    print("Invalid UTF-8 input", file=sys.stderr)
                    continue
                _metrics.inc("decode_errors")
                if self.bare_errors:
                    print(res.error, file=sys.stderr)
                else:
                    stripped = res.line.strip()
                    if not (self.quiet_empty and not stripped):
                        print(f"{res.error}: [{stripped}]", file=sys.stderr)
                continue
            _metrics.inc("decoded_records")
            _metrics.inc("enqueued")
            if expanded is not None:
                _tenancy.set_current(expanded[i])
            self.tx.put(res.encoded)

    def _emit(self, results, runs=None) -> None:
        _metrics.inc("input_lines", len(results))
        # Per-row tenant attribution via the ingest-order runs when they
        # cover this batch (results are in row order, error rows
        # included): drives both mining/enrichment AND the fair queue's
        # lane choice, so a mixed-tenant Record-route batch never lands
        # wholesale on whichever tenant's thread happened to flush.  A
        # run mismatch falls back to the emitting thread's tag rather
        # than smearing rows across tenants non-deterministically.
        expanded = self._expand_runs(runs, len(results))
        default_tenant = None
        if self._miners is not None and expanded is None:
            default_tenant = _tenancy.current_or_default()
        prev_tag = _tenancy.current_name() if expanded is not None else None
        try:
            self._emit_rows(results, expanded, default_tenant)
        finally:
            if expanded is not None:
                _tenancy.set_current(prev_tag)

    @staticmethod
    def _expand_runs(runs, n_rows: int):
        if runs and sum(n for _, n in runs) == n_rows:
            return [t for t, n in runs for _ in range(n)]
        return None

    def _emit_rows(self, results, expanded, default_tenant) -> None:
        for i, res in enumerate(results):
            if res.record is None:
                if res.error == "__utf8__":
                    _metrics.inc("invalid_utf8")
                    print("Invalid UTF-8 input", file=sys.stderr)
                    continue
                _metrics.inc("decode_errors")
                if self.bare_errors:
                    print(res.error, file=sys.stderr)
                else:
                    stripped = res.line.strip()
                    if not (self.quiet_empty and not stripped):
                        print(f"{res.error}: [{stripped}]", file=sys.stderr)
                continue
            if self._miners is not None:
                tenant = expanded[i] if expanded is not None else default_tenant
                # with enrichment the hook both mines and stamps
                # _template_id pre-encode
                if self._enrich_hook is not None:
                    self._enrich_hook(res.record, tenant)
                else:
                    self._miners.observe_msg(tenant, res.record.msg or "")
            try:
                encoded = self.encoder.encode(res.record)
            except EncodeError as e:
                _metrics.inc("encode_errors")
                stripped = res.line.strip()
                if not (self.quiet_empty and not stripped):
                    print(f"{e}: [{stripped}]", file=sys.stderr)
                continue
            _metrics.inc("decoded_records")
            _metrics.inc("enqueued")
            if expanded is not None:
                # lane attribution for the fair queue: the put rides
                # the row's own tenant tag, not the flusher's
                _tenancy.set_current(expanded[i])
            self.tx.put(encoded)


# bound on a single session's buffered region (bytes) before a flush is
# forced regardless of the record estimate — keeps a no-separator flood
# (or a giant syslen body) from growing the RegionBuffer unboundedly
_RAW_REGION_CAP = 4 << 20


class _RawSession:
    """Per-connection RegionBuffer for device-resident framing.

    One splitter ``run`` (one connection/stream) owns one session: raw
    chunks accumulate here untouched, the handler frames them at flush
    (device kernel or host fallback), and the carry-over tail — a
    record split across a chunk or flush boundary — stays in the
    session between flushes.  ``tag`` pins the whole session to the
    connection's tenant (one stream = one tenant), so per-row run
    attribution is exact without per-chunk record counts.

    ``est`` is the pending-record estimate driving the batch-size
    flush trigger: exact for line/nul (one memchr-speed separator
    count per chunk), an upper bound for syslen (each frame consumes
    at least one space).
    """

    def __init__(self, handler, framing: str):
        self.handler = handler
        self.framing = framing
        self.sep = b"\0" if framing == "nul" else b"\n"
        self.carry = b""
        self.chunks: List[bytes] = []
        self.est = 0
        self.nbytes = 0
        self.dead = False
        self.tag = _tenancy.current_name()

    def push(self, chunk: bytes) -> bool:
        """Buffer one raw chunk; returns False when the session died
        (a mid-stream framing error — the splitter closes the stream
        like the host scan does)."""
        if self.dead:
            return False
        h = self.handler
        est = chunk.count(b" " if self.framing == "syslen" else self.sep)
        with h._lock:
            self.chunks.append(chunk)
            self.nbytes += len(chunk)
            self.est += est
            h._raw_est += est
            full = (h._pending_locked() >= h.batch_size
                    or self.nbytes + len(self.carry) >= _RAW_REGION_CAP)
            if not full and h._timer is None and h._start_timer:
                h._timer = threading.Timer(h.flush_ms / 1000.0, h.flush)
                h._timer.daemon = True
                h._timer.start()
        if full:
            h.flush(drain=False)
        return not self.dead

    def finish(self, idle: bool = False) -> None:
        """End of stream: flush pending data, then resolve the carry
        with the host splitters' exact EOF semantics — line/nul emit a
        trailing partial frame (BufRead::lines parity), syslen prints
        the host scan's short-read / bad-length message."""
        h = self.handler
        h.flush(drain=True)
        with h._lock:
            carry, self.carry = self.carry, b""
            if self in h._raw_sessions:
                h._raw_sessions.remove(self)
        if self.dead:
            return
        if self.framing == "syslen":
            from ..splitters import SyslenSplitter

            # stderr parity with SyslenSplitter._run_spans: a carry
            # mid-body is a short read; an idle timeout outside a body
            # (even with a partial length prefix buffered) closes
            # quietly; only a hard EOF on a non-body carry is a
            # bad-length error
            if carry and SyslenSplitter._mid_body(carry):
                print("failed to fill whole buffer", file=sys.stderr)
            elif idle:
                print(
                    "Client hasn't sent any data for a while - Closing "
                    "idle connection", file=sys.stderr)
            elif carry:
                print("Can't read message's length", file=sys.stderr)
            return
        if carry:
            if self.framing == "line" and carry.endswith(b"\r"):
                carry = carry[:-1]
            charge = getattr(self, "charge", None)
            if charge is not None and not charge.admit_region(
                    1, len(carry)):
                # EOF partial frame charges like the host splitter's
                # handle_bytes(raw): one record, its bytes
                return
            h.handle_bytes(carry)


def block_submit(fmt, packed, sharded=None, device=None):
    """Dispatch one packed tuple's kernel asynchronously (JAX futures);
    pair with block_fetch_encode.  ``sharded`` (parallel.mesh.
    ShardedDecode) swaps in the multi-chip mesh kernel.  ``device``
    (lane dispatch) commits the inputs to that device before the jit
    call, so the decode — and every downstream device-encode stage that
    reuses the handle's device arrays — runs on the lane's chip."""
    batch, lens = packed[0], packed[1]
    if device is not None and sharded is None:
        import jax

        # committed placement: the jit executes on the lane device and
        # jnp.asarray inside the submit fns is a no-op on these
        batch = jax.device_put(batch, device)
        lens = jax.device_put(lens, device)
    if fmt == "rfc3164":
        from . import rfc3164

        return rfc3164.decode_rfc3164_submit(batch, lens, sharded)
    if fmt == "ltsv":
        from . import ltsv

        return ltsv.decode_ltsv_submit(batch, lens, sharded)
    if fmt == "gelf":
        from . import gelf

        return gelf.decode_gelf_submit(batch, lens, sharded)
    if fmt == "jsonl":
        from . import jsonl

        return jsonl.decode_jsonl_submit(batch, lens, sharded)
    if fmt == "dns":
        from . import dns

        return dns.decode_dns_submit(batch, lens, sharded)
    from . import rfc5424

    return rfc5424.decode_rfc5424_submit(batch, lens, sharded=sharded)


def block_fetch_encode(fmt, handle, packed, encoder, merger,
                       ltsv_decoder=None, route_state=None,
                       allow_device=True, stats=None, column_tap=None):
    """Block on a submitted kernel and run the format's columnar block
    encoder; returns (BlockResult-or-None, fetch_seconds,
    declined_seconds) — the last is wall time burned by a declined
    device-encode attempt, so callers can keep stage metrics additive.

    ``allow_device=False`` skips the device-encode tier outright (the
    route economics measured the host block path as cheaper on this
    backend); ``stats`` (optional dict) gets ``stats["path"]`` set to
    ``"device"`` or ``"host"`` for whichever tier produced the block.
    ``column_tap`` (template mining) is called with the fetched decode
    channels on the host path — callers that set it pass
    ``allow_device=False`` so the channels are actually fetched; a tap
    failure is contained (counted + logged), never a lost batch."""
    import time as _time

    t0 = _time.perf_counter()
    declined_s = 0.0
    # decline/cooldown hysteresis is per format: in auto mode several
    # legs share the caller's dict, and one leg's success must not
    # reset another leg's decline count (nor double-decrement cooldowns)
    if route_state is not None:
        route_state = route_state.setdefault(fmt, {})
    if fmt == "rfc3164":
        from ..encoders.passthrough import PassthroughEncoder
        from ..encoders.rfc3164 import RFC3164Encoder
        from . import (
            device_rfc3164,
            encode_passthrough_block,
            encode_rfc3164_3164_block,
            encode_rfc3164_gelf_block,
            encode_rfc5424_block,
            rfc3164,
        )
        from ..encoders.rfc5424 import RFC5424Encoder

        if allow_device and device_rfc3164.route_ok(encoder, merger):
            res, fetch_s = device_rfc3164.fetch_encode(
                handle, packed, encoder, merger, route_state)
            if res is not None:
                if stats is not None:
                    stats["path"] = "device"
                return res, fetch_s, 0.0
            declined_s = _time.perf_counter() - t0
            _metrics.add_seconds("device_encode_declined_seconds",
                                 declined_s)
            t0 = _time.perf_counter()
        elif allow_device and type(encoder) is RFC5424Encoder:
            # PR 19: rfc3164→rfc5424 device leg (shared SD-assembly
            # core with the rfc5424→rfc5424 kernel)
            from . import device_rfc5424_out

            if device_rfc5424_out.route_ok(encoder, merger):
                res, fetch_s = device_rfc5424_out.fetch_encode_3164(
                    handle, packed, encoder, merger, route_state)
                if res is not None:
                    if stats is not None:
                        stats["path"] = "device"
                    return res, fetch_s, 0.0
                declined_s = _time.perf_counter() - t0
                _metrics.add_seconds("device_encode_declined_seconds",
                                     declined_s)
                t0 = _time.perf_counter()
        host_out = rfc3164.decode_rfc3164_fetch(handle)
        t1 = _time.perf_counter()
        _tap_columns(column_tap, host_out)
        from ..encoders.capnp import CapnpEncoder
        from ..encoders.ltsv import LTSVEncoder
        from . import encode_capnp_block, encode_ltsv_block

        fn3164 = {
            PassthroughEncoder:
                encode_passthrough_block.encode_rfc3164_passthrough_block,
            RFC3164Encoder:
                encode_rfc3164_3164_block.encode_rfc3164_3164_block,
            CapnpEncoder:
                encode_capnp_block.encode_rfc3164_capnp_block,
            LTSVEncoder:
                encode_ltsv_block.encode_rfc3164_ltsv_block,
            RFC5424Encoder:
                encode_rfc5424_block.encode_rfc3164_rfc5424_block,
        }.get(type(encoder),
              encode_rfc3164_gelf_block.encode_rfc3164_gelf_block)
        res = fn3164(
            packed[2], packed[3], packed[4], host_out, packed[5],
            packed[0].shape[1], encoder, merger)
    elif fmt == "ltsv":
        from . import device_ltsv, encode_ltsv_gelf_block, ltsv

        if allow_device and device_ltsv.route_ok(encoder, merger,
                                                 ltsv_decoder):
            res, fetch_s = device_ltsv.fetch_encode(
                handle, packed, encoder, merger, route_state,
                ltsv_decoder)
            if res is not None:
                if stats is not None:
                    stats["path"] = "device"
                return res, fetch_s, 0.0
            declined_s = _time.perf_counter() - t0
            _metrics.add_seconds("device_encode_declined_seconds",
                                 declined_s)
            t0 = _time.perf_counter()
        host_out = ltsv.decode_ltsv_fetch(handle)
        t1 = _time.perf_counter()
        _tap_columns(column_tap, host_out)
        from ..encoders.capnp import CapnpEncoder
        from ..encoders.ltsv import LTSVEncoder
        from ..encoders.rfc5424 import RFC5424Encoder

        if type(encoder) is CapnpEncoder:
            from . import encode_capnp_block

            res = encode_capnp_block.encode_ltsv_capnp_block(
                packed[2], packed[3], packed[4], host_out, packed[5],
                packed[0].shape[1], encoder, merger, ltsv_decoder)
        elif type(encoder) is LTSVEncoder:
            from . import encode_ltsv_block

            res = encode_ltsv_block.encode_ltsv_ltsv_block(
                packed[2], packed[3], packed[4], host_out, packed[5],
                packed[0].shape[1], encoder, merger, ltsv_decoder)
        elif type(encoder) is RFC5424Encoder:
            from . import encode_rfc5424_block

            res = encode_rfc5424_block.encode_ltsv_rfc5424_block(
                packed[2], packed[3], packed[4], host_out, packed[5],
                packed[0].shape[1], encoder, merger, ltsv_decoder)
        else:
            res = encode_ltsv_gelf_block.encode_ltsv_gelf_block(
                packed[2], packed[3], packed[4], host_out, packed[5],
                packed[0].shape[1], encoder, merger, ltsv_decoder)
    elif fmt == "jsonl":
        from ..encoders.ltsv import LTSVEncoder
        from . import encode_jsonl_block, jsonl

        # no device-encode tier for the new formats (yet): the host
        # block path is the fast tier, so the fetch is unconditional
        host_out = jsonl.decode_jsonl_fetch(handle)
        t1 = _time.perf_counter()
        _tap_columns(column_tap, host_out)
        if type(encoder) is LTSVEncoder:
            res = encode_jsonl_block.encode_jsonl_ltsv_block(
                packed[2], packed[3], packed[4], host_out, packed[5],
                packed[0].shape[1], encoder, merger)
        else:
            res = encode_jsonl_block.encode_jsonl_gelf_block(
                packed[2], packed[3], packed[4], host_out, packed[5],
                packed[0].shape[1], encoder, merger)
    elif fmt == "dns":
        from ..encoders.ltsv import LTSVEncoder
        from . import dns, encode_dns_block

        host_out = dns.decode_dns_fetch(handle)
        t1 = _time.perf_counter()
        _tap_columns(column_tap, host_out)
        if type(encoder) is LTSVEncoder:
            res = encode_dns_block.encode_dns_ltsv_block(
                packed[2], packed[3], packed[4], host_out, packed[5],
                packed[0].shape[1], encoder, merger)
        else:
            res = encode_dns_block.encode_dns_gelf_block(
                packed[2], packed[3], packed[4], host_out, packed[5],
                packed[0].shape[1], encoder, merger)
    elif fmt == "gelf":
        from ..encoders.ltsv import LTSVEncoder
        from ..encoders.rfc5424 import RFC5424Encoder
        from . import device_gelf_gelf, encode_gelf_gelf_block, gelf

        if allow_device and device_gelf_gelf.route_ok(encoder, merger):
            res, fetch_s = device_gelf_gelf.fetch_encode(
                handle, packed, encoder, merger, route_state)
            if res is not None:
                if stats is not None:
                    stats["path"] = "device"
                return res, fetch_s, 0.0
            declined_s = _time.perf_counter() - t0
            _metrics.add_seconds("device_encode_declined_seconds",
                                 declined_s)
            t0 = _time.perf_counter()
        host_out = gelf.decode_gelf_fetch(handle)
        t1 = _time.perf_counter()
        from ..encoders.capnp import CapnpEncoder

        if type(encoder) is LTSVEncoder:
            from . import encode_ltsv_block

            res = encode_ltsv_block.encode_gelf_ltsv_block(
                packed[2], packed[3], packed[4], host_out, packed[5],
                packed[0].shape[1], encoder, merger)
        elif type(encoder) is CapnpEncoder:
            from . import encode_capnp_block

            res = encode_capnp_block.encode_gelf_capnp_block(
                packed[2], packed[3], packed[4], host_out, packed[5],
                packed[0].shape[1], encoder, merger)
        elif type(encoder) is RFC5424Encoder:
            from . import encode_rfc5424_block

            res = encode_rfc5424_block.encode_gelf_rfc5424_block(
                packed[2], packed[3], packed[4], host_out, packed[5],
                packed[0].shape[1], encoder, merger)
        else:
            res = encode_gelf_gelf_block.encode_gelf_gelf_block(
                packed[2], packed[3], packed[4], host_out, packed[5],
                packed[0].shape[1], encoder, merger)
    else:
        from . import rfc5424

        # the rfc5424 device-encode tier is per output leg: GELF keeps
        # its original module; the PR 19 legs (rfc5424/ltsv/capnp out)
        # each bring their own kernel + route gate.  One module per
        # encoder type, so at most one device attempt per batch.
        dev_mod = _rfc5424_device_module(encoder)
        if (allow_device and dev_mod is not None
                and dev_mod.route_ok(encoder, merger)):
            res, fetch_s = dev_mod.fetch_encode(handle, packed,
                                                encoder, merger,
                                                route_state)
            if res is not None:
                if stats is not None:
                    stats["path"] = "device"
                return res, fetch_s, 0.0
            # charge the declined attempt to its own metric, not to the
            # host path's fetch or encode share
            declined_s = _time.perf_counter() - t0
            _metrics.add_seconds("device_encode_declined_seconds",
                                 declined_s)
            t0 = _time.perf_counter()
        host_out = rfc5424.decode_rfc5424_fetch(handle)
        t1 = _time.perf_counter()
        _tap_columns(column_tap, host_out)
        res = _encode_block_from_host(host_out, packed, encoder, merger)
    if stats is not None and res is not None:
        stats["path"] = "host"
    return res, t1 - t0, declined_s


def _rfc5424_device_module(encoder):
    """The split device-encode module for an rfc5424-input batch, keyed
    on the concrete output encoder type — None when no device kernel
    exists for this leg (host block path is the only tier)."""
    from ..encoders.capnp import CapnpEncoder
    from ..encoders.gelf import GelfEncoder
    from ..encoders.ltsv import LTSVEncoder
    from ..encoders.rfc5424 import RFC5424Encoder

    t = type(encoder)
    if t is GelfEncoder:
        from . import device_gelf

        return device_gelf
    if t is RFC5424Encoder:
        from . import device_rfc5424_out

        return device_rfc5424_out
    if t is LTSVEncoder:
        from . import device_ltsv_out

        return device_ltsv_out
    if t is CapnpEncoder:
        from . import device_capnp

        return device_capnp
    return None


def _tap_columns(column_tap, host_out) -> None:
    """Run the template-mining column tap over one fetched kernel
    output; mining is a statistics stage, so a tap failure is counted
    and logged but never costs the batch."""
    if column_tap is None:
        return
    try:
        column_tap(host_out)
    except Exception as e:  # noqa: BLE001 - stats stage, never lose the batch
        _metrics.inc("template_tap_errors")
        print(f"template column tap failed ({type(e).__name__}: {e}); "
              "batch not mined", file=sys.stderr)


def _encode_block_from_host(host_out, packed, encoder, merger):
    """Columnar block encode from fetched kernel channels, dispatched
    on the encoder type (caller pre-checked applicability)."""
    from ..encoders.capnp import CapnpEncoder
    from ..encoders.ltsv import LTSVEncoder
    from ..encoders.passthrough import PassthroughEncoder
    from ..encoders.rfc5424 import RFC5424Encoder
    from . import (
        encode_capnp_block,
        encode_gelf_block,
        encode_ltsv_block,
        encode_passthrough_block,
        encode_rfc5424_block,
    )

    batch, lens, chunk, starts, orig_lens, n_real = packed
    fn = {
        PassthroughEncoder:
            encode_passthrough_block.encode_rfc5424_passthrough_block,
        RFC5424Encoder: encode_rfc5424_block.encode_rfc5424_rfc5424_block,
        LTSVEncoder: encode_ltsv_block.encode_rfc5424_ltsv_block,
        CapnpEncoder: encode_capnp_block.encode_rfc5424_capnp_block,
    }.get(type(encoder), encode_gelf_block.encode_rfc5424_gelf_block)
    return fn(chunk, starts, orig_lens, host_out, n_real, batch.shape[1],
              encoder, merger)


def _encode_packed_rfc5424_gelf(packed, encoder):
    import jax.numpy as jnp

    from ..encoders.passthrough import PassthroughEncoder
    from . import encode_gelf, encode_passthrough, rfc5424

    batch, lens, chunk, starts, orig_lens, n_real = packed
    host_out = rfc5424.decode_rfc5424_host(batch, lens)
    if type(encoder) is PassthroughEncoder:
        return encode_passthrough.encode_rfc5424_passthrough(
            chunk, starts, orig_lens, host_out, n_real, batch.shape[1], encoder)
    return encode_gelf.encode_rfc5424_gelf(chunk, starts, orig_lens, host_out,
                                           n_real, batch.shape[1], encoder)


def _decode_packed(fmt, packed, decoder=None):
    """Run the columnar kernel + materializer for one packed tuple
    (batch, lens, chunk, starts, orig_lens, n_real)."""
    import jax.numpy as jnp

    batch, lens, chunk, starts, orig_lens, n_real = packed
    if fmt == "rfc5424":
        from . import materialize, rfc5424

        host_out = rfc5424.decode_rfc5424_host(batch, lens)
        return materialize.materialize(chunk, starts, lens, orig_lens, host_out,
                                       n_real, max_len=batch.shape[1])
    jb, jl = jnp.asarray(batch), jnp.asarray(lens)
    if fmt == "ltsv":
        from . import ltsv, materialize_ltsv

        out = ltsv.decode_ltsv_jit(jb, jl)
        host_out = {k: np.asarray(v) for k, v in out.items()}
        return materialize_ltsv.materialize_ltsv(chunk, starts, orig_lens, host_out,
                                                 n_real, batch.shape[1], decoder)
    if fmt == "gelf":
        from . import gelf, materialize_gelf

        host_out = gelf.decode_gelf_fetch(
            gelf.decode_gelf_submit(batch, lens))
        return materialize_gelf.materialize_gelf(chunk, starts, orig_lens, host_out,
                                                 n_real, batch.shape[1])
    if fmt == "jsonl":
        from . import jsonl, materialize_jsonl

        host_out = jsonl.decode_jsonl_fetch(
            jsonl.decode_jsonl_submit(batch, lens))
        return materialize_jsonl.materialize_jsonl(
            chunk, starts, orig_lens, host_out, n_real, batch.shape[1])
    if fmt == "dns":
        from . import dns, materialize_dns

        host_out = dns.decode_dns_fetch(dns.decode_dns_submit(batch, lens))
        return materialize_dns.materialize_dns(
            chunk, starts, orig_lens, host_out, n_real, batch.shape[1])
    if fmt == "rfc3164":
        from ..utils.timeparse import current_year_utc
        from . import materialize_rfc3164, rfc3164

        out = rfc3164.decode_rfc3164_jit(jb, jl, np.int32(current_year_utc()))
        host_out = {k: np.asarray(v) for k, v in out.items()}
        return materialize_rfc3164.materialize_rfc3164(
            chunk, starts, orig_lens, host_out, n_real, batch.shape[1])
    raise ValueError(f"no kernel for format {fmt}")


def _decode_gelf_batch(lines, max_len):
    from . import pack

    return _decode_packed("gelf", pack.pack_lines_2d(lines, max_len))


def _decode_jsonl_batch(lines, max_len):
    from . import pack

    return _decode_packed("jsonl", pack.pack_lines_2d(lines, max_len))


def _decode_dns_batch(lines, max_len):
    from . import pack

    return _decode_packed("dns", pack.pack_lines_2d(lines, max_len))


def _decode_auto_batch(lines, max_len, ltsv_decoder=None, extras=()):
    from .autodetect import decode_auto_batch

    return decode_auto_batch(lines, max_len, ltsv_decoder, extras)


def _decode_ltsv_batch(lines, max_len, decoder):
    from . import pack

    return _decode_packed("ltsv", pack.pack_lines_2d(lines, max_len), decoder)


def _decode_rfc5424_batch(lines, max_len):
    from . import pack

    return _decode_packed("rfc5424", pack.pack_lines_2d(lines, max_len))


def _decode_rfc3164_batch(lines, max_len):
    from . import pack

    return _decode_packed("rfc3164", pack.pack_lines_2d(lines, max_len))

