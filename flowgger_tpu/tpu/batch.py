"""BatchHandler: the TPU-path replacement for ScalarHandler.

Accumulates framed lines into a batch arena, ships the arena to the
device (pack + columnar decode in one jitted call), materializes Records,
encodes, and enqueues — preserving input order and the reference's
per-line error behavior (stderr + drop, line_splitter.rs:37-54).

Latency bound (SURVEY.md §7 hard-parts): the batch flushes when it
reaches ``input.tpu_batch_size`` lines (default 16384), when
``input.tpu_flush_ms`` (default 50) elapses with data pending, or at end
of stream — at most one batch-fill window of added latency vs the
scalar path.
"""

from __future__ import annotations

import sys
import threading
from typing import List, Optional

import numpy as np

from ..config import Config
from ..encoders import EncodeError
from ..splitters import Handler, ScalarHandler
from ..record import Record

DEFAULT_BATCH_SIZE = 16384
DEFAULT_FLUSH_MS = 50
DEFAULT_MAX_LINE_LEN = 512


class BatchHandler(Handler):
    def __init__(self, tx, decoder, encoder, config: Optional[Config] = None,
                 fmt: str = "rfc5424", start_timer: bool = True):
        self.tx = tx
        self.encoder = encoder
        self.fmt = fmt
        # scalar path for fallback rows and capnp handle_record
        self.scalar = ScalarHandler(tx, decoder, encoder)
        cfg = config or Config.from_string("")
        self.batch_size = cfg.lookup_int(
            "input.tpu_batch_size", "input.tpu_batch_size must be an integer",
            DEFAULT_BATCH_SIZE)
        self.flush_ms = cfg.lookup_int(
            "input.tpu_flush_ms", "input.tpu_flush_ms must be an integer",
            DEFAULT_FLUSH_MS)
        self.max_len = cfg.lookup_int(
            "input.tpu_max_line_len", "input.tpu_max_line_len must be an integer",
            DEFAULT_MAX_LINE_LEN)
        self._lines: List[bytes] = []
        self._lock = threading.Lock()
        # serializes batch decodes so a timer flush racing a size flush
        # cannot reorder output
        self._decode_lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self._start_timer = start_timer
        # single source of truth for kernel dispatch: fmt -> batch decoder
        auto_ltsv = self._auto_ltsv_decoder(cfg) if fmt == "auto" else None
        self._kernel_fn = {
            "rfc5424": lambda lines: _decode_rfc5424_batch(lines, self.max_len),
            "ltsv": lambda lines: _decode_ltsv_batch(
                lines, self.max_len, self.scalar.decoder),
            "gelf": lambda lines: _decode_gelf_batch(lines, self.max_len),
            "auto": lambda lines: _decode_auto_batch(
                lines, self.max_len, auto_ltsv),
        }.get(fmt)

    # -- Handler interface -------------------------------------------------
    def handle_bytes(self, raw: bytes) -> None:
        with self._lock:
            self._lines.append(raw)
            full = len(self._lines) >= self.batch_size
            if not full and self._timer is None and self._start_timer:
                self._timer = threading.Timer(self.flush_ms / 1000.0, self.flush)
                self._timer.daemon = True
                self._timer.start()
        if full:
            self.flush()

    def handle_record(self, record: Record) -> None:
        self.scalar.handle_record(record)

    def flush(self) -> None:
        with self._lock:
            lines, self._lines = self._lines, []
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        if lines:
            with self._decode_lock:
                self._decode_batch(lines)

    # -- batched decode ----------------------------------------------------
    @staticmethod
    def _auto_ltsv_decoder(config):
        from ..decoders.ltsv import LTSVDecoder

        return LTSVDecoder(config)

    def _decode_batch(self, lines: List[bytes]) -> None:
        if self._kernel_fn is None:
            # formats without a columnar kernel yet: scalar per line
            for raw in lines:
                self.scalar.handle_bytes(raw)
            return
        results = self._kernel_fn(lines)
        for res in results:
            if res.record is None:
                if res.error == "__utf8__":
                    print("Invalid UTF-8 input", file=sys.stderr)
                elif self.bare_errors:
                    print(res.error, file=sys.stderr)
                else:
                    stripped = res.line.strip()
                    if not (self.quiet_empty and not stripped):
                        print(f"{res.error}: [{stripped}]", file=sys.stderr)
                continue
            try:
                encoded = self.encoder.encode(res.record)
            except EncodeError as e:
                stripped = res.line.strip()
                if not (self.quiet_empty and not stripped):
                    print(f"{e}: [{stripped}]", file=sys.stderr)
                continue
            self.tx.put(encoded)


def _decode_gelf_batch(lines, max_len):
    import jax.numpy as jnp

    from . import gelf, materialize_gelf, pack

    batch, lens, chunk, starts, orig_lens, n_real = pack.pack_lines_2d(lines, max_len)
    out = gelf.decode_gelf_jit(jnp.asarray(batch), jnp.asarray(lens))
    host_out = {k: np.asarray(v) for k, v in out.items()}
    return materialize_gelf.materialize_gelf(chunk, starts, orig_lens, host_out,
                                             n_real, max_len)


def _decode_auto_batch(lines, max_len, ltsv_decoder=None):
    from .autodetect import decode_auto_batch

    return decode_auto_batch(lines, max_len, ltsv_decoder)


def _decode_ltsv_batch(lines, max_len, decoder):
    import jax.numpy as jnp

    from . import ltsv, materialize_ltsv, pack

    batch, lens, chunk, starts, orig_lens, n_real = pack.pack_lines_2d(lines, max_len)
    out = ltsv.decode_ltsv_jit(jnp.asarray(batch), jnp.asarray(lens))
    host_out = {k: np.asarray(v) for k, v in out.items()}
    return materialize_ltsv.materialize_ltsv(chunk, starts, orig_lens, host_out,
                                             n_real, max_len, decoder)


def _decode_rfc5424_batch(lines, max_len):
    import jax.numpy as jnp

    from . import materialize, pack, rfc5424

    batch, lens, chunk, starts, orig_lens, n_real = pack.pack_lines_2d(lines, max_len)
    out = rfc5424.decode_rfc5424_jit(jnp.asarray(batch), jnp.asarray(lens))
    host_out = {k: np.asarray(v) for k, v in out.items()}
    return materialize.materialize(chunk, starts, lens, orig_lens, host_out,
                                   n_real, max_len)

