"""Device-side GELF→GELF re-canonicalization: framed canonical GELF
bytes assembled on-device from the JSON tokenizer's span channels
(device_common machinery — same contract as the other device tiers).

Layout mirrors the host tier (encode_gelf_gelf_block.py) byte-for-byte::

    {"_<key>":V..., ["full_message":"F",] "host":H|unknown,
     ["level":D,] "short_message":"S"|"-", "timestamp":T,
     "version":"1.1"}

Unlike the other formats this tier is **escape-free**: string spans
re-emit verbatim, so rows with escape flags, control bytes, or
non-ASCII fall back (serde escaping of clean text is identity) and the
assembly source is the raw row — no escape stage at all.

Special keys route by *elementwise quoted-name pattern matches* over
packed 4-byte words (``"timestamp"`` including both quotes — the
closing quote pins the key length, so prefix collisions are
impossible), extracted per field as 3-bit ids in packed point-sum
words.  Pair keys sort by their final name (leading ``_`` stripped —
the emitted name always carries exactly one) through the shared
Batcher sorter with the span payload riding the swaps.

The timestamp re-formats like the host tier (json_f64 of the parsed
span): the kernel carries an exact split-integer parse (ts_hi/ts_lo ×
1e9 + frac scale, correctly rounded within 2**53 — same scheme as the
ltsv device tier) back through the phase-1 probe dict, and the driver
uploads the formatted text.

Off-tier (host span tier / scalar oracle, bytes identical either way):
escaped keys/values, non-canonical numbers, floats as pair values,
17+-digit timestamps, duplicate final names or ambiguous 8-byte sort
prefixes, repeated specials, >F fields (the wide hook re-decodes at 16
fields first; 17+ keeps the host rescue path), gelf_extra configured
(dynamic keys cannot place statically — route-gated).

Reference parity: gelf_decoder.rs:34-125 (decode semantics),
gelf_encoder.rs:51-116 (sorted-key canonical emit).
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.gelf:GelfEncoder"
DIFF_TEST = "tests/test_device_gelf_gelf.py::test_device_gelf_gelf_matches_scalar_and_engages"

from functools import partial

import jax
import jax.numpy as jnp

from .device_common import (
    TS_W,
    _out_width,
    assemble_rows,
    build_bank,
    fetch_encode_driver,
    sort_pairs_by_key8,
)
from .gelf import VT_FALSE, VT_NULL, VT_NUMBER, VT_STRING, VT_TRUE
from .rfc5424 import _shift_left

_I32 = jnp.int32

FALLBACK_FRAC = 0.05
DECLINE_LIMIT = 3
COOLDOWN = 16

_TSW = 24   # host-tier bound: longer timestamp spans take the oracle
_SPECIALS = (b"timestamp", b"host", b"short_message", b"full_message",
             b"version", b"level")
_SP_TS, _SP_HOST, _SP_SHORT, _SP_FULL, _SP_VER, _SP_LVL = range(1, 7)

_PARTS = {
    "open": b"{",
    "kpre": b'"_',
    "q": b'"',
    "colon": b'":',
    "qc": b'",',
    "true": b"true",
    "false": b"false",
    "null": b"null",
    "full": b'"full_message":"',
    "host": b'"host":"',
    "lvl": b'"level":',
    "short": b'"short_message":"',
    "ts": b'"timestamp":',
    "unknown": b"unknown",
    "dash": b"-",
    "comma": b",",
    "tail": b'"version":"1.1"}',
}


@partial(jax.jit, static_argnames=("suffix", "assemble", "elide"))
def _encode_kernel(batch, lens, dec, ts_text, ts_len, *, suffix: bytes,
                   assemble: bool = True, elide: bool = False):
    N, L = batch.shape
    bank, off = build_bank(dict(_PARTS), suffix)
    F = dec["key_start"].shape[1]
    OW = _out_width(L, L + len(bank) + TS_W)
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)
    bb = jnp.where(iota < lens.astype(_I32)[:, None], batch,
                   jnp.uint8(0)).astype(_I32)
    lens32 = lens.astype(_I32)
    valid = iota < lens32[:, None]

    ok = dec["ok"].astype(bool)
    nf = jnp.minimum(dec["n_fields"].astype(_I32), F)
    key_s = dec["key_start"].astype(_I32)
    key_e = dec["key_end"].astype(_I32)
    val_s = dec["val_start"].astype(_I32)
    val_e = dec["val_end"].astype(_I32)
    val_t = dec["val_type"].astype(_I32)
    key_esc = dec["key_esc"].astype(bool)
    val_esc = dec["val_esc"].astype(bool)
    frange = jnp.arange(F, dtype=_I32)
    jm = (frange[None, :] < nf[:, None]) & ok[:, None]

    # escape-free tier: any control byte or non-ASCII in the row → host
    # (encode_gelf_gelf_block.py's bad_cum screen, exactly)
    viol_row = jnp.any(((bb >= 128) | (bb < 32)) & valid, axis=1)

    # ---- quoted-name pattern planes -------------------------------------
    # w4 planes carry bytes p..p+3 big-endian; tier rows are pure ASCII
    # 0x20..0x7f so the packed words are always positive
    w2 = (bb << 8) | _shift_left(bb, 1, 0)
    w4 = (w2 << 16) | _shift_left(w2, 2, 0)
    planes = (w4, _shift_left(w4, 4, 0), _shift_left(w4, 8, 0),
              _shift_left(w4, 12, 0))

    def pat(name: bytes):
        s = b'"' + name + b'"'
        m = None
        for blk in range(0, len(s), 4):
            piece = s[blk:blk + 4]
            pl = planes[blk // 4]
            if len(piece) == 4:
                c = pl == int.from_bytes(piece, "big")
            else:
                sh = (4 - len(piece)) * 8
                c = (pl >> sh) == int.from_bytes(piece, "big")
            m = c if m is None else (m & c)
        return m

    sp = jnp.zeros((N, L), dtype=_I32)
    for sid, name in enumerate(_SPECIALS, start=1):
        sp = jnp.where(pat(name), sid, sp)

    # spid per field: the plane's value at the key's open quote, packed
    # 3-bit × 10 fields per point-sum word
    kopen = key_s - 1
    spid = []
    per = 10
    for base in range(0, F, per):
        acc = jnp.zeros((N, L), dtype=_I32)
        for s_ in range(min(per, F - base)):
            acc = acc + jnp.where(iota == kopen[:, base + s_][:, None],
                                  sp << (3 * s_), 0)
        word = jnp.sum(acc, axis=1)
        for s_ in range(min(per, F - base)):
            spid.append((word >> (3 * s_)) & 7)

    # ---- per-field point bytes + span counts ----------------------------
    # five bytes per field (key first byte; value bytes 0,1,2,last) and
    # three counts per field (dots / non-digits / frac chars in the
    # value span), all packed 3 per i32 word
    is_dot = bb == ord(".")
    is_nondig = ((bb < ord("0")) | (bb > ord("9"))) & valid
    is_fracc = is_dot | (bb == ord("e")) | (bb == ord("E"))

    def point_bytes(pos_cols):
        """bytes at per-field positions: list of [N] byte values."""
        outs = []
        for base in range(0, len(pos_cols), 3):
            grp = pos_cols[base:base + 3]
            acc = jnp.zeros((N, L), dtype=_I32)
            for s_, pos in enumerate(grp):
                acc = acc + jnp.where(iota == pos[:, None], bb << (8 * s_),
                                      0)
            word = jnp.sum(acc, axis=1)
            for s_ in range(len(grp)):
                outs.append((word >> (8 * s_)) & 255)
        return outs

    def span_counts(mask, a_cols, b_cols):
        """count of mask inside [a, b) per field, packed 3/word."""
        outs = []
        for base in range(0, len(a_cols), 3):
            acc = jnp.zeros((N, L), dtype=_I32)
            for s_ in range(min(3, len(a_cols) - base)):
                a = a_cols[base + s_]
                b = b_cols[base + s_]
                inside = mask & (iota >= a[:, None]) & (iota < b[:, None])
                acc = acc + (inside.astype(_I32) << (10 * s_))
            word = jnp.sum(acc, axis=1)
            for s_ in range(min(3, len(a_cols) - base)):
                outs.append((word >> (10 * s_)) & 1023)
        return outs

    va = [val_s[:, f] for f in range(F)]
    vb = [val_e[:, f] for f in range(F)]
    kfirst = point_bytes([key_s[:, f] for f in range(F)])
    v0 = point_bytes(va)
    v1 = point_bytes([x + 1 for x in va])
    v2 = point_bytes([x + 2 for x in va])
    vlast = point_bytes([x - 1 for x in vb])
    dots = span_counts(is_dot, va, vb)
    nondig = span_counts(is_nondig, va, vb)
    fracc = span_counts(is_fracc, va, vb)

    def canonical_number(f):
        r"""JSON grammar ``-?(0|[1-9][0-9]*)(\.[0-9]+)?`` (the host
        tier's canonical_number, field-wise)."""
        ln = vb[f] - va[f]
        neg = (v0[f] == ord("-")).astype(_I32)
        dfirst = jnp.where(neg == 1, v1[f], v0[f])
        dsecond = jnp.where(neg == 1, v2[f], v1[f])
        okn = (ln > neg) & (nondig[f] == neg + dots[f])
        okn &= (dots[f] <= 1) & (dfirst != ord(".")) \
            & (vlast[f] != ord("."))
        okn &= ((dfirst != ord("0")) | (ln - neg == 1)
                | (dsecond == ord(".")))
        okn &= ~((neg == 1) & (dfirst == ord("0")) & (dots[f] == 0))
        return okn

    # ---- specials: presence, uniqueness, per-special field selects ------
    def sel_field(sid, chans):
        """per-row values of ``chans`` at the (unique) field whose spid
        is ``sid``; also returns presence."""
        pres = jnp.zeros((N,), dtype=bool)
        outs = [jnp.zeros((N,), dtype=_I32) for _ in chans]
        for f in range(F):
            hit = jm[:, f] & (spid[f] == sid)
            pres |= hit
            for c, ch in enumerate(chans):
                cv = ch[f] if isinstance(ch, list) else ch[:, f]
                outs[c] = jnp.where(hit, cv.astype(_I32), outs[c])
        return pres, outs

    rep_special = jnp.zeros((N,), dtype=bool)
    for sid in range(1, 7):
        cnt = jnp.zeros((N,), dtype=_I32)
        for f in range(F):
            cnt = cnt + (jm[:, f] & (spid[f] == sid)).astype(_I32)
        rep_special |= cnt > 1

    has_ts, (tsa, tsb, ts_vt) = sel_field(_SP_TS, [va, vb, val_t])
    _, (ts_dots, ts_nondig, ts_v0, ts_v1, ts_v2, ts_vlast) = sel_field(
        _SP_TS, [dots, nondig, v0, v1, v2, vlast])
    has_host, (host_a, host_b, host_vt) = sel_field(
        _SP_HOST, [va, vb, val_t])
    _, (host_esc,) = sel_field(_SP_HOST, [val_esc])
    has_short, (short_a, short_b, short_vt) = sel_field(
        _SP_SHORT, [va, vb, val_t])
    _, (short_esc,) = sel_field(_SP_SHORT, [val_esc])
    has_full, (full_a, full_b, full_vt) = sel_field(
        _SP_FULL, [va, vb, val_t])
    _, (full_esc,) = sel_field(_SP_FULL, [val_esc])
    has_ver, (ver_vt, ver_ln0, ver_v0, ver_v1, ver_v2) = sel_field(
        _SP_VER, [val_t, [vb[f] - va[f] for f in range(F)], v0, v1, v2])
    _, (ver_esc,) = sel_field(_SP_VER, [val_esc])
    has_lvl, (lvl_a, lvl_b, lvl_vt, lvl_v0) = sel_field(
        _SP_LVL, [va, vb, val_t, v0])

    # ---- timestamp validation + exact split-integer parse ---------------
    ts_ln = tsb - tsa
    ts_neg = (ts_v0 == ord("-")).astype(_I32)
    ts_dfirst = jnp.where(ts_neg == 1, ts_v1, ts_v0)
    ts_dsecond = jnp.where(ts_neg == 1, ts_v2, ts_v1)
    ts_canon = (ts_ln > ts_neg) & (ts_nondig == ts_neg + ts_dots)
    ts_canon &= (ts_dots <= 1) & (ts_dfirst != ord(".")) \
        & (ts_vlast != ord("."))
    ts_canon &= ((ts_dfirst != ord("0")) | (ts_ln - ts_neg == 1)
                 | (ts_dsecond == ord(".")))
    ts_canon &= ~((ts_neg == 1) & (ts_dfirst == ord("0"))
                  & (ts_dots == 0))
    ts_ok = has_ts & (ts_vt == VT_NUMBER) & ts_canon & (ts_ln <= _TSW)

    r = iota - tsa[:, None]
    in_ts = (r >= 0) & (r < ts_ln[:, None])
    dot_r = jnp.min(jnp.where(in_ts & is_dot, r, 1 << 20), axis=1)
    has_dot = ts_dots == 1
    nd_digits = ts_ln - ts_neg - has_dot.astype(_I32)
    frac_digits = jnp.where(has_dot, ts_ln - 1 - dot_r, 0)
    di = r - ts_neg[:, None] - (r > dot_r[:, None]).astype(_I32)
    place = nd_digits[:, None] - 1 - di
    dig = bb - 48
    dig_m = (in_ts & ~is_nondig & (r >= ts_neg[:, None])
             & (r != dot_r[:, None]))
    lo_w = jnp.where(dig_m & (place >= 0) & (place <= 8),
                     10 ** jnp.clip(place, 0, 8), 0)
    hi_w = jnp.where(dig_m & (place >= 9) & (place <= 17),
                     10 ** jnp.clip(place - 9, 0, 8), 0)
    ts_lo = jnp.sum(dig * lo_w, axis=1)
    ts_hi = jnp.sum(dig * hi_w, axis=1)
    ts_meta = (jnp.clip(frac_digits, 0, 255)
               | (jnp.clip(nd_digits, 0, 255) << 8)
               | (ts_neg << 16))
    f16_ok = (ts_hi < 9007199) | ((ts_hi == 9007199)
                                  & (ts_lo <= 254740992))
    ts_ok &= (nd_digits <= 15) | ((nd_digits == 16) & f16_ok)

    # ---- other specials --------------------------------------------------
    host_ok = has_host & (host_vt == VT_STRING) & (host_esc == 0)
    short_ok = ~has_short | ((short_vt == VT_STRING) & (short_esc == 0))
    full_ok = ~has_full | ((full_vt == VT_STRING) & (full_esc == 0))
    ver_ok = ~has_ver | ((ver_vt == VT_STRING) & (ver_esc == 0)
                         & (ver_ln0 == 3) & (ver_v0 == ord("1"))
                         & (ver_v1 == ord("."))
                         & ((ver_v2 == ord("0")) | (ver_v2 == ord("1"))))
    lvl_ok = ~has_lvl | ((lvl_vt == VT_NUMBER) & (lvl_b - lvl_a == 1)
                         & (lvl_v0 >= ord("0")) & (lvl_v0 <= ord("7")))

    # ---- pair validation + slot compaction ------------------------------
    pair_bad = jnp.zeros((N,), dtype=bool)
    is_pair_cols = []
    run = jnp.zeros((N,), dtype=_I32)
    for f in range(F):
        isp = jm[:, f] & (spid[f] == 0)
        neg = (v0[f] == ord("-")).astype(_I32)
        int_ok = ((val_t[:, f] == VT_NUMBER) & (fracc[f] == 0)
                  & (vb[f] - va[f] - neg <= 18) & canonical_number(f)
                  & ~((v0[f] == ord("0")) & (vb[f] - va[f] > 1))
                  & ~((neg == 1) & (v1[f] == ord("0"))))
        p_ok = (((val_t[:, f] == VT_STRING) & ~val_esc[:, f])
                | (val_t[:, f] == VT_TRUE) | (val_t[:, f] == VT_FALSE)
                | (val_t[:, f] == VT_NULL) | int_ok)
        pair_bad |= isp & ~p_ok
        pair_bad |= jm[:, f] & key_esc[:, f]
        run = run + isp.astype(_I32)
        is_pair_cols.append(isp)
    pair_count = run

    # pair slots feed the sorter in RAW FIELD ORDER with a per-slot
    # validity mask: non-pair/absent fields key to _BIG and the sort
    # itself compacts them to the tail — no O(F^2) where-chain
    # compaction (the F=24 wide kernel would not compile in reasonable
    # time with one).  Sort key = final name (leading '_' stripped).
    ns_true = [key_s[:, f] for f in range(F)]
    ne_slot = [key_e[:, f] for f in range(F)]
    us_slot = [(b == ord("_")).astype(_I32) for b in kfirst]
    # NB: "ne_raw" and "ne" must be DISTINCT list objects (the sorter
    # swaps each payload list in place; an aliased list would swap
    # twice and end unsorted)
    cols = {"_pair_count": pair_count,
            "ns_raw": [ns + us for ns, us in zip(ns_true, us_slot)],
            "ne_raw": list(ne_slot),
            "ns": list(ns_true), "ne": ne_slot, "us": us_slot,
            "vs": [val_s[:, f] for f in range(F)],
            "ve": [val_e[:, f] for f in range(F)],
            "vt": [val_t[:, f] for f in range(F)]}
    ambig = sort_pairs_by_key8(bb, iota, cols, F,
                               slot_valid=is_pair_cols)

    # ---- segment table (host tier's 1 + 7p + 16 layout) -----------------
    cbase = L
    tbase = L + len(bank)
    zero = jnp.zeros((N,), dtype=_I32)
    # elide=True: the "{" head, '"timestamp":' label, and
    # ',"version":"1.1"}'+suffix tail stay off the device row — the
    # host splice restores them (device_common.splice_elided_rows)
    segs = ([] if elide
            else [(zero + (cbase + off["open"]), zero + 1)])
    for p in range(F):
        pv = p < pair_count
        us = cols["us"][p] == 1
        is_str = cols["vt"][p] == VT_STRING
        vsrc = jnp.where(
            is_str | (cols["vt"][p] == VT_NUMBER), cols["vs"][p],
            jnp.where(cols["vt"][p] == VT_TRUE, cbase + off["true"],
                      jnp.where(cols["vt"][p] == VT_FALSE,
                                cbase + off["false"],
                                cbase + off["null"])))
        vln = jnp.where(
            is_str | (cols["vt"][p] == VT_NUMBER),
            cols["ve"][p] - cols["vs"][p],
            jnp.where(cols["vt"][p] == VT_TRUE, 4,
                      jnp.where(cols["vt"][p] == VT_FALSE, 5, 4)))
        segs.append((jnp.where(us, cbase + off["q"], cbase + off["kpre"]),
                     jnp.where(pv, jnp.where(us, 1, 2), 0)))
        segs.append((cols["ns"][p],
                     jnp.where(pv, cols["ne"][p] - cols["ns"][p], 0)))
        segs.append((zero + (cbase + off["colon"]),
                     jnp.where(pv, 2, 0)))
        segs.append((zero + (cbase + off["q"]),
                     jnp.where(pv & is_str, 1, 0)))
        segs.append((vsrc, jnp.where(pv, vln, 0)))
        segs.append((zero + (cbase + off["q"]),
                     jnp.where(pv & is_str, 1, 0)))
        segs.append((zero + (cbase + off["comma"]),
                     jnp.where(pv, 1, 0)))

    host_len0 = host_b - host_a
    host_empty = host_len0 <= 0
    segs += [
        (zero + (cbase + off["full"]),
         jnp.where(has_full, len(_PARTS["full"]), 0)),
        (full_a, jnp.where(has_full, full_b - full_a, 0)),
        (zero + (cbase + off["qc"]), jnp.where(has_full, 2, 0)),
        (zero + (cbase + off["host"]), zero + len(_PARTS["host"])),
        (jnp.where(host_empty, cbase + off["unknown"], host_a),
         jnp.where(host_empty, len(_PARTS["unknown"]), host_len0)),
        (zero + (cbase + off["qc"]), zero + 2),
        (zero + (cbase + off["lvl"]),
         jnp.where(has_lvl, len(_PARTS["lvl"]), 0)),
        (lvl_a, jnp.where(has_lvl, 1, 0)),
        (zero + (cbase + off["comma"]), jnp.where(has_lvl, 1, 0)),
        (zero + (cbase + off["short"]), zero + len(_PARTS["short"])),
        (jnp.where(has_short, short_a, cbase + off["dash"]),
         jnp.where(has_short, short_b - short_a, 1)),
        (zero + (cbase + off["qc"]), zero + 2),
    ]
    if not elide:
        segs.append((zero + (cbase + off["ts"]),
                     zero + len(_PARTS["ts"])))
    segs.append((zero + tbase, ts_len.astype(_I32)))
    if not elide:
        segs.append((zero + (cbase + off["comma"]), zero + 1))
        segs.append((zero + (cbase + off["tail"]),
                     zero + len(_PARTS["tail"]) + len(suffix)))

    out_len = segs[0][1]
    for _, ln in segs[1:]:
        out_len = out_len + ln

    tier = (ok & ~viol_row & ~rep_special
            & ts_ok & host_ok & short_ok & full_ok & ver_ok & lvl_ok
            & ~pair_bad & ~ambig & (out_len <= OW))
    if not assemble:
        return {"tier": tier, "ts_hi": ts_hi, "ts_lo": ts_lo,
                "ts_meta": ts_meta, "ts_ok_row": tier}
    acc, out_len2 = assemble_rows(segs, batch, bank, ts_text, N, OW)
    return acc, out_len2, tier


def route_ok(encoder, merger) -> bool:
    """GELF output over line/nul/syslen framing; gelf_extra cannot place
    statically in a re-canonicalized object (dynamic input keys), so any
    extras keep the host paths — exactly the host block's gate."""
    from .device_common import gelf_route_ok

    return gelf_route_ok(encoder, merger, lambda e: False)


TS_KEYS = ("ts_hi", "ts_lo", "ts_meta")


def ts_vals_gelf(small, okh):
    """Combine the kernel's split-integer parse; sign rides
    ts_meta bit 16 (canonical JSON allows negative stamps).  Shared
    by the split and fused gelf→GELF tiers."""
    import numpy as np

    hi = small["ts_hi"].astype(np.float64)
    lo = small["ts_lo"].astype(np.float64)
    meta = small["ts_meta"]
    frac = (meta & 255).astype(np.int64)
    sign = np.where((meta >> 16) & 1, -1.0, 1.0)
    return sign * (hi * 1e9 + lo) / np.power(10.0, frac)


def elide_spec(suffix: bytes):
    """(head, ts-label, tail) constants the elided kernel skips and the
    host splice restores — single source shared with the fused route."""
    return (_PARTS["open"], _PARTS["ts"],
            _PARTS["comma"] + _PARTS["tail"] + suffix)


def fetch_encode(handle, packed, encoder, merger, route_state=None):
    """Device gelf→GELF encode for a submitted gelf decode handle;
    returns (BlockResult | None, fetch_seconds)."""
    from .block_common import merger_suffix
    from .materialize_gelf import _scalar_gelf

    out, batch_dev, lens_dev, _batch_host, _lens_host = handle
    suffix, syslen = merger_suffix(merger)

    def kernel(ts_text, ts_len, assemble):
        return _encode_kernel(batch_dev, lens_dev, dict(out), ts_text,
                              ts_len, suffix=suffix, assemble=assemble,
                              elide=True)

    # zero-JIT boot: consult the AOT artifact store before compiling
    # (this route never engages with extras — route_ok gates them — so
    # the impl/extras args are key bookkeeping only)
    from .aot import encode_wrap
    from .rfc5424 import best_scan_impl as _impl

    kernel = encode_wrap("device_gelf_gelf", kernel, batch_dev,
                         lens_dev, dict(out), suffix, _impl(), ())

    def wide():
        """16-field escalation: re-decode wider (the [N, F] field axis
        sizes every loop in the kernel).  16 rather than the 24-field
        decode rescue bound: the per-field point/count extraction words
        scale compile time with F, and 17+-field GELF objects are rare
        enough to leave on the host rescue path."""
        from .gelf import decode_gelf_jit

        out_w = decode_gelf_jit(batch_dev, lens_dev, max_fields=16)

        def kernel_w(ts_text, ts_len, assemble):
            return _encode_kernel(batch_dev, lens_dev, dict(out_w),
                                  ts_text, ts_len, suffix=suffix,
                                  assemble=assemble, elide=True)
        return out_w, kernel_w

    return fetch_encode_driver(
        kernel, out, batch_dev, lens_dev, packed, encoder, merger,
        route_state, suffix, syslen, scalar_fn=_scalar_gelf,
        fallback_frac=FALLBACK_FRAC, decline_limit=DECLINE_LIMIT,
        cooldown=COOLDOWN,
        ts_keys=TS_KEYS, ts_vals_fn=ts_vals_gelf,
        wide=wide, elide=elide_spec(suffix))
