r"""Columnar GELF (flat JSON) tokenizer (BASELINE.json config #3).

Scalar spec: flowgger_tpu/decoders/gelf.py (reference
gelf_decoder.rs:34-125).  GELF messages are flat JSON objects of scalar
values — exactly the shape a simdjson-style structural pass handles:

stage 1 (device, this module): backslash-run parity marks escaped
quotes; prefix parity classifies in/out-of-string; three scan channels
answer every "what comes next/before" question without gathers —
  ``P`` forward: last significant byte before each position,
  ``C`` reverse: next significant byte at/after each position,
  ``Q`` reverse: next real quote after each position —
(significant = non-whitespace outside strings, plus quotes).  Key
strings are strings whose preceding significant byte is ``{`` or ``,``;
per-pair masked min-reductions then walk key-close → colon → value →
value-end through the channels, emitting span tables and a value-type
code per pair.  Arrays, nested objects, >max_fields keys, or any
structural surprise flags the row for the scalar oracle.

stage 2 (host, materialize_gelf.py): slices spans, json-parses only the
tokens that need it (escaped strings, numbers), routes the special GELF
keys in sorted order like serde's BTreeMap.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp

from .rfc5424 import _cummax, _cumsum, _min_where, _shift_left, _shift_right

DEFAULT_MAX_FIELDS = 24
_I32 = jnp.int32

VT_STRING, VT_NUMBER, VT_TRUE, VT_FALSE, VT_NULL = 0, 1, 2, 3, 4


def _rev_next_min(packed, big, impl):
    """Reverse scan: per position, the minimum of ``packed`` at or after
    it (packed = pos<<8|byte so min == nearest)."""
    flipped = jnp.flip(packed, axis=1)
    acc = _cummax(-flipped, impl)
    return jnp.flip(-acc, axis=1)


def _match_token(bb, text: bytes):
    """positions where ``text`` starts, via shifted byte planes."""
    m = bb == text[0]
    for i, ch in enumerate(text[1:], start=1):
        m &= _shift_left(bb, i, 0) == ch
    return m


def decode_gelf(batch: jnp.ndarray, lens: jnp.ndarray,
                max_fields: int = DEFAULT_MAX_FIELDS,
                scan_impl: str = "lax") -> Dict[str, jnp.ndarray]:
    N, L = batch.shape
    lens = lens.astype(_I32)
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)
    valid = iota < lens[:, None]
    bb = jnp.where(valid, batch, jnp.uint8(0)).astype(jnp.int16)

    is_ws = ((bb == 32) | (bb == 9) | (bb == 10) | (bb == 13)) & valid

    # escaped quotes via backslash-run parity
    is_bs = (bb == 92) & valid
    non_bs_pos = jnp.where(~is_bs, iota, -1)
    last_non_bs = _cummax(non_bs_pos, scan_impl)
    prev_last = _shift_right(last_non_bs, 1, -1)
    escaped = ((iota - 1 - prev_last) % 2) == 1

    quote = (bb == ord('"')) & valid
    real_q = quote & ~escaped
    q_excl = _cumsum(real_q, scan_impl) - real_q
    outside = (q_excl % 2) == 0
    open_q = real_q & outside
    close_q = real_q & ~outside
    ok = (q_excl[:, -1] + real_q[:, -1]) % 2 == 0  # even quote count

    significant = ((~is_ws & outside & valid) | real_q)

    PACK = lambda: (iota << 8) | bb.astype(_I32)  # noqa: E731
    BIG = jnp.int32((L + 1) << 8)

    # channels
    P = _shift_right(_cummax(jnp.where(significant, PACK(), -1), scan_impl), 1, -1)
    C = _rev_next_min(jnp.where(significant, PACK(), BIG), BIG, scan_impl)
    Q = _rev_next_min(jnp.where(real_q, PACK(), BIG), BIG, scan_impl)

    def chan_at(chan, pos):
        """chan[n, pos[n]] via masked reduction; (L+1)<<8 when pos >= L."""
        hit = iota == jnp.clip(pos, 0, L)[:, None]
        return jnp.min(jnp.where(hit, chan, BIG), axis=1)

    # overall shape: first significant is '{', last is '}'
    first_sig = C[:, 0]
    ok &= (first_sig & 0xFF) == ord("{")
    # no arrays / extra braces outside strings
    brace_open = (bb == ord("{")) & outside & valid
    ok &= jnp.sum(brace_open.astype(_I32), axis=1) == 1
    ok &= ~jnp.any(((bb == ord("[")) | (bb == ord("]"))) & outside & valid, axis=1)
    brace_close = (bb == ord("}")) & outside & valid
    ok &= jnp.sum(brace_close.astype(_I32), axis=1) == 1
    rb_pos = jnp.max(jnp.where(brace_close, iota, -1), axis=1)
    # nothing significant after the closing brace
    after_rb = chan_at(C, rb_pos + 1)
    ok &= after_rb >= BIG

    # every string must be a key (prev sig in {, ,) or a value (prev :)
    prev_at_oq_ch = jnp.where(P >= 0, P & 0xFF, -1)
    is_key_q = open_q & ((prev_at_oq_ch == ord("{")) | (prev_at_oq_ch == ord(",")))
    is_val_q = open_q & (prev_at_oq_ch == ord(":"))
    ok &= ~jnp.any(open_q & ~is_key_q & ~is_val_q, axis=1)

    key_ord = _cumsum(is_key_q, scan_impl)
    n_keys = key_ord[:, -1]
    ok &= n_keys <= max_fields

    POS = 8
    key_open = jnp.stack(
        [_min_where(is_key_q & (key_ord == k + 1), iota, L) for k in range(max_fields)],
        axis=1)  # [N, F]

    # walk the channels per key
    key_close_pk = jnp.stack(
        [chan_at(Q, key_open[:, k] + 1) for k in range(max_fields)], axis=1)
    key_close = key_close_pk >> POS
    colon_pk = jnp.stack(
        [chan_at(C, key_close[:, k] + 1) for k in range(max_fields)], axis=1)
    colon_ok = (colon_pk & 0xFF) == ord(":")
    colon_pos = colon_pk >> POS
    val_pk = jnp.stack(
        [chan_at(C, colon_pos[:, k] + 1) for k in range(max_fields)], axis=1)
    val_ch = val_pk & 0xFF
    val_pos = val_pk >> POS

    field_valid = (jnp.arange(max_fields, dtype=_I32)[None, :] < n_keys[:, None])
    ok &= jnp.where(field_valid, colon_ok & (key_close[:, :] < L + 1), True).all(axis=1)

    # value classification
    is_vstr = val_ch == ord('"')
    is_vnum = ((val_ch >= ord("0")) & (val_ch <= ord("9"))) | (val_ch == ord("-"))
    true_at = _match_token(bb, b"true")
    false_at = _match_token(bb, b"false")
    null_at = _match_token(bb, b"null")

    def mask_at(mask, pos):
        hit = iota == jnp.clip(pos, 0, L - 1)[:, None]
        return jnp.any(mask & hit, axis=1)

    is_vtrue = jnp.stack([mask_at(true_at, val_pos[:, k]) for k in range(max_fields)], axis=1)
    is_vfalse = jnp.stack([mask_at(false_at, val_pos[:, k]) for k in range(max_fields)], axis=1)
    is_vnull = jnp.stack([mask_at(null_at, val_pos[:, k]) for k in range(max_fields)], axis=1)

    val_type = jnp.where(
        is_vstr, VT_STRING,
        jnp.where(is_vnum, VT_NUMBER,
                  jnp.where(is_vtrue, VT_TRUE,
                            jnp.where(is_vfalse, VT_FALSE,
                                      jnp.where(is_vnull, VT_NULL, -1)))))
    ok &= jnp.where(field_valid, val_type >= 0, True).all(axis=1)

    # value end + after-value check
    # string: close quote; others: next ws/structural boundary
    vclose = jnp.stack(
        [chan_at(Q, val_pos[:, k] + 1) >> POS for k in range(max_fields)], axis=1)
    boundary = (is_ws | (((bb == ord(",")) | (bb == ord("}")) | (bb == ord(":")))
                         & outside)) & valid
    Bc = _rev_next_min(jnp.where(boundary, PACK(), BIG), BIG, scan_impl)
    vbound = jnp.stack(
        [chan_at(Bc, val_pos[:, k] + 1) >> POS for k in range(max_fields)], axis=1)
    vbound = jnp.minimum(vbound, lens[:, None])
    val_end = jnp.where(val_type == VT_STRING, vclose, vbound)
    # after-value char: strings end at their close quote (look past it);
    # number/literal val_end is already the first boundary byte (C skips
    # any whitespace from there to the structural ',' or '}')
    after_pos = jnp.where(val_type == VT_STRING, val_end + 1, val_end)
    after_pk = jnp.stack(
        [chan_at(C, after_pos[:, k]) for k in range(max_fields)], axis=1)
    after_ch = after_pk & 0xFF
    ok &= jnp.where(field_valid, (after_ch == ord(",")) | (after_ch == ord("}")),
                    True).all(axis=1)
    # literal tokens must end exactly at the boundary
    lit_len = jnp.where(val_type == VT_TRUE, 4,
                        jnp.where(val_type == VT_FALSE, 5,
                                  jnp.where(val_type == VT_NULL, 4, -1)))
    ok &= jnp.where(field_valid & (lit_len > 0),
                    vbound == val_pos + lit_len, True).all(axis=1)

    # escapes inside string values / keys -> host json-decodes the span
    bs_csum = _cumsum(is_bs, scan_impl)

    def bs_between(a, b):
        va = jnp.stack([chan_at(bs_csum[:, :] << 8, a[:, k]) >> 8
                        for k in range(max_fields)], axis=1)
        vb = jnp.stack([chan_at(bs_csum[:, :] << 8, jnp.maximum(b[:, k] - 1, 0)) >> 8
                        for k in range(max_fields)], axis=1)
        return (vb - va) > 0

    key_esc = bs_between(key_open, key_close)
    val_esc = bs_between(val_pos, val_end) & (val_type == VT_STRING)

    # every structural comma must introduce another key, and comma count
    # must match (rejects `{"a":1,}` and stray commas)
    comma = (bb == ord(",")) & outside & valid
    next_sig_ch = jnp.where(_shift_left(C, 1, BIG) < BIG,
                            _shift_left(C, 1, BIG) & 0xFF, -1)
    ok &= ~jnp.any(comma & (next_sig_ch != ord('"')), axis=1)
    n_commas = jnp.sum(comma.astype(_I32), axis=1)
    ok &= jnp.where(n_keys > 0, n_commas == n_keys - 1, n_commas == 0)

    # empty object: '{' directly followed by '}'
    ok &= jnp.where(n_keys == 0, (chan_at(C, (first_sig >> POS) + 1) & 0xFF)
                    == ord("}"), True)

    return {
        "ok": ok,
        "n_fields": jnp.where(ok, n_keys, 0),
        "key_start": key_open + 1, "key_end": key_close,
        "val_start": jnp.where(val_type == VT_STRING, val_pos + 1, val_pos),
        "val_end": val_end,
        "val_type": val_type,
        "key_esc": key_esc, "val_esc": val_esc,
    }


def decode_gelf_submit(batch, lens):
    """Asynchronous dispatch (pair with decode_gelf_fetch) — the gelf
    leg of the block pipeline's double buffering."""
    import jax.numpy as jnp

    return decode_gelf_jit(jnp.asarray(batch), jnp.asarray(lens))


def decode_gelf_fetch(handle):
    import numpy as np

    return {k: np.asarray(v) for k, v in handle.items()}


@functools.partial(jax.jit, static_argnames=("max_fields",))
def decode_gelf_jit(batch, lens, max_fields=DEFAULT_MAX_FIELDS):
    return decode_gelf(batch, lens, max_fields=max_fields)
