r"""Columnar GELF (flat JSON) tokenizer (BASELINE.json config #3).

Scalar spec: flowgger_tpu/decoders/gelf.py (reference
gelf_decoder.rs:34-125).  GELF messages are flat JSON objects of scalar
values.

Round-3 design: **scan-free** except two MXU matmul ordinal cumsums.
The previous kernel ran eight full-width scan channels (forward/reverse
packed cummaxes answering "prev/next significant byte") plus ~170
per-key masked reductions — v5e profiling showed each [1M, 256] scan
costs ~22-27ms and each reduction pass ~1-2ms, so it decoded at 2.4M
lines/s.  This version replaces every channel walk:

- **quote parity** classifies in/out-of-string (escaped quotes via the
  shared bit-packed backslash ladder); open/close quotes alternate, so
  no per-string bookkeeping is needed;
- **bounded-window lookarounds** replace the prev/next-significant
  scans: the previous/next non-whitespace byte is found by an
  elementwise select chain over W=8 shifted planes.  Flat JSON with a
  whitespace run longer than W between tokens falls back to the scalar
  oracle (a single fused AND-ladder detects that row-wise);
- token roles become **elementwise masks**: an open quote is a key iff
  its previous non-ws byte is ``{`` or ``,`` and a value iff it is
  ``:``; a close quote is a key-close iff its next non-ws byte is
  ``:``; a number/literal value starts at a non-ws byte whose previous
  non-ws byte is ``:``; literal runs end where the run mask switches
  off — no position is ever "walked to";
- **key-ordinal extraction**: every per-key quantity is pulled out with
  the shared packed-sum extractor keyed on the key-ordinal plane
  (cumsum of key-opens — one packed matmul with the key-close ordinal)
  — ceil(F/3) reduction words per channel instead of F reductions;
- **two-tier field budget**: the default kernel extracts
  DEFAULT_MAX_FIELDS keys; rows with more (up to RESCUE_MAX_FIELDS)
  re-dispatch through a lazily-compiled wider kernel in
  ``decode_gelf_fetch``, and only rows beyond that hit the oracle.

Anything structurally surprising (arrays, nested objects, stray
tokens, >1 value per key, windows overflowing) flags the row for the
scalar oracle, keeping observable output byte-identical
(tests/test_tpu_gelf_auto.py, tools/deep_fuzz.py).

stage 2 (host, materialize_gelf.py): slices spans, json-parses only the
tokens that need it (escaped strings, numbers), routes the special GELF
keys in sorted order like serde's BTreeMap.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp

from .jsonidx import (  # noqa: F401 - VT_* re-exported for consumers
    VT_FALSE,
    VT_NULL,
    VT_NUMBER,
    VT_STRING,
    VT_TRUE,
    WS_WINDOW,
    structural_index,
)
from .rfc5424 import (
    best_extract_impl,
    best_scan_impl,
    rescue_refetch,
)

DEFAULT_MAX_FIELDS = 8
RESCUE_MAX_FIELDS = 24


def decode_gelf(batch: jnp.ndarray, lens: jnp.ndarray,
                max_fields: int = DEFAULT_MAX_FIELDS,
                scan_impl: str = None,
                extract_impl: str = None) -> Dict[str, jnp.ndarray]:
    if scan_impl is None:
        scan_impl = best_scan_impl()
    if extract_impl is None:
        extract_impl = best_extract_impl()
    # stage 1 lives in tpu/jsonidx.py, shared with the generic
    # JSON-lines decoder (tpu/jsonl.py) — nested=0 is GELF's flat-only
    # contract: any bracket outside a string flags the row
    return structural_index(batch, lens, max_fields, scan_impl,
                            extract_impl, nested=0)


def decode_gelf_submit(batch, lens, sharded=None):
    """Asynchronous dispatch (pair with decode_gelf_fetch) — the gelf
    leg of the block pipeline's double buffering.  ``sharded`` swaps in
    the multi-chip mesh kernel (parallel.mesh.ShardedDecode)."""
    import jax.numpy as jnp

    # the handle carries BOTH the device arrays (for the device-encode
    # tier, no re-upload) and the caller's host arrays (so the tier-2
    # rescue in decode_gelf_fetch never pays a full-batch D2H just to
    # slice a few rescue rows)
    if sharded is not None:
        b, ln = sharded.put(batch, lens)
        return (sharded.fn(b, ln), b, ln, batch, lens)
    from .aot import decode_call

    b, ln = jnp.asarray(batch), jnp.asarray(lens)
    # zero-JIT boot: a loaded AOT artifact replaces the trace+compile
    out = decode_call("gelf", (b, ln))
    if out is None:
        out = decode_gelf_jit(b, ln)
    return (out, b, ln, batch, lens)


_FIELD_KEYS = ("key_start", "key_end", "val_start", "val_end", "val_type",
               "key_esc", "val_esc")


def decode_gelf_fetch(handle):
    """Block on a submitted decode; rows whose field count lies in
    (DEFAULT_MAX_FIELDS, RESCUE_MAX_FIELDS] re-dispatch through the
    wider tier-2 kernel so they stay on-device.  Field channels come
    back widened to RESCUE_MAX_FIELDS when tier 2 ran."""
    import numpy as np

    out, _b_dev, _ln_dev, batch, lens = handle
    host = {k: np.asarray(v) for k, v in out.items()}
    if host["key_start"].shape[1] >= RESCUE_MAX_FIELDS:
        return host
    nf = host["n_fields"]
    over = np.flatnonzero(~host["ok"] & (nf > DEFAULT_MAX_FIELDS)
                          & (nf <= RESCUE_MAX_FIELDS))

    def dispatch(sub_b, sub_l):
        out2 = decode_gelf_jit(jnp.asarray(sub_b), jnp.asarray(sub_l),
                               max_fields=RESCUE_MAX_FIELDS)
        return {k: np.asarray(v) for k, v in out2.items()}

    return rescue_refetch(host, batch, lens, over, _FIELD_KEYS, dispatch,
                          RESCUE_MAX_FIELDS)


@functools.partial(jax.jit, static_argnames=("max_fields", "demand"))
def decode_gelf_jit(batch, lens, max_fields=DEFAULT_MAX_FIELDS,
                    demand=None):
    """``demand`` (static frozenset): keep only the channels the
    consumer reads so XLA dead-code-eliminates the rest (fused
    gelf→GELF route)."""
    out = decode_gelf(batch, lens, max_fields=max_fields)
    if demand is not None:
        out = {k: v for k, v in out.items() if k in demand}
    return out
