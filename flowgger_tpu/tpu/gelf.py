r"""Columnar GELF (flat JSON) tokenizer (BASELINE.json config #3).

Scalar spec: flowgger_tpu/decoders/gelf.py (reference
gelf_decoder.rs:34-125).  GELF messages are flat JSON objects of scalar
values.

Round-3 design: **scan-free** except two MXU matmul ordinal cumsums.
The previous kernel ran eight full-width scan channels (forward/reverse
packed cummaxes answering "prev/next significant byte") plus ~170
per-key masked reductions — v5e profiling showed each [1M, 256] scan
costs ~22-27ms and each reduction pass ~1-2ms, so it decoded at 2.4M
lines/s.  This version replaces every channel walk:

- **quote parity** classifies in/out-of-string (escaped quotes via the
  shared bit-packed backslash ladder); open/close quotes alternate, so
  no per-string bookkeeping is needed;
- **bounded-window lookarounds** replace the prev/next-significant
  scans: the previous/next non-whitespace byte is found by an
  elementwise select chain over W=8 shifted planes.  Flat JSON with a
  whitespace run longer than W between tokens falls back to the scalar
  oracle (a single fused AND-ladder detects that row-wise);
- token roles become **elementwise masks**: an open quote is a key iff
  its previous non-ws byte is ``{`` or ``,`` and a value iff it is
  ``:``; a close quote is a key-close iff its next non-ws byte is
  ``:``; a number/literal value starts at a non-ws byte whose previous
  non-ws byte is ``:``; literal runs end where the run mask switches
  off — no position is ever "walked to";
- **key-ordinal extraction**: every per-key quantity is pulled out with
  the shared packed-sum extractor keyed on the key-ordinal plane
  (cumsum of key-opens — one packed matmul with the key-close ordinal)
  — ceil(F/3) reduction words per channel instead of F reductions;
- **two-tier field budget**: the default kernel extracts
  DEFAULT_MAX_FIELDS keys; rows with more (up to RESCUE_MAX_FIELDS)
  re-dispatch through a lazily-compiled wider kernel in
  ``decode_gelf_fetch``, and only rows beyond that hit the oracle.

Anything structurally surprising (arrays, nested objects, stray
tokens, >1 value per key, windows overflowing) flags the row for the
scalar oracle, keeping observable output byte-identical
(tests/test_tpu_gelf_auto.py, tools/deep_fuzz.py).

stage 2 (host, materialize_gelf.py): slices spans, json-parses only the
tokens that need it (escaped strings, numbers), routes the special GELF
keys in sorted order like serde's BTreeMap.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp

from .rfc5424 import (
    _bitpack32,
    _esc_parity,
    _scan_ordinals,
    _slot_geometry,
    _shift_left,
    _shift_right,
    best_extract_impl,
    best_scan_impl,
    extract_by_ord,
    extract_counts_by_ord,
    rescue_refetch,
)

DEFAULT_MAX_FIELDS = 8
RESCUE_MAX_FIELDS = 24
WS_WINDOW = 8
_I32 = jnp.int32

VT_STRING, VT_NUMBER, VT_TRUE, VT_FALSE, VT_NULL = 0, 1, 2, 3, 4


def decode_gelf(batch: jnp.ndarray, lens: jnp.ndarray,
                max_fields: int = DEFAULT_MAX_FIELDS,
                scan_impl: str = None,
                extract_impl: str = None) -> Dict[str, jnp.ndarray]:
    if scan_impl is None:
        scan_impl = best_scan_impl()
    if extract_impl is None:
        extract_impl = best_extract_impl()
    N, L = batch.shape
    lens = lens.astype(_I32)
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)
    valid = iota < lens[:, None]
    # uint8 byte plane (see rfc5424.py): widen inside consumer fusions
    bb = jnp.where(valid, batch, jnp.uint8(0))

    is_ws = ((bb == 32) | (bb == 9) | (bb == 10) | (bb == 13)) & valid
    nonws = valid & ~is_ws

    # ---- escaped quotes & parity ----------------------------------------
    is_bs = (bb == 92) & valid
    quote = (bb == ord('"')) & valid
    escaped, cap_plane, cap_words = _esc_parity(is_bs, scan_impl)
    real_q = quote & ~escaped
    if cap_plane is not None:
        cap_viol = jnp.any(cap_plane & quote, axis=1)
    else:
        cap_viol = jnp.any((cap_words & _bitpack32(quote)) != 0, axis=1)

    (q_incl,) = _scan_ordinals([real_q], scan_impl)
    q_excl = q_incl - real_q.astype(q_incl.dtype)
    outside = (q_excl & 1) == 0
    open_q = real_q & outside
    close_q = real_q & ~outside
    inside_str = (~outside) & valid
    ok = ~cap_viol

    # ---- bounded-window lookarounds -------------------------------------
    # ptb/ntb: byte of the nearest non-ws position within WS_WINDOW
    # before/after each position (0 when none in window).  Rows with a
    # longer outside-string whitespace run fall back, so "not found in
    # window" can never silently mean "found nothing relevant".
    #
    # Round-5 fold: the old per-shift select chain materialized ~2*W
    # [N, L] pad fusions (the shifted planes each had many consumers, so
    # XLA would not rematerialize them); a reduce-window over a packed
    # (position << 8 | byte) word is ONE windowed pass each way — max
    # over [p-W, p-1] picks the nearest previous non-ws (largest
    # position) with its byte in the low bits, min over [p+1, p+W] the
    # nearest next.
    bi32 = bb.astype(_I32)
    pv = jnp.where(nonws, (iota << 8) | bi32, -1)
    rw_p = jax.lax.reduce_window(
        pv, jnp.int32(-1), jax.lax.max, (1, WS_WINDOW), (1, 1),
        ((0, 0), (WS_WINDOW - 1, 0)))
    ptb_w = _shift_right(rw_p, 1, -1)
    ptb = jnp.where(ptb_w >= 0, ptb_w & 255, 0)
    _BIG = jnp.int32(1 << 30)
    nv = jnp.where(nonws, (iota << 8) | bi32, _BIG)
    rw_n = jax.lax.reduce_window(
        nv, _BIG, jax.lax.min, (1, WS_WINDOW), (1, 1),
        ((0, 0), (0, WS_WINDOW - 1)))
    ntb_w = _shift_left(rw_n, 1, _BIG)
    ntb = jnp.where(ntb_w < _BIG, ntb_w & 255, 0)

    # ws run > WS_WINDOW outside strings: a windowed count hitting W+1
    # (edge padding contributes 0, so short runs at the line start can
    # never flag, matching the old shifted-AND ladder's False fill)
    run = is_ws & outside
    rw_run = jax.lax.reduce_window(
        run.astype(_I32), jnp.int32(0), jax.lax.add,
        (1, WS_WINDOW + 1), (1, 1), ((0, 0), (WS_WINDOW, 0)))
    # every row-disqualifying plane ORs into one mask reduced by a single
    # any at the end (round-5 fold: was 7 separate any-reductions)
    viol = rw_run == WS_WINDOW + 1

    # ---- structure: braces, arrays --------------------------------------
    lb = (bb == ord("{")) & outside
    rb = (bb == ord("}")) & outside
    viol |= ((bb == ord("[")) | (bb == ord("]"))) & outside
    # first/last non-ws position with an is-it-the-brace tag packed into
    # the reduction word (fold: was 4 reductions — first_nonws/lb_pos
    # mins, last_nonws/rb_pos maxes).  Combined with the exactly-one
    # lb/rb count checks below this is equivalent to first_nonws==lb_pos
    # & last_nonws==rb_pos.
    wf = jnp.min(jnp.where(nonws, 2 * iota + (~lb).astype(_I32), 2 * L + 2),
                 axis=1)
    first_is_lb = (wf & 1) == 0
    first_nonws = wf >> 1
    wl = jnp.max(jnp.where(nonws, 2 * iota + rb.astype(_I32), -1), axis=1)
    last_is_rb = (wl & 1) == 1
    last_nonws = wl >> 1
    ok &= first_is_lb & last_is_rb & (first_nonws < last_nonws)

    # ---- token roles (elementwise) --------------------------------------
    is_key_open = open_q & ((ptb == ord("{")) | (ptb == ord(",")))
    is_val_open = open_q & (ptb == ord(":"))
    viol |= open_q & ~is_key_open & ~is_val_open
    is_key_close = close_q & (ntb == ord(":"))
    is_val_close = close_q & ~is_key_close
    # a value close must be followed by ',' or '}'
    viol |= is_val_close & (ntb != ord(",")) & (ntb != ord("}"))

    colon_out = (bb == ord(":")) & outside & valid
    comma_out = (bb == ord(",")) & outside & valid
    # every comma introduces another key (next non-ws is a quote)
    viol |= comma_out & (ntb != ord('"'))

    key_ord, kc_ord = _scan_ordinals(
        [is_key_open, is_key_close], scan_impl)
    # the seven row counts ride packed sums, as many per-count fields per
    # i32 word as L allows (fold: was 3 maxes + 4 sums); the ordinal-plane
    # maxes equal plain mask counts because the ordinals are inclusive
    # cumsums
    cbits, per, cmask = _slot_geometry(L)

    def packed_counts(masks):
        outs = []
        for base in range(0, len(masks), per):
            grp = masks[base:base + per]
            acc = grp[0].astype(_I32)
            for s, m in enumerate(grp[1:], 1):
                acc = acc + (m.astype(_I32) << (cbits * s))
            word = jnp.sum(acc, axis=1)
            for s in range(len(grp)):
                outs.append((word >> (cbits * s)) & cmask)
        return outs

    n_quotes, lbc, rbc, n_keys, n_kc, n_colons, n_commas = packed_counts(
        [real_q, lb, rb, is_key_open, is_key_close, colon_out, comma_out])
    ok &= (n_quotes & 1) == 0  # every string closed
    ok &= (lbc == 1) & (rbc == 1)
    ok &= n_kc == n_keys
    ok &= n_keys <= max_fields
    ok &= n_colons == n_keys
    ok &= n_commas == jnp.maximum(n_keys - 1, 0)

    # ---- literal/number runs --------------------------------------------
    structural = (colon_out | comma_out | lb | rb | real_q)
    is_lit = nonws & outside & ~structural
    lit_start = is_lit & ~_shift_right(is_lit, 1, False)
    lit_end_m = is_lit & ~_shift_left(is_lit, 1, False)
    # nothing significant may precede the first key (between '{' and it)
    viol |= is_lit & (key_ord == 0)
    # backslashes are only legal inside strings in flat JSON; a bs
    # "outside" (per possibly-garbled parity) sends the row to the
    # oracle, which also shields the parity math itself from junk input
    viol |= is_bs & outside
    ok &= ~jnp.any(viol, axis=1)

    # number/literal value start: a literal-run start whose previous
    # non-ws byte is ':'
    is_lit_val = lit_start & (ptb == ord(":"))
    is_val_start = is_val_open | is_lit_val
    # literal tokens match against a packed next-4-bytes word (2 shifted
    # planes) instead of per-token shifted-plane chains (was ~11 planes);
    # high input bytes overflow into the sign bit deterministically and
    # can never collide with the ASCII token constants
    w2 = (bi32 << 8) | _shift_left(bi32, 1, 0)
    w4 = (w2 << 16) | _shift_left(w2, 2, 0)
    true_at = w4 == int.from_bytes(b"true", "big")
    null_at = w4 == int.from_bytes(b"null", "big")
    false_at = (w4 == int.from_bytes(b"fals", "big")) & \
        (_shift_left(bi32, 4, 0) == ord("e"))
    is_num0 = ((bb >= 48) & (bb <= 57)) | (bb == ord("-"))
    vclass = jnp.where(
        is_val_open, 1 + VT_STRING,
        jnp.where(true_at, 1 + VT_TRUE,
                  jnp.where(false_at, 1 + VT_FALSE,
                            jnp.where(null_at, 1 + VT_NULL,
                                      jnp.where(is_num0, 1 + VT_NUMBER, 0)))))

    # ---- per-key extraction (packed-sum words) --------------------------
    F = max_fields
    key_open_pos = extract_by_ord(is_key_open, key_ord, iota, F, L,
                                  extract_impl)
    key_close_pos = extract_by_ord(is_key_close, kc_ord, iota, F, L,
                                   extract_impl)
    # value position and class share one extraction word per slot: the
    # class rides bits above the position field (fold: was 2 channels =
    # 6 reduction words at F=8; fill L keeps the class field 0)
    pbits = max(10, int(L + 1).bit_length())
    vs_packed = extract_by_ord(is_val_start, key_ord,
                               iota | (vclass << pbits), F, L,
                               extract_impl, slot_bits=pbits + 3)
    val_start_pos = vs_packed & ((1 << pbits) - 1)
    val_class1 = vs_packed >> pbits
    val_close_pos = extract_by_ord(is_val_close, key_ord, iota, F, L,
                                   extract_impl)
    lit_end_pos = extract_by_ord(lit_end_m, key_ord, iota, F, L,
                                 extract_impl)
    # exactly one value token per key: a string close or a literal run
    val_tokens = extract_counts_by_ord(is_val_close | lit_start, key_ord,
                                       F, extract_impl)
    esc_count = extract_counts_by_ord(is_bs & inside_str, key_ord, F,
                                      extract_impl)

    field_valid = (jnp.arange(F, dtype=_I32)[None, :] < n_keys[:, None])
    ok &= jnp.where(field_valid, val_tokens == 1, val_tokens == 0).all(axis=1)
    ok &= jnp.where(field_valid, val_class1 >= 1, True).all(axis=1)
    val_type = jnp.where(field_valid, val_class1 - 1, -1)

    # per-key ordering sanity: open < close < value start
    ok &= jnp.where(field_valid,
                    (key_open_pos < key_close_pos)
                    & (key_close_pos < val_start_pos), True).all(axis=1)
    # extraction-collision guard: multiple val-starts per key would
    # corrupt the packed sums — val_tokens==1 bounds val_close/lit runs,
    # and >1 val_start implies >1 lit_start or val_open (the former is
    # bounded above; a second val_open implies a second ':' which the
    # colon count bounds)

    # string values: close quote; literals: last run byte + 1
    is_string = val_type == VT_STRING
    val_end = jnp.where(is_string, val_close_pos, lit_end_pos + 1)
    val_end = jnp.minimum(val_end, lens[:, None])
    # literal token length must match exactly (rejects "truex")
    lit_len = jnp.where(val_type == VT_TRUE, 4,
                        jnp.where(val_type == VT_FALSE, 5,
                                  jnp.where(val_type == VT_NULL, 4, -1)))
    ok &= jnp.where(field_valid & (lit_len > 0),
                    val_end - val_start_pos == lit_len, True).all(axis=1)
    # string values must close after they open
    ok &= jnp.where(field_valid & is_string,
                    val_close_pos > val_start_pos, True).all(axis=1)

    esc_flag = (esc_count > 0) & field_valid

    return {
        "ok": ok,
        # n_fields stays un-zeroed on not-ok rows so the fetch-side
        # rescue can screen precisely; every consumer gates on ok
        # before reading it (materialize_gelf.py, encode_gelf_gelf_block)
        "n_fields": n_keys,
        "key_start": key_open_pos + 1, "key_end": key_close_pos,
        "val_start": jnp.where(is_string, val_start_pos + 1, val_start_pos),
        "val_end": val_end,
        "val_type": val_type,
        "key_esc": esc_flag, "val_esc": esc_flag & is_string,
    }


def decode_gelf_submit(batch, lens, sharded=None):
    """Asynchronous dispatch (pair with decode_gelf_fetch) — the gelf
    leg of the block pipeline's double buffering.  ``sharded`` swaps in
    the multi-chip mesh kernel (parallel.mesh.ShardedDecode)."""
    import jax.numpy as jnp

    # the handle carries BOTH the device arrays (for the device-encode
    # tier, no re-upload) and the caller's host arrays (so the tier-2
    # rescue in decode_gelf_fetch never pays a full-batch D2H just to
    # slice a few rescue rows)
    if sharded is not None:
        b, ln = sharded.put(batch, lens)
        return (sharded.fn(b, ln), b, ln, batch, lens)
    from .aot import decode_call

    b, ln = jnp.asarray(batch), jnp.asarray(lens)
    # zero-JIT boot: a loaded AOT artifact replaces the trace+compile
    out = decode_call("gelf", (b, ln))
    if out is None:
        out = decode_gelf_jit(b, ln)
    return (out, b, ln, batch, lens)


_FIELD_KEYS = ("key_start", "key_end", "val_start", "val_end", "val_type",
               "key_esc", "val_esc")


def decode_gelf_fetch(handle):
    """Block on a submitted decode; rows whose field count lies in
    (DEFAULT_MAX_FIELDS, RESCUE_MAX_FIELDS] re-dispatch through the
    wider tier-2 kernel so they stay on-device.  Field channels come
    back widened to RESCUE_MAX_FIELDS when tier 2 ran."""
    import numpy as np

    out, _b_dev, _ln_dev, batch, lens = handle
    host = {k: np.asarray(v) for k, v in out.items()}
    if host["key_start"].shape[1] >= RESCUE_MAX_FIELDS:
        return host
    nf = host["n_fields"]
    over = np.flatnonzero(~host["ok"] & (nf > DEFAULT_MAX_FIELDS)
                          & (nf <= RESCUE_MAX_FIELDS))

    def dispatch(sub_b, sub_l):
        out2 = decode_gelf_jit(jnp.asarray(sub_b), jnp.asarray(sub_l),
                               max_fields=RESCUE_MAX_FIELDS)
        return {k: np.asarray(v) for k, v in out2.items()}

    return rescue_refetch(host, batch, lens, over, _FIELD_KEYS, dispatch,
                          RESCUE_MAX_FIELDS)


@functools.partial(jax.jit, static_argnames=("max_fields", "demand"))
def decode_gelf_jit(batch, lens, max_fields=DEFAULT_MAX_FIELDS,
                    demand=None):
    """``demand`` (static frozenset): keep only the channels the
    consumer reads so XLA dead-code-eliminates the rest (fused
    gelf→GELF route)."""
    out = decode_gelf(batch, lens, max_fields=max_fields)
    if demand is not None:
        out = {k: v for k, v in out.items() if k in demand}
    return out
