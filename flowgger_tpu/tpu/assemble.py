"""Vectorized byte assembly: build output buffers from span tables with
numpy offset math and a native threaded gather — zero per-row Python on
the fast tier.

This is the round-2 answer to the host materialization tail: instead of
slicing/joining strings per record (~23us/row), output bytes for a whole
batch are produced by three C-speed primitives:

1. ``escape_json`` — JSON-escape an entire chunk buffer once, sparsely:
   escapable bytes (quotes, backslashes, control chars) are rare in log
   streams, so the escaped buffer is assembled from plain-run segments
   plus a 256-entry escape-sequence bank, and original→escaped position
   mapping is ``x + extra_before(x)`` answered by a binary search over
   the escape positions — O(escapes), not O(bytes), beyond one copy.
2. ``concat_segments`` — materialize an output buffer described as a
   flat list of (source offset, length) segments.  Native path: a
   threaded memcpy loop (native/flowgger_host.cpp fg_concat_segments);
   fallback: one ``np.repeat`` + fancy-index gather in int32.
3. ``decimal_segments`` — render an int array as ASCII decimal via
   fixed-width digit segments with zero-length leading-zero segments,
   so even length prefixes (syslen framing) stay columnar.

The per-record reference behavior being replicated bytewise is
``handle_line`` = decode→encode→send (line_splitter.rs:44-54) with the
merger applied by the sink (merger/mod.rs:30-32); differential tests
assert equality against the scalar encoder output.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# JSON escaping (json.encoder.encode_basestring semantics: escape
# backslash, double quote, \b \t \n \f \r shortcuts, \u00XX other ctrl)
# ---------------------------------------------------------------------------

_EXPAND = np.ones(256, dtype=np.int64)
_EXPAND[ord('"')] = 2
_EXPAND[ord("\\")] = 2
for _c in range(0x20):
    _EXPAND[_c] = 6
for _c in (0x08, 0x09, 0x0A, 0x0C, 0x0D):
    _EXPAND[_c] = 2

_NEEDS = _EXPAND != 1


def _esc_seq(b: int) -> bytes:
    if b == 0x22:
        return b'\\"'
    if b == 0x5C:
        return b"\\\\"
    shortcuts = {0x08: b"\\b", 0x09: b"\\t", 0x0A: b"\\n",
                 0x0C: b"\\f", 0x0D: b"\\r"}
    if b in shortcuts:
        return shortcuts[b]
    return ("\\u%04x" % b).encode("ascii")


_ESC_BANK = b"".join(_esc_seq(b) if _NEEDS[b] else b"" for b in range(256))
_ESC_OFF = np.zeros(256, dtype=np.int64)
_pos = 0
for _b in range(256):
    _ESC_OFF[_b] = _pos
    if _NEEDS[_b]:
        _pos += int(_EXPAND[_b])
del _pos


class EscapeMap:
    """JSON-escaped view of a chunk plus original→escaped offset map.

    ``esc``  — the escaped u8 buffer.
    ``map(x)`` — vectorized: escaped offset of original offset x (valid
    for span endpoints: escapes are byte-local so spans stay contiguous).
    """

    __slots__ = ("esc", "pos", "cum", "identity")

    def __init__(self, esc: np.ndarray, pos: Optional[np.ndarray],
                 cum: Optional[np.ndarray]):
        self.esc = esc
        self.pos = pos
        self.cum = cum
        self.identity = pos is None

    def map(self, x: np.ndarray) -> np.ndarray:
        if self.identity:
            return x.astype(np.int64, copy=False)
        k = np.searchsorted(self.pos, x, side="left")
        return x.astype(np.int64, copy=False) + self.cum[k]


def escape_json(buf: np.ndarray) -> EscapeMap:
    pos = np.flatnonzero(_NEEDS[buf])
    e = pos.size
    if e == 0:
        return EscapeMap(buf, None, None)
    widths = _EXPAND[buf[pos]]
    extra = widths - 1
    cum = np.empty(e + 1, dtype=np.int64)
    cum[0] = 0
    np.cumsum(extra, out=cum[1:])
    # alternating segments: plain run, escape sequence, plain run, ...
    nseg = 2 * e + 1
    seg_src = np.empty(nseg, dtype=np.int64)
    seg_len = np.empty(nseg, dtype=np.int64)
    plain_start = np.empty(e + 1, dtype=np.int64)
    plain_start[0] = 0
    plain_start[1:] = pos + 1
    plain_end = np.empty(e + 1, dtype=np.int64)
    plain_end[:e] = pos
    plain_end[e] = buf.size
    seg_src[0::2] = plain_start
    seg_len[0::2] = plain_end - plain_start
    seg_src[1::2] = buf.size + _ESC_OFF[buf[pos]]
    seg_len[1::2] = widths
    src = np.concatenate([buf, np.frombuffer(_ESC_BANK, dtype=np.uint8)])
    esc = concat_segments(src, seg_src, seg_len)
    return EscapeMap(esc, pos, cum)


# ---------------------------------------------------------------------------
# Segment gather
# ---------------------------------------------------------------------------

def exclusive_cumsum(x: np.ndarray) -> np.ndarray:
    out = np.empty(x.size + 1, dtype=np.int64)
    out[0] = 0
    np.cumsum(x, out=out[1:])
    return out


def concat_segments(src: np.ndarray, seg_src: np.ndarray,
                    seg_len: np.ndarray,
                    dst0: Optional[np.ndarray] = None) -> np.ndarray:
    """Concatenate ``src[seg_src[i] : seg_src[i]+seg_len[i]]`` for all i
    into one u8 buffer.  ``dst0`` is the (len+1) exclusive prefix sum of
    seg_len if the caller already computed it."""
    from .. import native

    seg_len = seg_len.astype(np.int64, copy=False)
    if dst0 is None:
        dst0 = exclusive_cumsum(seg_len)
    total = int(dst0[-1])
    out = native.concat_segments_native(src, seg_src, seg_len, dst0, total)
    if out is not None:
        return out
    # numpy fallback: one repeat + one arange + one gather, int32 when
    # the buffers allow (they do for any chunk under 2 GiB)
    if total < 2**31 and src.size < 2**31:
        shift = np.repeat(
            seg_src.astype(np.int32, copy=False) - dst0[:-1].astype(np.int32),
            seg_len)
        idx = np.arange(total, dtype=np.int32)
    else:
        shift = np.repeat(seg_src.astype(np.int64, copy=False) - dst0[:-1],
                          seg_len)
        idx = np.arange(total, dtype=np.int64)
    idx += shift
    return src[idx]


# ---------------------------------------------------------------------------
# Decimal rendering as segments
# ---------------------------------------------------------------------------

_DEC_WIDTH = 10  # covers int32 magnitudes


def decimal_segments(values: np.ndarray, digits_off: int,
                     width: int = _DEC_WIDTH
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """(seg_src, seg_len) rendering each non-negative value as ASCII
    decimal using ``width`` fixed slots per value; leading-zero slots
    get length 0 so the gather emits exactly ``str(v)``.

    ``digits_off`` is the offset of a 10-byte "0123456789" table in the
    source buffer the caller gathers from.
    """
    v = values.astype(np.int64, copy=False)
    pow10 = 10 ** np.arange(width - 1, -1, -1, dtype=np.int64)
    digs = (v[:, None] // pow10[None, :]) % 10          # [n, W]
    # significant from the first nonzero (last slot always significant)
    sig = np.cumsum(digs != 0, axis=1) > 0
    sig[:, -1] = True
    seg_src = digits_off + digs.reshape(-1)
    seg_len = sig.astype(np.int64).reshape(-1)
    return seg_src, seg_len


def count_in_spans(cum: np.ndarray, a: np.ndarray, b: np.ndarray):
    """Occurrences within [a, b) given an inclusive prefix-count.
    Indices are clipped: callers mask out invalid spans afterwards, but
    padded/kernel-flagged rows may carry out-of-range placeholders.
    An empty source buffer (all-empty messages) counts as zero
    everywhere — np.where evaluates both branches, so the clip alone
    cannot protect indexing into a zero-length array."""
    if cum.size == 0:
        return np.zeros(np.broadcast(a, b).shape, dtype=np.int64)
    top = cum.size - 1
    hi = np.where(b > 0, cum[np.clip(b - 1, 0, top)], 0)
    lo = np.where(a > 0, cum[np.clip(a - 1, 0, top)], 0)
    return hi - lo


def syslen_prefix_segments(body_lens: np.ndarray, digits_base: int
                           ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-row syslen framing prefix ``"{body_len} "`` as 2D segment
    columns: (src2d [R, W+1], len2d [R, W+1], prefix_lens [R]).  Callers
    hstack their body columns and ravel; ``digits_base`` is the offset
    of a ``b"0123456789 "`` table in the gather source.  The single
    place the syslen prefix layout lives (syslen_merger.rs:14-31)."""
    r = body_lens.size
    dsrc, dlen = decimal_segments(body_lens, digits_base)
    src2 = np.empty((r, _DEC_WIDTH + 1), dtype=np.int64)
    len2 = np.empty((r, _DEC_WIDTH + 1), dtype=np.int64)
    src2[:, :_DEC_WIDTH] = dsrc.reshape(r, _DEC_WIDTH)
    len2[:, :_DEC_WIDTH] = dlen.reshape(r, _DEC_WIDTH)
    src2[:, _DEC_WIDTH] = digits_base + 10  # the space
    len2[:, _DEC_WIDTH] = 1
    return src2, len2, len2.sum(axis=1)


def build_source(*parts: bytes) -> Tuple[np.ndarray, List[int]]:
    """Concatenate byte strings into one u8 source array; returns the
    array and each part's base offset."""
    offs = []
    pos = 0
    for p in parts:
        offs.append(pos)
        pos += len(p)
    buf = np.frombuffer(b"".join(parts), dtype=np.uint8)
    return buf, offs
