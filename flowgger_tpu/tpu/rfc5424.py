r"""Columnar RFC5424 decoder: the TPU-native replacement for the
reference's per-line parser (rfc5424_decoder.rs:17-242).

Grammar recap (scalar spec: flowgger_tpu/decoders/rfc5424.py):
``[BOM]<PRI>1 TS HOST APP PROCID MSGID ( - | [id k="v" ...]+ ) [msg]``

Design rule learned from TPU profiling: **no gathers**.  XLA lowers
dynamic gathers (``take_along_axis``/``jnp.take``) to near-serial code on
TPU (measured ~650ms for one [N,L] pack gather at N=256k vs ~5ms for a
full cumulative scan), so every "value at computed position" here is
expressed with primitives the VPU executes wide:

- masked min-reductions ``min(where(mask & ord==k, iota<<SHIFT|payload))``
  extract the k-th delimiter position *and* its local context in one
  reduction — context bits (preceding byte class, run starts, escape
  counts) are packed into the low bits of the minimized value;
- value-dependent lookback ("the byte before this name run") rides along
  a ``cummax`` of ``pos<<8 | byte`` over non-name positions;
- fixed-layout fields (PRI digits, the RFC3339 timestamp) are parsed by
  weighting each byte with a function of its *field-relative offset*
  ``r = iota - field_start`` and summing — never by slicing a window.

Second rule, from live-chip profiling: **scans are the cost model** —
one [1M,256] i32 cumsum/cummax costs ~22ms on v5e while a group of
sibling masked reductions fuses to ~10ms, so the decode runs on two
scan channels, both lowered as MXU matmuls against a triangular ones
matrix (see _scan_ordinals): spaces+quotes packed into one, brackets in
the other.  Backslash-run parity is a bounded bit-packed shifted-AND
ladder (no scan), and the name lookback is per-pair fused masked
max-reductions instead of a cummax.

Everything else is elementwise/reduction arithmetic: prefix parity of
real quotes for in/out-of-value classification, Hinnant civil-date math
in int32 (the identical formula to utils/timeparse.py so the final f64
is bit-equal).

Any deviation from the fast-path grammar (bogus quotes, empty PRI, nil
timestamps, >max_sd blocks, >max_pairs pairs...) sets ``ok=False`` for
that row only — the host re-runs the scalar oracle on it, keeping
observable output byte-identical (differential-tested in
tests/test_tpu_rfc5424.py).

Returned spans are byte offsets relative to each row.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp

DEFAULT_MAX_LEN = 512
DEFAULT_MAX_SD = 4
# two-tier pair budget: the common-case kernel extracts 6 pairs (every
# extract channel costs ceil(max_pairs/3) reduction passes, so a small
# budget is most of the win of the round-2 pass-count rework); rows with
# more pairs re-dispatch to a wider second-tier kernel compiled lazily,
# and only rows beyond the rescue budget fall back to the scalar oracle
DEFAULT_MAX_PAIRS = 6
RESCUE_MAX_PAIRS = 16
# backslash runs are resolved by a bounded shifted-AND ladder instead of
# a scan; a run of >= ESC_RUN_CAP backslashes feeding a quote sends the
# row to the scalar oracle (exact semantics preserved via fallback)
ESC_RUN_CAP = 16

_I32 = jnp.int32


def _min_where(mask, packed, notfound, manual: bool = False):
    """Per-row min of ``packed`` where mask, else ``notfound``."""
    return _row_min(jnp.where(mask, packed, notfound), manual)


def _at(iota, pos, values, default=0):
    """values[n, pos[n]] as a masked reduction (no gather): pos is [N].
    (The rfc5424 kernel folds its own uses into packed sum words; the
    ltsv/rfc3164/gelf kernels still use this directly.)"""
    hit = iota == pos[:, None]
    return jnp.max(jnp.where(hit, values, default), axis=1)


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _days_in_month(y, m):
    # arithmetic form (no table lookup — gathers are banned, and Mosaic
    # can't lower them anyway): 31 for odd months through July and even
    # months from August, 30 otherwise, February special-cased
    is31 = jnp.where(m >= 8, (m % 2) == 0, (m % 2) == 1)
    base = jnp.where(is31, 31, 30)
    leap = (y % 4 == 0) & ((y % 100 != 0) | (y % 400 == 0))
    return jnp.where(m == 2, jnp.where(leap, 29, 28), base)


def _shift_right(arr, k, fill):
    """arr shifted right by k along axis 1 (prepending fill)."""
    return jnp.pad(arr[:, :-k], ((0, 0), (k, 0)), constant_values=fill)


def _shift_left(arr, k, fill):
    return jnp.pad(arr[:, k:], ((0, 0), (0, k)), constant_values=fill)


def _cumsum(x, impl: str):
    """Inclusive prefix sum along axis 1.  ``impl='manual'`` uses a
    Hillis–Steele log-shift ladder built only from pad/slice/add, which
    Mosaic (Pallas TPU) lowers where lax's scan-based cumsum cannot."""
    if impl in ("lax", "mm"):
        return jnp.cumsum(x, axis=1)
    x = x.astype(_I32)
    L = x.shape[1]
    k = 1
    while k < L:
        x = x + _shift_right(x, k, 0)
        k <<= 1
    return x


def _scan_ordinals(channels, impl: str):
    """Inclusive prefix sums (ordinals) of bool channels along axis 1.

    ``impl='mm'`` (the TPU path) computes each scan as a matmul against
    a triangular ones matrix — the MXU runs [1M,256]@[256,256] in ~1ms
    of FLOPs where a VPU log-shift cumsum pays ~8 materialized [N,L]
    passes (measured 8.8ms vs 21.8ms on v5e; two channels share one f32
    matmul via slot packing, 9.5ms).

    Exactness of the packed f32 path: channels MUST be pairwise
    disjoint (at most one set per position) — element values are then
    {0, 1, 2**bits}, all exactly representable even after the TPU's
    default-precision bf16 input truncation, and the MXU's f32
    accumulator keeps sums <= 2**(2*bits) <= 2**24 exact.  Packing
    applies for bits <= 12, i.e. L <= 4094; wider geometries use one
    int8 matmul per channel (i32 accumulate, exact for any mask).
    Other impls fall back to bit-packed i32 cumsums."""
    L = channels[0].shape[1]
    bits = max(10, int(L + 1).bit_length())
    # ordinal channels are re-read by every downstream extraction word,
    # so they come back as int16 where L allows (ordinals are bounded by
    # L, and the guard keeps L < 32000 < 2**15-1) — halving the HBM
    # bytes of the hottest reads in the kernel.  The 'manual'
    # (Pallas/Mosaic) path stays int32: 16-bit vector support inside
    # the block kernel is not worth the risk.
    out_t = jnp.int16 if (impl != "manual" and L < 32000) else _I32
    if impl != "mm":
        mask = (1 << bits) - 1
        per = max(1, 31 // bits)
        outs = []
        for base in range(0, len(channels), per):
            grp = channels[base:base + per]
            word = grp[0].astype(_I32)
            for s, ch in enumerate(grp[1:], 1):
                word = word + (ch.astype(_I32) << (bits * s))
            scanned = _cumsum(word, impl)
            for s in range(len(grp)):
                outs.append(((scanned >> (bits * s)) & mask).astype(out_t))
        return outs
    iota_l = jnp.arange(L, dtype=_I32)
    tri_f = (iota_l[:, None] <= iota_l[None, :]).astype(jnp.float32)
    tri_i = tri_f.astype(jnp.int8)
    pack2 = 2 * bits <= 24
    outs = []
    base = 0
    while base < len(channels):
        if pack2 and base + 1 < len(channels):
            packed = (channels[base].astype(jnp.float32)
                      + channels[base + 1].astype(jnp.float32) * float(1 << bits))
            s = jax.lax.dot_general(
                packed, tri_f, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(_I32)
            outs.append((s & ((1 << bits) - 1)).astype(out_t))
            outs.append((s >> bits).astype(out_t))
            base += 2
        else:
            s = jax.lax.dot_general(
                channels[base].astype(jnp.int8), tri_i,
                (((1,), (0,)), ((), ())), preferred_element_type=_I32)
            outs.append(s.astype(out_t))
            base += 1
    return outs


def _cummax(x, impl: str):
    if impl in ("lax", "mm"):
        return jax.lax.cummax(x, axis=1)
    L = x.shape[1]
    k = 1
    neg = jnp.iinfo(x.dtype).min
    while k < L:
        x = jnp.maximum(x, _shift_right(x, k, neg))
        k <<= 1
    return x


# ---- Mosaic-safe row reductions -------------------------------------
# Mosaic (this jax's Pallas TPU lowering) implements float but not
# integer/bool reductions, so the manual path computes every axis-1
# reduction as a log-shift ladder (elementwise adds/min/max over the
# VMEM-resident plane) and reads column 0.  The XLA path keeps the
# native reductions.

def _row_sum(x, manual: bool = False):
    if not manual:
        return jnp.sum(x, axis=1)
    x = x.astype(_I32)
    L = x.shape[1]
    k = 1
    while k < L:
        x = x + _shift_left(x, k, 0)
        k <<= 1
    return x[:, 0]


def _row_max(x, manual: bool = False):
    if not manual:
        return jnp.max(x, axis=1)
    x = x.astype(_I32)
    L = x.shape[1]
    k = 1
    neg = jnp.iinfo(_I32).min
    while k < L:
        x = jnp.maximum(x, _shift_left(x, k, neg))
        k <<= 1
    return x[:, 0]


def _row_min(x, manual: bool = False):
    if not manual:
        return jnp.min(x, axis=1)
    x = x.astype(_I32)
    L = x.shape[1]
    k = 1
    pos = jnp.iinfo(_I32).max
    while k < L:
        x = jnp.minimum(x, _shift_left(x, k, pos))
        k <<= 1
    return x[:, 0]


def _row_any(x, manual: bool = False):
    if not manual:
        return jnp.any(x, axis=1)
    return _row_max(x.astype(_I32), True) != 0


def _row_all(x, manual: bool = False):
    if not manual:
        return jnp.all(x, axis=1)
    return ~_row_any(~x, True)


def _bitpack32(plane):
    """[N, L] bool -> [N, ceil(L/32)] uint32, bit j of word w = plane[:,
    32w+j].  The reshape/broadcast form beats 32 strided slices on TPU:
    a stride-32 minor-axis slice still reads every 128-lane tile, so the
    slice formulation pays ~32 reads of the plane (measured +13ms on the
    full kernel)."""
    N, L = plane.shape
    W = (L + 31) // 32
    if W * 32 != L:
        plane = jnp.pad(plane, ((0, 0), (0, W * 32 - L)))
    lane = jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(
        plane.reshape(N, W, 32).astype(jnp.uint32) << lane[None, None, :],
        axis=2)


def _bitunpack32(words, L):
    """Inverse of _bitpack32: [N, W] uint32 -> [N, L] bool."""
    N, W = words.shape
    lane = jnp.arange(32, dtype=jnp.uint32)
    b = ((words[:, :, None] >> lane[None, None, :]) & 1) != 0
    return b.reshape(N, W * 32)[:, :L]


def _esc_parity(is_bs, impl: str):
    """Backslash-run parity without a scan: ``escaped[i]`` <=> the run of
    backslashes ending at ``i-1`` has odd length (exact for runs <
    ESC_RUN_CAP).

    Returns a 3-tuple ``(escaped, cap_plane, cap_words)`` — exactly one
    of the cap channels is non-None, by path:
    - manual (Pallas/Mosaic): ``cap_plane`` is an [N, L] bool plane of
      positions whose run reached the cap; ``cap_words`` is None;
    - XLA: ``cap_words`` is the [N, ceil(L/32)] packed uint32 stream
      (same bit layout as ``_bitpack32``) for the caller to AND against
      a packed quote plane; ``cap_plane`` is None.

    The ladder XORs nested run-indicators ``a_k = bs at i-1..i-k``.  On
    the XLA path the [N, L] bool planes are bit-packed into [N, L/32]
    uint32 lanes first — the 15 shifted ANDs then touch 1/32nd of the
    bytes.  The Pallas path (`impl='manual'`) keeps the plane form:
    Mosaic has no cheap lane-crossing reshape."""
    if impl == "manual":
        a_k = _shift_right(is_bs, 1, False)
        escaped = a_k
        for k in range(2, ESC_RUN_CAP):
            a_k = a_k & _shift_right(is_bs, k, False)
            escaped = escaped ^ a_k
        cap_hit = a_k & _shift_right(is_bs, ESC_RUN_CAP, False)
        return escaped, cap_hit, None
    N, L = is_bs.shape
    packed = _bitpack32(is_bs)

    def sr(w, k):
        # shift right in *position* space by k (1 <= k <= 31): bit j of
        # word w comes from bit j-k, borrowing the top of word w-1
        prev = jnp.pad(w[:, :-1], ((0, 0), (1, 0)))
        return (w << jnp.uint32(k)) | (prev >> jnp.uint32(32 - k))

    a_k = sr(packed, 1)
    esc = a_k
    for k in range(2, ESC_RUN_CAP):
        a_k = a_k & sr(packed, k)
        esc = esc ^ a_k
    assert ESC_RUN_CAP < 32  # sr() handles shifts of 1..31 only
    cap = a_k & sr(packed, ESC_RUN_CAP)

    return _bitunpack32(esc, L), None, cap


def _slot_geometry(L: int):
    """Slot geometry for the bit-packed sum extraction: each i32 word
    carries as many (value+1) slots as fit in 30 bits, with slot width
    sized to the packed byte axis — 10 bits / 3 slots for the common
    L <= 1022, widening automatically for long-record configs
    (tpu_max_line_len)."""
    slot_bits = max(10, int(L + 1).bit_length())
    slots = max(1, 30 // slot_bits)
    return slot_bits, slots, (1 << slot_bits) - 1


def extract_by_ord(mask, ord_, value, K, fill, extract_impl="sum",
                   slot_bits=None, manual: bool = False):
    """out[n, k] = ``value`` at the position with ordinal k+1 (masked),
    else ``fill``.  The ordinal channel must hit each ordinal at most
    once per row.  Shared by every format kernel.

    - ``"sum"``: bit-packed masked sums — few wide passes, no scatter;
      the TPU path (XLA:TPU lowers scatter/gather near-serially);
    - ``"scatter"``: one scatter-min per channel — the CPU path.

    ``slot_bits`` overrides the position-sized slot geometry when the
    caller packs several small fields into one value (fewer slots per
    word, but fewer reduction words for the channel group overall)."""
    N, L = mask.shape
    if slot_bits is None:
        slot_bits, slots, slot_mask = _slot_geometry(L)
    else:
        slots = max(1, 30 // slot_bits)
        slot_mask = (1 << slot_bits) - 1
    if extract_impl == "scatter":
        # ord_ may be parity-derived and go negative before its zone;
        # gate on >= 1 so .at[] never wraps a negative column index
        big = jnp.iinfo(jnp.int32).max
        hit = mask & (ord_ >= 1)
        rows = jax.lax.broadcasted_iota(_I32, mask.shape, 0)
        cols = jnp.where(hit, jnp.minimum(ord_ - 1, K), K)
        init = jnp.full((N, K + 1), big, _I32)
        out = init.at[rows, cols].min(
            jnp.where(hit, value.astype(_I32), big))[:, :K]
        return jnp.where(out == big, fill, out)
    cols = []
    v1 = jnp.clip(value, 0, slot_mask - 1) + 1
    for base in range(0, K, slots):
        acc = jnp.where(mask & (ord_ == base + 1), v1, 0)
        for s in range(1, slots):
            if base + s < K:
                acc = acc + (jnp.where(mask & (ord_ == base + 1 + s),
                                       v1, 0) << (slot_bits * s))
        word = _row_sum(acc, manual)
        for slot in range(min(slots, K - base)):
            v = (word >> (slot_bits * slot)) & slot_mask
            cols.append(jnp.where(v == 0, fill, v - 1))
    return jnp.stack(cols, axis=1)


def extract_counts_by_ord(mask, ord_, K, extract_impl="sum",
                          manual: bool = False):
    """out[n, k] = number of masked positions with ordinal k+1 — an
    *accumulating* variant of extract_by_ord (the mask may hit many
    positions per ordinal; each per-word slot's total is bounded by
    L < 2**slot_bits, so slots cannot carry)."""
    N, L = mask.shape
    slot_bits, slots, slot_mask = _slot_geometry(L)
    if extract_impl == "scatter":
        hit = mask & (ord_ >= 1)
        rows = jax.lax.broadcasted_iota(_I32, mask.shape, 0)
        cols = jnp.where(hit, jnp.minimum(ord_ - 1, K), K)
        init = jnp.zeros((N, K + 1), _I32)
        return init.at[rows, cols].add(hit.astype(_I32))[:, :K]
    cols = []
    for base in range(0, K, slots):
        acc = jnp.where(mask & (ord_ == base + 1), 1, 0)
        for s in range(1, slots):
            if base + s < K:
                acc = acc + (jnp.where(mask & (ord_ == base + 1 + s),
                                       1, 0) << (slot_bits * s))
        word = _row_sum(acc, manual)
        for slot in range(min(slots, K - base)):
            cols.append((word >> (slot_bits * slot)) & slot_mask)
    return jnp.stack(cols, axis=1)


def decode_rfc5424(batch: jnp.ndarray, lens: jnp.ndarray,
                   max_sd: int = DEFAULT_MAX_SD,
                   max_pairs: int = DEFAULT_MAX_PAIRS,
                   scan_impl: str = None,
                   extract_impl: str = "sum") -> Dict[str, jnp.ndarray]:
    """Decode a packed ``[N, L]`` uint8 batch (jit/pjit/shard_map safe).

    ``scan_impl`` picks the prefix-scan lowering: ``"mm"`` (MXU matmul
    against a triangular ones matrix — the TPU default, ~2.4x a VPU
    cumsum), ``"lax"`` (jnp.cumsum — the CPU default), or ``"manual"``
    (a pad/slice/add log-shift ladder Mosaic can lower, so the same body
    runs inside the Pallas block kernel).  None resolves by backend.

    ``extract_impl`` picks how k-th-delimiter values come out:
    - ``"sum"``: bit-packed masked sums — few wide passes, no scatter;
      the TPU path (XLA:TPU lowers scatter/gather near-serially);
    - ``"scatter"``: one scatter-min per channel — the CPU path, where
      scatters are cheap and the [N,L] reduction passes are what hurts
      (~70x faster than "sum" on the CPU backend).
    Identical outputs; differential-tested against each other."""
    if scan_impl is None:
        scan_impl = best_scan_impl()
    manual = scan_impl == "manual"
    N, L = batch.shape

    def _extract(mask, ord_, value, K, fill):
        return extract_by_ord(mask, ord_, value, K, fill, extract_impl,
                              manual=manual)

    def _extract_counts(mask, ord_, K):
        return extract_counts_by_ord(mask, ord_, K, extract_impl,
                                     manual=manual)
    lens = lens.astype(_I32)
    iota = jax.lax.broadcasted_iota(_I32, (N, L), 1)
    bu = batch  # uint8 view for comparisons (half the HBM traffic of i32)
    valid = iota < lens[:, None]
    # fill follows the batch dtype: u8 on the jnp tier, i32 under the
    # Pallas kernels (Mosaic cannot carry u8 constants)
    bb = jnp.where(valid, bu, jnp.asarray(0, bu.dtype))
    # uint8 byte plane: every mask read touches 1 byte/position; sites
    # that need arithmetic widen inside their own fusion (free VPU work
    # vs doubled HBM traffic for a materialized int16 plane)
    is_digit = (bb >= 48) & (bb <= 57)
    dig = bb.astype(_I32) - 48

    # ---- BOM (rs:57-72) --------------------------------------------------
    bom = (
        (lens >= 3)
        & (bb[:, 0] == 0xEF) & (bb[:, 1] == 0xBB) & (bb[:, 2] == 0xBF)
    )
    start0 = jnp.where(bom, 3, 0).astype(_I32)
    first_ch = jnp.where(bom, bb[:, 3] if L > 3 else 0, bb[:, 0])
    ok = first_ch == ord("<")

    # ---- scan budget ------------------------------------------------------
    # Scans are the kernel's dominant cost on TPU (measured ~22ms per
    # [1M,256] i32 cumsum/cummax vs ~10ms for a group of fused masked
    # reductions — tools/profile_kernel.py / profile_r3.py), so the
    # whole decode runs on TWO scan channels, both MXU matmuls:
    #   1: ordinals of (is_sp, real_q) — one packed scan (space + quote)
    #   2: ordinals of rbrack — its mask needs stage 1's quote parity
    # The backslash-parity cummax is replaced by a bounded shifted-AND
    # ladder (exact for runs < ESC_RUN_CAP; longer runs before a quote
    # fall back to the scalar oracle); open/close-quote ordinals are
    # parity-DERIVED from scan 1 (zone quotes strictly alternate), with
    # their zone from a min-reduction SD terminator instead of the
    # chain-walk sd_end so no scan has to wait on the bracket chain; the
    # name lookback that used to be scan 3 (a cummax) is now max_pairs
    # fused masked max-reductions keyed on the extracted open-quote
    # positions (see the pair-extraction section).

    # ---- escape parity (bounded bit-packed ladder, no scan) --------------
    # escaped[i] <=> the backslash run ending at i-1 has odd length
    # (exact while run < ESC_RUN_CAP; cap hits feeding a quote send the
    # row to the scalar oracle — semantics preserved via fallback).
    is_bs = (bb == 92) & valid
    escaped, cap_plane, cap_words = _esc_parity(is_bs, scan_impl)

    # ---- stage B scan: space ordinals + quote parity ----------------------
    is_sp = (bb == 32) & valid
    quote = (bb == ord('"')) & valid
    real_q_all = quote & ~escaped
    if cap_plane is not None:
        viol2d = cap_plane & quote
    else:
        # packed-ladder path: the cap-hit stream never leaves bit-packed
        # form — AND against the packed quote plane and fold the row-wise
        # "a quote consumed an unknown run parity" violation straight
        # into ok (no [N, L] unpack for a channel consumed row-wise)
        viol2d = jnp.zeros_like(quote)
        ok &= ~jnp.any((cap_words & _bitpack32(quote)) != 0, axis=1)
    sp_ord, q_incl_all = _scan_ordinals([is_sp, real_q_all], scan_impl)
    sp = _extract(is_sp, sp_ord, iota, 6, L)  # [N, 6]
    ok &= sp[:, 5] < L
    f_start = jnp.concatenate([start0[:, None], sp + 1], axis=1)  # [N,7]
    f_end = jnp.concatenate([sp, lens[:, None]], axis=1)          # [N,7]

    # ---- PRI + version (rs:74-92) ---------------------------------------
    gt = _min_where((bb == ord(">")) & (iota > start0[:, None]) & valid,
                    iota, L, manual)
    ndig = gt - start0 - 1
    ok &= (gt < f_end[:, 0]) & (ndig >= 1) & (ndig <= 3)
    # digits weighted by 10^(gt-1-iota); non-digit in range -> violation
    e = gt[:, None] - 1 - iota
    pri_zone = (iota > start0[:, None]) & (iota < gt[:, None])
    w_pri = jnp.where(e == 0, 1, jnp.where(e == 1, 10, jnp.where(e == 2, 100, 0)))
    viol2d |= pri_zone & ~is_digit   # accumulated; reduced once at the end

    # ---- packed field sums ------------------------------------------------
    # every fixed-layout numeric field and single-position structural flag
    # comes out of three bit-packed sum reductions instead of one pass
    # each: component sums are bounded by construction (2-digit fields
    # <= 99, year <= 9999, PRI <= 999, flags are unique-position bits),
    # so the packed spans cannot carry into each other.
    ts_s = f_start[:, 1]
    tlen = f_end[:, 1] - ts_s
    r = iota - ts_s[:, None]
    in_ts = (r >= 0) & (r < tlen[:, None])
    dz = jnp.where(in_ts, dig, 0)
    rest_s = f_start[:, 6]

    # word1: year[0:14] month[14:21] day[21:28] has_frac[28] version[29]
    w1 = (
        dz * ((r == 0) * 1000 + (r == 1) * 100 + (r == 2) * 10 + (r == 3))
        + (dz * ((r == 5) * 10 + (r == 6)) << 14)
        + (dz * ((r == 8) * 10 + (r == 9)) << 21)
        + (jnp.where(in_ts & (r == 19) & (bb == ord(".")), 1, 0) << 28)
        + (jnp.where((iota == gt[:, None] + 1) & (bb == ord("1")), 1, 0) << 29)
    )
    word1 = _row_sum(w1, manual)
    year = word1 & 0x3FFF
    month = (word1 >> 14) & 0x7F
    day = (word1 >> 21) & 0x7F
    has_frac = ((word1 >> 28) & 1) == 1
    ver_ok = ((word1 >> 29) & 1) == 1

    # word2: hour[0:7] minute[7:14] sec[14:21] pri[21:31]
    w2 = (
        dz * ((r == 11) * 10 + (r == 12))
        + (dz * ((r == 14) * 10 + (r == 15)) << 7)
        + (dz * ((r == 17) * 10 + (r == 18)) << 14)
        + (jnp.where(pri_zone, dig * w_pri, 0) << 21)
    )
    word2 = _row_sum(w2, manual)
    hour = word2 & 0x7F
    minute = (word2 >> 7) & 0x7F
    sec = (word2 >> 14) & 0x7F
    pri = word2 >> 21

    ok &= pri <= 255
    ok &= ver_ok & (f_end[:, 0] == gt + 2)
    facility = pri >> 3
    severity = pri & 7

    digit_off = ((r >= 0) & (r <= 18) &
                 (r != 4) & (r != 7) & (r != 10) & (r != 13) & (r != 16))
    viol2d |= in_ts & digit_off & ~is_digit
    viol2d |= in_ts & ((r == 4) | (r == 7)) & (bb != ord("-"))
    viol2d |= in_ts & (r == 10) & (bb != ord("T")) & (bb != ord("t"))
    viol2d |= in_ts & ((r == 13) | (r == 16)) & (bb != ord(":"))
    ok &= tlen >= 20
    ok &= (month >= 1) & (month <= 12) & (day >= 1) & (day <= _days_in_month(year, month))
    ok &= (hour <= 23) & (minute <= 59) & (sec <= 59)

    # fractional seconds: run of digits from r==20
    rd = r - 20
    # first non-digit offset in [0, 10) == run length (capped)
    frac_run = _min_where(in_ts & (rd >= 0) & (rd < 10) & ~is_digit,
                          rd, 10, manual)
    frac_run = jnp.minimum(frac_run, jnp.maximum(tlen - 20, 0))
    frac_len = jnp.where(has_frac, frac_run, 0)
    ok &= jnp.where(has_frac, (frac_len >= 1) & (frac_len <= 9), True)
    w_frac = (
        (rd == 0) * 100000000 + (rd == 1) * 10000000 + (rd == 2) * 1000000
        + (rd == 3) * 100000 + (rd == 4) * 10000 + (rd == 5) * 1000
        + (rd == 6) * 100 + (rd == 7) * 10 + (rd == 8) * 1
    )
    in_frac = in_ts & (rd >= 0) & (rd < frac_len[:, None])
    nanos = _row_sum(jnp.where(in_frac, dig * w_frac, 0), manual)

    # offset zone at r2 = r - opos; word3 packs its digits, the
    # remaining single-position flags, and (for the common L <= 1023
    # geometry) the high-byte count that used to be its own reduction:
    # oh[0:7] om[7:14] zulu[14] plus[15] minus[16] dash[17] sd_open[18]
    # high_count[19:29]
    opos = jnp.where(has_frac, 20 + frac_len, 19)
    r2 = r - opos[:, None]
    at_off = in_ts & (r2 == 0)
    at_rest = iota == rest_s[:, None]
    pack_high = L <= 1023  # count <= L must fit bits [19:29)
    w3 = (
        dz * ((r2 == 1) * 10 + (r2 == 2))
        + (dz * ((r2 == 4) * 10 + (r2 == 5)) << 7)
        + (jnp.where(at_off & ((bb == ord("Z")) | (bb == ord("z"))), 1, 0) << 14)
        + (jnp.where(at_off & (bb == ord("+")), 1, 0) << 15)
        + (jnp.where(at_off & (bb == ord("-")), 1, 0) << 16)
        + (jnp.where(at_rest & (bb == ord("-")), 1, 0) << 17)
        + (jnp.where(at_rest & (bb == ord("[")), 1, 0) << 18)
    )
    if pack_high:
        w3 = w3 + (jnp.where((bb >= 128) & valid, 1, 0) << 19)
    word3 = _row_sum(w3, manual)
    oh = word3 & 0x7F
    om = (word3 >> 7) & 0x7F
    is_zulu = ((word3 >> 14) & 1) == 1
    neg_off = ((word3 >> 16) & 1) == 1
    is_num_off = (((word3 >> 15) & 3) != 0)
    is_dash = ((word3 >> 17) & 1) == 1
    is_sd = ((word3 >> 18) & 1) == 1

    ok &= is_zulu | is_num_off
    ok &= jnp.where(is_zulu, tlen == opos + 1, True)
    off_dig = (r2 == 1) | (r2 == 2) | (r2 == 4) | (r2 == 5)
    viol2d |= in_ts & off_dig & ~is_digit & is_num_off[:, None]
    viol2d |= in_ts & (r2 == 3) & (bb != ord(":")) & is_num_off[:, None]
    ok &= jnp.where(is_num_off,
                    (tlen == opos + 6) & (oh <= 23) & (om <= 59), True)
    off_secs = jnp.where(is_num_off,
                         jnp.where(neg_off, -1, 1) * (oh * 3600 + om * 60),
                         0)
    days = _days_from_civil(year, month, day)
    sod = hour * 3600 + minute * 60 + sec

    # ---- structured data (field 6 / "rest") ------------------------------
    ok &= rest_s < lens
    ok &= is_dash | is_sd

    in_rest = (iota >= rest_s[:, None]) & valid

    # quote parity relative to the rest zone: stage B counted *all* real
    # quotes (header fields may legally contain '"'); subtracting the
    # running count at rest_s restores the in-rest-only ordinals the
    # grammar needs — one fused reduction instead of a second scan.
    q_before_rest = _row_max(
        jnp.where(valid & (iota < rest_s[:, None]), q_incl_all, 0), manual)
    q_excl = (q_incl_all - real_q_all.astype(q_incl_all.dtype)
              - q_before_rest[:, None])
    real_q = real_q_all & in_rest
    outside = (q_excl & 1) == 0
    open_q = real_q & outside
    close_q = real_q & ~outside

    prev_bb = _shift_right(bb, 1, 0)
    next_bb = _shift_left(bb, 1, 0)
    # name characters: printable 33..126 except ' " = ]'  (rs:175-179)
    is_name = (
        (bb >= 33) & (bb <= 126)
        & (bb != 34) & (bb != 61) & (bb != 93)
    )

    # structural ']' chain with payload bits:
    #   bit0: legal terminator (prev is ' ' or closing quote)
    #   bit1: next is '['   bit2: next is ' '
    prev_closeq = _shift_right(close_q, 1, False)
    rbrack = (bb == ord("]")) & outside & in_rest
    next_valid = _shift_left(valid, 1, False)
    rb_payload = (
        ((prev_bb == 32) | prev_closeq).astype(_I32)
        + ((next_bb == ord("[")) & next_valid).astype(_I32) * 2
        + ((next_bb == 32) & next_valid).astype(_I32) * 4
    )

    # ---- stage C scan: bracket + pair ordinals ---------------------------
    # brackets need a real scan (their mask depends on quote parity), but
    # open/close-quote ordinals come free from the stage-B parity: zone
    # quotes strictly alternate, so the j-th rest-quote (j = q_excl + 1)
    # is open iff q_excl is even, with oq_ord = q_excl//2 + 1 at opens,
    # cq_ord = (q_excl+1)//2 at closes — and at value-interior positions
    # (q_excl odd) the enclosing pair is (q_excl+1)//2, which is what the
    # escape-count attribution below needs.
    (rb_ord,) = _scan_ordinals([rbrack], scan_impl)
    oq_ord = (q_excl >> 1) + 1
    cq_ord = (q_excl + 1) >> 1
    # pos and payload flags ride one packed value (pos<<3 | flags, 12-bit
    # slots): 3 reduction words for the ']' chain instead of 2+2
    rb_sb = (((L << 3) | 7) + 1).bit_length()
    rb_word = extract_by_ord(rbrack, rb_ord, (iota << 3) | rb_payload,
                             max_sd + 1, L << 3, extract_impl,
                             slot_bits=rb_sb, manual=manual)
    rb_pos = rb_word >> 3
    rb_flags = rb_word & 7
    rb_found = rb_pos < L

    # SD terminator for the pair-ordinal zone, derived from the
    # extracted ']' columns instead of a dedicated [N, L] min-reduction:
    # the first structural ']' followed by a space or EOL.  On rows that
    # pass the chain checks below this equals the chain-walk sd_end
    # (every earlier chain ']' is followed by '[').  Rows whose first
    # terminator lies beyond the max_sd+1 extracted brackets always fail
    # the sd_count / end-flags checks below and fall back, so the
    # truncated view never changes an accepted row's zone.
    term_col = rb_found & (((rb_flags & 4) != 0)
                           | (rb_pos == (lens - 1)[:, None]))
    sd_end_zone = _row_min(jnp.where(term_col, rb_pos, L), manual)
    zone_c = in_rest & (iota <= sd_end_zone[:, None]) & is_sd[:, None]
    oq_mask = open_q & zone_c
    cq_mask = close_q & zone_c

    # running AND over the (small, static) block axis
    chain_alive = ((rb_flags[:, :max_sd] & 2) != 0) & rb_found[:, :max_sd]
    sd_count_raw = jnp.ones_like(lens)
    alive = chain_alive[:, 0]
    for k in range(max_sd):
        sd_count_raw = sd_count_raw + alive.astype(_I32)
        if k + 1 < max_sd:
            alive = alive & chain_alive[:, k + 1]
    sd_count = jnp.where(is_sd, sd_count_raw, 0)
    # sd_end / flags of the terminating ']' via a small where-chain
    last_idx = jnp.clip(sd_count - 1, 0, max_sd)
    sd_end = rb_pos[:, 0]
    end_flags = rb_flags[:, 0]
    for k in range(1, max_sd + 1):
        sel = last_idx == k
        sd_end = jnp.where(sel, rb_pos[:, k], sd_end)
        end_flags = jnp.where(sel, rb_flags[:, k], end_flags)
    ok &= jnp.where(is_sd, (sd_count_raw <= max_sd) & (sd_end < L), True)

    blk_start = jnp.concatenate(
        [rest_s[:, None], rb_pos[:, :max_sd - 1] + 1], axis=1) if max_sd > 1 \
        else rest_s[:, None]
    blk_idx_valid = (jnp.arange(max_sd, dtype=_I32)[None, :]
                     < sd_count[:, None])
    blk_rb = rb_pos[:, :max_sd]

    # every block's ']' must be a legal terminator
    rb_legal = (rb_flags[:, :max_sd] & 1) != 0
    ok &= jnp.where(is_sd,
                    _row_all(jnp.where(blk_idx_valid, rb_legal, True),
                             manual), True)

    # sd_id span per block: blk_start+1 .. first space (must precede ']').
    # The first space of block k is the only structural space there not
    # preceded by a close quote or another space, and its inclusive
    # bracket ordinal is k-1 — so all max_sd sid_end channels come out
    # of one packed-sum extraction instead of per-block [N, L]
    # min-reductions.  Multi-hit ordinals only occur on rows already
    # flagged by the name-run violations above (they fall back), where
    # the old per-block first-space answer was equally meaningless.
    sid_start = blk_start + 1
    prev_sp = _shift_right(is_sp, 1, False)
    sid_sp_mask = is_sp & outside & zone_c & ~prev_closeq & ~prev_sp
    sid_end = _extract(sid_sp_mask, rb_ord + 1, iota, max_sd, L)
    ok &= jnp.where(is_sd,
                    _row_all(jnp.where(blk_idx_valid, sid_end < blk_rb, True),
                             manual), True)

    # pair regions: strictly between sd_id space and block ']'
    in_pair = jnp.zeros((N, L), dtype=bool)
    for k in range(max_sd):
        in_pair |= (
            (iota > sid_end[:, k:k + 1]) & (iota < blk_rb[:, k:k + 1])
            & blk_idx_valid[:, k:k + 1]
        )
    in_pair &= is_sd[:, None]
    sd_zone = in_rest & (iota <= sd_end[:, None]) & is_sd[:, None]

    # structural rules the parity model needs checked explicitly:
    viol2d |= open_q & sd_zone & (prev_bb != ord("="))
    name_struct = is_name & (bb != 32) & outside & in_pair
    prev_name = _shift_right(name_struct, 1, False)
    next_name = _shift_left(name_struct, 1, False)
    ns_mask = name_struct & ~prev_name        # name-run starts
    name_run_end = name_struct & ~next_name
    viol2d |= name_run_end & (next_bb != ord("="))
    # a pair name must be preceded by a space (the sd_id terminator or
    # the separator after the previous pair's close quote) — the byte
    # the old per-pair lookback checked
    viol2d |= ns_mask & (prev_bb != 32)
    eq_struct = (bb == ord("=")) & outside & in_pair
    next_open = _shift_left(open_q & in_pair, 1, False)
    viol2d |= eq_struct & ~next_open
    viol2d |= real_q & sd_zone & ~in_pair

    # ---- pair extraction -------------------------------------------------
    # oq_ord is parity-derived (not a cumsum), so the pair total is the
    # max ordinal over the zone's open quotes rather than a last-column
    # read of a running count
    pair_total = _row_max(jnp.where(oq_mask, oq_ord, 0), manual)
    pair_count = jnp.where(is_sd, pair_total, 0)
    ok &= jnp.where(is_sd, pair_count <= max_pairs, True)

    # per-pair quantities via the dual-impl extractor
    oq_pos = _extract(oq_mask, oq_ord, iota, max_pairs, L)
    cq_pos = _extract(cq_mask, cq_ord, iota, max_pairs, L)
    # backslashes per value interior: quote-parity marks the inside of a
    # value, open-quote ordinal attributes each backslash to its pair —
    # one accumulating extract replaces the two bs-cumsum channels
    inside_val = (q_excl % 2) == 1
    val_esc_count = _extract_counts(is_bs & inside_val, oq_ord, max_pairs)

    pair_valid = (jnp.arange(max_pairs, dtype=_I32)[None, :]
                  < pair_count[:, None])

    # name starts: a name-run start's pair index IS the parity-derived
    # open-quote ordinal (2(k-1) zone quotes precede pair k's name, and
    # no quote sits between the name and its open quote), so the k-th
    # name start comes out of the same packed-sum extractor as the quote
    # positions — replacing the round-3 stack of max_pairs masked
    # max-reductions (one [N, L] traversal per pair) with one 2-word
    # extraction.  Rows with several runs per ordinal (malformed pairs)
    # produce garbage sums, but every such row is already flagged by the
    # name_run_end / eq_struct / prev-space violations above and falls
    # back to the scalar oracle.
    ns_pos = _extract(ns_mask, oq_ord, iota, max_pairs, L)
    oq_name_start = jnp.where(pair_valid, ns_pos, 0)

    # name sanity per extracted pair: a run was found and it is nonempty
    # ('=' sits at oq_pos-1, so the run spans [ns_pos, oq_pos-1)).
    ok &= _row_all(jnp.where(pair_valid, ns_pos <= oq_pos - 2, True),
                   manual)

    ok &= _row_all(jnp.where(pair_valid, cq_pos > oq_pos, True), manual)
    name_end = oq_pos - 1  # position of '='


    # block assignment: number of block starts at or before the quote
    # (python loop over the small static block axis; no 3-D tensors)
    pair_sd = -jnp.ones_like(oq_pos)
    for k in range(max_sd):
        pair_sd = pair_sd + (blk_start[:, k:k + 1] <= oq_pos).astype(_I32)
    pair_sd = jnp.where(pair_valid, jnp.clip(pair_sd, 0, max_sd - 1), 0)

    # value escapes: backslashes strictly inside the value
    val_has_esc = val_esc_count > 0
    val_has_esc &= pair_valid & (cq_pos > oq_pos + 1)

    # ---- message span ----------------------------------------------------
    after_sd_pos = sd_end + 1
    sd_msg_ok = (after_sd_pos < lens) & ((end_flags & 4) != 0)
    ok &= jnp.where(is_sd, sd_msg_ok, True)
    msg_start = jnp.where(is_dash, rest_s + 1, after_sd_pos)

    # ---- host-assembly aux channels --------------------------------------
    # Python str whitespace over ASCII is {\t..\r, \x1c..\x1f, ' '}; these
    # three reductions let the host build output bytes without re-scanning
    # the batch (tpu/assemble.py): rstrip end of the full message, lstrip
    # start of msg, and the ASCII-purity flag that gates the fast tier.
    is_ws = ((bb >= 9) & (bb <= 13)) | ((bb >= 28) & (bb <= 32))
    non_ws = valid & ~is_ws
    trim_end = jnp.maximum(
        _row_max(jnp.where(non_ws, iota + 1, 0), manual), start0)
    msg_a = _min_where(non_ws & (iota >= msg_start[:, None]), iota, L,
                       manual)
    msg_trim_start = jnp.minimum(msg_a, trim_end)
    if pack_high:
        has_high = ((word3 >> 19) & 0x3FF) > 0
    else:
        has_high = _row_any((bb >= 128) & valid, manual)

    # single reduction over every accumulated 2-D violation
    ok &= ~_row_any(viol2d, manual)

    return {
        "ok": ok,
        "bom": bom,
        "facility": facility,
        "severity": severity,
        "days": days,
        "sod": sod,
        "off": off_secs,
        "nanos": nanos,
        "host_start": f_start[:, 2], "host_end": f_end[:, 2],
        "app_start": f_start[:, 3], "app_end": f_end[:, 3],
        "proc_start": f_start[:, 4], "proc_end": f_end[:, 4],
        "msgid_start": f_start[:, 5], "msgid_end": f_end[:, 5],
        "msg_start": msg_start,
        "sd_count": sd_count,
        "sid_start": sid_start, "sid_end": sid_end,
        "pair_count": pair_count,
        "name_start": oq_name_start, "name_end": name_end,
        "val_start": oq_pos + 1, "val_end": cq_pos,
        "pair_sd": pair_sd,
        "val_has_esc": val_has_esc,
        "full_start": start0,
        "trim_end": trim_end,
        "msg_trim_start": msg_trim_start,
        "has_high": has_high,
    }


@functools.partial(jax.jit,
                   static_argnames=("max_sd", "max_pairs", "extract_impl",
                                    "demand"))
def decode_rfc5424_jit(batch, lens, max_sd=DEFAULT_MAX_SD,
                       max_pairs=DEFAULT_MAX_PAIRS, extract_impl="sum",
                       demand=None):
    """``demand`` (static frozenset of channel names, On-Demand parsing
    per arxiv 2312.17149) keeps only the channels the consumer actually
    reads: dropping a channel from the traced output makes every
    computation feeding only it dead code, so XLA never materializes the
    fields the output format elides (e.g. msgid/facility on the GELF
    route).  None = the full channel dict (host materializers)."""
    out = decode_rfc5424(batch, lens, max_sd=max_sd, max_pairs=max_pairs,
                         extract_impl=extract_impl)
    if demand is not None:
        out = {k: v for k, v in out.items() if k in demand}
    return out


_PAIR_KEYS = ("name_start", "name_end", "val_start", "val_end",
              "pair_sd", "val_has_esc")


def decode_rfc5424_submit(batch, lens, max_sd: int = DEFAULT_MAX_SD,
                          extract_impl: str = None, sharded=None):
    """Dispatch the kernel asynchronously (JAX returns futures); pair
    with ``decode_rfc5424_fetch``.  Splitting submit from fetch lets the
    batch pipeline overlap device decode of batch N with host encoding
    of batch N-1 (double buffering).  ``sharded`` (a
    parallel.mesh.ShardedDecode) swaps in the multi-chip mesh kernel."""
    impl = extract_impl or best_extract_impl()
    if sharded is not None:
        # the sharded fn was jitted with its own kernel params; the
        # handle must reflect those (rescue and device-encode stages
        # size their work from the handle's max_sd/impl)
        max_sd = sharded.kw.get("max_sd", DEFAULT_MAX_SD)
        impl = sharded.kw.get("extract_impl", "sum")
        batch_dev, lens_dev = sharded.put(batch, lens)
        out = sharded.fn(batch_dev, lens_dev)
    else:
        from .aot import decode_call

        batch_dev, lens_dev = jnp.asarray(batch), jnp.asarray(lens)
        # zero-JIT boot: a loaded AOT artifact replaces the trace+compile
        # (same channels, byte-identical by construction); None → jit
        out = decode_call("rfc5424", (batch_dev, lens_dev),
                          {"max_sd": max_sd, "extract_impl": impl})
        if out is None:
            # Pallas tier: the single-VMEM structural decode (one HBM
            # read of the batch, one index write) — None on decline /
            # cooldown / tier off, then the jnp jit exactly as before
            from .pallas_kernels import decode_tier

            out = decode_tier("rfc5424", batch_dev, lens_dev,
                              max_sd=max_sd)
        if out is None:
            out = decode_rfc5424_jit(batch_dev, lens_dev,
                                     max_sd=max_sd, extract_impl=impl)
    # the handle keeps the original *host* arrays (rescue_refetch slices
    # them without a device round-trip) plus the uploaded *device*
    # arrays so downstream device-side stages (tpu/device_gelf.py) can
    # reuse them without a re-upload
    return (out, batch, lens, max_sd, impl, batch_dev, lens_dev)


def rescue_refetch(host, batch, lens, rows_idx, field_keys, dispatch,
                   width):
    """Tier-2 rescue: re-dispatch ``rows_idx`` through a wider kernel
    (``dispatch(sub_batch, sub_lens) -> host dict``) and merge results
    back; per-field channels in ``field_keys`` widen to ``width``.
    Shared by every two-tier format kernel."""
    import numpy as np

    if not rows_idx.size:
        return host
    rows = 256
    while rows < rows_idx.size:
        rows <<= 1
    batch_np = np.asarray(batch)
    lens_np = np.asarray(lens)
    sub_b = np.zeros((rows, batch_np.shape[1]), dtype=np.uint8)
    sub_l = np.zeros(rows, dtype=lens_np.dtype)
    sub_b[:rows_idx.size] = batch_np[rows_idx]
    sub_l[:rows_idx.size] = lens_np[rows_idx]
    host2 = dispatch(sub_b, sub_l)
    merged = {}
    for k, v in host.items():
        if k in field_keys:
            wide = np.zeros((v.shape[0], width), dtype=v.dtype)
            wide[:, :v.shape[1]] = v
            wide[rows_idx] = host2[k][:rows_idx.size]
            merged[k] = wide
        else:
            v = v.copy()
            v[rows_idx] = host2[k][:rows_idx.size]
            merged[k] = v
    return merged


def decode_rfc5424_fetch(handle):
    """Block on a submitted decode and return host numpy channels,
    re-dispatching pair-overflow rows (DEFAULT_MAX_PAIRS < pairs <=
    RESCUE_MAX_PAIRS) through the wider tier-2 kernel so they stay
    on-device instead of hitting the scalar fallback.  Pair channels
    come back widened to RESCUE_MAX_PAIRS when any row needed tier 2."""
    import numpy as np

    out, batch, lens, max_sd, impl = handle[:5]
    host = {k: np.asarray(v) for k, v in out.items()}
    pc = host["pair_count"]
    over = np.flatnonzero((pc > DEFAULT_MAX_PAIRS) & (pc <= RESCUE_MAX_PAIRS))

    def dispatch(sub_b, sub_l):
        out2 = decode_rfc5424_jit(jnp.asarray(sub_b), jnp.asarray(sub_l),
                                  max_sd=max_sd,
                                  max_pairs=RESCUE_MAX_PAIRS,
                                  extract_impl=impl)
        return {k: np.asarray(v) for k, v in out2.items()}

    return rescue_refetch(host, batch, lens, over, _PAIR_KEYS, dispatch,
                          RESCUE_MAX_PAIRS)


def decode_rfc5424_host(batch, lens, max_sd: int = DEFAULT_MAX_SD,
                        extract_impl: str = None):
    """Synchronous submit + fetch."""
    return decode_rfc5424_fetch(
        decode_rfc5424_submit(batch, lens, max_sd, extract_impl))


def best_scan_impl() -> str:
    """MXU matmul scans on accelerators (tri-matrix dot: 8.8ms vs 21.8ms
    per [1M,256] scan channel on v5e — the matmul trades O(L) extra
    FLOPs for ~6 fewer memory passes, a good trade only where a systolic
    array makes the FLOPs free); plain cumsum on the CPU backend.

    The platform->impl mapping is single-sourced in aot._scan_impl_for:
    the AOT builder stamps it into every fused/encode artifact key, and
    a drift between the two would make every artifact silently miss."""
    from .aot import _scan_impl_for

    return _scan_impl_for(jax.default_backend())


def best_extract_impl() -> str:
    """Bit-packed sums everywhere since the round-2 pass-count rework:
    with the 6-pair default tier the sum path's reduction count dropped
    ~2x and now beats scatter-min on the CPU backend too (measured
    1.86s vs 2.18s per 65k batch); on TPU scatters were never viable
    (XLA lowers them near-serially)."""
    return "sum"


def pack_on_device(buf: jnp.ndarray, starts: jnp.ndarray, lens: jnp.ndarray,
                   max_len: int) -> jnp.ndarray:
    """Gather a raw chunk ``uint8[B]`` into a padded ``[N, max_len]``
    batch on device.

    NOTE: XLA lowers this gather poorly on TPU (near-serial); the hot
    path packs on the host instead (tpu/pack.py pack_lines_2d).  Kept
    for the CPU backend and as the seam a Pallas DMA pack kernel will
    replace.
    """
    idx = starts[:, None].astype(_I32) + jnp.arange(max_len, dtype=_I32)[None, :]
    mask = jnp.arange(max_len, dtype=_I32)[None, :] < lens[:, None]
    gathered = jnp.take(buf, jnp.clip(idx, 0, buf.shape[0] - 1))
    return jnp.where(mask, gathered, 0).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("max_len", "max_sd", "max_pairs"))
def decode_chunk_jit(buf, starts, lens, max_len=DEFAULT_MAX_LEN,
                     max_sd=DEFAULT_MAX_SD, max_pairs=DEFAULT_MAX_PAIRS):
    """Fused pack+decode from a raw chunk buffer (CPU-backend path)."""
    batch = pack_on_device(buf, starts, lens, max_len)
    return decode_rfc5424(batch, jnp.minimum(lens, max_len),
                          max_sd=max_sd, max_pairs=max_pairs)


# ---------------------------------------------------------------------------
# Pallas TPU block kernel
# ---------------------------------------------------------------------------
# The XLA version above materializes each masked reduction's operands in
# HBM (~60 passes over [N, L] int32). The Pallas form tiles the batch into
# [BLOCK_ROWS, L] VMEM blocks and runs the *same* decode body (with
# Mosaic-lowerable manual scans) entirely on-chip: HBM traffic collapses
# to one read of the bytes plus the compact span outputs.

_KEYS_1D = (
    "ok", "bom", "facility", "severity", "days", "sod", "off", "nanos",
    "host_start", "host_end", "app_start", "app_end", "proc_start",
    "proc_end", "msgid_start", "msgid_end", "msg_start", "sd_count",
    "pair_count", "full_start", "trim_end", "msg_trim_start", "has_high",
)
_KEYS_SD = ("sid_start", "sid_end")
_KEYS_PAIR = ("name_start", "name_end", "val_start", "val_end",
              "pair_sd", "val_has_esc")
_BOOL_KEYS = ("ok", "bom", "val_has_esc", "has_high")

DEFAULT_BLOCK_ROWS = 256


def decode_rfc5424_pallas(batch, lens, max_sd: int = DEFAULT_MAX_SD,
                          max_pairs: int = DEFAULT_MAX_PAIRS,
                          block_rows: int = DEFAULT_BLOCK_ROWS,
                          interpret: bool = False) -> Dict[str, jnp.ndarray]:
    """Same contract as decode_rfc5424, executed as a Pallas TPU kernel.

    ``interpret=True`` runs the kernel in Pallas interpreter mode so the
    CPU-backend tests can differential-check this path too.
    """
    from jax.experimental import pallas as pl

    N_orig, L = batch.shape
    N = N_orig
    br = min(block_rows, N)
    if N % br:
        pad = br - N % br
        batch = jnp.pad(batch, ((0, pad), (0, 0)))
        lens = jnp.pad(lens, (0, pad))
        N += pad
    # widen u8 -> i32 outside the kernel: Mosaic cannot load u8 VMEM
    # refs on this jax; one elementwise pass, and the decode body's
    # byte compares are dtype-agnostic
    batch = batch.astype(_I32)
    lens2 = lens.astype(_I32).reshape(N, 1)

    def kernel(b_ref, l_ref, *outs):
        res = decode_rfc5424(b_ref[...], l_ref[...][:, 0],
                             max_sd=max_sd, max_pairs=max_pairs,
                             scan_impl="manual")
        i = 0
        for k in _KEYS_1D:
            outs[i][...] = res[k].astype(_I32).reshape(br, 1)
            i += 1
        for k in _KEYS_SD:
            outs[i][...] = res[k].astype(_I32)
            i += 1
        for k in _KEYS_PAIR:
            outs[i][...] = res[k].astype(_I32)
            i += 1

    out_shape = (
        [jax.ShapeDtypeStruct((N, 1), _I32) for _ in _KEYS_1D]
        + [jax.ShapeDtypeStruct((N, max_sd), _I32) for _ in _KEYS_SD]
        + [jax.ShapeDtypeStruct((N, max_pairs), _I32) for _ in _KEYS_PAIR]
    )
    out_specs = (
        [pl.BlockSpec((br, 1), lambda i: (i, 0)) for _ in _KEYS_1D]
        + [pl.BlockSpec((br, max_sd), lambda i: (i, 0)) for _ in _KEYS_SD]
        + [pl.BlockSpec((br, max_pairs), lambda i: (i, 0)) for _ in _KEYS_PAIR]
    )
    outs = pl.pallas_call(
        kernel,
        grid=(N // br,),
        in_specs=[
            pl.BlockSpec((br, L), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(batch, lens2)

    res = {}
    i = 0
    for k in _KEYS_1D:
        v = outs[i][:N_orig, 0]
        res[k] = (v != 0) if k in _BOOL_KEYS else v
        i += 1
    for k in _KEYS_SD + _KEYS_PAIR:
        v = outs[i][:N_orig]
        res[k] = (v != 0) if k in _BOOL_KEYS else v
        i += 1
    return res
