"""Device-side →RFC5424 encode: the shared SD-assembly core for the
rfc5424→rfc5424 re-encode and the rfc3164→rfc5424 relay upgrade
(rfc5424_encoder.rs:28-93 semantics, mirroring encode_rfc5424_block.py
byte-for-byte).

The output tier re-emits decoded spans verbatim from the raw batch
(record.rs:55-62 — RFC5424 output never escapes), so unlike the →GELF
kernels there is no escape stage at all: the source row for
device_common.assemble_rows is ``raw line ∥ constant bank ∥ timestamp
text`` and every segment is either a raw span, a constant, or a
magnitude-gated PRI digit.  Multi-block structured data nests pairs
inside their block's brackets via the decoder's ``pair_sd``
attribution, exactly like the host block route.

Constant elision goes further than the →GELF routes' fixed
(head, ts-label, tail) triple: the elided head here carries *row-
dependent* bytes — ``<PRI>1 `` digits and the rfc3339-ms stamp — so the
kernel exports two one-byte probe channels (``fac8``/``sev8``, plus
``pri1``/``hostl16`` on the 3164 leg) and a callable elide
(device_common.splice_rows) rebuilds the exact host-tier head from
them.  Net D2H stays under the elided bytes: ~27 fetched/row against a
33+-byte head+tail.

Rows outside the tier (kernel-flagged, non-ASCII, >6 pairs, escaped SD
values, oversized output) keep their existing host paths, so observable
bytes stay identical to the scalar route in every case.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.rfc5424:RFC5424Encoder"
DIFF_TEST = (
    "tests/test_device_encode_out.py::test_device_rfc5424_out_matches_scalar",
    "tests/test_device_encode_out.py::test_device_rfc3164_rfc5424_matches_scalar",
)

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .device_common import (
    TS_W,
    _out_width,
    assemble_rows,
    encode_route_ok,
    fetch_encode_driver,
)

_I32 = jnp.int32
_U8 = jnp.uint8

# constant bank: the same byte constants the host tier uses
# (encode_rfc5424_block.py builds them per batch via build_source; the
# two tiers must never diverge, since fallback rows splice host-tier
# output into device-tier blocks)
_PARTS = {
    "lt": b"<",
    "gt1": b">1 ",
    "dflt": b"<13>1 ",       # 3164 leg: PRI-less default head
    "sp": b" ",
    "eqq": b'="',
    "q": b'"',
    "lb": b"[",
    "rb": b"]",
    "dash": b"-",
    "t3164": b" - - - ",     # 3164 leg: appname/procid/msgid/sd slots
    "dec": b"0123456789",
    "tail": b"",
}


def _bank(suffix: bytes) -> Tuple[bytes, Dict[str, int], Dict[str, bytes]]:
    from .device_common import build_bank

    parts = dict(_PARTS)
    bank, offs = build_bank(parts, suffix)
    return bank, offs, parts


def _render_rfc3339(val: float) -> bytes:
    """Timestamp text for the elided-head splice and the non-elided
    kernel upload: the exact ms-truncated rfc3339 form the scalar
    encoder and the host block tier emit."""
    from ..utils.timeparse import unix_to_rfc3339_ms

    return unix_to_rfc3339_ms(val).encode("ascii")


def _head_rows(pri: np.ndarray, has_pri, ts_rows: np.ndarray,
               ts_lens: np.ndarray):
    """Host-side reconstruction of the elided ``<PRI>1 <ts> `` head
    (``<13>1 <ts> `` where the 3164 line carried no PRI): returns
    (flat bytes, per-row offsets, per-row lengths).  Mirrors host cols
    0-6 of encode_rfc5424_block.py exactly — same decimal_segments
    digit gating, same constants."""
    from .assemble import (
        build_source,
        concat_segments,
        decimal_segments,
        exclusive_cumsum,
    )

    R = pri.shape[0]
    consts, offs = build_source(b"<", b">1 ", b"<13>1 ", b" ",
                                b"0123456789")
    o_lt, o_gt1, o_dflt, o_sp, o_dec = offs
    W = ts_rows.shape[1] if ts_rows.ndim == 2 else 0
    src = np.concatenate([consts, np.asarray(ts_rows, np.uint8).ravel()])
    tbase = len(consts)
    dsrc, dlen = decimal_segments(pri, o_dec, width=3)
    if has_pri is None:
        has_pri = np.ones(R, dtype=bool)
    else:
        has_pri = np.asarray(has_pri, dtype=bool)
    ndig = np.where(has_pri,
                    1 + (pri >= 10).astype(np.int64)
                    + (pri >= 100).astype(np.int64), 0)
    seg_src = np.stack([
        np.where(has_pri, o_lt, 0),
        dsrc[0::3], dsrc[1::3], dsrc[2::3],
        np.where(has_pri, o_gt1, o_dflt),
        tbase + np.arange(R, dtype=np.int64) * W,
        np.full(R, o_sp, dtype=np.int64),
    ], axis=1)
    seg_len = np.stack([
        np.where(has_pri, 1, 0),
        np.where(has_pri, dlen[0::3], 0),
        np.where(has_pri, dlen[1::3], 0),
        np.where(has_pri, dlen[2::3], 0),
        np.where(has_pri, len(b">1 "), len(b"<13>1 ")),
        np.asarray(ts_lens, dtype=np.int64),
        np.ones(R, dtype=np.int64),
    ], axis=1)
    head = concat_segments(src, seg_src.ravel(), seg_len.ravel())
    head_len = (np.where(has_pri, 1 + 3, 6) + ndig
                + np.asarray(ts_lens, dtype=np.int64) + 1)
    return head, exclusive_cumsum(head_len)[:-1], head_len


def elide_spec(suffix: bytes, leg: str = "rfc5424"):
    """Single-sourced elide for both legs (split tier and fused route
    build their splice from here)."""
    return make_elide(suffix) if leg == "rfc5424" else make_elide_3164(suffix)


def make_elide(suffix: bytes):
    """Callable elide for the rfc5424→rfc5424 leg: the kernel skips the
    ``<PRI>1 <ts> `` head and the framing tail; this splice rebuilds
    them from the one-byte ``fac8``/``sev8`` probe channels and the
    rendered timestamp block (single source with the kernel's segment
    plan — the two sides cannot disagree)."""

    def splice(body, row_off, small, ts_text, ts_len, ridx):
        from .device_common import splice_rows

        R = ridx.size
        fac = small["fac8"][ridx].astype(np.int64)
        sev = small["sev8"][ridx].astype(np.int64)
        head, head_off, head_len = _head_rows(
            (fac << 3) + sev, None, ts_text[ridx], ts_len[ridx])
        ins_src = np.concatenate(
            [head, np.frombuffer(suffix, dtype=np.uint8)])
        lens = np.diff(row_off).astype(np.int64)
        ins_at = np.stack([np.zeros(R, dtype=np.int64), lens], axis=1)
        ins_a = np.stack([head_off,
                          np.full(R, head.size, dtype=np.int64)], axis=1)
        ins_l = np.stack([head_len,
                          np.full(R, len(suffix), dtype=np.int64)], axis=1)
        return splice_rows(body, row_off, ins_src, ins_at, ins_a, ins_l)

    return splice


def make_elide_3164(suffix: bytes):
    """Callable elide for the rfc3164→rfc5424 leg: head (PRI-gated
    ``<PRI>1 `` or the ``<13>1 `` default, stamp, space), the
    ``" - - - "`` slot constant at the per-row host boundary
    (``hostl16`` probe channel), and the framing tail."""
    T3164 = b" - - - "

    def splice(body, row_off, small, ts_text, ts_len, ridx):
        from .device_common import splice_rows

        R = ridx.size
        fac = small["fac8"][ridx].astype(np.int64)
        sev = small["sev8"][ridx].astype(np.int64)
        has_pri = small["pri1"][ridx].astype(bool)
        hostl = small["hostl16"][ridx].astype(np.int64)
        head, head_off, head_len = _head_rows(
            (fac << 3) + sev, has_pri, ts_text[ridx], ts_len[ridx])
        ins_src = np.concatenate(
            [head, np.frombuffer(T3164 + suffix, dtype=np.uint8)])
        lens = np.diff(row_off).astype(np.int64)
        ins_at = np.stack(
            [np.zeros(R, dtype=np.int64), hostl, lens], axis=1)
        ins_a = np.stack([
            head_off,
            np.full(R, head.size, dtype=np.int64),
            np.full(R, head.size + len(T3164), dtype=np.int64),
        ], axis=1)
        ins_l = np.stack([
            head_len,
            np.full(R, len(T3164), dtype=np.int64),
            np.full(R, len(suffix), dtype=np.int64),
        ], axis=1)
        return splice_rows(body, row_off, ins_src, ins_at, ins_a, ins_l)

    return splice


@partial(jax.jit, static_argnames=("suffix", "max_sd", "assemble",
                                   "elide"))
def _encode_kernel(batch, lens, dec, ts_text, ts_len, *, suffix: bytes,
                   max_sd: int, assemble: bool = True,
                   elide: bool = False):
    """rfc5424→RFC5424: encode_rfc5424_block.py's segment plan as a
    static device segment table.  No escape stage — spans re-emit
    verbatim."""
    N, L = batch.shape
    bank, off, parts = _bank(suffix)
    OW = _out_width(L, L + len(bank) + TS_W)
    zero = jnp.zeros((N,), dtype=_I32)
    cbase = L
    tbase = L + len(bank)
    segs = []

    def add_const(name, gate=None):
        ln = zero + len(parts[name]) + (len(suffix) if name == "tail"
                                        else 0)
        if gate is not None:
            ln = jnp.where(gate, ln, 0)
        segs.append((zero + (cbase + off[name]), ln))

    def add_span(s, e, gate=None):
        ln = jnp.maximum(e - s, 0)
        if gate is not None:
            ln = jnp.where(gate, ln, 0)
        segs.append((s, ln))

    fac = dec["facility"].astype(_I32)
    sev = dec["severity"].astype(_I32)
    host_s, host_e = dec["host_start"].astype(_I32), dec["host_end"].astype(_I32)
    app_s, app_e = dec["app_start"].astype(_I32), dec["app_end"].astype(_I32)
    proc_s, proc_e = dec["proc_start"].astype(_I32), dec["proc_end"].astype(_I32)
    msgid_s, msgid_e = (dec["msgid_start"].astype(_I32),
                        dec["msgid_end"].astype(_I32))
    msg_s = dec["msg_trim_start"].astype(_I32)
    trim_e = dec["trim_end"].astype(_I32)
    sdc = dec["sd_count"].astype(_I32)
    nsd = sdc > 0
    pc = dec["pair_count"].astype(_I32)
    P = dec["name_start"].shape[1]

    if not elide:
        # constant-elision mode skips the whole '<PRI>1 <ts> ' head and
        # the tail: the host splice (make_elide) restores them from the
        # fac8/sev8 probe channels + the rendered ts block
        pri = (fac << 3) + sev
        add_const("lt")
        d2, d1, d0 = (pri // 100) % 10, (pri // 10) % 10, pri % 10
        segs.append((cbase + off["dec"] + d2,
                     jnp.where(pri >= 100, 1, 0)))
        segs.append((cbase + off["dec"] + d1,
                     jnp.where(pri >= 10, 1, 0)))
        segs.append((cbase + off["dec"] + d0, zero + 1))
        add_const("gt1")
        segs.append((zero + tbase, ts_len.astype(_I32)))
        add_const("sp")

    add_span(host_s, host_e)
    add_const("sp")
    add_span(app_s, app_e)
    add_const("sp")
    add_span(proc_s, proc_e)
    add_const("sp")
    add_span(msgid_s, msgid_e)
    add_const("sp")

    # SD region: '-' on SD-less rows, else per block '[' sid pairs ']'
    # with pairs attributed to their block via pair_sd (same nesting as
    # the host route's pb_rb/p_in offsets — here the static (k, j) loop
    # order IS the host's ascending (block, pair-ordinal) order)
    add_const("dash", ~nsd)
    val_esc_any = jnp.zeros((N,), dtype=bool)
    for j in range(P):
        val_esc_any |= (dec["val_has_esc"][:, j].astype(bool)
                        & (j < pc))
    for k in range(max_sd):
        kv = k < sdc
        add_const("lb", kv)
        add_span(dec["sid_start"][:, k].astype(_I32),
                 dec["sid_end"][:, k].astype(_I32), kv)
        for j in range(P):
            pv = (j < pc) & (dec["pair_sd"][:, j].astype(_I32) == k) & kv
            add_const("sp", pv)
            add_span(dec["name_start"][:, j].astype(_I32),
                     dec["name_end"][:, j].astype(_I32), pv)
            add_const("eqq", pv)
            add_span(dec["val_start"][:, j].astype(_I32),
                     dec["val_end"][:, j].astype(_I32), pv)
            add_const("q", pv)
        add_const("rb", kv)

    add_const("sp")
    add_span(msg_s, trim_e)
    if not elide:
        add_const("tail")

    out_len = segs[0][1]
    for _, ln in segs[1:]:
        out_len = out_len + ln

    tier = (dec["ok"].astype(bool)
            & ~dec["has_high"].astype(bool)
            & (pc <= P)
            & (sdc <= max_sd)
            & ~val_esc_any
            & (out_len <= OW))
    if not assemble:
        return {"tier": tier,
                "fac8": fac.astype(_U8), "sev8": sev.astype(_U8)}
    acc, out_len2 = assemble_rows(segs, batch.astype(_U8), bank, ts_text,
                                  N, OW)
    return acc, out_len2, tier


@partial(jax.jit, static_argnames=("suffix", "assemble", "elide"))
def _encode_kernel_3164(batch, lens, dec, ts_text, ts_len, *,
                        suffix: bytes, assemble: bool = True,
                        elide: bool = False):
    """rfc3164→RFC5424 relay upgrade: encode_rfc5424_block.py's 11-col
    plan (PRI-gated digits or the <13>1 default, re-formatted stamp,
    host + message tail, constant " - - - " slots).  With elide, the
    device body is just ``host ∥ msg`` — two segments."""
    N, L = batch.shape
    bank, off, parts = _bank(suffix)
    OW = _out_width(L, L + len(bank) + TS_W)
    zero = jnp.zeros((N,), dtype=_I32)
    cbase = L
    tbase = L + len(bank)
    segs = []

    fac = dec["facility"].astype(_I32)
    sev = dec["severity"].astype(_I32)
    has_pri = dec["has_pri"].astype(bool)
    host_s = dec["host_start"].astype(_I32)
    host_e = dec["host_end"].astype(_I32)
    host_l = jnp.maximum(host_e - host_s, 0)
    msg_s = dec["msg_start"].astype(_I32)
    msg_l = jnp.maximum(lens.astype(_I32) - msg_s, 0)

    if not elide:
        pri = (fac << 3) + sev
        segs.append((zero + (cbase + off["lt"]),
                     jnp.where(has_pri, 1, 0)))
        d2, d1, d0 = (pri // 100) % 10, (pri // 10) % 10, pri % 10
        segs.append((cbase + off["dec"] + d2,
                     jnp.where(has_pri & (pri >= 100), 1, 0)))
        segs.append((cbase + off["dec"] + d1,
                     jnp.where(has_pri & (pri >= 10), 1, 0)))
        segs.append((cbase + off["dec"] + d0,
                     jnp.where(has_pri, 1, 0)))
        segs.append((jnp.where(has_pri, cbase + off["gt1"],
                               cbase + off["dflt"]),
                     jnp.where(has_pri, len(b">1 "), len(b"<13>1 "))))
        segs.append((zero + tbase, ts_len.astype(_I32)))
        segs.append((zero + (cbase + off["sp"]), zero + 1))

    segs.append((host_s, host_l))
    if not elide:
        segs.append((zero + (cbase + off["t3164"]),
                     zero + len(parts["t3164"])))
    segs.append((msg_s, msg_l))
    if not elide:
        segs.append((zero + (cbase + off["tail"]),
                     zero + len(suffix)))

    out_len = segs[0][1]
    for _, ln in segs[1:]:
        out_len = out_len + ln

    tier = (dec["ok"].astype(bool)
            & ~dec["has_high"].astype(bool)
            & (out_len <= OW))
    if not assemble:
        return {"tier": tier,
                "fac8": fac.astype(_U8), "sev8": sev.astype(_U8),
                "pri1": has_pri.astype(_U8),
                "hostl16": host_l.astype(jnp.uint16)}
    acc, out_len2 = assemble_rows(segs, batch.astype(_U8), bank, ts_text,
                                  N, OW)
    return acc, out_len2, tier


def _small_fetch(keys):
    """small_fetch_fn factory: ok + calendar channels + this route's
    one/two-byte probe extras (the elided head is row-dependent, so the
    splice needs them — narrowed on device so the fixed per-row D2H
    stays under the elided-constant savings)."""

    def fetch_small(out, fetch):
        small = {k: fetch(out[k])
                 for k in ("ok", "days", "sod", "off", "nanos")}
        for k in keys:
            small[k] = fetch(out[k])
        return small

    return fetch_small


def route_ok(encoder, merger) -> bool:
    """Device encode applies to RFC5424 output over line/nul/syslen
    framing (RFC5424Encoder carries no extras config)."""
    from ..encoders.rfc5424 import RFC5424Encoder

    return encode_route_ok(encoder, merger, RFC5424Encoder)


# same ladder constants as the →GELF split tier
FALLBACK_FRAC = 0.05
DECLINE_LIMIT = 3
COOLDOWN = 16


def fetch_encode(handle, packed, encoder, merger, route_state=None):
    """rfc5424→RFC5424 split-tier entry; returns
    (BlockResult | None, fetch_seconds).  None = caller should use the
    host block path."""
    from .block_common import merger_suffix
    from .materialize import _scalar_line

    out, _, _, max_sd, _impl_unused, batch_dev, lens_dev = handle
    suffix, syslen = merger_suffix(merger)

    def kernel(ts_text, ts_len, assemble):
        return _encode_kernel(batch_dev, lens_dev, dict(out), ts_text,
                              ts_len, suffix=suffix, max_sd=max_sd,
                              assemble=assemble, elide=True)

    from .aot import encode_wrap
    from .rfc5424 import best_scan_impl

    kernel = encode_wrap("device_rfc5424_out", kernel, batch_dev,
                         lens_dev, dict(out), suffix, best_scan_impl(),
                         (), max_sd=max_sd)

    return fetch_encode_driver(
        kernel, out, batch_dev, lens_dev, packed, encoder, merger,
        route_state, suffix, syslen, scalar_fn=_scalar_line,
        fallback_frac=FALLBACK_FRAC, decline_limit=DECLINE_LIMIT,
        cooldown=COOLDOWN, ts_render=_render_rfc3339,
        small_fetch_fn=_small_fetch(("fac8", "sev8")),
        elide=make_elide(suffix), route_label="rfc5424_rfc5424",
        fused_counters=False)


def fetch_encode_3164(handle, packed, encoder, merger, route_state=None):
    """rfc3164→RFC5424 split-tier entry (rfc3164 decode handle shape:
    (out, batch_dev, lens_dev))."""
    from .block_common import merger_suffix
    from .materialize_rfc3164 import _scalar_3164

    out, batch_dev, lens_dev = handle
    suffix, syslen = merger_suffix(merger)

    def kernel(ts_text, ts_len, assemble):
        return _encode_kernel_3164(batch_dev, lens_dev, dict(out),
                                   ts_text, ts_len, suffix=suffix,
                                   assemble=assemble, elide=True)

    from .aot import encode_wrap
    from .rfc5424 import best_scan_impl

    kernel = encode_wrap("device_rfc5424_out_3164", kernel, batch_dev,
                         lens_dev, dict(out), suffix, best_scan_impl(),
                         ())

    return fetch_encode_driver(
        kernel, out, batch_dev, lens_dev, packed, encoder, merger,
        route_state, suffix, syslen, scalar_fn=_scalar_3164,
        fallback_frac=FALLBACK_FRAC, decline_limit=DECLINE_LIMIT,
        cooldown=COOLDOWN, ts_render=_render_rfc3339,
        small_fetch_fn=_small_fetch(("fac8", "sev8", "pri1",
                                     "hostl16")),
        elide=make_elide_3164(suffix),
        route_label="rfc3164_rfc5424", fused_counters=False)
