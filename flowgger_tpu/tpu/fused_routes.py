"""Fused device-resident decode→encode routes: ONE compiled program per
(in-format, out-format) pair, so field span channels never leave the
device between the decode and the encode.

The split tier (tpu/device_*.py) runs decode and encode as two separate
XLA programs with the full decode channel dict materialized to HBM as
program outputs in between — and, on the host block path, fetched over
PCIe, spliced, and re-uploaded.  A fused route traces the block decode
(rfc5424/rfc3164/ltsv/gelf) and its device encode kernel into a single
jitted program: the decoder's span channels are internal values of one
XLA computation, fusible with the encode stages and never transferred.
This is the batched-TPU shape of the reference's per-line hot loop
(line_splitter.rs:44-54 → encoder/mod.rs:54-56), and it collapses the
AOT artifact matrix from decode×encode pairs to one program per route
(ROADMAP item 1).

Two further wins ride the fusion:

- **Field-demand masks** (On-Demand parsing, arxiv 2312.17149): each
  route declares the decode channels its encoder actually consumes
  (``DEMAND``), threaded into the decoder as a static ``demand``
  argument.  Channels the output format drops (rfc5424's msgid and
  facility on the GELF route, ltsv's raw timestamp span, ...) vanish
  from the traced output, so XLA dead-code-eliminates their entire
  materialization chain — the decode work for unused fields is never
  executed, not just never fetched.
- **Constant elision on every route** (PR 4 shipped it for
  rfc5424→GELF only): all four fused kernels run ``elide=True`` — the
  row-constant head, timestamp-label, and tail segments never cross
  PCIe, ``splice_elided_rows`` restores the exact host-tier bytes — so
  fetched bytes/row lands under emitted bytes/row on every route.

Degradation ladder (unchanged contract): every fused compile runs under
``guarded_compile_call`` watchdog slots (namespaced ``fused/<route>`` so
two routes at one shape cannot mask each other); a timeout or a
tier-fraction decline falls back to the existing split path — split
decode, device-or-host encode, scalar oracle — and the emitted bytes
stay identical at every rung.  ``FLOWGGER_FUSED_COMPILE_TIMEOUT_MS``
optionally tightens the first-compile wait for the fused tier alone
(the shared ``FLOWGGER_COMPILE_TIMEOUT_MS`` deadline applies otherwise).

Where this container's XLA cannot compile the fused programs at all,
byte identity is still enforced eagerly via ``jax.disable_jit()`` — see
the DIFF_TESTs below.
"""

from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterparts the
# fused route matrix must stay byte-identical to (one oracle per output
# format), and the differential tests that enforce it across the matrix
SCALAR_ORACLE = (
    "flowgger_tpu.encoders.gelf:GelfEncoder",
    "flowgger_tpu.encoders.rfc5424:RFC5424Encoder",
    "flowgger_tpu.encoders.ltsv:LTSVEncoder",
    "flowgger_tpu.encoders.capnp:CapnpEncoder",
)
DIFF_TEST = (
    "tests/test_fused_routes.py::test_fused_matches_scalar_oracle_all_routes",
    "tests/test_fused_routes.py::test_fused_route_fuzz_vs_scalar",
    "tests/test_device_encode_out.py::test_fused_new_output_routes_match_scalar",
)

import os
from functools import partial

import jax
import jax.numpy as jnp

from ..utils.metrics import registry as _metrics

# decline hysteresis — same ladder constants as the split device tiers
FALLBACK_FRAC = 0.05
DECLINE_LIMIT = 3
COOLDOWN = 16

FUSED_COMPILE_TIMEOUT_ENV = "FLOWGGER_FUSED_COMPILE_TIMEOUT_MS"

_TS4 = ("days", "sod", "off", "nanos")

# ---------------------------------------------------------------------------
# Field-demand masks: exactly the decode channels each route's encode
# kernel + fetch driver read.  Everything else is dead in the fused
# trace and never materialized.  A missing key fails fast (KeyError in
# the encode stage), so the eager differential tests double as
# completeness checks for these sets.
DEMAND = {
    "rfc5424_gelf": frozenset((
        "ok", "has_high", "severity", *_TS4,
        "host_start", "host_end", "app_start", "app_end",
        "proc_start", "proc_end", "full_start", "trim_end",
        "msg_trim_start", "sd_count", "sid_start", "sid_end",
        "pair_count", "name_start", "name_end", "val_start", "val_end",
        "val_has_esc",
    )),  # drops: bom, facility, msgid_start/end, msg_start, pair_sd
    "rfc3164_gelf": frozenset((
        "ok", "has_pri", "has_high", "severity", *_TS4,
        "host_start", "host_end", "msg_start",
    )),  # drops: facility
    "ltsv_gelf": frozenset((
        "ok", "has_high", "n_parts", "part_start", "part_end",
        "colon_pos", "time_pos", "host_pos", "msg_pos", "level_pos",
        "host_start", "host_end", "msg_start", "msg_end", "level_val",
        "ts_kind", "ts_hi", "ts_lo", "ts_meta", *_TS4,
    )),  # drops: ts_start, ts_end
    "gelf_gelf": frozenset((
        "ok", "n_fields", "key_start", "key_end", "val_start",
        "val_end", "val_type", "key_esc", "val_esc",
    )),  # the canonicalizing re-encode touches every channel
    "rfc5424_rfc5424": frozenset((
        "ok", "has_high", "facility", "severity", *_TS4,
        "host_start", "host_end", "app_start", "app_end",
        "proc_start", "proc_end", "msgid_start", "msgid_end",
        "msg_trim_start", "trim_end", "sd_count", "sid_start", "sid_end",
        "pair_count", "pair_sd", "name_start", "name_end",
        "val_start", "val_end", "val_has_esc",
    )),  # drops: bom, full_start, msg_start
    "rfc3164_rfc5424": frozenset((
        "ok", "has_pri", "has_high", "facility", "severity", *_TS4,
        "host_start", "host_end", "msg_start",
    )),  # the relay upgrade reads every rfc3164 channel
    "rfc5424_ltsv": frozenset((
        "ok", "has_high", "facility", "severity", *_TS4,
        "host_start", "host_end", "app_start", "app_end",
        "proc_start", "proc_end", "msgid_start", "msgid_end",
        "full_start", "msg_trim_start", "trim_end",
        "pair_count", "name_start", "name_end",
        "val_start", "val_end", "val_has_esc",
    )),  # drops: bom, msg_start, sd_count, sid_start/end, pair_sd
    "rfc5424_capnp": frozenset((
        "ok", "has_high", "facility", "severity", *_TS4,
        "host_start", "host_end", "app_start", "app_end",
        "proc_start", "proc_end", "msgid_start", "msgid_end",
        "full_start", "msg_trim_start", "trim_end",
        "sd_count", "sid_start", "sid_end",
        "pair_count", "pair_sd", "name_start", "name_end",
        "val_start", "val_end", "val_has_esc",
    )),  # drops: bom, msg_start
}


def fused_compile_timeout_s():
    """Deadline override for fused-tier guarded compiles; None = the
    shared watchdog deadline (FLOWGGER_COMPILE_TIMEOUT_MS)."""
    raw = os.environ.get(FUSED_COMPILE_TIMEOUT_ENV)
    if raw is None:
        return None
    try:
        return int(raw) / 1000.0
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# The fused programs: decode traced inline into the encode kernel.
# assemble=False returns a dict — the tier plus the channels the fetch
# driver formats timestamps from ("ok" + ts_keys) — so the driver needs
# no separate decode output dict at all.

def _rfc5424_leg(batch, lens, *, max_sd, demand, pallas: str):
    """The rfc5424 decode leg of a fused program: the Pallas
    single-VMEM structural pass when the tier is engaged (``pallas``
    is the config-resolved mode string, a static jit arg so flipping
    the tier retraces), else the demand-narrowed jnp decode."""
    if pallas in ("compiled", "interpret"):
        from .rfc5424 import decode_rfc5424_pallas

        return decode_rfc5424_pallas(batch, lens, max_sd=max_sd,
                                     interpret=pallas == "interpret")
    from .rfc5424 import decode_rfc5424_jit

    return decode_rfc5424_jit(batch, lens, max_sd=max_sd,
                              extract_impl="sum", demand=demand)


@partial(jax.jit, static_argnames=("max_sd", "suffix", "impl",
                                   "assemble", "extras", "demand",
                                   "pallas"))
def _fused_rfc5424_gelf(batch, lens, ts_text, ts_len, *, max_sd: int,
                        suffix: bytes, impl: str, assemble: bool,
                        extras, demand, pallas: str = "off"):
    from .device_gelf import _encode_kernel

    dec = _rfc5424_leg(batch, lens, max_sd=max_sd, demand=demand,
                       pallas=pallas)
    res = _encode_kernel(batch, lens, dec, ts_text, ts_len,
                         suffix=suffix, max_sd=max_sd, impl=impl,
                         assemble=assemble, extras=extras, elide=True)
    if not assemble:
        return {"tier": res,
                **{k: dec[k] for k in ("ok",) + _TS4}}
    return res


@partial(jax.jit, static_argnames=("suffix", "impl", "assemble",
                                   "extras", "demand"))
def _fused_rfc3164_gelf(batch, lens, year, ts_text, ts_len, *,
                        suffix: bytes, impl: str, assemble: bool,
                        extras, demand):
    from .device_rfc3164 import _encode_kernel
    from .rfc3164 import decode_rfc3164_jit

    dec = decode_rfc3164_jit(batch, lens, year, demand=demand)
    res = _encode_kernel(batch, lens, dec, ts_text, ts_len,
                         suffix=suffix, impl=impl, assemble=assemble,
                         extras=extras, elide=True)
    if not assemble:
        return {"tier": res,
                **{k: dec[k] for k in ("ok",) + _TS4}}
    return res


@partial(jax.jit, static_argnames=("suffix", "impl", "assemble",
                                   "extras", "demand"))
def _fused_ltsv_gelf(batch, lens, ts_text, ts_len, *, suffix: bytes,
                     impl: str, assemble: bool, extras, demand):
    from .device_ltsv import _encode_kernel
    from .ltsv import decode_ltsv_jit

    dec = decode_ltsv_jit(batch, lens, demand=demand)
    res = _encode_kernel(batch, lens, dec, ts_text, ts_len,
                         suffix=suffix, impl=impl, assemble=assemble,
                         extras=extras, elide=True)
    if not assemble:
        # narrowed timestamp channels: this route's head constant is a
        # single "{" (sorted "_key" pairs lead the object), so its
        # elided-constant savings are small — the fixed per-row small
        # fetch must shrink to stay under them.  Kind rides u8, the
        # fraction count u8, the offset i16 minutes (rfc3339 offsets
        # are whole minutes), and the host fetches the calendar vs
        # split-integer channels only for timestamp kinds the batch
        # actually contains (_ltsv_small_fetch).
        return {"tier": res, "ok": dec["ok"],
                "ts_kind8": dec["ts_kind"].astype(jnp.uint8),
                "ts_frac8": (dec["ts_meta"] & 255).astype(jnp.uint8),
                "off_min16": (dec["off"] // 60).astype(jnp.int16),
                "days": dec["days"], "sod": dec["sod"],
                "nanos": dec["nanos"],
                "ts_hi": dec["ts_hi"], "ts_lo": dec["ts_lo"]}
    return res


def _ltsv_small_fetch(out, fetch):
    """Kind-conditional small-channel fetch for the fused ltsv route:
    reconstructs the exact channel dict ``ts_vals_ltsv`` consumes
    (off = off_min*60 and frac = meta&255 are bit-exact by
    construction) while homogeneous-timestamp streams ship only the
    channels their kind needs."""
    import numpy as np

    ok = fetch(out["ok"]).astype(bool)
    kind = fetch(out["ts_kind8"])
    n_full = ok.shape[0]

    def z32():
        return np.zeros(n_full, dtype=np.int32)

    small = {"ok": ok, "ts_kind": kind.astype(np.int32)}
    if bool((ok & (kind == 0)).any()):
        small["days"] = fetch(out["days"])
        small["sod"] = fetch(out["sod"])
        small["off"] = fetch(out["off_min16"]).astype(np.int32) * 60
        small["nanos"] = fetch(out["nanos"])
    else:
        small.update(days=z32(), sod=z32(), off=z32(), nanos=z32())
    if bool((ok & (kind == 1)).any()):
        small["ts_hi"] = fetch(out["ts_hi"])
        small["ts_lo"] = fetch(out["ts_lo"])
        small["ts_meta"] = fetch(out["ts_frac8"]).astype(np.int32)
    else:
        small.update(ts_hi=z32(), ts_lo=z32(), ts_meta=z32())
    return small


@partial(jax.jit, static_argnames=("suffix", "assemble", "demand"))
def _fused_gelf_gelf(batch, lens, ts_text, ts_len, *, suffix: bytes,
                     assemble: bool, demand):
    from .device_gelf_gelf import _encode_kernel
    from .gelf import decode_gelf_jit

    dec = decode_gelf_jit(batch, lens, demand=demand)
    res = _encode_kernel(batch, lens, dec, ts_text, ts_len,
                         suffix=suffix, assemble=assemble, elide=True)
    if not assemble:
        # the gelf→GELF probe already returns a dict (its timestamp
        # parse exists encode-side only); add the decode's ok gate
        return {**res, "ok": dec["ok"]}
    return res


# The non-GELF output legs (PR 19): their probes all return dicts —
# tier plus the one/two-byte channels their callable elides splice the
# row-dependent heads from (fac8/sev8, gap offsets).

@partial(jax.jit, static_argnames=("max_sd", "suffix", "assemble",
                                   "demand", "pallas"))
def _fused_rfc5424_rfc5424(batch, lens, ts_text, ts_len, *, max_sd: int,
                           suffix: bytes, assemble: bool, demand,
                           pallas: str = "off"):
    from .device_rfc5424_out import _encode_kernel

    dec = _rfc5424_leg(batch, lens, max_sd=max_sd, demand=demand,
                       pallas=pallas)
    res = _encode_kernel(batch, lens, dec, ts_text, ts_len,
                         suffix=suffix, max_sd=max_sd,
                         assemble=assemble, elide=True)
    if not assemble:
        return {**res, **{k: dec[k] for k in ("ok",) + _TS4}}
    return res


@partial(jax.jit, static_argnames=("suffix", "assemble", "demand"))
def _fused_rfc3164_rfc5424(batch, lens, year, ts_text, ts_len, *,
                           suffix: bytes, assemble: bool, demand):
    from .device_rfc5424_out import _encode_kernel_3164
    from .rfc3164 import decode_rfc3164_jit

    dec = decode_rfc3164_jit(batch, lens, year, demand=demand)
    res = _encode_kernel_3164(batch, lens, dec, ts_text, ts_len,
                              suffix=suffix, assemble=assemble,
                              elide=True)
    if not assemble:
        return {**res, **{k: dec[k] for k in ("ok",) + _TS4}}
    return res


@partial(jax.jit, static_argnames=("max_sd", "suffix", "extras",
                                   "assemble", "demand", "pallas"))
def _fused_rfc5424_ltsv(batch, lens, ts_text, ts_len, *, max_sd: int,
                        suffix: bytes, extras, assemble: bool, demand,
                        pallas: str = "off"):
    from .device_ltsv_out import _encode_kernel

    dec = _rfc5424_leg(batch, lens, max_sd=max_sd, demand=demand,
                       pallas=pallas)
    res = _encode_kernel(batch, lens, dec, ts_text, ts_len,
                         suffix=suffix, extras=extras,
                         assemble=assemble, elide=True)
    if not assemble:
        return {**res, **{k: dec[k] for k in ("ok",) + _TS4}}
    return res


@partial(jax.jit, static_argnames=("max_sd", "suffix", "extras",
                                   "assemble", "demand", "pallas"))
def _fused_rfc5424_capnp(batch, lens, ts_text, ts_len, *, max_sd: int,
                         suffix: bytes, extras, assemble: bool, demand,
                         pallas: str = "off"):
    from .device_capnp import _encode_kernel

    dec = _rfc5424_leg(batch, lens, max_sd=max_sd, demand=demand,
                       pallas=pallas)
    res = _encode_kernel(batch, lens, dec, ts_text, ts_len,
                         suffix=suffix, extras=extras,
                         assemble=assemble, elide=True)
    if not assemble:
        return {**res, **{k: dec[k] for k in ("ok",) + _TS4}}
    return res


# ---------------------------------------------------------------------------


class FusedHandle:
    """A submitted fused batch: the committed device inputs plus the
    route that will run them.  All device work happens at fetch time on
    the lane fetcher thread (the in-flight window provides the
    ingest/compute overlap)."""

    __slots__ = ("route", "batch_dev", "lens_dev", "device")

    def __init__(self, route, batch_dev, lens_dev, device):
        self.route = route
        self.batch_dev = batch_dev
        self.lens_dev = lens_dev
        self.device = device


class FusedRoute:
    """One (in-format → out-format) fused program plus its driver
    recipe."""

    __slots__ = ("name", "fmt", "out")

    def __init__(self, name: str, fmt: str, out: str = "gelf"):
        self.name = name
        self.fmt = fmt
        self.out = out

    # -- applicability -----------------------------------------------------
    def route_ok(self, encoder, merger, decoder=None) -> bool:
        """Reuses the split device tier's gate (output encoder type,
        framing allowlist, extras placement, FLOWGGER_DEVICE_ENCODE
        kill switch, ltsv schema) — a route the split tier would refuse
        is never fused either."""
        if self.out == "rfc5424":
            from . import device_rfc5424_out

            return device_rfc5424_out.route_ok(encoder, merger)
        if self.out == "ltsv":
            from . import device_ltsv_out

            return device_ltsv_out.route_ok(encoder, merger)
        if self.out == "capnp":
            from . import device_capnp

            return device_capnp.route_ok(encoder, merger)
        if self.fmt == "rfc3164":
            from . import device_rfc3164

            return device_rfc3164.route_ok(encoder, merger)
        if self.fmt == "ltsv":
            from . import device_ltsv

            return device_ltsv.route_ok(encoder, merger, decoder)
        if self.fmt == "gelf":
            from . import device_gelf_gelf

            return device_gelf_gelf.route_ok(encoder, merger)
        from . import device_gelf

        return device_gelf.route_ok(encoder, merger)

    # -- driver recipe ------------------------------------------------------
    def make_kernel(self, handle, encoder, merger, ltsv_decoder=None):
        """Build the fused kernel closure plus the driver kwargs
        (scalar oracle, ts channel recipe, elide constants)."""
        # zero-JIT boot: fused_wrap makes each closure consult the AOT
        # artifact store per call (a hit runs the exported program —
        # the same trace, byte-identical); misses/rejects fall through
        # to the fused jit under the same compile watchdog
        from .aot import fused_wrap
        from .block_common import merger_suffix
        from .rfc5424 import best_scan_impl

        suffix, syslen = merger_suffix(merger)
        impl = best_scan_impl()
        extras = tuple((k, v) for k, v in getattr(encoder, "extra", ()))
        demand = DEMAND[self.name]
        b, ln = handle.batch_dev, handle.lens_dev
        kw = {"suffix": suffix, "syslen": syslen}

        if self.out != "gelf":
            return self._make_kernel_out(b, ln, suffix, impl, extras,
                                         demand, kw, fused_wrap)
        if self.fmt == "rfc3164":
            from ..utils.timeparse import current_year_utc
            from .device_rfc3164 import elide_spec
            from .materialize_rfc3164 import _scalar_3164

            year = jnp.int32(current_year_utc())

            def kernel(ts_text, ts_len, assemble):
                return _fused_rfc3164_gelf(
                    b, ln, year, ts_text, ts_len, suffix=suffix,
                    impl=impl, assemble=assemble, extras=extras,
                    demand=demand)

            kernel = fused_wrap(self.name, kernel, (b, ln, year),
                               suffix, impl, extras)
            kw.update(scalar_fn=_scalar_3164,
                      elide=elide_spec(suffix, extras))
            return kernel, kw
        if self.fmt == "ltsv":
            from .device_ltsv import elide_spec, ts_vals_ltsv
            from .materialize_ltsv import _scalar_ltsv

            def kernel(ts_text, ts_len, assemble):
                return _fused_ltsv_gelf(
                    b, ln, ts_text, ts_len, suffix=suffix, impl=impl,
                    assemble=assemble, extras=extras, demand=demand)

            kernel = fused_wrap(self.name, kernel, (b, ln), suffix,
                               impl, extras)
            kw.update(scalar_fn=lambda line: _scalar_ltsv(ltsv_decoder,
                                                          line),
                      ts_vals_fn=ts_vals_ltsv,
                      small_fetch_fn=_ltsv_small_fetch,
                      elide=elide_spec(suffix, extras))
            return kernel, kw
        if self.fmt == "gelf":
            from .device_gelf_gelf import TS_KEYS, elide_spec, ts_vals_gelf
            from .materialize_gelf import _scalar_gelf

            def kernel(ts_text, ts_len, assemble):
                return _fused_gelf_gelf(
                    b, ln, ts_text, ts_len, suffix=suffix,
                    assemble=assemble, demand=demand)

            kernel = fused_wrap(self.name, kernel, (b, ln), suffix,
                               impl, extras)
            kw.update(scalar_fn=_scalar_gelf, ts_keys=TS_KEYS,
                      ts_vals_fn=ts_vals_gelf, elide=elide_spec(suffix))
            return kernel, kw

        from .device_gelf import elide_spec
        from .materialize import _scalar_line
        from .pallas_kernels import fused_leg_mode
        from .rfc5424 import DEFAULT_MAX_SD

        pmode = fused_leg_mode()

        def kernel(ts_text, ts_len, assemble):
            return _fused_rfc5424_gelf(
                b, ln, ts_text, ts_len, max_sd=DEFAULT_MAX_SD,
                suffix=suffix, impl=impl, assemble=assemble,
                extras=extras, demand=demand, pallas=pmode)

        kernel = fused_wrap(self.name, kernel, (b, ln), suffix, impl,
                           extras)
        kw.update(scalar_fn=_scalar_line,
                  elide=elide_spec(suffix, extras))
        return kernel, kw

    def _make_kernel_out(self, b, ln, suffix, impl, extras, demand, kw,
                         fused_wrap):
        """Driver recipes for the non-GELF output legs (PR 19): each
        reuses its split module's single-sourced callable elide, stamp
        renderer, and narrowed small fetch."""
        from .materialize import _scalar_line
        from .pallas_kernels import fused_leg_mode
        from .rfc5424 import DEFAULT_MAX_SD

        pmode = fused_leg_mode()
        if self.name == "rfc5424_rfc5424":
            from . import device_rfc5424_out as m

            def kernel(ts_text, ts_len, assemble):
                return _fused_rfc5424_rfc5424(
                    b, ln, ts_text, ts_len, max_sd=DEFAULT_MAX_SD,
                    suffix=suffix, assemble=assemble, demand=demand,
                    pallas=pmode)

            kernel = fused_wrap(self.name, kernel, (b, ln), suffix,
                               impl, extras)
            kw.update(scalar_fn=_scalar_line,
                      ts_render=m._render_rfc3339,
                      small_fetch_fn=m._small_fetch(("fac8", "sev8")),
                      elide=m.elide_spec(suffix))
            return kernel, kw
        if self.name == "rfc3164_rfc5424":
            from ..utils.timeparse import current_year_utc
            from . import device_rfc5424_out as m
            from .materialize_rfc3164 import _scalar_3164

            year = jnp.int32(current_year_utc())

            def kernel(ts_text, ts_len, assemble):
                return _fused_rfc3164_rfc5424(
                    b, ln, year, ts_text, ts_len, suffix=suffix,
                    assemble=assemble, demand=demand)

            kernel = fused_wrap(self.name, kernel, (b, ln, year),
                               suffix, impl, extras)
            kw.update(scalar_fn=_scalar_3164,
                      ts_render=m._render_rfc3339,
                      small_fetch_fn=m._small_fetch(
                          ("fac8", "sev8", "pri1", "hostl16")),
                      elide=m.elide_spec(suffix, leg="rfc3164"))
            return kernel, kw
        if self.name == "rfc5424_ltsv":
            from . import device_ltsv_out as m

            def kernel(ts_text, ts_len, assemble):
                return _fused_rfc5424_ltsv(
                    b, ln, ts_text, ts_len, max_sd=DEFAULT_MAX_SD,
                    suffix=suffix, extras=extras, assemble=assemble,
                    demand=demand, pallas=pmode)

            kernel = fused_wrap(self.name, kernel, (b, ln), suffix,
                               impl, extras)
            kw.update(scalar_fn=_scalar_line,
                      ts_render=m._render_display,
                      small_fetch_fn=m._small_fetch,
                      elide=m.elide_spec(suffix, extras))
            return kernel, kw
        # rfc5424_capnp
        from . import device_capnp as m

        def kernel(ts_text, ts_len, assemble):
            return _fused_rfc5424_capnp(
                b, ln, ts_text, ts_len, max_sd=DEFAULT_MAX_SD,
                suffix=suffix, extras=extras, assemble=assemble,
                demand=demand, pallas=pmode)

        kernel = fused_wrap(self.name, kernel, (b, ln), suffix, impl,
                           extras)
        kw.update(scalar_fn=_scalar_line,
                  ts_render=m._render_le_f64,
                  small_fetch_fn=m._small_fetch,
                  elide=m.elide_spec(suffix, extras))
        return kernel, kw


ROUTES = {
    "rfc5424": FusedRoute("rfc5424_gelf", "rfc5424"),
    "rfc3164": FusedRoute("rfc3164_gelf", "rfc3164"),
    "ltsv": FusedRoute("ltsv_gelf", "ltsv"),
    "gelf": FusedRoute("gelf_gelf", "gelf"),
    # PR 19: the non-GELF output legs close the N×M matrix
    "rfc5424_rfc5424": FusedRoute("rfc5424_rfc5424", "rfc5424",
                                  out="rfc5424"),
    "rfc3164_rfc5424": FusedRoute("rfc3164_rfc5424", "rfc3164",
                                  out="rfc5424"),
    "rfc5424_ltsv": FusedRoute("rfc5424_ltsv", "rfc5424", out="ltsv"),
    "rfc5424_capnp": FusedRoute("rfc5424_capnp", "rfc5424",
                                out="capnp"),
}


def _out_key(encoder) -> str:
    """The output-format leg for this encoder type (fused routes
    dispatch on concrete encoder classes, like the split tiers)."""
    from ..encoders.capnp import CapnpEncoder
    from ..encoders.gelf import GelfEncoder
    from ..encoders.ltsv import LTSVEncoder
    from ..encoders.rfc5424 import RFC5424Encoder

    for cls, key in ((GelfEncoder, "gelf"), (RFC5424Encoder, "rfc5424"),
                     (LTSVEncoder, "ltsv"), (CapnpEncoder, "capnp")):
        if type(encoder) is cls:
            return key
    return ""


def route_for(fmt: str, encoder, merger, decoder=None):
    """The registered fused route for this (fmt, encoder, merger)
    config, or None when no fused program applies (the split path is
    then the route — ``input.tpu_fuse = "auto"`` semantics).  →GELF
    legs keep their original fmt-keyed registrations; the other output
    legs key on ``{fmt}_{out}``."""
    okey = _out_key(encoder)
    route = ROUTES.get(fmt if okey == "gelf" else f"{fmt}_{okey}")
    if route is None or not route.route_ok(encoder, merger, decoder):
        return None
    return route


def cooldown_state(route_state: dict, route: FusedRoute) -> dict:
    """The per-handler fused decline-hysteresis dict for ``route`` —
    the ONE key both the submit-side cooldown check (batch._emit_fast)
    and the driver's decline bookkeeping (fetch_encode) share.  Own
    namespace: a fused decline must not eat the split device tier's
    decline budget (or vice versa)."""
    return route_state.setdefault(f"fused:{route.name}", {})


def submit(route: FusedRoute, packed, device=None) -> FusedHandle:
    """Commit one packed tuple's inputs to the lane device.  No kernel
    runs here: the fused program dispatches on the lane fetcher thread
    (fetch_encode), where a compile-watchdog wait can never stall
    ingest."""
    batch, lens = packed[0], packed[1]
    if device is not None:
        batch_dev = jax.device_put(batch, device)
        lens_dev = jax.device_put(lens, device)
    else:
        batch_dev, lens_dev = jnp.asarray(batch), jnp.asarray(lens)
    return FusedHandle(route, batch_dev, lens_dev, device)


def fetch_encode(handle: FusedHandle, packed, encoder, merger,
                 ltsv_decoder=None, route_state=None):
    """Run the fused program for a submitted handle through the shared
    fetch driver; returns (BlockResult | None, fetch_seconds).  None =
    the fused tier declined (compile pending, cooldown, or tier
    fraction) — the caller falls back to the split path and counts a
    ``fused_fallbacks``."""
    from .device_common import fetch_encode_driver

    route = handle.route
    state = None
    if route_state is not None:
        state = cooldown_state(route_state, route)
    kernel, kw = route.make_kernel(handle, encoder, merger, ltsv_decoder)
    driver_kw = {k: kw[k] for k in ("ts_keys", "ts_vals_fn",
                                    "small_fetch_fn", "ts_render")
                 if k in kw}
    return fetch_encode_driver(
        kernel, {}, handle.batch_dev, handle.lens_dev, packed, encoder,
        merger, state, kw["suffix"], kw["syslen"],
        scalar_fn=kw["scalar_fn"], fallback_frac=FALLBACK_FRAC,
        decline_limit=DECLINE_LIMIT, cooldown=COOLDOWN,
        elide=kw["elide"], kname_prefix=f"fused/{route.name}",
        compile_timeout_s=fused_compile_timeout_s(),
        route_label=route.name, **driver_kw)
