"""Materialize columnar decode output into Records.

The kernel returns span tables (tpu/rfc5424.py); this module slices the
original line bytes into `Record` objects — the host-side tail of the
batched path.  Rows the kernel flagged (``ok=False``) re-run the scalar
oracle so errors and edge cases stay byte-identical with the reference's
per-line behavior (line_splitter.rs:37-39 stderr contract is handled by
the caller via DecodeError).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..decoders import DecodeError
from ..decoders.rfc5424 import RFC5424Decoder, _unescape_sd_value
from ..record import Record, SDValue, StructuredData

_SCALAR = RFC5424Decoder()


def compute_ts(out: Dict[str, np.ndarray]) -> np.ndarray:
    """Vectorized f64 timestamps from the kernel's int32 components —
    the same integer-nanos-then-divide the oracle uses, so results are
    bit-identical."""
    epoch = (
        out["days"].astype(np.int64) * 86400
        + out["sod"].astype(np.int64)
        - out["off"].astype(np.int64)
    )
    nanos = out["nanos"].astype(np.int64)
    with np.errstate(over="ignore"):
        ts = (epoch * 1_000_000_000 + nanos) / 1e9
    # |epoch| beyond ~year 2262 overflows int64 nanos; redo those rows with
    # exact Python integers (the oracle's arithmetic is arbitrary-precision)
    big = np.abs(epoch) > 9_000_000_000
    if big.any():
        for i in np.flatnonzero(big):
            ts[i] = (int(epoch[i]) * 1_000_000_000 + int(nanos[i])) / 1e9
    return ts


class LineResult:
    """Either a Record or a per-line decode error (message, line)."""

    __slots__ = ("record", "error", "line")

    def __init__(self, record: Optional[Record], error: Optional[str], line: str):
        self.record = record
        self.error = error
        self.line = line


def materialize(
    chunk_bytes: bytes,
    starts: np.ndarray,
    lens: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
) -> List[LineResult]:
    """Build Records for the first ``n_real`` rows.

    ``lens`` are the (possibly clipped) lengths the kernel saw;
    ``orig_lens`` the true line lengths — rows longer than ``max_len``
    bypass the kernel result entirely.
    """
    ts = compute_ts(out).tolist()
    # plain-list views: C-speed bulk conversion once per batch instead of
    # ~40 numpy scalar __getitem__/int() round-trips per record
    o = {k: np.asarray(v).tolist() for k, v in out.items()}
    ok = o["ok"]
    results: List[LineResult] = []
    for n in range(n_real):
        s = int(starts[n])
        ln = int(orig_lens[n])
        raw = chunk_bytes[s:s + ln]
        try:
            line = raw.decode("utf-8")
        except UnicodeDecodeError:
            results.append(LineResult(None, "__utf8__", ""))
            continue
        if not ok[n] or ln > max_len:
            from ..utils.metrics import registry as _m; _m.inc("fallback_rows")
            results.append(_scalar_line(line))
            continue
        ascii_line = len(line) == ln
        if not ascii_line:
            # byte spans != str indices: slice the bytes, decode per field
            results.append(_from_spans_bytes(raw, line, n, o, ts))
            continue
        results.append(_from_spans_str(line, n, o, ts))
    return results


def _scalar_line(line: str) -> LineResult:
    try:
        return LineResult(_SCALAR.decode(line), None, line)
    except DecodeError as e:
        return LineResult(None, str(e), line)


def _build_sd(n: int, o: Dict[str, np.ndarray], take) -> Optional[List[StructuredData]]:
    sd_count = int(o["sd_count"][n])
    if sd_count == 0:
        return None
    blocks = []
    for k in range(sd_count):
        blocks.append(StructuredData(take(int(o["sid_start"][n][k]),
                                          int(o["sid_end"][n][k]))))
    pair_count = int(o["pair_count"][n])
    has_esc = o["val_has_esc"]
    for j in range(pair_count):
        name = take(int(o["name_start"][n][j]), int(o["name_end"][n][j]))
        value = take(int(o["val_start"][n][j]), int(o["val_end"][n][j]))
        if has_esc[n][j]:
            value = _unescape_sd_value(value)
        blocks[int(o["pair_sd"][n][j])].pairs.append(("_" + name, SDValue.string(value)))
    return blocks


def _from_spans_str(line: str, n: int, o: Dict[str, np.ndarray],
                    ts: np.ndarray) -> LineResult:
    def take(a: int, b: int) -> str:
        return line[a:b]

    msg = line[int(o["msg_start"][n]):].strip()
    record = Record(
        ts=float(ts[n]),
        hostname=take(int(o["host_start"][n]), int(o["host_end"][n])),
        facility=int(o["facility"][n]),
        severity=int(o["severity"][n]),
        appname=take(int(o["app_start"][n]), int(o["app_end"][n])),
        procid=take(int(o["proc_start"][n]), int(o["proc_end"][n])),
        msgid=take(int(o["msgid_start"][n]), int(o["msgid_end"][n])),
        msg=msg if msg else None,
        full_msg=line[int(o["full_start"][n]):].rstrip(),
        sd=_build_sd(n, o, take),
    )
    return LineResult(record, None, line)


def _from_spans_bytes(raw: bytes, line: str, n: int, o: Dict[str, np.ndarray],
                      ts: np.ndarray) -> LineResult:
    def take(a: int, b: int) -> str:
        return raw[a:b].decode("utf-8", errors="surrogatepass")

    msg = raw[int(o["msg_start"][n]):].decode("utf-8").strip()
    record = Record(
        ts=float(ts[n]),
        hostname=take(int(o["host_start"][n]), int(o["host_end"][n])),
        facility=int(o["facility"][n]),
        severity=int(o["severity"][n]),
        appname=take(int(o["app_start"][n]), int(o["app_end"][n])),
        procid=take(int(o["proc_start"][n]), int(o["proc_end"][n])),
        msgid=take(int(o["msgid_start"][n]), int(o["msgid_end"][n])),
        msg=msg if msg else None,
        full_msg=raw[int(o["full_start"][n]):].decode("utf-8").rstrip(),
        sd=_build_sd(n, o, take),
    )
    return LineResult(record, None, line)
