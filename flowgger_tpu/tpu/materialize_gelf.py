"""Materialize columnar GELF tokenizer output into Records.

Stage 2 of the simdjson-style split: token spans → Python values.
Key routing and error precedence follow the scalar oracle
(flowgger_tpu/decoders/gelf.py): duplicate keys keep the last value,
processing iterates keys in *sorted* order (serde_json 0.8 BTreeMap),
special keys timestamp/host/short_message/full_message/version/level
are validated with the same messages.  Escaped strings and all numbers
are parsed with ``json.loads`` on the token span, so edge cases
(\\u escapes, leading zeros, huge exponents) behave exactly like the
oracle's whole-line parse.
"""

from __future__ import annotations

import json
from typing import Dict, List

import numpy as np

from ..decoders import DecodeError
from ..decoders.gelf import GelfDecoder, _I64_MIN, _U64_MAX
from ..record import Record, SDValue, SEVERITY_MAX, StructuredData
from ..utils.timeparse import now_precise
from .gelf import VT_FALSE, VT_NULL, VT_NUMBER, VT_STRING, VT_TRUE
from .materialize import LineResult

_PARSE_ERR = "Invalid GELF input, unable to parse as a JSON object"
_SCALAR = GelfDecoder()


def materialize_gelf(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
) -> List[LineResult]:
    out = {k: np.asarray(v).tolist() for k, v in out.items()}
    ok = out["ok"]
    results: List[LineResult] = []
    for n in range(n_real):
        s = int(starts[n])
        ln = int(orig_lens[n])
        raw = chunk_bytes[s:s + ln]
        try:
            line = raw.decode("utf-8")
        except UnicodeDecodeError:
            results.append(LineResult(None, "__utf8__", ""))
            continue
        if not ok[n] or ln > max_len:
            from ..utils.metrics import registry as _m; _m.inc("fallback_rows")
            results.append(_scalar_gelf(line))
            continue
        results.append(_from_spans(line, raw, len(line) == ln, n, out))
    return results


def _scalar_gelf(line: str) -> LineResult:
    try:
        return LineResult(_SCALAR.decode(line), None, line)
    except DecodeError as e:
        return LineResult(None, str(e), line)


def _from_spans(line: str, raw: bytes, byte_ok: bool, n: int,
                o: Dict[str, np.ndarray]) -> LineResult:
    def take(a: int, b: int) -> str:
        if byte_ok:
            return line[a:b]
        return raw[a:b].decode("utf-8")

    obj = {}
    try:
        for k in range(int(o["n_fields"][n])):
            ks, ke = int(o["key_start"][n][k]), int(o["key_end"][n][k])
            key = take(ks, ke)
            if o["key_esc"][n][k]:
                key = json.loads(f'"{key}"')
            elif any(ord(c) < 0x20 for c in key):
                raise ValueError("control char")
            vt = int(o["val_type"][n][k])
            vs, ve = int(o["val_start"][n][k]), int(o["val_end"][n][k])
            if vt == VT_STRING:
                value = take(vs, ve)
                if o["val_esc"][n][k]:
                    value = json.loads(f'"{value}"')
                elif any(ord(c) < 0x20 for c in value):
                    raise ValueError("control char")  # oracle rejects too
            elif vt == VT_NUMBER:
                value = json.loads(take(vs, ve))
            elif vt == VT_TRUE:
                value = True
            elif vt == VT_FALSE:
                value = False
            elif vt == VT_NULL:
                value = None
            else:
                raise ValueError("bad token")
            obj[key] = value  # duplicates: last wins, like json.loads
    except (ValueError, json.JSONDecodeError):
        return LineResult(None, _PARSE_ERR, line)

    # sorted-key routing, identical to the scalar oracle
    sd = StructuredData(None)
    ts = None
    hostname = None
    msg = None
    full_msg = None
    severity = None
    try:
        for key in sorted(obj.keys()):
            value = obj[key]
            if key == "timestamp":
                if isinstance(value, bool) or not isinstance(value, (int, float)):
                    raise DecodeError("Invalid GELF timestamp")
                ts = float(value)
            elif key == "host":
                if not isinstance(value, str):
                    raise DecodeError("GELF host name must be a string")
                hostname = value
            elif key == "short_message":
                if not isinstance(value, str):
                    raise DecodeError("GELF short message must be a string")
                msg = value
            elif key == "full_message":
                if not isinstance(value, str):
                    raise DecodeError("GELF full message must be a string")
                full_msg = value
            elif key == "version":
                if not isinstance(value, str):
                    raise DecodeError("GELF version must be a string")
                if value not in ("1.0", "1.1"):
                    raise DecodeError("Unsupported GELF version")
            elif key == "level":
                if isinstance(value, bool) or not isinstance(value, int) or value < 0:
                    raise DecodeError("Invalid severity level")
                if value > SEVERITY_MAX:
                    raise DecodeError("Invalid severity level (too high)")
                severity = value
            else:
                if isinstance(value, str):
                    sval = SDValue.string(value)
                elif isinstance(value, bool):
                    sval = SDValue.bool_(value)
                elif isinstance(value, float):
                    sval = SDValue.f64(value)
                elif isinstance(value, int):
                    if 0 <= value <= _U64_MAX:
                        sval = SDValue.u64(value)
                    elif _I64_MIN <= value < 0:
                        sval = SDValue.i64(value)
                    else:
                        raise DecodeError("Invalid value type in structured data")
                elif value is None:
                    sval = SDValue.null()
                else:
                    raise DecodeError("Invalid value type in structured data")
                name = key if key.startswith("_") else f"_{key}"
                sd.pairs.append((name, sval))
        if hostname is None:
            raise DecodeError("Missing hostname")
    except DecodeError as e:
        return LineResult(None, str(e), line)

    record = Record(
        ts=ts if ts is not None else now_precise(),
        hostname=hostname,
        severity=severity,
        msg=msg,
        full_msg=full_msg,
        sd=[sd] if sd.pairs else None,
    )
    return LineResult(record, None, line)
