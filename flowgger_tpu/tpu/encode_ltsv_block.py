"""Columnar →LTSV encoding: span tables → one framed output buffer per
batch (ltsv_encoder.rs:65-125 semantics), for the rfc5424, ltsv
(self-encode re-canonicalization), and rfc3164 decoders.

Field order per record: SD pairs (leading ``_`` stripped — i.e. the raw
decoded name span), ltsv_extra config pairs (static, pre-rendered),
host, time, message?, full_message?, level?, facility?, appname?,
procid?, msgid?.  Value escaping (tab/newline → space) is handled two
ways: spans that cannot contain a tab by construction re-emit raw, and
the one that can (a full_message covering a tab-separated LTSV line)
gets one vectorized tab→space pass over its destination intervals
after the gather; rows with newlines (possible only under nul/syslen
framing) fall back.  SD names containing ``:`` (the only key escape)
are screened per-span.
"""


from __future__ import annotations

# byte-identity contract (flowcheck FC03): the scalar counterpart
# this route must stay byte-identical to, and the differential
# test that enforces it
SCALAR_ORACLE = "flowgger_tpu.encoders.ltsv:LTSVEncoder"
DIFF_TEST = "tests/test_encode_ltsv_routes.py::test_ltsv_ltsv_block"

from typing import Dict, Optional

import numpy as np

from ..mergers import Merger
from ..utils.rustfmt import display_f64
from .assemble import (
    build_source,
    concat_segments,
    count_in_spans,
    decimal_segments,
    exclusive_cumsum,
)
from .block_common import (
    BlockResult,
    apply_syslen_prefix,
    finish_block,
    ltsv_extra_blob,
    ltsv_special_screen,
    merger_suffix,
    ts_scratch,
)



def encode_rfc5424_ltsv_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    spec = merger_suffix(merger)
    if spec is None:
        return None
    suffix, syslen = spec

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    val_has_esc = np.asarray(out["val_has_esc"][:n], dtype=bool)
    cand = ok & (lens64 <= max_len) & ~has_high
    if val_has_esc.shape[1]:
        cand &= ~val_has_esc.any(axis=1)

    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
    # rows containing a tab or newline would need LTSV value escaping
    # (both map to space): cumulative count per row span, one pass over
    # the chunk (newlines reach this route via nul/syslen framing)
    esc_cum = np.cumsum((chunk_arr == 9) | (chunk_arr == 10))
    row_esc = count_in_spans(esc_cum, starts64, starts64 + lens64)
    cand &= row_esc == 0
    # SD names containing ':' would need key escaping (rare): count per
    # name span, reduce per row
    pair_count_all = np.asarray(out["pair_count"])[:n]
    if pair_count_all.shape[0] and np.asarray(out["name_start"]).shape[1]:
        P = np.asarray(out["name_start"]).shape[1]
        jmask = np.arange(P)[None, :] < pair_count_all[:, None]
        ns_all = starts64[:, None] + np.asarray(out["name_start"])[:n]
        ne_all = starts64[:, None] + np.asarray(out["name_end"])[:n]
        col_cum = np.cumsum(chunk_arr == ord(":"))
        ncols = np.where(jmask,
                         count_in_spans(col_cum, ns_all, ne_all), 0)
        cand &= ncols.sum(axis=1) == 0

    ridx = np.flatnonzero(cand)
    R = ridx.size
    final_buf = b""
    row_off = np.zeros(1, dtype=np.int64)
    prefix_lens_tier: Optional[np.ndarray] = None

    if R:
        st = starts64[ridx]

        def span(skey, ekey):
            a = st + np.asarray(out[skey])[:n][ridx]
            return a, st + np.asarray(out[ekey])[:n][ridx] - a

        host_s, host_l = span("host_start", "host_end")
        app_s, app_l = span("app_start", "app_end")
        proc_s, proc_l = span("proc_start", "proc_end")
        msgid_s, msgid_l = span("msgid_start", "msgid_end")
        full_s = st + np.asarray(out["full_start"])[:n][ridx]
        full_l = st + np.asarray(out["trim_end"])[:n][ridx] - full_s
        msg_s = st + np.asarray(out["msg_trim_start"])[:n][ridx]
        msg_l = st + np.asarray(out["trim_end"])[:n][ridx] - msg_s

        fac = np.asarray(out["facility"])[:n][ridx].astype(np.int64)
        sev = np.asarray(out["severity"])[:n][ridx].astype(np.int64)
        pc = np.asarray(out["pair_count"])[:n][ridx].astype(np.int64)

        scratch, ts_off, ts_len = ts_scratch(out, n, ridx, display_f64)

        # static extra pairs, key/value-escaped once
        extra_blob = ltsv_extra_blob(encoder.extra)

        consts, offs = build_source(
            b":", b"\t", b"host:", b"\ttime:", b"\tmessage:",
            b"\tfull_message:", b"\tlevel:", b"\tfacility:",
            b"\tappname:", b"\tprocid:", b"\tmsgid:",
            b"0123456789 ", suffix, extra_blob, scratch)
        (o_col, o_tab, o_host, o_time, o_msg, o_full, o_lvl, o_fac,
         o_app, o_proc, o_msgid, o_dec, o_sfx, o_extra, o_ts) = offs
        cbase = int(chunk_arr.size)
        src = np.concatenate([chunk_arr, consts])

        T2 = int(pc.sum())
        if T2:
            rows2 = np.repeat(np.arange(R), pc)
            jop = np.arange(T2) - np.repeat(exclusive_cumsum(pc)[:-1], pc)
            pair_flat = (
                st[rows2] + np.asarray(out["name_start"])[:n][ridx][rows2, jop],
                st[rows2] + np.asarray(out["name_end"])[:n][ridx][rows2, jop],
                st[rows2] + np.asarray(out["val_start"])[:n][ridx][rows2, jop],
                st[rows2] + np.asarray(out["val_end"])[:n][ridx][rows2, jop],
            )
        else:
            pair_flat = None

        fac_d = decimal_segments(fac, cbase + o_dec, width=2)
        has_msg = msg_l > 0
        cols = (
            (cbase + o_extra, len(extra_blob)),
            # "host:" carries no leading tab — the pair stream and the
            # extra blob are tab-terminated, so it is always either the
            # first part or already separated
            (cbase + o_host, len(b"host:")),
            (host_s, host_l),
            (cbase + o_time, len(b"\ttime:")),
            (cbase + o_ts + ts_off, ts_len),
            (np.where(has_msg, cbase + o_msg, 0),
             np.where(has_msg, len(b"\tmessage:"), 0)),
            (msg_s, msg_l),
            (cbase + o_full, len(b"\tfull_message:")),
            (full_s, full_l),
            (cbase + o_lvl, len(b"\tlevel:")),
            (cbase + o_dec + sev, 1),
            (cbase + o_fac, len(b"\tfacility:")),
            (fac_d[0][0::2], fac_d[1][0::2]),
            (fac_d[0][1::2], fac_d[1][1::2]),
            (cbase + o_app, len(b"\tappname:")),
            (app_s, app_l),
            (cbase + o_proc, len(b"\tprocid:")),
            (proc_s, proc_l),
            (cbase + o_msgid, len(b"\tmsgid:")),
            (msgid_s, msgid_l),
            (cbase + o_sfx, len(suffix)),
        )
        return _ltsv_core(chunk_bytes, starts64, lens64, n, cand, ridx,
                          src, cbase, pc, pair_flat, o_col, o_tab,
                          cols, (), suffix, syslen, merger, encoder)

    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder)


def _ltsv_core(chunk_bytes, starts64, lens64, n, cand, ridx, src, cbase,
               pc, pair_flat, o_col, o_tab, fixed_cols, tabfix,
               suffix, syslen, merger, encoder, scalar_fn=None):
    """Segment assembly shared by every →LTSV wrapper.

    Per row: pairs (4 segs each: name ':' value '\\t'), then
    ``fixed_cols`` — (src [R]|scalar, len [R]|scalar) columns; leading
    tabs ride each "\\t<key>:" const.  ``pair_flat``: (ns, ne, vs, ve)
    absolute spans flattened row-major over valid pairs.  ``tabfix``:
    indices into fixed_cols whose gathered bytes get the LTSV value
    escape (tab→space) — one vectorized interval pass over the body."""
    R = ridx.size
    FIXED = len(fixed_cols)
    segc = 4 * pc + FIXED
    rstart = exclusive_cumsum(segc)[:-1]
    S = int(segc.sum())
    seg_src = np.zeros(S, dtype=np.int64)
    seg_len = np.zeros(S, dtype=np.int64)
    T2 = int(pc.sum())
    if T2:
        ns, ne, vs, ve = pair_flat
        rows2 = np.repeat(np.arange(R), pc)
        jop = np.arange(T2) - np.repeat(exclusive_cumsum(pc)[:-1], pc)
        p0 = rstart[rows2] + 4 * jop
        seg_src[p0] = ns
        seg_len[p0] = ne - ns
        seg_src[p0 + 1] = cbase + o_col
        seg_len[p0 + 1] = 1
        seg_src[p0 + 2] = vs
        seg_len[p0 + 2] = ve - vs
        seg_src[p0 + 3] = cbase + o_tab
        seg_len[p0 + 3] = 1

    fd = (rstart + 4 * pc)[:, None] + np.arange(FIXED,
                                                dtype=np.int64)[None, :]
    fsrc = np.empty((R, FIXED), dtype=np.int64)
    flen = np.empty((R, FIXED), dtype=np.int64)
    for k, (s, ln) in enumerate(fixed_cols):
        fsrc[:, k] = s
        flen[:, k] = ln
    seg_src[fd] = fsrc
    seg_len[fd] = flen

    dst0 = exclusive_cumsum(seg_len)
    body = concat_segments(src, seg_src, seg_len, dst0)
    for k in tabfix:
        a = dst0[fd[:, k]]
        ln = flen[:, k]
        d = np.zeros(body.size + 1, dtype=np.int64)
        np.add.at(d, a, 1)
        np.add.at(d, a + ln, -1)
        inside = np.cumsum(d[:-1]) > 0
        body[inside & (body == 9)] = 32
    row_off = np.concatenate([dst0[rstart], dst0[-1:]])
    tier_lens = np.diff(row_off)
    prefix_lens_tier = None
    if syslen:
        final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
            body, row_off, tier_lens)
    else:
        final_buf = body.tobytes()
    kw = {} if scalar_fn is None else {"scalar_fn": scalar_fn}
    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder, **kw)


def encode_ltsv_ltsv_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
    decoder=None,
) -> Optional[BlockResult]:
    """LTSV→LTSV re-canonicalization (the reference's self-encode,
    ltsv_encoder.rs:65-125): pairs keep their raw name/value spans (no
    tab/colon possible by construction), the timestamp re-formats as
    Rust Display, and full_message (the original tab-separated line)
    takes the vectorized tab→space value escape.  Typed ``ltsv_schema``
    rows keep the Record path (per-value rendering is host work)."""
    from .block_common import ltsv_ts_vals, vals_scratch
    from .materialize_ltsv import _scalar_ltsv
    from ..utils.rustfmt import display_f64

    spec = merger_suffix(merger)
    if spec is None:
        return None
    if decoder is not None and getattr(decoder, "schema", None):
        return None
    suffix, syslen = spec

    def scalar_fn(line):
        return _scalar_ltsv(decoder, line)

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    n_parts = np.asarray(out["n_parts"])[:n].astype(np.int64)
    part_start = np.asarray(out["part_start"])[:n]
    part_end = np.asarray(out["part_end"])[:n]
    colon_pos = np.asarray(out["colon_pos"])[:n]
    host_pos = np.asarray(out["host_pos"])[:n]

    P = part_start.shape[1]
    jmask = np.arange(P)[None, :] < n_parts[:, None]
    cand = ok & (lens64 <= max_len) & ~has_high & (host_pos >= 0)
    cand &= ~(jmask & (colon_pos < 0)).any(axis=1)

    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
    # newlines (possible under nul/syslen framing) would need the value
    # escape in arbitrary spans: screen per row, one cumsum pass
    nl_cum = np.cumsum(chunk_arr == 10)
    cand &= count_in_spans(nl_cum, starts64, starts64 + lens64) == 0

    # specials route by NAME; repeated special names → oracle (shared
    # screen, block_common.ltsv_special_screen)
    nlen = np.where(jmask, colon_pos - part_start, 0)
    special_name, uniq_ok = ltsv_special_screen(
        chunk_arr, starts64, part_start, nlen, jmask)
    cand &= uniq_ok

    ridx = np.flatnonzero(cand)
    R = ridx.size
    if not R:
        return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                            b"", np.zeros(1, dtype=np.int64), None,
                            suffix, syslen, merger, encoder,
                            scalar_fn=scalar_fn)
    st = starts64[ridx]

    def sp(a_key, b_key):
        a = np.asarray(out[a_key])[:n][ridx].astype(np.int64)
        b = np.asarray(out[b_key])[:n][ridx].astype(np.int64)
        return st + a, np.maximum(b - a, 0)

    host_s, host_l = sp("host_start", "host_end")
    msg_s, msg_l = sp("msg_start", "msg_end")
    has_msg = np.asarray(out["msg_pos"])[:n][ridx].astype(np.int64) >= 0
    level = np.asarray(out["level_val"])[:n][ridx].astype(np.int64)
    has_lvl = level >= 0

    ts = ltsv_ts_vals(out, n, ridx, chunk_bytes, starts64)
    scratch, ts_off, ts_len = vals_scratch(ts, display_f64)

    extra_blob = ltsv_extra_blob(encoder.extra)

    consts, offs = build_source(
        b":", b"\t", b"host:", b"\ttime:", b"\tmessage:",
        b"\tfull_message:", b"\tlevel:", b"0123456789",
        suffix, extra_blob, scratch)
    (o_col, o_tab, o_host, o_time, o_msg, o_full, o_lvl, o_dec,
     o_sfx, o_extra, o_ts) = offs
    cbase = int(chunk_arr.size)
    src = np.concatenate([chunk_arr, consts])

    # pairs: non-special parts in part order (raw "_"-stripped names)
    is_pair = jmask[ridx] & ~special_name[ridx]
    pc = is_pair.sum(axis=1).astype(np.int64)
    if int(pc.sum()):
        rr, cc = np.nonzero(is_pair)
        rop = rr.astype(np.int64)
        pair_flat = (
            st[rop] + part_start[ridx][rr, cc].astype(np.int64),
            st[rop] + colon_pos[ridx][rr, cc].astype(np.int64),
            st[rop] + colon_pos[ridx][rr, cc].astype(np.int64) + 1,
            st[rop] + part_end[ridx][rr, cc].astype(np.int64),
        )
    else:
        pair_flat = None

    cols = (
        (cbase + o_extra, len(extra_blob)),
        (cbase + o_host, len(b"host:")),
        (host_s, host_l),
        (cbase + o_time, len(b"\ttime:")),
        (cbase + o_ts + ts_off, ts_len),
        (np.where(has_msg, cbase + o_msg, 0),
         np.where(has_msg, len(b"\tmessage:"), 0)),
        (msg_s, np.where(has_msg, msg_l, 0)),
        (cbase + o_full, len(b"\tfull_message:")),
        (st, lens64[ridx]),                      # tab→space fixed below
        (np.where(has_lvl, cbase + o_lvl, 0),
         np.where(has_lvl, len(b"\tlevel:"), 0)),
        (cbase + o_dec + np.maximum(level, 0), np.where(has_lvl, 1, 0)),
        (cbase + o_sfx, len(suffix)),
    )
    return _ltsv_core(chunk_bytes, starts64, lens64, n, cand, ridx,
                      src, cbase, pc, pair_flat, o_col, o_tab,
                      cols, (8,), suffix, syslen, merger, encoder,
                      scalar_fn=scalar_fn)


def encode_rfc3164_ltsv_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    """rfc3164→LTSV: host + re-formatted time + message tail + full
    line + PRI-gated level/facility — the Record shape of
    materialize_rfc3164.py through ltsv_encoder.rs:65-125 (the kernel
    rejects control whitespace, so no value escape can fire here)."""
    from .block_common import vals_scratch
    from .materialize import compute_ts
    from .materialize_rfc3164 import _scalar_3164

    spec = merger_suffix(merger)
    if spec is None:
        return None
    suffix, syslen = spec

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    # no tab/newline screen needed: the rfc3164 kernel's strictness
    # pass already rejects any control whitespace in the line, so no
    # candidate span can need the LTSV value escape
    cand = ok & (lens64 <= max_len) & ~has_high
    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)

    ridx = np.flatnonzero(cand)
    R = ridx.size
    if not R:
        return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                            b"", np.zeros(1, dtype=np.int64), None,
                            suffix, syslen, merger, encoder,
                            scalar_fn=_scalar_3164)
    st = starts64[ridx]
    host_a = st + np.asarray(out["host_start"])[:n][ridx].astype(np.int64)
    host_l = (np.asarray(out["host_end"])[:n][ridx].astype(np.int64)
              - np.asarray(out["host_start"])[:n][ridx].astype(np.int64))
    msg_a = st + np.asarray(out["msg_start"])[:n][ridx].astype(np.int64)
    msg_l = np.maximum(st + lens64[ridx] - msg_a, 0)
    has_pri = np.asarray(out["has_pri"][:n], dtype=bool)[ridx]
    fac = np.asarray(out["facility"])[:n][ridx].astype(np.int64)
    sev = np.asarray(out["severity"])[:n][ridx].astype(np.int64)

    from ..utils.rustfmt import display_f64

    ts = compute_ts({k: np.asarray(v)[:n][ridx]
                     for k, v in out.items()
                     if k in ("days", "sod", "off", "nanos")})
    scratch, ts_off, ts_len = vals_scratch(ts, display_f64)

    extra_blob = ltsv_extra_blob(encoder.extra)

    consts, offs = build_source(
        b":", b"\t", b"host:", b"\ttime:", b"\tmessage:",
        b"\tfull_message:", b"\tlevel:", b"\tfacility:", b"0123456789",
        suffix, extra_blob, scratch)
    (o_col, o_tab, o_host, o_time, o_msg, o_full, o_lvl, o_fac, o_dec,
     o_sfx, o_extra, o_ts) = offs
    cbase = int(chunk_arr.size)
    src = np.concatenate([chunk_arr, consts])

    fac_d = decimal_segments(fac, cbase + o_dec, width=2)
    pc = np.zeros(R, dtype=np.int64)
    cols = (
        (cbase + o_extra, len(extra_blob)),
        (cbase + o_host, len(b"host:")),
        (host_a, host_l),
        (cbase + o_time, len(b"\ttime:")),
        (cbase + o_ts + ts_off, ts_len),
        (cbase + o_msg, len(b"\tmessage:")),
        (msg_a, msg_l),
        (cbase + o_full, len(b"\tfull_message:")),
        (st, lens64[ridx]),
        (np.where(has_pri, cbase + o_lvl, 0),
         np.where(has_pri, len(b"\tlevel:"), 0)),
        (cbase + o_dec + np.where(has_pri, sev, 0),
         np.where(has_pri, 1, 0)),
        (np.where(has_pri, cbase + o_fac, 0),
         np.where(has_pri, len(b"\tfacility:"), 0)),
        (fac_d[0][0::2], np.where(has_pri, fac_d[1][0::2], 0)),
        (fac_d[0][1::2], np.where(has_pri, fac_d[1][1::2], 0)),
        (cbase + o_sfx, len(suffix)),
    )
    return _ltsv_core(chunk_bytes, starts64, lens64, n, cand, ridx,
                      src, cbase, pc, None, o_col, o_tab,
                      cols, (), suffix, syslen, merger, encoder,
                      scalar_fn=_scalar_3164)


def encode_gelf_ltsv_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    """gelf→LTSV: the JSON tokenizer's spans through ltsv_encoder
    semantics.  Pairs emit in the Record's construction order — sorted
    by ORIGINAL key (materialize_gelf routes sorted(obj.keys()); the
    GELF re-encode sorts by final name instead) — with the leading
    ``_`` stripped back off; clean strings / canonical ints re-emit
    verbatim, true/false/null are constants, and the timestamp
    re-formats as Rust Display through the dedup scratch.  Duplicate
    keys (dict last-wins), floats, and escaped strings take the
    oracle."""
    from ..utils.rustfmt import display_f64
    from .encode_gelf_gelf_block import _NAME_CAP, gelf_screen
    from .gelf import VT_FALSE, VT_NULL, VT_NUMBER, VT_STRING, VT_TRUE
    from .materialize_gelf import _scalar_gelf

    spec = merger_suffix(merger)
    if spec is None:
        return None
    suffix, syslen = spec

    s = gelf_screen(chunk_bytes, starts, orig_lens, out, n_real, max_len)
    n, starts64, lens64, cand = (s["n"], s["starts64"], s["lens64"],
                                 s["cand"])
    chunk_arr, kabs, key_e = s["chunk_arr"], s["kabs"], s["key_e"]
    byte_at, vt_at, vspan_at = s["byte_at"], s["vt_at"], s["vspan_at"]
    is_pair = s["is_pair"] & cand[:, None]
    vabs_a, vabs_b = s["vabs_a"], s["vabs_b"]
    val_t = s["val_t"]

    # ---- pair table in ORIGINAL-key sorted order (shared helper;
    # drops duplicate-key rows from cand) --------------------------------
    from .block_common import gelf_sorted_pairs

    rop_s, ns_s, ne_s, pv_t, pv_a, pv_b = gelf_sorted_pairs(
        chunk_arr, starts64, cand, is_pair, kabs, key_e, vabs_a, vabs_b,
        val_t, byte_at, _NAME_CAP)

    ridx = np.flatnonzero(cand)
    R = ridx.size
    if not R:
        return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                            b"", np.zeros(1, dtype=np.int64), None,
                            suffix, syslen, merger, encoder,
                            scalar_fn=_scalar_gelf)

    # timestamps: dedupe span texts, per-unique float + Display
    from .block_common import span_f64_scratch

    scratch, ts_off, ts_len = span_f64_scratch(
        chunk_bytes, s["tsa_all"][ridx], s["tsb_all"][ridx], display_f64)

    extra_blob = ltsv_extra_blob(encoder.extra)
    consts, offs = build_source(
        b":", b"\t", b"host:", b"\ttime:", b"\tmessage:",
        b"\tfull_message:", b"\tlevel:", b"true", b"false",
        suffix, extra_blob, scratch)
    (o_col, o_tab, o_host, o_time, o_msg, o_full, o_lvl, o_true,
     o_false, o_sfx, o_extra, o_ts) = offs
    cbase = int(chunk_arr.size)
    src = np.concatenate([chunk_arr, consts])

    # pair values: verbatim spans for strings/ints, consts for literals.
    # pc counts in ORIGINAL row space then selects the candidate rows —
    # rop_s carries original row ids (a fallback row BEFORE a candidate
    # row must not shift the counts).
    if rop_s.size:
        is_txt = (pv_t == VT_STRING) | (pv_t == VT_NUMBER)
        vs_r = np.where(is_txt, pv_a,
                        np.where(pv_t == VT_TRUE, cbase + o_true,
                                 np.where(pv_t == VT_FALSE,
                                          cbase + o_false, 0)))
        vln = np.where(is_txt, pv_b - pv_a,
                       np.where(pv_t == VT_TRUE, 4,
                                np.where(pv_t == VT_FALSE, 5, 0)))
        pair_flat = (ns_s, ne_s, vs_r, vs_r + vln)
        pc = np.bincount(rop_s, minlength=n)[ridx].astype(np.int64)
    else:
        pair_flat = None
        pc = np.zeros(R, dtype=np.int64)

    host_a, host_b = vspan_at(s["host_f"])
    host_a, host_l = host_a[ridx], (host_b - host_a)[ridx]
    sh_a, sh_b = vspan_at(s["short_f"])
    msg_a, msg_l = sh_a[ridx], (sh_b - sh_a)[ridx]
    has_msg = s["has_short"][ridx]
    fm_a, fm_b = vspan_at(s["full_f"])
    full_a, full_l = fm_a[ridx], (fm_b - fm_a)[ridx]
    has_full = s["has_full"][ridx]
    lv_a, _lv_b = vspan_at(s["lvl_f"])
    lv_a = lv_a[ridx]
    has_lvl = s["has_lvl"][ridx]

    cols = (
        (cbase + o_extra, len(extra_blob)),
        (cbase + o_host, len(b"host:")),
        (host_a, host_l),
        (cbase + o_time, len(b"\ttime:")),
        (cbase + o_ts + ts_off, ts_len),
        (np.where(has_msg, cbase + o_msg, 0),
         np.where(has_msg, len(b"\tmessage:"), 0)),
        (msg_a, np.where(has_msg, msg_l, 0)),
        (np.where(has_full, cbase + o_full, 0),
         np.where(has_full, len(b"\tfull_message:"), 0)),
        (full_a, np.where(has_full, full_l, 0)),
        (np.where(has_lvl, cbase + o_lvl, 0),
         np.where(has_lvl, len(b"\tlevel:"), 0)),
        (lv_a, np.where(has_lvl, 1, 0)),
        (cbase + o_sfx, len(suffix)),
    )
    return _ltsv_core(chunk_bytes, starts64, lens64, n, cand, ridx,
                      src, cbase, pc, pair_flat, o_col, o_tab,
                      cols, (), suffix, syslen, merger, encoder,
                      scalar_fn=_scalar_gelf)
