"""Columnar RFC5424→LTSV encoding: span tables → one framed output
buffer per batch (ltsv_encoder.rs:65-125 semantics).

Field order per record: SD pairs (leading ``_`` stripped — i.e. the raw
decoded name span), ltsv_extra config pairs (static, pre-rendered),
host, time, message?, full_message, level, facility, appname, procid,
msgid.  The fast tier requires rows with no tab anywhere (LTSV's only
value escape that could fire here) and no ``:``/newline in SD names
(the only key escapes), checked vectorially with one cumulative-count
pass over the chunk; everything else is raw spans, constants, digits
and a deduplicated Rust-Display timestamp scratch.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..mergers import Merger
from ..utils.rustfmt import display_f64
from .assemble import (
    build_source,
    concat_segments,
    count_in_spans,
    decimal_segments,
    exclusive_cumsum,
)
from .block_common import (
    BlockResult,
    apply_syslen_prefix,
    finish_block,
    merger_suffix,
    ts_scratch,
)



def encode_rfc5424_ltsv_block(
    chunk_bytes: bytes,
    starts: np.ndarray,
    orig_lens: np.ndarray,
    out: Dict[str, np.ndarray],
    n_real: int,
    max_len: int,
    encoder,
    merger: Optional[Merger],
) -> Optional[BlockResult]:
    spec = merger_suffix(merger)
    if spec is None:
        return None
    suffix, syslen = spec

    n = int(n_real)
    starts64 = np.asarray(starts[:n], dtype=np.int64)
    lens64 = np.asarray(orig_lens[:n], dtype=np.int64)
    ok = np.asarray(out["ok"][:n], dtype=bool)
    has_high = np.asarray(out["has_high"][:n], dtype=bool)
    val_has_esc = np.asarray(out["val_has_esc"][:n], dtype=bool)
    cand = ok & (lens64 <= max_len) & ~has_high
    if val_has_esc.shape[1]:
        cand &= ~val_has_esc.any(axis=1)

    chunk_arr = np.frombuffer(chunk_bytes, dtype=np.uint8)
    # rows containing a tab or newline would need LTSV value escaping
    # (both map to space): cumulative count per row span, one pass over
    # the chunk (newlines reach this route via nul/syslen framing)
    esc_cum = np.cumsum((chunk_arr == 9) | (chunk_arr == 10))
    row_esc = count_in_spans(esc_cum, starts64, starts64 + lens64)
    cand &= row_esc == 0
    # SD names containing ':' would need key escaping (rare): count per
    # name span, reduce per row
    pair_count_all = np.asarray(out["pair_count"])[:n]
    if pair_count_all.shape[0] and np.asarray(out["name_start"]).shape[1]:
        P = np.asarray(out["name_start"]).shape[1]
        jmask = np.arange(P)[None, :] < pair_count_all[:, None]
        ns_all = starts64[:, None] + np.asarray(out["name_start"])[:n]
        ne_all = starts64[:, None] + np.asarray(out["name_end"])[:n]
        col_cum = np.cumsum(chunk_arr == ord(":"))
        ncols = np.where(jmask,
                         count_in_spans(col_cum, ns_all, ne_all), 0)
        cand &= ncols.sum(axis=1) == 0

    ridx = np.flatnonzero(cand)
    R = ridx.size
    final_buf = b""
    row_off = np.zeros(1, dtype=np.int64)
    prefix_lens_tier: Optional[np.ndarray] = None

    if R:
        st = starts64[ridx]

        def span(skey, ekey):
            a = st + np.asarray(out[skey])[:n][ridx]
            return a, st + np.asarray(out[ekey])[:n][ridx] - a

        host_s, host_l = span("host_start", "host_end")
        app_s, app_l = span("app_start", "app_end")
        proc_s, proc_l = span("proc_start", "proc_end")
        msgid_s, msgid_l = span("msgid_start", "msgid_end")
        full_s = st + np.asarray(out["full_start"])[:n][ridx]
        full_l = st + np.asarray(out["trim_end"])[:n][ridx] - full_s
        msg_s = st + np.asarray(out["msg_trim_start"])[:n][ridx]
        msg_l = st + np.asarray(out["trim_end"])[:n][ridx] - msg_s

        fac = np.asarray(out["facility"])[:n][ridx].astype(np.int64)
        sev = np.asarray(out["severity"])[:n][ridx].astype(np.int64)
        pc = np.asarray(out["pair_count"])[:n][ridx].astype(np.int64)

        scratch, ts_off, ts_len = ts_scratch(out, n, ridx, display_f64)

        # static extra pairs, key/value-escaped once
        extra_parts = []
        for k, v in encoder.extra:
            k = k[1:] if k.startswith("_") else k
            k = (k.replace("\n", " ").replace("\t", " ")
                 .replace(":", "_"))
            v = v.replace("\t", " ").replace("\n", " ")
            extra_parts.append(f"{k}:{v}\t".encode("utf-8"))
        extra_blob = b"".join(extra_parts)

        consts, offs = build_source(
            b":", b"\t", b"host:", b"\ttime:", b"\tmessage:",
            b"\tfull_message:", b"\tlevel:", b"\tfacility:",
            b"\tappname:", b"\tprocid:", b"\tmsgid:",
            b"0123456789 ", suffix, extra_blob, scratch)
        (o_col, o_tab, o_host, o_time, o_msg, o_full, o_lvl, o_fac,
         o_app, o_proc, o_msgid, o_dec, o_sfx, o_extra, o_ts) = offs
        cbase = int(chunk_arr.size)
        src = np.concatenate([chunk_arr, consts])

        # per row: pairs (4 segs each: name ':' value '\t') + extra blob
        # (1) + host(2: "host:" span) + time(2) + message(2, zero-len
        # when empty) + full(2) + level(2: const + digit) + facility(3)
        # + appname(2) + procid(2) + msgid(2) + framing suffix(1)
        # leading tabs ride each "\t<key>:" const; the first part is the
        # pair stream (tab-terminated) or the bare "host:" const.
        FIXED = 21
        segc = 4 * pc + FIXED
        rstart = exclusive_cumsum(segc)[:-1]
        S = int(segc.sum())
        seg_src = np.zeros(S, dtype=np.int64)
        seg_len = np.zeros(S, dtype=np.int64)

        T2 = int(pc.sum())
        if T2:
            rows2 = np.repeat(np.arange(R), pc)
            jop = np.arange(T2) - np.repeat(exclusive_cumsum(pc)[:-1], pc)
            ns = st[rows2] + np.asarray(out["name_start"])[:n][ridx][rows2, jop]
            ne = st[rows2] + np.asarray(out["name_end"])[:n][ridx][rows2, jop]
            vs = st[rows2] + np.asarray(out["val_start"])[:n][ridx][rows2, jop]
            ve = st[rows2] + np.asarray(out["val_end"])[:n][ridx][rows2, jop]
            p0 = rstart[rows2] + 4 * jop
            seg_src[p0] = ns
            seg_len[p0] = ne - ns
            seg_src[p0 + 1] = cbase + o_col
            seg_len[p0 + 1] = 1
            seg_src[p0 + 2] = vs
            seg_len[p0 + 2] = ve - vs
            seg_src[p0 + 3] = cbase + o_tab
            seg_len[p0 + 3] = 1

        fd = (rstart + 4 * pc)[:, None] + np.arange(FIXED,
                                                    dtype=np.int64)[None, :]
        fsrc = np.empty((R, FIXED), dtype=np.int64)
        flen = np.empty((R, FIXED), dtype=np.int64)
        fac_d = decimal_segments(fac, cbase + o_dec, width=2)
        has_msg = msg_l > 0
        cols = (
            (cbase + o_extra, len(extra_blob)),
            # "host:" carries no leading tab — the pair stream and the
            # extra blob are tab-terminated, so it is always either the
            # first part or already separated
            (cbase + o_host, len(b"host:")),
            (host_s, host_l),
            (cbase + o_time, len(b"\ttime:")),
            (cbase + o_ts + ts_off, ts_len),
            (np.where(has_msg, cbase + o_msg, 0),
             np.where(has_msg, len(b"\tmessage:"), 0)),
            (msg_s, msg_l),
            (cbase + o_full, len(b"\tfull_message:")),
            (full_s, full_l),
            (cbase + o_lvl, len(b"\tlevel:")),
            (cbase + o_dec + sev, 1),
            (cbase + o_fac, len(b"\tfacility:")),
            (fac_d[0][0::2], fac_d[1][0::2]),
            (fac_d[0][1::2], fac_d[1][1::2]),
            (cbase + o_app, len(b"\tappname:")),
            (app_s, app_l),
            (cbase + o_proc, len(b"\tprocid:")),
            (proc_s, proc_l),
            (cbase + o_msgid, len(b"\tmsgid:")),
            (msgid_s, msgid_l),
            (cbase + o_sfx, len(suffix)),
        )
        for k, (s, ln) in enumerate(cols):
            fsrc[:, k] = s
            flen[:, k] = ln
        fd_flat = fd
        seg_src[fd_flat] = fsrc
        seg_len[fd_flat] = flen

        dst0 = exclusive_cumsum(seg_len)
        body = concat_segments(src, seg_src, seg_len, dst0)
        row_off = np.concatenate([dst0[rstart], dst0[-1:]])
        tier_lens = np.diff(row_off)
        if syslen:
            final_buf, row_off, prefix_lens_tier = apply_syslen_prefix(
                body, row_off, tier_lens)
        else:
            final_buf = body.tobytes()

    return finish_block(chunk_bytes, starts64, lens64, n, cand, ridx,
                        final_buf, row_off, prefix_lens_tier, suffix,
                        syslen, merger, encoder)
