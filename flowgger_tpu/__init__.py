"""flowgger-tpu: a TPU-native log collector.

A from-scratch framework with the capabilities of awslabs/flowgger
(reference mounted at /root/reference): transports → framing → decode →
encode → queue → sinks, driven by the same TOML config surface, with the
hot decode path batched onto TPU via columnar JAX/Pallas kernels
(``input.format = "rfc5424_tpu"`` and friends).

Public API matches the reference's single entry point
(src/lib.rs:18-20): ``flowgger_tpu.start(config_path)``.
"""

from .pipeline import start

__version__ = "0.1.0"

__all__ = ["start", "__version__"]
