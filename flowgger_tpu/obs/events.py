"""Structured degradation events: the flight recorder's journal half.

The pipeline has ~10 distinct degradation rungs; before this module
each surfaced as a scattered stderr print plus (sometimes) a bare
counter, so an operator watching throughput fall could not reconstruct
*which* rung fired, *when*, or *what it cost*.  Every decline site now
calls :func:`emit` with a **typed reason code** — the single emitter:

=========================  =================================================
reason                     fired by
=========================  =================================================
``watchdog_decline``       device_common.guarded_compile_call deadline
``busy_decline``           guarded call queued behind an in-flight compile
``breaker_trip``           tpu/breaker.py CLOSED→OPEN (errors or ratio)
``breaker_recover``        tpu/breaker.py →CLOSED after a cured probe
``economics_switch``       overlap.RouteEconomics / framing.FramingEconomics
                           steady-state winner flip (device↔host,
                           fused↔split, framing↔hostpack)
``aot_reject``             tpu/aot.py boot/entry artifact rejection
``framing_decline``        tpu/framing.py device-framing decline
``fused_fallback``         tpu/batch.py fused tier → split path
``device_error``           tpu/batch.py device/XLA exception (breaker feed)
``tenant_shed``            tenancy/admission.py token-bucket denial
``queue_drop``             utils/bounded_queue.py + tenancy/fairqueue.py
                           shed/drop (cause + tenant attributed)
``rendezvous_failover``    fleet/federation.py — the agreed rendezvous
                           (lowest active rank) moved to another host
``fleet_rebalance``        fleet/federation.py — per-host traffic shares
                           redistributed (join/drain/eviction/capacity)
``roster_restore``         fleet/federation.py — boot used the durable
                           roster journal as bootstrap candidates
``slo_burn``               obs/slo.py — an objective's error budget is
                           burning faster than its threshold on BOTH
                           evaluation windows (fast + slow)
``slo_recover``            obs/slo.py — a burning objective fell back
                           under its burn threshold
``perf_regression``        obs/sentinel.py — a route's live throughput
                           (or fetch cost) sustained a drop against
                           its BENCH-seeded baseline
``spill_begin``            durability/manager.py — the queue crossed
                           the spill watermark and the first overflow
                           batch landed in the on-disk WAL
``spill_replay``           tpu/batch.py replay_spilled — one replay
                           round re-dispatched spilled records through
                           block_submit
``replay_complete``        durability/manager.py — every spilled
                           record has been sink-acknowledged; the
                           backlog is empty
``replay_stall``           durability/manager.py watchdog — nonzero
                           unacked backlog with a pinned replay cursor
                           (SLO-declarable: a stuck replay burns an
                           objective instead of rotting silently)
``admission_tighten``      control/plane.py — the burn-driven AIMD
                           loop multiplicatively tightened a tenant's
                           admitted token-bucket rate (cost = the
                           applied lines/sec rate)
``admission_relax``        control/plane.py — additive recovery raised
                           a controller-tightened tenant rate back
                           toward its configured ceiling
``share_decay``            control/plane.py — sustained local burn /
                           breaker / spill pressure decayed this
                           host's advertised fleet capacity weight
``share_restore``          control/plane.py — pressure cleared; the
                           advertised capacity weight recovered a step
``control_freeze``         control/plane.py — a controller tick was
                           skipped (the control_freeze fault drill /
                           controller death): everything stays frozen
                           at last-applied
``durability_reject``      durability/manager.py — ``mode = require``
                           hard-failed an offer (spill budget
                           exhausted or segment append error); the
                           batch is refused, not silently shed
=========================  =================================================

Each event carries ``(ts, site, reason)`` plus whatever context the
site has — ``route``/``lane``/``tenant``/``detail`` — and a **cost
hint** (``cost`` + ``cost_unit``: lines shed, seconds burned, rows
re-decoded), lands in a bounded ring served under ``/healthz``'s
``events`` section, mirrors to the per-reason ``events_{reason}``
counter family (+ the ``degradation_events`` aggregate), and
optionally appends to a JSONL sink.

``emit(..., msg=...)`` also writes the site's legacy stderr line, so
the one emitter owns both the structured journal and the operator
console — decline sites no longer hand-roll prints.

Config (``[metrics]``)::

    events_ring = 256            # journal depth (default)
    events_path = "ev.jsonl"     # optional JSONL sink
    events_max_mb = 64           # rotate the sink past this size
    events_keep = 3              # rotated files kept (ev.jsonl.1 ...)

Fleet correlation: once ``fleet/federation.py`` calls
:meth:`Journal.set_rank`, every event carries a ``rank`` field so the
``/fleetz`` union of rings stays attributable per host.

Cost model: events fire only on degradation (the healthy hot path
never calls in here), so one lock + deque append + counter bump per
occurrence is noise even under a sustained flood — the ring bounds
memory and the stderr half stays rate-limited where the legacy sites
rate-limited it.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .sink import JsonlSink

DEFAULT_RING = 256

# typed reason codes — the closed vocabulary FC06-adjacent tooling and
# the tests key on; emit() rejects anything else so a typo'd reason is
# a crash in CI, not a silent new counter family
REASONS = (
    "watchdog_decline",
    "busy_decline",
    "breaker_trip",
    "breaker_recover",
    "economics_switch",
    "aot_reject",
    "framing_decline",
    "pallas_decline",
    "fused_fallback",
    "device_error",
    "tenant_shed",
    "queue_drop",
    "rendezvous_failover",
    "fleet_rebalance",
    "roster_restore",
    "slo_burn",
    "slo_recover",
    "perf_regression",
    "spill_begin",
    "spill_replay",
    "replay_complete",
    "replay_stall",
    "admission_tighten",
    "admission_relax",
    "share_decay",
    "share_restore",
    "control_freeze",
    "durability_reject",
)
_REASON_SET = frozenset(REASONS)


class Journal:
    """Bounded degradation-event ring (module singleton ``journal``)."""

    def __init__(self, ring: int = DEFAULT_RING):
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=ring)
        self._counts: Dict[str, int] = {}
        self._total = 0
        self._sink = JsonlSink("events")
        self._rank: Optional[int] = None

    def configure(self, ring: int = DEFAULT_RING,
                  path: Optional[str] = None,
                  max_mb: Optional[float] = None, keep: int = 3) -> None:
        with self._lock:
            self._ring = deque(self._ring, maxlen=max(1, int(ring)))
        self._sink.open(path, max_mb=max_mb, keep=keep)

    def set_rank(self, rank: Optional[int]) -> None:
        """Fleet correlation: stamp every subsequent event with this
        host's fleet rank (federation.Fleet.start)."""
        self._rank = rank

    def emit(self, site: str, reason: str, *,
             detail: Optional[str] = None, route: Optional[str] = None,
             lane: Optional[int] = None, tenant: Optional[str] = None,
             cost: Optional[float] = None, cost_unit: Optional[str] = None,
             msg: Optional[str] = None) -> dict:
        """Record one degradation event.  ``msg`` (when given) is the
        operator's stderr line — the legacy print the structured event
        replaces."""
        if reason not in _REASON_SET:
            raise ValueError(f"unknown degradation reason: {reason!r} "
                             f"(known: {', '.join(REASONS)})")
        event = {"ts": round(time.time(), 4), "site": site,
                 "reason": reason}
        if self._rank is not None:
            event["rank"] = self._rank
        if detail is not None:
            event["detail"] = str(detail)
        if route is not None:
            event["route"] = route
        if lane is not None:
            event["lane"] = int(lane)
        if tenant is not None:
            event["tenant"] = tenant
        if cost is not None:
            event["cost"] = round(float(cost), 6)
            event["cost_unit"] = cost_unit or "units"
        with self._lock:
            self._ring.append(event)
            self._counts[reason] = self._counts.get(reason, 0) + 1
            self._total += 1
        # counter mirror: the registry has its own lock, taken OUTSIDE
        # ours (no nesting, no ordering hazard)
        from ..utils.metrics import registry as _metrics

        _metrics.inc("degradation_events")
        _metrics.inc(f"events_{reason}")
        if msg:
            print(msg, file=sys.stderr)
        self._sink.write(event)
        return event

    # -- export ------------------------------------------------------------
    def snapshot(self) -> List[dict]:
        """The event ring, oldest first (JSON-safe dicts)."""
        with self._lock:
            return [dict(e) for e in self._ring]

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def total(self) -> int:
        with self._lock:
            return self._total

    def health_section(self) -> dict:
        """The ``events`` section of the ``/healthz`` document."""
        with self._lock:
            return {"total": self._total,
                    "counts": dict(self._counts),
                    "ring": [dict(e) for e in self._ring]}

    def reset(self) -> None:
        """Tests only: empty the ring and counts (the registry's
        mirrored counters reset separately via registry.reset())."""
        with self._lock:
            self._ring.clear()
            self._counts.clear()
            self._total = 0
        self._rank = None

    def close(self) -> None:
        self._sink.close()


# the process-wide journal every degradation site imports
journal = Journal()


def emit(site: str, reason: str, **kw) -> dict:
    """Module-level convenience over ``journal.emit`` (the form the
    decline sites call)."""
    return journal.emit(site, reason, **kw)


def configure_from(config) -> None:
    """Wire ``[metrics] events_ring``/``events_path`` (+ the
    ``events_max_mb``/``events_keep`` rotation pair) — pipeline boot;
    no keys = defaults, ring only."""
    ring = config.lookup_int(
        "metrics.events_ring",
        "metrics.events_ring must be an integer (events kept)",
        DEFAULT_RING)
    path = config.lookup_str(
        "metrics.events_path",
        "metrics.events_path must be a string (file)")
    max_mb = config.lookup_float(
        "metrics.events_max_mb",
        "metrics.events_max_mb must be a number (MB before the JSONL "
        "sink rotates)")
    keep = config.lookup_int(
        "metrics.events_keep",
        "metrics.events_keep must be an integer (rotated files kept)", 3)
    try:
        journal.configure(ring=ring, path=path, max_mb=max_mb, keep=keep)
    except OSError as e:
        print(f"events: cannot open {path} ({e}); journal keeps the "
              "in-memory ring only", file=sys.stderr)
        journal.configure(ring=ring)
