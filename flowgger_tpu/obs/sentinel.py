"""Live perf-regression sentinel: per-route throughput (and fetch
cost) vs BENCH-seeded baselines.

The BENCH_r01..rNN series is the repo's committed performance memory,
but until now only a human rereading those files could notice that a
kernel/AOT/economics change quietly lost a hot path's throughput.  The
sentinel closes that loop inside the process: rolling EWMA estimates
of each route's live lines/s (from the ``route_rows_{route}`` counter
family tpu/batch.py feeds per batch) are compared against baselines
seeded from the committed BENCH trajectory (``tools/bench_trend.py``'s
extraction, minimum across the series — the conservative floor the
repo has actually sustained), and a route that holds below
``(1 - drop) x baseline`` for ``sustain`` consecutive ticks raises a
``perf_regression`` typed journal event carrying measured-vs-baseline
cost.  Fetch-B/row regressions mirror the same machinery against the
``fetch_bytes_per_row_{route}`` gauges (a *rise* past
``(1 + rise) x baseline`` is the regression there).

Config — scalar keys on the ``[slo]`` table (the engine's ticker
drives the sentinel)::

    [slo]
    sentinel = true
    sentinel_interval_s = 10     # evaluation cadence
    sentinel_drop = 0.5          # alert below (1-drop) x baseline
    sentinel_rise = 0.5          # fetch-B/row: alert above (1+rise) x
    sentinel_sustain = 3         # consecutive breaching ticks required
    sentinel_bench_root = "."    # BENCH_r*.json dir; absent = no
                                 # seeding, baselines self-learn
    sentinel_min_rows = 256      # ignore ticks with fewer new rows
                                 # (idle != slow)

Routes with no BENCH-mapped baseline self-learn one: the first
sustained traffic establishes a slow EWMA (the "what this box
normally does" estimate) and the fast EWMA is compared against it, so
the sentinel still catches a mid-run cliff on a never-benched route —
it just cannot catch "slow since boot" there.

Gauges: ``sentinel_{route}_ratio`` (live/baseline; the watchable
number) and ``sentinel_{route}_baseline`` (lines/s).  An alerted route
re-arms once it recovers above the threshold, so a flapping route
journals each episode, not each tick.
"""

from __future__ import annotations

import math
import re
import sys
import threading
import time
from typing import Dict, Optional

DEFAULT_INTERVAL_S = 10.0
DEFAULT_DROP = 0.5
DEFAULT_RISE = 0.5
DEFAULT_SUSTAIN = 3
DEFAULT_MIN_ROWS = 256
FAST_TAU_S = 30.0      # live-rate EWMA time constant
SLOW_TAU_S = 600.0     # self-learned baseline time constant

_ROUTE_RX = re.compile(r"route_rows_([A-Za-z0-9_]+)\Z")


class _RouteState:
    __slots__ = ("ewma", "self_base", "last_rows", "last_t",
                 "breach", "alerted", "fetch_breach", "fetch_alerted",
                 "ratio")

    def __init__(self):
        self.ewma: Optional[float] = None
        self.self_base: Optional[float] = None
        self.last_rows: Optional[int] = None
        self.last_t: Optional[float] = None
        self.breach = 0
        self.alerted = False
        self.fetch_breach = 0
        self.fetch_alerted = False
        self.ratio: Optional[float] = None


class Sentinel:
    """Module singleton ``sentinel``; ticked by the SLO engine's
    thread (or directly by tests/bench with a controlled clock)."""

    def __init__(self, registry=None, clock=time.monotonic):
        self._registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self.enabled = False
        self._interval = DEFAULT_INTERVAL_S
        self._drop = DEFAULT_DROP
        self._rise = DEFAULT_RISE
        self._sustain = DEFAULT_SUSTAIN
        self._min_rows = DEFAULT_MIN_ROWS
        self._fast_tau = FAST_TAU_S
        self._slow_tau = SLOW_TAU_S
        self._baselines: Dict[str, Dict[str, float]] = {}
        self._routes: Dict[str, _RouteState] = {}
        self._last_tick: Optional[float] = None
        self._events = 0

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ..utils.metrics import registry as _global

        return _global

    # -- configuration -----------------------------------------------------
    def configure(self, enabled: bool = False,
                  interval_s: float = DEFAULT_INTERVAL_S,
                  drop: float = DEFAULT_DROP, rise: float = DEFAULT_RISE,
                  sustain: int = DEFAULT_SUSTAIN,
                  min_rows: int = DEFAULT_MIN_ROWS,
                  bench_root: Optional[str] = None,
                  fast_tau_s: float = FAST_TAU_S,
                  slow_tau_s: float = SLOW_TAU_S,
                  registry=None) -> None:
        with self._lock:
            self.enabled = bool(enabled)
            self._interval = max(0.0, float(interval_s))
            self._drop = float(drop)
            self._rise = float(rise)
            self._sustain = max(1, int(sustain))
            self._min_rows = max(0, int(min_rows))
            self._fast_tau = max(1e-3, float(fast_tau_s))
            self._slow_tau = max(1e-3, float(slow_tau_s))
            self._routes = {}
            self._last_tick = None
            self._events = 0
            self._baselines = {}
            if registry is not None:
                self._registry = registry
        if enabled and bench_root:
            self.seed_from_bench(bench_root)

    def seed_from_bench(self, root: str) -> Dict[str, Dict[str, float]]:
        """Seed per-route baselines from the committed BENCH series via
        ``tools/bench_trend.py`` (loaded from ``<root>/tools``; an
        unreadable tool or series degrades to self-learned baselines
        with one notice, never a boot failure)."""
        try:
            bt = _load_bench_trend(root)
            baselines = bt.route_baselines(root)
        except (OSError, ImportError, AttributeError, ValueError) as e:
            print(f"sentinel: cannot seed baselines from {root} ({e}); "
                  "baselines will self-learn from live traffic",
                  file=sys.stderr)
            return {}
        with self._lock:
            self._baselines = baselines
        if baselines:
            print("sentinel: seeded baselines for "
                  + ", ".join(f"{r}={b['lines_per_sec']:,.0f}/s"
                              for r, b in sorted(baselines.items())
                              if "lines_per_sec" in b),
                  file=sys.stderr)
        return baselines

    def set_baseline(self, route: str, lines_per_sec: float,
                     fetch_bytes_per_row: Optional[float] = None) -> None:
        """Explicit baseline injection (tests, bench harness)."""
        with self._lock:
            entry = self._baselines.setdefault(route, {})
            entry["lines_per_sec"] = float(lines_per_sec)
            if fetch_bytes_per_row is not None:
                entry["fetch_bytes_per_row"] = float(fetch_bytes_per_row)

    # -- evaluation --------------------------------------------------------
    def maybe_tick(self, now: Optional[float] = None) -> None:
        """Pace off ``sentinel_interval_s`` (the SLO engine ticks more
        often than the sentinel needs)."""
        if not self.enabled:
            return
        now = self._clock() if now is None else now
        if self._last_tick is not None \
                and now - self._last_tick < self._interval:
            return
        self.tick(now)

    def tick(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        self._last_tick = now
        reg = self._reg()
        export = reg.export()
        alerts = []
        for key, rows in export["counters"].items():
            m = _ROUTE_RX.match(key)
            if m is None or not rows:
                continue
            route = m.group(1)
            st = self._routes.get(route)
            if st is None:
                # insert under the lock: health_section() iterates
                # this dict from HTTP handler threads, and a first
                # sighting mid-iteration would raise out of a
                # /healthz render (per-state field reads stay
                # unlocked — benign float races)
                with self._lock:
                    st = self._routes.setdefault(route, _RouteState())
            if st.last_rows is None:
                st.last_rows, st.last_t = rows, now
                continue
            delta, dt = rows - st.last_rows, now - st.last_t
            if delta < self._min_rows or dt <= 0:
                # idle (or sub-threshold trickle) is not evidence of a
                # regression — a drained route must not page anyone.
                # After a LONG idle span, re-anchor the delta window:
                # otherwise the first post-resume tick would average
                # the burst over the whole gap, collapse the EWMA, and
                # fire a false perf_regression on a healthy route
                if dt > 10.0 * max(self._interval, 1.0):
                    st.last_rows, st.last_t = rows, now
                continue
            st.last_rows, st.last_t = rows, now
            inst = delta / dt
            alpha = 1.0 - math.exp(-dt / self._fast_tau)
            st.ewma = inst if st.ewma is None \
                else st.ewma + alpha * (inst - st.ewma)
            slow_alpha = 1.0 - math.exp(-dt / self._slow_tau)
            st.self_base = inst if st.self_base is None \
                else st.self_base + slow_alpha * (inst - st.self_base)
            seeded = self._baselines.get(route, {})
            baseline = seeded.get("lines_per_sec") or st.self_base
            if not baseline or baseline <= 0:
                continue
            ratio = st.ewma / baseline
            st.ratio = ratio
            reg.set_gauge(f"sentinel_{route}_ratio", round(ratio, 4))
            reg.set_gauge(f"sentinel_{route}_baseline", round(baseline, 1))
            if ratio < 1.0 - self._drop:
                st.breach += 1
                if st.breach >= self._sustain and not st.alerted:
                    st.alerted = True
                    alerts.append((route, "lines/s", st.ewma, baseline,
                                   ratio))
            else:
                st.breach = 0
                st.alerted = False  # recovered: re-arm for a new episode
            # fetch-B/row axis: cost going UP is the regression
            fetch_base = seeded.get("fetch_bytes_per_row")
            if fetch_base:
                live_fetch = export["gauges"].get(
                    f"fetch_bytes_per_row_{route}")
                if live_fetch:
                    fr = live_fetch / fetch_base
                    if fr > 1.0 + self._rise:
                        st.fetch_breach += 1
                        if st.fetch_breach >= self._sustain \
                                and not st.fetch_alerted:
                            st.fetch_alerted = True
                            alerts.append((route, "fetch B/row",
                                           live_fetch, fetch_base, fr))
                    else:
                        st.fetch_breach = 0
                        st.fetch_alerted = False
        from . import events as _events

        for route, axis, measured, baseline, ratio in alerts:
            self._events += 1
            _events.emit(
                "obs/sentinel", "perf_regression", route=route,
                detail=f"{axis} {measured:,.1f} vs baseline "
                       f"{baseline:,.1f} ({ratio:.2f}x) sustained "
                       f"{self._sustain} ticks",
                cost=round(abs(1.0 - ratio), 4), cost_unit="ratio",
                msg=f"sentinel: route [{route}] {axis} regression — "
                    f"measured {measured:,.1f} vs baseline "
                    f"{baseline:,.1f} ({ratio:.2f}x)")

    # -- export ------------------------------------------------------------
    def health_section(self) -> dict:
        with self._lock:
            routes = {
                r: {
                    "live": round(st.ewma, 1) if st.ewma else 0.0,
                    "ratio": round(st.ratio, 4)
                    if st.ratio is not None else None,
                    "alerted": st.alerted or st.fetch_alerted,
                }
                for r, st in self._routes.items()
            }
            return {"enabled": self.enabled,
                    "seeded_routes": sorted(self._baselines),
                    "routes": routes,
                    "regressions": self._events}


sentinel = Sentinel()


def configure_from_table(table: dict) -> None:
    """The ``sentinel_*`` scalar keys of the ``[slo]`` table
    (obs/slo.configure_from hands the parsed table over)."""
    from ..config import ConfigError

    enabled = table.get("sentinel", False)
    if not isinstance(enabled, bool):
        raise ConfigError("slo.sentinel must be a boolean")

    def num(key, default):
        v = table.get(key, default)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ConfigError(f"slo.{key} must be a number")
        return float(v)

    root = table.get("sentinel_bench_root")
    if root is not None and not isinstance(root, str):
        raise ConfigError("slo.sentinel_bench_root must be a string "
                          "(directory holding BENCH_r*.json)")
    sentinel.configure(
        enabled=enabled,
        interval_s=num("sentinel_interval_s", DEFAULT_INTERVAL_S),
        drop=num("sentinel_drop", DEFAULT_DROP),
        rise=num("sentinel_rise", DEFAULT_RISE),
        sustain=int(num("sentinel_sustain", DEFAULT_SUSTAIN)),
        min_rows=int(num("sentinel_min_rows", DEFAULT_MIN_ROWS)),
        bench_root=root)


def _load_bench_trend(root: str):
    """Import ``tools/bench_trend.py`` from ``root`` (the BENCH series
    lives beside it in a checkout) or, failing that, from this repo's
    own tree — the tool is the single owner of BENCH-schema walking."""
    import importlib.util
    import os

    candidates = [os.path.join(root, "tools", "bench_trend.py")]
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    candidates.append(os.path.join(here, "tools", "bench_trend.py"))
    for path in candidates:
        if os.path.exists(path):
            spec = importlib.util.spec_from_file_location(
                "flowgger_bench_trend", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            return mod
    raise ImportError(f"tools/bench_trend.py not found under {root}")
