"""Flight recorder: the collector's observability layer.

Three cooperating subsystems, each cheap enough to stay wired through
the hot path permanently:

- :mod:`~flowgger_tpu.obs.trace` — per-batch stage spans.  A monotonic
  batch ID minted at flush follows each batch through
  frame → pack → submit → decode → fetch → encode → sequence → emit;
  completed batch traces park in a bounded ring and dump as Chrome
  trace-event JSON (``tools/trace_dump.py``, ``GET /trace``).  Off by
  default (``[metrics] trace``): when off, every instrumentation site
  is one predicted-false branch.
- :mod:`~flowgger_tpu.obs.events` — the structured degradation
  journal.  Every decline/degradation rung (compile-watchdog decline,
  busy decline, breaker trip/recover, economics re-route, AOT reject,
  framing decline, tenant shed, queue drop) emits one typed event —
  (ts, site, reason, route/lane/tenant, cost hint) — into a bounded
  ring served under ``/healthz``'s ``events`` section, mirrored to
  per-reason counters, optionally appended to a JSONL sink.
- :mod:`~flowgger_tpu.obs.prom` — Prometheus text exposition of the
  full metrics registry (counters, gauges, stage seconds, histogram
  families with ``_count``/``_sum`` + quantiles) at ``GET /metrics``
  on the fleet health server, or on a standalone ``[metrics]
  prom_port`` listener when fleet federation is off.

The pipeline layers import these lazily (inside functions) so the
package stays import-cycle-free: obs depends only on
``utils.metrics`` and the stdlib.
"""

from __future__ import annotations

__all__ = ["events", "prom", "trace"]
