"""Flight recorder: the collector's observability layer.

Three cooperating subsystems, each cheap enough to stay wired through
the hot path permanently:

- :mod:`~flowgger_tpu.obs.trace` — per-batch stage spans.  A monotonic
  batch ID minted at flush follows each batch through
  frame → pack → submit → decode → fetch → encode → sequence → emit;
  completed batch traces park in a bounded ring and dump as Chrome
  trace-event JSON (``tools/trace_dump.py``, ``GET /trace``).  Off by
  default (``[metrics] trace``): when off, every instrumentation site
  is one predicted-false branch.
- :mod:`~flowgger_tpu.obs.events` — the structured degradation
  journal.  Every decline/degradation rung (compile-watchdog decline,
  busy decline, breaker trip/recover, economics re-route, AOT reject,
  framing decline, tenant shed, queue drop) emits one typed event —
  (ts, site, reason, route/lane/tenant, cost hint) — into a bounded
  ring served under ``/healthz``'s ``events`` section, mirrored to
  per-reason counters, optionally appended to a JSONL sink.
- :mod:`~flowgger_tpu.obs.prom` — Prometheus text exposition of the
  full metrics registry (counters, gauges, stage seconds, histogram
  families with ``_count``/``_sum`` + quantiles and the
  bounded-window ``_sample_count`` disclosure) at ``GET /metrics``
  on the fleet health server, or on a standalone ``[metrics]
  prom_port`` listener when fleet federation is off.
- :mod:`~flowgger_tpu.obs.slo` — the SLO engine: ``[slo.*]``-declared
  objectives (latency percentile targets per tenant/route, throughput
  floors, degradation-event rate caps) evaluated continuously with
  Google-SRE multi-window burn rates; ``slo_burn``/``slo_recover``
  typed events, per-objective burn-rate/budget gauges, the ``slo``
  health-document section every /healthz and /fleetz consumer reads.
- :mod:`~flowgger_tpu.obs.sentinel` — the live perf-regression
  sentinel: per-route lines/s (and fetch-B/row) EWMAs compared
  against baselines seeded from the committed BENCH series
  (``tools/bench_trend.py``); a sustained drop journals
  ``perf_regression`` with measured-vs-baseline cost.

The pipeline layers import these lazily (inside functions) so the
package stays import-cycle-free: obs depends only on
``utils.metrics`` and the stdlib.
"""

from __future__ import annotations

__all__ = ["events", "prom", "sentinel", "slo", "trace"]
