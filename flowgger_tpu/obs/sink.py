"""Shared append-only JSONL sink for the flight recorder.

The journal (obs/events.py) and the tracer (obs/trace.py) both stream
completed records to an optional file; this is the ONE implementation
of that lifecycle — open/close under a lock, one JSON object per line,
size-capped rotation, and the error contract both callers rely on:

- ``open()`` raises ``OSError`` (the caller decides its fallback — a
  bad path at configure time is an operator-visible choice);
- ``write()`` is **best-effort**: a runtime failure (disk full, volume
  gone) disables the sink with one stderr notice and never raises —
  the callers sit inside degradation paths (queue shed, breaker trip,
  sequencer emit), and a full disk must never turn recording a
  degradation into a new one.

Rotation (``max_mb``/``keep``): a noisy decline loop used to grow the
journal file without limit — with ``max_mb`` set, a write that pushes
the file past the cap rotates it (``path`` → ``path.1`` → … →
``path.keep``, oldest dropped) and reopens fresh.  Rotation failures
fold into the best-effort write contract above (sink disabled, one
notice).  ``max_mb = None`` keeps the historical unbounded behavior.
"""

from __future__ import annotations

import json
import os
import sys
import threading
from typing import Optional

DEFAULT_KEEP = 3


class JsonlSink:
    def __init__(self, label: str):
        self._label = label
        self._lock = threading.Lock()
        self._fd = None
        self._path: Optional[str] = None
        self._max_bytes: Optional[int] = None
        self._keep = DEFAULT_KEEP
        self._size = 0

    def open(self, path: Optional[str], max_mb: Optional[float] = None,
             keep: int = DEFAULT_KEEP) -> None:
        """Point the sink at ``path`` (None = close).  Raises OSError —
        configure-time callers fall back explicitly.  ``max_mb`` caps
        the live file; ``keep`` rotated files are retained."""
        with self._lock:
            if self._fd is not None:
                self._fd.close()
                self._fd = None
            self._path = path
            self._max_bytes = None if not max_mb or max_mb <= 0 \
                else int(max_mb * 1024 * 1024)
            self._keep = max(1, int(keep))
            self._size = 0
            if path:
                # flowcheck: disable=FC07 -- guarding the fd lifecycle is this lock's whole job: open/rotate/close must be atomic against concurrent write(); there is no "after release" for the handle swap
                self._fd = open(path, "a")
                try:
                    self._size = os.path.getsize(path)
                except OSError:
                    self._size = 0

    @property
    def active(self) -> bool:
        return self._fd is not None

    def _rotate_locked(self) -> None:
        """``path`` → ``path.1`` → … → ``path.keep`` (oldest dropped),
        then reopen fresh.  Caller holds the lock; OSError propagates
        to the write handler, which disables the sink."""
        self._fd.close()
        self._fd = None
        for i in range(self._keep - 1, 0, -1):
            src = f"{self._path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self._path}.{i + 1}")  # flowcheck: disable=FC07 -- rotation must be atomic against concurrent write(): the rename ladder and reopen ARE the guarded state transition; journal sink, never the decode path
        # flowcheck: disable=FC07 -- same rotation transaction: final rename + reopen under the lock that owns the fd
        os.replace(self._path, f"{self._path}.1")
        self._fd = open(self._path, "a")  # flowcheck: disable=FC07 -- reopen completes the same lock-owned rotation transaction
        self._size = 0

    def write(self, doc: dict) -> None:
        """Append one record; a write failure disables the sink (one
        notice) instead of propagating into the recording site."""
        if self._fd is None:
            return
        line = json.dumps(doc, sort_keys=True)
        with self._lock:
            if self._fd is None:
                return
            try:
                if self._max_bytes is not None \
                        and self._size + len(line) + 1 > self._max_bytes \
                        and self._size > 0:
                    self._rotate_locked()
                self._fd.write(line + "\n")
                self._fd.flush()
                self._size += len(line) + 1
            except (OSError, ValueError) as e:
                # ValueError: write on a handle something else closed
                path, self._path = self._path, None
                if self._fd is not None:
                    try:
                        self._fd.close()
                    except OSError:  # flowcheck: disable=FC04 -- already failing; close is best-effort
                        pass
                self._fd = None
                print(f"{self._label}: sink write to {path} failed "
                      f"({e}); sink disabled, in-memory ring keeps "
                      "recording", file=sys.stderr)

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                self._fd.close()
                self._fd = None
