"""Shared append-only JSONL sink for the flight recorder.

The journal (obs/events.py) and the tracer (obs/trace.py) both stream
completed records to an optional file; this is the ONE implementation
of that lifecycle — open/close under a lock, one JSON object per line,
and the error contract both callers rely on:

- ``open()`` raises ``OSError`` (the caller decides its fallback — a
  bad path at configure time is an operator-visible choice);
- ``write()`` is **best-effort**: a runtime failure (disk full, volume
  gone) disables the sink with one stderr notice and never raises —
  the callers sit inside degradation paths (queue shed, breaker trip,
  sequencer emit), and a full disk must never turn recording a
  degradation into a new one.
"""

from __future__ import annotations

import json
import sys
import threading
from typing import Optional


class JsonlSink:
    def __init__(self, label: str):
        self._label = label
        self._lock = threading.Lock()
        self._fd = None
        self._path: Optional[str] = None

    def open(self, path: Optional[str]) -> None:
        """Point the sink at ``path`` (None = close).  Raises OSError —
        configure-time callers fall back explicitly."""
        with self._lock:
            if self._fd is not None:
                self._fd.close()
                self._fd = None
            self._path = path
            if path:
                self._fd = open(path, "a")

    @property
    def active(self) -> bool:
        return self._fd is not None

    def write(self, doc: dict) -> None:
        """Append one record; a write failure disables the sink (one
        notice) instead of propagating into the recording site."""
        if self._fd is None:
            return
        line = json.dumps(doc, sort_keys=True)
        with self._lock:
            if self._fd is None:
                return
            try:
                self._fd.write(line + "\n")
                self._fd.flush()
            except (OSError, ValueError) as e:
                # ValueError: write on a handle something else closed
                path, self._path = self._path, None
                try:
                    self._fd.close()
                except OSError:  # flowcheck: disable=FC04 -- already failing; close is best-effort
                    pass
                self._fd = None
                print(f"{self._label}: sink write to {path} failed "
                      f"({e}); sink disabled, in-memory ring keeps "
                      "recording", file=sys.stderr)

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                self._fd.close()
                self._fd = None
