"""Prometheus text exposition of the metrics registry.

``render()`` turns the full registry snapshot into the text exposition
format (version 0.0.4): every counter (and every cumulative stage-
seconds accumulator) as a ``_total``-suffixed counter, every gauge as
a gauge, every histogram family (``batch_seconds``,
``queue_wait_seconds``, ``e2e_batch_seconds``, …) as a summary with
``quantile`` labels plus ``_count``/``_sum``, and the degradation
journal mirrored once more as a labeled family
(``flowgger_degradation_events_by_reason_total{reason="…"}``) so a
PromQL ``sum by (reason)`` needs no regex over flat names.

Serving:

- fleet on — the fleet health server (fleet/health.py) answers
  ``GET /metrics`` with this text (same process, same registry);
- fleet off — ``[metrics] prom_port`` starts the standalone
  :class:`ObsServer` below, a minimal HTTP listener with the same
  ``GET /metrics`` / ``GET /trace`` / ``GET /healthz`` / ``POST
  /profile`` legs, so single-host deployments scrape without joining a
  fleet.

Names are sanitized to the ``[a-zA-Z_:][a-zA-Z0-9_:]*`` metric charset
and label values escaped per the format spec (backslash, double-quote,
newline); the strict pure-python parser in ``tests/test_obs.py`` is
the contract.
"""

from __future__ import annotations

import json
import re
import sys
import threading
from typing import Dict, Optional

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
NAMESPACE = "flowgger"

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_NAME_FIX = re.compile(r"[^a-zA-Z0-9_:]")

# summary quantiles rendered from each histogram's sliding window —
# the keys utils.metrics.Histogram.snapshot() exports
_QUANTILES = (("0.5", "p50"), ("0.99", "p99"))


def metric_name(raw: str, suffix: str = "") -> str:
    """``flowgger_<sanitized raw><suffix>`` in the legal charset."""
    name = f"{NAMESPACE}_{_NAME_FIX.sub('_', raw)}{suffix}"
    if not _NAME_OK.match(name):  # leading digit after namespace: impossible
        name = "_" + name
    return name


def escape_label_value(value: str) -> str:
    """Label-value escaping per the exposition format: backslash,
    double-quote, and newline."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def render_labeled(name: str, labels: Dict[str, str], value) -> str:
    pairs = ",".join(
        f'{_NAME_FIX.sub("_", k)}="{escape_label_value(str(v))}"'
        for k, v in labels.items())
    return f"{name}{{{pairs}}} {_fmt(value)}"


def _fmt(value) -> str:
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    v = float(value)
    if v != v or v in (float("inf"), float("-inf")):
        return {float("inf"): "+Inf", float("-inf"): "-Inf"}.get(v, "NaN")
    return repr(v)


def render(registry=None, journal=None) -> str:
    """The full exposition document (trailing newline included)."""
    if registry is None:
        from ..utils.metrics import registry as _reg

        registry = _reg
    if journal is None:
        from .events import journal as _journal

        journal = _journal
    export = registry.export()
    lines = []

    for raw, value in sorted(export["counters"].items()):
        name = metric_name(raw, "_total")
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_fmt(int(value))}")
    for raw, value in sorted(export["seconds"].items()):
        # cumulative stage wall-clock: monotonic, so a counter
        name = metric_name(raw, "_total")
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_fmt(float(value))}")
    for raw, value in sorted(export["gauges"].items()):
        name = metric_name(raw)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_fmt(value)}")
    for raw, snap in sorted(export["histograms"].items()):
        name = metric_name(raw)
        # the quantiles come from a BOUNDED sliding sample window, not
        # the full population — say so in the exposition itself, and
        # export the backing sample count so a scraper (and the fleet
        # merge) can judge quantile confidence
        lines.append(f"# HELP {name} summary over a bounded sliding "
                     f"sample window; quantiles are computed from the "
                     f"last {name}_sample_count samples, not the full "
                     f"{name}_count population")
        lines.append(f"# TYPE {name} summary")
        for q, key in _QUANTILES:
            if key in snap:
                lines.append(render_labeled(name, {"quantile": q},
                                            snap[key]))
        lines.append(f"{name}_sum {_fmt(float(snap.get('sum', 0.0)))}")
        lines.append(f"{name}_count {_fmt(int(snap.get('count', 0)))}")
        sc_name = metric_name(raw, "_sample_count")
        lines.append(f"# TYPE {sc_name} gauge")
        lines.append(f"{sc_name} {_fmt(int(snap.get('sample_count', 0)))}")

    counts = journal.counts()
    if counts:
        name = f"{NAMESPACE}_degradation_events_by_reason_total"
        lines.append(f"# TYPE {name} counter")
        for reason, n in sorted(counts.items()):
            lines.append(render_labeled(name, {"reason": reason}, n))
    return "\n".join(lines) + "\n"


class ObsServer:
    """Standalone observability listener for fleet-off deployments
    (``[metrics] prom_port``).  Same legs the fleet health server
    grew, minus the fleet document:

    - ``GET /metrics`` — the text exposition above;
    - ``GET /trace``   — the completed-batch ring as Chrome trace JSON;
    - ``GET /healthz`` — registry snapshot + events ring + trace stats
      (always 200: a solo host has no drain ladder to signal);
    - ``POST /profile`` — toggle the XLA profiler (the SIGUSR2 twin).
    """

    def __init__(self, bind: str, port: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        service = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, fmt, *args):  # noqa: N802 - stdlib name
                pass  # scrapers at 1Hz+ would flood stderr

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - stdlib name
                path = self.path.split("?")[0]
                code, body, ctype = service.handle_get(path)
                self._send(code, body, ctype)

            def do_POST(self):  # noqa: N802 - stdlib name
                path = self.path.split("?")[0]
                code, body, ctype = service.handle_post(path)
                self._send(code, body, ctype)

        self._server = ThreadingHTTPServer((bind, port), Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    # request handling is on the service (shared with tests; the fleet
    # server wires the same render/trace/profile helpers directly)
    def handle_get(self, path: str):
        if path == "/metrics":
            return 200, render().encode(), PROM_CONTENT_TYPE
        if path == "/trace":
            return 200, trace_document(), "application/json"
        if path == "/healthz":
            from ..utils.metrics import registry as _reg

            from .events import journal as _journal
            from .slo import engine as _slo
            from .trace import tracer as _tracer

            doc = {"metrics": _reg.snapshot(include_hist_samples=True),
                   "events": _journal.health_section(),
                   "trace": _tracer.stats(),
                   "slo": _slo.health_section()}
            return 200, json.dumps(doc).encode(), "application/json"
        doc = {"error": "unknown path",
               "paths": ["/metrics", "/trace", "/healthz", "/profile"]}
        return 404, json.dumps(doc).encode(), "application/json"

    def handle_post(self, path: str):
        if path == "/profile":
            return 200, json.dumps(profile_toggle()).encode(), \
                "application/json"
        doc = {"error": "unknown path", "paths": ["/profile"]}
        return 404, json.dumps(doc).encode(), "application/json"

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def addr(self) -> str:
        return f"{self._server.server_address[0]}:{self.port}"

    def start(self, supervisor=None) -> None:
        if self._thread is not None:
            return
        if supervisor is not None:
            self._thread = supervisor.spawn(
                self._server.serve_forever, "obs-http", exhausted="return")
        else:
            self._thread = threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name="obs-http")
            self._thread.start()
        print(f"obs: exposition endpoint http://{self.addr}/metrics",
              file=sys.stderr)

    def stop(self) -> None:
        if self._thread is None:
            return
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError as e:
            print(f"obs-http: shutdown error: {e}", file=sys.stderr)
        # shutdown() already waited for serve_forever to exit; the join
        # closes the last gap (the thread's own teardown) boundedly
        self._thread.join(timeout=2)
        self._thread = None


def trace_document() -> bytes:
    """The ``GET /trace`` body: Chrome trace JSON of the completed
    ring (``{"traceEvents": [...]}`` — loadable by Perfetto and
    chrome://tracing directly)."""
    from .trace import tracer as _tracer

    doc = {"traceEvents": _tracer.chrome_events(),
           "displayTimeUnit": "ms"}
    return json.dumps(doc).encode()


def profile_toggle() -> dict:
    """The ``POST /profile`` body: flip the XLA profiler and report the
    new state (shared by the fleet server and the SIGUSR2 handler)."""
    from ..utils import metrics as _metrics_mod

    profiling, log_dir = _metrics_mod.toggle_jax_profiler()
    return {"ok": True, "profiling": profiling, "log_dir": log_dir}


def maybe_start_from(config, supervisor=None) -> Optional[ObsServer]:
    """Start the standalone listener when ``[metrics] prom_port`` is
    configured (the caller only asks with fleet off — the fleet health
    server carries these legs itself)."""
    port = config.lookup_int(
        "metrics.prom_port",
        "metrics.prom_port must be an integer port (standalone "
        "exposition listener)")
    if port is None:
        return None
    from ..config import ConfigError

    if not 0 <= port < 65536:
        raise ConfigError("metrics.prom_port must be in [0, 65536)")
    bind = config.lookup_str(
        "metrics.prom_bind", "metrics.prom_bind must be a string",
        "127.0.0.1")
    try:
        server = ObsServer(bind, port)
    except OSError as e:
        # a taken port must not kill ingest; the scrape target is gone
        # and the operator is told why
        print(f"obs: cannot bind exposition listener on {bind}:{port} "
              f"({e}); metrics stay reachable via the JSONL reporter",
              file=sys.stderr)
        return None
    server.start(supervisor)
    return server
