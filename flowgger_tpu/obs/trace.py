"""Per-batch span tracing: the flight recorder's timeline half.

A monotonic batch ID is minted when a flush dispatches a packed batch;
every pipeline stage that touches the batch afterwards records a span
(a ``perf_counter`` pair plus row/byte annotations) against that ID —
frame → pack → submit → decode → fetch → encode → sequence → emit —
no matter which thread runs the stage (ingest thread, lane fetcher,
sequencer turnstile).  ``end()`` moves the completed trace into a
bounded ring of finished batches (and, in ``jsonl`` mode, appends it
to a sink), where ``tools/trace_dump.py`` and the health server's
``GET /trace`` leg render it as Chrome trace-event JSON
(Perfetto/chrome://tracing loadable).

Config (``[metrics]``)::

    trace = "off"          # "off" | "ring" | "jsonl"
    trace_ring = 256       # completed batch traces kept (ring/jsonl)
    trace_path = "t.jsonl" # jsonl mode: one JSON object per batch
    trace_max_mb = 64      # rotate the jsonl sink past this size
    trace_keep = 3         # rotated files kept (t.jsonl.1 ...)

Cost model: ``tracer.active`` is a plain attribute — when tracing is
off every instrumentation site is one attribute read and a
predicted-false branch (the ``bench.py --smoke`` obs section gates
this at < 1% of per-chunk e2e cost).  When on, a span append is one
lock + one list append; the ring is a ``deque(maxlen=...)`` so memory
is bounded regardless of uptime.

The stage timeline is wall-clock-anchored once per process
(``perf_counter`` ↔ ``time.time`` epoch pair) so Chrome trace ``ts``
microseconds are absolute and two hosts' dumps can be laid side by
side.  Fleet correlation: once ``fleet/federation.py`` calls
:meth:`Tracer.set_rank`, every completed batch trace carries a
``rank`` field, and ``tools/trace_dump.py --fleet`` merges every
routable host's ring into one document with per-host process lanes.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .sink import JsonlSink

OFF, RING, JSONL = "off", "ring", "jsonl"
MODES = (OFF, RING, JSONL)

DEFAULT_RING = 256

# canonical stage order (used by trace_dump sorting and the tests; a
# span may carry any stage name — these are the ones the pipeline
# records)
STAGES = ("frame", "pack", "submit", "decode", "fetch", "encode",
          "sequence", "emit")


class Tracer:
    """Process-wide batch-span recorder (module singleton ``tracer``)."""

    def __init__(self, ring: int = DEFAULT_RING):
        # plain attribute, read unlocked on the hot path: instrumenting
        # sites check ``tracer.active`` before touching anything else
        self.active = False
        self.mode = OFF
        self._lock = threading.Lock()
        self._next = 0
        self._open: Dict[int, dict] = {}
        self._ring: "deque[dict]" = deque(maxlen=ring)
        self._completed = 0
        self._dropped_open = 0
        self._sink = JsonlSink("trace")
        self._rank: Optional[int] = None
        # perf_counter -> wall anchor, fixed at construction: chrome ts
        # microseconds are absolute wall time
        self._epoch_wall = time.time()
        self._epoch_perf = time.perf_counter()

    # -- configuration -----------------------------------------------------
    def configure(self, mode: str, ring: int = DEFAULT_RING,
                  path: Optional[str] = None,
                  max_mb: Optional[float] = None, keep: int = 3) -> None:
        if mode not in MODES:
            raise ValueError(f"trace mode must be one of {MODES}")
        with self._lock:
            self.mode = mode
            # a reconfigured tracer starts fresh: configure is a boot-
            # time (or test-fixture) action, and stale batches from a
            # previous configuration would skew the new ring's stats
            self._ring = deque(maxlen=max(1, int(ring)))
            self._open.clear()
            self._completed = 0
            self._dropped_open = 0
        self._sink.open(path if mode == JSONL else None,
                        max_mb=max_mb, keep=keep)
        # flipped last: a site observing active=True sees a configured
        # tracer
        self.active = mode != OFF

    def set_rank(self, rank: Optional[int]) -> None:
        """Fleet correlation: stamp every subsequent batch trace with
        this host's fleet rank (federation.Fleet.start)."""
        self._rank = rank

    def close(self) -> None:
        self.active = False
        self._sink.close()

    # -- recording ---------------------------------------------------------
    def begin(self, route: Optional[str] = None) -> Optional[int]:
        """Mint one batch ID (monotonic) and open its trace; returns
        None when tracing is off so call sites can skip annotation
        work entirely."""
        if not self.active:
            return None
        t0 = time.perf_counter()
        with self._lock:
            self._next += 1
            bid = self._next
            if len(self._open) >= 4096:
                # a caller that began but never ended (a batch lost to
                # a crash path) must not leak the open table forever
                self._open.pop(next(iter(self._open)))
                self._dropped_open += 1
            rec = {"bid": bid, "route": route, "t0": t0,
                   "rows": 0, "spans": []}
            if self._rank is not None:
                rec["rank"] = self._rank
            self._open[bid] = rec
        return bid

    def span(self, bid: Optional[int], stage: str, t0: float, t1: float,
             rows: Optional[int] = None, nbytes: Optional[int] = None,
             note: Optional[str] = None) -> None:
        """Record one completed stage span for batch ``bid``.  The
        caller passes the perf_counter pair it already measured for its
        stage metrics, so tracing never adds clock reads of its own."""
        if bid is None or not self.active:
            return
        tname = threading.current_thread().name
        with self._lock:
            rec = self._open.get(bid)
            if rec is None:
                return
            rec["spans"].append({
                "stage": stage, "t0": t0, "t1": t1, "thread": tname,
                **({"rows": int(rows)} if rows is not None else {}),
                **({"bytes": int(nbytes)} if nbytes is not None else {}),
                **({"note": note} if note else {}),
            })
            if rows:
                rec["rows"] = max(rec["rows"], int(rows))

    def end(self, bid: Optional[int],
            e2e_s: Optional[float] = None) -> None:
        """Finish one batch trace: move it to the completed ring (and
        the JSONL sink when configured)."""
        if bid is None:
            return
        with self._lock:
            rec = self._open.pop(bid, None)
            if rec is None:
                return
            rec["t1"] = time.perf_counter()
            if e2e_s is not None:
                rec["e2e_s"] = round(e2e_s, 6)
            self._ring.append(rec)
            self._completed += 1
        if self.mode == JSONL:
            # best-effort: a failed write disables the sink (one
            # notice) — it must never propagate into the sequencer's
            # emit path that is closing this batch
            self._sink.write(rec)

    # -- export ------------------------------------------------------------
    def snapshot(self) -> List[dict]:
        """The completed ring, oldest first (JSON-safe dicts)."""
        with self._lock:
            return [dict(rec) for rec in self._ring]

    def stats(self) -> dict:
        with self._lock:
            return {"mode": self.mode, "completed": self._completed,
                    "ring": len(self._ring), "open": len(self._open),
                    "dropped_open": self._dropped_open}

    def chrome_events(self, traces: Optional[List[dict]] = None
                      ) -> List[dict]:
        """Render batch traces as Chrome trace-event ``"X"`` (complete)
        events: ``ts``/``dur`` in wall-anchored microseconds, ``pid``
        the process, ``tid`` a stable small integer per recorded
        thread name (thread names land in trace metadata events)."""
        if traces is None:
            traces = self.snapshot()
        return chrome_events(traces, self._epoch_wall, self._epoch_perf)


def chrome_events(traces: List[dict], epoch_wall: Optional[float] = None,
                  epoch_perf: Optional[float] = None) -> List[dict]:
    """Pure converter: batch-trace dicts → Chrome trace-event list.
    Used by the live tracer and by ``tools/trace_dump.py`` over a JSONL
    capture (where no live epoch exists — spans then anchor at 0)."""
    if epoch_wall is None or epoch_perf is None:
        epoch_wall, epoch_perf = 0.0, 0.0
    pid = os.getpid()
    tids: Dict[str, int] = {}
    events: List[dict] = []

    def tid_for(name: str) -> int:
        tid = tids.get(name)
        if tid is None:
            tid = len(tids) + 1
            tids[name] = tid
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": name}})
        return tid

    def us(t: float) -> float:
        return round((epoch_wall + (t - epoch_perf)) * 1e6, 3)

    for rec in traces:
        bid = rec.get("bid")
        for sp in rec.get("spans", ()):
            args = {"batch": bid}
            for key in ("rows", "bytes", "note"):
                if key in sp:
                    args[key] = sp[key]
            if rec.get("route"):
                args["route"] = rec["route"]
            events.append({
                "name": sp["stage"], "ph": "X", "cat": "batch",
                "ts": us(sp["t0"]),
                "dur": round(max(0.0, sp["t1"] - sp["t0"]) * 1e6, 3),
                "pid": pid, "tid": tid_for(sp.get("thread", "?")),
                "args": args,
            })
    return events


# the process-wide tracer every pipeline layer imports
tracer = Tracer()


def configure_from(config) -> None:
    """Wire ``[metrics] trace``/``trace_ring``/``trace_path`` (pipeline
    boot; no keys = tracing off, the production default)."""
    mode = config.lookup_str(
        "metrics.trace",
        'metrics.trace must be "off", "ring" or "jsonl"', OFF)
    if mode not in MODES:
        from ..config import ConfigError

        raise ConfigError('metrics.trace must be "off", "ring" or "jsonl"')
    ring = config.lookup_int(
        "metrics.trace_ring",
        "metrics.trace_ring must be an integer (batch traces kept)",
        DEFAULT_RING)
    path = config.lookup_str(
        "metrics.trace_path", "metrics.trace_path must be a string (file)")
    max_mb = config.lookup_float(
        "metrics.trace_max_mb",
        "metrics.trace_max_mb must be a number (MB before the JSONL "
        "sink rotates)")
    keep = config.lookup_int(
        "metrics.trace_keep",
        "metrics.trace_keep must be an integer (rotated files kept)", 3)
    if mode == JSONL and not path:
        from ..config import ConfigError

        raise ConfigError(
            'metrics.trace = "jsonl" requires metrics.trace_path')
    try:
        tracer.configure(mode, ring=ring, path=path, max_mb=max_mb,
                         keep=keep)
    except OSError as e:
        # an unwritable trace sink must never kill ingest: fall back to
        # the in-memory ring and say so
        print(f"trace: cannot open {path} ({e}); falling back to ring "
              "mode", file=sys.stderr)
        tracer.configure(RING, ring=ring)
