"""SLO engine: config-declared objectives evaluated continuously with
Google-SRE-style multi-window burn rates.

PR 13's flight recorder answers *what degraded*; this module answers
the operator's actual question — **is the service meeting its
latency/throughput targets, and how fast is each tenant/route burning
its error budget?**  Objectives are declared as ``[slo.*]`` tables and
evaluated on a background ticker against the metrics the pipeline
already records (no new hot-path instrumentation beyond the per-route
``e2e_batch_seconds_{route}`` / per-tenant ``queue_wait_seconds_
{tenant}`` families and one counter per batch)::

    [slo]
    eval_interval_s = 5            # ticker; 0 = manual tick() (tests)

    [slo.ingest_p99]               # "99% of batches emit under 250ms"
    kind = "latency"
    histogram = "e2e_batch_seconds"  # default; or queue_wait_seconds
    threshold_ms = 250             # a sample this fast is "good"
    objective = 0.99               # good-fraction target (p99 target)
    #route = "rfc5424"             # narrow to one route's family
    #tenant = "acme"               # narrow to one tenant's family

    [slo.acme_floor]               # "acme's admitted rate >= 5k/s"
    kind = "throughput"
    tenant = "acme"                # -> tenant_acme_lines counter
    min_lines_per_sec = 5000
    objective = 0.99               # fraction of ticks at/above floor

    [slo.quiet_journal]            # "degradations stay rare"
    kind = "events"
    #reason = "queue_drop"         # one reason; default: all events
    max_per_sec = 0.5

Burn-rate model (the Google SRE multi-window form): each objective has
an **error budget** — ``1 - objective`` for latency/throughput (the
allowed bad fraction), ``max_per_sec`` for event rates.  The burn rate
over a window is the observed bad share divided by the budget (1.0 =
burning exactly the sustainable rate; 10 = the monthly budget gone in
3 days).  An objective starts **burning** when BOTH the fast window
(default 5m) and the slow window (default 1h) exceed
``burn_threshold`` — the fast window confirms the problem is *current*,
the slow window that it is *significant* — and recovers when the fast
window clears.  Transitions land as typed journal events
(``slo_burn`` / ``slo_recover``, obs/events.py) and every tick
refreshes the ``slo_{name}_burn_rate`` (fast-window burn) and
``slo_{name}_budget_remaining`` (1 − slow-window burn, floored at 0)
gauges.

Latency accounting rides the registry's **observe taps**
(utils/metrics.py): the histogram's own ``observe()`` call increments
a per-objective good/bad pair, so the hot path pays one dict lookup
when no SLO targets that histogram and two guarded increments when one
does — never a second clock read or a sample scan.

The engine is also the home ticker for the regression sentinel
(obs/sentinel.py): one background thread drives both.
"""

from __future__ import annotations

import re
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional

DEFAULT_EVAL_INTERVAL_S = 5.0
DEFAULT_FAST_WINDOW_S = 300.0
DEFAULT_SLOW_WINDOW_S = 3600.0
DEFAULT_OBJECTIVE = 0.99
DEFAULT_BURN_THRESHOLD = 1.0

KINDS = ("latency", "throughput", "events")

# scalar keys accepted at the [slo] table top level (everything else
# at that level must be an objective sub-table); the sentinel_* family
# is parsed by obs/sentinel.configure_from over the same table
ENGINE_KEYS = frozenset({
    "eval_interval_s",
    "sentinel", "sentinel_interval_s", "sentinel_drop", "sentinel_rise",
    "sentinel_sustain", "sentinel_bench_root", "sentinel_min_rows",
})

_NAME_OK = re.compile(r"[A-Za-z0-9_]+\Z")


@dataclass
class Objective:
    """One parsed ``[slo.<name>]`` table (validation in
    :func:`parse_objectives`)."""

    name: str
    kind: str
    metric: str                    # resolved histogram / counter name
    threshold_s: float = 0.0       # latency: good at/under this
    objective: float = DEFAULT_OBJECTIVE
    floor_per_sec: float = 0.0     # throughput: minimum rate
    max_per_sec: float = 0.0       # events: allowed rate (the budget)
    tenant: Optional[str] = None
    route: Optional[str] = None
    fast_window_s: float = DEFAULT_FAST_WINDOW_S
    slow_window_s: float = DEFAULT_SLOW_WINDOW_S
    burn_threshold: float = DEFAULT_BURN_THRESHOLD

    @property
    def budget(self) -> float:
        """Allowed bad fraction (latency/throughput kinds)."""
        return max(1e-9, 1.0 - self.objective)


class _ObjState:
    """Runtime half of one objective: cumulative good/bad accounting
    plus the timestamped point ring the windows diff against."""

    def __init__(self, obj: Objective):
        self.obj = obj
        self.lock = threading.Lock()
        self.total = 0              # latency: samples; throughput: ticks
        self.bad = 0                # over-threshold / below-floor / events
        self.last_counter: Optional[int] = None  # throughput/events
        # (t, total, bad) per tick, pruned past the slow window
        self.points: "deque[tuple]" = deque()
        self.burning = False
        self.fast_burn = 0.0
        self.slow_burn = 0.0
        self.budget_remaining = 1.0

    # the latency observe tap — runs inside Registry.observe, so it
    # must stay two increments under a private lock and never raise
    def tap(self, value: float) -> None:
        with self.lock:
            self.total += 1
            if value > self.obj.threshold_s:
                self.bad += 1


def _num(table: dict, name: str, key: str, default=None,
         required: bool = False):
    from ..config import ConfigError

    v = table.get(key, default)
    if v is None:
        if required:
            raise ConfigError(f"slo.{name}.{key} is required for "
                              f"kind = \"{table.get('kind')}\"")
        return None
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise ConfigError(f"slo.{name}.{key} must be a number")
    return float(v)


def parse_objectives(table: dict) -> List[Objective]:
    """``[slo.*]`` sub-tables → validated :class:`Objective` list.
    Raises ``ConfigError`` with the offending key, matching the
    repo-wide config error style."""
    from ..config import ConfigError

    out: List[Objective] = []
    for name, sub in table.items():
        if not isinstance(sub, dict):
            if name not in ENGINE_KEYS:
                raise ConfigError(
                    f"unknown [slo] key {name!r} (engine keys: "
                    f"{', '.join(sorted(ENGINE_KEYS))}; objectives are "
                    "[slo.<name>] tables)")
            continue
        if not _NAME_OK.match(name):
            raise ConfigError(
                f"slo objective name {name!r} must match [A-Za-z0-9_]+ "
                "(it becomes the slo_{name}_* gauge family)")
        kind = sub.get("kind")
        if kind not in KINDS:
            raise ConfigError(
                f"slo.{name}.kind must be one of {KINDS}")
        tenant = sub.get("tenant")
        route = sub.get("route")
        for dim, val in (("tenant", tenant), ("route", route)):
            if val is not None and (not isinstance(val, str)
                                    or not _NAME_OK.match(val)):
                raise ConfigError(
                    f"slo.{name}.{dim} must be a [A-Za-z0-9_]+ string")
        if tenant is not None and route is not None:
            raise ConfigError(
                f"slo.{name}: tenant and route are mutually exclusive "
                "dimensions (one objective targets one family instance)")
        obj = Objective(name=name, kind=kind, metric="",
                        tenant=tenant, route=route)
        objective = _num(sub, name, "objective")
        if objective is not None:
            if not 0.0 < objective < 1.0:
                raise ConfigError(
                    f"slo.{name}.objective must be in (0, 1)")
            obj.objective = objective
        for key, attr, default in (
                ("fast_window_s", "fast_window_s", DEFAULT_FAST_WINDOW_S),
                ("slow_window_s", "slow_window_s", DEFAULT_SLOW_WINDOW_S),
                ("burn_threshold", "burn_threshold",
                 DEFAULT_BURN_THRESHOLD)):
            v = _num(sub, name, key)
            if v is not None:
                if v <= 0:
                    raise ConfigError(f"slo.{name}.{key} must be > 0")
                setattr(obj, attr, v)
        if obj.fast_window_s >= obj.slow_window_s:
            raise ConfigError(
                f"slo.{name}: fast_window_s must be < slow_window_s "
                "(the fast window confirms currency, the slow one "
                "significance)")
        if kind == "latency":
            hist = sub.get("histogram", "e2e_batch_seconds")
            if not isinstance(hist, str) or not _NAME_OK.match(hist):
                raise ConfigError(
                    f"slo.{name}.histogram must be a histogram name")
            dim = route or tenant
            obj.metric = f"{hist}_{dim}" if dim else hist
            obj.threshold_s = _num(sub, name, "threshold_ms",
                                   required=True) / 1000.0
            if obj.threshold_s <= 0:
                raise ConfigError(
                    f"slo.{name}.threshold_ms must be > 0")
        elif kind == "throughput":
            counter = sub.get("counter")
            if counter is None:
                if tenant:
                    counter = f"tenant_{tenant}_lines"
                elif route:
                    counter = f"route_rows_{route}"
                else:
                    counter = "input_lines"
            if not isinstance(counter, str) or not _NAME_OK.match(counter):
                raise ConfigError(
                    f"slo.{name}.counter must be a counter name")
            obj.metric = counter
            obj.floor_per_sec = _num(sub, name, "min_lines_per_sec",
                                     required=True)
            if obj.floor_per_sec <= 0:
                raise ConfigError(
                    f"slo.{name}.min_lines_per_sec must be > 0")
        else:  # events
            reason = sub.get("reason")
            if reason is not None:
                from .events import REASONS

                if reason not in REASONS:
                    raise ConfigError(
                        f"slo.{name}.reason must be a known degradation "
                        f"reason (one of: {', '.join(REASONS)})")
                obj.metric = f"events_{reason}"
            else:
                obj.metric = "degradation_events"
            obj.max_per_sec = _num(sub, name, "max_per_sec",
                                   required=True)
            if obj.max_per_sec <= 0:
                raise ConfigError(f"slo.{name}.max_per_sec must be > 0")
        known = {"kind", "histogram", "threshold_ms", "objective",
                 "counter", "min_lines_per_sec", "reason", "max_per_sec",
                 "tenant", "route", "fast_window_s", "slow_window_s",
                 "burn_threshold"}
        for key in sub:
            if key not in known:
                raise ConfigError(
                    f"unknown slo.{name}.{key} (known objective keys: "
                    f"{', '.join(sorted(known))})")
        out.append(obj)
    return out


class SloEngine:
    """Evaluates configured objectives on a ticker; module singleton
    ``engine``.  ``clock`` is injectable so tests drive windows
    deterministically."""

    def __init__(self, registry=None, clock=time.monotonic):
        self._registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._states: List[_ObjState] = []
        self._interval = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ticks = 0

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from ..utils.metrics import registry as _global

        return _global

    # -- configuration -----------------------------------------------------
    def configure(self, objectives: List[Objective],
                  interval_s: float = DEFAULT_EVAL_INTERVAL_S,
                  registry=None) -> None:
        """Install objectives (replacing any prior set), register the
        latency observe taps, and (re)start the ticker when
        ``interval_s > 0`` and there is anything to evaluate."""
        self.stop()
        # drop the PREVIOUS configuration's latency taps before
        # re-registering: add_observe_tap only appends, and a pipeline
        # that reconfigures without a registry reset must not leave
        # dead _ObjState closures on the observe hot path forever.
        # (The SLO engine is the registry's only tap consumer.)
        self._reg().clear_observe_taps()
        if registry is not None:
            self._registry = registry
        reg = self._reg()
        with self._lock:
            self._states = [_ObjState(o) for o in objectives]
            self._interval = float(interval_s)
            self._ticks = 0
            for st in self._states:
                if st.obj.kind == "latency":
                    reg.add_observe_tap(st.obj.metric, st.tap)
                # gauges visible from tick zero: a dashboard shows a
                # healthy 0-burn objective, not a missing series
                reg.set_gauge(f"slo_{st.obj.name}_burn_rate", 0.0)
                reg.set_gauge(f"slo_{st.obj.name}_budget_remaining", 1.0)
        from . import sentinel as _sentinel

        if self._interval > 0 and (self._states
                                   or _sentinel.sentinel.enabled):
            # one ticker drives both the objectives and the regression
            # sentinel (it paces itself off its own interval)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="slo-engine")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def reset(self) -> None:
        """Tests: drop objectives, their taps, and the ticker."""
        self.stop()
        self._reg().clear_observe_taps()
        with self._lock:
            self._states = []
            self._ticks = 0

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 - the evaluator must never die silently mid-soak
                print(f"slo: tick failed: {e}", file=sys.stderr)
            from . import sentinel as _sentinel

            _sentinel.sentinel.maybe_tick()

    # -- evaluation --------------------------------------------------------
    @staticmethod
    def _window_delta(points, now: float, window: float):
        """(dt, total_delta, bad_delta) between now's point and the
        oldest point still inside ``window`` (the point *at or before*
        the window edge, so a sparse ring still covers the full span)."""
        if len(points) < 2:
            return 0.0, 0, 0
        newest = points[-1]
        anchor = points[0]
        edge = now - window
        for p in points:
            if p[0] > edge:
                break
            anchor = p
        dt = newest[0] - anchor[0]
        return dt, newest[1] - anchor[1], newest[2] - anchor[2]

    def _burns(self, st: _ObjState, now: float):
        obj = st.obj
        out = []
        for window in (obj.fast_window_s, obj.slow_window_s):
            dt, total_d, bad_d = self._window_delta(st.points, now, window)
            if obj.kind == "events":
                rate = (bad_d / dt) if dt > 0 else 0.0
                out.append(rate / obj.max_per_sec)
            else:
                frac = (bad_d / total_d) if total_d > 0 else 0.0
                out.append(frac / obj.budget)
        return out  # [fast, slow]

    def tick(self, now: Optional[float] = None) -> None:
        """One evaluation pass (the ticker calls this; tests call it
        directly with a controlled ``now``)."""
        now = self._clock() if now is None else now
        reg = self._reg()
        with self._lock:
            states = list(self._states)
            self._ticks += 1
        transitions = []
        for st in states:
            obj = st.obj
            if obj.kind == "latency":
                with st.lock:
                    total, bad = st.total, st.bad
            else:
                value = reg.get(obj.metric)
                if obj.kind == "throughput":
                    if st.last_counter is None or not st.points:
                        # first sighting: no rate yet, no verdict
                        st.last_counter = value
                        st.points.append((now, 0, 0))
                        continue
                    prev_t = st.points[-1][0]
                    dt = now - prev_t
                    inst = ((value - st.last_counter) / dt) if dt > 0 \
                        else obj.floor_per_sec
                    st.last_counter = value
                    total = st.points[-1][1] + 1
                    bad = st.points[-1][2] + \
                        (1 if inst < obj.floor_per_sec else 0)
                else:  # events: cumulative event count IS the bad series
                    total, bad = 0, value
            st.points.append((now, total, bad))
            # prune past the slow window, keeping one anchor before it
            edge = now - obj.slow_window_s
            while len(st.points) > 2 and st.points[1][0] <= edge:
                st.points.popleft()
            fast, slow = self._burns(st, now)
            st.fast_burn, st.slow_burn = fast, slow
            st.budget_remaining = max(0.0, 1.0 - slow)
            reg.set_gauge(f"slo_{obj.name}_burn_rate", round(fast, 4))
            reg.set_gauge(f"slo_{obj.name}_budget_remaining",
                          round(st.budget_remaining, 4))
            th = obj.burn_threshold
            if not st.burning and fast >= th and slow >= th:
                st.burning = True
                transitions.append((st, "slo_burn"))
            elif st.burning and fast < th:
                st.burning = False
                transitions.append((st, "slo_recover"))
        # journal AFTER the evaluation loop: emit() may write the JSONL
        # sink (disk I/O) and must not sit between gauge updates
        from . import events as _events

        for st, reason in transitions:
            obj = st.obj
            verb = "burning" if reason == "slo_burn" else "recovered"
            _events.emit(
                "obs/slo", reason,
                detail=f"{obj.name} ({obj.kind}/{obj.metric}): "
                       f"fast {st.fast_burn:.2f}x, slow "
                       f"{st.slow_burn:.2f}x of budget "
                       f"(threshold {obj.burn_threshold:g}x)",
                route=obj.route, tenant=obj.tenant,
                cost=round(st.fast_burn, 4), cost_unit="burn_rate",
                msg=f"slo: objective [{obj.name}] {verb} — fast-window "
                    f"burn {st.fast_burn:.2f}x, budget remaining "
                    f"{st.budget_remaining:.0%}")

    # -- export ------------------------------------------------------------
    def burn_states(self) -> List[dict]:
        """Per-objective burn snapshot for consumers that *act* on burn
        (control/plane.py): name/kind/tenant/route plus the burning
        flag and both window burns.  Values are whatever the last tick
        computed — the control plane deliberately reuses the engine's
        evaluation instead of re-deriving windows."""
        with self._lock:
            states = list(self._states)
        return [{"name": st.obj.name, "kind": st.obj.kind,
                 "tenant": st.obj.tenant, "route": st.obj.route,
                 "burning": st.burning,
                 "fast_burn": st.fast_burn, "slow_burn": st.slow_burn,
                 "burn_threshold": st.obj.burn_threshold}
                for st in states]

    def health_section(self) -> dict:
        """The ``slo`` section of the health document (and the per-host
        half ``/fleetz`` aggregates)."""
        with self._lock:
            states = list(self._states)
            ticks = self._ticks
        from . import sentinel as _sentinel

        objectives = []
        for st in states:
            obj = st.obj
            entry = {
                "name": obj.name, "kind": obj.kind, "metric": obj.metric,
                "burning": st.burning,
                "fast_burn": round(st.fast_burn, 4),
                "slow_burn": round(st.slow_burn, 4),
                "budget_remaining": round(st.budget_remaining, 4),
                "burn_threshold": obj.burn_threshold,
            }
            if obj.tenant:
                entry["tenant"] = obj.tenant
            if obj.route:
                entry["route"] = obj.route
            objectives.append(entry)
        return {
            "configured": len(objectives),
            "burning": sum(1 for o in objectives if o["burning"]),
            "evaluations": ticks,
            "objectives": objectives,
            "sentinel": _sentinel.sentinel.health_section(),
        }


# the process-wide engine the pipeline, health servers and tests share
engine = SloEngine()


def configure_from(config) -> None:
    """Wire the ``[slo]`` table (pipeline boot, via
    utils.metrics.configure_from).  No table = engine idle, zero
    threads, zero taps.  Also hands the table to the regression
    sentinel (obs/sentinel.py), which shares the engine's ticker."""
    from ..config import ConfigError

    table = config.lookup_table(
        "slo", "slo must be a table of [slo.*] objective tables")
    from . import sentinel as _sentinel

    if table is None:
        engine.reset()
        _sentinel.sentinel.configure(enabled=False)
        return
    interval = table.get("eval_interval_s", DEFAULT_EVAL_INTERVAL_S)
    if isinstance(interval, bool) or not isinstance(interval, (int, float)):
        raise ConfigError("slo.eval_interval_s must be a number "
                          "(seconds; 0 disables the ticker)")
    objectives = parse_objectives(table)
    _sentinel.configure_from_table(table)
    engine.configure(objectives, interval_s=float(interval))
    if objectives:
        print(f"slo: {len(objectives)} objective(s) under evaluation "
              f"every {interval:g}s", file=sys.stderr)
