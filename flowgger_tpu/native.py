"""ctypes bindings for the C++ host tier (native/flowgger_host.cpp).

Loads ``native/libflowgger_host.so``, building it on first use when a
compiler is available; every entry degrades to the numpy implementation
when the library is missing, so the package works without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                           "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libflowgger_host.so")

_lock = threading.Lock()
_lib = None
_tried = False
_DEFAULT_THREADS = min(8, os.cpu_count() or 1)


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        # always invoke make: it no-ops when the .so is fresh and rebuilds
        # when flowgger_host.cpp changed (stale-binary protection)
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR, "-s"],
                           check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError):
            if not os.path.exists(_LIB_PATH):
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.fg_split_lines.restype = ctypes.c_int64
        lib.fg_split_lines.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int, ctypes.POINTER(ctypes.c_int64)]
        lib.fg_pack_lines.restype = None
        lib.fg_pack_lines.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int]
        if hasattr(lib, "fg_split_syslen"):
            lib.fg_split_syslen.restype = ctypes.c_int64
            lib.fg_split_syslen.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int)]
        if hasattr(lib, "fg_concat_segments"):
            lib.fg_concat_segments.restype = None
            lib.fg_concat_segments.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int]
        if hasattr(lib, "fg_crc32c"):
            lib.fg_crc32c.restype = ctypes.c_uint32
            lib.fg_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                      ctypes.c_uint32]
        if hasattr(lib, "fg_snappy_compress"):
            lib.fg_snappy_max_compressed.restype = ctypes.c_int64
            lib.fg_snappy_max_compressed.argtypes = [ctypes.c_int64]
            lib.fg_snappy_compress.restype = ctypes.c_int64
            lib.fg_snappy_compress.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
            lib.fg_snappy_decompress.restype = ctypes.c_int64
            lib.fg_snappy_decompress.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int64]
        if hasattr(lib, "fg_r5_lens"):
            r5common = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
                ctypes.c_int32,
            ]
            lib.fg_r5_lens.restype = None
            lib.fg_r5_lens.argtypes = r5common + [ctypes.c_void_p,
                                                  ctypes.c_int]
            lib.fg_r5_write.restype = None
            lib.fg_r5_write.argtypes = r5common + [ctypes.c_void_p,
                                                   ctypes.c_void_p,
                                                   ctypes.c_int]
        if hasattr(lib, "fg_gelf_lens_v2"):
            common = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
                ctypes.c_int32,
            ]
            lib.fg_gelf_lens_v2.restype = None
            lib.fg_gelf_lens_v2.argtypes = common + [ctypes.c_void_p,
                                                  ctypes.c_int]
            lib.fg_gelf_write_v2.restype = None
            lib.fg_gelf_write_v2.argtypes = common + [ctypes.c_void_p,
                                                   ctypes.c_void_p,
                                                   ctypes.c_int]
        if hasattr(lib, "fg_format_f64_json"):
            lib.fg_format_f64_json.restype = None
            lib.fg_format_f64_json.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_int]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def gelf_rows_available() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "fg_gelf_lens_v2")


def split_chunk_native(chunk: bytes, strip_cr: bool = True
                       ) -> Optional[Tuple[np.ndarray, np.ndarray, int, bytes]]:
    """(starts, lens, n, carry) via the native memchr scan; None when the
    library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    cap = max(16, chunk.count(b"\n") + 1)
    starts = np.empty(cap, dtype=np.int32)
    lens = np.empty(cap, dtype=np.int32)
    carry_start = ctypes.c_int64(0)
    buf = np.frombuffer(chunk, dtype=np.uint8)
    n = lib.fg_split_lines(
        buf.ctypes.data, buf.size,
        starts.ctypes.data, lens.ctypes.data, cap,
        1 if strip_cr else 0, ctypes.byref(carry_start))
    return starts[:n], lens[:n], int(n), chunk[carry_start.value:]


def pack_chunk_native(chunk: bytes, starts: np.ndarray, lens: np.ndarray,
                      max_len: int, n_rows: int,
                      n_threads: Optional[int] = None
                      ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Dense [n_rows, max_len] batch + clipped lens from a contiguous
    chunk; rows past len(starts) are zeroed.  ``n_threads`` overrides
    the library's default memcpy thread count (``input.pack_threads``)."""
    lib = _load()
    if lib is None:
        return None
    n = len(starts)
    batch = np.zeros((n_rows, max_len), dtype=np.uint8)
    lens_out = np.zeros(n_rows, dtype=np.int32)
    if n:
        buf = np.frombuffer(chunk, dtype=np.uint8)
        starts = np.ascontiguousarray(starts, dtype=np.int32)
        in_lens = np.ascontiguousarray(lens, dtype=np.int32)
        lib.fg_pack_lines(
            buf.ctypes.data, buf.size,
            starts.ctypes.data, in_lens.ctypes.data, n,
            max_len, batch.ctypes.data, lens_out.ctypes.data,
            n_threads or _DEFAULT_THREADS)
    return batch, lens_out


def format_f64_json_native(vals: np.ndarray, width: int
                           ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """serde_json-style text for a f64 vector: dense [n, width] u8 rows
    (zero-padded) + per-row lengths, via the threaded native shortest-
    round-trip formatter (exact json_f64 semantics; differential-fuzzed
    in tests/test_native_and_chunks.py).  None when the library is unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "fg_format_f64_json"):
        return None
    vals = np.ascontiguousarray(vals, dtype=np.float64)
    n = vals.size
    txt = np.empty((n, width), dtype=np.uint8)
    lens = np.empty(n, dtype=np.int32)
    if n:
        lib.fg_format_f64_json(vals.ctypes.data, n, txt.ctypes.data,
                               width, lens.ctypes.data, _DEFAULT_THREADS)
    return txt, lens


_CRC32C_TABLE = None


def crc32c(data: bytes, init: int = 0) -> int:
    """CRC32C (Castagnoli), as the Kafka record-batch v2 format requires;
    native table-driven implementation with a Python fallback."""
    lib = _load()
    if lib is not None and hasattr(lib, "fg_crc32c"):
        buf = np.frombuffer(data, dtype=np.uint8)
        return int(lib.fg_crc32c(buf.ctypes.data if len(data) else None,
                                 len(data), init))
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    c = ~init & 0xFFFFFFFF
    t = _CRC32C_TABLE
    for b in data:
        c = (c >> 8) ^ t[(c ^ b) & 0xFF]
    return ~c & 0xFFFFFFFF


def split_syslen_native(chunk: bytes
                        ) -> Optional[Tuple[np.ndarray, np.ndarray, int, int, bool]]:
    """(starts, lens, n, consumed, bad_prefix) via the native octet-count
    scan; None when the library is unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "fg_split_syslen"):
        return None
    # worst case one frame per two bytes ("0 " repeated)
    cap = max(16, len(chunk) // 2 + 1)
    starts = np.empty(cap, dtype=np.int32)
    lens = np.empty(cap, dtype=np.int32)
    consumed = ctypes.c_int64(0)
    err = ctypes.c_int(0)
    buf = np.frombuffer(chunk, dtype=np.uint8)
    n = lib.fg_split_syslen(
        buf.ctypes.data, buf.size, starts.ctypes.data, lens.ctypes.data,
        cap, ctypes.byref(consumed), ctypes.byref(err))
    return starts[:n], lens[:n], int(n), int(consumed.value), bool(err.value)


def gelf_rows_native(chunk: bytes, meta: np.ndarray,
                     pns: np.ndarray, pne: np.ndarray,
                     pvs: np.ndarray, pve: np.ndarray, pesc: np.ndarray,
                     ts_scratch: bytes, suffix: bytes, syslen: bool
                     ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(framed buffer u8, row offsets int64[R+1]) for the tier rows
    described by ``meta`` ([R, 17] int32, see flowgger_host.cpp) — the
    native span→GELF assembly.  None when the library is unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "fg_gelf_lens_v2"):
        return None
    meta = np.ascontiguousarray(meta, dtype=np.int32)
    R = meta.shape[0]
    P = pns.shape[1] if pns.size else 0
    pns = np.ascontiguousarray(pns, dtype=np.int32)
    pne = np.ascontiguousarray(pne, dtype=np.int32)
    pvs = np.ascontiguousarray(pvs, dtype=np.int32)
    pve = np.ascontiguousarray(pve, dtype=np.int32)
    pesc = np.ascontiguousarray(pesc, dtype=np.int32)
    cbuf = np.frombuffer(chunk, dtype=np.uint8)
    tbuf = np.frombuffer(ts_scratch or b"\0", dtype=np.uint8)
    sbuf = np.frombuffer(suffix or b"\0", dtype=np.uint8)
    lens = np.empty(R, dtype=np.int64)
    args = (cbuf.ctypes.data, meta.ctypes.data, R,
            pns.ctypes.data, pne.ctypes.data, pvs.ctypes.data,
            pve.ctypes.data, pesc.ctypes.data, P, tbuf.ctypes.data,
            sbuf.ctypes.data, len(suffix), 1 if syslen else 0)
    lib.fg_gelf_lens_v2(*args, lens.ctypes.data, _DEFAULT_THREADS)
    off = np.empty(R + 1, dtype=np.int64)
    off[0] = 0
    np.cumsum(lens, out=off[1:])
    out = np.empty(int(off[-1]), dtype=np.uint8)
    lib.fg_gelf_write_v2(*args, off.ctypes.data, out.ctypes.data,
                         _DEFAULT_THREADS)
    return out, off


def r5_rows_available() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "fg_r5_lens")


def r5_rows_native(chunk: bytes, meta: np.ndarray,
                   sid_s: np.ndarray, sid_e: np.ndarray,
                   pns: np.ndarray, pne: np.ndarray,
                   pvs: np.ndarray, pve: np.ndarray, psd: np.ndarray,
                   ts_scratch: bytes, suffix: bytes, syslen: bool
                   ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(framed buffer u8, row offsets int64[R+1]) for the RFC5424
    re-encode tier rows (fg_r5_lens/fg_r5_write)."""
    lib = _load()
    if lib is None or not hasattr(lib, "fg_r5_lens"):
        return None
    meta = np.ascontiguousarray(meta, dtype=np.int32)
    R = meta.shape[0]
    SD = sid_s.shape[1] if sid_s.size else 0
    P = pns.shape[1] if pns.size else 0
    arrs = [np.ascontiguousarray(a, dtype=np.int32)
            for a in (sid_s, sid_e, pns, pne, pvs, pve, psd)]
    sid_s, sid_e, pns, pne, pvs, pve, psd = arrs
    cbuf = np.frombuffer(chunk, dtype=np.uint8)
    tbuf = np.frombuffer(ts_scratch or b"\0", dtype=np.uint8)
    sbuf = np.frombuffer(suffix or b"\0", dtype=np.uint8)
    lens = np.empty(R, dtype=np.int64)
    args = (cbuf.ctypes.data, meta.ctypes.data, R,
            sid_s.ctypes.data, sid_e.ctypes.data, SD,
            pns.ctypes.data, pne.ctypes.data, pvs.ctypes.data,
            pve.ctypes.data, psd.ctypes.data, P,
            tbuf.ctypes.data, sbuf.ctypes.data, len(suffix),
            1 if syslen else 0)
    lib.fg_r5_lens(*args, lens.ctypes.data, _DEFAULT_THREADS)
    off = np.empty(R + 1, dtype=np.int64)
    off[0] = 0
    np.cumsum(lens, out=off[1:])
    out = np.empty(int(off[-1]), dtype=np.uint8)
    lib.fg_r5_write(*args, off.ctypes.data, out.ctypes.data,
                    _DEFAULT_THREADS)
    return out, off


def concat_segments_native(src: np.ndarray, seg_src: np.ndarray,
                           seg_len: np.ndarray, dst_off: np.ndarray,
                           total: int) -> Optional[np.ndarray]:
    """Threaded segment-gather memcpy; None when the library is missing
    or lacks the symbol (stale build)."""
    lib = _load()
    if lib is None or not hasattr(lib, "fg_concat_segments"):
        return None
    out = np.empty(total, dtype=np.uint8)
    seg_src = np.ascontiguousarray(seg_src, dtype=np.int64)
    seg_len = np.ascontiguousarray(seg_len, dtype=np.int64)
    dst_off = np.ascontiguousarray(dst_off, dtype=np.int64)
    src = np.ascontiguousarray(src)
    lib.fg_concat_segments(
        src.ctypes.data, seg_src.ctypes.data, seg_len.ctypes.data,
        dst_off.ctypes.data, seg_src.size, out.ctypes.data,
        _DEFAULT_THREADS)
    return out
