"""Disk-backed WAL spill tier with acked replay (``[durability]``).

See ``durability.manager`` for the spill → ack → replay lifecycle and
``durability.segments`` for the crash-safe on-disk format.
"""

from .manager import (
    MODES,
    DurabilityError,
    DurabilityManager,
    SpillRecord,
)
from .segments import (
    SegmentWriter,
    list_segments,
    load_cursor,
    read_segment,
    save_cursor,
    segment_path,
)

__all__ = [
    "MODES",
    "DurabilityError",
    "DurabilityManager",
    "SpillRecord",
    "SegmentWriter",
    "list_segments",
    "load_cursor",
    "read_segment",
    "save_cursor",
    "segment_path",
]
