"""Zero-loss ingestion: watermark-triggered spill + acked replay.

Every overload path before this tier ended in shedding — correct for
lossy syslog, disqualifying for billing/audit pipelines.  The
durability manager turns overflow into a disk detour instead::

    [durability]
    mode = "spill"         # off (default) | spill | require
    spill_dir = "spill"    # segment + cursor directory
    watermark_pct = 80.0   # queue fill that arms spilling
    max_spill_mb = 256     # on-disk budget; full -> decline (spill)
                           #                        or error (require)
    replay_batch = 64      # records per replay drain round

Lifecycle (spill → ack → replay):

- **spill** — when the bounded queue crosses ``watermark_pct``, the
  batch handler hands the packed region (bytes + span metadata, the
  same shape the dispatch lanes carry) to :meth:`DurabilityManager.
  spill`, which appends it to an fsynced segment file
  (``durability.segments``) and parks it on the in-memory backlog.
  In ``spill`` mode a full budget or a failed append *declines to
  shed*: the batch continues down the normal (lossy) dispatch path.
  ``require`` raises :class:`DurabilityError` instead — no silent
  loss, ever.
- **ack** — replayed batches carry an ack callback through the queue
  to the sink (``outputs.ack_item``); the persisted replay cursor
  advances **only on sink acknowledgment**, and fully-acked segment
  files are unlinked.  Records are dispatched at most once per
  process (the in-memory backlog pops on dispatch), so duplicates
  happen only across a crash — the at-least-once window.
- **replay** — the batch handler drains the backlog through the same
  ``block_submit`` path as live ingest (``BatchHandler.
  replay_spilled``): at boot, before fresh ingest is admitted, and
  again at drain, behind the output drain barrier.

Observability: ``spill_begin`` / ``spill_replay`` / ``replay_complete``
events mark the cycle, ``replay_stall`` fires when the cursor pins
with a nonzero backlog (SLO-declarable — a stuck replay burns an
objective instead of rotting silently), and the ``spill_bytes`` /
``spill_segments`` / ``replay_cursor_lag`` gauges plus the
``spill_records`` / ``replayed_lines`` counters ride /healthz and
Prometheus like every other family.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import List, Optional

import numpy as np

from ..utils.metrics import registry as _metrics
from . import segments as _seg

MODES = ("off", "spill", "require")
DEFAULT_WATERMARK_PCT = 80.0
DEFAULT_MAX_SPILL_MB = 256.0
DEFAULT_REPLAY_BATCH = 64


class DurabilityError(RuntimeError):
    """``durability.mode = "require"`` could not make a batch durable."""


class SpillRecord:
    """One spilled packed region, ready for replay dispatch."""

    __slots__ = ("seq", "idx", "fmt", "body", "starts", "lens", "runs", "n")

    def __init__(self, seq, idx, fmt, body, starts, lens, runs, n):
        self.seq = seq
        self.idx = idx
        self.fmt = fmt
        self.body = body
        self.starts = starts
        self.lens = lens
        self.runs = runs
        self.n = n


class DurabilityManager:
    # a pinned cursor under nonzero lag for this long journals a
    # replay_stall event (tests shrink it)
    stall_after_s = 5.0

    @classmethod
    def from_config(cls, config):
        """The configured manager, or None when ``durability.mode`` is
        absent or ``off`` (the zero-overhead default)."""
        from ..config import ConfigError

        mode = config.lookup_str(
            "durability.mode",
            'durability.mode must be "off", "spill" or "require"', "off")
        if mode not in MODES:
            raise ConfigError(
                'durability.mode must be "off", "spill" or "require"')
        if mode == "off":
            return None
        spill_dir = config.lookup_str(
            "durability.spill_dir",
            "durability.spill_dir must be a directory path string", "spill")
        watermark = config.lookup_float(
            "durability.watermark_pct",
            "durability.watermark_pct must be a number (queue fill "
            "percentage that arms spilling)", DEFAULT_WATERMARK_PCT)
        max_mb = config.lookup_float(
            "durability.max_spill_mb",
            "durability.max_spill_mb must be a number (on-disk spill "
            "budget in MB)", DEFAULT_MAX_SPILL_MB)
        replay_batch = config.lookup_int(
            "durability.replay_batch",
            "durability.replay_batch must be an integer (records per "
            "replay round)", DEFAULT_REPLAY_BATCH)
        return cls(mode, spill_dir, watermark_pct=watermark,
                   max_spill_mb=max_mb, replay_batch=replay_batch)

    def __init__(self, mode: str, spill_dir: str,
                 watermark_pct: float = DEFAULT_WATERMARK_PCT,
                 max_spill_mb: float = DEFAULT_MAX_SPILL_MB,
                 replay_batch: int = DEFAULT_REPLAY_BATCH,
                 start_watchdog: bool = True):
        if mode not in MODES:
            raise ValueError(f"unknown durability mode: {mode!r}")
        self.mode = mode
        self.dir = spill_dir
        self.watermark = max(0.0, float(watermark_pct)) / 100.0
        self.max_bytes = int(float(max_spill_mb) * (1 << 20))
        self.replay_batch = max(1, int(replay_batch))
        self._tx = None
        self._lock = threading.Lock()
        self._pending: "deque[SpillRecord]" = deque()
        self._acked: set = set()          # out-of-order (seq, idx) acks
        self._seg_counts: dict = {}       # seq -> known record count
        self._disk_bytes = 0
        self._unacked = 0
        self._cursor_path = os.path.join(spill_dir, "cursor.json")
        os.makedirs(spill_dir, exist_ok=True)
        cursor, err = _seg.load_cursor(self._cursor_path)
        if err is not None:
            _metrics.inc("spill_load_errors")
            print(f"durability: unreadable replay cursor ({err}); "
                  "replaying from the oldest segment", file=sys.stderr)
        self._cursor = cursor
        self._load_backlog()
        # the writer always opens a FRESH segment: appending past a
        # possibly-torn tail (or under a cursor that already consumed a
        # record prefix of the same seq) would corrupt the idx space
        seqs = [s for s in self._seg_counts]
        floor = self._cursor[0] + (1 if self._cursor[1] > 0 else 0)
        start_seq = max(seqs + [floor - 1]) + 1 if seqs else floor
        seg_cap = max(1 << 20, self.max_bytes // 8)
        self._writer = _seg.SegmentWriter(self.dir, seg_cap,
                                          start_seq=start_seq)
        self._set_gauges()
        self._stop = threading.Event()
        self._watchdog = None
        if start_watchdog:
            t = threading.Thread(target=self._watch,
                                 name="durability-watchdog", daemon=True)
            t.start()
            self._watchdog = t

    # -- boot --------------------------------------------------------------
    def _load_backlog(self) -> None:
        """Scan the spill dir: records at or past the cursor become the
        replay backlog; segments fully behind it are stale (a crash
        between cursor save and unlink) and are removed.  Corrupt tails
        degrade — count, recover the prefix, continue."""
        cur_seg, cur_rec = self._cursor
        for seq, path in _seg.list_segments(self.dir):
            if seq < cur_seg:
                try:
                    os.unlink(path)
                except OSError:  # flowcheck: disable=FC04 -- stale-segment cleanup is best-effort; the cursor already skips it
                    pass
                continue
            records, clean = _seg.read_segment(path)
            if not clean:
                _metrics.inc("spill_load_errors")
                print(f"durability: corrupt tail in {path}; "
                      f"{len(records)} whole record(s) recovered",
                      file=sys.stderr)
            self._seg_counts[seq] = len(records)
            try:
                self._disk_bytes += os.path.getsize(path)
            except OSError:  # flowcheck: disable=FC04 -- sizing is advisory; the budget check degrades to optimistic
                pass
            for idx, (hdr, body) in enumerate(records):
                if seq == cur_seg and idx < cur_rec:
                    continue  # already acked in a previous life
                try:
                    rec = SpillRecord(
                        seq, idx, str(hdr["fmt"]), body,
                        np.asarray(hdr["starts"], dtype=np.int32),
                        np.asarray(hdr["lens"], dtype=np.int32),
                        [(r[0], int(r[1])) for r in hdr["runs"]]
                        if hdr.get("runs") else None,
                        int(hdr["n"]))
                except (KeyError, IndexError, TypeError, ValueError):
                    _metrics.inc("spill_load_errors")
                    continue
                self._pending.append(rec)
                self._unacked += 1

    # -- spill (producer side) ---------------------------------------------
    def attach_queue(self, tx) -> None:
        """Bind the bounded queue whose fill fraction arms spilling."""
        self._tx = tx

    def should_spill(self) -> bool:
        tx = self._tx
        if tx is None:
            return False
        fill = getattr(tx, "fill_fraction", None)
        return fill is not None and fill() >= self.watermark

    def spill(self, fmt: str, body, starts, lens, n: int,
              runs=None) -> bool:
        """Durably append one packed region.  True: the WAL owns the
        batch now (the caller drops it from the hot path; replay will
        redeliver).  False: budget full or append failed in ``spill``
        mode — decline-to-shed, the caller continues down the normal
        lossy dispatch path.  ``require`` raises DurabilityError
        instead of declining."""
        n = int(n)
        body = bytes(body)
        starts = np.asarray(starts, dtype=np.int32)[:n]
        lens = np.asarray(lens, dtype=np.int32)[:n]
        hdr = {"fmt": fmt, "n": n,
               "starts": [int(x) for x in starts],
               "lens": [int(x) for x in lens],
               "runs": [[t, int(c)] for t, c in runs] if runs else None}
        # a require-mode hard failure journals + raises AFTER the lock
        # releases: the journal may write a disk sink, and the watchdog
        # and replay threads contend on this same lock
        reject: Optional[str] = None
        with self._lock:
            if self._disk_bytes >= self.max_bytes:
                if self.mode != "require":
                    return False
                reject = ("durability.max_spill_mb exhausted "
                          f"({self._disk_bytes >> 20} MB on disk) with "
                          "mode = require")
            else:
                was_empty = self._unacked == 0
                try:
                    seq, idx, nbytes = self._writer.append(hdr, body)
                except OSError as e:
                    _metrics.inc("spill_io_errors")
                    if self.mode != "require":
                        print(f"durability: segment append failed ({e}); "
                              "batch stays on the lossy path",
                              file=sys.stderr)
                        return False
                    reject = ("segment append failed with "
                              f"mode = require: {e}")
                else:
                    self._seg_counts[seq] = idx + 1
                    self._disk_bytes += nbytes
                    self._pending.append(SpillRecord(seq, idx, fmt, body,
                                                     starts, lens, runs, n))
                    self._unacked += 1
        if reject is not None:
            from ..obs import events as _events

            _events.emit("durability", "durability_reject", detail=reject,
                         cost=n, cost_unit="lines",
                         msg=f"durability: {reject}")
            raise DurabilityError(reject)
        _metrics.inc("spill_records")
        self._set_gauges()
        if was_empty:
            from ..obs import events as _events

            _events.emit("durability", "spill_begin", detail=self.dir,
                         cost=n, cost_unit="lines")
        return True

    # -- replay (consumer side) --------------------------------------------
    def next_records(self, limit: Optional[int] = None) -> List[SpillRecord]:
        """Pop up to ``limit`` (default ``replay_batch``) backlog
        records in replay order.  Dispatch-once per process: a popped
        record leaves the in-memory backlog immediately, so replay
        never duplicates in-process — only the persisted cursor (an
        ack) makes consumption durable, and a crash re-reads anything
        unacked from disk."""
        limit = self.replay_batch if limit is None else max(1, int(limit))
        out: List[SpillRecord] = []
        with self._lock:
            while self._pending and len(out) < limit:
                out.append(self._pending.popleft())
        return out

    def backlog(self) -> int:
        """Records awaiting dispatch this process."""
        with self._lock:
            return len(self._pending)

    def unacked(self) -> int:
        """Records spilled but not yet sink-acknowledged (the replay
        cursor lag)."""
        with self._lock:
            return self._unacked

    def backlog_stats(self) -> dict:
        with self._lock:
            return {"segments": len(self._seg_counts),
                    "bytes": self._disk_bytes,
                    "unacked": self._unacked,
                    "pending": len(self._pending),
                    "cursor": list(self._cursor)}

    def make_ack(self, seq: int, idx: int):
        """Idempotent ack callback for one record — the hook the sink
        fires once the record's bytes are flushed/sent."""
        fired = [False]

        def _ack() -> None:
            if fired[0]:
                return
            fired[0] = True
            self.ack(seq, idx)

        return _ack

    def ack(self, seq: int, idx: int) -> None:
        """Sink acknowledged one record: advance the persisted cursor
        over every contiguously-acked record, unlink fully-acked
        segments, and journal ``replay_complete`` when the backlog
        fully drains."""
        complete = False
        with self._lock:
            cur_seg, cur_rec = self._cursor
            if (seq, idx) in self._acked or seq < cur_seg or (
                    seq == cur_seg and idx < cur_rec):
                return  # duplicate ack (at-least-once redelivery)
            self._acked.add((seq, idx))
            self._unacked = max(0, self._unacked - 1)
            self._advance_locked()
            complete = self._unacked == 0 and not self._pending
        self._set_gauges()
        if complete:
            from ..obs import events as _events

            _events.emit("durability", "replay_complete", detail=self.dir)

    def _advance_locked(self) -> None:
        cur_seg, cur_rec = self._cursor
        moved = False
        while True:
            if (cur_seg, cur_rec) in self._acked:
                self._acked.discard((cur_seg, cur_rec))
                cur_rec += 1
                moved = True
                continue
            count = self._seg_counts.get(cur_seg)
            if (count is not None and cur_rec >= count
                    and cur_seg != self._writer.seq):
                # segment fully acked and no longer open: persist the
                # rollover below, then unlink the file
                path = _seg.segment_path(self.dir, cur_seg)
                try:
                    self._disk_bytes = max(
                        0, self._disk_bytes - os.path.getsize(path))
                    os.unlink(path)
                except OSError:  # flowcheck: disable=FC04 -- unlink is cleanup; the advanced cursor already skips the segment
                    pass
                self._seg_counts.pop(cur_seg, None)
                later = [s for s in self._seg_counts if s > cur_seg]
                cur_seg = min(later) if later else self._writer.seq
                cur_rec = 0
                moved = True
                continue
            break
        if moved:
            self._cursor = (cur_seg, cur_rec)
            try:
                _seg.save_cursor(self._cursor_path, cur_seg, cur_rec)
            except OSError as e:
                # a stale cursor only widens the at-least-once window
                print(f"durability: cursor save failed ({e}); replay "
                      "may redeliver after a crash", file=sys.stderr)

    # -- observability -----------------------------------------------------
    def _set_gauges(self) -> None:
        with self._lock:
            segs = len(self._seg_counts)
            nbytes = self._disk_bytes
            lag = self._unacked
        _metrics.set_gauge("spill_segments", segs)
        _metrics.set_gauge("spill_bytes", nbytes)
        _metrics.set_gauge("replay_cursor_lag", lag)

    def _watch(self) -> None:
        """~1 Hz watchdog: refresh the gauges and journal a
        ``replay_stall`` when the cursor pins under a nonzero backlog
        (once per stall episode; progress or full drain re-arms)."""
        last_cursor, last_t = self._cursor, time.monotonic()
        emitted = False
        while not self._stop.wait(1.0):
            with self._lock:
                lag = self._unacked
                cursor = self._cursor
            self._set_gauges()
            now = time.monotonic()
            if lag == 0 or cursor != last_cursor:
                last_cursor, last_t = cursor, now
                emitted = False
                continue
            if not emitted and now - last_t >= self.stall_after_s:
                emitted = True
                from ..obs import events as _events

                _events.emit(
                    "durability", "replay_stall", detail=self.dir,
                    cost=lag, cost_unit="records",
                    msg=f"durability: replay stalled — {lag} unacked "
                        f"record(s), cursor pinned at {cursor} for "
                        f">{self.stall_after_s:.0f}s")

    def stop(self) -> None:
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2)
        with self._lock:
            # retiring the writer lifts the open-segment exemption in
            # _advance_locked: a fully-acked final segment is unlinked
            # now, on clean shutdown, instead of lingering until
            # boot-time recovery sweeps it
            self._writer.abandon()
            self._advance_locked()
        self._set_gauges()
