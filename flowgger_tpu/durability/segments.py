"""Crash-safe spill segments: the on-disk WAL half of the durability
tier (see ``durability.manager`` for the lifecycle).

One segment file (``spill-<seq:08d>.seg``) is a sequence of framed
records, each one packed ingest region plus its framing metadata::

    MAGIC "FWSP" | hdr_len u32le | body_len u32le | crc32 u32le
    | hdr JSON | body

``hdr`` carries ``{"fmt", "n", "starts", "lens", "runs"}`` — exactly
what ``tpu/pack.pack_spans_2d`` needs to rebuild the device-ready
packed tuple at replay, so replay re-enters at ``block_submit`` with
zero re-framing cost; ``body`` is the raw region bytes.  The CRC
covers header and body together, so a torn append (power loss, or the
``spill_io`` fault site's deliberately-torn write) is detected as a
corrupt tail: :func:`read_segment` recovers the valid prefix and stops
there, never crashing on garbage.

Write discipline mirrors the roster journal (fleet/roster.py):
segments are appended unbuffered (``"ab", buffering=0``) and fsynced
per record, so a record the writer returned from is durable; the
replay cursor is a separate tiny JSON document persisted with the
tmp → flush → fsync → ``os.replace`` idiom, so it is atomically either
the old or the new position — never half-written.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import List, Optional, Tuple

from ..utils import faultinject as _faults

MAGIC = b"FWSP"
_FIXED = struct.Struct("<4sIII")  # magic, hdr_len, body_len, crc32


def segment_path(dirpath: str, seq: int) -> str:
    return os.path.join(dirpath, f"spill-{seq:08d}.seg")


def list_segments(dirpath: str) -> List[Tuple[int, str]]:
    """Sorted ``[(seq, path)]`` of every segment file in the spill
    directory (missing/unreadable directory -> empty, never raises)."""
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    out = []
    for name in names:
        if name.startswith("spill-") and name.endswith(".seg"):
            try:
                seq = int(name[len("spill-"):-len(".seg")])
            except ValueError:
                continue
            out.append((seq, os.path.join(dirpath, name)))
    out.sort()
    return out


def encode_record(hdr: dict, body: bytes) -> bytes:
    hdr_b = json.dumps(hdr, separators=(",", ":")).encode()
    crc = zlib.crc32(hdr_b + body) & 0xFFFFFFFF
    return _FIXED.pack(MAGIC, len(hdr_b), len(body), crc) + hdr_b + body


def read_segment(path: str) -> Tuple[List[Tuple[dict, bytes]], bool]:
    """``(records, clean)``: every validly framed ``(hdr, body)`` in
    on-disk order.  ``clean`` is False when the file ends in a torn or
    corrupt tail (a crash mid-append): reading stops at the first bad
    frame and the valid prefix survives — degradation, not a crash."""
    records: List[Tuple[dict, bytes]] = []
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return records, False
    off, n = 0, len(data)
    while off < n:
        if off + _FIXED.size > n:
            return records, False
        magic, hdr_len, body_len, crc = _FIXED.unpack_from(data, off)
        if magic != MAGIC:
            return records, False
        start = off + _FIXED.size
        end = start + hdr_len + body_len
        if end > n:
            return records, False
        blob = data[start:end]
        if (zlib.crc32(blob) & 0xFFFFFFFF) != crc:
            return records, False
        try:
            hdr = json.loads(blob[:hdr_len])
        except ValueError:
            return records, False
        if not isinstance(hdr, dict):
            return records, False
        records.append((hdr, bytes(blob[hdr_len:])))
        off = end
    return records, True


def load_cursor(path: str):
    """``((segment, record), error)`` — ``((0, 0), None)`` when the
    cursor file is simply absent (fresh spill dir); a present-but-
    unreadable cursor returns ``(0, 0)`` with the error string, which
    restarts replay from the oldest segment (duplicates stay inside the
    at-least-once window — never a loss)."""
    if not os.path.exists(path):
        return (0, 0), None
    try:
        with open(path, "rb") as f:
            doc = json.load(f)
        return (int(doc["segment"]), int(doc["record"])), None
    except (OSError, ValueError, KeyError, TypeError) as e:
        return (0, 0), f"{type(e).__name__}: {e}"


def save_cursor(path: str, segment: int, record: int) -> None:
    """Atomically persist the replay cursor (tmp + flush + fsync +
    ``os.replace`` — the roster-journal idiom)."""
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"segment": int(segment), "record": int(record)}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class SegmentWriter:
    """Fsynced record appender with size-based rotation.

    A failed append (real I/O error, or the ``spill_io`` fault site's
    injected torn write) *abandons* the open segment — subsequent
    appends go to a fresh file — so one bad tail never grows; the
    reader recovers the abandoned segment's valid prefix at the next
    boot."""

    def __init__(self, dirpath: str, max_bytes: int, fsync: bool = True,
                 start_seq: int = 0):
        self.dir = dirpath
        self.max_bytes = max(1, int(max_bytes))
        self.fsync = fsync
        self.seq = int(start_seq)
        self.count = 0       # records appended to the current segment
        self._f = None
        self._size = 0

    def append(self, hdr: dict, body: bytes):
        """Durably append one record; returns ``(seq, idx, nbytes)``.
        Raises OSError on failure — the current segment is abandoned
        first, so the caller may retry into a fresh file."""
        if self._f is not None and self._size >= self.max_bytes:
            self.close()
            self.seq += 1
            self.count = 0
        rec = encode_record(hdr, body)
        if self._f is None:
            self._f = open(segment_path(self.dir, self.seq), "ab",
                           buffering=0)
            self._size = self._f.tell()
        if _faults.enabled() and _faults.fire("spill_io"):
            # a realistic failure leaves a TORN record on disk, not a
            # clean boundary: write a fragment, then fail the append
            try:
                self._f.write(rec[:8])
            except OSError:  # flowcheck: disable=FC04 -- the injected OSError below is the failure under test
                pass
            self.abandon()
            raise OSError("injected spill_io failure (torn segment append)")
        try:
            self._f.write(rec)
            if self.fsync:
                os.fsync(self._f.fileno())
        except OSError:
            self.abandon()
            raise
        idx = self.count
        self.count += 1
        self._size += len(rec)
        return self.seq, idx, len(rec)

    def abandon(self) -> None:
        """The open segment may end in a torn tail: close it and point
        subsequent appends at a fresh segment file."""
        self.close()
        self.seq += 1
        self.count = 0

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:  # flowcheck: disable=FC04 -- close on an already-failed fd is best-effort
                pass
            self._f = None
        self._size = 0
