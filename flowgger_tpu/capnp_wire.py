"""Minimal Cap'n Proto wire format for the fixed `record.capnp` schema.

A from-scratch, dependency-free implementation of exactly the subset the
reference uses: single-segment messages holding one `Record` struct
(2 data words + 9 pointers) with `Pair` composite lists (2 data words +
2 pointers, value union discriminant at data u16[0], bool at bit 16,
f64/i64/u64 at data word 1).  Byte-identical with capnp's bump allocator
for the reference's allocation order (capnp_encoder.rs:45-106 golden test
bytes).  Schema: /root/reference/record.capnp; generated layout:
/root/reference/src/record_capnp.rs:481-483 (Record), 689-691 (Pair),
858-894 (union discriminants: string=0 bool=1 f64=2 i64=3 u64=4 null=5).

Framing (`capnp::serialize::write_message`): u32 little-endian segment
count minus one, u32 sizes per segment, zero padding to a word boundary,
then the raw segments.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

from .record import (
    FACILITY_MISSING,
    Record,
    SDValue,
    SEVERITY_MISSING,
    StructuredData,
)

WORD = 8

# Record struct layout (record_capnp.rs:481-483)
RECORD_DATA_WORDS = 2
RECORD_PTR_WORDS = 9
# data fields
_TS_OFF = 0        # f64 at data byte 0
_FACILITY_OFF = 8  # u8
_SEVERITY_OFF = 9  # u8
# pointer slots
_P_HOSTNAME, _P_APPNAME, _P_PROCID, _P_MSGID = 0, 1, 2, 3
_P_MSG, _P_FULL_MSG, _P_SD_ID, _P_PAIRS, _P_EXTRA = 4, 5, 6, 7, 8

PAIR_DATA_WORDS = 2
PAIR_PTR_WORDS = 2
_UNION_DISCRIMINANTS = {
    SDValue.STRING: 0,
    SDValue.BOOL: 1,
    SDValue.F64: 2,
    SDValue.I64: 3,
    SDValue.U64: 4,
    SDValue.NULL: 5,
}


class SegmentBuilder:
    """Single-segment bump allocator mirroring capnp's message builder."""

    def __init__(self):
        self.buf = bytearray(WORD)  # word 0: root pointer

    # -- low level ---------------------------------------------------------
    def alloc(self, nwords: int) -> int:
        at = len(self.buf) // WORD
        self.buf.extend(b"\x00" * (nwords * WORD))
        return at

    def _put_u64(self, word_idx: int, value: int):
        struct.pack_into("<Q", self.buf, word_idx * WORD, value)

    def put_struct_ptr(self, ptr_word: int, target_word: int, data_words: int, ptr_words: int):
        offset = target_word - ptr_word - 1
        lower = (offset << 2) & 0xFFFFFFFF
        upper = (data_words & 0xFFFF) | ((ptr_words & 0xFFFF) << 16)
        self._put_u64(ptr_word, lower | (upper << 32))

    def put_list_ptr(self, ptr_word: int, target_word: int, elem_size: int, count: int):
        offset = target_word - ptr_word - 1
        lower = ((offset << 2) | 1) & 0xFFFFFFFF
        upper = (elem_size & 7) | ((count & 0x1FFFFFFF) << 3)
        self._put_u64(ptr_word, lower | (upper << 32))

    # -- typed writes ------------------------------------------------------
    def set_data_u8(self, struct_word: int, byte_off: int, v: int):
        self.buf[struct_word * WORD + byte_off] = v & 0xFF

    def set_data_u16(self, struct_word: int, u16_index: int, v: int):
        struct.pack_into("<H", self.buf, struct_word * WORD + u16_index * 2, v & 0xFFFF)

    def set_data_f64(self, struct_word: int, word_off: int, v: float):
        struct.pack_into("<d", self.buf, (struct_word + word_off) * WORD, v)

    def set_data_i64(self, struct_word: int, word_off: int, v: int):
        struct.pack_into("<q", self.buf, (struct_word + word_off) * WORD, v)

    def set_data_u64(self, struct_word: int, word_off: int, v: int):
        struct.pack_into("<Q", self.buf, (struct_word + word_off) * WORD, v)

    def set_bool_bit(self, struct_word: int, bit: int, v: bool):
        if v:
            self.buf[struct_word * WORD + bit // 8] |= 1 << (bit % 8)

    def set_text(self, ptr_word: int, s: str):
        data = s.encode("utf-8") + b"\x00"
        nwords = (len(data) + WORD - 1) // WORD
        at = self.alloc(nwords)
        self.buf[at * WORD: at * WORD + len(data)] = data
        self.put_list_ptr(ptr_word, at, 2, len(data))

    def init_composite_list(self, ptr_word: int, count: int,
                            data_words: int, ptr_words: int) -> int:
        """Allocate tag word + elements; returns word index of element 0."""
        struct_words = data_words + ptr_words
        tag_at = self.alloc(1 + count * struct_words)
        # tag word: like a struct pointer whose offset field holds the count
        lower = (count << 2) & 0xFFFFFFFF
        upper = (data_words & 0xFFFF) | ((ptr_words & 0xFFFF) << 16)
        self._put_u64(tag_at, lower | (upper << 32))
        self.put_list_ptr(ptr_word, tag_at, 7, count * struct_words)
        return tag_at + 1

    def message_bytes(self) -> bytes:
        nwords = len(self.buf) // WORD
        return struct.pack("<II", 0, nwords) + bytes(self.buf)


def _write_pair(seg: SegmentBuilder, elem_word: int, key: str, value: SDValue):
    key_ptr = elem_word + PAIR_DATA_WORDS
    val_ptr = elem_word + PAIR_DATA_WORDS + 1
    seg.set_text(key_ptr, key)
    disc = _UNION_DISCRIMINANTS[value.kind]
    seg.set_data_u16(elem_word, 0, disc)
    if value.kind == SDValue.STRING:
        seg.set_text(val_ptr, value.value)
    elif value.kind == SDValue.BOOL:
        seg.set_bool_bit(elem_word, 16, value.value)
    elif value.kind == SDValue.F64:
        seg.set_data_f64(elem_word, 1, value.value)
    elif value.kind == SDValue.I64:
        seg.set_data_i64(elem_word, 1, value.value)
    elif value.kind == SDValue.U64:
        seg.set_data_u64(elem_word, 1, value.value)
    # NULL: discriminant only


def encode_record(record: Record, extra: List[Tuple[str, str]]) -> bytes:
    """Serialize a Record exactly as capnp_encoder.rs:45-106 does, in its
    allocation order (so the bytes match the reference's golden test)."""
    seg = SegmentBuilder()
    root = seg.alloc(RECORD_DATA_WORDS + RECORD_PTR_WORDS)
    seg.put_struct_ptr(0, root, RECORD_DATA_WORDS, RECORD_PTR_WORDS)
    ptrs = root + RECORD_DATA_WORDS

    seg.set_data_f64(root, 0, record.ts)
    seg.set_text(ptrs + _P_HOSTNAME, record.hostname)
    seg.set_data_u8(root, _FACILITY_OFF,
                    record.facility if record.facility is not None else FACILITY_MISSING)
    seg.set_data_u8(root, _SEVERITY_OFF,
                    record.severity if record.severity is not None else SEVERITY_MISSING)
    if record.appname is not None:
        seg.set_text(ptrs + _P_APPNAME, record.appname)
    if record.procid is not None:
        seg.set_text(ptrs + _P_PROCID, record.procid)
    if record.msgid is not None:
        seg.set_text(ptrs + _P_MSGID, record.msgid)
    if record.msg is not None:
        seg.set_text(ptrs + _P_MSG, record.msg)
    if record.full_msg is not None:
        seg.set_text(ptrs + _P_FULL_MSG, record.full_msg)
    if record.sd is not None:
        # only sd[0] fits the schema (capnp_encoder.rs:78-80)
        sd = record.sd[0]
        if sd.sd_id is not None:
            seg.set_text(ptrs + _P_SD_ID, sd.sd_id)
        elem0 = seg.init_composite_list(ptrs + _P_PAIRS, len(sd.pairs),
                                        PAIR_DATA_WORDS, PAIR_PTR_WORDS)
        for i, (name, value) in enumerate(sd.pairs):
            _write_pair(seg, elem0 + i * (PAIR_DATA_WORDS + PAIR_PTR_WORDS), name, value)
    if extra:
        elem0 = seg.init_composite_list(ptrs + _P_EXTRA, len(extra),
                                        PAIR_DATA_WORDS, PAIR_PTR_WORDS)
        for i, (name, value) in enumerate(extra):
            _write_pair(seg, elem0 + i * (PAIR_DATA_WORDS + PAIR_PTR_WORDS),
                        name, SDValue.string(value))
    return seg.message_bytes()


# ---------------------------------------------------------------------------
# Reader side (used by the capnp splitter)
# ---------------------------------------------------------------------------

class CapnpDecodeError(Exception):
    pass


class _SegmentReader:
    def __init__(self, segments: List[bytes]):
        self.segments = segments

    def word(self, seg: int, idx: int) -> int:
        data = self.segments[seg]
        off = idx * WORD
        if off + WORD > len(data):
            raise CapnpDecodeError("pointer out of bounds")
        return struct.unpack_from("<Q", data, off)[0]


def _read_text(rd: _SegmentReader, seg: int, ptr_word: int) -> Optional[str]:
    w = rd.word(seg, ptr_word)
    if w == 0:
        return None
    kind = w & 3
    if kind == 2:  # far pointer
        target_seg = (w >> 32) & 0xFFFFFFFF
        landing = (w >> 3) & 0x1FFFFFFF
        if w & 4:
            raise CapnpDecodeError("double-far pointers unsupported")
        return _read_text(rd, target_seg, landing)
    if kind != 1:
        raise CapnpDecodeError("expected list pointer for text")
    offset = _sign_extend_30((w & 0xFFFFFFFF) >> 2)
    count = (w >> 35) & 0x1FFFFFFF
    elem = (w >> 32) & 7
    if elem != 2 or count == 0:
        raise CapnpDecodeError("bad text pointer")
    start = (ptr_word + 1 + offset) * WORD
    data = rd.segments[seg][start:start + count]
    if len(data) != count or data[-1:] != b"\x00":
        raise CapnpDecodeError("bad text payload")
    return data[:-1].decode("utf-8", errors="strict")


def _sign_extend_30(v: int) -> int:
    return v - (1 << 30) if v & (1 << 29) else v


def _resolve_struct_ptr(rd: _SegmentReader, seg: int, ptr_word: int):
    w = rd.word(seg, ptr_word)
    if w == 0:
        return None
    kind = w & 3
    if kind == 2:
        target_seg = (w >> 32) & 0xFFFFFFFF
        landing = (w >> 3) & 0x1FFFFFFF
        if w & 4:
            raise CapnpDecodeError("double-far pointers unsupported")
        return _resolve_struct_ptr(rd, target_seg, landing)
    if kind != 0:
        raise CapnpDecodeError("expected struct pointer")
    offset = _sign_extend_30((w & 0xFFFFFFFF) >> 2)
    data_words = (w >> 32) & 0xFFFF
    ptr_words = (w >> 48) & 0xFFFF
    return seg, ptr_word + 1 + offset, data_words, ptr_words


def parse_message(data: bytes) -> "RecordReader":
    """Parse a framed capnp message into a RecordReader for the root Record."""
    if len(data) < 8:
        raise CapnpDecodeError("truncated segment table")
    nseg = struct.unpack_from("<I", data, 0)[0] + 1
    table_words = (1 + nseg + 1) // 2  # round up including the count slot
    sizes = struct.unpack_from(f"<{nseg}I", data, 4)
    off = table_words * WORD
    segments = []
    for sz in sizes:
        end = off + sz * WORD
        if end > len(data):
            raise CapnpDecodeError("truncated segment")
        segments.append(data[off:end])
        off = end
    rd = _SegmentReader(segments)
    resolved = _resolve_struct_ptr(rd, 0, 0)
    if resolved is None:
        raise CapnpDecodeError("null root")
    seg, struct_word, data_words, ptr_words = resolved
    return RecordReader(rd, seg, struct_word, data_words, ptr_words)


class RecordReader:
    """Typed accessor over a root Record struct (record_capnp.rs reader)."""

    def __init__(self, rd: _SegmentReader, seg: int, struct_word: int,
                 data_words: int, ptr_words: int):
        self.rd = rd
        self.seg = seg
        self.struct_word = struct_word
        self.data_words = data_words
        self.ptr_words = ptr_words

    def _data_bytes(self) -> bytes:
        start = self.struct_word * WORD
        return self.rd.segments[self.seg][start:start + self.data_words * WORD]

    def get_ts(self) -> float:
        d = self._data_bytes()
        if len(d) < 8:
            return 0.0
        return struct.unpack_from("<d", d, 0)[0]

    def _get_u8(self, off: int) -> int:
        d = self._data_bytes()
        return d[off] if off < len(d) else 0

    def get_facility(self) -> int:
        return self._get_u8(_FACILITY_OFF)

    def get_severity(self) -> int:
        return self._get_u8(_SEVERITY_OFF)

    def _text(self, slot: int) -> str:
        """capnp semantics: a null text pointer reads as the default "" —
        the reference's splitter golden test expects msgid Some("") for an
        unset field (capnp_splitter.rs:186)."""
        if slot >= self.ptr_words:
            return ""
        t = _read_text(self.rd, self.seg, self.struct_word + self.data_words + slot)
        return t if t is not None else ""

    def get_hostname(self):
        return self._text(_P_HOSTNAME)

    def get_appname(self):
        return self._text(_P_APPNAME)

    def get_procid(self):
        return self._text(_P_PROCID)

    def get_msgid(self):
        return self._text(_P_MSGID)

    def get_msg(self):
        return self._text(_P_MSG)

    def get_full_msg(self):
        return self._text(_P_FULL_MSG)

    def get_sd_id(self):
        return self._text(_P_SD_ID)

    def _pairs_from(self, slot: int) -> List[Tuple[str, SDValue]]:
        if slot >= self.ptr_words:
            return []
        ptr_word = self.struct_word + self.data_words + slot
        w = self.rd.word(self.seg, ptr_word)
        if w == 0:
            return []
        if (w & 3) != 1:
            raise CapnpDecodeError("expected list pointer for pairs")
        offset = _sign_extend_30((w & 0xFFFFFFFF) >> 2)
        elem = (w >> 32) & 7
        if elem != 7:
            raise CapnpDecodeError("expected composite list")
        tag_word = ptr_word + 1 + offset
        tag = self.rd.word(self.seg, tag_word)
        count = (tag & 0xFFFFFFFF) >> 2
        data_words = (tag >> 32) & 0xFFFF
        ptr_words = (tag >> 48) & 0xFFFF
        out = []
        stride = data_words + ptr_words
        for i in range(count):
            elem_word = tag_word + 1 + i * stride
            key = _read_text(self.rd, self.seg, elem_word + data_words) or ""
            ebytes = self.rd.segments[self.seg][elem_word * WORD:
                                                (elem_word + data_words) * WORD]
            disc = struct.unpack_from("<H", ebytes, 0)[0] if len(ebytes) >= 2 else 0
            if disc == 0:
                sval = SDValue.string(
                    _read_text(self.rd, self.seg, elem_word + data_words + 1) or "")
            elif disc == 1:
                sval = SDValue.bool_(bool(ebytes[2] & 1) if len(ebytes) > 2 else False)
            elif disc == 2:
                sval = SDValue.f64(struct.unpack_from("<d", ebytes, 8)[0])
            elif disc == 3:
                sval = SDValue.i64(struct.unpack_from("<q", ebytes, 8)[0])
            elif disc == 4:
                sval = SDValue.u64(struct.unpack_from("<Q", ebytes, 8)[0])
            elif disc == 5:
                sval = SDValue.null()
            else:
                raise CapnpDecodeError("unknown union discriminant")
            out.append((key, sval))
        return out

    def get_pairs(self):
        return self._pairs_from(_P_PAIRS)

    def get_extra(self):
        return self._pairs_from(_P_EXTRA)
