"""Stdin input.

Parity model: /root/reference/src/flowgger/input/stdin_input.rs:11-66.
Framing from ``input.framing`` (line/nul/syslen/capnp, default line).
"""

from __future__ import annotations

import sys

from . import Input
from ..config import Config, ConfigError
from ..splitters import get_splitter

DEFAULT_FRAMING = "line"


class _PipeStream:
    """``read(n)`` that returns as soon as *some* bytes arrive.

    ``BufferedReader.read(n)`` on a pipe blocks until n bytes or EOF, so
    a daemon fed over a still-open pipe would sit on buffered lines
    indefinitely; ``read1`` returns after one raw read — the reference's
    ``BufReader`` fill semantics."""

    def __init__(self, buf):
        self.buf = buf

    def read(self, n: int) -> bytes:
        if hasattr(self.buf, "read1"):
            return self.buf.read1(n)
        return self.buf.read(n)


class StdinInput(Input):
    def __init__(self, config: Config):
        framing = config.lookup("input.framing")
        if framing is None:
            framing = DEFAULT_FRAMING
        elif not isinstance(framing, str):
            raise ConfigError(
                'input.framing must be a string set to "line", "nul" or "syslen"'
            )
        self.framing = framing

    def accept(self, handler_factory) -> None:
        splitter = get_splitter(self.framing)
        splitter.run(_PipeStream(sys.stdin.buffer), handler_factory())
