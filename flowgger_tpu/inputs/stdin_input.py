"""Stdin input.

Parity model: /root/reference/src/flowgger/input/stdin_input.rs:11-66.
Framing from ``input.framing`` (line/nul/syslen/capnp, default line).
"""

from __future__ import annotations

import sys

from . import Input
from ..config import Config, ConfigError
from ..splitters import get_splitter

DEFAULT_FRAMING = "line"


class StdinInput(Input):
    def __init__(self, config: Config):
        framing = config.lookup("input.framing")
        if framing is None:
            framing = DEFAULT_FRAMING
        elif not isinstance(framing, str):
            raise ConfigError(
                'input.framing must be a string set to "line", "nul" or "syslen"'
            )
        self.framing = framing

    def accept(self, handler_factory) -> None:
        splitter = get_splitter(self.framing)
        splitter.run(sys.stdin.buffer, handler_factory())
