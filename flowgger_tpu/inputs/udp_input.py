"""UDP input: one datagram = one message, with transparent zlib/gzip
decompression.

Parity model: /root/reference/src/flowgger/input/udp_input.rs:12-143.
Magic sniffing: zlib = 0x78 {0x01,0x9c,0xda} with length >= 8; gzip =
1f 8b 08 with length >= 24.  Max datagram 65,527 bytes; decompression is
bounded at 5x the max packet size (the reference sizes its buffer to
that ratio; here the bound is enforced, rejecting bombs).
"""

from __future__ import annotations

import gzip
import socket
import sys
import zlib

from . import Input
from ..config import Config
from ..splitters import Handler
from .tcp_input import parse_listen

DEFAULT_LISTEN = "0.0.0.0:514"
MAX_UDP_PACKET_SIZE = 65_527
MAX_COMPRESSION_RATIO = 5
_MAX_DECOMPRESSED = MAX_UDP_PACKET_SIZE * MAX_COMPRESSION_RATIO


def handle_record_maybe_compressed(data: bytes, handler: Handler) -> None:
    """Sniff compression magic, inflate, hand off; errors go to stderr
    (udp_input.rs:100-123 semantics, messages included)."""
    if len(data) >= 8 and data[0] == 0x78 and data[1] in (0x01, 0x9C, 0xDA):
        try:
            d = zlib.decompressobj()
            out = d.decompress(data, _MAX_DECOMPRESSED)
            if d.unconsumed_tail:
                raise zlib.error("compression bomb")
            out += d.flush()
        except zlib.error:
            print("Corrupted compressed (gzip/zlib) record", file=sys.stderr)
            return
        handler.handle_bytes(out)
    elif len(data) >= 24 and data[:3] == b"\x1f\x8b\x08":
        try:
            # wbits=47: zlib-or-gzip auto-detect; max_length bounds the
            # expansion *during* decompression (no bomb-sized allocation)
            d = zlib.decompressobj(wbits=47)
            out = d.decompress(data, _MAX_DECOMPRESSED)
            if d.unconsumed_tail:
                raise zlib.error("compression bomb")
            out += d.flush()
        except zlib.error:
            print("Corrupted compressed (gzip) record", file=sys.stderr)
            return
        handler.handle_bytes(out)
    else:
        handler.handle_bytes(data)


class UdpInput(Input):
    def __init__(self, config: Config):
        listen = config.lookup_str(
            "input.listen", "input.listen must be an ip:port string", DEFAULT_LISTEN)
        self.listen = parse_listen(listen)
        self.bound_port = None

    def accept(self, handler_factory) -> None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            sock.bind(self.listen)
        except OSError:
            raise RuntimeError(f"Unable to listen to {self.listen[0]}:{self.listen[1]}")
        self.bound_port = sock.getsockname()[1]
        handler = handler_factory()
        handler.bare_errors = True
        while True:
            try:
                data, _src = sock.recvfrom(MAX_UDP_PACKET_SIZE)
            except OSError:
                continue
            handle_record_maybe_compressed(data, handler)
