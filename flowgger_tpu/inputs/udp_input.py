"""UDP input: one datagram = one message, with transparent zlib/gzip
decompression.

Parity model: /root/reference/src/flowgger/input/udp_input.rs:12-143.
Magic sniffing: zlib = 0x78 {0x01,0x9c,0xda} with length >= 8; gzip =
1f 8b 08 with length >= 24.  Max datagram 65,527 bytes; decompression is
bounded at 5x the max packet size (the reference sizes its buffer to
that ratio; here the bound is enforced, rejecting bombs).
"""

from __future__ import annotations

import gzip
import socket
import sys
import zlib

from . import Input
from ..config import Config
from ..splitters import Handler
from .tcp_input import parse_listen

DEFAULT_LISTEN = "0.0.0.0:514"
MAX_UDP_PACKET_SIZE = 65_527
MAX_COMPRESSION_RATIO = 5
_MAX_DECOMPRESSED = MAX_UDP_PACKET_SIZE * MAX_COMPRESSION_RATIO
# compression magic, shared between the scalar sniffing path and the
# vectorized recvmmsg classifier so the two can never drift
ZLIB_MIN_LEN = 8
ZLIB_MAGIC0 = 0x78
ZLIB_MAGIC1 = (0x01, 0x9C, 0xDA)
GZIP_MIN_LEN = 24
GZIP_MAGIC = (0x1F, 0x8B, 0x08)


def handle_record_maybe_compressed(data: bytes, handler: Handler) -> None:
    """Sniff compression magic, inflate, hand off; errors go to stderr
    (udp_input.rs:100-123 semantics, messages included)."""
    if (len(data) >= ZLIB_MIN_LEN and data[0] == ZLIB_MAGIC0
            and data[1] in ZLIB_MAGIC1):
        try:
            d = zlib.decompressobj()
            out = d.decompress(data, _MAX_DECOMPRESSED)
            if d.unconsumed_tail:
                raise zlib.error("compression bomb")
            out += d.flush()
        except zlib.error:
            print("Corrupted compressed (gzip/zlib) record", file=sys.stderr)
            return
        handler.handle_bytes(out)
    elif len(data) >= GZIP_MIN_LEN and data[:3] == bytes(GZIP_MAGIC):
        try:
            # wbits=47: zlib-or-gzip auto-detect; max_length bounds the
            # expansion *during* decompression (no bomb-sized allocation)
            d = zlib.decompressobj(wbits=47)
            out = d.decompress(data, _MAX_DECOMPRESSED)
            if d.unconsumed_tail:
                raise zlib.error("compression bomb")
            out += d.flush()
        except zlib.error:
            print("Corrupted compressed (gzip) record", file=sys.stderr)
            return
        handler.handle_bytes(out)
    else:
        handler.handle_bytes(data)


class UdpInput(Input):
    def __init__(self, config: Config):
        listen = config.lookup_str(
            "input.listen", "input.listen must be an ip:port string", DEFAULT_LISTEN)
        self.listen = parse_listen(listen)
        self.bound_port = None

    def accept(self, handler_factory) -> None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            sock.bind(self.listen)
        except OSError:
            raise RuntimeError(f"Unable to listen to {self.listen[0]}:{self.listen[1]}")
        self.bound_port = sock.getsockname()[1]
        handler = handler_factory()
        handler.bare_errors = True
        if hasattr(handler, "ingest_spans"):
            from ..utils import recvmmsg as _rm

            if _rm.available():
                # NOTE (tenancy): the recvmmsg fast path aggregates many
                # sources per syscall, so admission is listener-level —
                # the whole socket charges the default tenant.  The
                # per-datagram loop below resolves tenants per source IP.
                if self._accept_batched(sock, handler):
                    return  # socket closed: normal exit
                # the syscall exists but doesn't work (sandboxed/old
                # kernels return EINVAL/ENOSYS): degrade to recvfrom
                # instead of silently killing the input
                print("recvmmsg unusable on this kernel; falling back to "
                      "per-datagram recvfrom", file=sys.stderr)
        import errno

        from . import make_handler

        # per-source handlers so [tenants.*] peers match UDP senders;
        # bounded cache (spoofed-source floods must not grow it forever)
        per_src: dict = {}
        while True:
            try:
                data, src = sock.recvfrom(MAX_UDP_PACKET_SIZE)
            except OSError as e:
                # a closed socket must end the loop (so the pipeline can
                # drain), not busy-spin on EBADF forever
                if e.errno == errno.EBADF or sock.fileno() < 0:
                    return
                continue
            h = handler
            if src:
                h = per_src.get(src[0])
                if h is None:
                    if len(per_src) >= 1024:
                        per_src.clear()
                    h = make_handler(handler_factory, src[0])
                    h.bare_errors = True
                    per_src[src[0]] = h
            handle_record_maybe_compressed(data, h)

    @staticmethod
    def _accept_batched(sock, handler) -> bool:
        """recvmmsg fast path for span-capable handlers: up to 64
        datagrams per syscall; plain datagrams compact into one chunk
        and flow as frame spans with zero per-datagram Python, while
        compressed ones (zlib/gzip magic) take the sniffing path.
        Relative ordering between plain and compressed datagrams of one
        batch is unspecified — UDP guarantees no ordering anyway.

        Returns True on a normal exit (socket closed) and False when the
        syscall itself is unusable before ever delivering a batch, so
        the caller can fall back to the scalar recvfrom loop."""
        import errno
        import numpy as np

        from ..tpu.assemble import concat_segments, exclusive_cumsum
        from ..utils.recvmmsg import BatchReceiver

        rx = BatchReceiver(sock)
        delivered = False
        while True:
            try:
                got = rx.recv_batch()
            # flowcheck: disable=FC04 -- availability probe: False falls back to the recvfrom loop
            except OSError as e:
                if not delivered and e.errno in (
                        errno.EINVAL, errno.ENOSYS, errno.EOPNOTSUPP):
                    return False
                return True
            if got is None:
                continue
            delivered = True
            buf, starts, lens = got
            b0 = buf[starts]
            b1 = buf[starts + 1]
            b2 = buf[starts + 2]
            zlibm = (lens >= ZLIB_MIN_LEN) & (b0 == ZLIB_MAGIC0) & (
                (b1 == ZLIB_MAGIC1[0]) | (b1 == ZLIB_MAGIC1[1])
                | (b1 == ZLIB_MAGIC1[2]))
            gzm = ((lens >= GZIP_MIN_LEN) & (b0 == GZIP_MAGIC[0])
                   & (b1 == GZIP_MAGIC[1]) & (b2 == GZIP_MAGIC[2]))
            special = zlibm | gzm
            clean = ~special
            if clean.any():
                cs, cl = starts[clean], lens[clean]
                chunk = concat_segments(buf, cs, cl).tobytes()
                new_starts = exclusive_cumsum(cl)[:-1].astype(np.int32)
                handler.ingest_spans(chunk, new_starts,
                                     cl.astype(np.int32))
            for i in np.flatnonzero(special).tolist():
                s = int(starts[i])
                handle_record_maybe_compressed(
                    bytes(buf[s:s + int(lens[i])]), handler)
