"""File input: tail files matching a glob, discovering new ones.

Parity model: /root/reference/src/flowgger/input/file/{mod,discovery,worker}.rs.
``input.src`` is a glob; matching files that exist at startup are tailed
from EOF (worker.rs:89-91), files appearing later are read from the
start.  Discovery and tailing are inotify-driven (utils/inotify.py, the
equivalent of the reference's notify-crate watchers: parent directories
watched for Create/MovedTo — discovery.rs:44-87 — and each tailed file
for Modify — worker.rs:37-78), with a polling fallback on platforms
without inotify.  Truncation (size shrinks) rewinds to the new end,
matching follow-reader behavior.
"""

from __future__ import annotations

import glob as _glob
import os
import sys
import threading
import time

from . import Input
from ..config import Config, ConfigError
from ..utils import inotify as _ino

POLL_INTERVAL_S = 0.05        # fallback tail poll (no inotify)
DISCOVERY_INTERVAL_S = 0.5    # fallback discovery poll
STOP_CHECK_S = 0.5            # bounded event waits keep stop responsive


class FileWorker:
    def __init__(self, path: str, handler, from_tail: bool,
                 use_inotify: bool):
        self.path = path
        self.handler = handler
        self.from_tail = from_tail
        self.use_inotify = use_inotify
        self.stop = threading.Event()
        self.open_failed = False

    def run(self):
        try:
            fd = open(self.path, "rb")
        except OSError as e:
            self.open_failed = True
            print(f"Failed to open file {self.path}: {e}", file=sys.stderr)
            return
        if self.from_tail:
            fd.seek(0, os.SEEK_END)
        from ..splitters import LineAssembler

        asm = LineAssembler(self.handler)
        watcher = None
        if self.use_inotify:
            try:
                watcher = _ino.Inotify()
                watcher.add_watch(
                    self.path,
                    _ino.IN_MODIFY | _ino.IN_DELETE_SELF | _ino.IN_MOVE_SELF
                    | _ino.IN_ATTRIB | _ino.IN_CLOSE_WRITE)
            except OSError:  # flowcheck: disable=FC04 -- no inotify watch: the poll loop below still tails the file
                watcher = None
        try:
            while not self.stop.is_set():
                chunk = fd.read(1 << 16)
                if chunk:
                    asm.push(chunk)
                    continue
                # drained: check for truncation/deletion
                try:
                    size = os.path.getsize(self.path)
                except OSError:  # flowcheck: disable=FC04 -- file removed (logrotate); reap() starts a fresh worker
                    return
                if size < fd.tell():
                    fd.seek(0, os.SEEK_END)
                if hasattr(self.handler, "flush"):
                    self.handler.flush()
                if watcher is not None:
                    events = watcher.read(STOP_CHECK_S)
                    if any(m & (_ino.IN_DELETE_SELF | _ino.IN_MOVE_SELF)
                           for _, m, _, _ in events):
                        return
                else:
                    time.sleep(POLL_INTERVAL_S)
        finally:
            if watcher is not None:
                watcher.close()


class FileInput(Input):
    def __init__(self, config: Config):
        src = config.lookup("input.src")
        if src is None:
            raise ConfigError("input.src is missing")
        if not isinstance(src, str):
            raise ConfigError("input.src must be a string")
        self.src = src
        self.use_inotify = _ino.available()

    def accept(self, handler_factory) -> None:
        workers = {}

        def start_worker(path: str, from_tail: bool):
            from . import make_handler

            # the path is the source identity: [tenants.*] peers entries
            # may name watched files, not just addresses
            worker = FileWorker(path, make_handler(handler_factory, path),
                                from_tail, self.use_inotify)
            t = threading.Thread(target=worker.run, daemon=True,
                                 name=f"file-worker-{path}")
            t.start()
            workers[path] = (worker, t)

        def reap() -> bool:
            # drop finished workers so a vanished or atomically replaced
            # file (logrotate's rename+create) can start a fresh worker
            # reading from the start — EXCEPT unopenable files that
            # still exist, which stay parked instead of restarting in a
            # spawn/stderr loop (the pre-inotify behavior)
            reaped = False
            for path in list(workers):
                worker, t = workers[path]
                if t.is_alive():
                    continue
                if worker.open_failed and os.path.exists(path):
                    continue
                del workers[path]
                reaped = True
            return reaped

        for path in _glob.glob(self.src):
            if os.path.isfile(path):
                start_worker(path, from_tail=True)

        if self.use_inotify:
            self._discover_inotify(start_worker, workers, reap)
        else:
            while True:
                time.sleep(DISCOVERY_INTERVAL_S)
                for path in _glob.glob(self.src):
                    if os.path.isfile(path) and path not in workers:
                        start_worker(path, from_tail=False)
                reap()

    def _discover_inotify(self, start_worker, workers, reap) -> None:
        """Event-driven discovery: watch every directory the glob's
        parent pattern matches for Create/MovedTo (discovery.rs:44-87);
        new directories matching the parent pattern are watched as they
        appear, new files matching the glob start workers immediately."""
        ino = _ino.Inotify()
        dir_mask = (_ino.IN_CREATE | _ino.IN_MOVED_TO | _ino.IN_CLOSE_WRITE)
        watched = {}  # wd -> dir path

        # ancestor pattern chain: every wildcarded prefix of the glob's
        # directory part plus the first concrete ancestor, so creation
        # of an intermediate directory (e.g. the `*` in /logs/*/app.log)
        # is itself observable before any matching file exists
        dir_patterns = []
        p = os.path.dirname(self.src) or "."
        while True:
            dir_patterns.append(p)
            if not _glob.has_magic(p):
                break
            parent = os.path.dirname(p)
            if not parent or parent == p:
                break
            p = parent

        def watch_dirs():
            for pat in dir_patterns:
                for d in _glob.glob(pat):
                    if os.path.isdir(d) and d not in watched.values():
                        try:
                            wd = ino.add_watch(d, dir_mask)
                            watched[wd] = d
                        except OSError:  # flowcheck: disable=FC04 -- directory vanished mid-walk; the next event rescans
                            pass

        def rescan_files():
            # race closure: files that appeared before a watch went live
            for path in _glob.glob(self.src):
                if os.path.isfile(path) and path not in workers:
                    start_worker(path, from_tail=False)

        watch_dirs()
        rescan_files()

        while True:
            events = ino.read(STOP_CHECK_S)
            for wd, mask, _cookie, name in events:
                if mask & _ino.IN_IGNORED:
                    # the kernel dropped this watch (directory deleted
                    # or moved): forget it so a recreated directory gets
                    # re-watched, and rescan for anything created in the
                    # unwatched window
                    watched.pop(wd, None)
                    watch_dirs()
                    rescan_files()
                    continue
                base = watched.get(wd)
                if base is None or not name:
                    continue
                path = os.path.join(base, name)
                if mask & _ino.IN_ISDIR:
                    # a new directory may extend the watchable chain and
                    # may already contain matching files
                    watch_dirs()
                    rescan_files()
                    continue
                if (path not in workers and os.path.isfile(path)
                        and path in _glob.glob(self.src)):
                    # glob (not fnmatch) so event-driven discovery keeps
                    # glob's hidden-file semantics, same as the startup
                    # scan and the poll fallback
                    start_worker(path, from_tail=False)
            if reap():
                # a finished worker may have been replaced by a new file
                # whose create event raced the old entry: rescan now
                rescan_files()
