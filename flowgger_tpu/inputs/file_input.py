"""File input: tail files matching a glob, discovering new ones.

Parity model: /root/reference/src/flowgger/input/file/{mod,discovery,worker}.rs.
``input.src`` is a glob; matching files that exist at startup are tailed
from EOF (worker.rs:89-91), files appearing later are read from the
start.  The reference uses inotify; this implementation polls (stdlib
has no inotify binding) — discovery rescans the glob and workers poll
their file for growth, both on a short interval.  Truncation (size
shrinks) rewinds to the new end, matching follow-reader behavior.
"""

from __future__ import annotations

import glob as _glob
import os
import sys
import threading
import time

from . import Input
from ..config import Config, ConfigError

POLL_INTERVAL_S = 0.05
DISCOVERY_INTERVAL_S = 0.5


class FileWorker:
    def __init__(self, path: str, handler, from_tail: bool):
        self.path = path
        self.handler = handler
        self.from_tail = from_tail
        self.stop = threading.Event()

    def run(self):
        try:
            fd = open(self.path, "rb")
        except OSError as e:
            print(f"Failed to open file {self.path}: {e}", file=sys.stderr)
            return
        if self.from_tail:
            fd.seek(0, os.SEEK_END)
        from ..splitters import LineAssembler

        asm = LineAssembler(self.handler)
        while not self.stop.is_set():
            chunk = fd.read(1 << 16)
            if chunk:
                asm.push(chunk)
                continue
            # no growth: check for truncation/deletion
            try:
                size = os.path.getsize(self.path)
            except OSError:
                return  # file removed
            if size < fd.tell():
                fd.seek(0, os.SEEK_END)
            if hasattr(self.handler, "flush"):
                self.handler.flush()
            time.sleep(POLL_INTERVAL_S)


class FileInput(Input):
    def __init__(self, config: Config):
        src = config.lookup("input.src")
        if src is None:
            raise ConfigError("input.src is missing")
        if not isinstance(src, str):
            raise ConfigError("input.src must be a string")
        self.src = src

    def accept(self, handler_factory) -> None:
        workers = {}

        def start_worker(path: str, from_tail: bool):
            worker = FileWorker(path, handler_factory(), from_tail)
            t = threading.Thread(target=worker.run, daemon=True,
                                 name=f"file-worker-{path}")
            t.start()
            workers[path] = (worker, t)

        for path in _glob.glob(self.src):
            if os.path.isfile(path):
                start_worker(path, from_tail=True)
        while True:
            time.sleep(DISCOVERY_INTERVAL_S)
            for path in _glob.glob(self.src):
                if os.path.isfile(path) and path not in workers:
                    start_worker(path, from_tail=False)
            # reap workers whose files vanished so they can be re-tailed
            for path in list(workers):
                worker, t = workers[path]
                if not t.is_alive() and not os.path.exists(path):
                    del workers[path]
