"""TLS input: TCP + TLS handshake per connection.

Parity model: /root/reference/src/flowgger/input/tls/{mod,tls_input}.rs.
Config keys: input.listen (default 0.0.0.0:6514), input.tls_cert /
input.tls_key (default flowgger.pem), input.tls_ciphers,
input.tls_compatibility_level ("default"/"any"/"intermediate" → TLS1.0+,
"modern" → TLS1.2+), input.tls_verify_peer (+ input.tls_ca_file),
input.tls_compression (Python's ssl always disables TLS compression; a
``true`` here warns and proceeds), input.timeout, input.framing/framed.
The reference's custom ffdhe DH parameters (tls/mod.rs:41-49) have no
ssl-module equivalent; ECDHE suites cover forward secrecy.
"""

from __future__ import annotations

import socket
import ssl
import sys

from . import Input
from ..config import Config, ConfigError
from ..splitters import get_splitter
from .tcp_input import SocketStream, parse_listen

DEFAULT_CERT = "flowgger.pem"
DEFAULT_KEY = "flowgger.pem"
DEFAULT_LISTEN = "0.0.0.0:6514"
DEFAULT_TIMEOUT = 3600
DEFAULT_FRAMING = "line"
DEFAULT_COMPATIBILITY = "default"
DEFAULT_VERIFY_PEER = False
TLS_VERIFY_DEPTH = 6
DEFAULT_CIPHERS = (
    "ECDHE-ECDSA-AES128-GCM-SHA256:ECDHE-RSA-AES128-GCM-SHA256:"
    "ECDHE-ECDSA-CHACHA20-POLY1305:ECDHE-RSA-CHACHA20-POLY1305:"
    "ECDHE-ECDSA-AES256-GCM-SHA384:ECDHE-RSA-AES256-GCM-SHA384:"
    "AES128-GCM-SHA256:AES256-GCM-SHA384:AES128-SHA256:AES256-SHA256"
)


def tls_config_parse(config: Config, side: str = "input"):
    """Shared TLS context construction for the input (server) side; the
    output (client) side mirrors this in outputs/tls_output.py."""
    listen = config.lookup_str(
        "input.listen", "input.listen must be an ip:port string", DEFAULT_LISTEN)
    timeout = config.lookup_int(
        "input.timeout", "input.timeout must be an unsigned integer", DEFAULT_TIMEOUT)
    framed = config.lookup_bool(
        "input.framed", "input.framed must be a boolean", False)
    framing = "syslen" if framed else DEFAULT_FRAMING
    framing = config.lookup_str(
        "input.framing",
        'input.framing must be a string set to "line", "nul" or "syslen"',
        framing)
    cert = config.lookup_str(
        "input.tls_cert", "input.tls_cert must be a path to a .pem file", DEFAULT_CERT)
    key = config.lookup_str(
        "input.tls_key", "input.tls_key must be a path to a .pem file", DEFAULT_KEY)
    ciphers = config.lookup_str(
        "input.tls_ciphers", "input.tls_ciphers must be a string with a cipher suite",
        DEFAULT_CIPHERS)
    compat = config.lookup_str(
        "input.tls_compatibility_level",
        "input.tls_compatibility_level must be a string with the compatibility level",
        DEFAULT_COMPATIBILITY)
    verify_peer = config.lookup_bool(
        "input.tls_verify_peer", "input.tls_verify_peer must be a boolean",
        DEFAULT_VERIFY_PEER)
    ca_file = config.lookup_str(
        "input.tls_ca_file", "input.tls_ca_file must be a path to a file")
    compression = config.lookup_bool(
        "input.tls_compression", "input.tls_compression must be a boolean", False)

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    if compat.lower() in ("default", "any", "intermediate"):
        ctx.minimum_version = ssl.TLSVersion.TLSv1
    elif compat.lower() == "modern":
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    else:
        raise ConfigError(
            "Unsupported TLS compatibility level. Supported levels are: default, any, intermediate and modern"
        )
    try:
        ctx.load_cert_chain(certfile=cert, keyfile=key)
    except (OSError, ssl.SSLError) as e:
        raise ConfigError(f"Unable to load the TLS certificate/key [{cert}]: {e}")
    try:
        ctx.set_ciphers(ciphers)
    except ssl.SSLError:
        raise ConfigError("Unsupported TLS cipher suite")
    if verify_peer:
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.verify_flags |= ssl.VERIFY_X509_STRICT
        if ca_file is not None:
            ctx.load_verify_locations(cafile=ca_file)
    else:
        ctx.verify_mode = ssl.CERT_NONE
    if compression:
        print("WARNING: TLS compression is not supported by the ssl module; "
              "continuing without it", file=sys.stderr)
    return ctx, framing, listen, timeout


class TlsInput(Input):
    def __init__(self, config: Config):
        self.ctx, self.framing, self.listen, self.timeout = tls_config_parse(config)
        self.bound_port = None

    def accept(self, handler_factory) -> None:
        self._handler_factory = handler_factory
        host, port = parse_listen(self.listen)
        listener = socket.create_server((host, port))
        self.bound_port = listener.getsockname()[1]
        while True:
            try:
                client, peer = listener.accept()
            except OSError as e:
                # closed listener on shutdown — but also EMFILE and
                # friends, which must not look like a clean EOF
                print(f"TLS accept loop exiting: {e}", file=sys.stderr)
                return
            client.settimeout(self.timeout)
            print(f"Connection over TLS from [{peer[0]}:{peer[1]}]")
            self._spawn_handler(self._handle_client, (client, peer[0]))

    def _handle_client(self, client: socket.socket, peer_ip=None):
        try:
            tls_sock = self.ctx.wrap_socket(client, server_side=True)
        except (ssl.SSLError, OSError) as e:
            print(f"TLS handshake failed: {e}", file=sys.stderr)
            try:
                client.close()
            except OSError:  # flowcheck: disable=FC04 -- handshake already logged; close is best-effort
                pass
            return
        from . import make_handler

        splitter = get_splitter(self.framing)
        try:
            splitter.run(SocketStream(tls_sock),
                         make_handler(self._handler_factory, peer_ip))
        finally:
            try:
                tls_sock.close()
            except OSError:  # flowcheck: disable=FC04 -- fd already dead; close is best-effort
                pass


class TlsCoInput(TlsInput):
    """Coroutine tier over asyncio TLS (tlsco_input.rs:25-47)."""

    def accept(self, handler_factory) -> None:
        import asyncio

        from .tcp_input import _AsyncBridgeStream

        host, port = parse_listen(self.listen)
        framing = self.framing
        timeout = self.timeout
        ctx = self.ctx

        async def handle(reader, writer):
            from . import make_handler

            peer = writer.get_extra_info("peername")
            if peer:
                print(f"Connection over TLS from [{peer[0]}:{peer[1]}]")
            handler = make_handler(handler_factory,
                                   peer[0] if peer else None)
            splitter = get_splitter(framing)
            stream = _AsyncBridgeStream(reader, timeout)
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, splitter.run, stream, handler)
            writer.close()

        async def serve():
            server = await asyncio.start_server(handle, host, port, ssl=ctx)
            self.bound_port = server.sockets[0].getsockname()[1]
            async with server:
                await server.serve_forever()

        asyncio.run(serve())
