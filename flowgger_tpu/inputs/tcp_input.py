"""TCP input: thread-per-connection (plus a coroutine variant).

Parity model: /root/reference/src/flowgger/input/tcp/{mod,tcp_input}.rs
(defaults: listen 0.0.0.0:514, read timeout 3600s, line framing;
``input.framed = true`` selects syslen unless ``input.framing`` is set)
and tcpco_input.rs for the coroutine tier (the reference uses `may`
coroutines with ``input.tcp_threads`` workers; here: one asyncio event
loop with cooperative connection handling).
"""

from __future__ import annotations

import socket

from . import Input
from ..config import Config, ConfigError
from ..splitters import get_splitter

DEFAULT_FRAMING = "line"
DEFAULT_LISTEN = "0.0.0.0:514"
DEFAULT_THREADS = 1
DEFAULT_TIMEOUT = 3600


def parse_listen(listen: str):
    host, _, port = listen.rpartition(":")
    if not host or not port.isdigit():
        raise ConfigError("unable to parse ip:port string from input.listen")
    return host, int(port)


def tcp_config_parse(config: Config, threads_key: str = "input.tcp_threads"):
    listen = config.lookup_str(
        "input.listen", "input.listen must be an ip:port string", DEFAULT_LISTEN)
    threads = config.lookup_int(
        threads_key, f"{threads_key} must be an unsigned integer", DEFAULT_THREADS)
    timeout = config.lookup_int(
        "input.timeout", "input.timeout must be an unsigned integer", DEFAULT_TIMEOUT)
    framed = config.lookup_bool(
        "input.framed", "input.framed must be a boolean", False)
    framing = "syslen" if framed else DEFAULT_FRAMING
    framing = config.lookup_str(
        "input.framing",
        'input.framing must be a string set to "line", "nul" or "syslen"',
        framing)
    return framing, threads, listen, timeout


class SocketStream:
    """read(n) view over a socket; timeouts surface as TimeoutError
    (the splitters treat that as the reference's WouldBlock idle-close)."""

    def __init__(self, sock: socket.socket):
        self.sock = sock

    def read(self, n: int) -> bytes:
        return self.sock.recv(n)


class TcpInput(Input):
    def __init__(self, config: Config):
        self.framing, _, self.listen, self.timeout = tcp_config_parse(config)
        self.bound_port = None

    def accept(self, handler_factory) -> None:
        self._handler_factory = handler_factory
        host, port = parse_listen(self.listen)
        listener = socket.create_server((host, port))
        self.bound_port = listener.getsockname()[1]
        while True:
            try:
                client, peer = listener.accept()
            except OSError as e:
                # closed listener on shutdown — but also EMFILE and
                # friends, which must not look like a clean EOF
                import sys

                print(f"TCP accept loop exiting: {e}", file=sys.stderr)
                return
            client.settimeout(self.timeout)
            print(f"Connection over TCP from [{peer[0]}:{peer[1]}]")
            self._spawn_handler(self._handle_client, (client, peer[0]))

    def _handle_client(self, client: socket.socket, peer_ip=None):
        from . import make_handler

        splitter = get_splitter(self.framing)
        try:
            splitter.run(SocketStream(client),
                         make_handler(self._handler_factory, peer_ip))
        finally:
            try:
                client.close()
            except OSError:  # flowcheck: disable=FC04 -- fd already dead; close is best-effort
                pass


class TcpCoInput(TcpInput):
    """Coroutine tier: cooperative handling on an asyncio loop
    (tcpco_input.rs:25-47)."""

    def __init__(self, config: Config):
        self.framing, self.threads, self.listen, self.timeout = tcp_config_parse(config)
        self.bound_port = None

    def accept(self, handler_factory) -> None:
        import asyncio

        host, port = parse_listen(self.listen)
        framing = self.framing
        timeout = self.timeout

        async def handle(reader: "asyncio.StreamReader", writer):
            from . import make_handler

            peer = writer.get_extra_info("peername")
            if peer:
                print(f"Connection over TCP from [{peer[0]}:{peer[1]}]")
            handler = make_handler(handler_factory,
                                   peer[0] if peer else None)
            splitter = get_splitter(framing)
            stream = _AsyncBridgeStream(reader, timeout)
            # splitters are synchronous; run each connection's split loop
            # in the executor so the loop stays free for accepts while
            # reads await in the bridge
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, splitter.run, stream, handler)
            writer.close()

        async def serve():
            server = await asyncio.start_server(handle, host, port)
            self.bound_port = server.sockets[0].getsockname()[1]
            async with server:
                await server.serve_forever()

        asyncio.run(serve())


class _AsyncBridgeStream:
    """Synchronous read() facade over an asyncio StreamReader."""

    def __init__(self, reader, timeout):
        import asyncio

        self.reader = reader
        self.timeout = timeout
        self.loop = asyncio.get_running_loop()

    def read(self, n: int) -> bytes:
        import asyncio
        import concurrent.futures

        fut = asyncio.run_coroutine_threadsafe(
            asyncio.wait_for(self.reader.read(n), self.timeout), self.loop)
        try:
            return fut.result()
        except (asyncio.TimeoutError, concurrent.futures.TimeoutError):
            raise TimeoutError
