"""Inputs (transports): drive the splitter → handler pipeline.

Parity model: /root/reference/src/flowgger/input/ — trait
``Input { accept(tx, decoder, encoder) }`` (input/mod.rs:33-40).  The
redesigned signature takes a *handler factory* instead of decoder+encoder:
each connection/worker asks for a fresh handler (the reference clones the
boxed decoder/encoder per thread, tcp_input.rs:44); a factory lets the
TPU batch handler own per-connection batch arenas the same way.
"""

from __future__ import annotations

import inspect


def make_handler(handler_factory, peer=None):
    """Build one connection's handler, passing the transport's source
    identity (peer IP, file path) when the factory accepts it — the
    tenancy layer resolves ``peer`` to a tenant for admission.  Plain
    zero-arg factories (tests, embedded pipelines) keep working."""
    if peer is None:
        return handler_factory()
    try:
        params = inspect.signature(handler_factory).parameters
    except (TypeError, ValueError):
        return handler_factory()
    if "peer" in params or any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()):
        return handler_factory(peer=peer)
    return handler_factory()


class Input:
    def accept(self, handler_factory) -> None:
        """Run the transport forever (blocking).  ``handler_factory()``
        returns a fresh ``splitters.Handler`` per connection/worker;
        transports that know their peer build handlers through
        ``make_handler(handler_factory, peer)`` instead."""
        raise NotImplementedError


from .stdin_input import StdinInput  # noqa: E402

__all__ = ["Input", "StdinInput"]
