"""Inputs (transports): drive the splitter → handler pipeline.

Parity model: /root/reference/src/flowgger/input/ — trait
``Input { accept(tx, decoder, encoder) }`` (input/mod.rs:33-40).  The
redesigned signature takes a *handler factory* instead of decoder+encoder:
each connection/worker asks for a fresh handler (the reference clones the
boxed decoder/encoder per thread, tcp_input.rs:44); a factory lets the
TPU batch handler own per-connection batch arenas the same way.
"""

from __future__ import annotations


class Input:
    def accept(self, handler_factory) -> None:
        """Run the transport forever (blocking).  ``handler_factory()``
        returns a fresh ``splitters.Handler`` per connection/worker."""
        raise NotImplementedError


from .stdin_input import StdinInput  # noqa: E402

__all__ = ["Input", "StdinInput"]
