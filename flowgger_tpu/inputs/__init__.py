"""Inputs (transports): drive the splitter → handler pipeline.

Parity model: /root/reference/src/flowgger/input/ — trait
``Input { accept(tx, decoder, encoder) }`` (input/mod.rs:33-40).  The
redesigned signature takes a *handler factory* instead of decoder+encoder:
each connection/worker asks for a fresh handler (the reference clones the
boxed decoder/encoder per thread, tcp_input.rs:44); a factory lets the
TPU batch handler own per-connection batch arenas the same way.
"""

from __future__ import annotations

import inspect
import threading
import time


def make_handler(handler_factory, peer=None):
    """Build one connection's handler, passing the transport's source
    identity (peer IP, file path) when the factory accepts it — the
    tenancy layer resolves ``peer`` to a tenant for admission.  Plain
    zero-arg factories (tests, embedded pipelines) keep working."""
    if peer is None:
        return handler_factory()
    try:
        params = inspect.signature(handler_factory).parameters
    except (TypeError, ValueError):
        return handler_factory()
    if "peer" in params or any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()):
        return handler_factory(peer=peer)
    return handler_factory()


class Input:
    def accept(self, handler_factory) -> None:
        """Run the transport forever (blocking).  ``handler_factory()``
        returns a fresh ``splitters.Handler`` per connection/worker;
        transports that know their peer build handlers through
        ``make_handler(handler_factory, peer)`` instead."""
        raise NotImplementedError

    # -- per-connection handler-thread lifecycle ---------------------------
    # Thread-per-connection transports (tcp/tls) spawn through here so
    # every handler is *tracked*: finished ones are reaped on each
    # accept (the set stays bounded by live connections — the PR 6
    # unbounded-growth lesson), and drain can bounded-wait for the rest
    # through join_handlers().  Lazy init: transports don't call
    # super().__init__.

    def _spawn_handler(self, target, args: tuple) -> None:
        """Start a tracked daemon thread for one connection."""
        lock = self.__dict__.setdefault("_handlers_lock", threading.Lock())
        t = threading.Thread(target=target, args=args, daemon=True)
        with lock:
            live = {h for h in self.__dict__.get("_handlers", ())
                    if h.is_alive()}
            live.add(t)
            self._handlers = live
        t.start()

    def join_handlers(self, timeout: float = 2.0) -> int:
        """Drain hook: wait (boundedly, across ALL handlers) for
        in-flight connection handlers to finish; returns how many are
        still alive — those are abandoned daemon threads, the same
        contract as the output-thread drain stragglers."""
        lock = self.__dict__.setdefault("_handlers_lock", threading.Lock())
        with lock:
            live = [h for h in self.__dict__.get("_handlers", ())
                    if h.is_alive()]
        deadline = time.monotonic() + timeout
        for t in live:
            t.join(max(0.0, deadline - time.monotonic()))
        with lock:
            self._handlers = {h for h in live if h.is_alive()}
            return len(self._handlers)


from .stdin_input import StdinInput  # noqa: E402

__all__ = ["Input", "StdinInput"]
