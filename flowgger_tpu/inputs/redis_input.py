"""Redis input: reliable-queue consumer.

Parity model: /root/reference/src/flowgger/input/redis_input.rs:12-163.
Each of ``input.redis_threads`` workers:
1. drains its leftover ``{key}.tmp.{tid}`` queue back onto the main key
   (crash recovery — messages in flight when a previous process died are
   re-enqueued, giving at-least-once delivery);
2. loops BRPOPLPUSH main → tmp, processes the message, then LREMs it
   from tmp.
Connection loss *reconnects in-process* with the shared RetryPolicy
(jittered exponential backoff, ``input.redis_retry_*`` keys) — the
reliable-queue drain on reconnect re-enqueues in-flight messages, so
at-least-once delivery holds across reconnects exactly as it does
across process restarts.  Only an exhausted retry budget (when
``input.redis_retry_attempts`` is set; default unlimited) falls back to
the reference's exit-1 supervisor-restart contract.
Wire protocol is the built-in RESP client (utils/resp.py) — no redis-py
dependency.
"""

from __future__ import annotations

import sys
import threading
import time

from . import Input
from ..config import Config
from ..utils.resp import RespClient, RespError
from ..utils.retry import RetryPolicy, retry_config_kwargs

DEFAULT_CONNECT = "127.0.0.1"
DEFAULT_QUEUE_KEY = "logs"
DEFAULT_THREADS = 1
DEFAULT_RETRY_INIT = 200
DEFAULT_RETRY_MAX = 10_000


class RedisWorker:
    def __init__(self, tid: int, connect: str, queue_key: str, handler):
        self.tid = tid
        self.connect = connect
        self.queue_key = queue_key
        self.handler = handler
        try:
            self.cnx = RespClient.from_connect_string(connect)
        except OSError as e:
            raise RuntimeError(
                f"Unable to connect to the Redis server: [{connect}], error: {e}")

    def run(self):
        queue_key = self.queue_key
        tmp_key = f"{queue_key}.tmp.{self.tid}"
        print(f"Connected to Redis [{self.connect}], pulling messages from "
              f"key [{queue_key}]")
        # crash recovery: push any leftover in-flight messages back
        while True:
            try:
                if self.cnx.rpoplpush(tmp_key, queue_key) is None:
                    break
            except RespError:  # flowcheck: disable=FC04 -- recovery drain only; the main BRPOPLPUSH loop raises on real errors
                break
        while True:
            try:
                line = self.cnx.brpoplpush(queue_key, tmp_key, 0)
            except (RespError, OSError) as e:
                raise RuntimeError(f"Redis protocol error in BRPOPLPUSH: [{e}]")
            if line is None:
                continue
            self.handler.handle_bytes(line)
            try:
                self.cnx.lrem(tmp_key, 1, line)
            except (RespError, OSError) as e:
                raise RuntimeError(f"Redis protocol error in LREM: [{e}]")


class RedisInput(Input):
    def __init__(self, config: Config):
        self.connect = config.lookup_str(
            "input.redis_connect", "input.redis_connect must be an ip:port string",
            DEFAULT_CONNECT)
        self.queue_key = config.lookup_str(
            "input.redis_queue_key", "input.redis_queue_key must be a string",
            DEFAULT_QUEUE_KEY)
        self.threads = config.lookup_int(
            "input.redis_threads", "input.redis_threads must be a 32-bit integer",
            DEFAULT_THREADS)
        self._retry_kw = retry_config_kwargs(
            config, "input.redis",
            init_ms=DEFAULT_RETRY_INIT, max_ms=DEFAULT_RETRY_MAX)
        self.exit_on_failure = True  # tests disable to keep pytest alive

    def _worker(self, tid: int, handler_factory):
        handler = handler_factory()
        policy = RetryPolicy(metric="input_reconnects", **self._retry_kw)
        while True:
            policy.mark()
            started = time.monotonic()
            try:
                worker = RedisWorker(tid, self.connect, self.queue_key,
                                     handler)
                worker.run()
                return  # unreachable today; future clean-shutdown hook
            except (RuntimeError, OSError) as e:
                print(f"Redis connection lost - {e}", file=sys.stderr)
                policy.note_run(started)  # stable runs earn a fresh budget
                if policy.backoff() is None:
                    print("Redis connection lost, aborting", file=sys.stderr)
                    break
                print(f"Reconnecting to Redis [{self.connect}] "
                      f"(attempt #{policy.attempts})", file=sys.stderr)
        if self.exit_on_failure:
            import os

            os._exit(1)

    def accept(self, handler_factory) -> None:
        threads = []
        for tid in range(self.threads):
            t = threading.Thread(target=self._worker, args=(tid, handler_factory),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
