"""Built-in TCP steering proxy: enforced shares with no external LB.

For deployments without haproxy/nginx in front, ``control.proxy``
starts this minimal layer-4 proxy on any (usually every) host: each
inbound connection is routed to one fleet host picked
weighted-randomly by the live ``fleet.shares``, and the bytes are
pumped verbatim both ways until either side closes.  Routing honors
the 200/503 contract exactly — only routable (joining/active) hosts
are candidates, so a draining host stops receiving *new* connections
while its in-flight streams finish, which is precisely the behavior
the healthz contract promises an external LB.

Per-connection routing (not per-byte, not per-record): a syslog
sender's stream stays on one backend for the connection's life, so
framing, ordering, and tenant attribution are untouched — the proxy
is invisible at the byte level (the ``test_control`` byte-identity
tests pin this per framing mode).

The roster is re-read from the injected ``roster_fn`` on every
accept, so capacity decay (share feedback) shifts *new* connections
within one heartbeat of the weight change — no reload, no restart.

Scope: this is deliberately a minimal steering tier, not an LB
product — no health probing beyond membership state, no retry once
bytes have flowed (a mid-stream backend death drops the connection;
the sender's reconnect lands on a live host), no TLS termination
(point senders' TLS at the hosts directly, or keep a real LB for
that).  Counters: ``proxy_connections``, ``proxy_bytes``,
``proxy_route_errors``.
"""

from __future__ import annotations

import random
import socket
import sys
import threading
from typing import Callable, List, Optional

from ..utils.metrics import registry as _metrics

ROUTABLE_STATES = ("joining", "active")
_PUMP_CHUNK = 65536
_ACCEPT_POLL_S = 0.5


def _ingest_addr(fleet_addr: str, ingest_port: int) -> str:
    host = fleet_addr.rsplit(":", 1)[0] if ":" in fleet_addr else fleet_addr
    return f"{host}:{ingest_port}" if ingest_port > 0 else fleet_addr


def pick_backend(roster: List[dict], ingest_port: int,
                 rng: random.Random) -> Optional[str]:
    """Weighted-random routable host -> its ingest ``host:port`` (None
    when nothing is routable — the caller refuses the connection, the
    proxy's 503)."""
    routable = [p for p in roster if p.get("state") in ROUTABLE_STATES]
    if not routable:
        return None
    weights = [max(0.0, float(p.get("share", 0.0))) for p in routable]
    total = sum(weights)
    if total <= 0:
        chosen = routable[rng.randrange(len(routable))]
    else:
        roll = rng.random() * total
        chosen = routable[-1]
        for peer, w in zip(routable, weights):
            roll -= w
            if roll < 0:
                chosen = peer
                break
    return _ingest_addr(str(chosen["addr"]), ingest_port)


class SteeringProxy:
    """Accept loop + two pump threads per connection."""

    def __init__(self, bind: str, port: int,
                 roster_fn: Callable[[], List[dict]],
                 ingest_port: int = 0, rng: Optional[random.Random] = None,
                 dial_timeout: float = 5.0):
        self._bind = bind
        self._port = port
        self._roster_fn = roster_fn
        self._ingest_port = ingest_port
        self._rng = rng if rng is not None else random.Random()
        self._dial_timeout = dial_timeout
        self._listener: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @property
    def addr(self) -> str:
        assert self._listener is not None, "proxy not started"
        host, port = self._listener.getsockname()[:2]
        return f"{host}:{port}"

    def start(self) -> None:
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self._bind, self._port))
        listener.listen(128)
        listener.settimeout(_ACCEPT_POLL_S)
        self._listener = listener
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="steer-proxy")
        self._thread.start()

    def stop(self) -> None:
        """Stop accepting.  In-flight connections finish on their own
        pump threads — a proxy restart must not cut live streams."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None

    # -- internals ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _peer = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self._route(conn)

    def _route(self, conn: socket.socket) -> None:
        target = pick_backend(self._roster_fn(), self._ingest_port,
                              self._rng)
        if target is None:
            _metrics.inc("proxy_route_errors")
            conn.close()  # nothing routable: the proxy's 503
            return
        host, _, port = target.rpartition(":")
        try:
            upstream = socket.create_connection(
                (host, int(port)), timeout=self._dial_timeout)
        except OSError as e:
            _metrics.inc("proxy_route_errors")
            print(f"proxy: dial {target} failed ({e})", file=sys.stderr)
            conn.close()
            return
        upstream.settimeout(None)
        conn.settimeout(None)
        _metrics.inc("proxy_connections")
        # one pump per direction; each propagates EOF as a half-close
        # so framed protocols see the exact shutdown sequence a direct
        # connection would
        refs = [2]
        lock = threading.Lock()
        for src, dst in ((conn, upstream), (upstream, conn)):
            # flowcheck: disable=FC10 -- pump pair owns its own lifecycle: each exits on EOF/error and the refs+lock pair closes both sockets when the last pump leaves; a drain join would wait on idle-but-open client connections
            threading.Thread(
                target=self._pump, args=(src, dst, refs, lock),
                daemon=True, name="steer-pump").start()

    @staticmethod
    def _pump(src: socket.socket, dst: socket.socket,
              refs: list, lock: threading.Lock) -> None:
        try:
            while True:
                data = src.recv(_PUMP_CHUNK)
                if not data:
                    break
                dst.sendall(data)
                _metrics.inc("proxy_bytes", len(data))
        except OSError:
            pass
        try:
            dst.shutdown(socket.SHUT_WR)  # forward the EOF
        except OSError:
            pass
        with lock:
            refs[0] -= 1
            done = refs[0] == 0
        if done:
            for sock in (src, dst):
                try:
                    sock.close()
                except OSError:
                    pass
