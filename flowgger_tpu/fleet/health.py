"""Per-host health export + heartbeat transport: one small HTTP
endpoint per fleet host.

A load balancer (or ``tools/fleetctl.py``, or a peer) talks to it:

- ``GET /healthz`` — the full health document: local host identity and
  lifecycle state, the fleet view (per-peer states, last-heartbeat
  ages, ``fleet_hosts_*`` counts), and the complete metrics-registry
  snapshot (lane economics, breaker state, queue depth, aot_hits,
  tenant aggregates — everything ``utils/metrics.py`` reports).
  Status code is the contract for dumb LBs: **200** while the host
  should receive traffic (joining/active), **503** once it should not
  (draining/departed), so ``GET /healthz`` drops out of rotation the
  moment drain-on-departure begins.
- ``POST /hb`` (and ``/join``, the same handler — a join is just a
  first heartbeat) — the peer heartbeat exchange: body carries the
  sender's identity, the JSON reply carries this host's roster (the
  gossip channel) and its view of the sender (how an evicted host
  finds out).
- ``POST /drain`` — ask this host to drain: flips it to ``draining``
  and triggers the pipeline's SIGTERM drain path when one is attached
  (``fleetctl drain``).
- ``POST /fault`` — arm/disarm one ``utils/faultinject.py`` site at
  runtime (``{"site": ..., "spec": "once:1"}``).  Only served when the
  host opted in (``input.tpu_fleet_chaos = true``, the chaos-harness
  switch); otherwise 403 — production hosts must not expose a
  kill-me-on-request verb.
- ``GET /metrics`` — the registry in the Prometheus text exposition
  format (obs/prom.py): counters as ``_total`` series, gauges,
  histogram families as summaries — the scrape leg for fleet hosts.
- ``GET /fleetz`` — the fleet-level observability document
  (federation.Fleet.fleetz_payload): merged metrics snapshots across
  every routable host (counters summed, histogram quantiles from
  pooled samples), the union of recent degradation events tagged by
  rank, per-host staleness marking, and fleet-level SLO status.  Every
  host serves it from its own view; the agreed rendezvous host is the
  one ``fleetctl top`` (and operators) should ask.
- ``GET /trace`` — the flight recorder's completed-batch ring as
  Chrome trace-event JSON (Perfetto-loadable; empty when
  ``[metrics] trace`` is off).
- ``POST /profile`` — toggle the on-demand XLA profiler (the SIGUSR2
  twin): a soak run captures an xprof trace without a restart.

Transport choice: plain HTTP over TCP, one short-lived connection per
exchange, every socket under a hard timeout.  No JAX collectives, no
long-lived connections a dead peer could wedge — a peer that stops
answering costs exactly one timed-out connect per heartbeat interval,
on a background thread, never on the decode path.

The server threads run daemonized under ``ThreadingHTTPServer``; the
accept loop itself is spawned through the pipeline ``Supervisor`` so a
crashed exporter restarts with backoff instead of silently going dark
(see federation.py).
"""

from __future__ import annotations

import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

MAX_BODY = 1 << 20  # heartbeat bodies are ~100s of bytes; cap abuse


class PartitionDrop(Exception):
    """Raised by the heartbeat sink when the ``peer_partition`` fault
    site fires: the exchange is dropped as if the network ate it."""


class HealthService:
    """The HTTP listener.  ``on_heartbeat``/``on_drain``/``payload``
    are injected by ``federation.Fleet`` (tests inject fakes)."""

    def __init__(self, bind: str, port: int,
                 payload: Callable[[], Dict[str, object]],
                 healthy: Callable[[], bool],
                 on_heartbeat: Optional[Callable[[dict], dict]] = None,
                 on_drain: Optional[Callable[[], dict]] = None,
                 on_fault: Optional[Callable[[dict], dict]] = None,
                 on_fleetz: Optional[Callable[[], Dict[str, object]]] = None):
        self._payload = payload
        self._healthy = healthy
        self._on_heartbeat = on_heartbeat
        self._on_drain = on_drain
        self._on_fault = on_fault
        self._on_fleetz = on_fleetz
        service = self

        class Handler(BaseHTTPRequestHandler):
            # one heartbeat per connection; keep-alive would pin a
            # server thread per peer for no benefit
            protocol_version = "HTTP/1.0"

            def log_message(self, fmt, *args):  # noqa: N802 - stdlib name
                pass  # health probes at 1Hz+ would flood stderr

            def _reply(self, code: int, doc: Dict[str, object]) -> None:
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_raw(self, code: int, body: bytes,
                           ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - stdlib name
                path = self.path.split("?")[0]
                if path == "/metrics":
                    from ..obs import prom as _prom

                    self._reply_raw(200, _prom.render().encode(),
                                    _prom.PROM_CONTENT_TYPE)
                    return
                if path == "/trace":
                    from ..obs import prom as _prom

                    self._reply_raw(200, _prom.trace_document(),
                                    "application/json")
                    return
                if path == "/fleetz":
                    if service._on_fleetz is None:
                        self._reply(501, {"error": "no fleet aggregator"})
                        return
                    # always 200: /fleetz reports on the FLEET, and a
                    # draining host's view of its peers is still a
                    # valid (rank-attributed) answer
                    self._reply(200, service._on_fleetz())
                    return
                if path != "/healthz":
                    self._reply(404, {"error": "unknown path",
                                      "paths": ["/healthz", "/fleetz",
                                                "/metrics", "/trace"]})
                    return
                code = 200 if service._healthy() else 503
                self._reply(code, service._payload())

            def do_POST(self):  # noqa: N802 - stdlib name
                path = self.path.split("?")[0]
                if path == "/profile":
                    from ..obs import prom as _prom

                    self._reply(200, _prom.profile_toggle())
                    return
                if path == "/drain":
                    if service._on_drain is None:
                        self._reply(501, {"error": "no drain hook"})
                        return
                    self._reply(200, service._on_drain())
                    return
                if path == "/fault":
                    if service._on_fault is None:
                        # chaos control is opt-in (tpu_fleet_chaos):
                        # a production host refuses, loudly
                        self._reply(403, {"error": "fault control "
                                          "disabled (input."
                                          "tpu_fleet_chaos = false)"})
                        return
                    from ..utils.faultinject import FaultInjectError

                    try:
                        length = min(int(self.headers.get(
                            "Content-Length", 0)), MAX_BODY)
                        msg = json.loads(self.rfile.read(length) or b"{}")
                        if not isinstance(msg, dict):
                            raise ValueError("fault body must be an "
                                             "object")
                        self._reply(200, service._on_fault(msg))
                    except (ValueError, OSError,
                            FaultInjectError) as e:
                        self._reply(400, {"error": f"bad fault: {e}"})
                    return
                if path not in ("/hb", "/join"):
                    self._reply(404, {"error": "unknown path",
                                      "paths": ["/hb", "/join", "/drain",
                                                "/profile", "/fault"]})
                    return
                if service._on_heartbeat is None:
                    self._reply(501, {"error": "no heartbeat sink"})
                    return
                try:
                    length = min(int(self.headers.get("Content-Length", 0)),
                                 MAX_BODY)
                    msg = json.loads(self.rfile.read(length) or b"{}")
                    if not isinstance(msg, dict):
                        raise ValueError("heartbeat body must be an object")
                except (ValueError, OSError) as e:
                    self._reply(400, {"error": f"bad heartbeat: {e}"})
                    return
                try:
                    self._reply(200, service._on_heartbeat(msg))
                except PartitionDrop:
                    # injected partition: answer like a flaky network
                    # path would — the sender sees a failed delivery
                    self._reply(503, {"error": "partitioned"})

        self._server = ThreadingHTTPServer((bind, port), Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        """The bound port (resolves ``port=0`` ephemeral binds)."""
        return self._server.server_address[1]

    @property
    def addr(self) -> str:
        host = self._server.server_address[0]
        return f"{host}:{self.port}"

    def start(self, supervisor=None) -> None:
        """Serve until ``stop()``.  With a pipeline ``Supervisor`` the
        accept loop restarts on crash; without one (tests, fleetctl
        smoke) it runs on a plain daemon thread."""
        if self._thread is not None:
            return
        if supervisor is not None:
            # a dead health endpoint takes the host out of LB rotation,
            # not the process down: exhausted budget returns
            self._thread = supervisor.spawn(
                self._server.serve_forever, "fleet-health",
                exhausted="return")
        else:
            self._thread = threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name="fleet-health")
            self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError as e:
            # a half-closed listener at teardown is not worth a crash,
            # but say so — silent shutdown bugs hide port leaks
            print(f"fleet-health: shutdown error: {e}", file=sys.stderr)
        # shutdown() already waited for serve_forever to exit; the join
        # closes the last gap (the thread's own teardown) boundedly
        self._thread.join(timeout=2)
        self._thread = None
