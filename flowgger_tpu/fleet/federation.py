"""Fleet federation: N single-host pipelines become one fleet.

Topology (coordinator-rendezvous, then full-mesh peer heartbeats):
every host runs its own LaneSet over its own chips and its own
ingest/output stack — the fleet layer adds only *membership* and
*observability* on top, never a collective:

1. each host starts its :class:`~flowgger_tpu.fleet.health.HealthService`
   and heartbeats the configured coordinator (rank 0's endpoint);
2. heartbeat replies carry the responder's roster, so every host
   discovers every peer's address through the coordinator (gossip);
3. from then on hosts heartbeat **all** known peers directly — the
   coordinator is only the bootstrap address, and its death degrades
   rendezvous for *new* joiners, never the running fleet;
4. the per-host :class:`~flowgger_tpu.fleet.membership.Membership`
   ages peers through the missed-heartbeat ladder (active → suspect →
   draining/evicted → departed) and exports the view.

Config — all under ``[input]`` beside the ``tpu_*`` family (one
``flowgger.toml`` per host, same file everywhere except the rank)::

    tpu_fleet = true                      # master switch
    tpu_fleet_bind = "0.0.0.0"            # health/heartbeat listen host
    tpu_fleet_port = 8476                 # listen port (0 = ephemeral)
    tpu_fleet_advertise = "10.0.0.2:8476" # addr peers dial (default
                                          # bind:port)
    tpu_fleet_coordinator = "10.0.0.1:8476"  # rank 0's endpoint;
                                          # optional on rank 0 itself
    tpu_fleet_heartbeat_ms = 500          # ticker interval
    tpu_fleet_suspect_ms = 2000           # missed-heartbeat -> suspect
    tpu_fleet_evict_ms = 5000             # -> draining (evicted)
    tpu_fleet_depart_ms = 2000            # evicted -> departed grace
    tpu_fleet_rejoin_backoff_ms = 1000    # self-eviction rejoin backoff

Rank and fleet size default from the ``jax.distributed`` spec
(``input.tpu_process_id`` / ``tpu_num_processes``) so a multi-host JAX
config grows fleet membership with three added lines; fleet-only
deployments (scalar pipelines, heterogeneous hosts) set
``tpu_fleet_rank`` / ``tpu_fleet_hosts`` instead.

Failure semantics: heartbeats ride the ticker thread (supervised),
every send is a short-lived HTTP POST under a hard socket timeout, and
a dead peer costs one timed-out connect per interval — the decode hot
path never waits on the fleet.  A host that discovers its own eviction
(a reply's view of it says draining/departed at its incarnation) backs
off through ``Supervisor.fleet_policy`` and rejoins with a fresh
incarnation (counted as ``fleet_rejoins``).

Fault sites (``utils/faultinject.py``): ``peer_partition`` drops
inbound heartbeats (optionally only from ``FLOWGGER_PARTITION_PEER``),
``host_kill`` SIGKILLs this process from the ticker — both
deterministic, for the multi-process acceptance tests.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

from ..config import Config, ConfigError
from ..utils import faultinject
from ..utils.metrics import registry as _global_registry
from .health import HealthService, PartitionDrop
from .membership import (
    ACTIVE,
    DEPARTED,
    DRAINING,
    JOINING,
    Membership,
)

DEFAULT_HEARTBEAT_MS = 500
DEFAULT_SUSPECT_MS = 2_000
DEFAULT_EVICT_MS = 5_000
DEFAULT_DEPART_MS = 2_000
DEFAULT_REJOIN_BACKOFF_MS = 1_000

PARTITION_PEER_ENV = "FLOWGGER_PARTITION_PEER"

# health-document schema version; tests/resources/healthz_schema.json
# is the golden copy a CI test validates real payloads against.
# v2: added the observability sections — ``events`` (degradation
# journal ring + per-reason counts, obs/events.py) and ``trace``
# (flight-recorder mode/ring stats, obs/trace.py)
HEALTH_SCHEMA = 2


@dataclass
class FleetSpec:
    rank: int
    hosts: int
    bind: str
    port: int
    advertise: Optional[str]
    coordinator: Optional[str]
    heartbeat_ms: int
    suspect_ms: int
    evict_ms: int
    depart_ms: int
    rejoin_backoff_ms: int


def _check_mesh_conflict(config: Config) -> None:
    """Config-time lanes-vs-mesh resolution for fleet hosts: PR 5 lane
    dispatch supersedes the sharded decode mesh whenever more than one
    lane resolves, so a fleet config pinning both is an error *now*,
    not a silently-unused mesh at the first batch."""
    mesh_mode = config.lookup_str(
        "input.tpu_mesh", "input.tpu_mesh must be a string", "auto")
    lanes = config.lookup_int(
        "input.tpu_lanes",
        "input.tpu_lanes must be an integer (device lanes)", None)
    if mesh_mode == "on" and lanes is not None and lanes > 1:
        raise ConfigError(
            'input.tpu_lanes > 1 and input.tpu_mesh = "on" are mutually '
            "exclusive on a fleet host (lanes give each chip its own "
            "batches; the mesh shards one batch across chips) — drop "
            "one of the two keys")


def fleet_spec(config: Config) -> Optional[FleetSpec]:
    """Parse the ``input.tpu_fleet_*`` family; None when the config
    doesn't ask for fleet membership.  Validation raises ``ConfigError``
    with the key name, matching the reference's config error style."""
    enabled = config.lookup_bool(
        "input.tpu_fleet", "input.tpu_fleet must be a boolean", False)
    if not enabled:
        return None
    _check_mesh_conflict(config)
    # rank/size: fleet keys win, jax.distributed spec is the default
    dist_rank = config.lookup_int(
        "input.tpu_process_id", "input.tpu_process_id must be an integer")
    dist_hosts = config.lookup_int(
        "input.tpu_num_processes",
        "input.tpu_num_processes must be an integer")
    rank = config.lookup_int(
        "input.tpu_fleet_rank", "input.tpu_fleet_rank must be an integer",
        dist_rank if dist_rank is not None else 0)
    hosts = config.lookup_int(
        "input.tpu_fleet_hosts", "input.tpu_fleet_hosts must be an integer",
        dist_hosts if dist_hosts is not None else 1)
    if hosts < 1:
        raise ConfigError("input.tpu_fleet_hosts must be >= 1")
    if not 0 <= rank < hosts:
        raise ConfigError(
            "input.tpu_fleet_rank must be in [0, tpu_fleet_hosts)")
    bind = config.lookup_str(
        "input.tpu_fleet_bind", "input.tpu_fleet_bind must be a string",
        "127.0.0.1")
    port = config.lookup_int(
        "input.tpu_fleet_port",
        "input.tpu_fleet_port must be an integer (0 = ephemeral)", 0)
    if not 0 <= port < 65536:
        raise ConfigError("input.tpu_fleet_port must be in [0, 65536)")
    advertise = config.lookup_str(
        "input.tpu_fleet_advertise",
        "input.tpu_fleet_advertise must be a host:port string")
    if advertise is None and hosts > 1 and bind in ("0.0.0.0", "::", ""):
        # the advertise default is bind:port — a wildcard bind would
        # gossip "0.0.0.0:port", which every peer resolves to ITSELF
        # and the healthy host gets evicted fleet-wide.  Catch it at
        # config time, not as a mystery eviction in production
        raise ConfigError(
            "input.tpu_fleet_advertise is required when "
            "tpu_fleet_bind is a wildcard address (peers cannot dial "
            f"\"{bind}\")")
    coordinator = config.lookup_str(
        "input.tpu_fleet_coordinator",
        "input.tpu_fleet_coordinator must be a host:port string")
    if coordinator is None and rank != 0 and hosts > 1:
        raise ConfigError(
            "input.tpu_fleet_coordinator is required on ranks > 0 "
            "(rank 0's health endpoint is the rendezvous address)")
    heartbeat_ms = config.lookup_int(
        "input.tpu_fleet_heartbeat_ms",
        "input.tpu_fleet_heartbeat_ms must be an integer (ms)",
        DEFAULT_HEARTBEAT_MS)
    suspect_ms = config.lookup_int(
        "input.tpu_fleet_suspect_ms",
        "input.tpu_fleet_suspect_ms must be an integer (ms)",
        DEFAULT_SUSPECT_MS)
    evict_ms = config.lookup_int(
        "input.tpu_fleet_evict_ms",
        "input.tpu_fleet_evict_ms must be an integer (ms)",
        DEFAULT_EVICT_MS)
    depart_ms = config.lookup_int(
        "input.tpu_fleet_depart_ms",
        "input.tpu_fleet_depart_ms must be an integer (ms)",
        DEFAULT_DEPART_MS)
    rejoin_ms = config.lookup_int(
        "input.tpu_fleet_rejoin_backoff_ms",
        "input.tpu_fleet_rejoin_backoff_ms must be an integer (ms)",
        DEFAULT_REJOIN_BACKOFF_MS)
    if heartbeat_ms < 1:
        raise ConfigError("input.tpu_fleet_heartbeat_ms must be >= 1")
    if not heartbeat_ms < suspect_ms < evict_ms:
        raise ConfigError(
            "fleet deadlines must satisfy tpu_fleet_heartbeat_ms < "
            "tpu_fleet_suspect_ms < tpu_fleet_evict_ms")
    return FleetSpec(rank=rank, hosts=hosts, bind=bind, port=port,
                     advertise=advertise, coordinator=coordinator,
                     heartbeat_ms=heartbeat_ms, suspect_ms=suspect_ms,
                     evict_ms=evict_ms, depart_ms=depart_ms,
                     rejoin_backoff_ms=rejoin_ms)


def _http_post_json(addr: str, path: str, doc: dict, timeout: float,
                    registry=_global_registry) -> Optional[dict]:
    """One short-lived POST; None on any failed delivery — a fleet
    send failing is normal life under partition/churn, so it is counted
    (``fleet_hb_send_errors``), not logged.  ``addr`` is remote input
    (gossip can relay anything), so even parsing it stays inside the
    failure path: a malformed peer entry costs one counted miss, never
    the ticker thread."""
    import http.client

    conn = None
    try:
        host, _, port = addr.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
        body = json.dumps(doc).encode()
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            # a 503 (partitioned / draining listener) is a failed
            # delivery too — uncounted it would make a partition with
            # live listeners look like a clean network
            registry.inc("fleet_hb_send_errors")
            return None
        out = json.loads(data)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        registry.inc("fleet_hb_send_errors")
        return None
    finally:
        if conn is not None:
            conn.close()


class Fleet:
    """One host's fleet agent: health service + heartbeat ticker +
    membership, wired into the pipeline's drain lifecycle."""

    def __init__(self, spec: FleetSpec, supervisor=None, registry=None,
                 on_drain=None):
        self.spec = spec
        self.supervisor = supervisor
        self._registry = registry if registry is not None else _global_registry
        self._on_drain_cb = on_drain
        self._stop = threading.Event()
        self._ticker: Optional[threading.Thread] = None
        self._draining = False  # voluntary drain: disables rejoin
        self._lock = threading.Lock()
        self.membership: Optional[Membership] = None
        self.service: Optional[HealthService] = None
        self._rejoin_policy = None  # lazily built; persists across rejoins
        self._started = time.monotonic()

    @classmethod
    def from_config(cls, config: Config, supervisor=None, registry=None,
                    on_drain=None) -> Optional["Fleet"]:
        spec = fleet_spec(config)
        if spec is None:
            return None
        return cls(spec, supervisor=supervisor, registry=registry,
                   on_drain=on_drain)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        spec = self.spec
        self.service = HealthService(
            spec.bind, spec.port, payload=self.health_payload,
            healthy=self._lb_healthy, on_heartbeat=self.on_heartbeat,
            on_drain=self._drain_requested)
        advertise = spec.advertise or \
            f"{spec.bind}:{self.service.port}"
        self.membership = Membership(
            rank=spec.rank, addr=advertise, suspect_ms=spec.suspect_ms,
            evict_ms=spec.evict_ms, depart_ms=spec.depart_ms,
            registry=self._registry)
        self.service.start(self.supervisor)
        self.membership.activate()
        print(f"fleet: rank {spec.rank}/{spec.hosts} active, "
              f"health endpoint http://{self.service.addr}/healthz",
              file=sys.stderr)
        if self.supervisor is not None:
            self._ticker = self.supervisor.spawn(
                self._tick_loop, "fleet-ticker", exhausted="return")
        else:
            self._ticker = threading.Thread(
                target=self._tick_loop, daemon=True, name="fleet-ticker")
            self._ticker.start()

    def enter_draining(self, sync_wave: bool = True) -> None:
        """Drain-on-departure, phase 1 (SIGTERM / fleetctl / EOF): the
        host stops being routable (healthz flips to 503) and announces
        ``draining`` to every peer so they absorb new traffic while
        this host's ``Pipeline._drain`` fence-all/straggler machinery
        flushes in-flight batches byte-identically.

        ``sync_wave=False`` fires the announce wave on its own thread —
        the ``POST /drain`` handler uses it so its HTTP reply never
        waits out one socket timeout per unreachable peer."""
        if self.membership is None:
            return
        with self._lock:
            if self._draining:
                return
            self._draining = True
        self.membership.mark_draining()
        if sync_wave:
            self._send_heartbeats()  # don't wait a tick: announce now
        else:
            threading.Thread(target=self._send_heartbeats, daemon=True,
                             name="fleet-drain-wave").start()

    def shutdown(self) -> None:
        """Drain-on-departure, phase 2: in-flight batches are flushed,
        announce ``departed`` and stop the fleet threads."""
        if self.membership is not None:
            with self._lock:
                self._draining = True
            if self.membership.local.state != DEPARTED:
                self.membership.mark_departed()
                self._send_heartbeats()
        self._stop.set()
        if self.service is not None:
            self.service.stop()

    def wait_active(self, hosts: int, timeout: float = 60.0) -> bool:
        """Block until ``hosts`` members are active (tests/bench
        rendezvous barrier; never used on the decode path)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.membership is not None and \
                    self.membership.counts()[ACTIVE] >= hosts:
                return True
            time.sleep(0.02)
        return False

    # -- ticker ------------------------------------------------------------
    def _tick_loop(self) -> None:
        interval = self.spec.heartbeat_ms / 1000.0
        while not self._stop.wait(interval):
            if faultinject.enabled() and faultinject.fire("host_kill"):
                # deterministic hard host loss for the acceptance
                # tests: SIGKILL, no drain, no goodbye — peers must
                # discover it through the missed-heartbeat ladder
                import signal

                print("faultinject: host_kill firing — SIGKILL",
                      file=sys.stderr, flush=True)
                os.kill(os.getpid(), signal.SIGKILL)
            self._send_heartbeats()
            if self.membership is not None:
                self.membership.tick()

    def _heartbeat_doc(self) -> dict:
        local = self.membership.local
        return {"op": "hb", "rank": local.rank, "addr": local.addr,
                "state": local.state, "incarnation": local.incarnation}

    def _send_heartbeats(self) -> None:
        if self.membership is None:
            return
        local = self.membership.local
        targets: Dict[str, Optional[int]] = {}
        if self.spec.coordinator and self.spec.coordinator != local.addr:
            targets[self.spec.coordinator] = None
        for rank, addr in self.membership.heartbeat_targets():
            if addr != local.addr:
                targets[addr] = rank
        timeout = max(0.05, min(1.0, self.spec.heartbeat_ms / 1000.0))
        doc = self._heartbeat_doc()
        for addr, rank in targets.items():
            reply = _http_post_json(addr, "/hb", doc, timeout,
                                    registry=self._registry)
            if reply is None:
                continue
            self._absorb_reply(reply)

    def _absorb_reply(self, reply: dict) -> None:
        """A heartbeat reply is liveness proof for the responder, a
        roster to gossip-merge, and possibly the news of our own
        eviction."""
        sender = reply.get("from")
        if isinstance(sender, dict):
            try:
                s_rank = int(sender["rank"])
                if faultinject.enabled():
                    # a partition blocks BOTH directions: when the named
                    # peer answers our heartbeat, the reply is liveness
                    # proof too, and it must drop with the site.  (The
                    # unnamed everything-partition is handled inbound —
                    # the receiver 503s, so no reply reaches here.)
                    named = self._partition_peer()
                    if named == s_rank and faultinject.fire(
                            "peer_partition"):
                        return
                self.membership.note_heartbeat(
                    s_rank, str(sender["addr"]),
                    str(sender.get("state", ACTIVE)),
                    int(sender.get("incarnation", 0)))
            except (KeyError, TypeError, ValueError):
                self._registry.inc("fleet_hb_send_errors")
        for entry in reply.get("roster", []):
            if not isinstance(entry, dict):
                continue
            try:
                self.membership.note_roster(
                    int(entry["rank"]), str(entry["addr"]),
                    str(entry["state"]), int(entry.get("incarnation", 0)))
            except (KeyError, TypeError, ValueError):
                self._registry.inc("fleet_hb_send_errors")
        view = reply.get("view")
        if isinstance(view, dict):
            self._maybe_rejoin(view)

    def _maybe_rejoin(self, view: dict) -> None:
        """A peer's view of *us* says draining/departed at our own (or
        a higher) incarnation: the fleet evicted us.  Back off through
        the supervisor's fleet ladder, then rejoin with a fresh
        incarnation — the fleet-granularity analog of the PR 2 thread
        restart."""
        local = self.membership.local
        with self._lock:
            voluntary = self._draining
        if voluntary or view.get("state") not in (DRAINING, DEPARTED):
            return
        try:
            seen_inc = int(view.get("incarnation", 0))
        except (TypeError, ValueError):
            return
        if seen_inc < local.incarnation:
            return  # stale view of a life we already left behind
        if self.supervisor is not None:
            if self._rejoin_policy is None:
                self._rejoin_policy = self.supervisor.fleet_policy(
                    init_ms=self.spec.rejoin_backoff_ms)
            if self._rejoin_policy.backoff() is None:
                print("fleet: rejoin budget exhausted, staying departed",
                      file=sys.stderr)
                self._stop.set()
                return
        else:
            self._registry.inc("fleet_rejoins")
            time.sleep(self.spec.rejoin_backoff_ms / 1000.0)
        inc = self.membership.local_rejoin()
        print(f"fleet: evicted by peers (view: {view.get('state')}); "
              f"rejoining as incarnation {inc}", file=sys.stderr)
        self._send_heartbeats()

    # -- inbound (health service callbacks) --------------------------------
    def _partition_peer(self) -> Optional[int]:
        raw = os.environ.get(PARTITION_PEER_ENV)
        if raw is None or not raw.strip().lstrip("-").isdigit():
            return None
        return int(raw)

    def on_heartbeat(self, msg: dict) -> dict:
        """Inbound ``POST /hb``: tie-break + absorb, reply with our
        roster, our identity, and our view of the sender."""
        try:
            rank = int(msg["rank"])
            addr = str(msg["addr"])
            state = str(msg.get("state", ACTIVE))
            inc = int(msg.get("incarnation", 0))
        except (KeyError, TypeError, ValueError) as e:
            raise PartitionDrop() from e  # malformed == undeliverable
        if faultinject.enabled():
            named = self._partition_peer()
            if (named is None or named == rank) \
                    and faultinject.fire("peer_partition"):
                raise PartitionDrop()
        accepted = self.membership.note_heartbeat(rank, addr, state, inc)
        local = self.membership.local
        return {
            "ok": bool(accepted),
            "from": {"rank": local.rank, "addr": local.addr,
                     "state": local.state,
                     "incarnation": local.incarnation},
            "roster": self.membership.roster(),
            "view": self.membership.view_of(rank),
        }

    def _drain_requested(self) -> dict:
        """Inbound ``POST /drain`` (fleetctl): flip to draining and
        kick the pipeline's drain path off-thread — the HTTP reply must
        not wait out a full queue flush, nor (sync_wave=False) one
        socket timeout per dead peer."""
        self.enter_draining(sync_wave=False)
        if self._on_drain_cb is not None:
            t = threading.Thread(target=self._on_drain_cb, daemon=True,
                                 name="fleet-drain-request")
            t.start()
        state = self.membership.local.state if self.membership else DRAINING
        return {"ok": True, "state": state}

    # -- health document ---------------------------------------------------
    def _lb_healthy(self) -> bool:
        if self.membership is None:
            return False
        return self.membership.local.state in (JOINING, ACTIVE)

    def health_payload(self) -> Dict[str, object]:
        """The ``GET /healthz`` document.  Schema is golden-file-tested
        (tests/resources/healthz_schema.json) — additive changes bump
        ``HEALTH_SCHEMA``."""
        from ..obs.events import journal as _journal
        from ..obs.trace import tracer as _tracer

        local = self.membership.local if self.membership else None
        counts = self.membership.counts() if self.membership else {}
        return {
            "schema": HEALTH_SCHEMA,
            "ts": round(time.time(), 3),
            "uptime_s": round(time.monotonic() - self._started, 3),
            "host": {
                "rank": local.rank if local else -1,
                "addr": local.addr if local else "",
                "state": local.state if local else "down",
                "incarnation": local.incarnation if local else 0,
                "draining": bool(self._draining),
            },
            "fleet": {
                "hosts": self.spec.hosts,
                "counts": counts,
                "peers": self.membership.roster() if self.membership else [],
            },
            "metrics": self._registry.snapshot(),
            "events": _journal.health_section(),
            "trace": _tracer.stats(),
        }
