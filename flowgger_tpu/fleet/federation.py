"""Fleet federation: N single-host pipelines become one fleet.

Topology (coordinator-rendezvous, then full-mesh peer heartbeats):
every host runs its own LaneSet over its own chips and its own
ingest/output stack — the fleet layer adds only *membership* and
*observability* on top, never a collective:

1. each host starts its :class:`~flowgger_tpu.fleet.health.HealthService`
   and heartbeats the configured coordinator (rank 0's endpoint);
2. heartbeat replies carry the responder's roster, so every host
   discovers every peer's address through the coordinator (gossip);
3. from then on hosts heartbeat **all** known peers directly — the
   coordinator is only the bootstrap address, and its death degrades
   rendezvous for *new* joiners, never the running fleet;
4. the per-host :class:`~flowgger_tpu.fleet.membership.Membership`
   ages peers through the missed-heartbeat ladder (active → suspect →
   draining/evicted → departed) and exports the view.

Config — all under ``[input]`` beside the ``tpu_*`` family (one
``flowgger.toml`` per host, same file everywhere except the rank)::

    tpu_fleet = true                      # master switch
    tpu_fleet_bind = "0.0.0.0"            # health/heartbeat listen host
    tpu_fleet_port = 8476                 # listen port (0 = ephemeral)
    tpu_fleet_advertise = "10.0.0.2:8476" # addr peers dial (default
                                          # bind:port)
    tpu_fleet_coordinator = "10.0.0.1:8476"  # rank 0's endpoint;
                                          # optional on rank 0 itself
    tpu_fleet_heartbeat_ms = 500          # ticker interval
    tpu_fleet_suspect_ms = 2000           # missed-heartbeat -> suspect
    tpu_fleet_evict_ms = 5000             # -> draining (evicted)
    tpu_fleet_depart_ms = 2000            # evicted -> departed grace
    tpu_fleet_rejoin_backoff_ms = 1000    # self-eviction rejoin backoff
    tpu_fleet_roster_path = "/var/lib/flowgger/roster.json"
                                          # durable roster journal:
                                          # bootstrap candidates when
                                          # the coordinator is dead
    tpu_fleet_capacity = 1.0              # advertised traffic weight
                                          # (default: resolved lane
                                          # count on *_tpu pipelines)
    tpu_fleet_chaos = false               # enable POST /fault (chaos
                                          # harness only — never in
                                          # production)

Rank and fleet size default from the ``jax.distributed`` spec
(``input.tpu_process_id`` / ``tpu_num_processes``) so a multi-host JAX
config grows fleet membership with three added lines; fleet-only
deployments (scalar pipelines, heterogeneous hosts) set
``tpu_fleet_rank`` / ``tpu_fleet_hosts`` instead.

Failure semantics: heartbeats ride the ticker thread (supervised),
every send is a short-lived HTTP POST under a hard socket timeout with
a bounded full-jitter retry (``utils/retry.py``; retries counted as
``fleet_hb_retries``) so one dropped packet cannot start the suspect
clock, and a dead peer costs a few timed-out connects per interval —
the decode hot path never waits on the fleet.  A host that discovers
its own eviction (a reply's view of it says draining/departed at its
incarnation) backs off through ``Supervisor.fleet_policy`` and rejoins
with a fresh incarnation (counted as ``fleet_rejoins``).

Self-healing (the PR 14 tentpole — every single-host failure repairs
without an operator):

- **Durable roster** (``tpu_fleet_roster_path``, ``roster.py``): the
  gossiped roster journals to disk on change (crash-safe atomic
  rewrite) and loads at boot as bootstrap candidates — a joiner whose
  configured coordinator is dead walks the persisted peers instead
  (``roster_restore`` journal event); a corrupt/partial journal is
  counted and ignored (clean re-rendezvous).
- **Rendezvous failover**: every host deterministically elects the
  lowest active rank as the agreed rendezvous
  (``membership.rendezvous()``; tie-breaks are the incarnation rules).
  The election is announced in ``/healthz``'s ``fleet.rendezvous``
  field so ``fleetctl`` and LB stanzas can follow it; a change lands
  as a ``rendezvous_failover`` journal event.
- **Live rebalancing**: hosts advertise capacity weights on their
  heartbeats; ``membership.shares()`` turns membership into per-host
  traffic shares (joining/active hosts only — the healthz-200 set), so
  a joiner starts absorbing its share and a draining/evicted host's
  share redistributes across survivors through the existing LB 200/503
  contract.  Share changes land as ``fleet_rebalance`` journal events.

Fleet observability plane (PR 15 tentpole): every host serves ``GET
/fleetz`` — the fleet-level document answering "is the *fleet* meeting
its targets, and which tenant/route/host is burning the budget":

- the serving host scrapes every non-departed peer's ``/healthz`` over
  the same short-lived HTTP transport the heartbeats use (bounded
  timeout, parallel, never on the decode path) and caches the last
  good snapshot per rank — a host that stops answering is served from
  cache and **flagged stale with its age, never silently dropped**;
- metrics merge across hosts: counters and cumulative stage-seconds
  sum; histograms merge honestly (counts/sums summed, quantiles
  recomputed from the pooled per-host sample rings that HEALTH_SCHEMA
  4 snapshots carry) — never an average of per-host p99s;
- the degradation-event union is tagged by rank (obs/events.py
  ``set_rank``) and re-sorted by timestamp;
- fleet-level SLO status folds each host's ``slo`` section per
  objective name: burning anywhere = burning fleet-wide, burn rates
  are the worst observed, stale contributors marked.

Every host can serve ``/fleetz`` from its own view; consumers
(``fleetctl top``) follow ``fleet.rendezvous`` so the fleet has ONE
agreed answer that survives coordinator death via the existing
failover election.

Fault sites (``utils/faultinject.py``): ``peer_partition`` drops
heartbeat exchanges in BOTH directions at the armed host — outbound
sends are suppressed, inbound POSTs 503, and any stray replies are
discarded — so a single-host arming is a true network cut
(``FLOWGGER_PARTITION_PEER`` narrows it to one peer);  ``host_kill``
SIGKILLs this process from the ticker,
``coordinator_kill`` does the same but only while this host *is* the
agreed rendezvous, and ``roster_corrupt`` truncates the next roster
journal write — all deterministic, for the acceptance tests and
``tools/chaos.py``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

from ..config import Config, ConfigError
from ..utils import faultinject
from ..utils.metrics import registry as _global_registry
from .health import HealthService, PartitionDrop
from .membership import (
    ACTIVE,
    DEPARTED,
    DRAINING,
    JOINING,
    Membership,
)

DEFAULT_HEARTBEAT_MS = 500
DEFAULT_SUSPECT_MS = 2_000
DEFAULT_EVICT_MS = 5_000
DEFAULT_DEPART_MS = 2_000
DEFAULT_REJOIN_BACKOFF_MS = 1_000

PARTITION_PEER_ENV = "FLOWGGER_PARTITION_PEER"

# health-document schema version; tests/resources/healthz_schema.json
# is the golden copy a CI test validates real payloads against.
# v2: added the observability sections — ``events`` (degradation
# journal ring + per-reason counts, obs/events.py) and ``trace``
# (flight-recorder mode/ring stats, obs/trace.py)
# v3: self-healing fleet — ``fleet.rendezvous`` (the elected rendezvous
# every consumer should follow), ``fleet.shares`` (per-rank traffic
# shares), ``host.capacity``, and per-peer ``capacity``/``share``
# v4: observability plane — the ``slo`` section (objective burn state +
# sentinel status, obs/slo.py), histogram snapshots carry
# ``sample_count`` + bounded ``samples`` (the /fleetz quantile-merge
# raw material), and journal/trace records carry the fleet ``rank``
HEALTH_SCHEMA = 4

# /fleetz fleet-observability document schema (tests/resources/
# fleetz_schema.json is the golden copy)
# v2: added the "control" section (control plane: autoscale signal +
# applied factors; {"enabled": false, ...} when no [control] table)
FLEETZ_SCHEMA = 2

# bounded heartbeat-POST retry (utils/retry.py, full jitter): one
# dropped packet must not start a peer's suspect clock — but the whole
# attempt train must fit the ORIGINAL single-attempt budget (the send
# timeout is divided across attempts), because the ticker sends
# serially: a black-holed peer whose train ran 3x the old cost would
# delay this host's heartbeats to its HEALTHY peers past their suspect
# window, manufacturing exactly the false suspicion retries exist to
# prevent
HB_SEND_ATTEMPTS = 3
HB_RETRY_INIT_MS = 20
HB_RETRY_MAX_MS = 60


@dataclass
class FleetSpec:
    rank: int
    hosts: int
    bind: str
    port: int
    advertise: Optional[str]
    coordinator: Optional[str]
    heartbeat_ms: int
    suspect_ms: int
    evict_ms: int
    depart_ms: int
    rejoin_backoff_ms: int
    roster_path: Optional[str] = None
    capacity: Optional[float] = None  # None = caller default (lanes)
    chaos: bool = False


def _check_mesh_conflict(config: Config) -> None:
    """Config-time lanes-vs-mesh resolution for fleet hosts: PR 5 lane
    dispatch supersedes the sharded decode mesh whenever more than one
    lane resolves, so a fleet config pinning both is an error *now*,
    not a silently-unused mesh at the first batch."""
    mesh_mode = config.lookup_str(
        "input.tpu_mesh", "input.tpu_mesh must be a string", "auto")
    lanes = config.lookup_int(
        "input.tpu_lanes",
        "input.tpu_lanes must be an integer (device lanes)", None)
    if mesh_mode == "on" and lanes is not None and lanes > 1:
        raise ConfigError(
            'input.tpu_lanes > 1 and input.tpu_mesh = "on" are mutually '
            "exclusive on a fleet host (lanes give each chip its own "
            "batches; the mesh shards one batch across chips) — drop "
            "one of the two keys")


def fleet_spec(config: Config) -> Optional[FleetSpec]:
    """Parse the ``input.tpu_fleet_*`` family; None when the config
    doesn't ask for fleet membership.  Validation raises ``ConfigError``
    with the key name, matching the reference's config error style."""
    enabled = config.lookup_bool(
        "input.tpu_fleet", "input.tpu_fleet must be a boolean", False)
    if not enabled:
        return None
    _check_mesh_conflict(config)
    # rank/size: fleet keys win, jax.distributed spec is the default
    dist_rank = config.lookup_int(
        "input.tpu_process_id", "input.tpu_process_id must be an integer")
    dist_hosts = config.lookup_int(
        "input.tpu_num_processes",
        "input.tpu_num_processes must be an integer")
    rank = config.lookup_int(
        "input.tpu_fleet_rank", "input.tpu_fleet_rank must be an integer",
        dist_rank if dist_rank is not None else 0)
    hosts = config.lookup_int(
        "input.tpu_fleet_hosts", "input.tpu_fleet_hosts must be an integer",
        dist_hosts if dist_hosts is not None else 1)
    if hosts < 1:
        raise ConfigError("input.tpu_fleet_hosts must be >= 1")
    if not 0 <= rank < hosts:
        raise ConfigError(
            "input.tpu_fleet_rank must be in [0, tpu_fleet_hosts)")
    bind = config.lookup_str(
        "input.tpu_fleet_bind", "input.tpu_fleet_bind must be a string",
        "127.0.0.1")
    port = config.lookup_int(
        "input.tpu_fleet_port",
        "input.tpu_fleet_port must be an integer (0 = ephemeral)", 0)
    if not 0 <= port < 65536:
        raise ConfigError("input.tpu_fleet_port must be in [0, 65536)")
    advertise = config.lookup_str(
        "input.tpu_fleet_advertise",
        "input.tpu_fleet_advertise must be a host:port string")
    if advertise is None and hosts > 1 and bind in ("0.0.0.0", "::", ""):
        # the advertise default is bind:port — a wildcard bind would
        # gossip "0.0.0.0:port", which every peer resolves to ITSELF
        # and the healthy host gets evicted fleet-wide.  Catch it at
        # config time, not as a mystery eviction in production
        raise ConfigError(
            "input.tpu_fleet_advertise is required when "
            "tpu_fleet_bind is a wildcard address (peers cannot dial "
            f"\"{bind}\")")
    coordinator = config.lookup_str(
        "input.tpu_fleet_coordinator",
        "input.tpu_fleet_coordinator must be a host:port string")
    roster_path = config.lookup_str(
        "input.tpu_fleet_roster_path",
        "input.tpu_fleet_roster_path must be a string (journal file)")
    if coordinator is None and rank != 0 and hosts > 1 \
            and roster_path is None:
        raise ConfigError(
            "input.tpu_fleet_coordinator is required on ranks > 0 "
            "(rank 0's health endpoint is the rendezvous address) — "
            "unless input.tpu_fleet_roster_path names a durable roster "
            "journal to bootstrap from instead")
    heartbeat_ms = config.lookup_int(
        "input.tpu_fleet_heartbeat_ms",
        "input.tpu_fleet_heartbeat_ms must be an integer (ms)",
        DEFAULT_HEARTBEAT_MS)
    suspect_ms = config.lookup_int(
        "input.tpu_fleet_suspect_ms",
        "input.tpu_fleet_suspect_ms must be an integer (ms)",
        DEFAULT_SUSPECT_MS)
    evict_ms = config.lookup_int(
        "input.tpu_fleet_evict_ms",
        "input.tpu_fleet_evict_ms must be an integer (ms)",
        DEFAULT_EVICT_MS)
    depart_ms = config.lookup_int(
        "input.tpu_fleet_depart_ms",
        "input.tpu_fleet_depart_ms must be an integer (ms)",
        DEFAULT_DEPART_MS)
    rejoin_ms = config.lookup_int(
        "input.tpu_fleet_rejoin_backoff_ms",
        "input.tpu_fleet_rejoin_backoff_ms must be an integer (ms)",
        DEFAULT_REJOIN_BACKOFF_MS)
    if heartbeat_ms < 1:
        raise ConfigError("input.tpu_fleet_heartbeat_ms must be >= 1")
    if not heartbeat_ms < suspect_ms < evict_ms:
        raise ConfigError(
            "fleet deadlines must satisfy tpu_fleet_heartbeat_ms < "
            "tpu_fleet_suspect_ms < tpu_fleet_evict_ms")
    capacity = config.lookup_float(
        "input.tpu_fleet_capacity",
        "input.tpu_fleet_capacity must be a number (traffic weight)")
    if capacity is not None and capacity <= 0:
        raise ConfigError("input.tpu_fleet_capacity must be > 0")
    chaos = config.lookup_bool(
        "input.tpu_fleet_chaos",
        "input.tpu_fleet_chaos must be a boolean", False)
    return FleetSpec(rank=rank, hosts=hosts, bind=bind, port=port,
                     advertise=advertise, coordinator=coordinator,
                     heartbeat_ms=heartbeat_ms, suspect_ms=suspect_ms,
                     evict_ms=evict_ms, depart_ms=depart_ms,
                     rejoin_backoff_ms=rejoin_ms, roster_path=roster_path,
                     capacity=capacity, chaos=chaos)


class _Undeliverable(Exception):
    """One POST attempt failed at the transport/parse layer (connect
    refused, timeout, garbage body) — the retryable class.  A non-200
    reply is NOT this: the listener is alive and said no (draining /
    injected partition); retrying a refusal cannot change it and would
    perturb the deterministic fault-site counting."""


def _http_post_once(addr: str, path: str, body: bytes,
                    timeout: float) -> Optional[dict]:
    """One short-lived POST.  Raises ``_Undeliverable`` on transport
    failure; returns None on a delivered-but-refused (non-200) reply.
    ``addr`` is remote input (gossip can relay anything), so even
    parsing it stays inside the failure path: a malformed peer entry
    costs one counted miss, never the ticker thread."""
    import http.client

    conn = None
    try:
        host, _, port = addr.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            # a 503 (partitioned / draining listener) is a failed
            # delivery too — uncounted it would make a partition with
            # live listeners look like a clean network
            return None
        out = json.loads(data)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError) as e:
        raise _Undeliverable(str(e)) from e
    finally:
        if conn is not None:
            conn.close()


def _http_post_json(addr: str, path: str, doc: dict, timeout: float,
                    registry=_global_registry) -> Optional[dict]:
    """POST with a bounded full-jitter retry (``utils/retry.py``) over
    transport failures only: one dropped packet must not start the
    suspect clock.  ``timeout`` is the whole train's transport budget —
    it is divided across ``HB_SEND_ATTEMPTS`` so a black-holed peer
    costs roughly what the pre-retry single attempt cost (the ticker
    sends serially; a 3x train would stall heartbeats to healthy peers
    into THEIR suspect windows).  Retries count as ``fleet_hb_retries``;
    only the exhausted train counts as one ``fleet_hb_send_errors`` — a
    fleet send failing is normal life under partition/churn, counted
    not logged."""
    from ..utils.retry import RetryPolicy

    body = json.dumps(doc).encode()
    # 150ms floor per attempt: below it a loaded host's loopback HTTP
    # round trip starts missing the deadline outright and the retry
    # train fails forever (observed at 50ms on a busy 2-core box).
    # The floor only loosens the train-fits-old-budget bound for
    # sub-500ms heartbeat configs, which are loopback test fleets —
    # where a dead peer answers with an instant RST, never a timeout
    per_try = max(0.15, timeout / HB_SEND_ATTEMPTS)
    policy = RetryPolicy(init_ms=HB_RETRY_INIT_MS,
                         max_ms=HB_RETRY_MAX_MS,
                         mode="exponential",
                         max_attempts=HB_SEND_ATTEMPTS - 1)
    while True:
        try:
            out = _http_post_once(addr, path, body, per_try)
            if out is None:
                # delivered but refused (503 partition / drain): one
                # counted failure, no retry — the listener said no
                registry.inc("fleet_hb_send_errors")
            return out
        except _Undeliverable:
            if policy.backoff() is None:
                registry.inc("fleet_hb_send_errors")
                return None
            registry.inc("fleet_hb_retries")


def _http_get_json(addr: str, path: str, timeout: float) -> Optional[dict]:
    """One short-lived GET; None on transport/parse failure.  A non-200
    status with a JSON body still counts (a draining host's /healthz is
    a 503 carrying the full document — exactly what the fleet merge
    wants to keep aggregating)."""
    import http.client

    conn = None
    try:
        host, _, port = addr.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
        conn.request("GET", path)
        resp = conn.getresponse()
        out = json.loads(resp.read())
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None
    finally:
        if conn is not None:
            conn.close()


# -- /fleetz merge (pure functions; unit-tested directly) ------------------

# per-histogram cap on POOLED merge samples: local senders bound their
# rings to 128 (Histogram.samples), but peer snapshots are remote input
# and the merge must enforce its own bound, not trust theirs
_MERGE_SAMPLES_MAX = 2048


def merge_metric_snapshots(snaps) -> Dict[str, object]:
    """Merge per-host registry snapshots into one fleet view: counters
    and cumulative stage-seconds sum; histograms sum counts/sums and
    recompute quantiles from the POOLED per-host sample rings (an
    average of per-host p99s is not a p99); gauges are point-in-time
    per-host truth and stay out of the merged dict (read them under
    ``hosts[].metrics``)."""
    from ..utils.metrics import classify_metric, window_quantiles

    merged: Dict[str, object] = {}
    pools: Dict[str, dict] = {}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for key, val in snap.items():
            if key == "ts":
                continue
            if isinstance(val, dict) and "count" in val:
                pool = pools.setdefault(key, {
                    "count": 0, "sum": 0.0, "samples": [],
                    "min": None, "max": None})
                pool["count"] += int(val.get("count", 0))
                pool["sum"] += float(val.get("sum", 0.0))
                room = _MERGE_SAMPLES_MAX - len(pool["samples"])
                if room > 0:
                    pool["samples"].extend(
                        (val.get("samples") or ())[:room])
                for bound, pick in (("min", min), ("max", max)):
                    v = val.get(bound)
                    if isinstance(v, (int, float)):
                        pool[bound] = v if pool[bound] is None \
                            else pick(pool[bound], v)
                continue
            if not isinstance(val, (int, float)) or isinstance(val, bool):
                continue
            if classify_metric(key) in ("counter", "seconds"):
                merged[key] = merged.get(key, 0) + val
    for key, pool in pools.items():
        samples = sorted(s for s in pool["samples"]
                         if isinstance(s, (int, float)))
        entry: Dict[str, object] = {
            "count": pool["count"], "sum": round(pool["sum"], 6),
            "sample_count": len(samples)}
        if samples:
            entry.update(window_quantiles(samples))
            # per-host min/max cover samples the bounded rings dropped
            entry["min"] = pool["min"] if pool["min"] is not None \
                else samples[0]
            entry["max"] = pool["max"] if pool["max"] is not None \
                else samples[-1]
        merged[key] = entry
    return merged


def merge_event_sections(tagged, ring: int = 256) -> Dict[str, object]:
    """``[(rank, events_section), ...]`` → one union section: totals
    and per-reason counts summed, rings merged by timestamp (each
    entry tagged with its source rank — schema-4 hosts stamp it at
    emit; older snapshots are tagged here)."""
    total = 0
    counts: Dict[str, int] = {}
    union = []
    for rank, section in tagged:
        if not isinstance(section, dict):
            continue
        total += int(section.get("total", 0))
        for reason, n in (section.get("counts") or {}).items():
            counts[reason] = counts.get(reason, 0) + int(n)
        for event in section.get("ring") or ():
            if isinstance(event, dict):
                event = dict(event)
                event.setdefault("rank", rank)
                union.append(event)
    union.sort(key=lambda e: e.get("ts", 0))
    return {"total": total, "counts": counts, "ring": union[-ring:]}


def merge_slo_sections(tagged) -> Dict[str, object]:
    """``[(rank, stale, slo_section), ...]`` → fleet-level SLO status:
    per objective name, burning anywhere is burning fleet-wide, the
    reported burn rates are the worst observed, and every contributing
    host is listed (stale contributors marked — a dead host's last
    judgement stays on the board rather than reading as green)."""
    objectives: Dict[str, dict] = {}
    sentinel_regressions = 0
    sentinel_routes: Dict[str, dict] = {}
    for rank, stale, section in tagged:
        if not isinstance(section, dict):
            continue
        for obj in section.get("objectives") or ():
            if not isinstance(obj, dict) or "name" not in obj:
                continue
            entry = objectives.setdefault(obj["name"], {
                "name": obj["name"], "kind": obj.get("kind", ""),
                "burning": False, "fast_burn": 0.0, "slow_burn": 0.0,
                "budget_remaining": 1.0, "hosts": []})
            entry["burning"] = entry["burning"] or bool(obj.get("burning"))
            for key, pick in (("fast_burn", max), ("slow_burn", max),
                              ("budget_remaining", min)):
                v = obj.get(key)
                if isinstance(v, (int, float)):
                    entry[key] = pick(entry[key], v)
            entry["hosts"].append({
                "rank": rank, "burning": bool(obj.get("burning")),
                "fast_burn": obj.get("fast_burn", 0.0), "stale": stale})
        sent = section.get("sentinel")
        if isinstance(sent, dict):
            sentinel_regressions += int(sent.get("regressions", 0))
            for route, st in (sent.get("routes") or {}).items():
                if isinstance(st, dict):
                    prev = sentinel_routes.get(route)
                    if prev is None or (st.get("alerted")
                                        and not prev.get("alerted")):
                        sentinel_routes[route] = dict(st, rank=rank)
    objs = sorted(objectives.values(), key=lambda o: o["name"])
    return {
        "configured": len(objs),
        "burning": sum(1 for o in objs if o["burning"]),
        "objectives": objs,
        "sentinel": {"regressions": sentinel_regressions,
                     "routes": sentinel_routes},
    }


class Fleet:
    """One host's fleet agent: health service + heartbeat ticker +
    membership, wired into the pipeline's drain lifecycle."""

    def __init__(self, spec: FleetSpec, supervisor=None, registry=None,
                 on_drain=None):
        self.spec = spec
        self.supervisor = supervisor
        self._registry = registry if registry is not None else _global_registry
        self._on_drain_cb = on_drain
        self._stop = threading.Event()
        self._ticker: Optional[threading.Thread] = None
        self._draining = False  # voluntary drain: disables rejoin
        self._lock = threading.Lock()
        self.membership: Optional[Membership] = None
        self.service: Optional[HealthService] = None
        self._rejoin_policy = None  # lazily built; persists across rejoins
        self._started = time.monotonic()
        self._default_capacity = 1.0  # pipeline override (lane count)
        self._roster_store = None
        if spec.roster_path:
            from .roster import RosterStore

            self._roster_store = RosterStore(spec.roster_path,
                                             registry=self._registry)
        # fleet-watch state: last announced rendezvous / shares, so the
        # ticker emits one typed journal event per actual change.  The
        # dedicated lock totally orders derive->emit->journal across
        # the ticker and heartbeat threads: without it a ticker that
        # derived BEFORE an inbound join could journal its stale
        # roster AFTER the join's save (last-writer-wins rollback) and
        # the seen-state swap could emit phantom A->B/B->A event pairs
        self._watch_lock = threading.Lock()
        self._rendezvous_seen: Optional[tuple] = None
        self._shares_seen: Optional[Dict[int, float]] = None
        # /fleetz peer-snapshot cache: rank -> (healthz doc, monotonic
        # fetch time).  A peer that stops answering is served from here
        # with a stale flag — its last snapshot is evidence, not noise
        self._fleetz_lock = threading.Lock()
        self._fleetz_cache: Dict[int, tuple] = {}
        # control-plane hook (pipeline wires ControlPlane.fleetz_section
        # here); None = the schema-stable disabled section below
        self._control_source = None

    @classmethod
    def from_config(cls, config: Config, supervisor=None, registry=None,
                    on_drain=None) -> Optional["Fleet"]:
        spec = fleet_spec(config)
        if spec is None:
            return None
        return cls(spec, supervisor=supervisor, registry=registry,
                   on_drain=on_drain)

    # -- lifecycle ---------------------------------------------------------
    def set_control_source(self, fn) -> None:
        """Pipeline hook: a zero-arg callable returning the control
        plane's ``/fleetz`` section (ControlPlane.fleetz_section)."""
        self._control_source = fn

    def _control_section(self) -> Dict[str, object]:
        if self._control_source is not None:
            try:
                return self._control_source()
            except Exception:  # noqa: BLE001 - a dying controller must not take /fleetz down with it
                pass
        return {"enabled": False, "desired_hosts": 0,
                "capacity_factor": 1.0, "tenants": {}}

    def set_default_capacity(self, capacity: float) -> None:
        """Pipeline hook, before ``start()``: the advertised capacity
        weight when ``input.tpu_fleet_capacity`` is unset (a *_tpu
        pipeline passes its resolved lane count, so a 4-chip host
        advertises 4x a 1-chip host's share by default)."""
        if capacity > 0:
            self._default_capacity = float(capacity)

    @property
    def capacity(self) -> float:
        cap = self.spec.capacity
        return float(cap) if cap is not None else self._default_capacity

    def start(self) -> None:
        spec = self.spec
        self.service = HealthService(
            spec.bind, spec.port, payload=self.health_payload,
            healthy=self._lb_healthy, on_heartbeat=self.on_heartbeat,
            on_drain=self._drain_requested,
            on_fault=self._fault_requested if spec.chaos else None,
            on_fleetz=self.fleetz_payload)
        # cross-host correlation: stamp every journal event and batch
        # trace with this host's rank, so the /fleetz event union and
        # `trace_dump --fleet` process lanes stay attributable
        from ..obs.events import journal as _journal
        from ..obs.trace import tracer as _tracer

        _journal.set_rank(spec.rank)
        _tracer.set_rank(spec.rank)
        advertise = spec.advertise or \
            f"{spec.bind}:{self.service.port}"
        # durable-roster bootstrap: load the journal BEFORE membership
        # exists — a journaled entry for our own rank means this is a
        # restart within the same lineage, so start one incarnation
        # past the journaled life and peers accept the comeback without
        # an eviction-discovery round trip
        journaled = self._roster_store.load() if self._roster_store \
            else None
        incarnation = 0
        if journaled:
            for entry in journaled:
                if entry["rank"] == spec.rank:
                    incarnation = entry["incarnation"] + 1
        self.membership = Membership(
            rank=spec.rank, addr=advertise, incarnation=incarnation,
            suspect_ms=spec.suspect_ms,
            evict_ms=spec.evict_ms, depart_ms=spec.depart_ms,
            capacity=self.capacity, registry=self._registry)
        if journaled:
            restored = 0
            for entry in journaled:
                if entry["rank"] == spec.rank:
                    continue
                # journaled states are stale opinion, and bootstrap is
                # the one consumer that must DIAL, not trust: enter
                # every restored peer as joining (dialable) even when
                # the journal says draining/departed — the last host
                # to drain journals everyone departed, and honoring
                # that would boot a coordinator-less restart into a
                # silent singleton fleet.  A truly dead candidate
                # costs refused connects until the evict window ages
                # it out (one spurious fleet_eviction — the price of
                # checking)
                self.membership.note_roster(
                    entry["rank"], entry["addr"], JOINING,
                    entry["incarnation"], capacity=entry["capacity"])
                restored += 1
            if restored:
                from ..obs import events as _events

                _events.emit(
                    "fleet/roster", "roster_restore",
                    detail=f"{restored} bootstrap candidates from "
                           f"{spec.roster_path}",
                    cost=float(restored), cost_unit="peers",
                    msg=f"fleet-roster: restored {restored} bootstrap "
                        f"candidates from {spec.roster_path} (walked "
                        "alongside the configured coordinator)")
        if spec.coordinator is None and spec.hosts > 1 and not journaled \
                and spec.rank != 0:
            # roster_path waived the coordinator requirement but there
            # is no usable journal either: this host can only wait to
            # be dialed.  Say so loudly — a silent singleton answering
            # healthz 200 looks exactly like a healthy fleet of one.
            # (Rank 0 is exempt: it IS the conventional rendezvous, and
            # being dialed by joiners is its normal life, not a
            # misconfiguration.)
            print("fleet: WARNING — no coordinator configured and no "
                  f"usable roster journal at {spec.roster_path}; this "
                  "host has no peer to dial and will idle until a peer "
                  "dials it", file=sys.stderr)
        self.service.start(self.supervisor)
        self.membership.activate()
        print(f"fleet: rank {spec.rank}/{spec.hosts} active "
              f"(capacity {self.capacity:g}), "
              f"health endpoint http://{self.service.addr}/healthz",
              file=sys.stderr)
        if self.supervisor is not None:
            self._ticker = self.supervisor.spawn(
                self._tick_loop, "fleet-ticker", exhausted="return")
        else:
            self._ticker = threading.Thread(
                target=self._tick_loop, daemon=True, name="fleet-ticker")
            self._ticker.start()

    def enter_draining(self, sync_wave: bool = True) -> None:
        """Drain-on-departure, phase 1 (SIGTERM / fleetctl / EOF): the
        host stops being routable (healthz flips to 503) and announces
        ``draining`` to every peer so they absorb new traffic while
        this host's ``Pipeline._drain`` fence-all/straggler machinery
        flushes in-flight batches byte-identically.

        ``sync_wave=False`` fires the announce wave on its own thread —
        the ``POST /drain`` handler uses it so its HTTP reply never
        waits out one socket timeout per unreachable peer."""
        if self.membership is None:
            return
        with self._lock:
            if self._draining:
                return
            self._draining = True
        self.membership.mark_draining()
        # derive + journal NOW, not a tick later: the local share just
        # redistributed (fleet_rebalance) and a restarting host should
        # find its drain on disk
        self._fleet_watch()
        if sync_wave:
            self._send_heartbeats()  # don't wait a tick: announce now
        else:
            # flowcheck: disable=FC10 -- drain-announce wave is deliberately fire-and-forget: it may block one socket timeout per dead peer and must never hold the POST /drain reply (or drain itself) hostage; shutdown() departs loudly anyway
            threading.Thread(target=self._send_heartbeats, daemon=True,
                             name="fleet-drain-wave").start()

    def shutdown(self) -> None:
        """Drain-on-departure, phase 2: in-flight batches are flushed,
        announce ``departed`` and stop the fleet threads."""
        if self.membership is not None:
            with self._lock:
                self._draining = True
            if self.membership.local.state != DEPARTED:
                self.membership.mark_departed()
                self._fleet_watch()  # journal the departure durably
                self._send_heartbeats()
        self._stop.set()
        if self._ticker is not None \
                and self._ticker is not threading.current_thread():
            # bound the wait: the ticker wakes from its heartbeat sleep
            # on _stop and exits; a wedged send still can't hold
            # shutdown hostage past the timeout
            self._ticker.join(timeout=2)
        if self.service is not None:
            self.service.stop()

    def wait_active(self, hosts: int, timeout: float = 60.0) -> bool:
        """Block until ``hosts`` members are active (tests/bench
        rendezvous barrier; never used on the decode path)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.membership is not None and \
                    self.membership.counts()[ACTIVE] >= hosts:
                return True
            time.sleep(0.02)
        return False

    # -- ticker ------------------------------------------------------------
    def _tick_loop(self) -> None:
        interval = self.spec.heartbeat_ms / 1000.0
        while not self._stop.wait(interval):
            if faultinject.enabled():
                if faultinject.fire("host_kill"):
                    # deterministic hard host loss for the acceptance
                    # tests: SIGKILL, no drain, no goodbye — peers must
                    # discover it through the missed-heartbeat ladder
                    self._sigkill_self("host_kill")
                rdv = self.rendezvous()
                if rdv is not None and rdv.get("rank") == self.spec.rank \
                        and faultinject.fire("coordinator_kill"):
                    # like host_kill, but self-selecting: only the host
                    # that currently IS the agreed rendezvous checks the
                    # site, so `once:N` kills the coordinator on its Nth
                    # tick as rendezvous — the failover drill's trigger
                    self._sigkill_self("coordinator_kill")
            self._send_heartbeats()
            if self.membership is not None:
                self.membership.tick()
            self._fleet_watch()

    def _sigkill_self(self, site: str) -> None:
        import signal

        print(f"faultinject: {site} firing — SIGKILL",
              file=sys.stderr, flush=True)
        os.kill(os.getpid(), signal.SIGKILL)

    def _fleet_watch(self) -> None:
        """Post-tick fleet derivations: journal the roster when its
        durable part changed, and emit one typed event per rendezvous
        change (``rendezvous_failover``) / share redistribution
        (``fleet_rebalance``) — the why-did-traffic-move signal at
        fleet granularity."""
        m = self.membership
        if m is None:
            return
        from ..obs import events as _events

        with self._watch_lock:
            # derive INSIDE the lock: a snapshot taken outside could be
            # older than the save a concurrent watcher already wrote
            rdv = m.rendezvous()
            shares = m.shares()
            prev_rdv, self._rendezvous_seen = self._rendezvous_seen, rdv
            prev_shares, self._shares_seen = self._shares_seen, shares
            if self._roster_store is not None:
                rdv_doc = None if rdv is None else \
                    {"rank": rdv[0], "addr": rdv[1]}
                self._roster_store.maybe_save(m.roster(), self.spec.rank,
                                              rdv_doc)
        # emit AFTER release (the WFQ shed-event precedent): the event
        # sink write is disk I/O that must not serialize the heartbeat
        # handlers behind the watch lock.  The lock still totally
        # orders the derivations and journal saves; events from two
        # watchers may interleave in the ring, each built from its own
        # consistent (prev, new) snapshot
        if rdv != prev_rdv and prev_rdv is not None and rdv is not None:
            _events.emit(
                "fleet/federation", "rendezvous_failover",
                detail=f"rank{prev_rdv[0]}@{prev_rdv[1]} -> "
                       f"rank{rdv[0]}@{rdv[1]}",
                msg=f"fleet: rendezvous moved to rank {rdv[0]} "
                    f"({rdv[1]}) — was rank {prev_rdv[0]}")
        if prev_shares is not None and shares and shares != prev_shares:
            # (an EMPTY share map means no routable host remains in
            # this view — there is nobody to rebalance TO, and the
            # state gauges already tell that story)
            moved = sum(abs(shares.get(r, 0.0) - prev_shares.get(r, 0.0))
                        for r in set(shares) | set(prev_shares)) / 2.0
            _events.emit(
                "fleet/federation", "fleet_rebalance",
                detail="shares " + json.dumps(
                    {str(r): shares[r] for r in sorted(shares)}),
                cost=round(moved, 4), cost_unit="share_moved",
                msg=f"fleet: traffic shares rebalanced "
                    f"({moved:.0%} of traffic moved): "
                    + ", ".join(f"rank{r}={shares[r]:.0%}"
                                for r in sorted(shares)))

    def _heartbeat_doc(self) -> dict:
        local = self.membership.local
        return {"op": "hb", "rank": local.rank, "addr": local.addr,
                "state": local.state, "incarnation": local.incarnation,
                "capacity": local.capacity}

    def _send_heartbeats(self) -> None:
        if self.membership is None:
            return
        local = self.membership.local
        targets: Dict[str, Optional[int]] = {}
        if self.spec.coordinator and self.spec.coordinator != local.addr:
            targets[self.spec.coordinator] = None
        for rank, addr in self.membership.heartbeat_targets():
            if addr != local.addr:
                targets[addr] = rank
        timeout = max(0.05, min(1.0, self.spec.heartbeat_ms / 1000.0))
        doc = self._heartbeat_doc()
        named = self._partition_peer() if faultinject.enabled() else None
        for addr, rank in targets.items():
            if faultinject.enabled() \
                    and (named is None or (rank is not None
                                           and named == rank)) \
                    and faultinject.fire("peer_partition"):
                # a partitioned host must stop DELIVERING liveness too:
                # without this send-side drop the armed host keeps
                # proving itself alive to unarmed peers (multi-process
                # chaos) and their suspect clock never starts.  Counted
                # like the real thing — a black-holed send times out
                self._registry.inc("fleet_hb_send_errors")
                continue
            reply = _http_post_json(addr, "/hb", doc, timeout,
                                    registry=self._registry)
            if reply is None:
                continue
            self._absorb_reply(reply)

    def _absorb_reply(self, reply: dict) -> None:
        """A heartbeat reply is liveness proof for the responder, a
        roster to gossip-merge, and possibly the news of our own
        eviction."""
        sender = reply.get("from")
        if isinstance(sender, dict):
            try:
                s_rank = int(sender["rank"])
                if faultinject.enabled():
                    # belt for the send-side drop in _send_heartbeats:
                    # a reply that still arrives while the site is
                    # armed (race with arming) is liveness proof and
                    # must drop with the partition too
                    named = self._partition_peer()
                    if (named is None or named == s_rank) \
                            and faultinject.fire("peer_partition"):
                        return
                self.membership.note_heartbeat(
                    s_rank, str(sender["addr"]),
                    str(sender.get("state", ACTIVE)),
                    int(sender.get("incarnation", 0)),
                    capacity=sender.get("capacity"))
            except (KeyError, TypeError, ValueError):
                self._registry.inc("fleet_hb_send_errors")
        for entry in reply.get("roster", []):
            if not isinstance(entry, dict):
                continue
            try:
                self.membership.note_roster(
                    int(entry["rank"]), str(entry["addr"]),
                    str(entry["state"]), int(entry.get("incarnation", 0)),
                    capacity=entry.get("capacity"))
            except (KeyError, TypeError, ValueError):
                self._registry.inc("fleet_hb_send_errors")
        view = reply.get("view")
        if isinstance(view, dict):
            self._maybe_rejoin(view)

    def _maybe_rejoin(self, view: dict) -> None:
        """A peer's view of *us* says draining/departed at our own (or
        a higher) incarnation: the fleet evicted us.  Back off through
        the supervisor's fleet ladder, then rejoin with a fresh
        incarnation — the fleet-granularity analog of the PR 2 thread
        restart."""
        local = self.membership.local
        with self._lock:
            voluntary = self._draining
        if voluntary or view.get("state") not in (DRAINING, DEPARTED):
            return
        try:
            seen_inc = int(view.get("incarnation", 0))
        except (TypeError, ValueError):
            return
        if seen_inc < local.incarnation:
            return  # stale view of a life we already left behind
        if self.supervisor is not None:
            if self._rejoin_policy is None:
                self._rejoin_policy = self.supervisor.fleet_policy(
                    init_ms=self.spec.rejoin_backoff_ms)
            if self._rejoin_policy.backoff() is None:
                print("fleet: rejoin budget exhausted, staying departed",
                      file=sys.stderr)
                self._stop.set()
                return
        else:
            self._registry.inc("fleet_rejoins")
            time.sleep(self.spec.rejoin_backoff_ms / 1000.0)
        inc = self.membership.local_rejoin()
        print(f"fleet: evicted by peers (view: {view.get('state')}); "
              f"rejoining as incarnation {inc}", file=sys.stderr)
        self._send_heartbeats()

    # -- inbound (health service callbacks) --------------------------------
    def _partition_peer(self) -> Optional[int]:
        raw = os.environ.get(PARTITION_PEER_ENV)
        if raw is None or not raw.strip().lstrip("-").isdigit():
            return None
        return int(raw)

    def on_heartbeat(self, msg: dict) -> dict:
        """Inbound ``POST /hb``: tie-break + absorb, reply with our
        roster, our identity, and our view of the sender."""
        try:
            rank = int(msg["rank"])
            addr = str(msg["addr"])
            state = str(msg.get("state", ACTIVE))
            inc = int(msg.get("incarnation", 0))
        except (KeyError, TypeError, ValueError) as e:
            raise PartitionDrop() from e  # malformed == undeliverable
        if faultinject.enabled():
            named = self._partition_peer()
            if (named is None or named == rank) \
                    and faultinject.fire("peer_partition"):
                raise PartitionDrop()
        accepted = self.membership.note_heartbeat(
            rank, addr, state, inc, capacity=msg.get("capacity"))
        if accepted:
            # derive + journal NOW, on the thread that learned it — not
            # a tick later.  A host SIGKILLed between accepting a
            # joiner and its next ticker pass otherwise dies with a
            # journal that never heard of the joiner (observed in the
            # chaos drills: the stale journal's only candidate was a
            # dead address and the NEXT replacement had nobody to
            # dial).  maybe_save dedups by signature, so steady-state
            # heartbeats cost two dict compares, no disk I/O
            self._fleet_watch()
        local = self.membership.local
        return {
            "ok": bool(accepted),
            "from": {"rank": local.rank, "addr": local.addr,
                     "state": local.state,
                     "incarnation": local.incarnation,
                     "capacity": local.capacity},
            "roster": self.membership.roster(),
            "view": self.membership.view_of(rank),
        }

    def _fault_requested(self, msg: dict) -> dict:
        """Inbound ``POST /fault`` (chaos harness; only wired when
        ``input.tpu_fleet_chaos = true``): arm or disarm one fault site
        at runtime — ``{"site": "host_kill", "spec": "once:1"}`` — so
        ``tools/chaos.py`` can drive deterministic fault drills against
        long-running hosts without restarting them."""
        site = msg.get("site")
        spec = msg.get("spec", "off")
        if not isinstance(site, str) or not isinstance(spec, str):
            raise ValueError("fault body must carry string site/spec")
        faultinject.set_site(site, spec)  # FaultInjectError -> 400
        print(f"fleet-chaos: fault site [{site}] set to [{spec}]",
              file=sys.stderr)
        return {"ok": True, "site": site, "spec": spec}

    def _drain_requested(self) -> dict:
        """Inbound ``POST /drain`` (fleetctl): flip to draining and
        kick the pipeline's drain path off-thread — the HTTP reply must
        not wait out a full queue flush, nor (sync_wave=False) one
        socket timeout per dead peer."""
        self.enter_draining(sync_wave=False)
        if self._on_drain_cb is not None:
            # flowcheck: disable=FC10 -- the drain kick IS the drain path: it runs Pipeline._drain to completion and the process exits behind it; joining it here would make the HTTP reply wait out the full queue flush
            t = threading.Thread(target=self._on_drain_cb, daemon=True,
                                 name="fleet-drain-request")
            t.start()
        state = self.membership.local.state if self.membership else DRAINING
        return {"ok": True, "state": state}

    # -- health document ---------------------------------------------------
    def _lb_healthy(self) -> bool:
        if self.membership is None:
            return False
        return self.membership.local.state in (JOINING, ACTIVE)

    def rendezvous(self) -> Optional[Dict[str, object]]:
        """The agreed rendezvous as announced in ``/healthz``:
        ``{"rank", "addr", "fallback"}`` (None before membership
        starts).  ``fallback`` means the elected host is not rank 0 —
        the configured coordinator is rank 0's endpoint by convention,
        so a non-zero election is the failover consumers (fleetctl, LB
        templating, joining hosts) should follow."""
        if self.membership is None:
            return None
        rdv = self.membership.rendezvous()
        if rdv is None:
            return {"rank": -1, "addr": "", "fallback": False}
        return {"rank": rdv[0], "addr": rdv[1], "fallback": rdv[0] != 0}

    def health_payload(self) -> Dict[str, object]:
        """The ``GET /healthz`` document.  Schema is golden-file-tested
        (tests/resources/healthz_schema.json) — additive changes bump
        ``HEALTH_SCHEMA``."""
        from ..obs.events import journal as _journal
        from ..obs.slo import engine as _slo_engine
        from ..obs.trace import tracer as _tracer

        local = self.membership.local if self.membership else None
        counts = self.membership.counts() if self.membership else {}
        rdv = self.rendezvous() or \
            {"rank": -1, "addr": "", "fallback": False}
        shares = self.membership.shares() if self.membership else {}
        return {
            "schema": HEALTH_SCHEMA,
            "ts": round(time.time(), 3),
            "uptime_s": round(time.monotonic() - self._started, 3),
            "host": {
                "rank": local.rank if local else -1,
                "addr": local.addr if local else "",
                "state": local.state if local else "down",
                "incarnation": local.incarnation if local else 0,
                "draining": bool(self._draining),
                "capacity": local.capacity if local else 0.0,
            },
            "fleet": {
                "hosts": self.spec.hosts,
                "counts": counts,
                "peers": self.membership.roster() if self.membership else [],
                "rendezvous": rdv,
                "shares": {str(r): s for r, s in sorted(shares.items())},
            },
            # samples included: the /fleetz scrape on the rendezvous
            # host pools them for honest merged quantiles
            "metrics": self._registry.snapshot(include_hist_samples=True),
            "events": _journal.health_section(),
            "trace": _tracer.stats(),
            "slo": _slo_engine.health_section(),
        }

    # -- fleet observability (/fleetz) -------------------------------------
    def _scrape_peers(self, timeout: float) -> None:
        """Refresh the /fleetz snapshot cache from every non-departed
        remote peer, in parallel (one short-lived GET each, the
        heartbeat transport's rules: hard timeout, failure is data)."""
        if self.membership is None:
            return
        targets = self.membership.heartbeat_targets()

        def scrape(rank: int, addr: str) -> None:
            doc = _http_get_json(addr, "/healthz", timeout)
            if doc is not None:
                with self._fleetz_lock:
                    self._fleetz_cache[rank] = (doc, time.monotonic())

        threads = [threading.Thread(target=scrape, args=t, daemon=True,
                                    name=f"fleetz-scrape-{t[0]}")
                   for t in targets]
        for t in threads:
            t.start()
        deadline = time.monotonic() + timeout + 0.25
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))

    def fleetz_payload(self) -> Dict[str, object]:
        """The ``GET /fleetz`` document (schema ``FLEETZ_SCHEMA``,
        golden-file-tested): merged fleet metrics, rank-tagged event
        union, per-host staleness, and fleet-level SLO status.  Served
        by every host from its own view; the agreed rendezvous host is
        the canonical answer consumers follow."""
        timeout = max(0.2, min(1.0,
                               self.spec.heartbeat_ms * 2 / 1000.0))
        self._scrape_peers(timeout)
        now_mono = time.monotonic()
        local = self.membership.local if self.membership else None
        local_doc = self.health_payload()
        # freshness threshold: a snapshot older than one scrape round
        # was NOT refreshed this request — its host failed to answer
        stale_after = timeout + 0.5
        shares = self.membership.shares() if self.membership else {}
        hosts = []
        metric_snaps = []
        event_sections = []
        slo_sections = []

        def add(rank, addr, state, doc, stale, age_s):
            hosts.append({
                "rank": rank, "addr": addr, "state": state,
                "stale": bool(stale), "age_s": round(age_s, 3),
                "share": shares.get(rank, 0.0),
                "snapshot": doc is not None,
                "metrics": (doc or {}).get("metrics", {}),
            })
            if doc is None:
                return
            metric_snaps.append(doc.get("metrics", {}))
            event_sections.append((rank, doc.get("events", {})))
            slo_sections.append((rank, bool(stale), doc.get("slo", {})))

        if local is not None:
            add(local.rank, local.addr, local.state, local_doc,
                False, 0.0)
        with self._fleetz_lock:
            cached = dict(self._fleetz_cache)
        known = {p["rank"]: p
                 for p in (self.membership.roster()
                           if self.membership else [])}
        for rank in sorted(set(cached) | set(known)):
            if local is not None and rank == local.rank:
                continue
            peer = known.get(rank)
            doc, fetched = cached.get(rank, (None, None))
            age = (now_mono - fetched) if fetched is not None else 0.0
            stale = fetched is None or age > stale_after
            add(rank,
                peer["addr"] if peer else
                (doc or {}).get("host", {}).get("addr", ""),
                peer["state"] if peer else "unknown",
                doc, stale, age)
        rdv = self.rendezvous() or \
            {"rank": -1, "addr": "", "fallback": False}
        return {
            "schema": FLEETZ_SCHEMA,
            "ts": round(time.time(), 3),
            "served_by": local.rank if local else -1,
            "is_rendezvous": bool(local is not None
                                  and rdv.get("rank") == local.rank),
            "rendezvous": rdv,
            "hosts": hosts,
            "metrics": merge_metric_snapshots(metric_snaps),
            "events": merge_event_sections(event_sections),
            "slo": merge_slo_sections(slo_sections),
            "control": self._control_section(),
        }
