"""Durable fleet roster: the crash-safe journal half of rendezvous
failover.

The coordinator address (``input.tpu_fleet_coordinator``) is only the
*bootstrap* rendezvous — PR 9 deliberately made its death harmless to
the running fleet, but a brand-new (or rebooted) host still had nobody
else to dial.  This module closes that hole: each host journals the
gossiped roster to ``input.tpu_fleet_roster_path`` whenever it changes,
and a booting host loads the journal as bootstrap candidates — when the
configured coordinator is unreachable it simply walks the persisted
peers, whose replies carry the live roster and the currently elected
rendezvous.

Write discipline is crash-safe atomic rewrite (the metrics reporter /
AOT manifest idiom): serialize to a sibling temp file, ``fsync``, then
``os.replace`` — a SIGKILL mid-save leaves the *previous* journal
intact, never a half-written one.  Loads are strict: a corrupt,
truncated, or wrong-format file is counted
(``fleet_roster_load_errors``), reported once, and ignored — the host
falls back to the plain coordinator walk, exactly as if the journal
never existed (clean re-rendezvous, no crash).

Volatile fields (heartbeat ages, computed shares) are stripped before
the journal is written: the journal records *who exists where at which
incarnation*, not a point-in-time liveness opinion — liveness is
re-proven by dialing.

The ``roster_corrupt`` fault site (``utils/faultinject.py``) makes a
firing save write a deliberately truncated journal instead — the chaos
harness uses it to prove the corrupt-file path above end to end.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils import faultinject
from ..utils.metrics import registry as _global_registry

ROSTER_FORMAT = 1

# entry fields persisted per peer (everything else the roster() payload
# carries — hb_age_ms, share — is volatile and re-derived after boot)
_ENTRY_FIELDS = ("rank", "addr", "state", "incarnation", "capacity",
                 "evicted")

_VALID_STATES = frozenset(
    ("joining", "active", "suspect", "draining", "departed"))


def _clean_entry(entry: dict) -> Optional[dict]:
    """One validated, volatile-field-free journal entry; None when the
    entry is not a plausible peer (a corrupt journal must degrade to
    'no journal', never to a crash or a poisoned membership)."""
    try:
        out = {
            "rank": int(entry["rank"]),
            "addr": str(entry["addr"]),
            "state": str(entry["state"]),
            "incarnation": int(entry.get("incarnation", 0)),
            "capacity": float(entry.get("capacity", 1.0)),
            "evicted": bool(entry.get("evicted", False)),
        }
    except (KeyError, TypeError, ValueError):
        return None
    if out["rank"] < 0 or out["state"] not in _VALID_STATES \
            or not out["addr"]:
        return None
    return out


class RosterStore:
    """One host's roster journal (``input.tpu_fleet_roster_path``)."""

    def __init__(self, path: str, registry=None):
        self.path = path
        self._registry = registry if registry is not None \
            else _global_registry
        self._last_signature: Optional[Tuple] = None
        # the ticker (_fleet_watch per tick) and the drain path
        # (enter_draining/shutdown, signal or HTTP thread) both save;
        # unserialized they would share ONE tmp file and os.replace a
        # mixed-content journal — corrupting it exactly at drain, the
        # moment the next boot needs it most
        self._lock = threading.Lock()

    # -- save --------------------------------------------------------------
    def _signature(self, entries: List[dict]) -> Tuple:
        return tuple(tuple(e[f] for f in _ENTRY_FIELDS) for e in entries)

    def maybe_save(self, roster: List[dict], rank: int,
                   rendezvous: Optional[Dict[str, object]]) -> bool:
        """Persist when the durable part of the roster changed since the
        last save (heartbeat ages churn every tick; identity does not).
        Returns True when a write happened."""
        entries = [e for e in (_clean_entry(r) for r in roster)
                   if e is not None]
        sig = self._signature(entries)
        with self._lock:
            return self._save_locked(entries, sig, rank, rendezvous)

    def _save_locked(self, entries: List[dict], sig: Tuple, rank: int,
                     rendezvous: Optional[Dict[str, object]]) -> bool:
        if sig == self._last_signature:
            return False
        doc = {
            "format": ROSTER_FORMAT,
            "saved_ts": round(time.time(), 3),
            "saved_by_rank": rank,
            "rendezvous": rendezvous,
            "roster": entries,
        }
        body = json.dumps(doc, indent=1).encode()
        if faultinject.enabled() and faultinject.fire("roster_corrupt"):
            # deterministic journal corruption: write a truncated
            # prefix (still atomically — the corruption under test is
            # the CONTENT, not a torn write, which os.replace already
            # rules out)
            body = body[:max(8, len(body) // 3)]
            print("faultinject: roster_corrupt firing — truncated "
                  f"journal written to {self.path}", file=sys.stderr)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as fd:
                fd.write(body)
                fd.flush()
                os.fsync(fd.fileno())  # flowcheck: disable=FC07 -- durable-save is deliberately serialized under _lock (single-flight: one tmp file, one rename); it runs on the ticker thread, never the decode path
            os.replace(tmp, self.path)  # flowcheck: disable=FC07 -- same single-flight durable-save; the rename must happen before the next snapshot can start
        except OSError as e:
            # a full/readonly volume must not take the ticker down: the
            # fleet keeps running on gossip alone, the journal is a
            # bootstrap optimization
            print(f"fleet-roster: cannot journal to {self.path} ({e})",
                  file=sys.stderr)
            try:
                os.unlink(tmp)
            except OSError:
                pass  # flowcheck: disable=FC04 -- best-effort temp cleanup
            return False
        self._last_signature = sig
        self._registry.inc("fleet_roster_saves")
        return True

    # -- load --------------------------------------------------------------
    def load(self) -> Optional[List[dict]]:
        """The journaled entries, or None when there is no usable
        journal (missing file, corrupt/partial JSON, wrong format, no
        valid entries).  Corruption is counted and reported once; the
        caller falls back to the coordinator walk."""
        try:
            with open(self.path, "rb") as fd:
                raw = fd.read()
        except FileNotFoundError:
            return None
        except OSError as e:
            self._registry.inc("fleet_roster_load_errors")
            print(f"fleet-roster: cannot read {self.path} ({e}); "
                  "booting without bootstrap candidates", file=sys.stderr)
            return None
        try:
            doc = json.loads(raw)
            if not isinstance(doc, dict) \
                    or doc.get("format") != ROSTER_FORMAT \
                    or not isinstance(doc.get("roster"), list):
                raise ValueError("not a roster journal")
        except ValueError as e:
            self._registry.inc("fleet_roster_load_errors")
            print(f"fleet-roster: {self.path} is corrupt ({e}); "
                  "ignoring it and re-rendezvousing cleanly",
                  file=sys.stderr)
            return None
        entries = [e for e in (_clean_entry(r) for r in doc["roster"])
                   if e is not None]
        if not entries:
            self._registry.inc("fleet_roster_load_errors")
            print(f"fleet-roster: {self.path} carries no usable peers; "
                  "ignoring it", file=sys.stderr)
            return None
        return entries
