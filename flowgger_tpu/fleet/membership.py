"""Fleet membership: the per-host state machine that federates N
single-host pipelines into one fleet view.

Logs are embarrassingly data-parallel (SURVEY.md §2.8 — no cross-record
communication to preserve), so fleet membership is *advisory*: it never
gates the decode hot path.  Each host keeps its own view of every peer,
built purely from heartbeat observations, and exports it through the
health endpoint for a load balancer to act on.  There is no consensus
round and no JAX collective anywhere in this module — a dead peer can
never block a live host's decode.

Per-host lifecycle (the PR 2 breaker/supervisor ladder at fleet
granularity)::

    joining ──► active ──► draining ──► departed
                  │  ▲                      │
                  ▼  │ (heartbeat resumes)  │ rejoin: fresh
                suspect ──► draining        │ incarnation only
                (missed     (evicted)       ▼
                 heartbeats)              joining ...

- ``joining``   — announced (rendezvous/roster) but no direct heartbeat
  proof of liveness yet;
- ``active``    — heartbeating within ``suspect_ms``;
- ``suspect``   — heartbeats missing past ``suspect_ms``; cured by the
  next heartbeat (suspect → active);
- ``draining``  — the host is flushing in-flight batches.  Entered
  voluntarily (SIGTERM / ``fleetctl drain`` — the host announces it) or
  by *eviction* (heartbeats missing past ``evict_ms``: peers assume the
  host is gone and treat it as draining so the load balancer stops
  routing to it while any straggling output flushes);
- ``departed``  — terminal for this incarnation.  ``draining`` is
  deliberately unreachable from ``departed``: a departed rank can only
  come back by *rejoining* with a strictly higher incarnation, which
  restarts the ladder at ``joining``.

Rank tie-breaks are deterministic: when two hosts claim the same rank,
the strictly higher incarnation wins; on equal incarnations the
incumbent (first observed) keeps the rank and the newcomer is rejected.
No clock comparison, no address ordering — the same inputs produce the
same winner on every host.

The same determinism carries two fleet-wide derivations every host
computes locally from its own view (no consensus round):

- :meth:`Membership.rendezvous` — the agreed rendezvous is simply the
  **lowest active rank**.  Which host holds a rank is already settled
  by the incarnation tie-breaks above, so converged views elect the
  same host everywhere; when the configured coordinator (rank 0 by
  convention) dies, the election degrades to the next-lowest active
  rank with no extra protocol — that *is* the failover.
- :meth:`Membership.shares` — each host advertises a capacity weight
  on its heartbeats; a host's traffic share is its weight over the sum
  across routable (joining/active) hosts.  A joiner's weight enters
  the denominator the moment it is routable and an evicted/draining
  host's weight leaves it — live rebalancing falls out of membership
  plus the LB's 200/503 contract, again with no added protocol.

Exported metrics (consumed by the health endpoint and any scraper):
``fleet_hosts_{joining,active,suspect,draining,departed}`` gauges (the
local host counts toward its own state), per-peer
``fleet_peer{rank}_state`` / ``fleet_peer{rank}_hb_age_ms`` /
``fleet_peer{rank}_share`` gauges, the ``fleet_rendezvous_rank`` gauge
(-1 while no active host is known), and the ``fleet_evictions``
counter.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

JOINING, ACTIVE, SUSPECT, DRAINING, DEPARTED = (
    "joining", "active", "suspect", "draining", "departed")
STATES = (JOINING, ACTIVE, SUSPECT, DRAINING, DEPARTED)
STATE_GAUGE = {JOINING: 0, ACTIVE: 1, SUSPECT: 2, DRAINING: 3, DEPARTED: 4}

DEFAULT_SUSPECT_MS = 2_000
DEFAULT_EVICT_MS = 5_000
DEFAULT_DEPART_MS = 2_000

_ALLOWED = frozenset({
    (JOINING, ACTIVE),
    (JOINING, DRAINING),    # SIGTERM (or eviction) before any heartbeat
    (ACTIVE, SUSPECT),
    (SUSPECT, ACTIVE),      # heartbeat resumed within the evict window
    (ACTIVE, DRAINING),
    (SUSPECT, DRAINING),
    (DRAINING, DEPARTED),
    (DEPARTED, JOINING),    # rejoin — requires a fresh incarnation
})


class FleetStateError(Exception):
    """An illegal membership transition was requested explicitly (the
    heartbeat paths never raise: stale gossip is ignored, not fatal)."""


@dataclass
class PeerView:
    """One host as seen from here.  ``last_hb`` is this host's monotonic
    clock at the last liveness proof (a direct heartbeat either way);
    ``evicted`` marks a draining state entered by missed heartbeats
    rather than the peer's own announcement."""

    rank: int
    addr: str
    state: str = JOINING
    incarnation: int = 0
    last_hb: float = 0.0
    evicted: bool = False
    capacity: float = 1.0  # advertised traffic weight (heartbeat-borne)


class Membership:
    """Thread-safe fleet view for one host.  All mutation funnels
    through ``_transition`` so the ladder above is enforced everywhere
    and every change lands in ``transitions`` (the test- and
    debug-visible history, same idiom as ``tpu/breaker.py``)."""

    def __init__(self, rank: int, addr: str, incarnation: int = 0,
                 suspect_ms: int = DEFAULT_SUSPECT_MS,
                 evict_ms: int = DEFAULT_EVICT_MS,
                 depart_ms: int = DEFAULT_DEPART_MS,
                 capacity: float = 1.0,
                 clock=time.monotonic, registry=None):
        if suspect_ms >= evict_ms:
            raise ValueError("suspect_ms must be < evict_ms "
                             "(suspect is the rung before eviction)")
        if capacity <= 0:
            raise ValueError("capacity must be > 0 (a zero-weight host "
                             "would advertise itself unroutable while "
                             "answering healthz 200)")
        self.rank = rank
        self.suspect_ms = suspect_ms
        self.evict_ms = evict_ms
        self.depart_ms = depart_ms
        self._clock = clock
        if registry is None:
            from ..utils.metrics import registry as _global_registry

            registry = _global_registry
        self._metrics = registry
        self._lock = threading.Lock()
        self._peers: Dict[int, PeerView] = {}
        self._peers[rank] = PeerView(rank=rank, addr=addr, state=JOINING,
                                     incarnation=incarnation,
                                     last_hb=self._clock(),
                                     capacity=float(capacity))
        self.transitions: List[Tuple[float, int, str, str]] = []
        with self._lock:
            self._publish_gauges()

    # -- core transition (callers hold self._lock) -------------------------
    def _transition(self, peer: PeerView, new: str) -> bool:
        old = peer.state
        if old == new:
            return False
        if (old, new) not in _ALLOWED:
            raise FleetStateError(
                f"illegal fleet transition for rank {peer.rank}: "
                f"{old} -> {new}"
                + (" (departed is terminal for an incarnation; rejoin "
                   "with a higher incarnation instead)"
                   if old == DEPARTED else ""))
        peer.state = new
        self.transitions.append((self._clock(), peer.rank, old, new))
        return True

    def _publish_gauges(self) -> None:
        counts = {s: 0 for s in STATES}
        now = self._clock()
        shares = self._shares_locked()
        for peer in self._peers.values():
            counts[peer.state] += 1
            self._metrics.set_gauge(f"fleet_peer{peer.rank}_state",
                                    STATE_GAUGE[peer.state])
            age_ms = 0.0 if peer.rank == self.rank else \
                (now - peer.last_hb) * 1000.0
            self._metrics.set_gauge(f"fleet_peer{peer.rank}_hb_age_ms",
                                    round(age_ms, 1))
            self._metrics.set_gauge(f"fleet_peer{peer.rank}_share",
                                    shares.get(peer.rank, 0.0))
        for state, n in counts.items():
            self._metrics.set_gauge(f"fleet_hosts_{state}", n)
        rdv = self._rendezvous_locked()
        self._metrics.set_gauge("fleet_rendezvous_rank",
                                rdv[0] if rdv is not None else -1)

    # -- local lifecycle ---------------------------------------------------
    @property
    def local(self) -> PeerView:
        with self._lock:
            peer = self._peers[self.rank]
            return PeerView(rank=peer.rank, addr=peer.addr, state=peer.state,
                            incarnation=peer.incarnation,
                            last_hb=peer.last_hb, evicted=peer.evicted,
                            capacity=peer.capacity)

    def activate(self) -> None:
        """Local host is up (service listening): joining → active."""
        with self._lock:
            self._transition(self._peers[self.rank], ACTIVE)
            self._publish_gauges()

    def set_local_capacity(self, capacity: float) -> bool:
        """Retune the local host's advertised capacity weight in place
        (the control plane's share-feedback loop).  The next heartbeat
        doc carries the new weight, so the decay propagates to every
        peer's share denominator with no added protocol.  Returns True
        when the weight actually changed; non-positive values are
        rejected (a zero-weight host would advertise itself unroutable
        while answering healthz 200)."""
        capacity = self._clean_capacity(capacity)
        if capacity is None:
            return False
        with self._lock:
            peer = self._peers[self.rank]
            if peer.capacity == capacity:
                return False
            peer.capacity = capacity
            self._publish_gauges()
            return True

    def local_rejoin(self) -> int:
        """The fleet evicted *us* (a peer's view answered that our rank
        is draining/departed at our incarnation).  Bump the incarnation
        and restart the local ladder — peers accept the comeback only
        because the incarnation is strictly higher.  Returns the new
        incarnation."""
        with self._lock:
            peer = self._peers[self.rank]
            peer.incarnation += 1
            peer.evicted = False
            if peer.state != JOINING:
                # departed is the only legal source of a rejoin; walk the
                # ladder explicitly so the history stays legible
                if peer.state in (ACTIVE, SUSPECT):
                    self._transition(peer, DRAINING)
                if peer.state == DRAINING:
                    self._transition(peer, DEPARTED)
                self._transition(peer, JOINING)
            self._transition(peer, ACTIVE)
            peer.last_hb = self._clock()
            self._publish_gauges()
            return peer.incarnation

    # -- peer observations -------------------------------------------------
    def note_heartbeat(self, rank: int, addr: str, state: str = ACTIVE,
                       incarnation: int = 0,
                       capacity: Optional[float] = None) -> bool:
        """One direct liveness proof (inbound heartbeat, or a reply to
        ours).  Returns False when the claim loses its tie-break and was
        ignored (stale incarnation, or a rank collision the incumbent
        wins)."""
        if state not in STATES or rank == self.rank:
            # the local lifecycle is driven locally — a remote claim to
            # our rank never rewrites it (see view_of/local_rejoin for
            # how an evicted host learns its fate)
            return False
        capacity = self._clean_capacity(capacity)
        with self._lock:
            peer = self._peers.get(rank)
            if peer is None:
                peer = PeerView(rank=rank, addr=addr,
                                incarnation=incarnation,
                                last_hb=self._clock(),
                                capacity=capacity if capacity is not None
                                else 1.0)
                self._peers[rank] = peer
                self.transitions.append((self._clock(), rank, "", JOINING))
            else:
                if incarnation < peer.incarnation:
                    return False  # stale duplicate of an older life
                if incarnation == peer.incarnation:
                    if peer.state == DEPARTED:
                        # departed is terminal per incarnation: only a
                        # strictly fresher life can resurrect the rank
                        return False
                    if addr != peer.addr:
                        # rank collision, equal incarnation: incumbent
                        # wins, deterministically, on every host
                        return False
                else:
                    # higher incarnation always wins the rank: fold the
                    # old life to departed first so the ladder holds
                    if peer.state in (ACTIVE, SUSPECT, JOINING):
                        self._transition(peer, DRAINING)
                    if peer.state == DRAINING:
                        self._transition(peer, DEPARTED)
                    self._transition(peer, JOINING)
                    peer.incarnation = incarnation
                    peer.evicted = False
                peer.addr = addr
            if capacity is not None:
                peer.capacity = capacity
            peer.last_hb = self._clock()
            if state == DRAINING:
                if peer.state in (JOINING, ACTIVE, SUSPECT):
                    self._transition(peer, DRAINING)
            elif state == DEPARTED:
                if peer.state in (JOINING, ACTIVE, SUSPECT):
                    self._transition(peer, DRAINING)
                if peer.state == DRAINING:
                    self._transition(peer, DEPARTED)
            else:
                # a live (joining/active) claim cures suspicion; a
                # draining peer heartbeating stays draining (one-way)
                if peer.state in (JOINING, SUSPECT):
                    self._transition(peer, ACTIVE)
                    peer.evicted = False
            self._publish_gauges()
            return True

    @staticmethod
    def _clean_capacity(capacity) -> Optional[float]:
        """Capacity claims are remote input: non-numeric or non-positive
        values are ignored (None = keep what we have), never propagated
        into the share denominator."""
        if capacity is None:
            return None
        try:
            capacity = float(capacity)
        except (TypeError, ValueError):
            return None
        return capacity if capacity > 0 else None

    def note_roster(self, rank: int, addr: str, state: str,
                    incarnation: int = 0,
                    capacity: Optional[float] = None) -> None:
        """Gossip (a roster entry relayed by another host): introduces
        *new* peers, but never overrides a state we learned first-hand —
        only direct heartbeats move an already-known peer.  Live gossip
        states (joining/active/suspect) enter as ``joining`` (hearsay is
        not liveness proof; we heartbeat the peer directly and promote
        on its reply), while ``draining``/``departed`` enter as
        announced — a cleanly-departed host must not be resurrected,
        dialed for ``evict_ms``, and then counted as a spurious
        eviction by every fresh joiner."""
        if rank == self.rank or state not in STATES:
            return
        capacity = self._clean_capacity(capacity)
        entry_state = state if state in (DRAINING, DEPARTED) else JOINING
        with self._lock:
            if rank in self._peers:
                return
            self._peers[rank] = PeerView(rank=rank, addr=addr,
                                         state=entry_state,
                                         incarnation=incarnation,
                                         last_hb=self._clock(),
                                         capacity=capacity
                                         if capacity is not None else 1.0)
            self.transitions.append((self._clock(), rank, "", entry_state))
            self._publish_gauges()

    def mark_draining(self, rank: Optional[int] = None) -> None:
        """Explicit drain (SIGTERM / fleetctl): flips the host to
        draining.  Raises ``FleetStateError`` from ``departed`` —
        draining is unreachable from the terminal state."""
        rank = self.rank if rank is None else rank
        with self._lock:
            peer = self._peers.get(rank)
            if peer is None or peer.state == DRAINING:
                return
            self._transition(peer, DRAINING)
            self._publish_gauges()

    def mark_departed(self, rank: Optional[int] = None) -> None:
        """Drain complete: draining → departed.  Departure always passes
        through draining so in-flight batches get their flush window."""
        rank = self.rank if rank is None else rank
        with self._lock:
            peer = self._peers.get(rank)
            if peer is None or peer.state == DEPARTED:
                return
            if peer.state in (JOINING, ACTIVE, SUSPECT):
                self._transition(peer, DRAINING)
            self._transition(peer, DEPARTED)
            self._publish_gauges()

    # -- ageing (the fleet supervisor's ladder) ----------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """Age every remote peer against the heartbeat deadlines:
        ``suspect_ms`` → suspect, ``evict_ms`` → evicted (treated as
        draining so the LB stops routing while stragglers flush),
        ``evict_ms + depart_ms`` → departed."""
        now = self._clock() if now is None else now
        with self._lock:
            for peer in self._peers.values():
                if peer.rank == self.rank or peer.state == DEPARTED:
                    continue
                age_ms = (now - peer.last_hb) * 1000.0
                if peer.state == ACTIVE and age_ms > self.suspect_ms:
                    self._transition(peer, SUSPECT)
                if (peer.state in (SUSPECT, JOINING)
                        and age_ms > self.evict_ms):
                    self._transition(peer, DRAINING)
                    peer.evicted = True
                    self._metrics.inc("fleet_evictions")
                if (peer.state == DRAINING
                        and age_ms > self.evict_ms + self.depart_ms):
                    # evicted drainers age out; so does a VOLUNTARY
                    # drainer that announced draining and then died
                    # mid-flush — without this it would sit draining
                    # forever, costing every peer one timed-out
                    # connect per interval for the rest of the fleet's
                    # life
                    self._transition(peer, DEPARTED)
            self._publish_gauges()

    # -- read side ---------------------------------------------------------
    def counts(self) -> Dict[str, int]:
        with self._lock:
            out = {s: 0 for s in STATES}
            for peer in self._peers.values():
                out[peer.state] += 1
            return out

    def get(self, rank: int) -> Optional[PeerView]:
        with self._lock:
            peer = self._peers.get(rank)
            if peer is None:
                return None
            return PeerView(rank=peer.rank, addr=peer.addr, state=peer.state,
                            incarnation=peer.incarnation,
                            last_hb=peer.last_hb, evicted=peer.evicted,
                            capacity=peer.capacity)

    # -- fleet-wide derivations (deterministic, no consensus round) --------
    def _rendezvous_locked(self) -> Optional[Tuple[int, str]]:
        best = None
        for peer in self._peers.values():
            if peer.state == ACTIVE and (best is None
                                         or peer.rank < best.rank):
                best = peer
        return None if best is None else (best.rank, best.addr)

    def rendezvous(self) -> Optional[Tuple[int, str]]:
        """The agreed rendezvous: ``(rank, addr)`` of the lowest
        *active* rank in this host's view (None while nobody is
        active).  Deterministic on converged views — which host holds a
        rank is settled by the incarnation tie-breaks, so every host
        elects the same winner from the same facts; the configured
        coordinator dying simply shifts the election to the next-lowest
        active rank (the failover)."""
        with self._lock:
            return self._rendezvous_locked()

    def _shares_locked(self) -> Dict[int, float]:
        routable = [p for p in self._peers.values()
                    if p.state in (JOINING, ACTIVE)]
        total = sum(p.capacity for p in routable)
        if total <= 0:
            return {}
        return {p.rank: round(p.capacity / total, 4) for p in routable}

    def shares(self) -> Dict[int, float]:
        """Per-host traffic share: advertised capacity weight over the
        sum across *routable* (joining/active — the healthz-200 set)
        hosts.  A joiner absorbs its share the moment it is routable; a
        draining/evicted host's weight redistributes across survivors —
        live rebalancing as a pure function of membership."""
        with self._lock:
            return self._shares_locked()

    def heartbeat_targets(self) -> List[Tuple[int, str]]:
        """(rank, addr) of every remote peer worth heartbeating — the
        departed are left in peace until they rejoin."""
        with self._lock:
            return [(p.rank, p.addr) for p in self._peers.values()
                    if p.rank != self.rank and p.state != DEPARTED]

    def roster(self) -> List[Dict[str, object]]:
        """JSON-safe snapshot of every peer (self included) — the
        gossip payload carried on heartbeat replies."""
        now = self._clock()
        with self._lock:
            shares = self._shares_locked()
            out = []
            for peer in sorted(self._peers.values(), key=lambda p: p.rank):
                age_ms = 0.0 if peer.rank == self.rank else \
                    (now - peer.last_hb) * 1000.0
                out.append({
                    "rank": peer.rank,
                    "addr": peer.addr,
                    "state": peer.state,
                    "incarnation": peer.incarnation,
                    "hb_age_ms": round(age_ms, 1),
                    "evicted": peer.evicted,
                    "capacity": peer.capacity,
                    "share": shares.get(peer.rank, 0.0),
                })
            return out

    def view_of(self, rank: int) -> Optional[Dict[str, object]]:
        """This host's opinion of one rank (heartbeat replies carry the
        sender's entry so an evicted host can discover its own
        eviction and rejoin)."""
        peer = self.get(rank)
        if peer is None:
            return None
        return {"rank": peer.rank, "state": peer.state,
                "incarnation": peer.incarnation, "evicted": peer.evicted}
