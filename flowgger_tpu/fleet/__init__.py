"""Fleet federation: multi-host lane scale-out.

One host = one pipeline = one LaneSet over local chips (PR 5).  This
package federates N of them into a fleet with four powers —
**membership** (who is in, coordinator-rendezvous then full-mesh
heartbeats), **health export** (per-host HTTP endpoint a load balancer
consumes), **drain-on-departure** (SIGTERM or missed-heartbeat
eviction reuses the pipeline's fence-all drain so in-flight batches
emit byte-identically while peers absorb new traffic), and **fleet
observability** (``GET /fleetz``: merged metrics with pooled-sample
histogram quantiles, the rank-tagged degradation-event union,
per-host staleness marking, and fleet-level SLO status — see README
"Fleet aggregation").  It never adds a collective: logs are
embarrassingly data-parallel, so host failure degrades that host
alone.

    membership.py — the joining/active/suspect/draining/departed state
                    machine, deterministic rank tie-breaks, the
                    rendezvous election + capacity-share derivations,
                    gauges
    health.py     — per-host HTTP health + heartbeat endpoint
    federation.py — the Fleet agent: config spec, heartbeat ticker,
                    eviction ladder, rejoin-after-backoff, rendezvous
                    failover + live-rebalance watch
    roster.py     — the durable roster journal (crash-safe bootstrap
                    candidates for joiners whose coordinator is dead)

See README "Multi-host fleet" for topology, key surface, the health
document schema, the failure ladder, and the self-healing
(failover/rebalance/chaos) story.
"""

from .federation import Fleet, FleetSpec, fleet_spec  # noqa: F401
from .roster import RosterStore  # noqa: F401
from .membership import (  # noqa: F401
    ACTIVE,
    DEPARTED,
    DRAINING,
    JOINING,
    SUSPECT,
    FleetStateError,
    Membership,
)
