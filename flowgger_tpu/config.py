"""TOML config with dotted-path lookup.

Parity model: /root/reference/src/flowgger/config.rs:46-108 — a dumb,
untyped store; all validation lives in each component's constructor, which
raises ``ConfigError`` with the same messages the reference panics with.
"""

from __future__ import annotations

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomli is API-compatible
    import tomli as tomllib
from typing import Any, Optional


class ConfigError(Exception):
    """Equivalent of the reference's config-time panics."""


class Config:
    def __init__(self, table: dict):
        self._table = table

    @classmethod
    def from_path(cls, path: str) -> "Config":
        with open(path, "rb") as fd:
            data = fd.read()
        return cls.from_string(data.decode("utf-8"))

    @classmethod
    def from_string(cls, toml_text: str) -> "Config":
        try:
            table = tomllib.loads(toml_text)
        except (tomllib.TOMLDecodeError, UnicodeDecodeError):
            raise ConfigError("Syntax error - config file is not valid TOML")
        return cls(table)

    def lookup(self, path: str) -> Optional[Any]:
        """Dotted lookup, e.g. ``lookup("input.format")`` (config.rs:96-108).

        Reference quirk preserved: a non-table intermediate value is
        *skipped*, not rejected — the Rust loop only descends when the
        current value is a table and otherwise ignores the remaining path
        parts, so ``output = "file"`` makes ``lookup("output.file_path")``
        return ``"file"`` (config.rs:100-106).
        """
        cur: Any = self._table
        for part in path.split("."):
            if isinstance(cur, dict):
                if part not in cur:
                    return None
                cur = cur[part]
        return cur

    # -- typed helpers mirroring the reference's `expect()` call sites ----
    def lookup_str(self, path: str, err: str, default: Optional[str] = None) -> Optional[str]:
        v = self.lookup(path)
        if v is None:
            return default
        if not isinstance(v, str):
            raise ConfigError(err)
        return v

    def lookup_int(self, path: str, err: str, default: Optional[int] = None) -> Optional[int]:
        v = self.lookup(path)
        if v is None:
            return default
        if isinstance(v, bool) or not isinstance(v, int):
            raise ConfigError(err)
        return v

    def lookup_float(self, path: str, err: str, default: Optional[float] = None) -> Optional[float]:
        v = self.lookup(path)
        if v is None:
            return default
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ConfigError(err)
        return float(v)

    def lookup_bool(self, path: str, err: str, default: Optional[bool] = None) -> Optional[bool]:
        v = self.lookup(path)
        if v is None:
            return default
        if not isinstance(v, bool):
            raise ConfigError(err)
        return v

    def lookup_table(self, path: str, err: str) -> Optional[dict]:
        v = self.lookup(path)
        if v is None:
            return None
        if not isinstance(v, dict):
            raise ConfigError(err)
        return v
