"""EncodedBlock: a batch of framed, encoded messages in one buffer.

The reference's queue carries one ``Vec<u8>`` per message
(/root/reference/src/flowgger/mod.rs:461-468) and every sink applies the
merger per message.  For the columnar fast path that per-message hop is
the bottleneck (one queue put + one frame + one write per row), so the
batched pipeline enqueues a single ``EncodedBlock`` per decode batch:
framing is pre-applied by the producer (with the pipeline's own merger,
so the bytes on the wire are identical) and sinks either write ``data``
wholesale (file/tls/debug — byte-stream sinks) or iterate per-message
slices (kafka, rotation-enabled file output) via ``bounds``.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


class EncodedBlock:
    """A contiguous buffer of framed messages.

    ``data``      — the framed bytes, in input order.
    ``bounds``    — int64 array of n+1 offsets; message i occupies
                    ``data[bounds[i]:bounds[i+1]]`` *including* framing.
    ``prefix_lens`` — per-message framing-prefix length (int64 array) or
                    None when the framing has no prefix.
    ``suffix_len`` — framing suffix length (0, or 1 for line/nul).
    ``ack_cb``    — durability ack hook (or None, the usual case): a
                    replayed spill record's block carries the callback
                    the sink fires once the bytes are flushed/sent
                    (``outputs.ack_item``) — only then does the WAL's
                    replay cursor advance (durability/manager.py).
    """

    __slots__ = ("data", "bounds", "prefix_lens", "suffix_len", "ack_cb")

    def __init__(self, data: bytes, bounds: np.ndarray,
                 prefix_lens: Optional[np.ndarray] = None,
                 suffix_len: int = 0, ack_cb=None):
        self.data = data
        self.bounds = bounds
        self.prefix_lens = prefix_lens
        self.suffix_len = suffix_len
        self.ack_cb = ack_cb

    def __len__(self) -> int:
        return len(self.bounds) - 1

    def iter_framed(self) -> Iterator[bytes]:
        data, b = self.data, self.bounds
        for i in range(len(b) - 1):
            yield data[b[i]:b[i + 1]]

    def iter_unframed(self) -> Iterator[bytes]:
        """Per-message payloads with framing stripped (what a sink that
        ignores framing — kafka — would have received)."""
        data, b, suf = self.data, self.bounds, self.suffix_len
        pre = self.prefix_lens
        for i in range(len(b) - 1):
            start = b[i] + (int(pre[i]) if pre is not None else 0)
            yield data[start:b[i + 1] - suf]
