"""Device-decode circuit breaker: injected device faults must degrade
the batch handler to the scalar oracle with byte-identical output and
zero message loss, then recover after the cooldown."""

import io
import queue

import pytest

from flowgger_tpu.config import Config
from flowgger_tpu.tpu.breaker import CLOSED, HALF_OPEN, OPEN, DecodeBreaker
from flowgger_tpu.utils import faultinject
from flowgger_tpu.utils.metrics import registry

pytestmark = pytest.mark.faults

LINES = [
    b"<23>1 2015-08-05T15:53:45.637824Z host-a app 69 42 - the quick brown fox",
    b"<165>1 2003-10-11T22:14:15.003Z mymachine evntslog - ID47 "
    b'[exampleSDID@32473 iut="3" eventSource="App"] BOMAn application event',
    b"not a valid syslog line at all",
    b"<13>1 2024-01-01T00:00:00Z h app p m - plain message",
    b"",
]


@pytest.fixture(autouse=True)
def _clean():
    registry.reset()
    faultinject.reset()
    yield
    faultinject.reset()


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# DecodeBreaker state machine
# ---------------------------------------------------------------------------

def test_breaker_trips_after_consecutive_failures():
    clock = FakeClock()
    b = DecodeBreaker(failures=3, cooldown_ms=1000, clock=clock)
    assert b.state == CLOSED and b.allow()
    for _ in range(2):
        b.record_failure(RuntimeError("xla"))
    assert b.state == CLOSED  # below threshold
    b.record_failure(RuntimeError("xla"))
    assert b.state == OPEN
    assert not b.allow()  # cooldown not elapsed
    assert registry.get_gauge("device_breaker_state") == 1
    assert registry.get("breaker_trips") == 1


def test_breaker_success_resets_consecutive_count():
    b = DecodeBreaker(failures=2, clock=FakeClock())
    b.record_failure(RuntimeError("x"))
    b.record_success()
    b.record_failure(RuntimeError("x"))
    assert b.state == CLOSED  # never two in a row


def test_breaker_half_open_probe_recovers():
    clock = FakeClock()
    b = DecodeBreaker(failures=1, cooldown_ms=1000, clock=clock)
    b.record_failure(RuntimeError("x"))
    assert b.state == OPEN
    clock.t += 1.5  # past cooldown
    assert b.allow()  # this call IS the probe
    assert b.state == HALF_OPEN
    assert not b.allow()  # only one probe at a time
    b.record_success()
    assert b.state == CLOSED
    assert registry.get("breaker_recoveries") == 1
    assert registry.get_gauge("device_breaker_state") == 0


def test_breaker_failed_probe_reopens():
    clock = FakeClock()
    b = DecodeBreaker(failures=1, cooldown_ms=1000, clock=clock)
    b.record_failure(RuntimeError("x"))
    clock.t += 1.5
    assert b.allow()
    b.record_failure(RuntimeError("probe died"))
    assert b.state == OPEN
    clock.t += 0.5
    assert not b.allow()  # cooldown restarted from the failed probe


def test_breaker_trips_on_sustained_fallback_ratio():
    b = DecodeBreaker(failures=99, window=3, fallback_ratio=0.5,
                      clock=FakeClock())
    for _ in range(2):
        b.observe_batch(10, 9)
    assert b.state == CLOSED  # window not yet full
    b.observe_batch(10, 9)
    assert b.state == OPEN
    # one healthy batch inside the window prevents the trip
    b2 = DecodeBreaker(failures=99, window=3, fallback_ratio=0.5,
                       clock=FakeClock())
    for fb in (9, 1, 9):
        b2.observe_batch(10, fb)
    assert b2.state == CLOSED


def test_breaker_ratio_trip_not_cured_by_healthy_probe():
    """A ratio trip means the device round-trip is wasted work, not that
    the device is broken — a successful probe whose batch is still
    nearly-all-fallback must re-open instead of flapping closed."""
    clock = FakeClock()
    b = DecodeBreaker(failures=99, window=2, fallback_ratio=0.5,
                      cooldown_ms=1000, clock=clock)
    b.observe_batch(10, 9)
    b.observe_batch(10, 9)
    assert b.state == OPEN
    clock.t += 1.5
    assert b.allow()  # probe
    b.observe_batch(10, 9)  # probe batch still 90% fallback
    b.record_success()
    assert b.state == OPEN  # not cured: stays open for another cooldown
    # a probe whose batch genuinely uses the device tier closes it
    clock.t += 1.5
    assert b.allow()
    b.observe_batch(10, 1)
    b.record_success()
    assert b.state == CLOSED


def test_breaker_config_gating():
    assert DecodeBreaker.from_config(Config.from_string(
        "[input]\ntpu_breaker = false\n")) is None
    b = DecodeBreaker.from_config(Config.from_string(
        "[input]\ntpu_breaker_failures = 7\ntpu_breaker_cooldown_ms = 9\n"
        "tpu_breaker_window = 5\ntpu_breaker_fallback_ratio = 0.5\n"))
    assert (b.failures, b.cooldown_ms, b.window, b.fallback_ratio) == (
        7, 9, 5, 0.5)
    from flowgger_tpu.config import ConfigError

    with pytest.raises(ConfigError, match="fallback_ratio"):
        DecodeBreaker.from_config(Config.from_string(
            "[input]\ntpu_breaker_fallback_ratio = 1.5\n"))


# ---------------------------------------------------------------------------
# BatchHandler degradation: byte-identical output, no loss
# ---------------------------------------------------------------------------

def _run_handler(fault_spec=None, breaker_cfg="", lines=None, repeats=4):
    """Feed the same stream through a BatchHandler (rfc5424 block route,
    passthrough encoder: pure host encode after the device decode) and
    return the drained sink items as flat bytes."""
    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.passthrough import PassthroughEncoder
    from flowgger_tpu.mergers import LineMerger
    from flowgger_tpu.outputs import stream_bytes
    from flowgger_tpu.tpu.batch import BatchHandler

    faultinject.reset()
    if fault_spec:
        faultinject.configure({"device_decode": fault_spec})
    cfg = Config.from_string(
        "[input]\ntpu_batch_size = 4\n" + breaker_cfg)
    tx = queue.Queue()
    merger = LineMerger()
    handler = BatchHandler(tx, RFC5424Decoder(cfg), PassthroughEncoder(cfg),
                           cfg, fmt="rfc5424", start_timer=False,
                           merger=merger)
    chunk = b"".join(ln + b"\n" for ln in (lines or LINES))
    for _ in range(repeats):  # one device batch per cycle
        handler.ingest_chunk(chunk)
        handler.flush()
    out = b""
    while not tx.empty():
        data, _ = stream_bytes(tx.get_nowait(), merger)
        out += data
    return out, handler


def test_device_fault_output_byte_identical():
    """Acceptance: with a device fault every other batch, sink bytes are
    identical to the fault-free run — the breaker degrades, nothing is
    lost, and the gauge shows the transition."""
    clean, _ = _run_handler()
    registry.reset()
    faulty, handler = _run_handler(
        fault_spec="every:2",
        breaker_cfg="tpu_breaker_failures = 2\n"
                    "tpu_breaker_cooldown_ms = 3600000\n")
    assert faulty == clean and clean.count(b"\n") >= 8
    assert handler._breaker.state == OPEN
    assert registry.get("breaker_trips") == 1
    assert registry.get("device_decode_errors") >= 2
    assert registry.get_gauge("device_breaker_state") == 1
    # transitions were recorded (observed in metrics + history)
    assert [(a, b) for _, a, b in handler._breaker.transitions] == [
        (CLOSED, OPEN)]


def test_device_fault_every_batch_full_scalar():
    """failures=1 + fault on the first check: everything decodes through
    the oracle from the first batch on; output still identical."""
    clean, _ = _run_handler()
    registry.reset()
    faulty, handler = _run_handler(
        fault_spec="first:1000",
        breaker_cfg="tpu_breaker_failures = 1\n"
                    "tpu_breaker_cooldown_ms = 3600000\n")
    assert faulty == clean
    assert handler._breaker.state == OPEN


def test_breaker_disabled_propagates_device_fault():
    with pytest.raises(faultinject.InjectedFault):
        _run_handler(fault_spec="first:1000",
                     breaker_cfg="tpu_breaker = false\n")


def test_breaker_open_skips_device_checks():
    """Once open, batches bypass the device tier entirely: the fault
    site stops being consulted (no wasted device dispatches)."""
    _, handler = _run_handler(
        fault_spec="first:1000",
        breaker_cfg="tpu_breaker_failures = 1\n"
                    "tpu_breaker_cooldown_ms = 3600000\n")
    import flowgger_tpu.utils.faultinject as fi

    checks_when_open = fi._plan.count("device_decode")
    # a fresh stream through the (still open) handler adds no checks
    handler.ingest_chunk(b"".join(ln + b"\n" for ln in LINES))
    handler.flush()
    assert fi._plan.count("device_decode") == checks_when_open


def test_auto_format_scalar_fallback_byte_identical():
    """auto_tpu: the breaker fallback classifies per line host-side and
    uses each class's oracle — mixed-format streams stay byte-identical
    when degraded."""
    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.ltsv import LTSVEncoder
    from flowgger_tpu.mergers import LineMerger
    from flowgger_tpu.outputs import stream_bytes
    from flowgger_tpu.tpu.batch import BatchHandler

    mixed = [
        b"<23>1 2015-08-05T15:53:45.637824Z host-a app 69 42 - rfc5424 row",
        b'{"version":"1.1","host":"h","short_message":"gelf row",'
        b'"timestamp":1438790025.5}',
        b"time:[10/Oct/2000:13:55:36 -0700]\thost:10.0.0.1\tmsg:ltsv row",
        b"<34>Oct 11 22:14:15 mymachine su: legacy 3164 row",
    ] * 6

    def run(spec, breaker_cfg=""):
        faultinject.reset()
        if spec:
            faultinject.configure({"device_decode": spec})
        cfg = Config.from_string("[input]\ntpu_batch_size = 6\n" + breaker_cfg)
        tx = queue.Queue()
        merger = LineMerger()
        h = BatchHandler(tx, RFC5424Decoder(cfg), LTSVEncoder(cfg), cfg,
                         fmt="auto", start_timer=False, merger=merger)
        h.ingest_chunk(b"".join(ln + b"\n" for ln in mixed))
        h.flush()
        out = b""
        while not tx.empty():
            data, _ = stream_bytes(tx.get_nowait(), merger)
            out += data
        return out

    clean = run(None)
    degraded = run("first:1000",
                   "tpu_breaker_failures = 1\n"
                   "tpu_breaker_cooldown_ms = 3600000\n")
    assert degraded == clean and clean.count(b"\n") == len(mixed)


def test_breaker_recovers_via_half_open_probe_in_handler():
    """End-to-end recovery: trip on injected faults, wait out a tiny
    cooldown, and the next batch probes the device path and closes the
    breaker again."""
    import time

    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.passthrough import PassthroughEncoder
    from flowgger_tpu.mergers import LineMerger
    from flowgger_tpu.tpu.batch import BatchHandler

    faultinject.configure({"device_decode": "first:2"})
    cfg = Config.from_string(
        "[input]\ntpu_batch_size = 4\ntpu_breaker_failures = 2\n"
        "tpu_breaker_cooldown_ms = 50\n")
    tx = queue.Queue()
    handler = BatchHandler(tx, RFC5424Decoder(cfg), PassthroughEncoder(cfg),
                           cfg, fmt="rfc5424", start_timer=False,
                           merger=LineMerger())
    stream = b"".join(ln + b"\n" for ln in LINES)
    for _ in range(2):  # faults 1..2: each batch fails at dispatch
        handler.ingest_chunk(stream)
        handler.flush()
    assert handler._breaker.state == OPEN
    time.sleep(0.1)  # cooldown elapses
    handler.ingest_chunk(stream)
    handler.flush()  # probe succeeds (fault plan exhausted after 2)
    assert handler._breaker.state == CLOSED
    states = [(a, b) for _, a, b in handler._breaker.transitions]
    assert states == [(CLOSED, OPEN), (OPEN, HALF_OPEN), (HALF_OPEN, CLOSED)]
    assert registry.get("breaker_recoveries") == 1
    # every line of both streams made it out
    n = 0
    while not tx.empty():
        tx.get_nowait()
        n += 1
    assert n > 0


# ---------------------------------------------------------------------------
# Compile watchdog (device-encode tier)
# ---------------------------------------------------------------------------

def _isolated_watchdog(monkeypatch):
    """Give the test its own single-flight semaphore and slot table so
    real background kernel compiles from other tests can't queue it."""
    import threading

    from flowgger_tpu.tpu import device_common as dc

    monkeypatch.setattr(dc, "_compile_sema", threading.Semaphore(1))
    monkeypatch.setattr(dc, "_compile_active_box", {})
    monkeypatch.setattr(dc, "_compile_slots", {})
    monkeypatch.setattr(dc, "_compile_ready", set())
    return dc


def test_compile_watchdog_declines_then_lands(monkeypatch):
    """A slow kernel compile times out (decline), keeps running in the
    background, and once landed the same slot serves calls inline."""
    import threading
    import time

    dc = _isolated_watchdog(monkeypatch)
    monkeypatch.setenv(dc.COMPILE_TIMEOUT_ENV, "50")
    gate = threading.Event()
    calls = []

    def slow_compile():
        calls.append(1)
        gate.wait(5.0)
        return 42

    with pytest.raises(dc.CompileTimeout):
        dc.guarded_compile_call("test:slow-kernel", slow_compile)
    # still compiling: instant decline, no second worker spawned
    with pytest.raises(dc.CompileTimeout):
        dc.guarded_compile_call("test:slow-kernel", slow_compile)
    assert len(calls) == 1
    assert registry.get("device_encode_compile_declines") == 2
    gate.set()
    deadline = time.time() + 5
    while time.time() < deadline:
        try:
            assert dc.guarded_compile_call(
                "test:slow-kernel", slow_compile) == 42
            break
        except dc.CompileTimeout:
            time.sleep(0.02)
    else:
        pytest.fail("background compile never landed")
    # warm now: served inline without a worker thread
    n = len(calls)
    assert dc.guarded_compile_call("test:slow-kernel", slow_compile) == 42
    assert len(calls) == n + 1


def test_compile_watchdog_disabled_by_env(monkeypatch):
    dc = _isolated_watchdog(monkeypatch)
    monkeypatch.setenv(dc.COMPILE_TIMEOUT_ENV, "0")
    assert dc.guarded_compile_call("test:inline", lambda: "x") == "x"


def test_compile_watchdog_busy_declines_fresh_slot_instantly(monkeypatch):
    """While one compile holds the single-flight semaphore, a FRESH
    slot declines immediately (its queued compile cannot start before
    any deadline) instead of stalling the stream a full timeout — and
    once the semaphore frees, the queued compile lands normally."""
    import threading
    import time

    dc = _isolated_watchdog(monkeypatch)
    monkeypatch.setenv(dc.COMPILE_TIMEOUT_ENV, "30000")
    gate = threading.Event()

    def wedged():
        gate.wait(10.0)
        return "first"

    with pytest.raises(dc.CompileTimeout):
        dc.guarded_compile_call("test:wedged", wedged, timeout_s=0.05)
    t0 = time.monotonic()
    with pytest.raises(dc.CompileTimeout):
        # 30s deadline, but the decline must come back instantly: the
        # wedged compile above still holds the semaphore
        dc.guarded_compile_call("test:fresh", lambda: "second")
    assert time.monotonic() - t0 < 5.0
    gate.set()
    deadline = time.time() + 5
    while time.time() < deadline:
        try:
            assert dc.guarded_compile_call(
                "test:fresh", lambda: "second") == "second"
            break
        except dc.CompileTimeout:
            time.sleep(0.02)
    else:
        pytest.fail("queued compile never landed after the semaphore freed")


def test_compile_watchdog_propagates_errors(monkeypatch):
    dc = _isolated_watchdog(monkeypatch)
    monkeypatch.setenv(dc.COMPILE_TIMEOUT_ENV, "5000")

    def boom():
        raise RuntimeError("xla says no")

    with pytest.raises(RuntimeError, match="xla says no"):
        dc.guarded_compile_call("test:boom", boom)


# ---------------------------------------------------------------------------
# End-to-end pipeline acceptance (config-driven [faults] table)
# ---------------------------------------------------------------------------

def _run_pipeline(tmp_path, name, faults_toml=""):
    from flowgger_tpu.pipeline import Pipeline
    from flowgger_tpu.splitters import LineSplitter

    faultinject.reset()
    out = tmp_path / name
    config = Config.from_string(
        '[input]\ntype = "stdin"\nformat = "rfc5424_tpu"\n'
        "tpu_batch_size = 4\ntpu_breaker_failures = 1\n"
        "tpu_breaker_cooldown_ms = 3600000\n"
        '[output]\ntype = "file"\nformat = "passthrough"\n'
        f'framing = "line"\nfile_path = "{out}"\n' + faults_toml)
    pipeline = Pipeline(config)
    threads = pipeline.start_output()
    if not isinstance(threads, list):
        threads = [threads]
    handler = pipeline.handler_factory()
    stream = b"".join(ln + b"\n" for ln in LINES) * 6
    LineSplitter().run(io.BytesIO(stream), handler)
    pipeline._drain(threads)
    return out.read_bytes(), pipeline


def test_e2e_fault_injected_run_matches_clean_run(tmp_path):
    """ISSUE acceptance: device-decode exception every N batches → sink
    output byte-identical to a fault-free run, breaker state transitions
    visible in metrics."""
    clean_bytes, _ = _run_pipeline(tmp_path, "clean.log")
    registry.reset()
    faulty_bytes, pipeline = _run_pipeline(
        tmp_path, "faulty.log",
        '[faults]\ndevice_decode = "every:2"\n')
    assert faulty_bytes == clean_bytes and clean_bytes
    handler = pipeline._handlers[0]
    assert handler._breaker.state == OPEN
    assert registry.get("breaker_trips") == 1
    assert registry.get_gauge("device_breaker_state") == 1
    snap = registry.snapshot()
    assert snap["device_breaker_state"] == 1  # gauge visible in reports
