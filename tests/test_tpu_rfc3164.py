"""Differential tests: columnar RFC3164 fast path vs the scalar oracle."""

import random

from flowgger_tpu.decoders import DecodeError
from flowgger_tpu.decoders.rfc3164 import RFC3164Decoder
from flowgger_tpu.tpu.batch import _decode_rfc3164_batch

ORACLE = RFC3164Decoder()

CORPUS = [
    "<34>Oct 11 22:14:15 mymachine1 su: 'su root' failed for lonvick",
    "Oct 11 22:14:15 mymachine1 su: body",
    "<13>Aug  6 11:15:24 host9 appname 69 42 some test message",   # classic dbl-space day
    "Aug  6 11:15:24 host.example.com single message",
    "<0>Jan  1 00:00:00 h1 x",
    "<191>Dec 31 23:59:59 server-42 end of year",
    "Feb 28 12:00:00 web01 ok",
    "Feb 29 12:00:00 web01 leap-day-depends-on-year",
    "Mar  5 07:08:09 10.0.0.1 numeric host",
    "<34>Oct 11 22:14:15 host4 trailing spaces in msg  here",  # dbl space in msg
    "<34>Oct 11 22:14:15 host4 msg with tab\there",
    "Oct 11 22:14:15 UTC host-after-tz looks like tz",   # tz token -> scalar path
    "Oct 11 22:14:15 Europe/Paris msg after tz",
    "Oct 11 22:14:15 EST5EDT myhost hello",              # digit-bearing tz name
    "Oct 11 22:14:15 Etc/GMT+1 myhost hello",
    "Oct 11 22:14:15 GMT0 myhost hello",
    "Oct 11 22:14:15 host6 a\x1cb",                      # FS separator byte
    "Oct 11 22:14:15 host6 trailing-fs\x1d",
    "Oct 11 22:14:15 localtime after-alias",             # zoneinfo oddity
    "Oct 11 22:14:15 posixrules after-alias",
    "Oct 11 22:14:15 SERVER01 uppercase host",           # conservative fallback
    "2019 Mar 27 12:09:39 hostyear with year",
    "mymachine: Mar 27 12:09:39: custom layout message",
    "<34>mymachine: Mar 27 12:09:39: custom with pri",
    "Oct 11 22:14:15 onlyhost",                           # 4 tokens, empty msg
    "Oct 11 22:14:15",                                    # too few tokens
    "Oct 32 22:14:15 h m",                                # bad day
    "Oct 11 25:14:15 h m",                                # bad hour
    "not a syslog line",
    "",
    "<abc>Oct 11 22:14:15 h m",
    "<13>Oct 11 2:14:15 h m",                             # unpadded hour -> lenient?
    "Oct 11 22:14:15 host msg ünïcode",
    "\tOct 11 22:14:15 h m",
]


def run_both(lines):
    raw = [ln.encode("utf-8") for ln in lines]
    results = _decode_rfc3164_batch(raw, 512)
    pairs = []
    for ln, res in zip(lines, results):
        kernel = ("rec", res.record) if res.record is not None else ("err", res.error)
        try:
            oracle = ("rec", ORACLE.decode(ln))
        except DecodeError as e:
            oracle = ("err", str(e))
        pairs.append((ln, kernel, oracle))
    return pairs


def assert_identical(lines):
    for ln, kernel, oracle in run_both(lines):
        assert kernel == oracle, (
            f"divergence on {ln!r}:\n  kernel: {kernel}\n  oracle: {oracle}")


def test_corpus_differential(capsys):
    assert_identical(CORPUS)


def test_fast_path_coverage():
    import jax.numpy as jnp
    import numpy as np

    from flowgger_tpu.tpu import pack, rfc3164
    from flowgger_tpu.utils.timeparse import current_year_utc

    clean = [ln for ln in CORPUS[:9]]
    raw = [ln.encode() for ln in clean]
    batch, lens, chunk, starts, orig, n = pack.pack_lines_2d(raw, 256)
    out = rfc3164.decode_rfc3164_jit(jnp.asarray(batch), jnp.asarray(lens),
                                     np.int32(current_year_utc()))
    okf = np.asarray(out["ok"])[:n]
    assert okf.mean() >= 0.7, list(zip(clean, okf))


def test_fuzz_differential(capsys):
    rng = random.Random(3164)
    alphabet = list(" <>JanFebOct0123456789:.-host/U\t")
    base = "<34>Oct 11 22:14:15 host.example.com su: body text here"
    lines = []
    for _ in range(300):
        cs = list(base)
        for _ in range(rng.randint(1, 6)):
            i = rng.randrange(len(cs)) if cs else 0
            op = rng.random()
            if op < 0.4 and cs:
                cs[i] = rng.choice(alphabet)
            elif op < 0.7:
                cs.insert(i, rng.choice(alphabet))
            elif cs:
                del cs[i]
        lines.append("".join(cs))
    assert_identical(lines)


def test_autodetect_uses_rfc3164_kernel():
    from flowgger_tpu.tpu.batch import _decode_auto_batch

    mixed = [
        b"<34>Oct 11 22:14:15 legacyhost1 su: legacy message",
        b"<13>1 2015-08-05T15:53:45Z host5424 app 1 2 - new style",
    ]
    results = _decode_auto_batch(mixed, 512)
    assert results[0].record.hostname == "legacyhost1"
    assert results[1].record.hostname == "host5424"


def test_embedded_newline_falls_back():
    """A message byte-stream containing a raw LF (reachable via NUL
    framing or UDP datagrams, never via line framing) must take the
    scalar oracle: str.split() treats LF as whitespace and rebuilds the
    message with single spaces."""
    import queue

    from flowgger_tpu.config import Config
    from flowgger_tpu.block import EncodedBlock
    from flowgger_tpu.encoders.gelf import GelfEncoder
    from flowgger_tpu.mergers import LineMerger
    from flowgger_tpu.tpu.batch import BatchHandler

    cfg = Config.from_string("")
    enc = GelfEncoder(cfg)
    lines = [b"<34>Aug  5 15:53:45 host app[98\n: embedded lf",
             b"<34>Aug  5 15:53:45 host app: clean"]
    want = [enc.encode(ORACLE.decode(ln.decode())) for ln in lines]
    assert b"app[98 : embedded lf" in want[0]  # LF became a space
    tx = queue.Queue()
    h = BatchHandler(tx, ORACLE, enc, cfg, fmt="rfc3164",
                     start_timer=False, merger=LineMerger())
    for ln in lines:
        h.handle_bytes(ln)
    h.flush()
    got = []
    while not tx.empty():
        item = tx.get_nowait()
        got.extend(item.iter_unframed() if isinstance(item, EncodedBlock)
                   else [item])
    assert got == want
