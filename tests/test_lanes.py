"""Multi-device lane dispatch + shape bucketing + compile cache/prewarm.

Lane ordering/fencing tests run on any device count (two lanes on one
device still exercise the round-robin, the FIFO sequencer, and the
fence-all paths); the genuinely multi-device placement checks skip on a
single-device backend.  ci.sh additionally runs this module under
``XLA_FLAGS=--xla_force_host_platform_device_count=2``.
"""

import queue
import threading
import time

import numpy as np
import pytest

from flowgger_tpu.config import Config, ConfigError
from flowgger_tpu.tpu.overlap import LaneSet, resolve_lanes
from flowgger_tpu.utils import faultinject
from flowgger_tpu.utils.metrics import registry


@pytest.fixture(autouse=True)
def _clean():
    registry.reset()
    faultinject.reset()
    yield
    faultinject.reset()


# ---------------------------------------------------------------------------
# LaneSet: FIFO sequencing across lanes
# ---------------------------------------------------------------------------

def test_laneset_emits_in_submit_order_across_lanes():
    """Lanes compute concurrently with wildly skewed latencies; the
    sequencer must still run every emit closure in submit order."""
    done = []

    def pop(item, lane):
        time.sleep(0.004 if item % 3 == 0 else 0.0)
        return lambda: done.append(item)

    ls = LaneSet(2, pop, lanes=3)
    for i in range(30):
        ls.submit(ls.next_lane(), i)
    ls.fence()
    assert done == list(range(30))
    ls.close()


def test_laneset_single_lane_matches_window_contract():
    done = []
    ls = LaneSet(2, lambda item, lane: (lambda: done.append(item)), lanes=1)
    for i in range(12):
        ls.submit(ls.next_lane(), i)
    ls.fence()
    assert done == list(range(12))
    assert ls.pending() == 0
    ls.close()


def test_laneset_none_emit_is_allowed():
    seen = []

    def pop(item, lane):
        seen.append(item)
        return None  # nothing to emit; ticket must still release

    ls = LaneSet(2, pop, lanes=2)
    for i in range(8):
        ls.submit(ls.next_lane(), i)
    ls.fence()
    assert sorted(seen) == list(range(8))
    ls.close()


def test_laneset_pop_exception_releases_sequencer_and_ferries():
    """A fail-fast pop (breaker disabled contract) must not wedge the
    lanes behind it: its ticket releases, later batches emit in order,
    and the exception surfaces on the ingest thread at that lane's next
    submit/fence (the InflightWindow ferry contract).  The pops hold on
    a gate until every batch is submitted, so the ferry target here is
    deterministically the fence."""
    done = []
    gate = threading.Event()

    def pop(item, lane):
        gate.wait(5.0)
        if item == 3:
            raise RuntimeError("device died")
        return lambda: done.append(item)

    ls = LaneSet(4, pop, lanes=2)
    for i in range(8):
        ls.submit(ls.next_lane(), i)
    gate.set()
    with pytest.raises(RuntimeError, match="device died"):
        ls.fence()
    ls.fence()  # consumed; lane set stays usable
    assert done == [0, 1, 2, 4, 5, 6, 7]
    ls.submit(ls.next_lane(), 9)
    ls.fence()
    assert done[-1] == 9
    ls.close()


def test_laneset_ferried_submit_raise_releases_ticket():
    """A submit that re-raises a ferried exception issued a ticket the
    window never queued — that ticket must release or the sequencer
    wedges every later batch behind a turn that can never come."""
    done = []

    def pop(item, lane):
        if item == "boom":
            raise RuntimeError("boom")
        return lambda: done.append(item)

    ls = LaneSet(2, pop, lanes=1)
    ls.submit(0, "boom")
    deadline = time.time() + 5
    while ls.pending() and time.time() < deadline:
        time.sleep(0.01)
    with pytest.raises(RuntimeError, match="boom"):
        ls.submit(0, "a")  # ferry re-raised; "a" never queued
    ls.submit(0, "b")
    ls.submit(0, "c")
    ls.fence()
    assert done == ["b", "c"]
    ls.close()


def test_emit_failure_degrades_to_scalar_at_position():
    """An exception during the sequenced emit (sink hiccup) with the
    breaker armed must re-decode the batch through the scalar oracle at
    its position — not ferry to the ingest thread and lose the lines."""
    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.passthrough import PassthroughEncoder
    from flowgger_tpu.mergers import LineMerger
    from flowgger_tpu.outputs import stream_bytes
    from flowgger_tpu.tpu.batch import BatchHandler

    class FlakyQueue(queue.Queue):
        fails = 1

        def put(self, item, *a, **k):
            if self.fails:
                self.fails -= 1
                raise RuntimeError("sink hiccup")
            super().put(item, *a, **k)

    cfg = Config.from_string(
        "[input]\ntpu_batch_size = 5\ntpu_inflight = 2\ntpu_lanes = 2\n")
    tx = FlakyQueue()
    merger = LineMerger()
    handler = BatchHandler(tx, RFC5424Decoder(cfg), PassthroughEncoder(cfg),
                           cfg, fmt="rfc5424", start_timer=False,
                           merger=merger)
    valid = [ln for ln in LINES if ln != b"not a valid syslog line at all"]
    for _ in range(6):
        handler.ingest_chunk(b"".join(ln + b"\n" for ln in valid))
    handler.flush()  # must not raise: the emit failure degrades
    handler.close()
    out = b""
    while not tx.empty():
        data, _ = stream_bytes(tx.get_nowait(), merger)
        out += data
    # the failed block re-emitted through the scalar oracle: every line
    # still present exactly once, in order
    assert out == b"".join(ln + b"\n" for ln in valid) * 6
    assert registry.get("device_decode_errors") >= 1


def test_laneset_fence_fences_all_lanes():
    gates = [threading.Event(), threading.Event()]
    done = []

    def pop(item, lane):
        gates[lane].wait(5.0)
        return lambda: done.append(item)

    ls = LaneSet(2, pop, lanes=2)
    ls.submit(0, "a")
    ls.submit(1, "b")
    t = threading.Thread(target=ls.fence)
    t.start()
    time.sleep(0.05)
    assert t.is_alive()          # both lanes still in flight
    gates[0].set()
    time.sleep(0.05)
    assert t.is_alive()          # lane 1 still holds the fence
    gates[1].set()
    t.join(timeout=5)
    assert not t.is_alive() and done == ["a", "b"]
    ls.close()


def test_laneset_depth_gauges():
    ls = LaneSet(2, lambda item, lane: None, lanes=2)
    for i in range(8):
        ls.submit(ls.next_lane(), i)
    ls.fence()
    snap = registry.snapshot()
    assert snap.get("lane0_depth") == 0 and snap.get("lane1_depth") == 0
    assert snap.get("lane_depth") == 0 and snap.get("inflight_depth") == 0
    ls.close()


# ---------------------------------------------------------------------------
# lane resolution (config -> lanes, devices)
# ---------------------------------------------------------------------------

def test_resolve_lanes_auto_is_single_on_cpu():
    lanes, devs = resolve_lanes(Config.from_string(""), "auto")
    assert lanes == 1 and devs == [None]


def test_resolve_lanes_explicit_engages_on_cpu():
    import jax

    lanes, devs = resolve_lanes(
        Config.from_string("[input]\ntpu_lanes = 2\n"), "auto")
    assert lanes == 2 and len(devs) == 2
    # more lanes than devices cycle over them
    n = len(jax.local_devices())
    lanes, devs = resolve_lanes(
        Config.from_string(f"[input]\ntpu_lanes = {n + 1}\n"), "off")
    assert lanes == n + 1 and devs[n] == devs[0]


def test_resolve_lanes_validation():
    with pytest.raises(ConfigError):
        resolve_lanes(Config.from_string("[input]\ntpu_lanes = 0\n"))
    with pytest.raises(ConfigError):
        resolve_lanes(Config.from_string("[input]\ntpu_lanes = 2\n"), "on")
    # explicit single lane never conflicts with the mesh
    assert resolve_lanes(
        Config.from_string("[input]\ntpu_lanes = 1\n"), "on") == (1, [None])


def test_handler_config_validation():
    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.passthrough import PassthroughEncoder
    from flowgger_tpu.mergers import LineMerger
    from flowgger_tpu.tpu.batch import BatchHandler

    for bad in ("tpu_lanes = -2\n", "tpu_shape_buckets = 0\n",
                'tpu_lanes = 2\ntpu_mesh = "on"\n'):
        cfg = Config.from_string("[input]\n" + bad)
        with pytest.raises(ConfigError):
            BatchHandler(queue.Queue(), RFC5424Decoder(cfg),
                         PassthroughEncoder(cfg), cfg, fmt="rfc5424",
                         start_timer=False, merger=LineMerger())


# ---------------------------------------------------------------------------
# BatchHandler across lanes: ordering + byte identity
# ---------------------------------------------------------------------------

LINES = [
    b"<23>1 2015-08-05T15:53:45.637824Z host-a app 69 42 - the quick brown fox",
    b"<165>1 2003-10-11T22:14:15.003Z mymachine evntslog - ID47 "
    b'[exampleSDID@32473 iut="3" eventSource="App"] BOMAn application event',
    b"not a valid syslog line at all",
    b"<13>1 2024-01-01T00:00:00Z h app p m - plain message",
    b"<13>1 2024-06-01T00:00:00.5Z h2 app2 p m - second message",
]


def _stream_handler(lanes, fault_spec=None, breaker_cfg="", repeats=12,
                    extra_cfg=""):
    """Feed repeats x LINES through the rfc5424 block route with the
    given lane count; returns (drained sink bytes in queue order,
    handler)."""
    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.passthrough import PassthroughEncoder
    from flowgger_tpu.mergers import LineMerger
    from flowgger_tpu.outputs import stream_bytes
    from flowgger_tpu.tpu.batch import BatchHandler

    faultinject.reset()
    if fault_spec:
        faultinject.configure({"device_decode": fault_spec})
    cfg = Config.from_string(
        "[input]\ntpu_batch_size = 5\ntpu_inflight = 2\n"
        + (f"tpu_lanes = {lanes}\n" if lanes else "")
        + breaker_cfg + extra_cfg)
    tx = queue.Queue()
    merger = LineMerger()
    handler = BatchHandler(tx, RFC5424Decoder(cfg), PassthroughEncoder(cfg),
                           cfg, fmt="rfc5424", start_timer=False,
                           merger=merger)
    for _ in range(repeats):  # one device batch per cycle
        handler.ingest_chunk(b"".join(ln + b"\n" for ln in LINES))
    handler.flush()
    handler.close()
    out = b""
    while not tx.empty():
        data, _ = stream_bytes(tx.get_nowait(), merger)
        out += data
    return out, handler


def test_two_lane_stream_matches_single_lane_bytes_and_order():
    single, _ = _stream_handler(lanes=None)
    double, handler = _stream_handler(lanes=2)
    assert double == single and single.count(b"\n") >= 48
    assert handler._window.pending() == 0
    # both lanes actually carried traffic
    snap = registry.snapshot()
    assert snap.get("lane0_rows", 0) > 0 and snap.get("lane1_rows", 0) > 0


def test_three_lanes_on_fewer_devices_still_byte_identical():
    single, _ = _stream_handler(lanes=None)
    tripled, _ = _stream_handler(lanes=3)
    assert tripled == single


@pytest.mark.faults
def test_device_fault_mid_stream_keeps_order_and_bytes_across_lanes():
    """A device killed mid-stream on one lane must leave the merger
    output byte-identical: the failed batch re-decodes through the
    scalar oracle at its sequenced position while other lanes' batches
    stay put."""
    clean, _ = _stream_handler(lanes=2)
    registry.reset()
    faulty, _ = _stream_handler(
        lanes=2, fault_spec="every:3",
        breaker_cfg="tpu_breaker_failures = 3\n"
                    "tpu_breaker_cooldown_ms = 1\n")
    assert faulty == clean
    assert registry.get("device_decode_errors") >= 2


@pytest.mark.faults
def test_breaker_trip_fences_all_lanes_before_scalar_batches():
    """When the breaker opens mid-stream, later batches take the
    ingest-side scalar path — which must fence EVERY lane first so a
    still-in-flight batch on any lane cannot be overtaken."""
    from flowgger_tpu.tpu.breaker import OPEN

    clean, _ = _stream_handler(lanes=2)
    registry.reset()
    faulty, handler = _stream_handler(
        lanes=2, fault_spec="first:6",
        breaker_cfg="tpu_breaker_failures = 2\n"
                    "tpu_breaker_cooldown_ms = 3600000\n")
    assert faulty == clean
    assert handler._breaker.state == OPEN
    assert registry.get("breaker_trips") == 1


def test_drain_flush_fences_all_lanes():
    """flush(drain=True) + close (the pipeline._drain / SIGTERM path)
    must leave nothing in flight on any lane and the full stream on the
    queue."""
    out, handler = _stream_handler(lanes=3, repeats=8)
    assert handler._window.pending() == 0
    assert out.count(b"\n") == 8 * 4  # 4 valid lines per cycle


def test_multi_device_lanes_place_batches_on_distinct_devices():
    import jax

    if jax.local_device_count() < 2:
        pytest.skip("needs >= 2 devices")
    from flowgger_tpu.tpu import pack
    from flowgger_tpu.tpu.batch import block_submit

    lines = [b"<13>1 2024-01-01T00:00:00Z h a p m - hello %d" % i
             for i in range(64)]
    packed = pack.pack_lines_2d(lines, 128)
    devs = jax.local_devices()[:2]
    handles = [block_submit("rfc5424", packed, device=d) for d in devs]
    for h, d in zip(handles, devs):
        placed = h[5] if len(h) > 5 else h[1]  # batch_dev on the handle
        assert list(placed.devices()) == [d]


# ---------------------------------------------------------------------------
# shape bucketing: byte identity + bounded compile shapes
# ---------------------------------------------------------------------------

def _varied_lines(rng, n):
    out = []
    for i in range(n):
        msg = "x" * rng.randrange(1, 120)
        out.append(
            (f"<13>1 2024-03-0{1 + i % 9}T0{i % 9}:00:0{i % 9}Z h{i} app "
             f"{i} m - {msg}").encode())
    return out


@pytest.mark.parametrize("framing", ["line", "nul", "syslen"])
def test_bucketed_pad_byte_identical_to_exact_pad(framing):
    """Bucketed row padding must not change emitted bytes for any
    merger framing — padding rows are masked (differential vs the
    scalar-oracle-backed single-config stream)."""
    import random

    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.rfc5424 import RFC5424Encoder
    from flowgger_tpu.mergers import LineMerger, NulMerger, SyslenMerger
    from flowgger_tpu.outputs import stream_bytes
    from flowgger_tpu.tpu import pack
    from flowgger_tpu.tpu.batch import BatchHandler

    merger_cls = {"line": LineMerger, "nul": NulMerger,
                  "syslen": SyslenMerger}[framing]

    def run(buckets):
        cfg = Config.from_string(
            "[input]\ntpu_batch_size = 64\ntpu_max_line_len = 256\n"
            + (f"tpu_shape_buckets = {buckets}\n" if buckets else ""))
        tx = queue.Queue()
        merger = merger_cls()
        h = BatchHandler(tx, RFC5424Decoder(cfg), RFC5424Encoder(cfg), cfg,
                         fmt="rfc5424", start_timer=False, merger=merger)
        rng = random.Random(7)
        for size in (3, 64, 17, 120, 64, 5):
            h.ingest_chunk(b"".join(
                ln + b"\n" for ln in _varied_lines(rng, size)))
        h.flush()
        h.close()
        out = b""
        while not tx.empty():
            data, _ = stream_bytes(tx.get_nowait(), merger)
            out += data
        return out

    try:
        exact = run(None)          # legacy pow2 buckets
        bucketed = run(2)          # coarse 2-bucket grid
    finally:
        pack.configure_shape_buckets(None)
    assert bucketed == exact and len(exact) > 0


def test_varied_stream_stays_within_bucket_grid():
    """50 varied-length batches through a K-bucket grid must compile at
    most K distinct (rows, max_len) shapes (the distinct_compiled_shapes
    gauge tracks the process-wide set; diff it around the stream)."""
    import random

    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.passthrough import PassthroughEncoder
    from flowgger_tpu.mergers import LineMerger
    from flowgger_tpu.tpu import pack
    from flowgger_tpu.tpu.batch import BatchHandler

    # max_len 256 shares the [*, 256] decode compiles with the framing
    # tests above; batch 512 keeps the 50-batch stream cheap
    cfg = Config.from_string(
        "[input]\ntpu_batch_size = 512\ntpu_max_line_len = 256\n"
        "tpu_shape_buckets = 2\n")
    tx = queue.Queue()
    try:
        h = BatchHandler(tx, RFC5424Decoder(cfg), PassthroughEncoder(cfg),
                         cfg, fmt="rfc5424", start_timer=False,
                         merger=LineMerger())
        grid = pack.active_bucket_grid()
        assert grid is not None and len(grid) <= 2
        rng = random.Random(11)
        before = pack.shapes_seen()
        for _ in range(50):
            n = rng.randrange(1, 512)
            h.ingest_chunk(b"".join(
                ln + b"\n" for ln in _varied_lines(rng, n)))
            h.flush()
        h.close()
        new = {s for s in pack.shapes_seen() - before if s[1] == 256}
        assert 0 < len(new) <= len(grid)
        assert all(rows in grid for rows, _ in new)
        assert registry.get_gauge("distinct_compiled_shapes") >= len(new)
    finally:
        pack.configure_shape_buckets(None)


def test_bucket_grid_shapes():
    from flowgger_tpu.tpu import pack

    assert pack.shape_bucket_grid(3, 16384) == (256, 2048, 16384)
    assert pack.shape_bucket_grid(1, 5000) == (8192,)
    grid = pack.shape_bucket_grid(4, 8192)
    assert grid[0] == 256 and grid[-1] == 8192 and len(grid) <= 4
    try:
        pack.configure_shape_buckets((256, 2048))
        assert pack.bucket_rows(1) == 256
        assert pack.bucket_rows(256) == 256
        assert pack.bucket_rows(257) == 2048
        # beyond the grid top: fall back to pow2 rather than truncate
        assert pack.bucket_rows(5000) == 8192
    finally:
        pack.configure_shape_buckets(None)


# ---------------------------------------------------------------------------
# prewarm + persistent compile cache
# ---------------------------------------------------------------------------

def test_prewarm_compiles_bucket_grid(tmp_path, monkeypatch):
    """Prewarm (device-encode killed: this container can't compile those
    kernels) must land one warm decode per bucket shape and count it."""
    monkeypatch.setenv("FLOWGGER_DEVICE_ENCODE", "0")
    from flowgger_tpu.encoders.passthrough import PassthroughEncoder
    from flowgger_tpu.mergers import LineMerger
    from flowgger_tpu.tpu.device_common import prewarm_kernels

    t = prewarm_kernels(
        "rfc5424", 64, [256, 512],
        encoder=PassthroughEncoder(Config.from_string("")),
        merger=LineMerger())
    t.join(timeout=180)
    assert not t.is_alive()
    assert registry.get("prewarmed_shapes") == 2


def test_handler_prewarms_when_cache_dir_set(tmp_path, monkeypatch):
    """input.tpu_compile_cache_dir implies prewarm-by-default; the cache
    dir is created and populated, and cache monitoring counts traffic."""
    monkeypatch.setenv("FLOWGGER_DEVICE_ENCODE", "0")
    import os

    import jax

    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.passthrough import PassthroughEncoder
    from flowgger_tpu.mergers import LineMerger
    from flowgger_tpu.tpu.batch import BatchHandler

    cache = tmp_path / "xla-cache"
    # max_len 96 is unique to this test: the prewarm must pay a FRESH
    # compile (an in-process jit-cache hit would persist nothing)
    cfg = Config.from_string(
        "[input]\ntpu_batch_size = 64\ntpu_max_line_len = 96\n"
        f'tpu_compile_cache_dir = "{cache}"\n')
    tx = queue.Queue()
    from flowgger_tpu.tpu.device_common import CACHE_KNOBS

    old = {k: getattr(jax.config, k) for k in CACHE_KNOBS}
    try:
        h = BatchHandler(tx, RFC5424Decoder(cfg), PassthroughEncoder(cfg),
                         cfg, fmt="rfc5424", start_timer=False,
                         merger=LineMerger())
        # the prewarm worker compiles decode directly on its own thread
        # (never queued behind a stuck encode compile), so this is just
        # one small [256, 96] compile away
        deadline = time.time() + 90
        while (registry.get("prewarmed_shapes") < 1
               and time.time() < deadline):
            time.sleep(0.1)
        h.close()
        assert registry.get("prewarmed_shapes") >= 1
        assert cache.is_dir() and len(os.listdir(cache)) > 0
        assert (registry.get("compile_cache_hits")
                + registry.get("compile_cache_misses")) > 0
    finally:
        # un-point the process-global cache config from the tmp dir
        # (pytest deletes it) so the rest of the suite doesn't pay
        # serialize+write — or hit ENOENT — on every later compile
        for k, v in old.items():
            jax.config.update(k, v)
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()


@pytest.mark.slow
def test_second_cold_process_hits_cache_with_zero_misses(tmp_path):
    """ISSUE acceptance: with input.tpu_compile_cache_dir set, a second
    cold process of the same config performs 0 fresh top-level kernel
    compiles — every compile request is a cache hit."""
    import json
    import os
    import subprocess
    import sys

    cache = tmp_path / "xla-cache"
    script = r"""
import json, os, queue, sys
from flowgger_tpu.config import Config
from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
from flowgger_tpu.encoders.passthrough import PassthroughEncoder
from flowgger_tpu.mergers import LineMerger
from flowgger_tpu.outputs import stream_bytes
from flowgger_tpu.tpu.batch import BatchHandler
from flowgger_tpu.utils.metrics import registry

cfg = Config.from_string(
    "[input]\ntpu_batch_size = 64\ntpu_max_line_len = 64\n"
    "tpu_shape_buckets = 1\n"
    'tpu_compile_cache_dir = "CACHEDIR"\n'
    'tpu_prewarm = false\n')
tx = queue.Queue()
merger = LineMerger()
h = BatchHandler(tx, RFC5424Decoder(cfg), PassthroughEncoder(cfg), cfg,
                 fmt="rfc5424", start_timer=False, merger=merger)
h.ingest_chunk(b"".join(
    b"<13>1 2024-01-01T00:00:00Z h a p m - msg %d\n" % i
    for i in range(50)))
h.flush(); h.close()
out = b""
while not tx.empty():
    data, _ = stream_bytes(tx.get_nowait(), merger)
    out += data
print(json.dumps({"hits": registry.get("compile_cache_hits"),
                  "misses": registry.get("compile_cache_misses"),
                  "shapes": registry.get_gauge("distinct_compiled_shapes"),
                  "lines": out.count(b"\n")}))
""".replace("CACHEDIR", str(cache).replace("\\", "/"))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "FLOWGGER_DEVICE_ENCODE": "0"}

    def run_once():
        r = subprocess.run([sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stderr
        return json.loads(r.stdout.strip().splitlines()[-1])

    first = run_once()
    second = run_once()
    assert first["lines"] == second["lines"] == 50
    assert first["misses"] > 0           # cold: populated the cache
    assert second["misses"] == 0         # warm: zero fresh compiles
    assert second["hits"] > 0
    assert second["shapes"] == 1         # one bucket -> one shape
