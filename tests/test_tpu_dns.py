"""Differential tests: fixed-grammar columnar DNS decoder + block
routes vs the scalar oracle (flowgger_tpu/decoders/dns.py)."""

import queue

import jax
import pytest

from flowgger_tpu.block import EncodedBlock
from flowgger_tpu.config import Config
from flowgger_tpu.decoders import DecodeError, DNSDecoder
from flowgger_tpu.encoders.gelf import GelfEncoder
from flowgger_tpu.encoders.ltsv import LTSVEncoder
from flowgger_tpu.mergers import LineMerger, NulMerger, SyslenMerger
from flowgger_tpu.tpu.batch import BatchHandler, _decode_dns_batch

CFG = Config.from_string("[input]\ntpu_max_line_len = 160\n")
ORACLE = DNSDecoder()

CORPUS = [
    b"1438790025.123\t10.0.0.9\texample.com.\tA\tNOERROR\t523",
    b"1438790025\t192.168.1.1\tfoo.bar.baz.\tAAAA\tNXDOMAIN\t10923",
    b"1438790026.5\t2001:db8::1\twww.test.\tTXT\tSERVFAIL\t0",
    b"1438790026\t10.0.0.9\texample.com.\t28\t3\t99",
    b"1438790027.25\thost-a\tcache.hit.\tPTR\tNOERROR\t1200000",
    b"1\tc\tq.\t\t\t7",                          # empty qtype/rcode ok
    b"bad\t10.0.0.9\texample.com.\tA\tNOERROR\t1",
    b".5\tc\tq.\tA\tNOERROR\t1",                 # dot-first ts
    b"5.\tc\tq.\tA\tNOERROR\t1",                 # dot-last ts
    b"1.2.3\tc\tq.\tA\tNOERROR\t1",              # two dots
    b"-1\tc\tq.\tA\tNOERROR\t1",                 # signed ts
    b"1e5\tc\tq.\tA\tNOERROR\t1",                # exponent ts
    b"1\t\tq.\tA\tNOERROR\t1",                   # empty client
    b"1\tc\t\tA\tNOERROR\t1",                    # empty qname
    b"1\tc\tq.\tA\tNOERROR\t007",                # leading-zero latency
    b"1\tc\tq.\tA\tNOERROR\t18446744073709551615",  # u64 max (20 digits)
    b"1\tc\tq.\tA\tNOERROR\t18446744073709551616",  # > u64
    b"1\tc\tq.\tA\tNOERROR\t-1",
    b"1\tc\tq.\tA\tNOERROR",                     # 5 fields
    b"1\tc\tq.\tA\tNOERROR\t1\textra",           # 7 fields
    b'1.5\tc\tq"x\tA\tNOERROR\t4',               # quote (GELF escape)
    b"1.5\tc\tq\xc3\xa9\tA\tNOERROR\t4",         # non-ASCII
    b"not a dns line at all",
    b"",
]


def test_corpus_differential():
    with jax.disable_jit():
        results = _decode_dns_batch(list(CORPUS), 160)
    for ln, res in zip(CORPUS, results):
        kernel = ("rec", res.record) if res.record is not None else \
            ("err", res.error)
        try:
            oracle = ("rec", ORACLE.decode(ln.decode("utf-8")))
        except DecodeError as e:
            oracle = ("err", str(e))
        assert kernel == oracle, (
            f"divergence on {ln!r}:\n  kernel: {kernel}\n  oracle: {oracle}")


def _run_block(lines, enc_cls, merger, cfg=CFG):
    dec = DNSDecoder(cfg)
    enc = enc_cls(cfg)
    want = []
    for ln in lines:
        try:
            want.append(merger.frame(enc.encode(dec.decode(
                ln.decode("utf-8")))))
        except Exception:
            continue
    tx = queue.Queue()
    with jax.disable_jit():
        h = BatchHandler(tx, dec, enc, cfg, fmt="dns", start_timer=False,
                         merger=merger)
        for ln in lines:
            h.handle_bytes(ln)
        h.flush()
        h.close()
    got = []
    while not tx.empty():
        item = tx.get_nowait()
        if isinstance(item, EncodedBlock):
            got.extend(item.iter_framed())
        else:
            got.append(merger.frame(item))
    return got, want


@pytest.mark.parametrize("merger_cls", [LineMerger, NulMerger,
                                        SyslenMerger])
def test_dns_gelf_block_matches_scalar(merger_cls):
    got, want = _run_block(list(CORPUS), GelfEncoder, merger_cls())
    assert got == want


@pytest.mark.parametrize("merger_cls", [LineMerger, NulMerger,
                                        SyslenMerger])
def test_dns_ltsv_block_matches_scalar(merger_cls):
    got, want = _run_block(list(CORPUS), LTSVEncoder, merger_cls())
    assert got == want


@pytest.mark.slow
def test_dns_two_lane_identity():
    # slow-marked for the tier-1 wall budget; ci.sh's new-format step
    # runs it (that step filters on faults only)
    cfg = Config.from_string("[input]\ntpu_lanes = 2\n"
                             "tpu_batch_size = 8\n"
                             "tpu_max_line_len = 160\n")
    got, want = _run_block(list(CORPUS), GelfEncoder, LineMerger(),
                           cfg=cfg)
    assert got == want


@pytest.mark.faults
def test_dns_device_fault_fallback_splicing():
    from flowgger_tpu.utils import faultinject

    faultinject.reset()
    try:
        cfg = Config.from_string(
            "[input]\ntpu_batch_size = 8\ntpu_breaker_failures = 99\n"
            "tpu_max_line_len = 160\n")
        clean_got, want = _run_block(list(CORPUS) * 2, GelfEncoder,
                                     LineMerger(), cfg=cfg)
        faultinject.configure({"device_decode": "every:2"})
        faulty_got, _ = _run_block(list(CORPUS) * 2, GelfEncoder,
                                   LineMerger(), cfg=cfg)
        assert faulty_got == clean_got == want
    finally:
        faultinject.reset()


def test_dns_auto_leg_signature():
    from flowgger_tpu.tpu.autodetect import (F_DNS, F_LTSV, F_RFC3164,
                                             classify)

    dns_line = b"1438790025.5\t10.0.0.1\texample.com.\tA\tNOERROR\t523"
    assert classify(dns_line) == F_RFC3164       # classic table
    assert classify(dns_line, ("dns",)) == F_DNS
    # an ltsv line keeps its class even with the dns leg on
    ltsv_line = b"host:h\ttime:1\tmessage:m"
    assert classify(ltsv_line, ("dns",)) == F_LTSV
    # colon somewhere (ipv6 client) no longer misroutes to ltsv
    v6 = b"1\t2001:db8::1\tq.\tA\tNOERROR\t1"
    assert classify(v6) == F_LTSV
    assert classify(v6, ("dns",)) == F_DNS
    # a BOM'd first field is not a clean timestamp: both the scalar and
    # the vectorized classifier must keep the row OFF the dns leg
    bom = b"\xef\xbb\xbf" + dns_line
    assert classify(bom, ("dns",)) == F_RFC3164


def test_dns_vectorized_classify_matches_scalar():
    """classify_packed's numpy/device overlays agree with per-row
    classify for the dns/jsonl legs."""
    import numpy as np

    from flowgger_tpu.tpu import pack
    from flowgger_tpu.tpu.autodetect import classify, classify_packed

    lines = list(CORPUS) + [
        b'{"timestamp":1}',
        b"<13>1 2015-08-05T15:53:45Z h a 1 m - x",
        b"host:h\ttime:1\tmessage:m",
        b"plain text",
        b"\xef\xbb\xbf" + CORPUS[0],   # BOM'd dns line: off the leg
        b"\xef\xbb\xbf" + b'{"timestamp":1}',
    ]
    extras = ("jsonl", "dns")
    packed = pack.pack_lines_2d(lines, 160)
    got = classify_packed(packed, extras=extras)[:len(lines)]
    want = np.array([classify(ln, extras) for ln in lines])
    assert got.tolist() == want.tolist()


def test_dns_aot_decode_artifact_roundtrip(tmp_path):
    import numpy as np
    import jax.numpy as jnp

    from flowgger_tpu.tpu import aot, dns, pack

    out_dir = str(tmp_path / "art")
    aot.build_artifacts(out_dir, platforms=("cpu",),
                        families=("decode",), formats=("dns",),
                        rows_grid=(256,), max_len=96, quiet=True)
    store = aot.AotStore.load(out_dir)
    lines = [CORPUS[0]] * 4
    batch, lens, *_ = pack.pack_lines_2d(lines, 96)
    b, ln = jnp.asarray(batch), jnp.asarray(lens)
    call = store.find("decode_dns", aot.decode_statics("dns"), (b, ln))
    assert call is not None
    got = call(b, ln)
    want = dns.decode_dns_jit(b, ln)
    with jax.disable_jit():
        eager = dns.decode_dns(b, ln)
    for k in eager:
        # one compile does triple duty: exported == jit == eager
        assert np.array_equal(np.asarray(got[k]), np.asarray(want[k])), k
        assert np.array_equal(np.asarray(want[k]), np.asarray(eager[k])), k
