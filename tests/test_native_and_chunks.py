"""Native host tier + chunked ingest fast-path tests."""

import io
import queue

import numpy as np
import pytest

from flowgger_tpu import native
from flowgger_tpu.config import Config
from flowgger_tpu.decoders import RFC5424Decoder
from flowgger_tpu.encoders import GelfEncoder
from flowgger_tpu.splitters import LineSplitter, ScalarHandler
from flowgger_tpu.tpu.batch import BatchHandler

LINES = [
    b"<13>1 2015-08-05T15:53:45Z host app 1 2 - hello one",
    b'<23>1 2015-08-05T15:53:45.637824Z h a p m [id k="v"] two',
    b"garbage line",
    b"<13>1 2015-08-05T15:53:45Z host app 1 2 - three",
]


def test_native_split_matches_python():
    if not native.available():
        pytest.skip("native library not built")
    chunk = b"aaa\r\nbb\n\nccc\npartial"
    starts, lens, n, carry = native.split_chunk_native(chunk)
    assert n == 4
    assert carry == b"partial"
    got = [chunk[starts[i]:starts[i] + lens[i]] for i in range(n)]
    assert got == [b"aaa", b"bb", b"", b"ccc"]


def test_native_pack_matches_numpy():
    if not native.available():
        pytest.skip("native library not built")
    from flowgger_tpu.tpu import pack

    lines = [bytes([65 + i % 26]) * (i % 70) for i in range(1000)]
    b1 = pack.pack_lines_2d(lines, 48)
    orig = native.pack_chunk_native
    native.pack_chunk_native = lambda *a, **k: None
    try:
        b2 = pack.pack_lines_2d(lines, 48)
    finally:
        native.pack_chunk_native = orig
    assert (b1[0] == b2[0]).all()
    assert (b1[1] == b2[1]).all()


def test_pack_region_matches_pack_lines():
    from flowgger_tpu.tpu import pack

    region = b"".join(ln + b"\n" for ln in LINES)
    r1 = pack.pack_region_2d(region, 128)
    r2 = pack.pack_lines_2d(LINES, 128)
    assert r1[5] == r2[5]  # n_real
    assert (r1[0][:4] == r2[0][:4]).all()
    assert (r1[1][:4] == r2[1][:4]).all()
    assert (r1[4][:4] == r2[4][:4]).all()


def _run_handler(handler_cls_kwargs, data: bytes):
    tx = queue.Queue()
    handler = BatchHandler(tx, RFC5424Decoder(),
                           GelfEncoder(Config.from_string("")),
                           start_timer=False, **handler_cls_kwargs)
    LineSplitter().run(io.BytesIO(data), handler)
    out = []
    while not tx.empty():
        out.append(tx.get_nowait())
    return out


def test_chunked_ingest_equals_scalar_path(capsys):
    data = b"".join(ln + b"\n" for ln in LINES)
    got = _run_handler({}, data)

    tx = queue.Queue()
    scalar = ScalarHandler(tx, RFC5424Decoder(), GelfEncoder(Config.from_string("")))
    for ln in LINES:
        scalar.handle_bytes(ln)
    want = []
    while not tx.empty():
        want.append(tx.get_nowait())
    assert got == want
    # the bad line was reported on both paths
    assert capsys.readouterr().err.count("Unsupported BOM") == 2


def test_chunked_ingest_crlf_and_partial_tail():
    data = b"<13>1 2015-08-05T15:53:45Z h a p m - crlf\r\n" \
           b"<13>1 2015-08-05T15:53:45Z h a p m - tail-no-newline"
    got = _run_handler({}, data)
    assert len(got) == 2
    assert b'"short_message":"crlf"' in got[0]
    assert b'"short_message":"tail-no-newline"' in got[1]


def test_chunked_ingest_small_reads():
    """Regions split across many tiny reads must reassemble correctly."""

    class DribbleStream:
        def __init__(self, data):
            self.data = data
            self.pos = 0

        def read(self, n):
            chunk = self.data[self.pos:self.pos + 7]
            self.pos += len(chunk)
            return chunk

    data = b"".join(ln + b"\n" for ln in LINES)
    tx = queue.Queue()
    handler = BatchHandler(tx, RFC5424Decoder(),
                           GelfEncoder(Config.from_string("")), start_timer=False)
    LineSplitter().run(DribbleStream(data), handler)
    out = []
    while not tx.empty():
        out.append(tx.get_nowait())
    assert len(out) == 3  # three valid lines


def test_native_format_f64_json_matches_oracle():
    """fg_format_f64_json must byte-match utils.rustfmt.json_f64 across
    the full f64 space: random bit patterns (subnormals, huge/tiny
    magnitudes, NaN/inf payloads), timestamp-like values, integral
    floats, and signed zeros."""
    if not native.available():
        pytest.skip("native library not built")
    from flowgger_tpu.utils.rustfmt import json_f64

    rng = np.random.default_rng(20260729)
    bits = rng.integers(0, 2**64, size=20000, dtype=np.uint64)
    ts = (rng.integers(0, 2_000_000_000, 20000).astype(np.float64)
          + rng.integers(0, 10**9, 20000) / 1e9)
    specials = np.array([0.0, -0.0, 1.0, -1.0, 1e15, 1e16, -1e16,
                         0.0001, 1e-5, 9999999999999998.0, 5e-324,
                         -5e-324, 1.7976931348623157e308, np.nan,
                         np.inf, -np.inf, 2.0**53, 2.0**53 + 2])
    vals = np.concatenate([bits.view(np.float64), ts, specials])
    txt, lens = native.format_f64_json_native(vals, 32)
    assert txt.shape == (vals.size, 32)
    for i, v in enumerate(vals):
        want = json_f64(float(v)).encode("ascii")
        got = txt[i, :lens[i]].tobytes()
        assert got == want, (repr(float(v)), got, want)
        assert not txt[i, lens[i]:].any()


def test_ts_text_block_uses_native_and_matches_fallback():
    """_ts_text_block native path must agree with the dedup+json_f64
    fallback on realistic near-unique timestamps."""
    if not native.available():
        pytest.skip("native library not built")
    from flowgger_tpu.tpu import device_gelf

    rng = np.random.default_rng(3)
    n = 500
    small = {
        "ok": np.ones(n, dtype=np.uint8),
        "days": rng.integers(10000, 20000, n).astype(np.int32),
        "sod": rng.integers(0, 86400, n).astype(np.int32),
        "off": np.zeros(n, dtype=np.int32),
        "nanos": rng.integers(0, 10**9, n).astype(np.int32),
    }
    small["ok"][::7] = 0
    txt_n, len_n = device_gelf._ts_text_block(small)

    import unittest.mock as mock

    with mock.patch.object(native, "format_f64_json_native",
                           lambda *a, **k: None):
        txt_p, len_p = device_gelf._ts_text_block(small)
    assert (len_n == len_p).all()
    w = min(txt_n.shape[1], txt_p.shape[1])
    assert (txt_n[:, :w] == txt_p[:, :w]).all()
