"""Config lint (--check) tests: unknown keys flagged with suggestions,
free-form tables accepted, in-repo configs clean."""

import os
import subprocess
import sys

from flowgger_tpu.config import Config
from flowgger_tpu.lint import lint_config

REPO = os.path.join(os.path.dirname(__file__), "..")


def test_known_keys_clean():
    cfg = Config.from_string(
        '[input]\ntype = "stdin"\nformat = "rfc5424_tpu"\n'
        "tpu_batch_size = 1024\n"
        '[output]\ntype = "file"\nfile_path = "/tmp/x"\n')
    assert lint_config(cfg) == []


def test_typo_suggestion():
    cfg = Config.from_string('[input]\nfromat = "rfc5424"\n')
    warns = lint_config(cfg)
    assert len(warns) == 1
    assert "input.fromat" in warns[0]
    assert "input.format" in warns[0]


def test_free_tables_accepted():
    cfg = Config.from_string(
        "[input.ltsv_schema]\ncounter = \"u64\"\n"
        "[output.gelf_extra]\nanything_here = \"v\"\n")
    assert lint_config(cfg) == []


def test_repo_configs_are_clean():
    for rel in ("flowgger.toml", os.path.join("examples", "multihost-dp.toml")):
        cfg = Config.from_path(os.path.join(REPO, rel))
        assert lint_config(cfg) == [], rel


def test_cli_check_flag():
    r = subprocess.run(
        [sys.executable, "-m", "flowgger_tpu", "--check", "flowgger.toml"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_cli_check_flag_bad(tmp_path):
    bad = tmp_path / "bad.toml"
    bad.write_text('[output]\nkafka_compresion = "gzip"\n')
    r = subprocess.run(
        [sys.executable, "-m", "flowgger_tpu", "--check", str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "kafka_compression" in r.stdout  # the suggestion
