"""Config lint (--check) tests: unknown keys flagged with suggestions,
free-form tables accepted, in-repo configs clean."""

import os
import subprocess
import sys

from flowgger_tpu.config import Config
from flowgger_tpu.lint import lint_config

REPO = os.path.join(os.path.dirname(__file__), "..")


def test_known_keys_clean():
    cfg = Config.from_string(
        '[input]\ntype = "stdin"\nformat = "rfc5424_tpu"\n'
        "tpu_batch_size = 1024\n"
        '[output]\ntype = "file"\nfile_path = "/tmp/x"\n')
    assert lint_config(cfg) == []


def test_typo_suggestion():
    cfg = Config.from_string('[input]\nfromat = "rfc5424"\n')
    warns = lint_config(cfg)
    assert len(warns) == 1
    assert "input.fromat" in warns[0]
    assert "input.format" in warns[0]


def test_free_tables_accepted():
    cfg = Config.from_string(
        "[input.ltsv_schema]\ncounter = \"u64\"\n"
        "[output.gelf_extra]\nanything_here = \"v\"\n")
    assert lint_config(cfg) == []


def test_repo_configs_are_clean():
    for rel in ("flowgger.toml", os.path.join("examples", "multihost-dp.toml")):
        cfg = Config.from_path(os.path.join(REPO, rel))
        assert lint_config(cfg) == [], rel


def test_cli_check_flag():
    r = subprocess.run(
        [sys.executable, "-m", "flowgger_tpu", "--check", "flowgger.toml"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_cli_check_flag_bad(tmp_path):
    bad = tmp_path / "bad.toml"
    bad.write_text('[output]\nkafka_compresion = "gzip"\n')
    r = subprocess.run(
        [sys.executable, "-m", "flowgger_tpu", "--check", str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "kafka_compression" in r.stdout  # the suggestion


def test_cli_check_exit_codes(tmp_path):
    """Exit-code contract: 0 clean / 1 unknown keys / 2 unreadable or
    invalid TOML — distinct, so deploy gates can tell them apart."""
    unknown = tmp_path / "unknown.toml"
    unknown.write_text('[input]\nnot_a_real_key = 1\n')
    r = subprocess.run(
        [sys.executable, "-m", "flowgger_tpu", "--check", str(unknown)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "not_a_real_key" in r.stdout

    r = subprocess.run(
        [sys.executable, "-m", "flowgger_tpu", "--check",
         str(tmp_path / "missing.toml")],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 2
    assert "error:" in r.stderr

    invalid = tmp_path / "invalid.toml"
    invalid.write_text("this is [not toml\n")
    r = subprocess.run(
        [sys.executable, "-m", "flowgger_tpu", "--check", str(invalid)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert r.returncode == 2
    assert "error:" in r.stderr


def test_namespace_is_derived_from_code():
    """lint.py no longer hand-maintains KNOWN_KEYS: the namespace comes
    from the lookup call sites, so the four drifted keys the old set
    carried are gone and every key the code reads is present."""
    from flowgger_tpu.lint import FREE_TABLES, KNOWN_KEYS

    for dead in ("metrics.jsonl", "input.tls_threads",
                 "output.tls_compatibility_level", "output.tls_compression"):
        assert dead not in KNOWN_KEYS, dead
    for live in ("input.format", "input.tpu_batch_size",
                 "input.tpu_breaker_fallback_ratio", "input.queue_policy",
                 "output.kafka_retry_init", "output.tls_recovery_delay_max",
                 "supervisor.max_restarts", "metrics.jax_profile_dir"):
        assert live in KNOWN_KEYS, live
    assert {"faults", "input.ltsv_schema", "output.gelf_extra"} <= FREE_TABLES


def test_dead_key_now_warns():
    """A key the old hand-written set wrongly accepted is flagged."""
    cfg = Config.from_string("[metrics]\njsonl = true\n")
    warns = lint_config(cfg)
    assert len(warns) == 1 and "metrics.jsonl" in warns[0]
