"""Differential tests: columnar LTSV kernel vs the scalar oracle."""

import random

import pytest

from flowgger_tpu.config import Config
from flowgger_tpu.decoders import DecodeError
from flowgger_tpu.decoders.ltsv import LTSVDecoder
from flowgger_tpu.tpu.batch import _decode_ltsv_batch

_SCHEMA_CFG = (
    '[input]\n[input.ltsv_schema]\ncounter = "u64"\nscore = "i64"\n'
    'mean = "f64"\ndone = "bool"\n'
)

CORPUS = [
    "time:1438790025.99\thost:h\tname1:value1",
    "time:1438790025\thost:h\tk:v",
    "time:-5\thost:h\tk:v",
    "time:+12.5\thost:h\tk:v",
    "time:[2015-08-05T15:53:45.637824Z]\thost:h\tn:v",
    "time:2015-08-05T15:53:45Z\thost:h\tn:v",
    "time:[10/Oct/2000:13:55:36.3 -0700]\thost:h\tmessage:m",   # english -> fallback
    "time:1.5\thost:testhostname\tname 2: value 2\tn3:v3\tmessage:this is a test",
    "time:1.5\thost:h\tlevel:3\tmessage:hi",
    "time:1.5\thost:h\tlevel:9",            # error via fallback
    "time:1.5\thost:h\tlevel:abc",          # error via fallback
    "time:1.5\thost:h\tcounter:42\tscore:-1\tmean:0.42\tdone:true",
    "time:1.5\thost:h\tcounter:-1",         # schema type error
    "time:1.5\thost:h\tnocolonpart\tk:v",   # missing value print
    "host:h\tk:v",                          # missing timestamp
    "time:1.5\tk:v",                        # missing hostname
    "time:1.5\thost:h\t" + "\t".join(f"k{i}:{i}" for i in range(30)),  # >cap
    "time:1.5\thost:h\tmessage:ünïcode msg\tk:vàl",
    "time:1.5\thost:h\ttime:2.5",           # later time wins
    "time:1e5\thost:h",                     # exponent float -> fallback
    "time:inf\thost:h",                     # inf -> fallback path
    "time:.\thost:h",                       # bare dot -> error
    "",                                      # empty line
    "justtext",
    "time:1.5\thost:\tk:v",                 # empty hostname value
    "time:[1.5]\thost:h",                   # bracketed float
    "xtime:1.5\ttime:2.5\thost:h",          # key containing 'time' not special
    "time:1.5\thost:h\ttimex:9",
]


def run_both(lines, config_str=""):
    decoder = LTSVDecoder(Config.from_string(config_str))
    raw = [ln.encode("utf-8") for ln in lines]
    results = _decode_ltsv_batch(raw, 512, decoder)
    pairs = []
    for ln, res in zip(lines, results):
        kernel = ("rec", res.record) if res.record is not None else ("err", res.error)
        try:
            oracle = ("rec", decoder.decode(ln))
        except DecodeError as e:
            oracle = ("err", str(e))
        pairs.append((ln, kernel, oracle))
    return pairs


def assert_identical(lines, config_str=""):
    for ln, kernel, oracle in run_both(lines, config_str):
        assert kernel == oracle, (
            f"divergence on {ln!r}:\n  kernel: {kernel}\n  oracle: {oracle}")


def test_corpus_plain():
    assert_identical(CORPUS)


def test_corpus_with_schema():
    assert_identical(CORPUS, _SCHEMA_CFG)


def test_suffixes():
    cfg = _SCHEMA_CFG + '[input.ltsv_suffixes]\nu64 = "_u64"\ni64 = "_i64"\n'
    assert_identical(CORPUS, cfg)


def test_fast_path_coverage():
    import jax.numpy as jnp
    import numpy as np

    from flowgger_tpu.tpu import ltsv, pack

    clean = [ln for ln in CORPUS if ln.startswith("time:1") or ln.startswith("time:[2015")]
    raw = [ln.encode() for ln in clean]
    batch, lens, chunk, starts, orig, n = pack.pack_lines_2d(raw, 256)
    out = ltsv.decode_ltsv_jit(jnp.asarray(batch), jnp.asarray(lens))
    okf = np.asarray(out["ok"])[:n]
    assert okf.mean() >= 0.7, list(zip(clean, okf))


def test_fuzz_differential():
    rng = random.Random(77)
    alphabet = list("\t:timehoslvcabd0123456789.[]- Z")
    base = "time:1438790025.5\thost:abc\tlevel:3\tcounter:42\tmessage:hello there"
    lines = []
    for _ in range(300):
        chars = list(base)
        for _ in range(rng.randint(1, 5)):
            op = rng.random()
            pos = rng.randrange(len(chars)) if chars else 0
            if op < 0.4 and chars:
                chars[pos] = rng.choice(alphabet)
            elif op < 0.7:
                chars.insert(pos, rng.choice(alphabet))
            elif chars:
                del chars[pos]
        lines.append("".join(chars))
    assert_identical(lines, _SCHEMA_CFG)


def test_missing_value_notice(capsys):
    assert_identical(["time:1.5\thost:h\torphan\tk:v"])
    out = capsys.readouterr().out
    # both kernel and oracle printed the notice once each
    assert out.count("Missing value for name 'orphan'") == 2
