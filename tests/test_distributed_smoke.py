"""Real 2-process jax.distributed smoke test (VERDICT r3 #7): spawn two
actual processes that join one JAX process group over a local
coordinator, assert the global device view spans both, run the
production BatchHandler mesh path in each, and byte-compare the framed
output against the single-process scalar reference.  No monkeypatching
— this exercises jax.distributed.initialize for real on the CPU
backend (the DCN story is identical on TPU pods: one process per host,
a coordinator, and dp over independent shards)."""

import os
import socket
import subprocess
import sys

import pytest

from flowgger_tpu.config import Config
from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
from flowgger_tpu.encoders.gelf import GelfEncoder
from flowgger_tpu.mergers import LineMerger

_WORKER = os.path.join(os.path.dirname(__file__), "distributed_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _expected(pid: int) -> bytes:
    decoder, encoder, merger = (RFC5424Decoder(),
                                GelfEncoder(Config.from_string("")),
                                LineMerger())
    out = b""
    for i in range(64):
        line = (f'<{(3 * i + pid) % 192}>1 2023-09-20T12:35:45.{i:03d}Z '
                f'host{pid} app {i} m [sd@1 k="{i}" x="y"] '
                f'worker {pid} line {i}')
        out += merger.frame(encoder.encode(decoder.decode(line)))
    return out


def test_two_process_group_decodes_byte_identical(tmp_path):
    # bounded by the communicate(timeout=420) below, not pytest-timeout
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = []
    outs = [tmp_path / f"out_{pid}.bin" for pid in (0, 1)]
    for pid in (0, 1):
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER, str(pid), str(port), str(outs[pid])],
            env=env, cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    logs = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=420)
            logs.append((p.returncode, stdout.decode(errors="replace"),
                         stderr.decode(errors="replace")))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed workers timed out")
    for rc, stdout, stderr in logs:
        assert rc == 0, f"worker failed rc={rc}\n{stdout}\n{stderr}"
    for pid in (0, 1):
        got = outs[pid].read_bytes()
        assert got == _expected(pid), f"worker {pid} output diverged"
