"""Multi-tenant serving: registry, token-bucket admission, weighted-fair
queue, tenant-flood isolation, and online template mining."""

import queue as queue_mod
import threading

import pytest

from flowgger_tpu.config import Config, ConfigError
from flowgger_tpu import tenancy
from flowgger_tpu.tenancy.admission import AdmissionHandler, TokenBucket
from flowgger_tpu.tenancy.fairqueue import WeightedFairQueue
from flowgger_tpu.tenancy.registry import TenantRegistry
from flowgger_tpu.tenancy.templates import TemplateMiner, TemplateMinerSet
from flowgger_tpu.utils import faultinject
from flowgger_tpu.utils.metrics import registry


@pytest.fixture(autouse=True)
def _clean():
    registry.reset()
    faultinject.reset()
    tenancy.set_current(None)
    yield
    faultinject.reset()
    tenancy.set_current(None)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _registry(toml: str, clock=None) -> TenantRegistry:
    return TenantRegistry.from_config(Config.from_string(toml), clock=clock)


TWO_TENANTS = """
[tenants.flood]
peers = ["10.0.0.0/8"]
rate = 5
[tenants.good]
peers = ["192.0.2.7"]
"""


# ---------------------------------------------------------------------------
# registry: parsing + resolution
# ---------------------------------------------------------------------------

def test_registry_disabled_without_config():
    assert TenantRegistry.from_config(Config.from_string("")) is None
    assert TenantRegistry.from_config(Config.from_string(
        '[input]\ntype = "stdin"\n')) is None


def test_registry_enabled_by_default_rate_alone():
    reg = _registry("[tenant]\ndefault_rate = 100\n")
    assert reg is not None and reg.default.rate == 100
    assert reg.default.burst == 200  # 2x rate


def test_registry_resolution_cidr_exact_and_fallback():
    reg = _registry(TWO_TENANTS)
    assert reg.resolve_name("10.200.3.4") == "flood"
    assert reg.resolve_name("192.0.2.7") == "good"
    assert reg.resolve_name("203.0.113.9") == "default"
    assert reg.resolve_name(None) == "default"
    assert reg.resolve_name("/var/log/app.log") == "default"


def test_registry_first_declared_match_wins_over_exact():
    """Resolution is first match in declaration order: a CIDR declared
    before an exact-IP tenant captures that IP (the broad rate limit
    must not be bypassable by a later exact entry)."""
    reg = _registry('[tenants.fleet]\npeers = ["10.0.0.0/8"]\n'
                    '[tenants.vip]\npeers = ["10.1.2.3"]\n')
    assert reg.resolve_name("10.1.2.3") == "fleet"
    # declared the other way around, the exact entry wins
    reg2 = _registry('[tenants.vip]\npeers = ["10.1.2.3"]\n'
                     '[tenants.fleet]\npeers = ["10.0.0.0/8"]\n')
    assert reg2.resolve_name("10.1.2.3") == "vip"
    assert reg2.resolve_name("10.9.9.9") == "fleet"


def test_registry_file_path_and_star_peers():
    reg = _registry('[tenants.logs]\npeers = ["/var/log/app.log"]\n'
                    '[tenants.rest]\npeers = ["*"]\n')
    assert reg.resolve_name("/var/log/app.log") == "logs"
    assert reg.resolve_name("8.8.8.8") == "rest"


def test_registry_defaults_inherited_and_overridden():
    reg = _registry("[tenant]\ndefault_weight = 3\n"
                    'default_queue_policy = "drop_newest"\n'
                    "[tenants.a]\n[tenants.b]\nweight = 7\n"
                    'queue_policy = "block"\n')
    assert reg.spec("a").weight == 3 and reg.spec("a").queue_policy == "drop_newest"
    assert reg.spec("b").weight == 7 and reg.spec("b").queue_policy == "block"


def test_registry_validation_errors():
    with pytest.raises(ConfigError, match="unknown key"):
        _registry("[tenants.a]\nrte = 5\n")
    with pytest.raises(ConfigError, match="queue_policy"):
        _registry('[tenants.a]\nqueue_policy = "bogus"\n')
    with pytest.raises(ConfigError, match="weight"):
        _registry("[tenants.a]\nweight = 0\n")
    with pytest.raises(ConfigError, match="peers"):
        _registry("[tenants.a]\npeers = [5]\n")
    with pytest.raises(ConfigError, match="default_queue_policy"):
        _registry('[tenant]\ndefault_rate = 1\ndefault_queue_policy = "x"\n')


# ---------------------------------------------------------------------------
# token buckets + admission
# ---------------------------------------------------------------------------

def test_token_bucket_rate_and_burst():
    clock = FakeClock()
    b = TokenBucket(rate=10, burst=20, clock=clock)
    assert sum(b.try_take(1) for _ in range(30)) == 20  # burst drained
    clock.t += 0.5
    assert sum(b.try_take(1) for _ in range(30)) == 5   # refill 10/s


def test_token_bucket_unlimited():
    b = TokenBucket(rate=0, burst=0)
    assert all(b.try_take(10**9) for _ in range(100))


def test_admission_handler_sheds_and_counts():
    clock = FakeClock()
    reg = _registry(TWO_TENANTS, clock=clock)

    class Sink:
        def __init__(self):
            self.chunks = []
            self.ingest_sep = b"\n"
            self.ingest_strip_cr = True
            self.quiet_empty = False
            self.bare_errors = False

        def ingest_chunk(self, region):
            self.chunks.append(region)

        def flush(self):
            pass

    sink = Sink()
    h = AdmissionHandler(sink, reg.resolve("10.1.1.1"))
    region = b"one\ntwo\n"
    for _ in range(10):
        h.ingest_chunk(region)  # 2 lines each; burst = 10 lines
    assert len(sink.chunks) == 5
    assert registry.get("tenant_flood_lines") == 10
    assert registry.get("tenant_flood_bytes") == 5 * len(region)
    assert registry.get("tenant_flood_drops") == 10
    assert registry.snapshot().get("tenant_flood_state") == 1
    # the unlimited tenant admits everything and never throttles
    g = AdmissionHandler(sink, reg.resolve("192.0.2.7"))
    for _ in range(50):
        g.ingest_chunk(region)
    assert registry.get("tenant_good_drops") == 0
    assert registry.snapshot().get("tenant_good_state") == 0


def test_admission_handler_mirrors_fast_path_surface():
    class ScalarOnly:
        quiet_empty = False
        bare_errors = False
        ingest_sep = b"\n"
        ingest_strip_cr = True

        def handle_bytes(self, raw):
            pass

    reg = _registry(TWO_TENANTS)
    h = AdmissionHandler(ScalarOnly(), reg.resolve(None))
    # a scalar inner handler must not suddenly grow the chunk fast path
    assert not hasattr(h, "ingest_chunk") and not hasattr(h, "ingest_spans")


def test_admission_sets_thread_tenant_tag():
    reg = _registry(TWO_TENANTS)
    seen = []

    class Sink:
        quiet_empty = False
        bare_errors = False
        ingest_sep = b"\n"
        ingest_strip_cr = True

        def handle_bytes(self, raw):
            seen.append(tenancy.current_name())

    AdmissionHandler(Sink(), reg.resolve("10.0.0.1")).handle_bytes(b"x")
    assert seen == ["flood"]


@pytest.mark.faults
def test_tenant_flood_fault_site_targets_rate_limited_tenants():
    """The tenant_flood site denies admission checks of rate-limited
    tenants only: unlimited tenants never consult it, so the plan's
    deterministic numbering lands entirely on the flooder."""
    faultinject.configure({"tenant_flood": "every:2"})
    clock = FakeClock()
    reg = _registry(TWO_TENANTS, clock=clock)
    flood, good = reg.resolve("10.0.0.1"), reg.resolve("192.0.2.7")
    results = [flood.admit(1, 1) for _ in range(6)]
    assert results == [True, False, True, False, True, False]
    assert all(good.admit(1, 1) for _ in range(20))  # site untouched
    assert registry.get("tenant_good_drops") == 0


# ---------------------------------------------------------------------------
# weighted-fair queue
# ---------------------------------------------------------------------------

def _drain_queue(q):
    out = []
    while True:
        try:
            out.append(q.get_nowait())
        except queue_mod.Empty:
            return out


def test_fairqueue_single_lane_fifo():
    q = WeightedFairQueue(maxsize=10)
    for i in range(5):
        q.put(b"%d" % i)
    assert _drain_queue(q) == [b"0", b"1", b"2", b"3", b"4"]


def test_fairqueue_weighted_share():
    """A weight-3 tenant drains ~3x the bytes of a weight-1 tenant over
    one DRR cycle window."""
    reg = _registry("[tenants.heavy]\nweight = 3\n[tenants.light]\nweight = 1\n")
    q = WeightedFairQueue(registry=reg)
    item = b"x" * 1024
    tenancy.set_current("heavy")
    for _ in range(64):
        q.put(item)
    tenancy.set_current("light")
    for _ in range(64):
        q.put(item)
    tenancy.set_current(None)
    first = [q.get_nowait() for _ in range(32)]
    del first
    depths = q.lane_depths()
    # heavy drained ~3x light's items from the interleaved window
    assert depths["heavy"] < depths["light"]
    assert (64 - depths["heavy"]) >= 2 * (64 - depths["light"])


def test_fairqueue_per_lane_fifo_under_interleave():
    reg = _registry("[tenants.a]\n[tenants.b]\nweight = 2\n")
    q = WeightedFairQueue(registry=reg)
    for i in range(10):
        tenancy.set_current("a" if i % 2 == 0 else "b")
        q.put(b"%c%d" % (ord("a") + i % 2, i))
    tenancy.set_current(None)
    out = _drain_queue(q)
    a_items = [x for x in out if x.startswith(b"a")]
    b_items = [x for x in out if x.startswith(b"b")]
    assert a_items == sorted(a_items) and b_items == sorted(b_items)
    assert len(out) == 10


def test_fairqueue_shutdown_after_data_and_unsheddable():
    reg = _registry('[tenants.a]\nqueue_policy = "drop_oldest"\n')
    q = WeightedFairQueue(maxsize=2, registry=reg)
    q.put(None)  # SHUTDOWN first — must still deliver last, never shed
    tenancy.set_current("a")
    for i in range(5):
        q.put(b"%d" % i)  # maxsize 2: sheds oldest, sentinel exempt
    tenancy.set_current(None)
    out = _drain_queue(q)
    assert out[-1] is None and all(x is not None for x in out[:-1])
    assert registry.get("queue_dropped") == 3
    assert registry.get("tenant_a_shed") == 3


def test_fairqueue_sheds_noisiest_first():
    """Global pressure from a well-behaved put degrades the noisiest
    sheddable tenant, not the victim's own lane."""
    reg = _registry('[tenants.noisy]\nqueue_policy = "drop_oldest"\n'
                    '[tenants.quiet]\nqueue_policy = "drop_oldest"\n')
    q = WeightedFairQueue(maxsize=6, registry=reg)
    tenancy.set_current("noisy")
    for i in range(5):
        q.put(b"n%d" % i)
    tenancy.set_current("quiet")
    q.put(b"q0")
    q.put(b"q1")  # full: noisy (5 items) is the victim, not quiet
    tenancy.set_current(None)
    out = _drain_queue(q)
    assert b"q0" in out and b"q1" in out
    assert b"n0" not in out  # noisy's head shed
    assert registry.get("tenant_noisy_shed") == 1
    assert registry.get("tenant_quiet_shed") == 0
    assert registry.get("queue_dropped_shed_noisiest") == 1


def test_fairqueue_block_lane_never_shed():
    reg = _registry('[tenants.b]\nqueue_policy = "block"\n'
                    '[tenants.d]\nqueue_policy = "drop_newest"\n')
    q = WeightedFairQueue(maxsize=3, registry=reg)
    tenancy.set_current("b")
    for i in range(3):
        q.put(b"b%d" % i)
    tenancy.set_current("d")
    q.put(b"d0")  # full; only sheddable lane is d's own (empty) -> drop incoming
    tenancy.set_current(None)
    out = _drain_queue(q)
    assert out == [b"b0", b"b1", b"b2"]
    assert registry.get("queue_dropped_drop_newest") == 1
    assert registry.get("tenant_d_shed") == 1


def test_fairqueue_blocks_and_wakes_producer():
    reg = _registry('[tenants.a]\nqueue_policy = "block"\n')
    q = WeightedFairQueue(maxsize=1, registry=reg)
    tenancy.set_current("a")
    q.put(b"first")
    done = threading.Event()

    def produce():
        tenancy.set_current("a")
        q.put(b"second")  # blocks until the consumer makes room
        done.set()

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    assert not done.wait(0.1)
    assert q.get(timeout=1) == b"first"
    assert done.wait(2)
    assert q.get(timeout=1) == b"second"
    tenancy.set_current(None)


def test_fairqueue_put_nowait_and_timeout_raise_full():
    """queue.Queue parity: a non-blocking (or timed-out) put on a full
    queue whose lanes are all block-policy raises Full instead of
    waiting forever."""
    reg = _registry('[tenants.a]\nqueue_policy = "block"\n')
    q = WeightedFairQueue(maxsize=1, registry=reg)
    tenancy.set_current("a")
    q.put(b"first")
    with pytest.raises(queue_mod.Full):
        q.put_nowait(b"second")
    with pytest.raises(queue_mod.Full):
        q.put(b"second", timeout=0.01)
    tenancy.set_current(None)
    assert q.get_nowait() == b"first"


def test_fairqueue_queue_dropped_counts_items_not_lines():
    """queue_dropped keeps PolicyQueue units (one shed item = one
    drop) even for multi-line blocks; tenant_{t}_shed counts lines."""
    import numpy as np

    from flowgger_tpu.block import EncodedBlock

    reg = _registry('[tenant]\ndefault_queue_policy = "drop_newest"\n'
                    "default_rate = 1\n")
    q = WeightedFairQueue(maxsize=1, registry=reg)
    blk = EncodedBlock(b"a\nb\nc\n", np.array([0, 2, 4, 6], np.int64),
                       suffix_len=1)
    q.put(blk)
    q.put(blk)  # full -> own-lane drop_newest shed of a 3-line block
    assert registry.get("queue_dropped") == 1
    assert registry.get("tenant_default_shed") == 3


def test_fairqueue_task_accounting_survives_sheds():
    reg = _registry('[tenants.a]\nqueue_policy = "drop_oldest"\n')
    q = WeightedFairQueue(maxsize=1, registry=reg)
    tenancy.set_current("a")
    q.put(b"a")
    q.put(b"b")  # sheds a
    tenancy.set_current(None)
    assert q.get_nowait() == b"b"
    q.task_done()
    q.join()  # wedges if shed items leaked unfinished-task counts


def test_fairqueue_block_items_ride_default_lane():
    import numpy as np

    from flowgger_tpu.block import EncodedBlock

    reg = _registry(TWO_TENANTS)
    q = WeightedFairQueue(registry=reg)
    tenancy.set_current("flood")
    blk = EncodedBlock(b"ab\ncd\n", np.array([0, 3, 6], dtype=np.int64),
                       suffix_len=1)
    q.put(blk)
    tenancy.set_current(None)
    assert q.lane_depths() == {"default": 1}
    assert q.get_nowait() is blk


@pytest.mark.faults
def test_fairqueue_queue_pressure_site():
    faultinject.configure({"queue_pressure": "first:2"})
    reg = _registry('[tenants.a]\nqueue_policy = "drop_newest"\n')
    q = WeightedFairQueue(maxsize=16, registry=reg)
    tenancy.set_current("a")
    q.put(b"a")  # pressured -> shed
    q.put(b"b")  # pressured -> shed
    q.put(b"c")  # delivered
    tenancy.set_current(None)
    assert _drain_queue(q) == [b"c"]
    assert registry.get("queue_dropped") == 2


# ---------------------------------------------------------------------------
# drain-phase shed accounting (PolicyQueue + fair queue)
# ---------------------------------------------------------------------------

def test_policy_queue_labels_and_drain_shed_counter():
    from flowgger_tpu.utils.bounded_queue import PolicyQueue

    q = PolicyQueue(maxsize=1, policy="drop_newest")
    q.put(b"a")
    q.put(b"b")  # shed, pre-drain
    assert registry.get("queue_dropped_drop_newest") == 1
    assert registry.get("queue_shed_during_drain") == 0
    q.mark_draining()
    q.put(b"c")  # shed during drain
    assert registry.get("queue_shed_during_drain") == 1
    assert registry.get("queue_dropped") == 2


def test_fairqueue_drain_shed_counter():
    reg = _registry('[tenants.a]\nqueue_policy = "drop_newest"\n')
    q = WeightedFairQueue(maxsize=1, registry=reg)
    tenancy.set_current("a")
    q.put(b"a")
    q.mark_draining()
    q.put(b"b")
    tenancy.set_current(None)
    assert registry.get("queue_shed_during_drain") == 1


def test_pipeline_drain_marks_queue():
    from flowgger_tpu.pipeline import Pipeline

    p = Pipeline(Config.from_string(
        '[input]\ntype = "stdin"\n[output]\ntype = "debug"\n'))
    threads = p.start_output()
    p._drain(threads if isinstance(threads, list) else [threads])
    assert p.tx.draining


# ---------------------------------------------------------------------------
# pipeline wiring
# ---------------------------------------------------------------------------

def test_pipeline_default_path_has_no_tenancy_objects():
    """Zero-overhead-when-off: an unconfigured pipeline builds the exact
    pre-tenancy objects — PolicyQueue, unwrapped handlers, no miners."""
    from flowgger_tpu.pipeline import Pipeline
    from flowgger_tpu.splitters import ScalarHandler
    from flowgger_tpu.utils.bounded_queue import PolicyQueue

    p = Pipeline(Config.from_string(
        '[input]\ntype = "stdin"\n[output]\ntype = "debug"\n'))
    assert p.tenants is None and type(p.tx) is PolicyQueue
    assert type(p.handler_factory()) is ScalarHandler


def test_pipeline_tenancy_wires_queue_and_admission():
    from flowgger_tpu.pipeline import Pipeline

    p = Pipeline(Config.from_string(
        '[input]\ntype = "stdin"\n[output]\ntype = "debug"\n'
        + TWO_TENANTS))
    assert type(p.tx) is WeightedFairQueue
    h = p.handler_factory(peer="10.3.3.3")
    assert type(h) is AdmissionHandler and h._tenant.name == "flood"
    assert p.handler_factory(peer=None)._tenant.name == "default"


def test_make_handler_compat():
    from flowgger_tpu.inputs import make_handler

    calls = []
    assert make_handler(lambda: calls.append("plain") or "h") == "h"

    def factory(peer=None):
        calls.append(peer)
        return "h2"

    assert make_handler(factory, "10.0.0.1") == "h2"
    assert calls == ["plain", "10.0.0.1"]


# ---------------------------------------------------------------------------
# tenant-flood isolation: the acceptance bar
# ---------------------------------------------------------------------------

GOOD_LINE = (b"<13>1 2024-01-01T00:00:%02dZ good-host app %d g - "
             b"good message number %d")
FLOOD_LINE = (b"<13>1 2024-01-01T00:00:%02dZ flood-host app %d f - "
              b"flood flood flood %d")


def _flood_run(lanes, framing, flood=True, fault_spec=None):
    """Drive interleaved good/flood traffic through admission + the
    shared rfc5424 block-route handler; returns (merged output bytes,
    snapshot) — the flooder sends 10x its admitted token rate."""
    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.passthrough import PassthroughEncoder
    from flowgger_tpu.mergers import LineMerger, NulMerger
    from flowgger_tpu.outputs import stream_bytes
    from flowgger_tpu.tpu.batch import BatchHandler

    registry.reset()
    faultinject.reset()
    if fault_spec:
        faultinject.configure({"tenant_flood": fault_spec})
    clock = FakeClock()
    # flooder: 10 lines/sec, burst 20; good: unlimited
    reg = _registry("[tenants.flood]\npeers = [\"10.0.0.0/8\"]\nrate = 10\n"
                    "[tenants.good]\npeers = [\"192.0.2.7\"]\n", clock=clock)
    cfg = Config.from_string(
        "[input]\ntpu_batch_size = 10\ntpu_inflight = 2\n"
        + (f"tpu_lanes = {lanes}\n" if lanes else ""))
    sep, merger = ((b"\n", LineMerger()) if framing == "line"
                   else (b"\0", NulMerger()))
    tx = queue_mod.Queue()
    inner = BatchHandler(tx, RFC5424Decoder(cfg), PassthroughEncoder(cfg),
                         cfg, fmt="rfc5424", start_timer=False, merger=merger)
    inner.ingest_sep = sep
    inner.ingest_strip_cr = framing == "line"
    good = AdmissionHandler(inner, reg.resolve("192.0.2.7"))
    flooder = AdmissionHandler(inner, reg.resolve("10.9.9.9"))
    seq = 0
    for burst in range(10):
        region = b"".join(GOOD_LINE % (burst, i, seq + i) + sep
                          for i in range(5))
        seq += 5
        good.ingest_chunk(region)
        if flood:
            # 10x the flooder's rate: 100 lines over a frozen second
            flooder.ingest_chunk(b"".join(
                FLOOD_LINE % (burst, i, i) + sep for i in range(10)))
    inner.flush()
    inner.close()
    out = b""
    while not tx.empty():
        data, _ = stream_bytes(tx.get_nowait(), merger)
        out += data
    return out, registry.snapshot()


def _good_subset(out: bytes, sep: bytes):
    return [ln for ln in out.split(sep) if b"good-host" in ln]


@pytest.mark.faults
@pytest.mark.parametrize("framing", ["line", "nul"])
@pytest.mark.parametrize("lanes", [None, 2])
def test_flood_isolation_byte_identical_good_tenant(lanes, framing):
    """Acceptance: one tenant flooding at 10x its token rate is shed at
    admission while the well-behaved tenant's output stays byte-
    identical and in-order vs a no-flood run — line and nul framings,
    1-lane and 2-lane dispatch — and only the flooder's counters move."""
    sep = b"\n" if framing == "line" else b"\0"
    baseline, _ = _flood_run(lanes, framing, flood=False)
    flooded, snap = _flood_run(lanes, framing, flood=True)
    good_clean = _good_subset(baseline, sep)
    good_flood = _good_subset(flooded, sep)
    assert good_flood == good_clean  # byte-identical AND in-order
    assert len(good_clean) == 50
    # the flood was actually shed: admitted <= burst(20), rest dropped
    assert snap["tenant_flood_drops"] >= 80
    assert snap.get("tenant_good_drops", 0) == 0
    assert snap["tenant_good_lines"] == 50
    # some flood lines were admitted (burst) and decoded normally
    assert 0 < flooded.count(b"flood-host") <= 20


@pytest.mark.faults
def test_flood_isolation_via_fault_site():
    """Same isolation bar driven by the deterministic tenant_flood site:
    every admission check of the rate-limited flooder denies, the good
    tenant's stream is untouched."""
    baseline, _ = _flood_run(None, "line", flood=False)
    flooded, snap = _flood_run(None, "line", flood=True, fault_spec="every:1")
    assert _good_subset(flooded, b"\n") == _good_subset(baseline, b"\n")
    assert flooded.count(b"flood-host") == 0  # every flood chunk denied
    assert snap["tenant_flood_drops"] == 100
    assert snap.get("tenant_good_drops", 0) == 0


# ---------------------------------------------------------------------------
# template mining
# ---------------------------------------------------------------------------

def test_miner_clusters_and_wildcards():
    m = TemplateMiner()
    a = m.observe("Accepted password from 10.0.0.1 port 22")
    b = m.observe("Accepted password from 10.9.9.9 port 2222")
    c = m.observe("Failed password for root")
    assert a == b and a != c
    assert m.template(a) == "Accepted password from <*> port <*>"
    assert m.distinct() == 2


def test_miner_ids_stable_across_runs():
    corpus = [f"job {i} finished in {i * 3} ms" for i in range(50)]
    corpus += [f"user u{i} logged in" for i in range(50)]
    corpus += ["disk sda1 failed", "disk sdb2 failed"]

    def mine():
        m = TemplateMiner()
        return [m.observe(line) for line in corpus], m.templates()

    ids1, t1 = mine()
    ids2, t2 = mine()
    assert ids1 == ids2 and t1 == t2


def test_miner_template_cap_returns_unmined():
    m = TemplateMiner(max_templates=2)
    assert m.observe("alpha beta") != 0
    assert m.observe("gamma delta epsilon") != 0
    assert m.observe("zeta eta theta iota") == 0  # capped
    assert m.distinct() == 2


def test_miner_set_per_tenant_isolation_and_gauges():
    ms = TemplateMinerSet()
    ms.observe_msg("a", "user alice logged in")
    ms.observe_msg("b", "user bob logged in")
    assert ms.miner("a").distinct() == 1 and ms.miner("b").distinct() == 1
    snap = registry.snapshot()
    assert snap["template_hits"] == 2
    assert snap["tenant_templates_distinct"] == 2
    assert snap["tenant_a_templates_distinct"] == 1
    assert registry.get("tenant_a_template_1") == 1


def test_miner_set_config_gate():
    assert TemplateMinerSet.from_config(Config.from_string("")) is None
    assert TemplateMinerSet.from_config(Config.from_string(
        '[tenant]\ntemplates = "off"\n')) is None
    ms = TemplateMinerSet.from_config(Config.from_string(
        '[tenant]\ntemplates = "on"\ntemplate_sim = 0.7\n'))
    assert ms is not None and ms.sim == 0.7
    with pytest.raises(ConfigError, match="templates"):
        TemplateMinerSet.from_config(Config.from_string(
            '[tenant]\ntemplates = "maybe"\n'))
    with pytest.raises(ConfigError, match="template_enrich"):
        TemplateMinerSet.from_config(Config.from_string(
            "[tenant]\ntemplate_enrich = true\n"))
    with pytest.raises(ConfigError, match="template_sim"):
        TemplateMinerSet.from_config(Config.from_string(
            '[tenant]\ntemplates = "on"\ntemplate_sim = 1.5\n'))


MINE_LINES = [
    b"<13>1 2024-01-01T00:00:00Z h app p m - session 101 opened for user alice",
    b"<13>1 2024-01-01T00:00:01Z h app p m - session 202 opened for user bob",
    b"<13>1 2024-01-01T00:00:02Z h app p m - disk sda1 failed",
]


def _mine_block_run(lanes=None, tenant="alpha"):
    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.passthrough import PassthroughEncoder
    from flowgger_tpu.mergers import LineMerger
    from flowgger_tpu.outputs import stream_bytes
    from flowgger_tpu.tpu.batch import BatchHandler

    registry.reset()
    cfg = Config.from_string(
        "[input]\ntpu_batch_size = 3\ntpu_inflight = 2\n"
        + (f"tpu_lanes = {lanes}\n" if lanes else "")
        + '[tenant]\ntemplates = "on"\n')
    tx = queue_mod.Queue()
    merger = LineMerger()
    h = BatchHandler(tx, RFC5424Decoder(cfg), PassthroughEncoder(cfg),
                     cfg, fmt="rfc5424", start_timer=False, merger=merger)
    tenancy.set_current(tenant)
    for _ in range(4):
        h.ingest_chunk(b"".join(ln + b"\n" for ln in MINE_LINES))
    tenancy.set_current(None)
    h.flush()
    h.close()
    out = b""
    while not tx.empty():
        data, _ = stream_bytes(tx.get_nowait(), merger)
        out += data
    return out, h


def test_block_route_mining_consumes_decoded_columns():
    """Mining on the columnar block route: templates come from the
    kernel's message span channels, attributed to the ingesting tenant,
    and the emitted bytes are untouched."""
    out, h = _mine_block_run()
    assert h._miners is not None and h._block_route_ok()
    miner = h._miners.miner("alpha")
    assert miner.distinct() == 2
    assert "session <*> opened for user <*>" in miner.templates().values()
    snap = registry.snapshot()
    assert snap["template_hits"] == 12
    assert snap["tenant_alpha_templates_distinct"] == 2
    # mining never perturbs output bytes
    plain, _ = _stream_plain()
    assert out == plain


def test_block_route_mining_stable_across_lanes():
    out1, h1 = _mine_block_run()
    out2, h2 = _mine_block_run(lanes=2)
    assert out1 == out2
    assert h1._miners.miner("alpha").templates() == \
        h2._miners.miner("alpha").templates()


def _stream_plain():
    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.passthrough import PassthroughEncoder
    from flowgger_tpu.mergers import LineMerger
    from flowgger_tpu.outputs import stream_bytes
    from flowgger_tpu.tpu.batch import BatchHandler

    cfg = Config.from_string("[input]\ntpu_batch_size = 3\ntpu_inflight = 2\n")
    tx = queue_mod.Queue()
    merger = LineMerger()
    h = BatchHandler(tx, RFC5424Decoder(cfg), PassthroughEncoder(cfg),
                     cfg, fmt="rfc5424", start_timer=False, merger=merger)
    for _ in range(4):
        h.ingest_chunk(b"".join(ln + b"\n" for ln in MINE_LINES))
    h.flush()
    h.close()
    out = b""
    while not tx.empty():
        data, _ = stream_bytes(tx.get_nowait(), merger)
        out += data
    return out, h


def test_mining_off_by_default_zero_residue():
    _out, h = _stream_plain()
    assert h._miners is None and h._enrich_hook is None
    assert h._chunk_runs == [] and h._mine_block is False
    assert registry.get("template_hits") == 0


def test_record_route_mining_attributes_rows_by_ingest_runs():
    """A mixed-tenant batch on the Record route mines each row into its
    own tenant's miner — attribution follows the ingest runs, not
    whichever thread happened to trigger the flush."""
    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.gelf import GelfEncoder
    from flowgger_tpu.mergers import NulMerger
    from flowgger_tpu.tpu.batch import BatchHandler

    cfg = Config.from_string(
        "[input]\ntpu_batch_size = 64\n"
        '[tenant]\ntemplates = "on"\ntemplate_enrich = true\n')
    tx = queue_mod.Queue()
    h = BatchHandler(tx, RFC5424Decoder(cfg), GelfEncoder(cfg), cfg,
                     fmt="rfc5424", start_timer=False, merger=NulMerger())
    tenancy.set_current("alpha")
    h.ingest_chunk(b"<13>1 2024-01-01T00:00:00Z h a p m - alpha says hello\n")
    tenancy.set_current("beta")
    h.ingest_chunk(b"<13>1 2024-01-01T00:00:01Z h a p m - beta says goodbye\n")
    tenancy.set_current("neither")  # the flushing thread's tag is a red herring
    h.flush()
    h.close()
    tenancy.set_current(None)
    assert "alpha says hello" in h._miners.miner("alpha").templates().values()
    assert "beta says goodbye" in h._miners.miner("beta").templates().values()
    assert h._miners.miner("neither").distinct() == 0


def test_record_route_rows_land_on_their_own_queue_lanes():
    """Record-route emits lane each row by its ingest tenant on the
    fair queue — not by whichever thread triggered the flush — so
    pressure shedding can never pick a well-behaved tenant's rows out
    of a noisier tenant's lane."""
    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.gelf import GelfEncoder
    from flowgger_tpu.mergers import NulMerger
    from flowgger_tpu.tpu.batch import BatchHandler

    reg = _registry("[tenants.a]\n[tenants.b]\n")
    cfg = Config.from_string(
        "[input]\ntpu_batch_size = 64\n"
        '[tenant]\ntemplates = "on"\ntemplate_enrich = true\n')
    tx = WeightedFairQueue(registry=reg)
    h = BatchHandler(tx, RFC5424Decoder(cfg), GelfEncoder(cfg), cfg,
                     fmt="rfc5424", start_timer=False, merger=NulMerger())
    tenancy.set_current("a")
    h.ingest_chunk(b"<13>1 2024-01-01T00:00:00Z h a p m - from tenant a\n")
    tenancy.set_current("b")
    h.ingest_chunk(b"<13>1 2024-01-01T00:00:01Z h a p m - from tenant b\n")
    tenancy.set_current("neither")
    h.flush()
    h.close()
    tenancy.set_current(None)
    assert h._miners is not None
    depths = tx.lane_depths()
    assert depths.get("a") == 1 and depths.get("b") == 1
    assert "neither" not in depths


def test_udp_per_source_tenant_resolution():
    """UDP datagrams resolve tenants per source IP on the per-datagram
    path: a [tenants.*] peers entry for the sender's address charges
    that tenant's buckets, not the default tenant's."""
    import socket
    import threading
    import time

    from flowgger_tpu.pipeline import Pipeline

    p = Pipeline(Config.from_string(
        '[input]\ntype = "udp"\nlisten = "127.0.0.1:0"\n'
        'format = "rfc5424"\n[output]\ntype = "debug"\n'
        '[tenants.local]\npeers = ["127.0.0.1"]\n'))
    t = threading.Thread(target=p.input.accept, args=(p.handler_factory,),
                         daemon=True)
    t.start()
    deadline = time.time() + 10
    while p.input.bound_port is None:
        assert time.time() < deadline
        time.sleep(0.01)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.sendto(b"<13>1 2024-01-01T00:00:00Z h app p m - over udp",
             ("127.0.0.1", p.input.bound_port))
    deadline = time.time() + 10
    while registry.get("tenant_local_lines") < 1:
        assert time.time() < deadline, registry.snapshot()
        time.sleep(0.02)
    s.close()
    assert registry.get("tenant_local_lines") == 1
    assert registry.get("tenant_default_lines") == 0


def test_scalar_pipeline_mines_templates():
    """tenant.templates = "on" engages on scalar (non-*_tpu) pipelines
    too: the pipeline wires a record hook onto its ScalarHandlers."""
    from flowgger_tpu.pipeline import Pipeline

    p = Pipeline(Config.from_string(
        '[input]\ntype = "stdin"\nformat = "rfc5424"\n'
        '[output]\ntype = "debug"\nformat = "gelf"\n'
        '[tenant]\ntemplates = "on"\ntemplate_enrich = true\n'))
    h = p.handler_factory()
    h.handle_bytes(b"<13>1 2024-01-01T00:00:00Z h app p m - scalar mined")
    out = p.tx.get_nowait()
    assert b'"_template_id":1' in out
    assert p._scalar_miners.miner("default").distinct() == 1
    # and the tpu path must NOT double-build a pipeline-level miner set
    p2 = Pipeline(Config.from_string(
        '[input]\ntype = "stdin"\nformat = "rfc5424_tpu"\n'
        '[output]\ntype = "debug"\nformat = "gelf"\n'
        '[tenant]\ntemplates = "on"\n'))
    assert p2._scalar_miners is None


def test_tenant_template_opt_out():
    """[tenants.<name>] templates = false excludes that tenant from
    mining while others keep mining."""
    ms = TemplateMinerSet.from_config(Config.from_string(
        '[tenant]\ntemplates = "on"\n'
        "[tenants.quiet]\ntemplates = false\n[tenants.chatty]\n"))
    assert ms.observe_msg("quiet", "user alice logged in") == 0
    assert ms.observe_msg("chatty", "user alice logged in") == 1
    assert ms.miner("quiet").distinct() == 0
    assert registry.get("template_hits") == 1
    ms.observe_rows(["a b c", "d e f"],
                    [("quiet", 1), ("chatty", 1)])
    assert ms.miner("quiet").distinct() == 0
    # chatty gained only its own row ("d e f"); quiet's "a b c" skipped
    assert ms.miner("chatty").distinct() == 2


def test_fairqueue_drop_cause_label_matches_lane_policy():
    """An incoming-item discard on a drop_oldest lane whose own queue is
    empty is labeled drop_oldest, not drop_newest."""
    reg = _registry('[tenants.b]\nqueue_policy = "block"\n'
                    '[tenants.d]\nqueue_policy = "drop_oldest"\n')
    q = WeightedFairQueue(maxsize=2, registry=reg)
    tenancy.set_current("b")
    q.put(b"b0")
    q.put(b"b1")
    tenancy.set_current("d")
    q.put(b"d0")  # full; nothing sheddable; d's own lane empty
    tenancy.set_current(None)
    assert registry.get("queue_dropped_drop_oldest") == 1
    assert registry.get("queue_dropped_drop_newest") == 0


def test_gelf_enrichment_stamps_template_id():
    from flowgger_tpu.decoders.rfc5424 import RFC5424Decoder
    from flowgger_tpu.encoders.gelf import GelfEncoder
    from flowgger_tpu.mergers import NulMerger
    from flowgger_tpu.tpu.batch import BatchHandler

    cfg = Config.from_string(
        "[input]\ntpu_batch_size = 2\n"
        '[tenant]\ntemplates = "on"\ntemplate_enrich = true\n')
    tx = queue_mod.Queue()
    h = BatchHandler(tx, RFC5424Decoder(cfg), GelfEncoder(cfg), cfg,
                     fmt="rfc5424", start_timer=False, merger=NulMerger())
    # enrichment rides the Record route: the block route must disengage
    assert not h._block_route_ok()
    assert "template_enrich" in h._route_cliff_reason()
    h.ingest_chunk(
        b"<13>1 2024-01-01T00:00:00Z h app p m - login from 10.1.1.1\n"
        b"<13>1 2024-01-01T00:00:01Z h app p m - login from 10.2.2.2\n")
    h.flush()
    h.close()
    items = _drain_queue(tx)
    assert len(items) == 2
    assert all(b'"_template_id":1' in item for item in items)
    # the scalar fallback path stamps the same id (byte-consistency)
    h.scalar.handle_bytes(
        b"<13>1 2024-01-01T00:00:02Z h app p m - login from 10.3.3.3")
    assert b'"_template_id":1' in tx.get_nowait()
