"""RFC3164 decoder golden tests (reference: rfc3164_decoder.rs:215-425
inline tests, with current-year-relative expectations computed like
utils/test_utils.rs does)."""

import pytest

from flowgger_tpu.decoders import DecodeError, RFC3164Decoder
from flowgger_tpu.utils.timeparse import current_year_utc, rfc3339_to_unix

D = RFC3164Decoder()


def _ts(month, day, h, m, s, year=None):
    year = year if year is not None else current_year_utc()
    return rfc3339_to_unix(f"{year:04d}-{month:02d}-{day:02d}T{h:02d}:{m:02d}:{s:02d}Z")


def test_decode_nopri():
    msg = "Aug  6 11:15:24 testhostname appname 69 42 some test message"
    res = D.decode(msg)
    assert res.facility is None and res.severity is None
    assert res.ts == _ts(8, 6, 11, 15, 24)
    assert res.hostname == "testhostname"
    assert res.msg == "appname 69 42 some test message"
    assert res.full_msg == msg


def test_decode_with_pri():
    msg = "<13>Aug  6 11:15:24 testhostname appname 69 42 msg"
    res = D.decode(msg)
    assert res.facility == 1 and res.severity == 5
    assert res.hostname == "testhostname"


def test_decode_with_year():
    msg = "2019 Mar 27 12:09:39 testhostname msg text"
    res = D.decode(msg)
    assert res.ts == _ts(3, 27, 12, 9, 39, year=2019)
    assert res.hostname == "testhostname"
    assert res.msg == "msg text"


def test_decode_with_tz():
    msg = "2019 Mar 27 12:09:39 UTC testhostname msg text"
    res = D.decode(msg)
    assert res.ts == _ts(3, 27, 12, 9, 39, year=2019)
    assert res.hostname == "testhostname"
    assert res.msg == "msg text"


def test_decode_custom_format():
    # [<pri>]<hostname>: <datetime>: <message>
    msg = "<34>mymachine: Mar 27 12:09:39: failed for lonvick on /dev/pts/8"
    res = D.decode(msg)
    assert res.facility == 4 and res.severity == 2
    assert res.hostname == "mymachine"
    assert res.ts == _ts(3, 27, 12, 9, 39)
    assert res.msg == "failed for lonvick on /dev/pts/8"


def test_custom_format_message_rejoined_with_colon_space():
    msg = "host: Mar 27 12:09:39: part1: part2: part3"
    res = D.decode(msg)
    assert res.msg == "part1: part2: part3"


def test_multiple_spaces_collapse():
    msg = "Aug  6 11:15:24 host   appname  msg"
    res = D.decode(msg)
    assert res.msg == "appname msg"


def test_errors(capsys):
    with pytest.raises(DecodeError):
        D.decode("not a syslog line at all")
    captured = capsys.readouterr()
    assert "Unable to parse the rfc3164 input" in captured.err


def test_bad_pri():
    with pytest.raises(DecodeError, match="Invalid priority"):
        D.decode("<abc>Aug  6 11:15:24 host app msg")
