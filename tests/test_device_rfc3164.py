"""Device-side RFC3164→GELF encode (tpu/device_rfc3164.py):
differential tests vs the scalar oracle (RFC3164Decoder → GelfEncoder →
merger.frame), including fallback splicing, framing variants, and the
production BatchHandler route."""

import queue
import random

import numpy as np
import pytest

from flowgger_tpu.config import Config
from flowgger_tpu.block import EncodedBlock
from flowgger_tpu.decoders import DecodeError
from flowgger_tpu.decoders.rfc3164 import RFC3164Decoder
from flowgger_tpu.encoders.gelf import GelfEncoder
from flowgger_tpu.mergers import LineMerger, NulMerger, SyslenMerger
from flowgger_tpu.tpu import device_rfc3164, pack, rfc3164
from flowgger_tpu.tpu.batch import BatchHandler
from flowgger_tpu.utils.metrics import registry as metrics

ORACLE = RFC3164Decoder()
ENC = GelfEncoder(Config.from_string(""))


def scalar_frames(lines, merger):
    out = []
    for ln in lines:
        try:
            rec = ORACLE.decode(ln.decode("utf-8"))
        except (DecodeError, UnicodeDecodeError):
            continue
        payload = ENC.encode(rec)
        out.append(merger.frame(payload) if merger is not None else payload)
    return out


def run_device(lines, merger, max_len=256):
    packed = pack.pack_lines_2d(lines, max_len)
    handle = rfc3164.decode_rfc3164_submit(packed[0], packed[1])
    return device_rfc3164.fetch_encode(handle, packed, ENC, merger)


CLEAN = [
    b"<13>Sep 20 12:35:45 host app: a legacy message",
    b"<34>Oct 11 22:14:15 mymachine su: 'su root' failed for lonvick",
    b"Sep 20 12:35:45 nopri-host appname: message without pri",
    b"<165>Aug  1 03:00:00 h1 proc: short",
]


@pytest.mark.parametrize("merger", [None, LineMerger(), NulMerger(),
                                    SyslenMerger()],
                         ids=["noop", "line", "nul", "syslen"])
@pytest.mark.requires_device_encode_compile
def test_device_3164_matches_scalar_and_engages(merger):
    n0 = metrics.get("device_encode_rows")
    res, _ = run_device(CLEAN * 4, merger)
    assert res is not None
    assert metrics.get("device_encode_rows") - n0 == len(CLEAN) * 4
    want = b"".join(scalar_frames(CLEAN * 4, merger))
    assert res.block.data == want


@pytest.mark.requires_device_encode_compile
def test_device_3164_fallback_splicing(monkeypatch):
    monkeypatch.setattr(device_rfc3164, "FALLBACK_FRAC", 1.1)
    mixed = [
        CLEAN[0],
        b'<13>Sep 20 12:35:45 host app: quotes "here" and\ttabs',
        "<13>Sep 20 12:35:45 hést app: non-ascii host".encode(),
        CLEAN[1],
        b"\xff\xfe invalid utf8",
        CLEAN[3],
    ]
    res, _ = run_device(mixed, LineMerger())
    assert res is not None
    want = b"".join(scalar_frames(mixed, LineMerger()))
    assert res.block.data == want


@pytest.mark.requires_device_encode_compile
def test_device_3164_fuzz_vs_scalar(monkeypatch):
    monkeypatch.setattr(device_rfc3164, "FALLBACK_FRAC", 1.1)
    rng = random.Random(7)
    months = ["Jan", "Feb", "Mar", "Sep", "Oct", "Dec"]
    msgs = ["hello", 'say "hi"', "tab\there", "", "-", "trail   ",
            "back\\slash", "x" * 150]
    lines = []
    for i in range(200):
        pri = f"<{rng.randrange(0, 192)}>" if rng.random() < 0.8 else ""
        day = rng.randint(1, 28)
        line = (f"{pri}{rng.choice(months)} {day:2d} "
                f"{rng.randint(0, 23):02d}:{rng.randint(0, 59):02d}:"
                f"{rng.randint(0, 59):02d} host{i % 9} app{i % 5}: "
                f"{rng.choice(msgs)}")
        lines.append(line.encode())
    for merger in (LineMerger(), NulMerger(), SyslenMerger()):
        res, _ = run_device(lines, merger)
        assert res is not None
        want = b"".join(scalar_frames(lines, merger))
        assert res.block.data == want


@pytest.mark.requires_device_encode_compile
def test_batch_handler_3164_uses_device_engine():
    tx = queue.Queue()
    h = BatchHandler(tx, ORACLE, ENC, Config.from_string(""),
                     fmt="rfc3164", start_timer=False, merger=LineMerger())
    n0 = metrics.get("device_encode_rows")
    for ln in CLEAN * 4:
        h.handle_bytes(ln)
    h.flush()
    assert metrics.get("device_encode_rows") - n0 == len(CLEAN) * 4
    data = b""
    while not tx.empty():
        item = tx.get_nowait()
        data += item.data if isinstance(item, EncodedBlock) else item
    assert data == b"".join(scalar_frames(CLEAN * 4, LineMerger()))


@pytest.mark.requires_device_encode_compile
def test_device_3164_compaction_fetch_is_output_sized():
    rng = random.Random(3)
    lines = []
    for i in range(192):
        msg = "y" * rng.randrange(1, 100)
        lines.append(
            f"<{i % 192}>Sep {1 + i % 28:2d} 12:35:{i % 60:02d} "
            f"h{i} app: {msg}".encode())
    n0 = metrics.get("device_encode_fetch_bytes")
    res, _ = run_device(lines, LineMerger())
    assert res is not None
    want = b"".join(scalar_frames(lines, LineMerger()))
    assert res.block.data == want
    fetched = metrics.get("device_encode_fetch_bytes") - n0
    assert fetched < len(res.block.data) * 1.2 + 64 * len(lines)


@pytest.mark.requires_device_encode_compile
def test_3164_gelf_extra_static_slots():
    """gelf_extra on the rfc3164→GELF pair: keys covering every static
    slot of THIS layout (incl. the dual-form level→short slot exercised
    by both PRI and no-PRI rows) must match the scalar encoder on the
    device tier; unplaceable keys (fixed-key overwrite) refuse."""
    enc = GelfEncoder(Config.from_string(
        "[output.gelf_extra]\n"
        'about = "pre-slot"\n'       # < full_message
        'gateway = "fh"\n'           # full_message < k < host
        'kind = "hl"\n'              # host < k < level
        'region = "l2"\n'            # level < k < short_message (dual)\n
        'stage = "st"\n'             # short_message < k < timestamp
        'tier = "tv"\n'              # timestamp < k < version
        'zzz = "tail"\n'))           # > version
    assert device_rfc3164.route_ok(enc, LineMerger()) is True

    def oracle(lines):
        return b"".join(LineMerger().frame(enc.encode(ORACLE.decode(
            ln.decode()))) for ln in lines)

    packed = pack.pack_lines_2d(CLEAN * 3, 256)
    handle = rfc3164.decode_rfc3164_submit(packed[0], packed[1])
    res, _ = device_rfc3164.fetch_encode(handle, packed, enc,
                                         LineMerger())
    assert res is not None
    assert res.block.data == oracle(CLEAN * 3)

    # host tier too
    from flowgger_tpu.tpu.encode_rfc3164_gelf_block import (
        encode_rfc3164_gelf_block,
    )

    host_out = rfc3164.decode_rfc3164_fetch(handle)
    res2 = encode_rfc3164_gelf_block(packed[2], packed[3], packed[4],
                                     host_out, packed[5], 256, enc,
                                     LineMerger())
    assert res2 is not None and res2.block.data == oracle(CLEAN * 3)

    bad = GelfEncoder(Config.from_string(
        '[output.gelf_extra]\nhost = "overwrite"\n'))
    assert device_rfc3164.route_ok(bad, LineMerger()) is False


# ---- rfc3164 -> rfc3164 self-encode (syslog relay mode) --------------------

def test_3164_self_encode_block_matches_scalar():
    from flowgger_tpu.encoders.rfc3164 import RFC3164Encoder
    from flowgger_tpu.tpu.batch import block_fetch_encode, block_submit

    enc = RFC3164Encoder(Config.from_string(""))

    def oracle(lines, merger):
        out = []
        for ln in lines:
            try:
                rec = ORACLE.decode(ln.decode("utf-8"))
            except (DecodeError, UnicodeDecodeError):
                continue
            payload = enc.encode(rec)
            out.append(merger.frame(payload) if merger is not None
                       else payload)
        return b"".join(out)

    mixed = CLEAN * 3 + [b"\xff bad utf8", b""]
    for merger in (LineMerger(), NulMerger(), SyslenMerger()):
        packed = pack.pack_lines_2d(mixed, 256)
        handle = block_submit("rfc3164", packed)
        res, _, _ = block_fetch_encode("rfc3164", handle, packed, enc,
                                       merger)
        assert res is not None
        assert res.block.data == oracle(mixed, merger)


def test_3164_self_encode_handler_route():
    from flowgger_tpu.encoders.rfc3164 import RFC3164Encoder

    enc = RFC3164Encoder(Config.from_string(""))
    tx = queue.Queue()
    h = BatchHandler(tx, ORACLE, enc, Config.from_string(""),
                     fmt="rfc3164", start_timer=False, merger=LineMerger())
    assert h._block_route_ok()
    for ln in CLEAN * 3:
        h.handle_bytes(ln)
    h.flush()
    data = b""
    while not tx.empty():
        item = tx.get_nowait()
        data += item.data if isinstance(item, EncodedBlock) else item
    want = b"".join(LineMerger().frame(enc.encode(ORACLE.decode(
        ln.decode()))) for ln in CLEAN * 3)
    assert data == want

    # prepend-timestamp configs stay on the Record path, loudly
    enc_ts = RFC3164Encoder(Config.from_string(
        '[output]\nsyslog_prepend_timestamp = "[%Y-%m-%dT%H:%M:%SZ] "\n'))
    h2 = BatchHandler(queue.Queue(), ORACLE, enc_ts, Config.from_string(""),
                      fmt="rfc3164", start_timer=False,
                      merger=LineMerger())
    assert not h2._block_route_ok()
